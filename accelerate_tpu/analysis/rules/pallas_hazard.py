"""pallas-hazard: host leaks inside Pallas kernel bodies, and kernel call
sites with no lowering-mode fallback.

Two hazard shapes (docs/kernels.md §graftlint):

1. **Host work in a kernel body.** The function handed to
   ``pl.pallas_call`` executes on the accelerator core (or the
   interpreter): a host callback (``jax.debug.callback`` /
   ``io_callback`` / ``pure_callback``), a python ``print``/``breakpoint``,
   or a python-side ``if``/``while`` branching on a kernel *ref* parameter
   either fails to lower (Mosaic has no host channel) or silently bakes
   one trace-time branch into every invocation.  ``pl.debug_print`` and
   branches on static (keyword-only / closure) config are fine — the rule
   only fires on tests that reference the kernel's positional (ref)
   parameters.

2. **Un-gated call site.** A ``pl.pallas_call`` invocation with no
   ``interpret=`` argument and no interpret/backend-gated branch in scope
   compiles Mosaic unconditionally — the program is then TPU-only, which
   breaks the policy discipline this repo's kernels follow (the
   ``KernelPolicy.interpret`` mode must reach every call so tier-1 can run
   the kernel under the CPU interpreter; docs/kernels.md §policy).
"""

from __future__ import annotations

import ast
import re

from ..engine import Finding, Rule

# host-side calls that cannot (or must not) live in a kernel body;
# pl.debug_print is the sanctioned in-kernel print and does not match
_HOST_CALLBACK_LEAVES = {
    "debug_callback",
    "io_callback",
    "pure_callback",
    "breakpoint",
}

_FALLBACK_GUARD_RE = re.compile(r"interpret|backend|platform|tpu", re.IGNORECASE)


def _call_leaf(node: ast.Call, module) -> str:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        resolved = module.resolve(fn) or fn.id
        return resolved.rsplit(".", 1)[-1]
    return ""


def _kernel_fn_name(call: ast.Call) -> str | None:
    """The kernel function a ``pallas_call`` receives: a bare name, or the
    first argument of a ``functools.partial(...)`` wrapper."""
    if not call.args:
        return None
    first = call.args[0]
    if isinstance(first, ast.Name):
        return first.id
    if isinstance(first, ast.Call):
        inner = first.func
        leaf = inner.attr if isinstance(inner, ast.Attribute) else getattr(
            inner, "id", ""
        )
        if leaf == "partial" and first.args and isinstance(first.args[0], ast.Name):
            return first.args[0].id
    return None


def _positional_params(fn_node) -> set[str]:
    """The kernel's ref parameters: Pallas passes refs positionally, so
    keyword-only params (static config bound via functools.partial) are
    excluded on purpose — branching on those is trace-time specialization,
    not a host leak."""
    args = fn_node.args
    return {a.arg for a in list(args.posonlyargs) + list(args.args)}


def _mentions_any(test: ast.AST, names: set[str]) -> bool:
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and node.id in names:
            return True
    return False


class _KernelBodyVisitor(ast.NodeVisitor):
    """Scan one kernel function's body for host leaks."""

    def __init__(self, rule, module, fn_info):
        self.rule = rule
        self.module = module
        self.fn = fn_info
        self.ref_params = _positional_params(fn_info.node)
        self.findings: list[Finding] = []

    def _flag(self, node, message: str) -> None:
        self.findings.append(
            Finding(
                self.rule.id,
                self.module.rel_path,
                node.lineno,
                node.col_offset,
                message,
                symbol=self.fn.qualname,
            )
        )

    def visit_Call(self, node):
        leaf = _call_leaf(node, self.module)
        if leaf in _HOST_CALLBACK_LEAVES:
            self._flag(
                node,
                f"{leaf}() inside a pallas kernel body is a host callback — "
                "Mosaic has no host channel; use pl.debug_print or move the "
                "callback outside the kernel",
            )
        elif isinstance(node.func, ast.Name) and node.func.id == "print":
            self._flag(
                node,
                "print() inside a pallas kernel body runs at trace time only "
                "(or fails to lower) — use pl.debug_print",
            )
        self.generic_visit(node)

    def _check_branch(self, node, kind: str) -> None:
        if _mentions_any(node.test, self.ref_params):
            self._flag(
                node,
                f"python-side {kind} on a kernel ref parameter bakes one "
                "trace-time branch into every invocation — use @pl.when / "
                "jnp.where / jax.lax.cond on the loaded value instead",
            )

    def visit_If(self, node):
        self._check_branch(node, "if")
        self.generic_visit(node)

    def visit_While(self, node):
        self._check_branch(node, "while")
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        pass  # nested defs (e.g. run_scoped bodies) scan as their own fns

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef


class _CallSiteVisitor(ast.NodeVisitor):
    """Find pallas_call invocations; collect (call, guarded) pairs."""

    def __init__(self, module):
        self.module = module
        self.guard_depth = 0
        self.sites: list[tuple[ast.Call, bool]] = []

    def visit_If(self, node):
        guarded = bool(
            _FALLBACK_GUARD_RE.search(ast.dump(node.test))
        )
        self.guard_depth += guarded
        self.generic_visit(node)
        self.guard_depth -= guarded

    def visit_Call(self, node):
        if _call_leaf(node, self.module) == "pallas_call":
            self.sites.append((node, self.guard_depth > 0))
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        pass  # nested defs are their own FunctionInfos: scanning them here
        # too would report each of their call sites twice

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef


class PallasHazard(Rule):
    id = "pallas-hazard"
    description = (
        "pl.pallas_call whose kernel body contains a host callback or a "
        "python-side branch on a ref parameter; or a pallas_call site with "
        "no interpret=/policy-gated fallback in scope"
    )
    kind = "syntactic"
    fix_hint = (
        "use pl.when for branches and pl.debug_print for logging inside "
        "kernels; thread KernelPolicy.interpret to the pallas_call site"
    )

    def check(self, module, ctx):
        findings: list[Finding] = []
        # kernel functions by bare name, for call-site -> body resolution
        by_name = {}
        for info in module.callgraph.functions.values():
            by_name.setdefault(info.name, info)
        scanned_bodies: set[str] = set()
        for info in module.callgraph.functions.values():
            v = _CallSiteVisitor(module)
            for stmt in info.node.body:
                v.visit(stmt)
            for call, guarded in v.sites:
                has_interpret = any(
                    kw.arg == "interpret" for kw in call.keywords
                )
                if not has_interpret and not guarded:
                    findings.append(
                        Finding(
                            self.id,
                            module.rel_path,
                            call.lineno,
                            call.col_offset,
                            "pl.pallas_call without an interpret= argument or "
                            "an interpret/backend-gated fallback in scope "
                            "compiles Mosaic unconditionally — thread the "
                            "kernel policy's lowering mode (KernelPolicy."
                            "interpret) so non-TPU backends keep a path",
                            symbol=info.qualname,
                        )
                    )
                kernel_name = _kernel_fn_name(call)
                target = by_name.get(kernel_name) if kernel_name else None
                if target is not None and target.qualname not in scanned_bodies:
                    scanned_bodies.add(target.qualname)
                    findings.extend(self._scan_kernel(module, target))
        return findings

    def _scan_kernel(self, module, target) -> list[Finding]:
        """Scan one kernel function's body, INCLUDING its nested defs —
        a ``pl.run_scoped`` closure executes inside the kernel, so a host
        callback hidden there is the same leak.  Nested defs inherit the
        outer kernel's ref-parameter set (the closure sees those refs)
        plus their own positional params (scoped scratch/semaphores)."""
        body_visitor = _KernelBodyVisitor(self, module, target)
        for stmt in target.node.body:
            body_visitor.visit(stmt)
        findings = list(body_visitor.findings)
        outer_refs = body_visitor.ref_params
        for node in ast.walk(target.node):
            if isinstance(node, ast.FunctionDef) and node is not target.node:
                nested = _KernelBodyVisitor(self, module, target)
                nested.ref_params = outer_refs | _positional_params(node)
                for stmt in node.body:
                    nested.visit(stmt)
                findings.extend(nested.findings)
        return findings
