"""``accelerate-tpu env`` — environment report for bug reports.

Counterpart of ``/root/reference/src/accelerate/commands/env.py:47``.
"""

from __future__ import annotations

import argparse
import os
import platform
from typing import Optional

__all__ = ["env_command", "env_command_parser"]


def env_command_parser(subparsers: Optional[argparse._SubParsersAction] = None):
    description = "Print the accelerate-tpu environment report"
    if subparsers is not None:
        parser = subparsers.add_parser("env", help=description)
    else:
        parser = argparse.ArgumentParser("accelerate-tpu env", description=description)
    parser.add_argument("--config_file", default=None)
    if subparsers is not None:
        parser.set_defaults(func=env_command)
    return parser


def env_command(args) -> None:
    import numpy as np

    import accelerate_tpu

    info = {
        "`accelerate_tpu` version": accelerate_tpu.__version__,
        "Platform": platform.platform(),
        "Python version": platform.python_version(),
        "Numpy version": np.__version__,
    }
    try:
        import jax
        import jaxlib

        info["JAX version"] = jax.__version__
        info["jaxlib version"] = jaxlib.__version__
        try:
            devices = jax.devices()
            info["JAX backend"] = devices[0].platform
            info["JAX device count"] = str(len(devices))
            info["JAX process count"] = str(jax.process_count())
        except Exception as e:  # no backend attachable from this shell
            info["JAX backend"] = f"unavailable ({e})"
    except ImportError:
        info["JAX version"] = "not installed"

    from .config.config_args import default_config_file, load_config_from_file

    config_file = args.config_file or default_config_file
    if os.path.isfile(config_file):
        config = load_config_from_file(config_file)
        info["Default config"] = ""
        print_config = {f"\t{k}": v for k, v in config.to_dict().items()}
    else:
        info["Default config"] = "not found"
        print_config = {}

    relevant_env = {
        k: v
        for k, v in os.environ.items()
        if k.startswith(("ACCELERATE_", "JAX_", "XLA_", "TPU_", "LIBTPU"))
        or k.endswith("_SIZE")
    }

    print("\nCopy-and-paste the text below in your GitHub issue\n")
    for key, value in info.items():
        print(f"- {key}: {value}")
    for key, value in print_config.items():
        print(f"{key}: {value}")
    if relevant_env:
        print("- Environment variables:")
        for key in sorted(relevant_env):
            print(f"\t{key}={relevant_env[key]}")


def main():
    args = env_command_parser().parse_args()
    env_command(args)


if __name__ == "__main__":
    main()
