"""Collective-ops correctness under the launcher (reference
test_utils/scripts/test_ops.py): gather/reduce/broadcast/pad over pytrees."""

from __future__ import annotations

import numpy as np

from accelerate_tpu import Accelerator
from accelerate_tpu.utils.operations import (
    broadcast,
    gather,
    gather_object,
    pad_across_processes,
    reduce,
)


def main():
    acc = Accelerator()
    state = acc.state
    shards = max(1, state.num_devices)

    # gather: each shard contributes its slice; global result is the full batch
    local = np.arange(4, dtype=np.float32) + 1
    gathered = np.asarray(gather({"t": local})["t"]).ravel()
    assert gathered.size >= local.size

    # reduce(sum): pytree of per-shard values sums across shards
    summed = reduce({"v": np.ones(3, dtype=np.float32)}, reduction="sum")
    total = np.asarray(summed["v"])
    assert np.allclose(total, total[0]), "reduce must be replicated"

    # broadcast from main: all shards end with main's value
    value = np.full((2,), float(state.process_index), dtype=np.float32)
    out = np.asarray(broadcast(value))
    assert np.allclose(out, 0.0), f"broadcast failed: {out}"

    # gather_object flattens the per-process lists (reference semantics)
    objs = gather_object([state.process_index])
    assert 0 in objs and len(objs) == state.num_processes

    # pad_across_processes makes ragged dims uniform
    ragged = np.ones((2 + state.process_index % 2, 3), dtype=np.float32)
    padded = pad_across_processes(ragged, dim=0)
    assert np.asarray(padded).shape[0] >= ragged.shape[0]

    state.wait_for_everyone()
    if state.is_main_process:
        print("All ops checks passed")


if __name__ == "__main__":
    main()
