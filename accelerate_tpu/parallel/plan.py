"""ParallelPlan — dp × fsdp × pp × sp resolved ONCE, read everywhere.

Before this module every subsystem rediscovered the parallelism layout for
itself: the optimizer re-derived the ZeRO-1 dp axis, compression re-checked
its wire axis, the pipelined model poked ``mesh.shape.get("pp")`` and the
plugin registry, fleet resize re-read dp off the mesh, and the AOT cache
hashed mesh shape + compression but not the schedule that shaped the
program.  Each rediscovery was one more place a layout flip could silently
disagree (ROADMAP, top ambitious item).

Now ``Accelerator`` resolves ONE frozen :class:`ParallelPlan` from
``ParallelismConfig``/plugins/env at construction (and re-resolves it on a
fleet resize), publishes it on the Borg ``AcceleratorState`` so any module
can call :func:`current_plan`, and every consumer reads the plan:

* **capture** pins the plan and drops compiled variants when it moves;
* **optimizer relayout** takes its ZeRO-1 state shardings from
  :meth:`ParallelPlan.state_spec`;
* **compression** reads the armed policy name and wire axis off the plan;
* **AOT fingerprint** carries :meth:`ParallelPlan.describe` as a ``plan``
  field, so a plan flip is a loud miss NAMING the field;
* **fleet resize/grow** read dp and the re-mesh constraints from the plan
  instead of the mesh dict;
* **the pipelined model** reads schedule / stage layout / virtual-stage
  factor from :attr:`ParallelPlan.stage`.

graftlint's ``stage-boundary-vs-plan`` rule keeps it this way: literal
``"pp"`` axis reads or hand-sliced layer spans outside the owner modules
(this file, pipeline.py, mesh.py, the config layer) fire.

Resolution precedence (tested): explicit plugin kwargs beat env vars
(``PP_SCHEDULE``/``PP_VIRTUAL``/``PP_SIZE``), env beats defaults, and bad
values raise at construction — never mid-first-step.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

# the canonical axis names this plan arbitrates; consumers import these
# instead of spelling the literals (the graftlint rule watches for literals)
DP_AXIS = "dp"
FSDP_AXIS = "fsdp"
TP_AXIS = "tp"
SP_AXIS = "sp"
EP_AXIS = "ep"
PP_AXIS = "pp"

# the three resolvable layer layouts (docs/parallel_plan.md §layout contract):
# "plain"     — stacked layer axis in model order (identity; the ONLY layout
#               at V=1, and what every pre-layout checkpoint holds)
# "committed" — stacked layer axis physically permuted into
#               ``StagePlan.layer_order`` ONCE at ``Accelerator.prepare()``;
#               the captured step moves zero permutation bytes (default V>1)
# "gather"    — legacy in-program ``jnp.take`` of the order every step; kept
#               as the A/B reference arm and the unprepared-model fallback
LAYER_LAYOUTS = ("plain", "committed", "gather")


@functools.lru_cache(maxsize=None)
def _layer_orders(num_stages: int, virtual: int, num_layers: int) -> tuple:
    """``(order, inverse)`` permutations of the stacked layer axis for one
    ``(num_stages, virtual, num_layers)`` geometry, computed once per process
    (``inverse_layer_order`` sits on the loss-wrapper path — recomputing the
    full order and inverting it on every call was measurable)."""
    sv = num_stages * virtual
    if num_layers % sv:
        raise ValueError(
            f"num_layers {num_layers} not divisible by "
            f"num_stages×virtual = {num_stages}×{virtual}"
        )
    c = num_layers // sv
    order = []
    for d in range(num_stages):
        for k in range(virtual):
            v = k * num_stages + d
            order.extend(range(v * c, (v + 1) * c))
    inv = [0] * len(order)
    for i, j in enumerate(order):
        inv[j] = i
    return tuple(order), tuple(inv)


@dataclasses.dataclass(frozen=True)
class StagePlan:
    """Pipeline-stage layout: the ONE owner of stage/layer boundaries.

    ``virtual`` is the interleave factor V (MPMD pipeline-parallelism,
    PAPERS.md #4): each pp device hosts V non-contiguous virtual-stage layer
    spans, microbatches hop V× around the ring, and the fill/drain bubble
    shrinks by V (``parallel.pipeline.bubble_fraction``).  ``virtual == 1``
    is the fused 1F1B (or GPipe) layout with one contiguous span per device.
    """

    num_stages: int
    virtual: int = 1
    num_microbatches: int = 1
    schedule: str = "gpipe"  # "gpipe" | "1f1b" | "interleaved"
    # resolved layer layout of record (LAYER_LAYOUTS above).  None = resolve
    # the default: "plain" at V=1 (identity — nothing to commit), "committed"
    # at V>1 (prepare() permutes once, the step moves zero permutation bytes)
    layout: Optional[str] = None

    def __post_init__(self):
        if self.num_stages < 1 or self.virtual < 1 or self.num_microbatches < 1:
            raise ValueError(f"invalid stage plan {self!r}")
        if self.layout is None:
            object.__setattr__(
                self, "layout", "plain" if self.virtual == 1 else "committed"
            )
        if self.layout not in LAYER_LAYOUTS:
            raise ValueError(
                f"layer layout {self.layout!r} not in {LAYER_LAYOUTS}"
            )
        if self.virtual == 1 and self.layout != "plain":
            raise ValueError(
                f"layer_layout={self.layout!r} is meaningless at virtual=1 "
                "(the interleave order is the identity) — use 'plain'"
            )
        if self.virtual > 1 and self.layout == "plain":
            raise ValueError(
                "virtual_stages > 1 needs layer_layout 'committed' (default) "
                "or the legacy 'gather' reference arm, not 'plain'"
            )
        if self.virtual > 1 and self.schedule != "interleaved":
            raise ValueError(
                f"virtual_stages={self.virtual} requires schedule="
                f"'interleaved', got {self.schedule!r}"
            )
        if self.schedule == "interleaved":
            if self.virtual < 2:
                raise ValueError(
                    "schedule='interleaved' needs virtual_stages >= 2 "
                    "(virtual_stages=1 IS the fused '1f1b' schedule)"
                )
            if self.num_stages > 1 and self.num_microbatches % self.num_stages:
                raise ValueError(
                    f"interleaved 1F1B needs num_microbatches "
                    f"({self.num_microbatches}) divisible by the pipeline "
                    f"size ({self.num_stages})"
                )

    @property
    def total_virtual_stages(self) -> int:
        return self.num_stages * self.virtual

    def layers_per_virtual_stage(self, num_layers: int) -> int:
        sv = self.total_virtual_stages
        if num_layers % sv:
            raise ValueError(
                f"num_layers {num_layers} not divisible by "
                f"num_stages×virtual = {self.num_stages}×{self.virtual}"
            )
        return num_layers // sv

    def layer_spans(self, num_layers: int) -> tuple:
        """``((start, stop), ...)`` in VIRTUAL-STAGE order: span ``v`` runs
        on device ``v % num_stages`` as its chunk ``v // num_stages``."""
        c = self.layers_per_virtual_stage(num_layers)
        return tuple((v * c, (v + 1) * c) for v in range(self.total_virtual_stages))

    def layer_order(self, num_layers: int) -> tuple:
        """Host-computed permutation of the stacked layer axis so the plain
        contiguous ``P(pp)`` sharding hands device ``d`` exactly its V
        interleaved chunks, grouped: local rows ``[k*c:(k+1)*c]`` = chunk
        ``k`` = global virtual stage ``k*S + d``.  Identity at V=1.  Under
        the (default) ``committed`` layout ``Accelerator.prepare()`` applies
        this ONCE, physically, and the captured step never permutes; the
        legacy ``gather`` layout applies it as an in-program ``jnp.take``
        every step (the A/B reference arm)."""
        return _layer_orders(self.num_stages, self.virtual, num_layers)[0]

    def inverse_layer_order(self, num_layers: int) -> tuple:
        """Inverse of :meth:`layer_order` — cached per geometry with it."""
        return _layer_orders(self.num_stages, self.virtual, num_layers)[1]

    def permutation_bytes(self, stacked_params) -> int:
        """Analytic bytes the in-program ``gather`` layout moves per step:
        the order leaves only the ``1/V`` of rows already resident in place,
        and the gather runs twice (params forward, grads backward).  Zero
        under ``committed``/``plain`` — the bench A/B row."""
        if self.layout != "gather" or self.virtual == 1:
            return 0
        import jax

        moved_frac = 1.0 - 1.0 / self.virtual
        total = sum(
            leaf.nbytes for leaf in jax.tree_util.tree_leaves(stacked_params)
        )
        return int(total * moved_frac) * 2


@dataclasses.dataclass(frozen=True)
class ParallelPlan:
    """The resolved parallelism layout — one object, every axis.

    Frozen and JSON-describable: :meth:`describe` is the ``plan`` field of
    the AOT-cache topology fingerprint, so any flip that changes the
    compiled program (an axis size, ZeRO mode, compression policy, pipeline
    schedule or virtual factor) is a loud cache miss naming ``plan``.
    ``generation`` moves when a fleet resize re-resolves the plan; captured
    steps drop their compiled variants when it does.
    """

    axes: tuple  # ((name, size), ...) in mesh order
    data_axes: tuple  # axes the global batch shards over
    zero1: bool = False
    zero2: bool = False
    compression: str = "none"
    sp_mode: str = "ring"
    stage: Optional[StagePlan] = None
    generation: int = 0

    # -- axis accessors ------------------------------------------------------
    @property
    def axis_sizes(self) -> dict:
        return dict(self.axes)

    def axis_size(self, name: str) -> int:
        return dict(self.axes).get(name, 1)

    @property
    def dp(self) -> int:
        return self.axis_size(DP_AXIS)

    @property
    def fsdp(self) -> int:
        return self.axis_size(FSDP_AXIS)

    @property
    def tp(self) -> int:
        return self.axis_size(TP_AXIS)

    @property
    def sp(self) -> int:
        return self.axis_size(SP_AXIS)

    @property
    def pp(self) -> int:
        return self.axis_size(PP_AXIS)

    @property
    def layer_layout(self) -> str:
        """The resolved stacked-layer-axis layout of record ("plain" /
        "committed" / "gather", LAYER_LAYOUTS) — who owns the interleave
        permutation.  "plain" outside a pipeline plan."""
        return self.stage.layout if self.stage is not None else "plain"

    @property
    def non_dp_extent(self) -> int:
        """Devices consumed per dp block — the re-mesh constraint fleet
        grow uses to bound a target dp against the visible device pool."""
        out = 1
        for name, size in self.axes:
            if name != DP_AXIS:
                out *= size
        return out

    # -- state shardings (ZeRO-1 masters/moments) ----------------------------
    def state_spec(self, shape: tuple, mesh, param_spec=None):
        """PartitionSpec for one param's optimizer state (fp32 masters +
        moments) under this plan: the ZeRO-1 dp sharding when the plan arms
        it, else the param's own layout — the ONE rule the optimizer
        relayout, checkpoint specs and fleet reshard all follow."""
        from .sharding import canonical_spec, zero1_state_spec
        from jax.sharding import PartitionSpec as P

        if not self.zero1:
            return canonical_spec(param_spec if param_spec is not None else P(), mesh)
        return zero1_state_spec(shape, mesh, param_spec)

    # -- fingerprint ---------------------------------------------------------
    def describe(self) -> dict:
        """JSON-able digest — the AOT fingerprint's ``plan`` field."""
        out = {
            "axes": {name: size for name, size in self.axes if size > 1},
            "zero1": self.zero1,
            "zero2": self.zero2,
            "compression": self.compression,
        }
        if self.sp > 1:
            out["sp_mode"] = self.sp_mode
        if self.stage is not None and (
            self.stage.num_stages > 1 or self.stage.virtual > 1
        ):
            out["schedule"] = self.stage.schedule
            out["virtual"] = self.stage.virtual
            out["microbatches"] = self.stage.num_microbatches
            if self.stage.virtual > 1:
                # committed vs gather compile DIFFERENT steady-state programs
                # (no permutation tensors vs two takes) — a layout flip must
                # be a loud AOT miss naming layer_layout.  Not emitted at
                # V=1 ("plain" is the only layout there; emitting it would
                # gratuitously invalidate every stored fused-1F1B entry).
                out["layer_layout"] = self.stage.layout
        return out

    # -- resolution ----------------------------------------------------------
    @classmethod
    def resolve(cls, state, compression: Optional[str] = None,
                generation: int = 0) -> "ParallelPlan":
        """Resolve the plan from the live AcceleratorState: mesh axis sizes,
        plugins (already env-resolved with kwargs precedence by their own
        ``__post_init__``), and the ZeRO flags.  Bad combinations raise HERE,
        at construction, not mid-first-step."""
        from .mesh import data_axes

        mesh = state.mesh
        axes = tuple((name, int(size)) for name, size in mesh.shape.items())
        pp_size = dict(axes).get(PP_AXIS, 1)

        pp_plugin = getattr(state, "pp_plugin", None)
        stage = None
        if pp_plugin is not None or pp_size > 1:
            schedule = getattr(pp_plugin, "schedule", None) or "gpipe"
            virtual = int(getattr(pp_plugin, "virtual_stages", 1) or 1)
            microbatches = int(getattr(pp_plugin, "num_microbatches", 1) or 1)
            stage = StagePlan(
                num_stages=pp_size,
                virtual=virtual,
                num_microbatches=microbatches,
                schedule=schedule,
                layout=getattr(pp_plugin, "layout", None) or None,
            )

        sp_plugin = getattr(state, "sp_plugin", None)
        return cls(
            axes=axes,
            data_axes=tuple(data_axes(mesh)),
            zero1=bool(state.zero1_enabled),
            zero2=bool(state.zero2_enabled),
            compression=compression or "none",
            sp_mode=getattr(sp_plugin, "mode", "ring") if sp_plugin else "ring",
            stage=stage,
            generation=generation,
        )


def current_plan() -> Optional[ParallelPlan]:
    """The plan of the live Accelerator context (None outside one) — how
    models and library code read the resolved layout without re-deriving
    axis sizes from the mesh."""
    from ..state import AcceleratorState

    return AcceleratorState._shared_state.get("plan")
