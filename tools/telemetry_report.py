#!/usr/bin/env python
"""telemetry_report — render a telemetry JSONL run (docs/telemetry.md).

    python tools/telemetry_report.py run.jsonl
    python tools/telemetry_report.py run.jsonl --json

Input: the ``kind``-tagged JSONL that ``Telemetry.write_jsonl`` /
``ACCELERATE_TELEMETRY_JSONL`` produces (one JSON object per line; kinds:
``meta``/``step``/``device_step``/``recompile``/``program``/``resources``/
``collectives``/``serving``/``aot_cache``/``fleet``/``summary``).
Output: a step-time breakdown table (build steps split out from replays —
averaging a compile into replay dispatch would hide both), the sampled
device-time attribution joined launch-vs-device per step, the recompile
history with attributed causes, per-program HBM/FLOP accounting, a serving
SLO section (TTFT/TPOT percentiles), and fleet skew when the artifact was
rank-aggregated.  Pre-device-time artifacts simply lack those kinds and
render without the new sections.

``validate()`` is the well-formedness check behind ``make telemetry-smoke``:
it returns a list of schema errors (empty = valid).
"""

from __future__ import annotations

import argparse
import json
import sys

STEP_PHASES = (
    "dataloader_wait_ms",
    "assembly_ms",
    "trace_ms",
    "compile_ms",
    "dispatch_ms",
)
STEP_FIELDS = ("step", "key", "built", "total_ms") + STEP_PHASES


def load_records(path: str) -> list[dict]:
    records = []
    with open(path, encoding="utf-8") as f:
        for n, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except ValueError as e:
                raise ValueError(f"{path}:{n}: not JSON: {e}") from None
            if not isinstance(record, dict):
                raise ValueError(f"{path}:{n}: record is not an object")
            records.append(record)
    return records


def validate(records: list[dict], min_steps: int = 0) -> list[str]:
    """Schema errors for a telemetry run; [] when well-formed."""
    errors: list[str] = []
    kinds = [r.get("kind") for r in records]
    if "meta" not in kinds:
        errors.append("no meta record")
    steps = [r for r in records if r.get("kind") == "step"]
    if len(steps) < min_steps:
        errors.append(f"expected >= {min_steps} step records, got {len(steps)}")
    for i, record in enumerate(steps):
        record_ok = True
        for field in STEP_FIELDS:
            if field not in record:
                errors.append(f"step record {i} missing field {field!r}")
                record_ok = False
            elif field.endswith("_ms") and (
                not isinstance(record[field], (int, float)) or record[field] < 0
            ):
                errors.append(f"step record {i}: {field}={record[field]!r}")
                record_ok = False
        if record_ok and record["total_ms"] > 0:
            # the in-call phases partition total_ms; dataloader_wait_ms is
            # measured *between* calls (loader-side) and sits outside it, so
            # it is excluded.  A large hole means a timer went missing
            # (>100% means one double-counted).
            in_call = (p for p in STEP_PHASES if p != "dataloader_wait_ms")
            # retry_wait_ms (split out of dispatch by the resilience PR) is
            # optional: older artifacts predate the field
            covered = (
                sum(record[p] for p in in_call)
                + record.get("retry_wait_ms", 0.0)
            ) / record["total_ms"]
            if not 0.5 <= covered <= 1.5:
                errors.append(
                    f"step record {i}: phases cover {covered:.0%} of total_ms"
                )
    for i, record in enumerate(r for r in records if r.get("kind") == "recompile"):
        if not record.get("cause"):
            errors.append(f"recompile record {i} has no cause")
    # aot_cache records (persistent executable cache) are OPTIONAL — pre-
    # cache artifacts lack them — but a present record must name its event,
    # and a miss must say why (the loud-miss acceptance contract)
    for i, record in enumerate(r for r in records if r.get("kind") == "aot_cache"):
        if record.get("event") not in ("hit", "miss", "store", "store_failed", "warm"):
            errors.append(
                f"aot_cache record {i}: unknown event {record.get('event')!r}"
            )
        elif record["event"] in ("miss", "store_failed") and not record.get("cause"):
            errors.append(f"aot_cache record {i} ({record['event']}) has no cause")
    # device_step records (sampled device-time attribution) are OPTIONAL —
    # pre-device-time artifacts lack them entirely — but when present they
    # must be well-formed and their busy+idle split must account for the
    # profiled window
    for i, record in enumerate(
        r for r in records if r.get("kind") == "device_step"
    ):
        for field in ("step", "window_ms", "busy_ms", "idle_ms"):
            if not isinstance(record.get(field), (int, float)) or record[field] < 0:
                errors.append(
                    f"device_step record {i}: {field}={record.get(field)!r}"
                )
                break
        else:
            if record["window_ms"] > 0:
                covered = (record["busy_ms"] + record["idle_ms"]) / record["window_ms"]
                if not 0.8 <= covered <= 1.2:
                    errors.append(
                        f"device_step record {i}: busy+idle cover "
                        f"{covered:.0%} of the profiled window"
                    )
    return errors


def _mean(values):
    return sum(values) / len(values) if values else 0.0


def _pct(values, q):
    """Nearest-rank percentile of a non-empty list."""
    ordered = sorted(values)
    return ordered[int(q / 100.0 * (len(ordered) - 1))]


def render(records: list[dict]) -> str:
    steps = [r for r in records if r.get("kind") == "step"]
    recompiles = [r for r in records if r.get("kind") == "recompile"]
    programs = [r for r in records if r.get("kind") == "program"]
    collectives = [r for r in records if r.get("kind") == "collectives"]
    resources = [r for r in records if r.get("kind") == "resources"]
    replays = [r for r in steps if not r.get("built")]
    builds = [r for r in steps if r.get("built")]

    lines = [f"telemetry run: {len(steps)} steps ({len(builds)} builds), "
             f"{len(recompiles)} recompile event(s)"]

    lines.append("")
    lines.append("step-time breakdown (ms)")
    header = f"  {'phase':<18}{'replay mean':>12}{'replay max':>12}{'build mean':>12}"
    lines.append(header)
    lines.append("  " + "-" * (len(header) - 2))
    # .get with 0.0: a record missing a phase field already produced a
    # validate() warning — the report must degrade, not crash.
    # retry_wait_ms is rendered but NOT in STEP_FIELDS: pre-split artifacts
    # lack it, and a missing optional field is not a schema error
    for phase in STEP_PHASES + ("retry_wait_ms",):
        lines.append(
            f"  {phase[:-3]:<18}"
            f"{_mean([r.get(phase, 0.0) for r in replays]):>12.3f}"
            f"{max([r.get(phase, 0.0) for r in replays], default=0.0):>12.3f}"
            f"{_mean([r.get(phase, 0.0) for r in builds]):>12.3f}"
        )
    lines.append(
        f"  {'total':<18}"
        f"{_mean([r.get('total_ms', 0.0) for r in replays]):>12.3f}"
        f"{max([r.get('total_ms', 0.0) for r in replays], default=0.0):>12.3f}"
        f"{_mean([r.get('total_ms', 0.0) for r in builds]):>12.3f}"
    )

    device_steps = [r for r in records if r.get("kind") == "device_step"]
    if device_steps:
        # join each sampled device record to its host step by (rank, step):
        # launch latency (dispatch_ms) next to actual device time is the
        # async-dispatch gap this section exists to expose
        by_step = {(r.get("rank"), r.get("step")): r for r in steps}
        lines.append("")
        lines.append("device-time attribution (sampled)")
        header = (
            f"  {'step':>6}{'launch':>9}{'device':>9}{'busy':>9}{'idle':>9}"
            f"{'compute':>9}{'coll':>8}{'xfer':>8}{'coll%':>7}{'mfu':>7}"
        )
        lines.append(header + "   (ms)")
        lines.append("  " + "-" * (len(header) - 2))
        for r in device_steps:
            host = by_step.get((r.get("rank"), r.get("step")), {})
            mfu = r.get("mfu")
            lines.append(
                f"  {r.get('step', '?'):>6}"
                f"{host.get('dispatch_ms', 0.0):>9.2f}"
                f"{r.get('window_ms', 0.0):>9.2f}"
                f"{r.get('busy_ms', 0.0):>9.2f}"
                f"{r.get('idle_ms', 0.0):>9.2f}"
                f"{r.get('compute_ms', 0.0):>9.2f}"
                f"{r.get('collective_ms', 0.0):>8.2f}"
                f"{r.get('transfer_ms', 0.0):>8.2f}"
                f"{100 * r.get('collective_share', 0.0):>6.1f}%"
                + (f"{100 * mfu:>6.1f}%" if isinstance(mfu, (int, float)) else f"{'-':>7}")
            )
        top = (device_steps[-1].get("top_ops") or [])[:5]
        if top:
            lines.append(
                "  top ops (last sample): "
                + ", ".join(f"{name} {ms:.2f}ms" for name, ms in top)
            )
        phases = device_steps[-1].get("phases") or {}
        if phases:
            lines.append("  per-phase split (last sample, ms):")
            for name in sorted(phases):
                split = phases[name]
                lines.append(
                    f"    {name:<22}"
                    f" total {split.get('total_ms', 0.0):>8.2f}"
                    f"  compute {split.get('compute_ms', 0.0):>8.2f}"
                    f"  coll {split.get('collective_ms', 0.0):>8.2f}"
                    f"  xfer {split.get('transfer_ms', 0.0):>7.2f}"
                    f"  ({split.get('ops', 0)} ops)"
                )

    lines.append("")
    if recompiles:
        lines.append("recompile history")
        for r in recompiles:
            lines.append(f"  step {r.get('step', '?'):>4}  [{r.get('recompile_kind', 'key')}] {r.get('cause')}")
    else:
        lines.append("recompile history: none (steady state)")

    if programs:
        lines.append("")
        lines.append("captured programs")
        for r in programs:
            flops = r.get("flops")
            arg_mb = r.get("argument_size_bytes", 0) / 1e6
            tmp_mb = r.get("temp_size_bytes", 0) / 1e6
            lines.append(
                f"  {r.get('label', '?'):<12} {r.get('key', '?'):<13}"
                f" args {arg_mb:8.1f} MB  temps {tmp_mb:8.1f} MB"
                + (f"  {flops / 1e9:8.2f} GFLOP" if flops else "")
            )
    if collectives:
        lines.append("")
        lines.append("dp-collective bytes (per step, analytic)")
        for r in collectives:
            total = r.get("dp_collective_bytes", 0)
            raw = r.get("dp_collective_bytes_uncompressed", 0)
            lines.append(
                f"  policy {r.get('policy', '?'):<18} {total / 1e6:8.2f} MB"
                f"  (uncompressed {raw / 1e6:8.2f} MB,"
                f" ratio {r.get('compression_ratio', 1.0):.2f}x,"
                f" {r.get('tensors_compressed', 0)}/{r.get('tensors_total', 0)}"
                " tensors)"
            )
    kernels = [r for r in records if r.get("kind") == "kernel"]
    if kernels:
        lines.append("")
        lines.append("armed pallas kernels (docs/kernels.md)")
        for r in kernels:
            mode = "interpreter" if r.get("interpret") else "mosaic"
            lines.append(
                f"  {r.get('kernel', '?'):<20} [{mode}]  {r.get('target', '')}"
            )
    if resources:
        lines.append("")
        lines.append("live-bytes samples")
        for r in resources:
            lines.append(
                f"  {r.get('tag', '?'):<12} total {r.get('total_bytes', 0) / 1e6:8.1f} MB"
                f" over {len(r.get('devices', {}))} device(s)"
            )

    aot = [r for r in records if r.get("kind") == "aot_cache"]
    if aot:
        hits = [r for r in aot if r.get("event") == "hit"]
        misses = [r for r in aot if r.get("event") == "miss"]
        stores = [r for r in aot if r.get("event") == "store"]
        lines.append("")
        lines.append(
            f"aot executable cache ({len(hits)} hit(s), {len(misses)} miss(es), "
            f"{len(stores)} store(s))"
        )
        for r in hits:
            avoided = r.get("avoided_compile_ms")
            lines.append(
                f"  hit   [{r.get('scope', '?'):<7}] {str(r.get('key', '?')):<16}"
                f" {(r.get('bytes') or 0) / 1e6:7.2f} MB"
                f"  load {r.get('load_ms', 0.0) or 0.0:8.2f} ms"
                + (
                    f"  (avoided ~{avoided:.0f} ms compile)"
                    if isinstance(avoided, (int, float))
                    else ""
                )
            )
        for r in misses:
            lines.append(
                f"  miss  [{r.get('scope', '?'):<7}] {str(r.get('key', '?')):<16}"
                f" {r.get('cause', '?')}"
            )
        warm = [r for r in aot if r.get("event") == "warm"]
        if warm:
            lines.append(
                f"  restore warms: {len(warm)}, entries staged "
                f"{sum(r.get('entries', 0) or 0 for r in warm)}"
            )

    serving = [r for r in records if r.get("kind") == "serving"]
    if serving:
        completions = [r for r in serving if r.get("event") == "complete"]
        srv_steps = [r for r in serving if r.get("event") == "step"]
        lines.append("")
        lines.append(f"serving SLO ({len(completions)} completions, "
                     f"{len(srv_steps)} engine steps)")
        ttfts = [r["ttft_ms"] for r in completions
                 if isinstance(r.get("ttft_ms"), (int, float))]
        tpots = [r["tpot_ms"] for r in completions
                 if isinstance(r.get("tpot_ms"), (int, float))]
        for name, values in (("TTFT", ttfts), ("TPOT", tpots)):
            if values:
                lines.append(
                    f"  {name:<6} p50 {_pct(values, 50):8.2f} ms   "
                    f"p99 {_pct(values, 99):8.2f} ms   "
                    f"mean {_mean(values):8.2f} ms"
                )
        if srv_steps:
            lines.append(
                f"  occupancy mean {_mean([r.get('occupancy', 0.0) for r in srv_steps]):.2f}"
                f"   queue-depth peak {max(r.get('queue_depth', 0) for r in srv_steps)}"
            )

    for r in records:
        if r.get("kind") != "fleet":
            continue
        lines.append("")
        header = f"fleet skew ({r.get('ranks', '?')} rank(s))"
        if r.get("periodic"):
            # mid-run signal record (docs/elastic.md): one per aggregation
            # cadence tick, so the report shows the skew trajectory
            header += f" — mid-run at step {r.get('at_step', '?')}"
        lines.append(header)
        for stat in r.get("per_rank", []):
            mean_ms = stat.get("replay_total_ms_mean")
            lines.append(
                f"  rank {stat.get('rank', '?'):>3}: "
                + (f"replay mean {mean_ms:8.2f} ms over "
                   f"{stat.get('replay_steps', 0)} steps"
                   if isinstance(mean_ms, (int, float)) else "no replay steps")
            )
        if r.get("slowest_rank") is not None:
            lines.append(
                f"  slowest rank {r['slowest_rank']} vs fastest "
                f"{r['fastest_rank']}: +{r.get('skew_ms', 0.0):.2f} ms"
                + (f" ({r['skew_pct']}%)" if r.get("skew_pct") is not None else "")
                + f", mostly {r.get('straggler_phase', '?')}"
                f" (+{r.get('straggler_phase_delta_ms', 0.0):.2f} ms)"
            )
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="telemetry_report", description=__doc__)
    parser.add_argument("run", help="telemetry JSONL file")
    parser.add_argument("--json", action="store_true", help="summary as JSON")
    parser.add_argument(
        "--validate",
        type=int,
        metavar="N",
        default=None,
        help="validate only: require >= N step records, exit 1 on schema errors",
    )
    args = parser.parse_args(argv)
    try:
        records = load_records(args.run)
    except (OSError, ValueError) as e:
        print(f"telemetry_report: {e}", file=sys.stderr)
        return 2
    errors = validate(records, min_steps=args.validate or 0)
    if args.validate is not None:
        for error in errors:
            print(f"telemetry_report: {error}", file=sys.stderr)
        print(
            f"telemetry_report: {args.run}: "
            + ("INVALID" if errors else "ok")
            + f" ({len([r for r in records if r.get('kind') == 'step'])} steps)"
        )
        return 1 if errors else 0
    if errors:
        for error in errors:
            print(f"telemetry_report: warning: {error}", file=sys.stderr)
    if args.json:
        summaries = [r for r in records if r.get("kind") == "summary"]
        print(json.dumps(summaries[-1] if summaries else {}, indent=2))
    else:
        print(render(records))
    return 0


if __name__ == "__main__":
    sys.exit(main())
