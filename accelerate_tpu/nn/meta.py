"""Meta ("empty") tensors — zero-memory model instantiation.

Capability parity with the reference's ``init_empty_weights`` /
``init_on_device`` (reference: big_modeling.py:58,94), rebuilt for JAX: the
reference re-targets torch's meta device; here a :class:`MetaArray` carries
only (shape, dtype) — the shape/dtype algebra that sizing and placement
planners need — and materialisation happens later via checkpoint loading or
explicit init, placed straight onto its final TPU/host device so peak host
memory never sees the full model.

Creation helpers in :mod:`accelerate_tpu.nn.init` consult the thread-local
meta mode set up here, so ``with init_empty_weights(): model = GPT(cfg)``
allocates nothing and runs no RNG.
"""

from __future__ import annotations

import threading
from typing import Optional

import jax.numpy as jnp
import numpy as np


class MetaArray:
    """Shape+dtype stand-in for an unmaterialised array (torch meta tensor)."""

    __slots__ = ("shape", "dtype")

    def __init__(self, shape, dtype=jnp.float32):
        self.shape = tuple(int(s) for s in shape)
        self.dtype = jnp.dtype(dtype)

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def size(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def nbytes(self) -> int:
        return self.size * self.dtype.itemsize

    def astype(self, dtype) -> "MetaArray":
        return MetaArray(self.shape, dtype)

    def __repr__(self):
        return f"MetaArray(shape={self.shape}, dtype={self.dtype})"


def is_meta(x) -> bool:
    return isinstance(x, MetaArray)


class _MetaState(threading.local):
    def __init__(self):
        self.active: bool = False
        self.include_buffers: bool = True


_meta_state = _MetaState()


def meta_mode_active() -> bool:
    return _meta_state.active


def meta_include_buffers() -> bool:
    return _meta_state.include_buffers


class meta_init:
    """Context manager: array creation through ``nn.init`` yields MetaArrays.

    ``include_buffers=False`` materialises buffers (rotary caches, position
    ids) for real while parameters stay meta — matching the reference's
    ``init_empty_weights(include_buffers=False)`` behavior.
    """

    def __init__(self, include_buffers: bool = True):
        self.include_buffers = include_buffers

    def __enter__(self):
        self._prev = (_meta_state.active, _meta_state.include_buffers)
        _meta_state.active = True
        _meta_state.include_buffers = self.include_buffers
        return self

    def __exit__(self, *exc):
        _meta_state.active, _meta_state.include_buffers = self._prev
        return False
