import jax
import jax.numpy as jnp
import numpy as np
import pytest

import accelerate_tpu.nn as nn
from accelerate_tpu.models import (
    BertConfig,
    BertForSequenceClassification,
    GPTConfig,
    GPTLMHeadModel,
)
from accelerate_tpu.nn import Tensor


@pytest.fixture(autouse=True)
def _seed():
    nn.manual_seed(0)


def test_bert_forward_and_loss():
    cfg = BertConfig.small()
    model = BertForSequenceClassification(cfg)
    ids = jnp.ones((2, 16), dtype=jnp.int32)
    mask = jnp.ones((2, 16), dtype=jnp.int32)
    labels = jnp.array([0, 1])
    out = model(ids, attention_mask=mask, labels=labels)
    assert out["logits"].shape == (2, 2)
    assert np.isfinite(out["loss"].item())
    out["loss"].backward()
    emb_grad = model.bert.embeddings.word_embeddings.weight.grad
    assert emb_grad is not None and bool(jnp.isfinite(emb_grad).all())


def test_bert_padding_mask_effect():
    cfg = BertConfig.small()
    model = BertForSequenceClassification(cfg).eval()
    ids = jnp.ones((1, 8), dtype=jnp.int32)
    full = model(ids, attention_mask=jnp.ones((1, 8)))["logits"].numpy()
    half = model(ids, attention_mask=jnp.array([[1, 1, 1, 1, 0, 0, 0, 0]]))["logits"].numpy()
    assert not np.allclose(full, half)


def test_gpt_forward_loss_and_tied_head():
    cfg = GPTConfig.tiny()
    model = GPTLMHeadModel(cfg)
    ids = jnp.ones((2, 32), dtype=jnp.int32)
    out = model(ids, labels=ids)
    assert out["logits"].shape == (2, 32, cfg.vocab_size)
    assert np.isfinite(out["loss"].item())
    out["loss"].backward()
    assert model.wte.weight.grad is not None
    # tied head: wte grads include the lm-head contribution → nonzero beyond
    # the embedding rows of token 1
    g = np.asarray(model.wte.weight.grad)
    assert np.abs(g).sum() > 0
    names = [n for n, _ in model.named_parameters()]
    # tied head: lm_head.weight IS wte.weight (one object, deduped by default)
    assert "wte.weight" in names and "lm_head.weight" not in names
    assert "lm_head.weight" in dict(model.named_parameters(remove_duplicate=False))
    assert model.lm_head.weight is model.wte.weight


def test_gpt_trains_to_memorize():
    cfg = GPTConfig.tiny()
    model = GPTLMHeadModel(cfg)
    import accelerate_tpu.optim as optim

    opt = optim.AdamW(model.parameters(), lr=1e-2)
    seq = jnp.asarray(np.random.default_rng(0).integers(0, 64, size=(4, 32)))
    losses = []
    for _ in range(30):
        opt.zero_grad()
        out = model(seq, labels=seq)
        out["loss"].backward()
        opt.step()
        losses.append(float(out["loss"].item()))
    assert losses[-1] < losses[0] * 0.5


def test_gpt_causality():
    cfg = GPTConfig.tiny()
    model = GPTLMHeadModel(cfg).eval()
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.integers(0, 64, size=(1, 16)))
    b = jnp.asarray(np.concatenate([np.asarray(a)[:, :8], rng.integers(0, 64, size=(1, 8))], axis=1))
    la = model(a)["logits"].numpy()[:, :8]
    lb = model(b)["logits"].numpy()[:, :8]
    np.testing.assert_allclose(la, lb, atol=1e-5)
