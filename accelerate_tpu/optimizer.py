"""AcceleratedOptimizer — accumulation-aware optimizer wrapper.

Counterpart of ``/root/reference/src/accelerate/optimizer.py`` (212 LoC).
Differences born of SPMD: there is no XLA gradient all-reduce here (reference
optimizer.py:148-154) — under GSPMD the mean over the global batch already
produces identical gradients on every device, compiled into the step.  What
remains is the reference's accumulation contract: ``step``/``zero_grad`` are
no-ops while ``GradientState.sync_gradients`` is False, and fp16 loss-scale
handling wraps the real step.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .state import AcceleratorState, GradientState
from .utils.dataclasses import GradScalerKwargs


class DynamicLossScaler:
    """Dynamic fp16 loss scaling (GradScaler parity, reference via torch).

    bf16 — the TPU default — never needs this; it exists for
    ``mixed_precision='fp16'`` parity and numerics experiments.
    """

    def __init__(self, kwargs: Optional[GradScalerKwargs] = None):
        kwargs = kwargs or GradScalerKwargs()
        self.scale = float(kwargs.init_scale)
        self.growth_factor = kwargs.growth_factor
        self.backoff_factor = kwargs.backoff_factor
        self.growth_interval = kwargs.growth_interval
        self.enabled = kwargs.enabled
        self._growth_tracker = 0

    def scale_loss(self, loss):
        return loss * self.scale if self.enabled else loss

    def unscale_(self) -> float:
        return 1.0 / self.scale if self.enabled else 1.0

    def update(self, found_inf: bool) -> None:
        if not self.enabled:
            return
        if found_inf:
            self.scale = max(self.scale * self.backoff_factor, 1.0)
            self._growth_tracker = 0
        else:
            self._growth_tracker += 1
            if self._growth_tracker >= self.growth_interval:
                self.scale *= self.growth_factor
                self._growth_tracker = 0

    def state_dict(self) -> dict:
        return {"scale": self.scale, "growth_tracker": self._growth_tracker}

    def load_state_dict(self, state: dict) -> None:
        self.scale = state["scale"]
        self._growth_tracker = state["growth_tracker"]


class AcceleratedOptimizer:
    """Wraps an ``accelerate_tpu.optim.Optimizer`` (or anything with
    step/zero_grad/state_dict) with accumulation + scaler semantics."""

    def __init__(self, optimizer, device_placement: bool = True, scaler: Optional[DynamicLossScaler] = None):
        self.optimizer = optimizer
        self.scaler = scaler
        self.accelerator_state = AcceleratorState() if AcceleratorState._shared_state else None
        self.gradient_state = GradientState()
        self.device_placement = device_placement
        self._is_overflow = False
        self._accelerate_step_called = False

    # pass-throughs ----------------------------------------------------------
    @property
    def param_groups(self):
        return self.optimizer.param_groups

    @property
    def defaults(self):
        return self.optimizer.defaults

    @property
    def lr(self):
        return self.optimizer.lr

    @lr.setter
    def lr(self, value):
        self.optimizer.lr = value

    def state_dict(self):
        return self.optimizer.state_dict()

    def load_state_dict(self, state_dict):
        self.optimizer.load_state_dict(state_dict)

    # accumulation-aware ops ---------------------------------------------------
    def zero_grad(self, set_to_none: bool = True) -> None:
        if self.gradient_state.sync_gradients:
            self.optimizer.zero_grad(set_to_none)

    def step(self, closure=None) -> None:
        if not self.gradient_state.sync_gradients:
            return  # mid-accumulation micro-step: skip (reference optimizer.py:161)
        self._accelerate_step_called = True
        if self.scaler is not None:
            import jax

            # single fused finite-check over all grads
            grads = [
                p.grad for p in self.optimizer.param_list if p.grad is not None
            ]
            finite = all(bool(jnp.isfinite(g).all()) for g in grads)
            if finite:
                self.optimizer.step(closure, grad_scale=self.scaler.unscale_())
                self._is_overflow = False
            else:
                self._is_overflow = True
            self.scaler.update(found_inf=not finite)
        else:
            self.optimizer.step(closure)

    @property
    def step_was_skipped(self) -> bool:
        """True when the last ``step`` was dropped due to fp16 overflow."""
        return self._is_overflow

    def train(self):
        if hasattr(self.optimizer, "train"):
            self.optimizer.train()

    def eval(self):
        if hasattr(self.optimizer, "eval"):
            self.optimizer.eval()

    def __repr__(self):
        return f"AcceleratedOptimizer({self.optimizer})"
