"""Rich traceback install (reference /root/reference/src/accelerate/utils/rich.py)."""

from .imports import is_rich_available


def install_rich_tracebacks() -> None:
    if is_rich_available():
        from rich.traceback import install

        install(show_locals=False)
