import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from accelerate_tpu.ops.attention import sdpa_reference
from accelerate_tpu.ops.ring_attention import ring_attention
from accelerate_tpu.state import AcceleratorState
from accelerate_tpu.utils.dataclasses import ParallelismConfig


def _setup(sp=4, dp_extra=2):
    state = AcceleratorState(parallelism_config=ParallelismConfig(sp_size=sp, dp_size=dp_extra))
    return state.mesh


def _place(x, mesh):
    return jax.device_put(x, NamedSharding(mesh, P("dp", None, "sp", None)))


@pytest.mark.parametrize("is_causal", [False, True])
def test_ring_attention_matches_reference(is_causal):
    mesh = _setup()
    b, h, s, d = 2, 2, 32, 8
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (b, h, s, d), dtype=jnp.float32)
    k = jax.random.normal(ks[1], (b, h, s, d), dtype=jnp.float32)
    v = jax.random.normal(ks[2], (b, h, s, d), dtype=jnp.float32)
    expected = sdpa_reference(q, k, v, is_causal=is_causal)
    qs, ks_, vs = _place(q, mesh), _place(k, mesh), _place(v, mesh)
    out = jax.jit(
        lambda a, b_, c: ring_attention(a, b_, c, mesh=mesh, is_causal=is_causal)
    )(qs, ks_, vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=2e-4, atol=2e-5)


def test_ring_attention_grads_match():
    mesh = _setup()
    b, h, s, d = 2, 2, 32, 8
    ks = jax.random.split(jax.random.key(1), 3)
    q = jax.random.normal(ks[0], (b, h, s, d))
    k = jax.random.normal(ks[1], (b, h, s, d))
    v = jax.random.normal(ks[2], (b, h, s, d))

    def ring_loss(q_, k_, v_):
        return ring_attention(q_, k_, v_, mesh=mesh, is_causal=True).sum()

    def ref_loss(q_, k_, v_):
        return sdpa_reference(q_, k_, v_, is_causal=True).sum()

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(_place(q, mesh), _place(k, mesh), _place(v, mesh))
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for gr, ge in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(ge), rtol=5e-4, atol=1e-5)


def test_ring_attention_sp1_fallback():
    state = AcceleratorState()  # sp == 1 → plain attention path
    q = jax.random.normal(jax.random.key(0), (1, 2, 16, 8))
    out = ring_attention(q, q, q, mesh=state.mesh, is_causal=True)
    expected = sdpa_reference(q, q, q, is_causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-5)


@pytest.mark.parametrize("is_causal", [False, True])
def test_ring_flash_hop_path_matches_reference(is_causal, monkeypatch):
    """The TPU hop-kernel ring path (forced on CPU via interpret mode):
    parity with monolithic attention, forward and backward."""
    import accelerate_tpu.ops.flash_attention as fa
    import accelerate_tpu.ops.ring_attention as ra

    monkeypatch.setattr(fa, "_INTERPRET", True)
    monkeypatch.setattr(ra, "_FORCE_FLASH_HOPS", True)

    mesh = _setup(sp=2, dp_extra=4)
    b, h, s, d = 1, 1, 256, 64  # chunk 128 per sp shard: one full MXU tile
    ks = jax.random.split(jax.random.key(2), 3)
    q = jax.random.normal(ks[0], (b, h, s, d), dtype=jnp.float32)
    k = jax.random.normal(ks[1], (b, h, s, d), dtype=jnp.float32)
    v = jax.random.normal(ks[2], (b, h, s, d), dtype=jnp.float32)
    expected = sdpa_reference(q, k, v, is_causal=is_causal)

    spec = NamedSharding(mesh, P(None, None, "sp", None))
    qs, ks_, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = jax.jit(
        lambda a, b_, c: ring_attention(
            a, b_, c, mesh=mesh, is_causal=is_causal, batch_axes=()
        )
    )(qs, ks_, vs)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), rtol=2e-4, atol=2e-4
    )

    def ring_loss(q_, k_, v_):
        return (
            ring_attention(q_, k_, v_, mesh=mesh, is_causal=is_causal, batch_axes=())
            * jnp.arange(d)
        ).sum()

    def ref_loss(q_, k_, v_):
        return (sdpa_reference(q_, k_, v_, is_causal=is_causal) * jnp.arange(d)).sum()

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(qs, ks_, vs)
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for gr, ge in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(ge), rtol=2e-3, atol=2e-3)


# ---------------------------------------------------------------------------
# Ulysses (all-to-all) mode
# ---------------------------------------------------------------------------
def test_ulysses_matches_reference():
    from accelerate_tpu.ops.ring_attention import ulysses_attention

    mesh = _setup(sp=4, dp_extra=2)
    rng = np.random.default_rng(0)
    b, h, s, d = 2, 4, 64, 16
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    for causal in (True, False):
        want = sdpa_reference(q, k, v, is_causal=causal)
        got = jax.jit(
            lambda q, k, v: ulysses_attention(
                _place(q, mesh), _place(k, mesh), _place(v, mesh),
                mesh=mesh, is_causal=causal,
            )
        )(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_ulysses_grads_match_reference():
    from accelerate_tpu.ops.ring_attention import ulysses_attention

    mesh = _setup(sp=4, dp_extra=2)
    rng = np.random.default_rng(1)
    b, h, s, d = 2, 4, 64, 16
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)

    def loss(fn):
        return lambda q, k, v: (fn(q, k, v) ** 2).sum()

    g_want = jax.grad(
        loss(lambda q, k, v: sdpa_reference(q, k, v, is_causal=True)), argnums=(0, 1, 2)
    )(q, k, v)
    g_got = jax.jit(
        jax.grad(
            loss(
                lambda q, k, v: ulysses_attention(
                    _place(q, mesh), _place(k, mesh), _place(v, mesh),
                    mesh=mesh, is_causal=True,
                )
            ),
            argnums=(0, 1, 2),
        )
    )(q, k, v)
    for a, b_ in zip(g_got, g_want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=3e-5)


def test_ulysses_falls_back_when_heads_not_divisible():
    from accelerate_tpu.ops.ring_attention import ulysses_attention

    mesh = _setup(sp=4, dp_extra=2)
    rng = np.random.default_rng(2)
    b, h, s, d = 2, 3, 64, 16  # 3 heads % sp=4 != 0 -> ring fallback
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    want = sdpa_reference(q, k, v, is_causal=True)
    got = ulysses_attention(
        _place(q, mesh), _place(k, mesh), _place(v, mesh), mesh=mesh, is_causal=True
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_sequence_parallel_attention_dispatch():
    from accelerate_tpu.ops import ring_attention as ra

    mesh = _setup(sp=2, dp_extra=4)
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.standard_normal((4, 4, 32, 16)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((4, 4, 32, 16)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((4, 4, 32, 16)), jnp.float32)
    want = sdpa_reference(q, k, v, is_causal=True)
    for mode in ("ring", "all_to_all"):
        got = ra.sequence_parallel_attention(
            _place(q, mesh), _place(k, mesh), _place(v, mesh),
            mesh=mesh, is_causal=True, mode=mode,
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_pipelined_gpt_trains_with_all_to_all_mode():
    """SequenceParallelPlugin(mode='all_to_all') is honored by the trunk."""
    import accelerate_tpu.nn as nn
    import accelerate_tpu.optim as optim
    from accelerate_tpu import Accelerator
    from accelerate_tpu.data_loader import batch_to_global_array
    from accelerate_tpu.models import GPTConfig, PipelinedGPTLMHeadModel
    from accelerate_tpu.utils.dataclasses import SequenceParallelPlugin

    Accelerator._reset_state()
    nn.manual_seed(0)
    acc = Accelerator(
        parallelism_config=ParallelismConfig(sp_size=2, pp_size=2),
        sp_plugin=SequenceParallelPlugin(mode="all_to_all"),
        mixed_precision="bf16",
    )
    cfg = GPTConfig.tiny()
    model = PipelinedGPTLMHeadModel(cfg, num_microbatches=2)
    opt = optim.AdamW(model.parameters(), lr=1e-3)
    model, opt = acc.prepare(model, opt)

    def step_fn(ids):
        opt.zero_grad()
        out = model(ids, labels=ids)
        acc.backward(out["loss"])
        opt.step()
        return out["loss"]

    step = acc.compile_step(step_fn)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, 1024, (4, 64), dtype=np.int32)
    )
    batch = batch_to_global_array(ids, mesh=acc.mesh)
    l1 = float(step(batch))
    l2 = float(step(batch))
    assert np.isfinite(l1) and np.isfinite(l2) and l2 < l1
