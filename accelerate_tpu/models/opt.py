"""OPT-family decoder — the BASELINE.json config-5 north-star family
("benchmarks/big_model_inference OPT-6.7B device_map='auto' sharded
inference", reference benchmarks/big_model_inference/README.md:31-37).

Pre-norm decoder with learned positions (HF's +2 offset), separate biased
q/k/v/out projections, ReLU FFN and a weight-tied head.  Same one-math
structure as models/llama.py: each layer's forward is a single ``tape_op``
over the pure ``opt_attn_in`` / ``opt_attn_out`` pair that the KV-cache
decode engine (models/generation.py) scans over.  Parameter naming mirrors
the HF layout (``layers.N.self_attn.q_proj`` …) for key-mapped checkpoint
ingestion (utils/hf.py) and the torch bridge.

Only ``do_layer_norm_before=True`` geometry is supported (every OPT except
350m; the 6.7B target is pre-norm), and ``word_embed_proj_dim`` must equal
``hidden_size`` (true for 125m/1.3b/2.7b/6.7b/13b/30b/66b).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from .. import nn
from ..nn import Tensor
from .gpt import _pure_layernorm, lm_head_loss, maybe_remat


@dataclasses.dataclass
class OPTConfig:
    vocab_size: int = 50272  # HF value, kept unpadded (head is weight-tied;
    # XLA pads the lone head matmul's N dim internally — measured immaterial
    # next to the decode-loop gathers)
    hidden_size: int = 4096
    ffn_dim: int = 16384
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    max_position_embeddings: int = 2048
    layer_norm_eps: float = 1e-5
    initializer_range: float = 0.02
    do_layer_norm_before: bool = True

    @classmethod
    def tiny(cls) -> "OPTConfig":
        return cls(
            vocab_size=1024, hidden_size=128, ffn_dim=256,
            num_hidden_layers=2, num_attention_heads=4,
            max_position_embeddings=256,
        )

    @classmethod
    def opt_125m(cls) -> "OPTConfig":
        return cls(hidden_size=768, ffn_dim=3072, num_hidden_layers=12,
                   num_attention_heads=12)

    @classmethod
    def opt_1_3b(cls) -> "OPTConfig":
        return cls(hidden_size=2048, ffn_dim=8192, num_hidden_layers=24,
                   num_attention_heads=32)

    @classmethod
    def opt_6_7b(cls) -> "OPTConfig":
        return cls()  # the defaults are OPT-6.7B

    def __post_init__(self):
        if not self.do_layer_norm_before:
            raise NotImplementedError(
                "OPT post-norm geometry (do_layer_norm_before=False, i.e. "
                "opt-350m) is not supported; every other OPT size is pre-norm"
            )


# HF OPTLearnedPositionalEmbedding reserves 2 rows (legacy padding offset):
# table has max_positions + 2 rows, position p reads row p + 2
_POS_OFFSET = 2

# ---------------------------------------------------------------------------
# Pure per-layer math — single source of truth for training AND decode.
# Keys: ln1_{w,b}, {q,k,v,o}_{w,b}, ln2_{w,b}, fc1_{w,b}, fc2_{w,b}
# ---------------------------------------------------------------------------
_LAYER_KEYS = (
    "ln1_w", "ln1_b", "q_w", "q_b", "k_w", "k_b", "v_w", "v_b",
    "o_w", "o_b", "ln2_w", "ln2_b", "fc1_w", "fc1_b", "fc2_w", "fc2_b",
)


def opt_attn_in(l, x, positions, *, n_head: int, eps: float):
    """Pre-norm LN + separate biased q/k/v projections, heads split."""
    b, s, c = x.shape
    d = c // n_head
    h = _pure_layernorm(x, l["ln1_w"], l["ln1_b"], eps)

    def heads(t):
        return t.reshape(b, s, n_head, d).transpose(0, 2, 1, 3)

    q = heads(h @ l["q_w"].T + l["q_b"])
    k = heads(h @ l["k_w"].T + l["k_b"])
    v = heads(h @ l["v_w"].T + l["v_b"])
    return q, k, v


def opt_attn_out(l, x, att, *, eps: float):
    """out_proj + residual, then LN + ReLU FFN + residual."""
    b, s, c = x.shape
    att = att.transpose(0, 2, 1, 3).reshape(b, s, c)
    h = x + att @ l["o_w"].T + l["o_b"]
    h2 = _pure_layernorm(h, l["ln2_w"], l["ln2_b"], eps)
    ff = jnp.maximum(h2 @ l["fc1_w"].T + l["fc1_b"], 0.0)
    return h + ff @ l["fc2_w"].T + l["fc2_b"]


def _opt_block(l, x, positions, *, n_head, eps):
    from ..ops.attention import sdpa_tpu

    q, k, v = opt_attn_in(l, x, positions, n_head=n_head, eps=eps)
    att = sdpa_tpu(q, k, v, is_causal=True)
    return opt_attn_out(l, x, att, eps=eps)


# ---------------------------------------------------------------------------
# Modules
# ---------------------------------------------------------------------------
class OPTAttention(nn.Module):
    def __init__(self, config: OPTConfig):
        super().__init__()
        c = config.hidden_size
        self.q_proj = nn.Linear(c, c)
        self.k_proj = nn.Linear(c, c)
        self.v_proj = nn.Linear(c, c)
        self.out_proj = nn.Linear(c, c)


class OPTDecoderLayer(nn.Module):
    def __init__(self, config: OPTConfig):
        super().__init__()
        self.config = config
        self.self_attn = OPTAttention(config)
        self.self_attn_layer_norm = nn.LayerNorm(
            config.hidden_size, eps=config.layer_norm_eps
        )
        self.fc1 = nn.Linear(config.hidden_size, config.ffn_dim)
        self.fc2 = nn.Linear(config.ffn_dim, config.hidden_size)
        self.final_layer_norm = nn.LayerNorm(
            config.hidden_size, eps=config.layer_norm_eps
        )

    def param_tensors(self):
        a = self.self_attn
        return [  # order == _LAYER_KEYS
            self.self_attn_layer_norm.weight, self.self_attn_layer_norm.bias,
            a.q_proj.weight, a.q_proj.bias, a.k_proj.weight, a.k_proj.bias,
            a.v_proj.weight, a.v_proj.bias, a.out_proj.weight, a.out_proj.bias,
            self.final_layer_norm.weight, self.final_layer_norm.bias,
            self.fc1.weight, self.fc1.bias, self.fc2.weight, self.fc2.bias,
        ]

    def forward(self, x):
        cfg = self.config
        positions = jnp.arange(x.shape[1])

        def fn(xv, *flat):
            l = dict(zip(_LAYER_KEYS, flat))
            return _opt_block(
                l, xv, positions,
                n_head=cfg.num_attention_heads, eps=cfg.layer_norm_eps,
            )

        return nn.tape_op(maybe_remat(fn), x, *self.param_tensors())


class OPTForCausalLM(nn.Module):
    _no_split_modules = ["OPTDecoderLayer"]
    tp_plan = {
        r".*\.(q_proj|k_proj|v_proj)\.weight": ("tp", None),
        r".*\.(q_proj|k_proj|v_proj)\.bias": ("tp",),
        r".*\.out_proj\.weight": (None, "tp"),
        r".*\.fc1\.weight": ("tp", None),
        r".*\.fc1\.bias": ("tp",),
        r".*\.fc2\.weight": (None, "tp"),
        r"embed_tokens\.weight": ("tp", None),
    }

    def __init__(self, config: OPTConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = nn.Embedding(config.vocab_size, config.hidden_size)
        self.embed_positions = nn.Embedding(
            config.max_position_embeddings + _POS_OFFSET, config.hidden_size
        )
        self.layers = nn.ModuleList(
            [OPTDecoderLayer(config) for _ in range(config.num_hidden_layers)]
        )
        self.final_layer_norm = nn.LayerNorm(
            config.hidden_size, eps=config.layer_norm_eps
        )
        from ..nn.meta import is_meta, meta_init

        with meta_init():  # weight-tied head (OPT ties like GPT-2)
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size, bias=False)
        self.lm_head.weight = self.embed_tokens.weight
        from ..nn import random as nn_random

        import jax as _jax

        std = config.initializer_range
        for name, p in self.named_parameters():
            if is_meta(p.data):
                continue
            if p.ndim >= 2:
                p.data = std * _jax.random.normal(nn_random.next_key(), p.shape, p.dtype)
            elif name.endswith("bias"):
                p.data = jnp.zeros_like(p.data)

    def forward(self, input_ids, labels=None):
        from ..parallel.sharding import constrain_activation

        ids = jnp.asarray(input_ids.data if isinstance(input_ids, Tensor) else input_ids)
        s = ids.shape[1]
        pos = jnp.arange(s)[None, :] + _POS_OFFSET
        x = self.embed_tokens(ids) + self.embed_positions(pos)
        x = constrain_activation(x)
        for layer in self.layers:
            x = constrain_activation(layer(x))
        x = self.final_layer_norm(x)
        if labels is not None:
            loss, logits = lm_head_loss(
                x, self.lm_head, labels, self.config.vocab_size
            )
            return {"loss": loss, "logits": logits}
        return {"logits": self.lm_head(x)}

    def generate(self, input_ids, max_new_tokens: int, temperature: float = 0.0,
                 rng=None, quantize_weights=None, **kwargs):
        from .generation import generate

        return generate(self, input_ids, max_new_tokens, temperature, rng,
                        quantize_weights=quantize_weights, **kwargs)

    @property
    def num_flops_per_token(self) -> float:
        n = self.num_parameters
        c = self.config
        attn = 12 * c.num_hidden_layers * c.hidden_size * c.max_position_embeddings
        return 6 * n + attn

    # -- cached decode hooks -------------------------------------------------
    def _decoder_spec(self):
        from .generation import DecoderSpec

        cfg = self.config
        return DecoderSpec(
            family=OPT_DECODER,
            cfg=_OPTDecodeCfg(
                n_head=cfg.num_attention_heads,
                n_kv_head=cfg.num_attention_heads,
                head_dim=cfg.hidden_size // cfg.num_attention_heads,
                eps=cfg.layer_norm_eps,
            ),
            max_len=cfg.max_position_embeddings,
            stack=self._stack_decoder_params,
        )

    def _stack_decoder_params(self) -> tuple[dict, dict]:
        layer_stacks = [layer.param_tensors() for layer in self.layers]
        layers = {
            key: jnp.stack([ts[i].data for ts in layer_stacks])
            for i, key in enumerate(_LAYER_KEYS)
        }
        g = {
            "wte": self.embed_tokens.weight.data,
            "wpe": self.embed_positions.weight.data,
            "ln_f_w": self.final_layer_norm.weight.data,
            "ln_f_b": self.final_layer_norm.bias.data,
        }
        return g, layers


@dataclasses.dataclass(frozen=True)
class _OPTDecodeCfg:
    n_head: int
    n_kv_head: int
    head_dim: int
    eps: float


def _dec_embed(g, ids, positions, cfg):
    return g["wte"][ids] + g["wpe"][positions + _POS_OFFSET][None]


def _dec_attn_in(l, x, positions, cfg):
    return opt_attn_in(l, x, positions, n_head=cfg.n_head, eps=cfg.eps)


def _dec_attn_out(l, x, att, cfg):
    return opt_attn_out(l, x, att, eps=cfg.eps)


def _dec_finalize(g, x, cfg):
    x = _pure_layernorm(x[:, -1], g["ln_f_w"], g["ln_f_b"], cfg.eps)
    return x @ g["wte"].T  # weight-tied head


def _make_opt_decoder():
    from .generation import DecoderFamily

    return DecoderFamily(
        embed=_dec_embed,
        attn_in=_dec_attn_in,
        attn_out=_dec_attn_out,
        finalize=_dec_finalize,
    )


OPT_DECODER = _make_opt_decoder()
