"""Telemetry subsystem (docs/telemetry.md): phases recorded per step on CPU,
recompile forensics attribute the right cause, the disabled path touches
nothing, the tracker bridge writes valid JSONL, and the telemetry AOT
capture path is loss-bitwise-identical to the plain jit path."""

import json
import os
import sys

import jax.numpy as jnp
import numpy as np
import pytest

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator, TelemetryKwargs
from accelerate_tpu.data_loader import batch_to_global_array
from accelerate_tpu.models import GPTConfig, GPTLMHeadModel
from accelerate_tpu.telemetry import (
    StepRecord,
    StepTimeline,
    Telemetry,
    _set_active,
    current_telemetry,
    diff_keys,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _reset_active_telemetry():
    yield
    _set_active(None)


def _tiny_cfg():
    return GPTConfig(vocab_size=256, n_positions=64, n_embd=32, n_layer=1, n_head=2)


def _make_step(enabled=True, acc_kwargs=None, **tel_kwargs):
    nn.manual_seed(0)
    acc = Accelerator(
        kwargs_handlers=[TelemetryKwargs(enabled=enabled, **tel_kwargs)],
        **(acc_kwargs or {}),
    )
    model = GPTLMHeadModel(_tiny_cfg())
    opt = optim.AdamW(model.parameters(), lr=1e-3)
    model, opt = acc.prepare(model, opt)

    def step_fn(ids):
        opt.zero_grad()
        out = model(ids, labels=ids)
        acc.backward(out["loss"])
        opt.step()
        return out["loss"]

    return acc, model, acc.compile_step(step_fn)


def _batch(acc, seq=32, seed=0):
    ids = np.random.default_rng(seed).integers(0, 256, (8, seq), dtype=np.int32)
    return batch_to_global_array(jnp.asarray(ids), mesh=acc.mesh)


# ---------------------------------------------------------------------------
# pillar 1: step-phase timing
# ---------------------------------------------------------------------------

def test_phases_recorded_per_step_and_cover_wall_clock():
    acc, _, step = _make_step()
    batch = _batch(acc)
    for _ in range(3):
        loss = step(batch)
    assert np.isfinite(float(loss))
    records = acc.telemetry.timeline.records()
    assert len(records) == 3
    build, *replays = records
    assert build.built and not any(r.built for r in replays)
    assert build.trace_ms > 0 and build.compile_ms > 0
    for rec in records:
        assert rec.total_ms > 0
        for phase in ("assembly_ms", "trace_ms", "compile_ms", "dispatch_ms",
                      "dataloader_wait_ms"):
            assert getattr(rec, phase) >= 0.0
        # the phases partition __call__: their sum accounts for the wall
        # clock (acceptance: within 20%)
        assert rec.phase_sum_ms <= rec.total_ms * 1.001
        assert rec.phase_sum_ms >= rec.total_ms * 0.8, (
            rec.phase_sum_ms,
            rec.total_ms,
        )
    # replays share the build's variant key and do not re-trace
    assert {r.key for r in records} == {build.key}
    assert len(step._cache) == 1


def test_dataloader_wait_phase_flows_from_prepared_loader():
    acc, _, step = _make_step()

    data = np.random.default_rng(0).integers(0, 256, (128, 32)).astype(np.int32)

    class Dataset:
        def __len__(self):
            return len(data)

        def __getitem__(self, i):
            return data[i]

    from accelerate_tpu.data_loader import prepare_data_loader

    loader = prepare_data_loader(Dataset(), batch_size=8, mesh=acc.mesh)
    waits = []
    for batch in loader:
        step(batch)
        waits.append(acc.telemetry.timeline.last().dataloader_wait_ms)
    assert len(waits) == 2
    assert all(w > 0 for w in waits), waits


def test_prepared_loader_keeps_pinned_hub_after_later_accelerator():
    acc, _, step = _make_step()

    data = np.random.default_rng(0).integers(0, 256, (128, 32)).astype(np.int32)

    class Dataset:
        def __len__(self):
            return len(data)

        def __getitem__(self, i):
            return data[i]

    from accelerate_tpu.data_loader import prepare_data_loader

    loader = acc.prepare_data_loader(
        prepare_data_loader(Dataset(), batch_size=8, mesh=acc.mesh)
    )
    assert loader._telemetry is acc.telemetry
    # a later telemetry-off Accelerator clears the module-global slot …
    acc2 = Accelerator()
    assert current_telemetry() is None
    # … but the prepared loader's wait accounting survives via its pin
    for batch in loader:
        step(batch)
    assert acc.telemetry.timeline.last().dataloader_wait_ms > 0


def test_eager_eval_epoch_wait_is_not_dumped_on_next_step():
    """Batch-scoped wait attribution (ISSUE 8 satellite): an eager eval
    epoch consumes its batches with no captured step, so its accumulated
    loader wait must be settled at epoch end into the hub's eager counter —
    pre-fix it stayed pending and the NEXT captured step's record absorbed
    the whole eval epoch's wait as its own."""
    acc, _, step = _make_step()

    data = np.random.default_rng(0).integers(0, 256, (128, 32)).astype(np.int32)

    class Dataset:
        def __len__(self):
            return len(data)

        def __getitem__(self, i):
            return data[i]

    from accelerate_tpu.data_loader import prepare_data_loader

    loader = prepare_data_loader(Dataset(), batch_size=8, mesh=acc.mesh)
    for _ in loader:  # eager eval epoch: no captured step pops any wait
        pass
    # the regression pin: nothing pending for the next step, the eval
    # epoch's wait is accounted where it belongs
    assert acc.telemetry._dataloader_wait_ms == 0.0
    assert acc.telemetry.eager_dataloader_wait_ms > 0
    assert acc.telemetry.summary()["eager_dataloader_wait_ms"] > 0
    # a captured step after the eval phase still gets its own batch's wait
    for batch in loader:
        step(batch)
        break
    assert acc.telemetry.timeline.last().dataloader_wait_ms > 0


def test_program_labels_stay_unique_across_rebuilds():
    acc, _, step = _make_step()
    step(_batch(acc, seq=32))
    step(_batch(acc, seq=48))
    # evict a variant and replay it: the rebuild (the layout-drift retry
    # shape — pop + rebuild) must get a fresh label, not reuse an old one
    step._cache.clear()
    step(_batch(acc, seq=32))
    labels = [p.label for p in acc.telemetry.program_records]
    assert labels == ["capture:0", "capture:1", "capture:2"]


def test_telemetry_losses_bitwise_equal_to_disabled_path():
    def run(enabled):
        Accelerator._reset_state()
        _set_active(None)
        acc, _, step = _make_step(enabled=enabled)
        batch = _batch(acc)
        return [float(step(batch)) for _ in range(3)]

    assert run(True) == run(False)


# ---------------------------------------------------------------------------
# pillar 2: recompile forensics
# ---------------------------------------------------------------------------

def test_shape_change_emits_recompile_event_naming_the_argument():
    acc, _, step = _make_step()
    step(_batch(acc, seq=32))
    assert len(acc.telemetry.recompile_events) == 0  # first build: expected
    step(_batch(acc, seq=48))
    events = list(acc.telemetry.recompile_events)
    assert len(events) == 1
    assert "arg[0] shape changed" in events[0].cause
    assert "(8, 32)" in events[0].cause and "(8, 48)" in events[0].cause
    assert events[0].kind == "key"
    assert acc.telemetry.recompiles_total == 1


def test_train_eval_flip_emits_recompile_event():
    acc, model, step = _make_step()
    batch = _batch(acc)
    step(batch)
    model.eval()
    step(batch)
    events = list(acc.telemetry.recompile_events)
    assert len(events) == 1
    assert "training changed" in events[0].cause


def test_accumulate_refile_keeps_forensics_baseline():
    """First-call accumulate re-files the cache entry under the traced
    sync_gradients flag; forensics must diff later misses against the
    re-filed key, or the flagship accumulation-boundary recompile loses
    its cause attribution."""
    from accelerate_tpu.nn import F, Tensor

    nn.manual_seed(0)
    acc = Accelerator(
        gradient_accumulation_steps=2,
        kwargs_handlers=[TelemetryKwargs(enabled=True)],
    )
    model = nn.Linear(4, 1)
    opt = optim.SGD(model.parameters(), lr=0.1)
    model, opt = acc.prepare(model, opt)

    def step_fn(xb, yb):
        with acc.accumulate(model):
            pred = model(Tensor(xb)).squeeze(-1)
            loss = F.mse_loss(pred, Tensor(yb))
            acc.backward(loss)
            opt.step()
            opt.zero_grad()
        return loss

    step = acc.compile_step(step_fn)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 4)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(2,)).astype(np.float32))
    step(x, y)  # builds + re-files under the traced sync flag
    step(x, y)  # sync flips at the accumulation boundary → second variant
    events = list(acc.telemetry.recompile_events)
    assert len(events) == 1
    assert "sync_gradients flipped" in events[0].cause, events[0].cause
    # the build's record key matches its variant's replays, not the
    # popped pre-advance key
    records = acc.telemetry.timeline.records()
    step(x, y)  # replay of variant 1
    assert acc.telemetry.timeline.last().key == records[0].key
    # program records follow the re-file too: each variant's HBM/FLOP
    # stats join to its own key, with no cross-variant collision
    prog_keys = [p.key for p in acc.telemetry.program_records]
    assert prog_keys == [records[0].key, records[1].key]
    assert len(set(prog_keys)) == 2


def test_repeated_layout_drift_falls_back_to_plain_jit():
    """One layout drift rebuilds AOT (loud event, fresh executable); a
    second drift on the same variant means layouts alternate — the AOT
    path must yield to plain jit or it would trace+compile every step."""
    acc, _, step = _make_step()
    batch = _batch(acc)
    loss0 = float(step(batch))
    key = next(iter(step._cache))

    class _Rejecting:
        def __call__(self, *a, **k):
            raise ValueError("simulated sharding/layout mismatch")

    def _inject():
        entry = step._cache[key]
        step._cache[key] = (_Rejecting(), *entry[1:])

    _inject()  # drift 1 → loud event, rebuilt still AOT (no .lower on Compiled)
    step(batch)
    assert acc.telemetry.recompile_events[-1].kind == "layout"
    assert not hasattr(step._cache[key][0], "lower")

    _inject()  # drift 2 on the same key → plain-jit fallback (jitted has .lower)
    loss2 = float(step(batch))
    assert "falling back to plain jit" in acc.telemetry.recompile_events[-1].cause
    assert hasattr(step._cache[key][0], "lower")
    assert np.isfinite(loss2) and loss2 != loss0  # training kept moving

    events_before = len(acc.telemetry.recompile_events)
    step(batch)  # jit dispatch absorbs further calls: no new events, no rebuild
    assert len(acc.telemetry.recompile_events) == events_before
    rec = acc.telemetry.timeline.last()
    assert not rec.built and rec.trace_ms == 0.0 and rec.compile_ms == 0.0


def test_diff_keys_names_every_moved_component():
    prev = ("treeA", (((4, 32), "int32"),), True, (True,))
    new = ("treeA", (((4, 48), "int32"),), False, (False,))
    causes = diff_keys(prev, new)
    text = "\n".join(causes)
    assert "arg[0] shape changed" in text
    assert "sync_gradients flipped" in text
    assert "model[0].training changed" in text


# ---------------------------------------------------------------------------
# pillar 3: resource accounting
# ---------------------------------------------------------------------------

def test_capture_records_program_stats_and_resource_sample():
    acc, _, step = _make_step()
    step(_batch(acc))
    programs = list(acc.telemetry.program_records)
    assert len(programs) == 1
    # CPU backend exposes both analyses; at minimum the FLOP count must land
    assert programs[0].stats.get("flops", 0) > 0
    samples = list(acc.telemetry.resource_samples)
    assert len(samples) == 1
    assert samples[0].total_bytes > 0
    # on-demand sampling works outside capture too
    sample = acc.telemetry.sample_resources("manual")
    assert sample.total_bytes > 0 and sample.tag == "manual"


# ---------------------------------------------------------------------------
# telemetry off: identical path, no allocations
# ---------------------------------------------------------------------------

def test_disabled_leaves_ring_buffer_and_counters_untouched(monkeypatch):
    monkeypatch.delenv("ACCELERATE_TELEMETRY", raising=False)
    nn.manual_seed(0)
    acc = Accelerator()  # no handler, env unset → default off
    model = GPTLMHeadModel(_tiny_cfg())
    opt = optim.AdamW(model.parameters(), lr=1e-3)
    model, opt = acc.prepare(model, opt)

    def step_fn(ids):
        opt.zero_grad()
        out = model(ids, labels=ids)
        acc.backward(out["loss"])
        opt.step()
        return out["loss"]

    step = acc.compile_step(step_fn)
    assert step._telemetry is None
    assert current_telemetry() is None
    slots_before = list(acc.telemetry.timeline._slots)
    batch = _batch(acc)
    for _ in range(3):
        step(batch)
    assert len(acc.telemetry.timeline) == 0
    assert acc.telemetry.timeline._slots == slots_before  # ring untouched
    assert acc.telemetry.steps_total == 0
    assert acc.telemetry.recompiles_total == 0
    assert len(acc.telemetry._export_queue) == 0
    # the pre-telemetry host-assembly counters still tick (replays only)
    assert step.host_assembly_calls == 2


def test_ring_buffer_capacity_bounds_retention():
    timeline = StepTimeline(capacity=4)
    for i in range(10):
        timeline.append(
            StepRecord(
                step=i, key="k", built=False, total_ms=1.0, assembly_ms=0.2,
                trace_ms=0.0, compile_ms=0.0, dispatch_ms=0.8,
                dataloader_wait_ms=0.0,
            )
        )
    assert len(timeline) == 4
    assert timeline.total_appended == 10
    assert [r.step for r in timeline.records()] == [6, 7, 8, 9]
    assert timeline.last().step == 9


# ---------------------------------------------------------------------------
# pillar 4: export
# ---------------------------------------------------------------------------

def test_tracker_bridge_writes_valid_jsonl(tmp_path):
    acc, _, step = _make_step(
        acc_kwargs={"log_with": "jsonl", "project_dir": str(tmp_path)}
    )
    acc.init_trackers("run", config={"lr": 1e-3}, init_kwargs={})
    # the bridge was auto-inserted FIRST so end_training's in-order finish()
    # flushes it into delegates that are still open
    names = [t.name for t in acc.trackers]
    assert names == ["telemetry", "jsonl"]
    assert acc.get_tracker("telemetry").tracker is acc.telemetry

    step(_batch(acc, seq=32))
    step(_batch(acc, seq=48))  # recompile event
    acc.log({"loss": 1.0}, step=0)  # piggyback drain
    acc.end_training()

    path = os.path.join(str(tmp_path), "run", "metrics.jsonl")
    records = [json.loads(line) for line in open(path)]
    assert all(isinstance(r, dict) for r in records)
    keys = {k for r in records for k in r}
    assert "telemetry/step/total_ms" in keys
    assert "telemetry/recompile/cause" in keys
    assert any(k.startswith("telemetry/program/") for k in keys)
    # the drain is one-shot: nothing pending after flush
    assert len(acc.telemetry._export_queue) == 0


def test_write_jsonl_roundtrips_through_report_tool(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        from telemetry_report import load_records, render, validate
    finally:
        sys.path.pop(0)

    acc, _, step = _make_step()
    for _ in range(3):
        step(_batch(acc))
    path = str(tmp_path / "run.jsonl")
    acc.telemetry.write_jsonl(path)
    records = load_records(path)
    assert validate(records, min_steps=3) == []
    kinds = {r["kind"] for r in records}
    assert {"meta", "step", "program", "resources", "summary"} <= kinds
    report = render(records)
    assert "step-time breakdown" in report
    assert "steady state" in report  # no recompiles in this run


def test_export_queue_skipped_without_sink():
    """ROADMAP item: with no tracker bridge attached, per-step records skip
    the export queue (and its to_dict()) entirely — sink-less runs like
    bench's primary loop pay zero per-step export work.  The retained
    history (timeline, JSONL dump) is unaffected."""
    acc, _, step = _make_step()
    for _ in range(3):
        step(_batch(acc))
    assert len(acc.telemetry.timeline) == 3  # retained history intact
    assert len(acc.telemetry.program_records) == 1
    assert len(acc.telemetry._export_queue) == 0  # nothing enqueued
    # the JSONL dump feed reads the retained history, not the queue
    kinds = {r["kind"] for r in acc.telemetry.all_records()}
    assert {"step", "program"} <= kinds


def test_bridge_attach_backfills_pre_attach_records(tmp_path):
    """Records produced BEFORE init_trackers (no sink yet → not enqueued)
    still reach the delegates: the bridge backfills from retained history
    when it attaches."""
    acc, _, step = _make_step(
        acc_kwargs={"log_with": "jsonl", "project_dir": str(tmp_path)}
    )
    step(_batch(acc, seq=32))  # pre-attach: queue stays empty
    assert len(acc.telemetry._export_queue) == 0
    acc.init_trackers("run", config=None, init_kwargs={})
    assert len(acc.telemetry._export_queue) > 0  # backfilled on attach
    step(_batch(acc, seq=48))  # post-attach: normal enqueue (recompile too)
    acc.log({"loss": 1.0}, step=0)
    acc.end_training()
    path = os.path.join(str(tmp_path), "run", "metrics.jsonl")
    keys = {k for line in open(path) for k in json.loads(line)}
    # both the pre-attach step and the post-attach recompile were exported
    assert "telemetry/step/total_ms" in keys
    assert "telemetry/recompile/cause" in keys
