"""Bullet-style selection menu on raw terminal input.

Reference parity: commands/menu/selection_menu.py (BulletMenu with ↑/↓, j/k,
digit shortcuts, Enter to confirm, Ctrl-C/Ctrl-D abort) — rebuilt as one
module on termios/tty directly instead of the reference's four-module
cursor/keymap stack.
"""

from __future__ import annotations

import os
import sys
from typing import Optional

_UP = "\x1b[A"
_DOWN = "\x1b[B"
_HIDE_CURSOR = "\x1b[?25l"
_SHOW_CURSOR = "\x1b[?25h"
_CLEAR_LINE = "\x1b[2K"


import contextlib


@contextlib.contextmanager
def _raw_mode(fd: int):
    """Hold the tty in raw mode for the WHOLE menu session.

    One raw window, not one per key: switching back to canonical mode
    between keys makes the line discipline reprocess (and discard) any
    queued bytes — a pasted "↑↑⏎" would lose its tail.
    """
    import termios
    import tty

    old = termios.tcgetattr(fd)
    try:
        tty.setraw(fd)
        yield
    finally:
        termios.tcsetattr(fd, termios.TCSADRAIN, old)


def _read_key(fd: Optional[int] = None) -> str:
    """One keypress from a raw-mode fd (escape sequences folded to one key).

    A bare Escape press is returned as "\\x1b" — the CSI suffix is read only
    when bytes are already pending (select peek), so Esc never blocks waiting
    for two keys that aren't coming.  os.read on the raw fd, not the buffered
    TextIO: readahead would hide pending bytes from the peek.
    """
    import select

    if fd is None:
        fd = sys.stdin.fileno()
    ch = os.read(fd, 1).decode(errors="replace")
    if ch == "\x1b":
        seq = b""
        for _ in range(2):
            ready, _w, _x = select.select([fd], [], [], 0.05)
            if not ready:
                break
            seq += os.read(fd, 1)
        return ch + seq.decode(errors="replace")
    return ch


class BulletMenu:
    """Interactive single-choice menu; returns the selected index.

    Keys: ↑/↓ or k/j move, 0-9 jump, Enter select, Ctrl-C/Ctrl-D raise
    KeyboardInterrupt.  Non-TTY stdin → numbered input() fallback.
    """

    def __init__(self, prompt: str, choices: list[str]):
        self.prompt = prompt
        self.choices = list(choices)

    # -- rendering -----------------------------------------------------------
    def _render(self, pos: int, first: bool, out) -> None:
        if not first:
            out.write(f"\x1b[{len(self.choices)}A")  # cursor up N lines
        for i, choice in enumerate(self.choices):
            marker = "➤ " if i == pos else "  "
            out.write(f"{_CLEAR_LINE}{marker}{choice}\r\n")
        out.flush()

    # -- fallback ------------------------------------------------------------
    def _numbered_fallback(self, default: Optional[int]) -> int:
        labels = " / ".join(f"{i}:{c}" for i, c in enumerate(self.choices))
        suffix = f" [default {default}]" if default is not None else ""
        while True:
            raw = input(f"{self.prompt} ({labels}){suffix}: ").strip()
            if not raw and default is not None:
                return default
            if raw.isdigit() and 0 <= int(raw) < len(self.choices):
                return int(raw)
            lowered = raw.lower()
            for i, c in enumerate(self.choices):
                if c.lower() == lowered:
                    return i
            print(f"Please answer 0-{len(self.choices) - 1} or a choice name.")

    # -- main loop -----------------------------------------------------------
    def run(self, default: Optional[int] = 0) -> int:
        if not sys.stdin.isatty() or not sys.stdout.isatty():
            return self._numbered_fallback(default)

        out = sys.stdout
        pos = default or 0
        out.write(self.prompt + "\r\n")
        out.write(_HIDE_CURSOR)
        fd = sys.stdin.fileno()
        try:
            with _raw_mode(fd):
                first = True
                while True:
                    self._render(pos, first, out)
                    first = False
                    key = _read_key(fd)
                    if key in (_UP, "k"):
                        pos = (pos - 1) % len(self.choices)
                    elif key in (_DOWN, "j"):
                        pos = (pos + 1) % len(self.choices)
                    elif key.isdigit() and int(key) < len(self.choices):
                        pos = int(key)
                    elif key in ("\r", "\n"):
                        return pos
                    elif key in ("\x03", "\x04"):  # Ctrl-C / Ctrl-D
                        raise KeyboardInterrupt
        finally:
            out.write(_SHOW_CURSOR)
            out.flush()
