"""Drive the external-deps analog scripts through subprocesses (reference
Pattern 2/6: tests/test_multigpu.py → test_utils/scripts/external_deps/*)."""

import os
import subprocess
import sys

import pytest

from accelerate_tpu.test_utils.testing import are_slow_tests_enabled

# every test here is a cold subprocess with full XLA recompiles (~90s of
# suite wall-clock); the same script logic runs in-process elsewhere
# (test_launcher.py, test_sharded_checkpoint.py), so the subprocess CLI
# surface is RUN_SLOW-gated as one slow split (VERDICT r3 item 4)
pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(not are_slow_tests_enabled(), reason="test is slow"),
]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(module: str, timeout: int = 420) -> str:
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
        PYTHONPATH=os.pathsep.join(p for p in (REPO, os.environ.get("PYTHONPATH", "")) if p),
    )
    proc = subprocess.run(
        [sys.executable, "-m", module],
        capture_output=True,
        text=True,
        timeout=timeout,
        env=env,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    return proc.stdout


def test_checkpointing_script():
    out = _run("accelerate_tpu.test_utils.scripts.external_deps.test_checkpointing")
    assert "All checkpointing checks passed" in out


def test_peak_memory_script():
    out = _run("accelerate_tpu.test_utils.scripts.external_deps.test_peak_memory_usage")
    assert "All peak-memory checks passed" in out


def test_performance_script():
    out = _run("accelerate_tpu.test_utils.scripts.external_deps.test_performance")
    assert "All performance-parity checks passed" in out


def test_distributed_data_loop_script():
    out = _run("accelerate_tpu.test_utils.scripts.test_distributed_data_loop")
    assert "All distributed data-loop checks passed" in out


def test_merge_weights_script():
    out = _run("accelerate_tpu.test_utils.scripts.test_merge_weights")
    assert "All merge-weights checks passed" in out


def test_metrics_script():
    out = _run("accelerate_tpu.test_utils.scripts.external_deps.test_metrics")
    assert "All metrics checks passed" in out


def test_zero3_integration_script():
    out = _run("accelerate_tpu.test_utils.scripts.external_deps.test_zero3_integration")
    assert "zero3 integration ok" in out


def test_ds_multiple_model_script():
    out = _run("accelerate_tpu.test_utils.scripts.external_deps.test_ds_multiple_model")
    assert "multiple-model ds training ok" in out


def test_pippy_script():
    out = _run("accelerate_tpu.test_utils.scripts.external_deps.test_pippy")
    assert "pipelined gpt2 parity ok" in out
