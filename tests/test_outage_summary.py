"""tools/outage_summary.py: probe-log parsing and up/down accounting."""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.graftlint  # pure stdlib, no tracing — same split

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
from outage_summary import parse_log, summarize  # noqa: E402

LOG = """\
1000 TPU_UP PROBE_OK tpu 1
1300 TPU_UP PROBE_OK tpu 1
1600 DOWN WARNING: something broke
1900 DOWN WARNING: still broken
2500 TPU_UP PROBE_OK tpu 1
2800 DOWN WARNING: broke again
3100 DOWN WARNING: remains broken
garbage line without a timestamp
3400 TPU_UP PROBE_OK tpu 1
"""


def _write(tmp_path, text=LOG, name="TPU_OUTAGE_test.log"):
    path = tmp_path / name
    path.write_text(text)
    return str(path)


def test_parse_skips_malformed_lines(tmp_path):
    probes = parse_log(_write(tmp_path))
    assert len(probes) == 8  # the garbage line is dropped
    assert probes[0] == (1000, True)
    assert probes[2] == (1600, False)


def test_summarize_up_down_and_longest_window(tmp_path):
    s = summarize(parse_log(_write(tmp_path)))
    # intervals attributed to the earlier probe's state:
    # up: 1000→1600 (600) + 2500→2800 (300) = 900
    # down: 1600→2500 (900) + 2800→3400 (600) = 1500
    assert s["up_s"] == 900
    assert s["down_s"] == 1500
    assert s["observed_s"] == 2400
    # longest DOWN window runs from its first DOWN probe to the next UP probe
    assert s["longest_down_s"] == 900
    assert s["longest_down_start"] == 1600
    assert s["longest_down_end"] == 2500
    assert s["transitions"] == 4
    assert s["probes_up"] == 4 and s["probes_down"] == 4


def test_trailing_down_run_counts_to_last_probe(tmp_path):
    text = "1000 TPU_UP ok\n1600 DOWN err\n2600 DOWN err\n"
    s = summarize(parse_log(_write(tmp_path, text)))
    assert s["down_s"] == 1000
    assert s["longest_down_s"] == 1000
    assert s["longest_down_end"] == 2600


def test_cli_json_on_real_repo_logs(tmp_path):
    path = _write(tmp_path)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "outage_summary.py"),
         "--json", path],
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0, proc.stderr
    payload = json.loads(proc.stdout)
    assert payload[path]["availability_pct"] == round(100 * 900 / 2400, 1)


def test_cli_exits_2_when_nothing_parseable(tmp_path):
    path = _write(tmp_path, "no probes here\n", name="empty.log")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "outage_summary.py"), path],
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 2


# ------------------------------------------------------- --bench-json join
from outage_summary import down_windows, join_bench, load_bench_diag  # noqa: E402


def test_down_windows_match_summarize_attribution(tmp_path):
    windows = down_windows(parse_log(_write(tmp_path)))
    assert [(w["start"], w["end"], w["seconds"]) for w in windows] == [
        (1600, 2500, 900),
        (2800, 3400, 600),
    ]


def _write_bench(tmp_path, payload, name="BENCH_test.json", wrap=False):
    path = tmp_path / name
    path.write_text(json.dumps({"parsed": payload} if wrap else payload))
    return str(path)


def test_bench_join_inside_down_window(tmp_path):
    windows = down_windows(parse_log(_write(tmp_path)))
    diag = load_bench_diag(
        _write_bench(
            tmp_path,
            {"init_attempts": 5, "init_detail": "backend init exceeded 120s",
             "fallback": "cpu", "init_ts": 2000},
            wrap=True,  # the driver's {"parsed": {...}} wrapper form
        )
    )
    joined = join_bench("b.json", diag, windows)
    assert joined["init_failed"] is True
    assert joined["in_down_window"] is True
    assert joined["down_window"]["start"] == 1600


def test_bench_join_outside_window_and_unknown_without_ts(tmp_path):
    windows = down_windows(parse_log(_write(tmp_path)))
    outside = join_bench(
        "b.json",
        load_bench_diag(
            _write_bench(tmp_path, {"init_attempts": 1, "init_detail": "cpu 1",
                                    "init_ts": 1100})
        ),
        windows,
    )
    assert outside["init_failed"] is False and outside["in_down_window"] is False
    # r02-r05 artifacts predate init_ts: overlap must report unknown, not False
    legacy = join_bench(
        "r05.json",
        load_bench_diag(
            _write_bench(tmp_path, {"init_attempts": 5, "fallback": "cpu"},
                         name="r05.json")
        ),
        windows,
    )
    assert legacy["init_failed"] is True and legacy["in_down_window"] is None


def test_cli_bench_json_join(tmp_path):
    log = _write(tmp_path)
    bench = _write_bench(
        tmp_path,
        {"init_attempts": 3, "init_detail": "hung", "fallback": "cpu",
         "init_ts": 3000},
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "outage_summary.py"),
         "--json", log, "--bench-json", bench],
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0, proc.stderr
    payload = json.loads(proc.stdout)
    (joined,) = payload["bench_join"]
    assert joined["in_down_window"] is True
    assert joined["down_window"] == {"start": 2800, "end": 3400, "seconds": 600}


# -------------------------------------------------- --telemetry-jsonl join
from outage_summary import join_autopilot, load_autopilot_records  # noqa: E402


def _write_telemetry(tmp_path, records, name="run.jsonl"):
    path = tmp_path / name
    lines = [json.dumps(r) for r in records]
    lines.insert(1, "not json at all")  # the dump interleaves; must be skipped
    path.write_text("\n".join(lines) + "\n")
    return str(path)


_DECISIONS = [
    {"kind": "meta", "schema_version": 1},
    {"kind": "step", "step": 0, "total_ms": 5.0},
    # inside DOWN window 1 (1600-2500): a fired shrink
    {"kind": "autopilot", "ts": 2000, "signal": "host_lost", "action": "shrink",
     "fired": True, "suppressed": False,
     "resize": {"old_dp": 4, "dp": 2, "direction": "shrink"}},
    # inside DOWN window 2 (2800-3400): a suppressed flap
    {"kind": "autopilot", "ts": 3000, "signal": "skew_pct", "action": "shrink",
     "fired": False, "suppressed": True,
     "reason": "debounce: held 1/3 samples"},
    # outside every window
    {"kind": "autopilot", "ts": 1100, "signal": "host_gained", "action": "grow",
     "fired": True, "suppressed": False,
     "resize": {"old_dp": 2, "dp": 4, "direction": "grow"}},
    # no timestamp: counted but unjoinable
    {"kind": "autopilot", "signal": "queue_depth", "action": "grow",
     "fired": False, "suppressed": True},
]


def test_load_autopilot_records_filters_kind_and_bad_lines(tmp_path):
    path = _write_telemetry(tmp_path, _DECISIONS)
    records = load_autopilot_records(path)
    assert len(records) == 4
    assert all(r["kind"] == "autopilot" for r in records)


def test_join_autopilot_attributes_decisions_to_down_windows(tmp_path):
    """ISSUE satellite: the post-mortem join — what the autopilot did
    during each outage window, with fired/suppressed tallies and the dp
    move, plus honest counts for unjoinable records."""
    windows = down_windows(parse_log(_write(tmp_path)))
    records = load_autopilot_records(_write_telemetry(tmp_path, _DECISIONS))
    joined = join_autopilot("run.jsonl", records, windows)
    assert joined["decisions_total"] == 4
    assert joined["decisions_no_ts"] == 1
    assert joined["decisions_outside_windows"] == 1
    w1, w2 = joined["windows"]
    assert w1["window"]["start"] == 1600
    assert w1["fired"] == 1 and w1["suppressed"] == 0
    (d1,) = w1["decisions"]
    assert d1["action"] == "shrink" and d1["signal"] == "host_lost"
    assert d1["resize"] == {"old_dp": 4, "dp": 2, "direction": "shrink"}
    assert w2["fired"] == 0 and w2["suppressed"] == 1
    (d2,) = w2["decisions"]
    assert d2["reason"].startswith("debounce")


def test_cli_telemetry_jsonl_join(tmp_path):
    log = _write(tmp_path)
    jsonl = _write_telemetry(tmp_path, _DECISIONS)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "outage_summary.py"),
         "--json", log, "--telemetry-jsonl", jsonl],
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0, proc.stderr
    payload = json.loads(proc.stdout)
    (joined,) = payload["autopilot_join"]
    assert joined["decisions_total"] == 4
    assert [w["fired"] for w in joined["windows"]] == [1, 0]
    # human rendering names the dp move and the suppression reason
    human = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "outage_summary.py"),
         log, "--telemetry-jsonl", jsonl],
        capture_output=True,
        text=True,
    )
    assert human.returncode == 0, human.stderr
    assert "shrink(host_lost) fired dp 4->2" in human.stdout
    assert "suppressed" in human.stdout and "debounce" in human.stdout


# --------------------------------------------------------- --blackbox join
from outage_summary import join_blackbox, load_blackbox_dumps  # noqa: E402


def _write_blackbox(tmp_path, rank, time_unix=None, reason="watchdog_stall",
                    seq=3, subdir="blackbox"):
    d = tmp_path / subdir
    d.mkdir(exist_ok=True)
    payload = {"kind": "blackbox", "reason": reason, "rank": rank,
               "collective_seq": seq, "events": []}
    if time_unix is not None:
        payload["time_unix"] = time_unix
    path = d / f"blackbox_rank{rank}.json"
    path.write_text(json.dumps(payload))
    return str(path)


def test_blackbox_join_places_dumps_on_the_outage_timeline(tmp_path):
    windows = down_windows(parse_log(_write(tmp_path)))
    _write_blackbox(tmp_path, 0, time_unix=2000)  # inside DOWN 1600→2500
    _write_blackbox(tmp_path, 1, time_unix=2600, reason="signal")  # outside
    dumps = load_blackbox_dumps(str(tmp_path / "blackbox"))
    assert len(dumps) == 2
    joined = join_blackbox("blackbox", dumps, windows)
    assert joined["in_down_windows"] == 1
    by_rank = {d["rank"]: d for d in joined["dumps"]}
    assert by_rank[0]["in_down_window"] is True
    assert by_rank[0]["down_window"] == {"start": 1600, "end": 2500,
                                         "seconds": 900}
    assert by_rank[0]["reason"] == "watchdog_stall"
    assert by_rank[0]["collective_seq"] == 3
    assert by_rank[1]["in_down_window"] is False


def test_blackbox_join_without_timestamp_reports_unknown(tmp_path):
    windows = down_windows(parse_log(_write(tmp_path)))
    _write_blackbox(tmp_path, 0)  # no time_unix: overlap unknowable
    dumps = load_blackbox_dumps(str(tmp_path / "blackbox"))
    joined = join_blackbox("blackbox", dumps, windows)
    assert joined["dumps"][0]["in_down_window"] is None
    assert joined["in_down_windows"] == 0  # unknown is not counted as inside


def test_cli_blackbox_join(tmp_path):
    log = _write(tmp_path)
    _write_blackbox(tmp_path, 0, time_unix=3000)  # inside DOWN 2800→3400
    _write_blackbox(tmp_path, 1, time_unix=2600, reason="signal")
    blackbox_dir = str(tmp_path / "blackbox")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "outage_summary.py"),
         "--json", log, "--blackbox", blackbox_dir],
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0, proc.stderr
    payload = json.loads(proc.stdout)
    (joined,) = payload["blackbox_join"]
    assert joined["in_down_windows"] == 1
    by_rank = {d["rank"]: d for d in joined["dumps"]}
    assert by_rank[0]["down_window"] == {"start": 2800, "end": 3400,
                                         "seconds": 600}
    # human rendering names the rank, reason, seq and the verdict
    human = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "outage_summary.py"),
         log, "--blackbox", blackbox_dir],
        capture_output=True,
        text=True,
    )
    assert human.returncode == 0, human.stderr
    assert "rank 0 (watchdog_stall, seq=3)" in human.stdout
    assert "inside DOWN" in human.stdout
    assert "rank 1 (signal, seq=3)" in human.stdout
    assert "NOT inside any observed DOWN window" in human.stdout
