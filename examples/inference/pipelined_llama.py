"""Pipeline ANY homogeneous-block model: Llama inference over the `pp` axis.

The reference's PiPPy examples split arbitrary torch models at layer
boundaries (/root/reference/examples/inference/pippy/llama.py:1, t5.py:1 —
`prepare_pippy(model, split_points="auto")`). The TPU-native equivalent is a
three-step recipe that works for any model whose trunk is a stack of
shape-preserving blocks, shown here end to end for Llama (GQA + RoPE +
SwiGLU), with the pure per-layer math imported from the model family:

1. stack each layer's weights into one pytree with a leading layer axis,
2. write a ``stage_fn(layer_params, hidden)`` from the family's pure block
   functions (models/llama.py llama_attn_in/llama_attn_out),
3. hand both to ``gpipe`` (parallel/pipeline.py): stages = spans of the
   `pp` mesh axis, microbatches hop over ICI inside one compiled program.

Embedding and LM head stay outside the pipelined trunk (GPipe classic);
``PipelinedGPTLMHeadModel`` packages the same recipe as a ready-made module
(see pipelined_gpt2.py).

Run (CPU smoke, 8 virtual chips):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/inference/pipelined_llama.py --tiny
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.append(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)

from accelerate_tpu import Accelerator, ParallelismConfig  # noqa: E402
from accelerate_tpu.data_loader import batch_to_global_array  # noqa: E402
from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM  # noqa: E402
from accelerate_tpu.models.llama import llama_attn_in, llama_attn_out  # noqa: E402
from accelerate_tpu.ops.attention import sdpa_tpu  # noqa: E402
from accelerate_tpu.parallel.pipeline import gpipe  # noqa: E402
from accelerate_tpu.utils.random import set_seed  # noqa: E402

# one name per tensor in LlamaDecoderLayer.param_tensors() order — the keys
# llama_attn_in/llama_attn_out read
LAYER_KEYS = ("ln1_w", "q_w", "k_w", "v_w", "o_w", "ln2_w", "gate_w", "up_w", "down_w")


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--tiny", action="store_true")
    parser.add_argument("--model_path", default=None, help="HF Llama checkpoint dir")
    parser.add_argument("--pp_size", type=int, default=None)
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--seq_len", type=int, default=128)
    parser.add_argument("--microbatches", type=int, default=2)
    args = parser.parse_args()

    set_seed(42)
    if args.model_path:
        from accelerate_tpu.utils.hf import from_pretrained

        model = from_pretrained(args.model_path, architecture="llama")
    else:
        cfg = LlamaConfig.tiny() if args.tiny else LlamaConfig.llama2_7b_proxy()
        model = LlamaForCausalLM(cfg)
    cfg = model.config

    n_dev = len(jax.devices())
    pp = args.pp_size or max(
        d for d in range(1, n_dev + 1)
        if cfg.num_hidden_layers % d == 0 and n_dev % d == 0
    )
    acc = Accelerator(parallelism_config=ParallelismConfig(pp_size=pp))

    # 1. stack layers: leaf shape (num_layers, ...) — gpipe scans each
    #    stage's contiguous span
    stacked = {
        key: jnp.stack([layer.param_tensors()[i].data for layer in model.layers])
        for i, key in enumerate(LAYER_KEYS)
    }
    globals_ = {
        "wte": model.embed_tokens.weight.data,
        "norm_w": model.norm.weight.data,
        "head_w": model.lm_head.weight.data,
    }

    # 2. pure per-layer stage from the family's block math
    def stage_fn(layer, h):
        positions = jnp.arange(h.shape[1])
        q, k, v = llama_attn_in(
            layer, h, positions,
            n_head=cfg.num_attention_heads, n_kv_head=cfg.num_key_value_heads,
            eps=cfg.rms_norm_eps, theta=cfg.rope_theta,
        )
        group = cfg.num_attention_heads // cfg.num_key_value_heads
        if group > 1:  # GQA: expand kv heads for the flash kernel
            k = jnp.repeat(k, group, axis=1)
            v = jnp.repeat(v, group, axis=1)
        att = sdpa_tpu(q, k, v, is_causal=True, window=cfg.sliding_window)
        return llama_attn_out(layer, h, att, eps=cfg.rms_norm_eps)

    # 3. embedding -> pipelined trunk -> final norm + head, one XLA program
    @jax.jit
    def forward(stacked, g, ids):
        x = g["wte"][ids]
        x = gpipe(stage_fn, stacked, x, num_microbatches=args.microbatches, mesh=acc.mesh)
        x = x * jax.lax.rsqrt(
            jnp.mean(jnp.square(x.astype(jnp.float32)), -1, keepdims=True)
            + cfg.rms_norm_eps
        ).astype(x.dtype) * g["norm_w"]
        return x @ g["head_w"].T

    ids = batch_to_global_array(
        jnp.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab_size, (args.batch_size, args.seq_len)),
            jnp.int32,
        ),
        mesh=acc.mesh,
    )

    t0 = time.perf_counter()
    logits = jax.block_until_ready(forward(stacked, globals_, ids))
    first = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(5):
        logits = forward(stacked, globals_, ids)
    jax.block_until_ready(logits)
    avg = (time.perf_counter() - t0) / 5

    acc.print(f"pp={pp}, batch={args.batch_size}x{args.seq_len}, logits {tuple(logits.shape)}")
    acc.print(f"Time of first pass: {first:.3f}s (includes XLA compile)")
    acc.print(f"Average time per batch: {avg * 1000:.1f}ms")

    # cross-check against the unpipelined model (same weights, same math)
    ref = model(ids)["logits"]
    np.testing.assert_allclose(
        np.asarray(jax.device_get(logits)),
        np.asarray(jax.device_get(ref.data)),
        rtol=2e-2, atol=2e-2,
    )
    acc.print("pipelined logits match the unpipelined forward")


if __name__ == "__main__":
    main()
