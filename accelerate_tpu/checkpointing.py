"""Checkpoint save/load — training-state persistence.

Counterpart of ``/root/reference/src/accelerate/checkpointing.py`` (320 LoC)
with the same on-disk layout contract (one folder per checkpoint holding
model/optimizer/scheduler/sampler/RNG files, names from utils/constants.py)
and the same capabilities: per-object state, registered custom objects,
mid-epoch sampler state, full RNG restoration.

Formats are TPU-native: safetensors (numpy) for weights — zero-copy mmap
loading, no pickle execution — and msgpack (flax.serialization) for optax
pytrees.  Multi-host: only the main process writes replicated state; sharded
params are fully gathered before writing (sharded-per-host layouts land with
the distributed-checkpoint milestone; orbax remains available for that).
"""

from __future__ import annotations

import json
import os
import pickle
import random
from typing import Any, Optional

import jax
import numpy as np

from .logging import get_logger
from .nn import random as nn_random
from .state import PartialState
from .utils.constants import (
    CUSTOM_STATES_NAME,
    MODEL_NAME,
    OPTIMIZER_NAME,
    RNG_STATE_NAME,
    SAMPLER_NAME,
    SCHEDULER_NAME,
)

logger = get_logger(__name__)


def _gather_numpy(value) -> np.ndarray:
    """Device (possibly sharded) array → host numpy, gathering if needed.

    The result is forced C-contiguous: TPU device_get can hand back
    transposed-stride views of the device tiling, and safetensors serializes
    the raw buffer without honoring strides — silent corruption otherwise.
    """
    if isinstance(value, jax.Array) and not value.is_fully_addressable:
        from jax.experimental import multihost_utils

        value = multihost_utils.process_allgather(value, tiled=True)
    arr = np.asarray(jax.device_get(value))
    # ascontiguousarray promotes 0-d to (1,) — scalar params (e.g. a bare
    # nn.Parameter(0.)) must round-trip with their shape intact
    return np.ascontiguousarray(arr).reshape(arr.shape)


def _write_weight_arrays(arrays: dict, directory: str, safe_serialization: bool, name: str) -> str:
    os.makedirs(directory, exist_ok=True)
    if safe_serialization:
        from .native.st import pick_save_file

        save_file = pick_save_file()
        path = os.path.join(directory, f"{name}.safetensors")
        save_file(arrays, path)
    else:
        path = os.path.join(directory, f"{name}.npz")
        np.savez(path, **arrays)
    return path


def save_model_weights(state_dict: dict, directory: str, safe_serialization: bool = True, name: str = MODEL_NAME) -> str:
    """Write a flat {path: array} dict. safetensors by default.

    The host gather is collective (all processes must call this); the write
    happens wherever it is invoked — gate on is_main_process at call sites
    that run on every host.
    """
    arrays = {k: _gather_numpy(v) for k, v in state_dict.items()}
    return _write_weight_arrays(arrays, directory, safe_serialization, name)


def load_model_weights(directory_or_file: str, name: str = MODEL_NAME) -> dict:
    if os.path.isdir(directory_or_file):
        st = os.path.join(directory_or_file, f"{name}.safetensors")
        npz = os.path.join(directory_or_file, f"{name}.npz")
        path = st if os.path.exists(st) else npz
    else:
        path = directory_or_file
    if path.endswith(".safetensors"):
        from .native.st import pick_load_file

        return pick_load_file()(path)
    data = np.load(path)
    return {k: data[k] for k in data.files}


def save_object(obj: Any, path: str, safe_serialization: bool = False) -> None:
    """Generic object save (reference `accelerator.save`, utils/other.py:62)."""
    if hasattr(obj, "state_dict"):
        obj = obj.state_dict()
    if isinstance(obj, dict) and all(
        isinstance(v, (np.ndarray, jax.Array)) for v in obj.values()
    ) and safe_serialization:
        save_model_weights(obj, os.path.dirname(path) or ".", name=os.path.basename(path))
        return
    with open(path, "wb") as f:
        pickle.dump(jax.tree_util.tree_map(_maybe_numpy, obj), f)


def load_object(path: str) -> Any:
    with open(path, "rb") as f:
        return pickle.load(f)


def _maybe_numpy(x):
    if isinstance(x, jax.Array):
        return _gather_numpy(x)
    return x


def _rng_states() -> dict:
    states = {
        "python": random.getstate(),
        "numpy": np.random.get_state(),
        "nn_rng": nn_random.default_rng.get_state(),
    }
    return states


def _restore_rng_states(states: dict) -> None:
    if "python" in states:
        random.setstate(states["python"])
    if "numpy" in states:
        np.random.set_state(states["numpy"])
    if "nn_rng" in states:
        nn_random.default_rng.set_state(states["nn_rng"])


class FrozenState:
    """Immutable ``state_dict()`` stand-in: lets the save path below run on a
    snapshot taken at call time (async checkpointing) instead of live
    objects that training keeps rebinding."""

    def __init__(self, state_dict):
        self._state_dict = state_dict

    def state_dict(self):
        return self._state_dict


import dataclasses as _dataclasses


@_dataclasses.dataclass
class SavePlan:
    """Everything ``write_accelerator_save`` needs, holding NO device handles
    and requiring NO collectives: ``prepare_accelerator_save`` runs every
    gather/D2H at call time on the main thread, so the write phase is safe to
    run from a background thread even multi-process (a thread issuing
    collectives would race the training loop's own — the dispatch-loader
    producer hazard)."""

    output_dir: str
    payloads: list  # (filename, payload, kind in {"weights", "pickle"})
    shard_files: list  # (filename, {slice_key: np.ndarray}) — this host's shards
    index_files: list  # (filename, json_payload) — rank-0 writes
    meta: dict
    rng_filename: str
    rng_payload: dict
    preexisting: set
    ckpt_names: list
    sharded_state: bool
    safe_serialization: bool
    is_main: bool


def prepare_accelerator_save(
    output_dir: str,
    models: list = (),
    optimizers: list = (),
    schedulers: list = (),
    dataloaders: list = (),
    custom_objects: list = (),
    step: int = 0,
    scaler=None,
    safe_serialization: bool = True,
    sharded_state: bool = False,
    rng_states: Optional[dict] = None,
    snapshot: bool = False,
    extra_meta: Optional[dict] = None,
) -> SavePlan:
    """Assemble a :class:`SavePlan`: the collective/device half of a save.

    Every cross-process gather (unsharded multi-host arrays) and every
    device→host transfer happens HERE, so it must run on the main thread of
    every process.  ``snapshot=True`` additionally deep-copies Python-side
    state (scheduler/sampler/scaler dicts) so a training loop that keeps
    running before the write lands cannot mutate the checkpoint — device
    arrays are always materialised to fresh host numpy regardless (donation
    in a later captured step invalidates live buffers, so holding references
    would not be enough).
    """
    state = PartialState()

    # Record which artifacts already exist for every name we are about to
    # write: a reused checkpoint directory may hold files from a PREVIOUS
    # save with a different world size or sharded-ness, and the loader globs
    # every {name}.shard-* file / prefers an index.json — stale files would
    # be silently mixed into (or preferred over) the new state.  Cleanup
    # runs in finalize, AFTER the new artifacts are fully written (deleting
    # first would destroy the only checkpoint if this save crashes
    # mid-write), gated per HOST (dirs may be host-local, not shared).
    import copy as _copy
    import glob as _glob

    ckpt_names = [MODEL_NAME if i == 0 else f"{MODEL_NAME}_{i}" for i in range(len(models))]
    ckpt_names += [
        OPTIMIZER_NAME if i == 0 else f"{OPTIMIZER_NAME}_{i}" for i in range(len(optimizers))
    ]
    preexisting: set[str] = set()
    for name in ckpt_names:
        preexisting.update(_glob.glob(os.path.join(output_dir, f"{name}.shard-*.safetensors")))
        for f in (f"{name}.index.json", f"{name}.safetensors", f"{name}.npz",
                  f"{name}.bin", f"{name}.meta.bin"):
            path = os.path.join(output_dir, f)
            if os.path.exists(path):
                preexisting.add(path)

    def _copy_if_snapshot(obj):
        return _copy.deepcopy(obj) if snapshot else obj

    def _start_d2h(tree):
        # D2H overlap: kick off every device→host copy before the first
        # blocking np.asarray, so the stall is max(transfer), not sum
        for leaf in jax.tree_util.tree_leaves(tree):
            if hasattr(leaf, "copy_to_host_async") and getattr(
                leaf, "is_fully_addressable", True
            ):
                leaf.copy_to_host_async()

    payloads: list[tuple[str, Any, str]] = []  # (filename, payload, kind)
    shard_files: list[tuple[str, dict]] = []
    index_files: list[tuple[str, Any]] = []
    if sharded_state:
        from .utils.fsdp_utils import collect_sharded_model_state, sharded_index_path

        # every process collects (and later writes) its own shards — the
        # assembly is host-local, no collectives involved
        for i, model in enumerate(models):
            name = MODEL_NAME if i == 0 else f"{MODEL_NAME}_{i}"
            fname, arrays, index = collect_sharded_model_state(
                model.state_dict(), name=name
            )
            shard_files.append((fname, arrays))
            index_files.append((os.path.basename(sharded_index_path(".", name)), index))
        for i, opt in enumerate(optimizers):
            inner = opt.optimizer if hasattr(opt, "optimizer") else opt
            oname = OPTIMIZER_NAME if i == 0 else f"{OPTIMIZER_NAME}_{i}"
            arrays, meta = inner.sharded_state_arrays()
            fname, collected, index = collect_sharded_model_state(arrays, name=oname)
            shard_files.append((fname, collected))
            index_files.append((os.path.basename(sharded_index_path(".", oname)), index))
            payloads.append((f"{oname}.meta.bin", _copy_if_snapshot(meta), "pickle"))
    else:
        for model in models:
            _start_d2h(list(model.state_dict().values()))
        for opt in optimizers:
            _start_d2h(opt.state_dict())
        for i, model in enumerate(models):
            name = MODEL_NAME if i == 0 else f"{MODEL_NAME}_{i}"
            arrays = {k: _gather_numpy(v) for k, v in model.state_dict().items()}
            payloads.append((name, arrays, "weights"))
        for i, opt in enumerate(optimizers):
            name = f"{OPTIMIZER_NAME}.bin" if i == 0 else f"{OPTIMIZER_NAME}_{i}.bin"
            # deepcopy under snapshot for the same reason as custom_objects:
            # tree_map rebuilds containers but passes unregistered mutable
            # leaves through by reference
            payloads.append(
                (
                    name,
                    _copy_if_snapshot(jax.tree_util.tree_map(_maybe_numpy, opt.state_dict())),
                    "pickle",
                )
            )
    for i, sched in enumerate(schedulers):
        name = f"{SCHEDULER_NAME}.bin" if i == 0 else f"{SCHEDULER_NAME}_{i}.bin"
        payloads.append((name, _copy_if_snapshot(sched.state_dict()), "pickle"))
    for i, dl in enumerate(dataloaders):
        name = f"{SAMPLER_NAME}.bin" if i == 0 else f"{SAMPLER_NAME}_{i}.bin"
        if hasattr(dl, "state_dict"):
            payloads.append((name, _copy_if_snapshot(dl.state_dict()), "pickle"))
    for i, obj in enumerate(custom_objects):
        name = f"{CUSTOM_STATES_NAME}_{i}.pkl"
        # deepcopy under snapshot: tree_map rebuilds dict/list containers but
        # passes unregistered mutable leaves (deques, stats objects) through
        # by reference — training could mutate them before the write lands
        payloads.append(
            (
                name,
                _copy_if_snapshot(jax.tree_util.tree_map(_maybe_numpy, obj.state_dict())),
                "pickle",
            )
        )
    meta = {"step": step}
    if scaler is not None:
        meta["scaler"] = _copy_if_snapshot(scaler.state_dict())
    if extra_meta:
        # spec-carrying descriptors the Accelerator owns — e.g. the
        # ``layer_layout`` record (docs/parallel_plan.md §layout contract):
        # arrays are saved AS-IS in the run's committed layer order, and the
        # descriptor is what lets a restore into a DIFFERENT layout
        # transpose them (pre-layout checkpoints simply lack the field)
        meta.update(_copy_if_snapshot(dict(extra_meta)))

    # RNG state is per-process (reference checkpointing.py:143-172) and
    # captured at call time so async saves don't leak later draws
    return SavePlan(
        output_dir=output_dir,
        payloads=payloads,
        shard_files=shard_files,
        index_files=index_files,
        meta=meta,
        rng_filename=f"{RNG_STATE_NAME}_{state.process_index}.pkl",
        rng_payload=rng_states if rng_states is not None else _rng_states(),
        preexisting=preexisting,
        ckpt_names=ckpt_names,
        sharded_state=sharded_state,
        safe_serialization=safe_serialization,
        is_main=state.is_main_process,
    )


def write_accelerator_save(plan: SavePlan) -> None:
    """Pure file IO — no collectives, no device access.  Safe to run from a
    background thread on every process concurrently with training."""
    from .native.st import pick_save_file
    from .telemetry import flightrec
    from .utils.fsdp_utils import SHARD_FILE_METADATA

    # flight events bracket the IO (docs/telemetry.md §flight recorder): a
    # process that dies mid-checkpoint shows ckpt_write_begin with no _end
    flightrec.record(
        "ckpt_write_begin", dir=plan.output_dir, shards=len(plan.shard_files)
    )
    os.makedirs(plan.output_dir, exist_ok=True)
    save_file = pick_save_file()
    for fname, arrays in plan.shard_files:
        save_file(arrays, os.path.join(plan.output_dir, fname), metadata=SHARD_FILE_METADATA)
    if plan.is_main:
        for fname, index in plan.index_files:
            with open(os.path.join(plan.output_dir, fname), "w") as f:
                json.dump(index, f, indent=1)
        for name, payload, kind in plan.payloads:
            if kind == "weights":
                _write_weight_arrays(payload, plan.output_dir, plan.safe_serialization, name)
            else:
                with open(os.path.join(plan.output_dir, name), "wb") as f:
                    pickle.dump(payload, f)
    with open(os.path.join(plan.output_dir, plan.rng_filename), "wb") as f:
        pickle.dump(plan.rng_payload, f)
    flightrec.record("ckpt_write_end", dir=plan.output_dir)
    # NOTE: accelerator_meta.json — the completion sentinel — is written in
    # finalize_accelerator_save, AFTER the cross-process barrier: only then
    # have EVERY rank's shard/rng writes landed, so its presence proves the
    # whole checkpoint (not just this rank's slice) is durable.


def finalize_accelerator_save(plan: SavePlan, cleanup: bool = True) -> None:
    """Collective epilogue: barrier all processes past their writes, write
    the completion sentinel, then drop PREEXISTING artifacts this save did
    not overwrite (e.g. shard files from a different world size, or a stale
    index.json after a sharded→full transition).  Runs on the main thread —
    for async saves, from ``wait_for_checkpoint`` after the writer joins;
    ``cleanup=False`` (a writer failed on some rank) skips BOTH — the folder
    stays detectably incomplete and older checkpoint files stay loadable."""
    import glob as _glob

    state = PartialState()
    state.wait_for_everyone()
    if cleanup and plan.is_main:
        # the sentinel: past the barrier above, every rank's writes are on
        # disk, so accelerator_meta.json's presence proves the WHOLE
        # checkpoint complete (is_complete_checkpoint/latest_checkpoint)
        with open(os.path.join(plan.output_dir, "accelerator_meta.json"), "w") as f:
            json.dump(plan.meta, f)
    if cleanup and getattr(state, "is_local_main_process", state.is_main_process):
        world = state.num_processes
        valid: set[str] = set()
        for name in plan.ckpt_names:
            if plan.sharded_state:
                valid.update(
                    _glob.glob(
                        os.path.join(
                            plan.output_dir, f"{name}.shard-*-of-{world:05d}.safetensors"
                        )
                    )
                )
                valid.add(os.path.join(plan.output_dir, f"{name}.index.json"))
                valid.add(os.path.join(plan.output_dir, f"{name}.meta.bin"))
            else:
                valid.add(os.path.join(plan.output_dir, f"{name}.safetensors"))
                valid.add(os.path.join(plan.output_dir, f"{name}.npz"))
                valid.add(os.path.join(plan.output_dir, f"{name}.bin"))
        for path in plan.preexisting - valid:
            if os.path.exists(path):
                os.remove(path)
    state.wait_for_everyone()
    logger.info(f"Saved accelerator state to {plan.output_dir}")


def save_accelerator_state(
    output_dir: str,
    models: list = (),
    optimizers: list = (),
    schedulers: list = (),
    dataloaders: list = (),
    custom_objects: list = (),
    step: int = 0,
    scaler=None,
    safe_serialization: bool = True,
    sharded_state: bool = False,
    rng_states: Optional[dict] = None,
) -> str:
    """Reference save_accelerator_state checkpointing.py:57.

    ``sharded_state=True`` writes model weights AND optimizer state as
    per-host GSPMD shard files (utils/fsdp_utils.py) instead of gathering
    full arrays to every host — no full-model materialisation, O(shard)
    host memory, N→M resharded restore.  Counterpart of the reference's
    FSDP SHARDED_STATE_DICT path incl. the optimizer
    (fsdp_utils.py:66-246, save_fsdp_optimizer :175).

    Implemented as prepare (collectives + D2H) → write (file IO) →
    finalize (barriers + stale-artifact cleanup); the async checkpoint path
    (accelerator.save_state) runs the same three phases with the middle one
    on a writer thread.
    """
    plan = prepare_accelerator_save(
        output_dir,
        models=models,
        optimizers=optimizers,
        schedulers=schedulers,
        dataloaders=dataloaders,
        custom_objects=custom_objects,
        step=step,
        scaler=scaler,
        safe_serialization=safe_serialization,
        sharded_state=sharded_state,
        rng_states=rng_states,
    )
    write_accelerator_save(plan)
    finalize_accelerator_save(plan)
    return output_dir


def load_accelerator_state(
    input_dir: str,
    models: list = (),
    optimizers: list = (),
    schedulers: list = (),
    dataloaders: list = (),
    custom_objects: list = (),
    scaler=None,
) -> dict:
    """Reference load_accelerator_state checkpointing.py:175. Returns
    overrides (e.g. {'step': n})."""
    from .telemetry import flightrec

    state = PartialState()
    if not os.path.isdir(input_dir):
        raise FileNotFoundError(f"checkpoint dir {input_dir} does not exist")
    flightrec.record("ckpt_load_begin", dir=input_dir)

    from .utils.fsdp_utils import load_sharded_resharded, sharded_index_path

    for i, model in enumerate(models):
        name = MODEL_NAME if i == 0 else f"{MODEL_NAME}_{i}"
        if os.path.exists(sharded_index_path(input_dir, name)):
            # sharded checkpoint: assemble only this host's blocks, on the
            # CURRENT layout (N→M resharding is free — bounds are global)
            targets = model.state_dict()
            loaded = load_sharded_resharded(targets, input_dir, name=name)
            model.load_state_dict(loaded)
            continue
        weights = load_model_weights(input_dir, name=name)
        prior_shardings = {
            n: (p.data.sharding if isinstance(p.data, jax.Array) else None)
            for n, p in model.named_parameters()
        }
        model.load_state_dict(weights)
        # loading replaced arrays host-side; restore each param's mesh layout
        for n, p in model.named_parameters():
            sharding = prior_shardings.get(n)
            if sharding is not None:
                p.data = jax.device_put(p.data, sharding)
    for i, opt in enumerate(optimizers):
        oname = OPTIMIZER_NAME if i == 0 else f"{OPTIMIZER_NAME}_{i}"
        if os.path.exists(sharded_index_path(input_dir, oname)):
            inner = opt.optimizer if hasattr(opt, "optimizer") else opt
            with open(os.path.join(input_dir, f"{oname}.meta.bin"), "rb") as f:
                meta = pickle.load(f)
            targets = inner.sharded_state_targets()
            with open(sharded_index_path(input_dir, oname)) as f:
                stored = json.load(f).get("tensors", {})
            # EF residual targets (docs/compression.md) are OPTIONAL: a
            # checkpoint saved before the compression layer, or under a
            # different policy, doesn't carry them — the residual then
            # restarts at zero instead of failing the whole restore
            targets = {
                k: v
                for k, v in targets.items()
                if k in stored or not k.startswith("comp_rs_")
            }
            arrays = load_sharded_resharded(targets, input_dir, name=oname)
            inner.load_sharded_state_arrays(arrays, meta)
            continue
        name = f"{OPTIMIZER_NAME}.bin" if i == 0 else f"{OPTIMIZER_NAME}_{i}.bin"
        with open(os.path.join(input_dir, name), "rb") as f:
            opt.load_state_dict(pickle.load(f))
    for i, sched in enumerate(schedulers):
        name = f"{SCHEDULER_NAME}.bin" if i == 0 else f"{SCHEDULER_NAME}_{i}.bin"
        with open(os.path.join(input_dir, name), "rb") as f:
            sched.load_state_dict(pickle.load(f))
    for i, dl in enumerate(dataloaders):
        name = f"{SAMPLER_NAME}.bin" if i == 0 else f"{SAMPLER_NAME}_{i}.bin"
        path = os.path.join(input_dir, name)
        if os.path.exists(path) and hasattr(dl, "load_state_dict"):
            with open(path, "rb") as f:
                dl.load_state_dict(pickle.load(f))
    for i, obj in enumerate(custom_objects):
        name = f"{CUSTOM_STATES_NAME}_{i}.pkl"
        with open(os.path.join(input_dir, name), "rb") as f:
            obj.load_state_dict(pickle.load(f))

    overrides: dict = {}
    meta_path = os.path.join(input_dir, "accelerator_meta.json")
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        overrides["step"] = meta.get("step", 0)
        if "layer_layout" in meta:
            # the saver's stacked-layer-axis layout descriptor; the caller
            # (Accelerator.load_state) transposes restored arrays when it
            # differs from the live layout.  Absent on every pre-layout
            # checkpoint — those are plain and load bitwise into plain runs.
            overrides["layer_layout"] = meta["layer_layout"]
        if scaler is not None and "scaler" in meta:
            scaler.load_state_dict(meta["scaler"])

    rng_file = os.path.join(input_dir, f"{RNG_STATE_NAME}_{state.process_index}.pkl")
    if not os.path.exists(rng_file):
        rng_file = os.path.join(input_dir, f"{RNG_STATE_NAME}_0.pkl")
    if os.path.exists(rng_file):
        with open(rng_file, "rb") as f:
            _restore_rng_states(pickle.load(f))
    flightrec.record("ckpt_load_end", dir=input_dir)
    logger.info(f"Loaded accelerator state from {input_dir}")
    return overrides


def is_complete_checkpoint(path: str) -> bool:
    """True when ``path`` holds a checkpoint whose save finished everywhere.

    ``accelerator_meta.json`` is written by ``finalize_accelerator_save``
    after the cross-process barrier, so its presence proves every rank's
    model/optimizer/scheduler/RNG artifacts landed — the sentinel the
    resilience subsystem (rollback targets, preemption resume) keys on.
    """
    return os.path.isfile(os.path.join(path, "accelerator_meta.json"))


def checkpoint_step(path: str) -> Optional[int]:
    """The training step a COMPLETE checkpoint was taken at (its meta
    sentinel's ``step``), or ``None`` for an incomplete/foreign folder.
    The elastic fleet's restore-point vote orders candidates by this."""
    meta_path = os.path.join(path, "accelerator_meta.json")
    try:
        with open(meta_path, encoding="utf-8") as f:
            meta = json.load(f)
        if not isinstance(meta, dict):
            return None  # foreign/corrupt sentinel: not a candidate, not a crash
        return int(meta.get("step", 0))
    except (OSError, ValueError, TypeError):
        return None


def latest_checkpoint(base_dir: str) -> Optional[str]:
    """Newest COMPLETE ``checkpoint_N`` folder under ``base_dir`` (the
    automatic-checkpoint-naming layout), or ``None``.  Skips folders whose
    completion sentinel is missing — a save killed mid-write must not be
    chosen over the older checkpoint it was about to supersede."""
    if not os.path.isdir(base_dir):
        return None
    folders = [
        f
        for f in os.listdir(base_dir)
        if f.startswith("checkpoint_") and f.split("_")[-1].isdigit()
    ]
    for folder in sorted(folders, key=lambda f: int(f.split("_")[-1]), reverse=True):
        path = os.path.join(base_dir, folder)
        if is_complete_checkpoint(path):
            return path
    return None


def save_custom_state(obj, path: str, index: int = 0) -> None:
    with open(os.path.join(path, f"{CUSTOM_STATES_NAME}_{index}.pkl"), "wb") as f:
        pickle.dump(jax.tree_util.tree_map(_maybe_numpy, obj.state_dict()), f)


def load_custom_state(obj, path: str, index: int = 0) -> None:
    with open(os.path.join(path, f"{CUSTOM_STATES_NAME}_{index}.pkl"), "rb") as f:
        obj.load_state_dict(pickle.load(f))
