"""AcceleratedOptimizer — accumulation-aware optimizer wrapper.

Counterpart of ``/root/reference/src/accelerate/optimizer.py`` (212 LoC).
Differences born of SPMD: there is no XLA gradient all-reduce here (reference
optimizer.py:148-154) — under GSPMD the mean over the global batch already
produces identical gradients on every device, compiled into the step.  What
remains is the reference's accumulation contract: ``step``/``zero_grad`` are
no-ops while ``GradientState.sync_gradients`` is False, and fp16 loss-scale
handling wraps the real step.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .state import AcceleratorState, GradientState
from .utils.dataclasses import GradScalerKwargs


class DynamicLossScaler:
    """Dynamic fp16 loss scaling (GradScaler parity, reference via torch,
    accelerator.py:2384 + optimizer.py:161-178).

    bf16 — the TPU default — never needs this; it exists for
    ``mixed_precision='fp16'`` parity and numerics experiments.

    State (scale, growth tracker, last-overflow flag) lives in jnp arrays so
    the whole scaler — overflow detection, step skip, scale backoff/growth —
    traces into the captured XLA step program: ``update_traced`` is pure
    ``jnp.where`` math on that state, no host branching.  The overflow flag
    needs no explicit all-reduce: under GSPMD every device computes the same
    global isfinite() over the (sharded) grads, XLA inserts the collective.
    """

    def __init__(self, kwargs: Optional[GradScalerKwargs] = None):
        kwargs = kwargs or GradScalerKwargs()
        self.scale = jnp.asarray(float(kwargs.init_scale), dtype=jnp.float32)
        self.growth_factor = kwargs.growth_factor
        self.backoff_factor = kwargs.backoff_factor
        self.growth_interval = kwargs.growth_interval
        self.enabled = kwargs.enabled
        self._growth_tracker = jnp.asarray(0, dtype=jnp.int32)
        self.last_overflow = jnp.asarray(False)

    def scale_loss(self, loss):
        return loss * self.scale if self.enabled else loss

    def unscale_(self):
        return 1.0 / self.scale if self.enabled else 1.0

    def update_traced(self, finite) -> None:
        """Pure-jnp scale update: works traced (capture) and eager alike."""
        if not self.enabled:
            return
        finite = jnp.asarray(finite)
        tracker = self._growth_tracker + 1
        grow = tracker >= self.growth_interval
        scale_ok = jnp.where(grow, self.scale * self.growth_factor, self.scale)
        tracker_ok = jnp.where(grow, 0, tracker).astype(jnp.int32)
        self.scale = jnp.where(
            finite, scale_ok, jnp.maximum(self.scale * self.backoff_factor, 1.0)
        )
        self._growth_tracker = jnp.where(finite, tracker_ok, 0).astype(jnp.int32)
        self.last_overflow = ~finite

    def update(self, found_inf: bool) -> None:
        self.update_traced(jnp.asarray(not found_inf))

    # -- capture threading ----------------------------------------------------
    def capture_state(self) -> dict:
        return {
            "scale": self.scale,
            "growth_tracker": self._growth_tracker,
            "last_overflow": self.last_overflow,
        }

    def bind_capture_state(self, state: dict) -> None:
        self.scale = state["scale"]
        self._growth_tracker = state["growth_tracker"]
        self.last_overflow = state["last_overflow"]

    def state_dict(self) -> dict:
        return {
            "scale": float(self.scale),
            "growth_tracker": int(self._growth_tracker),
        }

    def load_state_dict(self, state: dict) -> None:
        self.scale = jnp.asarray(float(state["scale"]), dtype=jnp.float32)
        self._growth_tracker = jnp.asarray(int(state["growth_tracker"]), dtype=jnp.int32)


class AcceleratedOptimizer:
    """Wraps an ``accelerate_tpu.optim.Optimizer`` (or anything with
    step/zero_grad/state_dict) with accumulation + scaler semantics."""

    def __init__(self, optimizer, device_placement: bool = True, scaler: Optional[DynamicLossScaler] = None):
        self.optimizer = optimizer
        self.scaler = scaler
        self.accelerator_state = AcceleratorState() if AcceleratorState._shared_state else None
        self.gradient_state = GradientState()
        self.device_placement = device_placement
        self._is_overflow = False
        self._accelerate_step_called = False
        self._grads_unscaled = False

    # pass-throughs ----------------------------------------------------------
    @property
    def param_groups(self):
        return self.optimizer.param_groups

    @property
    def defaults(self):
        return self.optimizer.defaults

    @property
    def lr(self):
        return self.optimizer.lr

    @lr.setter
    def lr(self, value):
        self.optimizer.lr = value

    def state_dict(self):
        return self.optimizer.state_dict()

    def load_state_dict(self, state_dict):
        self.optimizer.load_state_dict(state_dict)

    # accumulation-aware ops ---------------------------------------------------
    def zero_grad(self, set_to_none: bool = True) -> None:
        if self.gradient_state.sync_gradients:
            self.optimizer.zero_grad(set_to_none)
            self._grads_unscaled = False

    def unscale_grads(self) -> None:
        """Divide the loss scale out of the grads now (reference
        unscale_gradients via torch GradScaler.unscale_): clipping must see
        TRUE gradient magnitudes, and the subsequent ``step`` must not
        divide again.  No-op without an fp16 scaler; pure jnp math, so it
        works identically eagerly and under capture.

        Two precision rules (round-4 review findings): the unscaled grads
        STAY fp32 — casting back to fp16 would flush exactly the
        small-gradient range loss scaling exists to protect (the step path
        upcasts anyway) — and mid-accumulation calls are no-ops: later
        micro-steps would pile scaled grads onto unscaled ones and the sync
        step would then apply them 1024x too large.  Unscaling only ever
        happens on the step that will actually apply."""
        if (
            self.scaler is None
            or self._grads_unscaled
            or not self.gradient_state.sync_gradients
        ):
            return
        inv = self.scaler.unscale_()
        for p in self.optimizer.param_list:
            if p.grad is not None:
                p.grad = p.grad.astype(jnp.float32) * inv
        self._grads_unscaled = True

    def step(self, closure=None) -> None:
        if not self.gradient_state.sync_gradients:
            return  # mid-accumulation micro-step: skip (reference optimizer.py:161)
        self._accelerate_step_called = True
        if self.scaler is not None:
            self._step_with_scaler(closure)
        else:
            self.optimizer.step(closure)
        from .capture import current_capture

        if current_capture() is None:
            # eager: the update left the new moments/masters (and, with
            # param offload, the params) in device HBM — re-pin to host if
            # offload was requested (no-ops otherwise).  Under capture this
            # runs on tracers, so the CapturedStep does it after each replay.
            self.optimizer.reoffload_state_to_host()
            self.optimizer.reoffload_params_to_host()

    def _step_with_scaler(self, closure) -> None:
        """fp16 step: finite-check, unscale, conditionally apply, update scale.

        Fully traceable: instead of a host-side branch (reference
        optimizer.py:161-178 via torch GradScaler), the update always runs on
        overflow-sanitized grads and a ``jnp.where`` select keeps the old
        params/opt-state when any grad was non-finite — so the same code path
        works eagerly and inside ``compile_step`` (one XLA program, the skip
        compiled in as a select).
        """
        import jax

        opt = self.optimizer
        grads = [p.grad for p in opt.param_list if p.grad is not None]
        finite = jnp.asarray(True)
        for g in grads:
            finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(g)))

        opt._ensure_master()
        # host-offloaded state must be device-resident BEFORE the snapshot:
        # the jnp.where select below mixes old and new state, and XLA
        # refuses mixed memory spaces
        opt.stage_state_on_device()
        already_unscaled = self._grads_unscaled
        self._grads_unscaled = False
        params_before = [p.data for p in opt.param_list]
        masters_before = list(opt.master_params)
        opt_state_before = opt.opt_state
        # quantized-collective error-feedback residuals (docs/compression.md)
        # are state too: an overflow-skipped step must not carry the
        # speculative update's residual forward
        comp_active = getattr(opt, "_compression", None) is not None
        rs_before = list(opt._comp_rs_err) if comp_active else []
        # sanitize so the speculative update never poisons Adam moments
        for p in opt.param_list:
            if p.grad is not None:
                p.grad = jnp.where(jnp.isfinite(p.grad), p.grad, 0.0).astype(p.grad.dtype)
        opt.step(
            closure,
            grad_scale=1.0 if already_unscaled else self.scaler.unscale_(),
        )

        def _sel(new, old):
            return jnp.where(finite, new, old) if hasattr(old, "dtype") else new

        for i, p in enumerate(opt.param_list):
            p.data = _sel(p.data, params_before[i])
            if opt.master_params[i] is not None and masters_before[i] is not None:
                opt.master_params[i] = _sel(opt.master_params[i], masters_before[i])
        opt.opt_state = jax.tree_util.tree_map(_sel, opt.opt_state, opt_state_before)
        if comp_active:
            opt._comp_rs_err = [
                _sel(new, old) if old is not None else new
                for new, old in zip(opt._comp_rs_err, rs_before)
            ]
        self.scaler.update_traced(finite)
        try:
            self._is_overflow = bool(~finite)  # eager: concrete immediately
        except jax.errors.TracerBoolConversionError:
            self._is_overflow = None  # captured: read scaler.last_overflow

    @property
    def step_was_skipped(self) -> bool:
        """True when the last ``step`` was dropped due to fp16 overflow."""
        if self._is_overflow is None and self.scaler is not None:
            # captured step: the flag was threaded through the compiled
            # program; by the time anyone asks (scheduler replay, user code)
            # the state has been written back as a concrete array
            return bool(self.scaler.last_overflow)
        return bool(self._is_overflow)

    def train(self):
        if hasattr(self.optimizer, "train"):
            self.optimizer.train()

    def eval(self):
        if hasattr(self.optimizer, "eval"):
            self.optimizer.eval()

    def __repr__(self):
        return f"AcceleratedOptimizer({self.optimizer})"
