"""Scale-safe sharded checkpointing through save_state/load_state.

Round-2 verdict Missing #3: the reference saves FSDP *sharded* state dicts
per rank including the optimizer (reference fsdp_utils.py:66-246,
save_fsdp_optimizer :175) precisely so checkpointing never materialises the
full model; this suite proves the same contract here — per-host shard files
for params AND optimizer state, O(shard) assembly on load, and N→M
resharded restore (save on fsdp=8, resume on fsdp=4×dp=2).
"""

import glob
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.data_loader import batch_to_global_array
from accelerate_tpu.models import GPTConfig, GPTLMHeadModel
from accelerate_tpu.utils.constants import MODEL_NAME, OPTIMIZER_NAME


def _make_training(fsdp_size: int, seed: int = 0):
    Accelerator._reset_state()
    nn.manual_seed(seed)
    acc = Accelerator(
        parallelism_config=ParallelismConfig(fsdp_size=fsdp_size),
        mixed_precision="bf16",
    )
    model = GPTLMHeadModel(GPTConfig.tiny())
    opt = optim.AdamW(model.parameters(), lr=1e-3)
    model, opt = acc.prepare(model, opt)

    def step_fn(ids):
        opt.zero_grad()
        out = model(ids, labels=ids)
        acc.backward(out["loss"])
        opt.step()
        return out["loss"]

    step = acc.compile_step(step_fn)
    return acc, model, opt, step


def _batch(acc, seed=0):
    ids = np.random.default_rng(seed).integers(0, 1024, (8, 32), dtype=np.int32)
    return batch_to_global_array(jnp.asarray(ids), mesh=acc.mesh)


def test_sharded_save_writes_per_shard_files_no_full_model(tmp_path):
    acc, model, opt, step = _make_training(fsdp_size=8)
    float(step(_batch(acc)))
    out = str(tmp_path / "ckpt")
    acc.save_state(out)  # default resolves to sharded (fsdp=8)

    model_shards = sorted(glob.glob(os.path.join(out, f"{MODEL_NAME}.shard-*.safetensors")))
    opt_shards = sorted(glob.glob(os.path.join(out, f"{OPTIMIZER_NAME}.shard-*.safetensors")))
    assert model_shards and opt_shards
    # the full-gather artifacts must NOT exist
    assert not os.path.exists(os.path.join(out, f"{MODEL_NAME}.safetensors"))
    assert not os.path.exists(os.path.join(out, f"{OPTIMIZER_NAME}.bin"))
    # optimizer meta (treedef scalars) rides alongside the shard files
    assert os.path.exists(os.path.join(out, f"{OPTIMIZER_NAME}.meta.bin"))


def test_resharded_resume_matches_uninterrupted_run(tmp_path):
    """Save on fsdp=8 → restore on fsdp=4 (different mesh) → identical losses."""
    acc, model, opt, step = _make_training(8)
    b0, b1 = _batch(acc, 0), _batch(acc, 1)
    float(step(b0))
    float(step(b1))
    out = str(tmp_path / "ckpt8")
    acc.save_state(out)
    # uninterrupted continuation
    cont = [float(step(_batch(acc, s))) for s in (2, 3, 4)]

    # fresh run on a DIFFERENT mesh layout: fsdp=4 (dp picks up the rest)
    acc2, model2, opt2, step2 = _make_training(fsdp_size=4, seed=123)
    assert dict(acc2.mesh.shape)["fsdp"] == 4
    acc2.load_state(out)
    resumed = [float(step2(_batch(acc2, s))) for s in (2, 3, 4)]
    np.testing.assert_allclose(resumed, cont, rtol=2e-5, atol=2e-5)


def test_same_mesh_resume_is_bit_identical(tmp_path):
    acc, model, opt, step = _make_training(8)
    float(step(_batch(acc, 0)))
    out = str(tmp_path / "ckpt")
    acc.save_state(out)
    cont = [float(step(_batch(acc, s))) for s in (1, 2)]

    acc2, model2, opt2, step2 = _make_training(fsdp_size=8, seed=999)
    acc2.load_state(out)
    resumed = [float(step2(_batch(acc2, s))) for s in (1, 2)]
    assert resumed == cont  # bit-identical: same mesh, same program, same state


def test_load_peak_block_is_shard_sized(tmp_path):
    """The loader must assemble per-device blocks, never a full tensor."""
    from accelerate_tpu.utils import fsdp_utils

    acc, model, opt, step = _make_training(8)
    float(step(_batch(acc)))
    out = str(tmp_path / "ckpt")
    acc.save_state(out)

    acc2, model2, opt2, step2 = _make_training(fsdp_size=8, seed=5)
    stats = fsdp_utils.load_stats
    stats.clear()
    acc2.load_state(out)
    assert stats["max_block_bytes"] > 0
    # largest single allocation during load ≤ largest per-device shard of the
    # biggest tensor (wte is (1024, 128) fp32 → full 512 KiB, shard 64 KiB);
    # embeddings are fsdp-exempt (replicated), so the bound is the largest
    # REPLICATED tensor, and every fsdp-sharded tensor must assemble in
    # shard-sized blocks — assert strictly less than the biggest sharded
    # tensor's full size would require excluding replicated ones, so track
    # the per-tensor max instead:
    for tname, (block_bytes, full_bytes, n_blocks) in stats["tensors"].items():
        if n_blocks > 1:  # sharded tensor → blocks must be fractions
            assert block_bytes < full_bytes, (tname, block_bytes, full_bytes)


def test_resave_clears_stale_artifacts(tmp_path):
    """Re-saving into a reused directory must remove artifacts from a prior
    save with a different world size or sharded-ness — the loader globs all
    shard files and prefers an index, so stale ones would silently win."""
    out = str(tmp_path / "ckpt")
    os.makedirs(out)
    # plant stale artifacts: an 8-way shard set and a stale full file
    for r in range(8):
        with open(os.path.join(out, f"{MODEL_NAME}.shard-{r:05d}-of-00008.safetensors"), "wb") as f:
            f.write(b"stale")
    with open(os.path.join(out, f"{OPTIMIZER_NAME}.bin"), "wb") as f:
        f.write(b"stale")

    acc, model, opt, step = _make_training(8)
    float(step(_batch(acc)))
    acc.save_state(out)
    # stale 8-way files gone; only this save's world-size files remain
    leftovers = [
        f for f in glob.glob(os.path.join(out, f"{MODEL_NAME}.shard-*-of-00008.safetensors"))
    ]
    assert not leftovers
    assert not os.path.exists(os.path.join(out, f"{OPTIMIZER_NAME}.bin"))
    # and the checkpoint still loads cleanly
    acc2, model2, opt2, step2 = _make_training(8, seed=3)
    acc2.load_state(out)

    # sharded → full transition in the same dir must clear the index too
    acc2.save_state(out, sharded_state=False)
    assert not os.path.exists(os.path.join(out, f"{MODEL_NAME}.index.json"))
    assert os.path.exists(os.path.join(out, f"{MODEL_NAME}.safetensors"))


def test_full_checkpoint_still_default_without_fsdp(tmp_path):
    acc, model, opt, step = _make_training(fsdp_size=1)
    float(step(_batch(acc)))
    out = str(tmp_path / "ckpt_full")
    acc.save_state(out)
    assert os.path.exists(os.path.join(out, f"{MODEL_NAME}.safetensors"))
    assert not glob.glob(os.path.join(out, f"{MODEL_NAME}.shard-*"))
