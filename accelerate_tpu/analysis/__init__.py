"""graftlint — static trace-safety & collective-correctness analysis.

The paper's promise is that one unmodified loop body runs from 1-process CPU
to a multi-chip TPU mesh.  The failure modes that break that promise — host
syncs baked into a ``jax.jit`` trace, per-step recompiles, collectives over
axis names the mesh does not carry — surface only at runtime, often only on
hardware (see TPU_OUTAGE_r0*.log).  This subsystem catches them from the AST,
in CI, on the virtual 8-device CPU mesh.

Layout:
  engine.py     file discovery, suppressions, baseline, rule runner, cache glue
  callgraph.py  per-module call graph + traced-region reachability
  program.py    whole-program import graph: cross-module reachability,
                donors/escapers/blockers resolved through imports
  cache.py      on-disk per-module cache (content hash + environment hash)
  rules/        one module per rule

Entry point: ``tools/graftlint.py`` (also ``make lint``).
"""

from .engine import (
    ANALYSIS_VERSION,
    AnalysisResult,
    Finding,
    ModuleInfo,
    Rule,
    load_baseline,
    load_ckpt_specs,
    run_analysis,
    sarif_report,
    write_baseline,
)
from .program import ModuleSummary, ProgramGraph, module_name_for
from .rules import ALL_RULES, get_rules

__all__ = [
    "ALL_RULES",
    "ANALYSIS_VERSION",
    "AnalysisResult",
    "Finding",
    "ModuleInfo",
    "ModuleSummary",
    "ProgramGraph",
    "Rule",
    "get_rules",
    "load_baseline",
    "load_ckpt_specs",
    "module_name_for",
    "run_analysis",
    "sarif_report",
    "write_baseline",
]
