"""Complete CV example: every by_feature capability in one CNN script
(reference examples/complete_cv_example.py parity).

On top of examples/cv_example.py's training loop this adds — mirroring
complete_nlp_example.py so the example-diff checker can verify feature
coverage —

* experiment tracking (``--with_tracking``: init_trackers / log / end_training),
* checkpointing every epoch or every N steps (``--checkpointing_steps``),
* resumption from a checkpoint (``--resume_from_checkpoint``), including
  mid-epoch resume through ``accelerator.skip_first_batches``,
* eval with duplicate-free ``gather_for_metrics``.
"""

from __future__ import annotations

import argparse
import os
import time

import numpy as np

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator, ProjectConfiguration, prepare_data_loader
from accelerate_tpu.nn import F, Tensor

from cv_example import SmallResNet, get_data


def get_dataloaders(batch_size: int, seed: int = 0):
    train = prepare_data_loader(
        dataset=get_data(512, seed), batch_size=batch_size, shuffle=True, data_seed=seed
    )
    evald = prepare_data_loader(
        dataset=get_data(128, seed + 1), batch_size=batch_size, shuffle=False
    )
    return train, evald


def training_function(args):
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        log_with="jsonl" if args.with_tracking else None,
        project_config=ProjectConfiguration(
            project_dir=args.project_dir, automatic_checkpoint_naming=False
        ),
    )
    nn.manual_seed(args.seed)

    model = SmallResNet()
    optimizer = optim.AdamW(model.parameters(), lr=args.lr)
    train_dl, eval_dl = get_dataloaders(args.batch_size, args.seed)
    model, optimizer, train_dl, eval_dl = accelerator.prepare(
        model, optimizer, train_dl, eval_dl
    )

    if args.with_tracking:
        accelerator.init_trackers("complete_cv_example", config=vars(args))

    # checkpoint cadence: int steps or "epoch"
    checkpointing_steps = args.checkpointing_steps
    if checkpointing_steps is not None and checkpointing_steps.isdigit():
        checkpointing_steps = int(checkpointing_steps)

    overall_step = 0
    starting_epoch = 0
    resume_step = None
    acc = None  # eval accuracy; None when resume skips all remaining epochs
    if args.resume_from_checkpoint:
        accelerator.print(f"resuming from {args.resume_from_checkpoint}")
        accelerator.load_state(args.resume_from_checkpoint)
        tag = os.path.basename(os.path.normpath(args.resume_from_checkpoint))
        if "epoch" in tag:
            starting_epoch = int(tag.replace("epoch_", "")) + 1
        else:
            overall_step = int(tag.replace("step_", ""))
            starting_epoch = overall_step // len(train_dl)
            resume_step = overall_step % len(train_dl)

    def step_fn(batch):
        optimizer.zero_grad()
        logits = model(Tensor(batch["image"]))
        loss = F.cross_entropy(logits, batch["label"])
        accelerator.backward(loss)
        optimizer.step()
        return loss

    step = accelerator.compile_step(step_fn)

    for epoch in range(starting_epoch, args.num_epochs):
        model.train()
        t0 = time.perf_counter()
        total_loss = 0.0
        active_dl = train_dl
        if args.resume_from_checkpoint and epoch == starting_epoch and resume_step:
            # mid-epoch resume: fast-forward the exact number of seen batches
            active_dl = accelerator.skip_first_batches(train_dl, resume_step)
        for batch in active_dl:
            with accelerator.accumulate(model):
                loss = step(batch)
            total_loss += float(loss.item() if hasattr(loss, "item") else loss)
            overall_step += 1
            if isinstance(checkpointing_steps, int) and overall_step % checkpointing_steps == 0:
                out = os.path.join(args.project_dir, f"step_{overall_step}")
                accelerator.save_state(out)

        model.eval()
        correct = total = 0
        for batch in eval_dl:
            logits = model(Tensor(batch["image"]))
            preds = np.argmax(np.asarray(logits.data), axis=-1).astype(np.int32)
            preds, labels = accelerator.gather_for_metrics((preds, batch["label"]))
            correct += int((np.asarray(preds) == np.asarray(labels)).sum())
            total += len(np.asarray(preds))
        acc = correct / max(total, 1)
        accelerator.print(
            f"epoch {epoch}: loss={total_loss / max(len(train_dl), 1):.4f} "
            f"eval_acc={acc:.3f} ({time.perf_counter() - t0:.1f}s)"
        )
        if args.with_tracking:
            accelerator.log(
                {"train_loss": total_loss / max(len(train_dl), 1), "eval_acc": acc},
                step=overall_step,
            )
        if checkpointing_steps == "epoch":
            accelerator.save_state(os.path.join(args.project_dir, f"epoch_{epoch}"))

    if args.with_tracking:
        accelerator.end_training()
    if acc is None:
        accelerator.print(
            f"nothing to do: resumed at epoch {starting_epoch} >= num_epochs {args.num_epochs}"
        )
    return acc


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--mixed_precision", default="bf16", choices=["no", "fp16", "bf16"])
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--num_epochs", type=int, default=3)
    parser.add_argument("--lr", type=float, default=1e-3)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--with_tracking", action="store_true")
    parser.add_argument("--checkpointing_steps", type=str, default=None)
    parser.add_argument("--resume_from_checkpoint", type=str, default=None)
    parser.add_argument("--project_dir", type=str, default="cv_outputs")
    args = parser.parse_args()
    training_function(args)


if __name__ == "__main__":
    main()
