"""Feature: Megatron-class GPT pretraining — tp x pp x dp in one program.

Counterpart of reference examples/by_feature/megatron_lm_gpt_pretraining.py.
The reference rebuilds the model inside the Megatron-LM engine
(utils/megatron_lm.py) to get tensor/pipeline/data parallel training; here
the SAME capabilities are mesh-axis layouts of one compiled step:

* tp   — attention/MLP weights sharded per the model's tp_plan,
* pp   — the trunk runs as GPipe microbatches over the ``pp`` axis
         (PipelinedGPTLMHeadModel, shard_map + ppermute),
* dp   — whatever devices remain consume distinct batch shards,
* distributed optimizer — optimizer state follows the param shardings
         (the fsdp axis generalizes it; see docs/sharding.md).

Run on any machine: 8 virtual CPU devices stand in for a pod slice —

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python megatron_style_gpt_pretraining.py --pp 2 --sp 2
"""

from __future__ import annotations

import argparse
import time

import numpy as np

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.data_loader import prepare_data_loader
from accelerate_tpu.models import GPTConfig, GPTLMHeadModel, PipelinedGPTLMHeadModel


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--tp", type=int, default=1)
    parser.add_argument("--pp", type=int, default=1)
    parser.add_argument("--sp", type=int, default=1)
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--seq_len", type=int, default=128)
    parser.add_argument("--num_steps", type=int, default=20)
    parser.add_argument("--num_microbatches", type=int, default=2)
    parser.add_argument("--lr", type=float, default=1e-3)
    args = parser.parse_args()

    accelerator = Accelerator(
        mixed_precision="bf16",
        parallelism_config=ParallelismConfig(
            tp_size=args.tp, pp_size=args.pp, sp_size=args.sp
        ),
    )
    accelerator.print(f"mesh: {dict(accelerator.mesh.shape)}")

    nn.manual_seed(0)
    cfg = GPTConfig.tiny()
    cfg.n_positions = max(cfg.n_positions, args.seq_len)
    if args.pp > 1:
        # pipeline trunk: GPipe microbatch schedule over the pp axis
        model = PipelinedGPTLMHeadModel(cfg, num_microbatches=args.num_microbatches)
    else:
        model = GPTLMHeadModel(cfg)
    optimizer = optim.AdamW(model.parameters(), lr=args.lr)

    rng = np.random.default_rng(0)
    data = [
        {"input_ids": rng.integers(1, cfg.vocab_size, args.seq_len).astype(np.int32)}
        for _ in range(args.batch_size * 8)
    ]
    dl = prepare_data_loader(dataset=data, batch_size=args.batch_size, shuffle=True)
    model, optimizer, dl = accelerator.prepare(model, optimizer, dl)

    def step_fn(ids):
        optimizer.zero_grad()
        out = model(ids, labels=ids)
        accelerator.backward(out["loss"])
        optimizer.step()
        return out["loss"]

    step = accelerator.compile_step(step_fn)

    if args.num_steps < 1:
        raise SystemExit("--num_steps must be >= 1")
    done = 0
    t0 = time.perf_counter()
    while done < args.num_steps:
        for batch in dl:
            loss = step(batch["input_ids"])
            done += 1
            if done >= args.num_steps:
                break
    accelerator.print(
        f"{done} steps: final loss={float(loss.item()):.4f} "
        f"({(time.perf_counter() - t0) / done * 1e3:.0f} ms/step)"
    )


if __name__ == "__main__":
    main()
