"""Pipelined GPT-2 inference over the `pp` mesh axis.

TPU-native counterpart of the reference's PiPPy inference examples
(/root/reference/examples/inference/pippy/gpt2.py:1): there, PiPPy traces the
torch model, splits it at `split_points="auto"`, and micro-batches flow
between per-GPU stage processes; here the transformer trunk is a stacked-layer
pytree pipelined by ``gpipe`` (parallel/pipeline.py) inside ONE compiled SPMD
program — stages are spans of the `pp` mesh axis, microbatches hop stage to
stage over ICI `ppermute`, and XLA overlaps the hops with stage compute.

Mirrors the reference's measurement: one timed first pass (includes compile —
the analog of PiPPy's warmup), then the average of 5 replays.

Run (CPU smoke, 8 virtual chips = 8 pipeline stages):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/inference/pipelined_gpt2.py --tiny

Run (TPU slice):
    python examples/inference/pipelined_gpt2.py --seq_len 1024
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.append(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)

from accelerate_tpu import Accelerator, ParallelismConfig  # noqa: E402
from accelerate_tpu.data_loader import batch_to_global_array  # noqa: E402
from accelerate_tpu.models import GPTConfig, PipelinedGPTLMHeadModel  # noqa: E402
from accelerate_tpu.utils.random import set_seed  # noqa: E402


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--tiny", action="store_true")
    parser.add_argument("--pp_size", type=int, default=None, help="pipeline stages (default: all devices)")
    parser.add_argument("--batch_size", type=int, default=2)
    parser.add_argument("--seq_len", type=int, default=None)
    parser.add_argument("--microbatches", type=int, default=2)
    args = parser.parse_args()

    set_seed(42)
    cfg = GPTConfig.tiny() if args.tiny else GPTConfig.small()
    if args.pp_size:
        pp = args.pp_size
    else:
        # stages scan contiguous layer spans, so pp must divide n_layer:
        # largest divisor that fits the slice (PiPPy's split_points="auto"
        # makes the same per-GPU span choice)
        pp = max(
            d for d in range(1, len(jax.devices()) + 1)
            if cfg.n_layer % d == 0 and len(jax.devices()) % d == 0
        )
    acc = Accelerator(parallelism_config=ParallelismConfig(pp_size=pp))

    seq_len = args.seq_len or min(128, cfg.n_positions)
    model = PipelinedGPTLMHeadModel(cfg, num_microbatches=args.microbatches)
    model.eval()
    model = acc.prepare(model)

    ids = batch_to_global_array(
        jnp.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab_size, (args.batch_size, seq_len)),
            jnp.int32,
        ),
        mesh=acc.mesh,
    )

    # forward-only inference step: one compiled program containing embedding,
    # the pipelined trunk, and the LM head
    step = acc.compile_step(lambda batch: model(batch)["logits"])

    t0 = time.perf_counter()
    logits = step(ids)
    jax.block_until_ready(logits)
    first = time.perf_counter() - t0

    t0 = time.perf_counter()
    for _ in range(5):
        logits = step(ids)
    jax.block_until_ready(logits)
    avg = (time.perf_counter() - t0) / 5

    # under SPMD the (sharded) logits are addressable on every process, not
    # only the last stage — no gather_output= equivalent is needed
    acc.print(f"pp={pp}, batch={args.batch_size}x{seq_len}, logits {tuple(logits.shape)}")
    acc.print(f"Time of first pass: {first:.3f}s (includes XLA compile)")
    acc.print(f"Average time per batch: {avg * 1000:.1f}ms")


if __name__ == "__main__":
    main()
