"""Rule registry. Adding a rule = new module here + an entry in ALL_RULES."""

from .axis_names import AxisNameMismatch
from .blocking import BlockingInHotLoop
from .collective_divergence import CollectiveDivergence
from .donation import DonationReuse
from .dtype_widen import DtypeWiden
from .host_sync import HostSyncInTrace
from .pallas_hazard import PallasHazard
from .recompile import RecompileHazard
from .spec_drift import ShardingSpecDrift
from .stage_boundary import StageBoundaryVsPlan
from .transitive_donation import TransitiveDonation

ALL_RULES = [
    HostSyncInTrace,
    RecompileHazard,
    AxisNameMismatch,
    DonationReuse,
    TransitiveDonation,
    DtypeWiden,
    BlockingInHotLoop,
    ShardingSpecDrift,
    PallasHazard,
    StageBoundaryVsPlan,
    CollectiveDivergence,
]


def get_rules(ids=None):
    """Instantiate all rules, or the subset named in ``ids``."""
    if ids is None:
        return [cls() for cls in ALL_RULES]
    by_id = {cls.id: cls for cls in ALL_RULES}
    unknown = set(ids) - set(by_id)
    if unknown:
        raise KeyError(f"unknown rule id(s): {sorted(unknown)}")
    return [by_id[i]() for i in ids]
