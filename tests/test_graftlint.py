"""graftlint: every rule must fire on its bad fixture and stay silent on the
good twin, suppressions and the baseline must filter, and the CLI must run
clean over the real package fast enough to live inside `make test`."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from accelerate_tpu.analysis import (
    get_rules,
    load_baseline,
    run_analysis,
    write_baseline,
)

pytestmark = pytest.mark.graftlint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GRAFTLINT = os.path.join(REPO, "tools", "graftlint.py")


def lint(tmp_path, source, rule=None, name="snippet.py"):
    f = tmp_path / name
    f.write_text(textwrap.dedent(source))
    rules = get_rules([rule]) if rule else None
    return run_analysis([str(f)], rules=rules)


# ---------------------------------------------------------------------------
# good/bad fixture pairs, one per rule
# ---------------------------------------------------------------------------

FIXTURES = {
    "host-sync-in-trace": (
        """
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            y = x.item()          # host transfer inside trace
            z = np.asarray(x)     # numpy concretization inside trace
            return float(x)       # python-scalar cast inside trace
        """,
        3,
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            return jnp.asarray(x) * 2   # device op: trace-safe

        def report(loss):
            return float(loss.item())   # eager host code: not traced
        """,
    ),
    "recompile-hazard": (
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def pad(x, n):
            if n:                       # concretizes the tracer
                x = x + 1
            return jnp.zeros((n, 4))    # traced value as a shape
        """,
        2,
        """
        import functools
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnums=(1,))
        def pad(x, n):
            if n:
                x = x + 1
            return jnp.zeros((n, 4))
        """,
    ),
    "axis-name-mismatch": (
        """
        import jax
        from jax.sharding import Mesh, PartitionSpec as P
        import numpy as np

        mesh = Mesh(np.array(jax.devices()), ("dp", "tp"))

        def allreduce(x):
            return jax.lax.psum(x, "batch")      # mesh has no 'batch'

        spec = P("model", None)                  # nor 'model'
        """,
        2,
        """
        import jax
        from jax.sharding import Mesh, PartitionSpec as P
        import numpy as np

        mesh = Mesh(np.array(jax.devices()), ("dp", "tp"))

        def allreduce(x):
            return jax.lax.psum(x, ("dp", "tp"))

        spec = P("dp", None)
        """,
    ),
    "donation-reuse": (
        """
        import jax

        def f(a):
            return a + 1

        g = jax.jit(f, donate_argnums=(0,))

        def train(x):
            y = g(x)
            return x + y      # x's buffer was donated to g
        """,
        1,
        """
        import jax

        def f(a):
            return a + 1

        g = jax.jit(f, donate_argnums=(0,))

        def train(x):
            x = g(x)          # rebinding the name is the blessed pattern
            return x
        """,
    ),
    "dtype-widen": (
        """
        import jax
        import jax.numpy as jnp

        def make():
            jax.config.update("jax_enable_x64", True)
            return jnp.zeros((4,), dtype=jnp.float64)
        """,
        2,
        """
        import jax.numpy as jnp

        def make():
            return jnp.zeros((4,), dtype=jnp.float32)
        """,
    ),
    "blocking-in-hot-loop": (
        """
        def train(step, batches):
            for b in batches:
                out = step(b)
                out.block_until_ready()     # drains the dispatch queue
            return out
        """,
        1,
        """
        def train(step, batches, profile_every=0):
            for i, b in enumerate(batches):
                out = step(b)
                if profile_every and i % profile_every == 0:
                    out.block_until_ready()  # profiling guard: allowed
            out.block_until_ready()          # after the loop: allowed
            return out
        """,
    ),
}


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_rule_fires_on_bad_fixture(tmp_path, rule):
    bad, expected, _ = FIXTURES[rule]
    res = lint(tmp_path, bad, rule=rule)
    assert len(res.new_findings) == expected, [f.render() for f in res.new_findings]
    assert all(f.rule == rule for f in res.new_findings)


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_rule_silent_on_good_twin(tmp_path, rule):
    _, _, good = FIXTURES[rule]
    res = lint(tmp_path, good, rule=rule)
    assert res.new_findings == [], [f.render() for f in res.new_findings]


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_good_twin_clean_under_all_rules(tmp_path, rule):
    """The good fixtures must not trip *other* rules either."""
    _, _, good = FIXTURES[rule]
    res = lint(tmp_path, good)
    assert res.new_findings == [], [f.render() for f in res.new_findings]


def test_shape_control_flow_is_trace_static(tmp_path):
    """`if x.shape[0] > 2:` inside jit is legal (shapes are static at trace
    time) and must not trip recompile-hazard."""
    res = lint(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            if x.shape[0] > 2:
                x = x[:2]
            return jnp.zeros((x.shape[0], 4))
        """,
        rule="recompile-hazard",
    )
    assert res.new_findings == [], [f.render() for f in res.new_findings]


def test_blocking_in_while_test_is_flagged(tmp_path):
    """A While test re-evaluates every iteration — a blocking call there is
    a per-step sync, same as in the body."""
    res = lint(
        tmp_path,
        """
        def converge(state, step):
            while not state.done.block_until_ready():
                state = step(state)
            return state
        """,
        rule="blocking-in-hot-loop",
    )
    assert len(res.new_findings) == 1


# ---------------------------------------------------------------------------
# suppression comments
# ---------------------------------------------------------------------------

def test_same_line_suppression(tmp_path):
    res = lint(
        tmp_path,
        """
        import jax

        @jax.jit
        def step(x):
            return x.item()  # graftlint: disable=host-sync-in-trace
        """,
        rule="host-sync-in-trace",
    )
    assert res.new_findings == []
    assert res.suppressed == 1


def test_preceding_line_suppression(tmp_path):
    res = lint(
        tmp_path,
        """
        import jax

        @jax.jit
        def step(x):
            # graftlint: disable=host-sync-in-trace
            return x.item()
        """,
        rule="host-sync-in-trace",
    )
    assert res.new_findings == []
    assert res.suppressed == 1


def test_suppression_is_per_rule(tmp_path):
    """Disabling one rule must not silence another on the same line."""
    res = lint(
        tmp_path,
        """
        import jax

        @jax.jit
        def step(x):
            return x.item()  # graftlint: disable=dtype-widen
        """,
        rule="host-sync-in-trace",
    )
    assert len(res.new_findings) == 1


def test_suppression_tolerates_justification_text(tmp_path):
    """Project policy requires a justification after the rule id — it must
    not break the rule-name parse."""
    res = lint(
        tmp_path,
        """
        import jax

        @jax.jit
        def step(x):
            return x.item()  # graftlint: disable=host-sync-in-trace -- demo of policy-mandated justification
        """,
        rule="host-sync-in-trace",
    )
    assert res.new_findings == []
    assert res.suppressed == 1


def test_docstring_mentioning_syntax_does_not_suppress(tmp_path):
    """Only real comments suppress; prose in a docstring that documents the
    syntax must not disable rules for the file."""
    res = lint(
        tmp_path,
        '''
        """Docs: silence a rule with `# graftlint: disable-file=host-sync-in-trace`."""
        import jax

        @jax.jit
        def step(x):
            return x.item()
        ''',
        rule="host-sync-in-trace",
    )
    assert len(res.new_findings) == 1


def test_file_level_suppression(tmp_path):
    res = lint(
        tmp_path,
        """
        # graftlint: disable-file=host-sync-in-trace
        import jax

        @jax.jit
        def step(x):
            return x.item()
        """,
        rule="host-sync-in-trace",
    )
    assert res.new_findings == []


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def test_baseline_grandfathers_existing_findings(tmp_path):
    bad, _, _ = FIXTURES["donation-reuse"]
    f = tmp_path / "legacy.py"
    f.write_text(textwrap.dedent(bad))
    first = run_analysis([str(f)])
    assert first.new_findings
    baseline_path = tmp_path / "baseline.json"
    write_baseline(first.findings, str(baseline_path))
    again = run_analysis([str(f)], baseline=load_baseline(str(baseline_path)))
    assert again.new_findings == []       # baselined
    assert len(again.findings) == len(first.findings)  # still detected


def test_baseline_survives_line_drift_but_not_new_findings(tmp_path):
    bad, _, _ = FIXTURES["donation-reuse"]
    f = tmp_path / "legacy.py"
    f.write_text(textwrap.dedent(bad))
    baseline_path = tmp_path / "baseline.json"
    write_baseline(run_analysis([str(f)]).findings, str(baseline_path))
    # unrelated edit above shifts every line; old finding stays baselined,
    # the fresh violation (a new symbol) is reported
    f.write_text(
        "HEADER = 1\n"
        + textwrap.dedent(bad)
        + textwrap.dedent(
            """
            def train2(x):
                y = g(x)
                return x + y
            """
        )
    )
    res = run_analysis([str(f)], baseline=load_baseline(str(baseline_path)))
    assert len(res.new_findings) == 1
    assert res.new_findings[0].symbol == "train2"


def test_unknown_rule_id_raises():
    with pytest.raises(KeyError):
        get_rules(["not-a-rule"])


# ---------------------------------------------------------------------------
# CLI (subprocess: the exact invocation `make lint` runs)
# ---------------------------------------------------------------------------

def _run_cli(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, GRAFTLINT, *args],
        cwd=cwd,
        capture_output=True,
        text=True,
        timeout=120,
    )


def test_cli_exits_nonzero_with_findings(tmp_path):
    bad, _, _ = FIXTURES["blocking-in-hot-loop"]
    (tmp_path / "bad.py").write_text(textwrap.dedent(bad))
    proc = _run_cli(str(tmp_path))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "blocking-in-hot-loop" in proc.stdout


def test_cli_json_output(tmp_path):
    bad, _, _ = FIXTURES["dtype-widen"]
    (tmp_path / "bad.py").write_text(textwrap.dedent(bad))
    proc = _run_cli(str(tmp_path), "--format", "json")
    assert proc.returncode == 1
    data = json.loads(proc.stdout)
    assert data["files_analyzed"] == 1
    assert {f["rule"] for f in data["findings"]} == {"dtype-widen"}
    assert all("fingerprint" in f for f in data["findings"])


def test_cli_write_then_use_baseline(tmp_path):
    bad, _, _ = FIXTURES["donation-reuse"]
    (tmp_path / "bad.py").write_text(textwrap.dedent(bad))
    baseline = tmp_path / "baseline.json"
    assert _run_cli(str(tmp_path), "--write-baseline", str(baseline)).returncode == 0
    proc = _run_cli(str(tmp_path), "--baseline", str(baseline))
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_list_rules():
    proc = _run_cli("--list-rules")
    assert proc.returncode == 0
    for rule in FIXTURES:
        assert rule in proc.stdout


def test_package_is_clean_and_fast():
    """Acceptance gate: the real package lints clean, within the <15 s budget
    that lets `make lint` sit in front of every `make test`."""
    proc = _run_cli("accelerate_tpu", "--format", "json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    data = json.loads(proc.stdout)
    assert data["findings"] == []
    assert data["files_analyzed"] > 100
    assert data["duration_s"] < 15.0, f"analysis took {data['duration_s']}s"


# ---------------------------------------------------------------------------
# donation-reuse: loop second pass (use-after-donate across iterations)
# ---------------------------------------------------------------------------

LOOP_DONATION_BAD = """
import jax

step = jax.jit(lambda s: s * 2, donate_argnums=(0,))

def train(state, batches):
    for batch in batches:
        report(state)        # fine on iteration 1, dead buffer on iteration 2
        out = step(state)    # donates `state` without rebinding it
    return out
"""

LOOP_DONATION_GOOD = """
import jax

step = jax.jit(lambda s: s * 2, donate_argnums=(0,))

def train(state, batches):
    for batch in batches:
        report(state)        # rebind below makes iteration 2 read live data
        state = step(state)
    return state
"""

LOOP_DONATION_WHILE_BAD = """
import jax

step = jax.jit(lambda s: s, donate_argnums=(0,))

def train(state):
    while state_norm(state) > 1.0:   # the TEST reads the donated buffer too
        _ = step(state)
    return None
"""


def test_donation_loop_carried_reuse_is_flagged(tmp_path):
    res = lint(tmp_path, LOOP_DONATION_BAD, rule="donation-reuse")
    assert len(res.new_findings) == 1, [f.render() for f in res.new_findings]
    assert "state" in res.new_findings[0].message


def test_donation_loop_rebind_is_clean(tmp_path):
    res = lint(tmp_path, LOOP_DONATION_GOOD, rule="donation-reuse")
    assert res.new_findings == [], [f.render() for f in res.new_findings]


def test_donation_while_test_reuse_is_flagged(tmp_path):
    res = lint(tmp_path, LOOP_DONATION_WHILE_BAD, rule="donation-reuse")
    assert len(res.new_findings) == 1, [f.render() for f in res.new_findings]


def test_donation_straight_line_in_loop_reported_once(tmp_path):
    """The second pass must not duplicate findings the linear scan already
    reported."""
    src = """
    import jax

    step = jax.jit(lambda s: s, donate_argnums=(0,))

    def train(state, batches):
        for batch in batches:
            out = step(state)
            loss = state.sum()   # straight-line use-after-donate
            state = out
    """
    res = lint(tmp_path, src, rule="donation-reuse")
    assert len(res.new_findings) == 1, [f.render() for f in res.new_findings]


# ---------------------------------------------------------------------------
# sharding-spec-drift (needs a checkpoint index to compare against)
# ---------------------------------------------------------------------------

PLAN_SNIPPET = """
class Model:
    tp_plan = {
        ".*q_proj.weight": ("tp", None),
        ".*mlp.weight": (None, "tp"),
    }
"""


def _write_index(tmp_path, specs, name="model"):
    index = {
        "metadata": {"num_shards": 1},
        "tensors": {
            tensor: {"shape": [8, 8], "dtype": "float32", "spec": spec}
            for tensor, spec in specs.items()
        },
    }
    path = tmp_path / f"{name}.index.json"
    path.write_text(json.dumps(index))
    return str(path)


def _lint_with_index(tmp_path, source, index_path):
    f = tmp_path / "plan.py"
    f.write_text(textwrap.dedent(source))
    return run_analysis(
        [str(f)], rules=get_rules(["sharding-spec-drift"]), ckpt_index=index_path
    )


def test_spec_drift_flags_plan_edit(tmp_path):
    # checkpoint was saved with q_proj sharded ("tp", None); the plan now
    # says (None, "tp") — same axes, different dim: silent step-one reshard
    index = _write_index(
        tmp_path,
        {"layers.0.q_proj.weight": [None, "tp"], "layers.0.mlp.weight": [None, "tp"]},
    )
    res = _lint_with_index(tmp_path, PLAN_SNIPPET, index)
    assert len(res.new_findings) == 1, [f.render() for f in res.new_findings]
    f = res.new_findings[0]
    assert f.rule == "sharding-spec-drift"
    assert "q_proj" in f.message


def test_spec_drift_silent_when_plan_matches(tmp_path):
    index = _write_index(
        tmp_path,
        {"layers.0.q_proj.weight": ["tp"], "layers.0.mlp.weight": [None, "tp"]},
    )
    res = _lint_with_index(tmp_path, PLAN_SNIPPET, index)
    assert res.new_findings == [], [f.render() for f in res.new_findings]


def test_spec_drift_ignores_replicated_record(tmp_path):
    """A fully-replicated record proves nothing (a tp:1 mesh canonicalizes
    every template away) — no finding."""
    index = _write_index(tmp_path, {"layers.0.q_proj.weight": []})
    res = _lint_with_index(tmp_path, PLAN_SNIPPET, index)
    assert res.new_findings == [], [f.render() for f in res.new_findings]


def test_spec_drift_inert_without_index(tmp_path):
    res = lint(tmp_path, PLAN_SNIPPET, rule="sharding-spec-drift")
    assert res.new_findings == []


def test_spec_drift_cli_ckpt_index(tmp_path):
    index = _write_index(tmp_path, {"layers.0.q_proj.weight": [None, "tp"]})
    (tmp_path / "plan.py").write_text(textwrap.dedent(PLAN_SNIPPET))
    proc = _run_cli(str(tmp_path / "plan.py"), "--ckpt-index", index)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "sharding-spec-drift" in proc.stdout
    # same invocation minus the index: clean
    proc = _run_cli(str(tmp_path / "plan.py"))
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_spec_drift_ignores_auto_added_fsdp_axis(tmp_path):
    """plan_param_spec layers "fsdp" onto a template-free dim on fsdp>1
    meshes; a recorded fsdp the template never mentioned is auto-sharding,
    not drift (false-positive regression from review)."""
    index = _write_index(tmp_path, {"layers.0.q_proj.weight": ["tp", "fsdp"]})
    res = _lint_with_index(tmp_path, PLAN_SNIPPET, index)
    assert res.new_findings == [], [f.render() for f in res.new_findings]
