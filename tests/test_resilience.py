"""Resilience subsystem (docs/resilience.md): injector-driven init
retry/backoff sequencing and fallback, SIGTERM → complete checkpoint →
bitwise-equal resume, transient dispatch faults retried then rolled back,
and the default-off path touching nothing."""

import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator, ResilienceKwargs, TelemetryKwargs
from accelerate_tpu.checkpointing import is_complete_checkpoint, latest_checkpoint
from accelerate_tpu.nn import Tensor
from accelerate_tpu.resilience import (
    FaultInjector,
    FaultPlan,
    InjectedTransientError,
    PreemptionGuard,
    classify_failure,
    init_backend,
    probe_backend_once,
)
from accelerate_tpu.resilience import backend as res_backend
from accelerate_tpu.resilience import preemption as res_preemption


@pytest.fixture(autouse=True)
def _resilience_hygiene():
    """Tests install real signal handlers and publish a process-global init
    report; both must not leak across tests."""
    yield
    if res_preemption._INSTALLED is not None:
        res_preemption._INSTALLED.uninstall()
    res_backend.LAST_INIT_REPORT = None


def _make_step(res_kwargs=None, tel=False):
    nn.manual_seed(0)
    handlers = []
    if res_kwargs is not None:
        handlers.append(res_kwargs)
    if tel:
        handlers.append(TelemetryKwargs(enabled=True))
    acc = Accelerator(kwargs_handlers=handlers or None)
    model = nn.Linear(8, 4)
    opt = optim.AdamW(model.parameters(), lr=1e-2)
    model, opt = acc.prepare(model, opt)

    def step_fn(x):
        opt.zero_grad()
        loss = model(Tensor(x)).sum()
        acc.backward(loss)
        opt.step()
        return loss

    return acc, model, acc.compile_step(step_fn)


def _batches(n):
    rng = np.random.default_rng(0)
    return [jnp.asarray(rng.normal(size=(4, 8)), jnp.float32) for _ in range(n)]


# ---------------------------------------------------------------------------
# fault plan / injector
# ---------------------------------------------------------------------------

def test_fault_plan_parses_grammar():
    plan = FaultPlan.parse("init_hang:times=2; dispatch:step=3,times=1; sigterm:step=2")
    kinds = [(d.kind, d.step, d.times) for d in plan.directives]
    assert kinds == [("init_hang", None, 2), ("dispatch", 3, 1), ("sigterm", 2, 1)]


@pytest.mark.parametrize(
    "bad", ["explode", "dispatch:times=1", "dispatch:step=x", "sigterm", "dispatch:step=1,frob=2"]
)
def test_fault_plan_rejects_garbage(bad):
    with pytest.raises(ValueError):
        FaultPlan.parse(bad)


def test_injector_dispatch_fault_fires_exactly_times():
    inj = FaultInjector(FaultPlan.parse("dispatch:step=1,times=2"))
    inj.maybe_dispatch_fault(0)  # wrong step: no fault
    with pytest.raises(InjectedTransientError):
        inj.maybe_dispatch_fault(1)
    with pytest.raises(InjectedTransientError):
        inj.maybe_dispatch_fault(1)  # a retry of the same call keeps faulting
    inj.maybe_dispatch_fault(1)  # times exhausted: clean


def test_fault_plan_parses_hang_directive():
    plan = FaultPlan.parse("hang:step=2,seconds=7")
    (d,) = plan.directives
    assert (d.kind, d.step, d.seconds) == ("hang", 2, 7)
    # seconds defaults to effectively-forever (the watchdog is the way out)
    assert FaultPlan.parse("hang:step=1").directives[0].seconds == 3600
    with pytest.raises(ValueError):
        FaultPlan.parse("hang:times=2")  # hang requires an anchor step
    with pytest.raises(ValueError):
        FaultPlan.parse("dispatch:step=1,seconds=5")  # seconds is hang-only


def test_injector_hang_sleeps_and_records_flight_event(monkeypatch):
    from accelerate_tpu.telemetry import flightrec
    from accelerate_tpu.telemetry.flightrec import FlightRecorder

    fresh = FlightRecorder(capacity=32)
    monkeypatch.setattr(flightrec, "_RECORDER", fresh)
    naps = []
    monkeypatch.setattr("time.sleep", lambda s: naps.append(s))
    inj = FaultInjector(FaultPlan.parse("hang:step=2,seconds=5"))
    assert inj.maybe_hang(0) is False and naps == []
    assert inj.maybe_hang(2) is True
    assert naps == [5]
    assert inj.maybe_hang(2) is False  # times exhausted: one hang only
    events = [e for e in fresh.snapshot() if e["kind"] == "hang_injected"]
    assert len(events) == 1
    assert events[0]["step"] == 2 and events[0]["seconds"] == 5


def test_fault_plan_parses_serving_verbs():
    plan = FaultPlan.parse("decode_fault:step=2,times=3; serving_sigterm:step=1")
    kinds = [(d.kind, d.step, d.times) for d in plan.directives]
    assert kinds == [("decode_fault", 2, 3), ("serving_sigterm", 1, 1)]
    # both verbs pin an engine step — a plan without one is ambiguous
    with pytest.raises(ValueError, match="needs step"):
        FaultPlan.parse("decode_fault:times=2")
    with pytest.raises(ValueError, match="needs step"):
        FaultPlan.parse("serving_sigterm")
    # the unknown-verb message teaches the full vocabulary
    with pytest.raises(ValueError, match="serving_sigterm"):
        FaultPlan.parse("decode_fualt:step=1")


def test_injector_decode_fault_fires_exactly_times():
    inj = FaultInjector(FaultPlan.parse("decode_fault:step=1,times=2"))
    inj.maybe_decode_fault(0)  # wrong engine step: no fault
    with pytest.raises(InjectedTransientError, match="engine step 1"):
        inj.maybe_decode_fault(1)
    with pytest.raises(InjectedTransientError):
        inj.maybe_decode_fault(1)  # a retry of the same step keeps faulting
    inj.maybe_decode_fault(1)  # times exhausted: clean
    # the injected error is classified transient — the serving retry loop
    # and the training rollback share one classifier
    try:
        FaultInjector(
            FaultPlan.parse("decode_fault:step=0")
        ).maybe_decode_fault(0)
    except InjectedTransientError as exc:
        assert classify_failure(exc) == "transient"


def test_injector_serving_sigterm_delivers_real_signal():
    seen = []
    saved = signal.signal(signal.SIGTERM, lambda s, f: seen.append(s))
    try:
        inj = FaultInjector(FaultPlan.parse("serving_sigterm:step=2"))
        inj.maybe_serving_sigterm(0)
        assert seen == []  # wrong step: nothing delivered
        inj.maybe_serving_sigterm(2)
        assert seen == [signal.SIGTERM]
        inj.maybe_serving_sigterm(2)  # times exhausted: one delivery only
        assert seen == [signal.SIGTERM]
    finally:
        signal.signal(signal.SIGTERM, saved)


# ---------------------------------------------------------------------------
# pillar 1: hardened backend init
# ---------------------------------------------------------------------------

def test_init_retry_backoff_sequencing_with_injector():
    """Two injected hangs, success on probe 3; the sleeps between attempts
    follow the exponential schedule and every attempt is recorded."""
    inj = FaultInjector(FaultPlan.parse("init_hang:times=2"))
    sleeps = []
    report = init_backend(
        platforms=["cpu"],
        attempts=4,
        timeout_s=7,
        backoff_s=2.0,
        jitter=0.0,
        injector=inj,
        sleep=sleeps.append,
    )
    assert report.ok and report.platform == "cpu" and report.fallback is None
    assert [a.ok for a in report.attempts] == [False, False, True]
    assert "exceeded 7s" in report.attempts[0].detail
    assert sleeps == [2.0, 4.0]  # base * 2**attempt, no jitter
    diag = report.to_bench_diag()
    assert diag["init_attempts"] == 3
    assert "fallback" not in diag
    assert diag["init_ts"] > 0


def test_init_backoff_jitter_bounded():
    from accelerate_tpu.resilience.backend import backoff_delays
    import random

    delays = backoff_delays(5, 5.0, cap_s=30.0, jitter=0.25, rng=random.Random(7))
    assert len(delays) == 4
    for i, delay in enumerate(delays):
        nominal = min(30.0, 5.0 * 2 ** i)
        assert nominal * 0.75 <= delay <= nominal * 1.25


def test_init_falls_down_platform_chain(monkeypatch):
    """Every probe of the requested platform hangs; the chain lands on cpu,
    pins the env, and the bench-schema diag says so (the r05 shape)."""
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")  # restore after
    inj = FaultInjector(FaultPlan.parse("init_hang:times=10"))
    report = init_backend(
        platforms=["axon", "cpu"],
        attempts=3,
        timeout_s=120,
        backoff_s=0.0,
        injector=inj,
        sleep=lambda s: None,
    )
    assert report.fallback == "cpu" and report.platform == "cpu"
    assert not report.ok  # even the cpu probe was injected-hung: last resort
    assert os.environ["JAX_PLATFORMS"] == "cpu"
    diag = report.to_bench_diag()
    # the exact keys bench.py has emitted since r02
    assert diag["init_attempts"] == 3
    assert diag["init_detail"].startswith("backend init exceeded 120s")
    assert diag["fallback"] == "cpu"


def test_real_probe_subprocess_succeeds_on_cpu():
    ok, detail = probe_backend_once(platform="cpu", timeout_s=120)
    assert ok, detail
    assert detail.startswith("cpu")


def test_init_report_reaches_telemetry_via_hub():
    """An init that ran before the Accelerator existed (state hardening,
    bench) still lands in the resilience event stream."""
    inj = FaultInjector(FaultPlan.parse("init_hang:times=1"))
    init_backend(
        platforms=["cpu"], attempts=2, backoff_s=0.0, injector=inj,
        sleep=lambda s: None,
    )
    nn.manual_seed(0)
    acc = Accelerator(
        kwargs_handlers=[
            ResilienceKwargs(enabled=True, preemption=False, retry=False),
            TelemetryKwargs(enabled=True),
        ]
    )
    inits = [e for e in acc.resilience.events if e["event"] == "init"]
    assert len(inits) == 1 and inits[0]["attempts"] == 2 and inits[0]["ok"]
    tele = [r for r in acc.telemetry.all_records() if r.get("kind") == "resilience"]
    assert any(r["event"] == "init" for r in tele)


# ---------------------------------------------------------------------------
# pillar 2: preemption-safe checkpointing
# ---------------------------------------------------------------------------

def test_sigterm_sets_sticky_flags_and_drain_writes_complete_checkpoint(tmp_path):
    acc, model, step = _make_step(ResilienceKwargs(enabled=True, retry=False))
    x = _batches(1)[0]
    step(x)
    assert not acc.resilience.should_save
    os.kill(os.getpid(), signal.SIGTERM)
    assert acc.resilience.should_save and acc.resilience.should_exit
    assert acc.resilience.guard.signal_name == "SIGTERM"
    out = acc.resilience.drain(acc, str(tmp_path / "preempt"))
    assert is_complete_checkpoint(out)
    assert acc.resilience.last_checkpoint == out
    assert any(e["event"] == "preemption" for e in acc.resilience.events)
    assert any(e["event"] == "drain" for e in acc.resilience.events)


def test_wallclock_deadline_trips_flags():
    clock = [100.0]
    guard = PreemptionGuard(deadline_s=50.0, time_fn=lambda: clock[0])
    assert not guard.deadline_reached()
    assert guard.seconds_to_deadline() == 50.0
    clock[0] = 149.9
    assert not guard.deadline_reached()
    clock[0] = 150.0
    assert guard.deadline_reached()


def test_sigterm_mid_run_resumes_bitwise_equal(tmp_path):
    """The acceptance matrix row: an injected SIGTERM mid-step makes the loop
    drain and exit with a complete checkpoint whose resume reproduces the
    uninterrupted run's losses bitwise."""
    batches = _batches(5)

    # uninterrupted reference run
    Accelerator._reset_state()
    _, _, step = _make_step()
    reference = [float(step(b)) for b in batches]

    # interrupted run: SIGTERM delivered right before dispatch 2 (mid-step);
    # the loop finishes that step, sees the sticky flag, drains and "exits"
    Accelerator._reset_state()
    acc, _, step = _make_step(
        ResilienceKwargs(enabled=True, fault_plan="sigterm:step=2", retry=False)
    )
    seen = []
    for batch in batches:
        seen.append(float(step(batch)))
        if acc.resilience.should_exit:
            ckpt = acc.resilience.drain(acc, str(tmp_path / "preempted"))
            break
    assert seen == reference[:3]  # step 2 completed despite the signal
    acc.resilience.close()

    # resumed run: fresh process-equivalent state, restore, finish the epoch
    Accelerator._reset_state()
    acc2, _, step2 = _make_step()
    acc2.load_state(ckpt)
    resumed = [float(step2(b)) for b in batches[3:]]
    assert resumed == reference[3:]  # bitwise equality, not allclose


# ---------------------------------------------------------------------------
# pillar 3: step retry with rollback
# ---------------------------------------------------------------------------

def test_transient_dispatch_fault_retried_with_zero_extra_recompiles():
    acc, _, step = _make_step(
        ResilienceKwargs(
            enabled=True, preemption=False,
            fault_plan="dispatch:step=2,times=1", retry_backoff_s=0.0,
        ),
        tel=True,
    )
    x = _batches(1)[0]
    losses = [float(step(x)) for _ in range(4)]
    assert all(np.isfinite(losses))
    retries = [e for e in acc.resilience.events if e["event"] == "dispatch_retry"]
    assert len(retries) == 1 and retries[0]["step"] == 2
    assert acc.telemetry.recompiles_total == 0  # retry reused the program
    tele = [r for r in acc.telemetry.all_records() if r.get("kind") == "resilience"]
    assert any(r["event"] == "dispatch_retry" for r in tele)


def test_retry_wait_split_out_of_dispatch_timing():
    """ROADMAP carried item: resilience backoff sleeps must land in the step
    record's ``retry_wait_ms``, NOT in ``dispatch_ms`` — before the split a
    retried run's dispatch timing was inflated by the whole backoff, making
    A/B bench comparisons lie about the hot path."""
    backoff_s = 0.05
    acc, _, step = _make_step(
        ResilienceKwargs(
            enabled=True, preemption=False,
            fault_plan="dispatch:step=2,times=1", retry_backoff_s=backoff_s,
        ),
        tel=True,
    )
    x = _batches(1)[0]
    for _ in range(4):
        float(step(x))
    records = acc.telemetry.timeline.records()
    waits = [r.retry_wait_ms for r in records]
    # exactly the faulted call (index 2) slept; backoff_delay jitters
    # SYMMETRICALLY (±25%), so the measured sleep lives in
    # [0.75·backoff, 1.25·backoff] plus scheduler slack
    assert waits[0] == waits[1] == waits[3] == 0.0, waits
    assert backoff_s * 1e3 * 0.7 <= waits[2] <= backoff_s * 1e3 * 1.3 + 50, waits
    faulted = records[2]
    # dispatch no longer swallows the sleep: the clean replay's dispatch is
    # the honest scale, and the faulted call's dispatch must be within an
    # order of it rather than backoff-sized
    assert faulted.dispatch_ms < waits[2], (faulted.dispatch_ms, waits[2])
    # the split still partitions the call's wall clock
    assert faulted.phase_sum_ms <= faulted.total_ms * 1.5
    # schema: the field exports with the record
    assert faulted.to_dict()["retry_wait_ms"] == waits[2]


def test_exhausted_retries_roll_back_to_last_checkpoint_and_replay(tmp_path):
    acc, _, step = _make_step(
        ResilienceKwargs(
            enabled=True, preemption=False, max_retries=1,
            fault_plan="dispatch:step=3,times=3", retry_backoff_s=0.0,
        )
    )
    x = _batches(1)[0]
    losses = [float(step(x)) for _ in range(2)]
    acc.save_state(str(tmp_path / "good"))
    assert acc.resilience.last_checkpoint == str(tmp_path / "good")
    l2 = float(step(x))
    # dispatch 3 faults through 2 attempts, rolls back to the post-step-1
    # checkpoint, and the replay (fault 3 then a clean retry) re-runs step
    # 2's math from the restored state — bitwise the same loss
    l3 = float(step(x))
    assert l3 == l2
    events = [e["event"] for e in acc.resilience.events]
    assert events.count("rollback") == 1
    assert acc.resilience.retrier.rollbacks_total == 1


def test_exhaustion_without_checkpoint_raises():
    acc, _, step = _make_step(
        ResilienceKwargs(
            enabled=True, preemption=False, max_retries=1,
            fault_plan="dispatch:step=1,times=5", retry_backoff_s=0.0,
        )
    )
    x = _batches(1)[0]
    step(x)
    with pytest.raises(InjectedTransientError):
        step(x)
    assert any(e["event"] == "dispatch_exhausted" for e in acc.resilience.events)


def test_failure_classification():
    assert classify_failure(InjectedTransientError("boom")) == "transient"
    assert classify_failure(RuntimeError("UNAVAILABLE: socket closed")) == "transient"
    assert classify_failure(RuntimeError("DEADLINE_EXCEEDED: dcn timeout")) == "transient"
    # OOM retries the same program into the same HBM: not transient
    assert classify_failure(RuntimeError("RESOURCE_EXHAUSTED: out of memory")) == "user"
    assert classify_failure(ValueError("shapes do not match")) == "user"
    assert classify_failure(TypeError("bad arg")) == "user"


# ---------------------------------------------------------------------------
# default-off / checkpoint helpers
# ---------------------------------------------------------------------------

def test_default_off_touches_nothing(tmp_path):
    prev_term = signal.getsignal(signal.SIGTERM)
    acc, _, step = _make_step()
    assert not acc.resilience.enabled
    assert acc.resilience.retrier is None and acc.resilience.guard is None
    assert step._resilience is None  # capture path: one None-check, no hooks
    assert signal.getsignal(signal.SIGTERM) is prev_term
    step(_batches(1)[0])
    acc.save_state(str(tmp_path / "ckpt"))
    assert acc.resilience.last_checkpoint is None
    assert acc.resilience.events == []


def test_latest_checkpoint_skips_incomplete(tmp_path):
    base = tmp_path / "checkpoints"
    for i, complete in ((0, True), (1, True), (2, False)):
        folder = base / f"checkpoint_{i}"
        folder.mkdir(parents=True)
        (folder / "pytree_model.safetensors").write_bytes(b"")
        if complete:
            (folder / "accelerator_meta.json").write_text("{}")
    # checkpoint_2 has no completion sentinel (killed mid-write): skipped
    assert latest_checkpoint(str(base)) == str(base / "checkpoint_1")
    assert not is_complete_checkpoint(str(base / "checkpoint_2"))
    assert latest_checkpoint(str(tmp_path / "missing")) is None


def test_meta_sentinel_written_last(tmp_path):
    """A complete save has the sentinel; its presence is what load_state's
    automatic path and the rollback machinery trust."""
    acc, _, step = _make_step()
    step(_batches(1)[0])
    out = acc.save_state(str(tmp_path / "ckpt"))
    assert is_complete_checkpoint(out)


# ----------------------------------------------------- review-pinned edges

def test_second_sigint_raises_keyboard_interrupt():
    """The sticky flag must not make Ctrl-C a no-op: the first SIGINT
    records, the second means NOW."""
    guard = PreemptionGuard()
    assert guard.install()
    try:
        os.kill(os.getpid(), signal.SIGINT)
        assert guard.triggered and guard.signal_name == "SIGINT"
        with pytest.raises(KeyboardInterrupt):
            os.kill(os.getpid(), signal.SIGINT)
    finally:
        guard.uninstall()


def test_consumed_donated_leaves_skip_retry_budget():
    """A mid-execution fault that consumed donated inputs must not burn
    retries it cannot win — it escalates straight to the rollback decision
    (here: no checkpoint → immediate exhaustion, zero retries slept)."""
    from accelerate_tpu.resilience.retry import StepRetrier

    class _Hub:
        dispatch_calls = 1
        injector = None
        last_checkpoint = None

        def __init__(self):
            self.events = []

        def record_event(self, event, **fields):
            self.events.append({"event": event, **fields})

    class _DeletedLeaf:
        def is_deleted(self):
            return True

    hub = _Hub()
    retrier = StepRetrier(hub, max_retries=3, backoff_s=0.0)

    def dispatch(dev, host, entry):
        raise RuntimeError("UNAVAILABLE: device halted mid-program")

    with pytest.raises(RuntimeError):
        retrier.run_dispatch(
            None, dispatch, entry=None,
            dev_leaves=(_DeletedLeaf(),), host_leaves=(), host_mask=(False,),
        )
    assert retrier.retries_total == 0  # no doomed re-invocations
    (event,) = hub.events
    assert event["event"] == "dispatch_exhausted"
    assert event["donated_consumed"] is True


def test_init_report_consumed_by_first_hub():
    """A stale LAST_INIT_REPORT must not be re-emitted by every later hub
    in the same process."""
    inj = FaultInjector(FaultPlan.parse("init_hang:times=1"))
    init_backend(
        platforms=["cpu"], attempts=2, backoff_s=0.0, injector=inj,
        sleep=lambda s: None,
    )
    from accelerate_tpu.resilience import Resilience
    from accelerate_tpu.utils.dataclasses import ResilienceKwargs as RK

    first = Resilience(RK(enabled=True, preemption=False, retry=False))
    second = Resilience(RK(enabled=True, preemption=False, retry=False))
    assert [e["event"] for e in first.events] == ["init"]
    assert second.events == []  # consumed on first pickup
    assert res_backend.LAST_INIT_REPORT is None
