"""accelerate_tpu — a TPU-native training & inference framework.

A from-scratch rebuild of the capability surface of HuggingFace Accelerate
(reference snapshot surveyed in SURVEY.md) designed for JAX/XLA/Pallas on
Cloud TPU: one SPMD program over a ``jax.sharding.Mesh`` replaces the
reference's ten process backends; FSDP/TP/SP/PP are mesh-axis layouts, not
wrapper modules; collectives are compiled into the step by XLA and ride ICI.
"""

__version__ = "0.1.0"

from .accelerator import Accelerator
from .state import AcceleratorState, GradientState, PartialState
from .logging import get_logger
from .data_loader import prepare_data_loader, skip_first_batches
from .utils.memory import find_executable_batch_size
from .utils.random import set_seed, synchronize_rng_states
from .utils.dataclasses import (
    DataLoaderConfiguration,
    DistributedType,
    FullyShardedDataParallelPlugin,
    GradientAccumulationPlugin,
    InitProcessGroupKwargs,
    ParallelismConfig,
    ProfileKwargs,
    ProjectConfiguration,
    SequenceParallelPlugin,
    TensorParallelPlugin,
)
