import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from accelerate_tpu.ops.attention import sdpa_reference
from accelerate_tpu.ops.ring_attention import ring_attention
from accelerate_tpu.state import AcceleratorState
from accelerate_tpu.utils.dataclasses import ParallelismConfig


def _setup(sp=4, dp_extra=2):
    state = AcceleratorState(parallelism_config=ParallelismConfig(sp_size=sp, dp_size=dp_extra))
    return state.mesh


def _place(x, mesh):
    return jax.device_put(x, NamedSharding(mesh, P("dp", None, "sp", None)))


@pytest.mark.parametrize("is_causal", [False, True])
def test_ring_attention_matches_reference(is_causal):
    mesh = _setup()
    b, h, s, d = 2, 2, 32, 8
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (b, h, s, d), dtype=jnp.float32)
    k = jax.random.normal(ks[1], (b, h, s, d), dtype=jnp.float32)
    v = jax.random.normal(ks[2], (b, h, s, d), dtype=jnp.float32)
    expected = sdpa_reference(q, k, v, is_causal=is_causal)
    qs, ks_, vs = _place(q, mesh), _place(k, mesh), _place(v, mesh)
    out = jax.jit(
        lambda a, b_, c: ring_attention(a, b_, c, mesh=mesh, is_causal=is_causal)
    )(qs, ks_, vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=2e-4, atol=2e-5)


def test_ring_attention_grads_match():
    mesh = _setup()
    b, h, s, d = 2, 2, 32, 8
    ks = jax.random.split(jax.random.key(1), 3)
    q = jax.random.normal(ks[0], (b, h, s, d))
    k = jax.random.normal(ks[1], (b, h, s, d))
    v = jax.random.normal(ks[2], (b, h, s, d))

    def ring_loss(q_, k_, v_):
        return ring_attention(q_, k_, v_, mesh=mesh, is_causal=True).sum()

    def ref_loss(q_, k_, v_):
        return sdpa_reference(q_, k_, v_, is_causal=True).sum()

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(_place(q, mesh), _place(k, mesh), _place(v, mesh))
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for gr, ge in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(ge), rtol=5e-4, atol=1e-5)


def test_ring_attention_sp1_fallback():
    state = AcceleratorState()  # sp == 1 → plain attention path
    q = jax.random.normal(jax.random.key(0), (1, 2, 16, 8))
    out = ring_attention(q, q, q, mesh=state.mesh, is_causal=True)
    expected = sdpa_reference(q, q, q, is_causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-5)


@pytest.mark.parametrize("is_causal", [False, True])
def test_ring_flash_hop_path_matches_reference(is_causal, monkeypatch):
    """The TPU hop-kernel ring path (forced on CPU via interpret mode):
    parity with monolithic attention, forward and backward."""
    import accelerate_tpu.ops.flash_attention as fa
    import accelerate_tpu.ops.ring_attention as ra

    monkeypatch.setattr(fa, "_INTERPRET", True)
    monkeypatch.setattr(ra, "_FORCE_FLASH_HOPS", True)

    mesh = _setup(sp=2, dp_extra=4)
    b, h, s, d = 1, 1, 256, 64  # chunk 128 per sp shard: one full MXU tile
    ks = jax.random.split(jax.random.key(2), 3)
    q = jax.random.normal(ks[0], (b, h, s, d), dtype=jnp.float32)
    k = jax.random.normal(ks[1], (b, h, s, d), dtype=jnp.float32)
    v = jax.random.normal(ks[2], (b, h, s, d), dtype=jnp.float32)
    expected = sdpa_reference(q, k, v, is_causal=is_causal)

    spec = NamedSharding(mesh, P(None, None, "sp", None))
    qs, ks_, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = jax.jit(
        lambda a, b_, c: ring_attention(
            a, b_, c, mesh=mesh, is_causal=is_causal, batch_axes=()
        )
    )(qs, ks_, vs)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), rtol=2e-4, atol=2e-4
    )

    def ring_loss(q_, k_, v_):
        return (
            ring_attention(q_, k_, v_, mesh=mesh, is_causal=is_causal, batch_axes=())
            * jnp.arange(d)
        ).sum()

    def ref_loss(q_, k_, v_):
        return (sdpa_reference(q_, k_, v_, is_causal=is_causal) * jnp.arange(d)).sum()

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(qs, ks_, vs)
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for gr, ge in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(ge), rtol=2e-3, atol=2e-3)
