"""axis-name-mismatch: collective axis names the mesh does not declare.

``lax.psum(x, "batch")`` over a mesh whose axes are ``("dp", "fsdp", "tp",
"sp", "ep", "pp")`` is a NameError *at trace time on hardware* — i.e. in the
one environment we can't always reach (TPU_OUTAGE logs).  The declared axis
universe is harvested in the engine's first pass from ``MESH_AXIS_*`` /
``ALL_MESH_AXES`` constants, ``Mesh(..., axis_names=...)`` literals and
``make_mesh({...})`` keys, so the rule checks every literal collective axis,
``PartitionSpec`` entry, and ``axis_name=``-style default against it.
"""

from __future__ import annotations

import ast

from ..engine import Finding, Rule, _literal_strs

# canonical leaf -> positional index of the axis-name argument
_COLLECTIVES = {
    "psum": 1,
    "pmean": 1,
    "pmax": 1,
    "pmin": 1,
    "all_gather": 1,
    "ppermute": 1,
    "pshuffle": 1,
    "all_to_all": 1,
    "psum_scatter": 1,
    "pbroadcast": 1,
    "axis_index": 0,
    "axis_size": 0,
}
_SPEC_LEAVES = {"PartitionSpec"}


def _axis_literals(module, node: ast.AST) -> list[tuple[str, ast.AST]]:
    """String axis names in an expression: literals, tuples of literals, and
    bare Names that resolve to module-level string constants."""
    out = []
    if isinstance(node, ast.Name) and node.id in module.str_constants:
        out.append((module.str_constants[node.id], node))
    for s in _literal_strs(node):
        out.append((s, node))
    if isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            if isinstance(e, ast.Name) and e.id in module.str_constants:
                out.append((module.str_constants[e.id], e))
    return out


class AxisNameMismatch(Rule):
    id = "axis-name-mismatch"
    kind = "syntactic"
    description = (
        "collective/PartitionSpec axis name not declared by any mesh "
        "(MESH_AXIS_* constants, Mesh(axis_names=...), make_mesh({...}))"
    )
    fix_hint = (
        "use an axis name the mesh declares (the MESH_AXIS_* constants) "
        "instead of a free-hand string"
    )

    def check(self, module, ctx):
        findings = []
        universe = ctx.axis_universe

        def verify(name, node, what):
            if name not in universe:
                findings.append(
                    Finding(
                        self.id,
                        module.rel_path,
                        node.lineno,
                        node.col_offset,
                        f"{what} axis name '{name}' is not a declared mesh axis "
                        f"(declared: {sorted(universe)})",
                    )
                )

        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                resolved = module.resolve(node.func) or ""
                leaf = resolved.rsplit(".", 1)[-1]
                if leaf in _COLLECTIVES and (
                    "lax" in resolved.split(".") or resolved.startswith("jax.")
                ):
                    pos = _COLLECTIVES[leaf]
                    axis_expr = node.args[pos] if len(node.args) > pos else None
                    for kw in node.keywords:
                        if kw.arg in ("axis_name", "axis_names"):
                            axis_expr = kw.value
                    if axis_expr is not None:
                        for name, n in _axis_literals(module, axis_expr):
                            verify(name, n, f"lax.{leaf}")
                elif leaf in _SPEC_LEAVES:
                    for arg in list(node.args) + [kw.value for kw in node.keywords]:
                        for name, n in _axis_literals(module, arg):
                            verify(name, n, "PartitionSpec")
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # `axis_name: str = "sp"`-style defaults are axis declarations
                # consumed far from any mesh; check them where they're written
                a = node.args
                pos = [p.arg for p in a.posonlyargs + a.args]
                named = dict(zip(pos[len(pos) - len(a.defaults):], a.defaults))
                named.update(
                    (p.arg, d)
                    for p, d in zip(a.kwonlyargs, a.kw_defaults)
                    if d is not None
                )
                for pname, d in named.items():
                    if "axis" in pname and not pname.endswith("axes"):
                        for name, n in _axis_literals(module, d):
                            verify(name, n, f"default of parameter '{pname}'")
        return findings
