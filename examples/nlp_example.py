"""BERT sequence-classification fine-tuning — the north-star workload.

Mirrors the reference training loop shape (/root/reference/examples/
nlp_example.py): build dataloaders, wrap everything in Accelerator.prepare,
run the imperative loop with accelerator.backward.  TPU-first differences:
bf16 by default, sequences padded to a fixed 128 multiple (static shapes; the
reference itself pads to 128 on XLA, nlp_example.py:81), and the whole step
captured into one XLA program via accelerator.compile_step.

Runs on real MRPC when `datasets`/`transformers` can reach disk caches;
otherwise generates a synthetic separable dataset with the same shapes so the
example is runnable on an air-gapped TPU VM.
"""

from __future__ import annotations

import argparse
import time

import jax.numpy as jnp
import numpy as np

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator, prepare_data_loader
from accelerate_tpu.models import BertConfig, BertForSequenceClassification
from accelerate_tpu.nn import Tensor

MAX_LEN = 128


def get_dataloaders(
    accelerator: Accelerator,
    batch_size: int,
    seed: int = 0,
    fold: int = 0,
    num_folds: int = 0,
):
    """Real MRPC if cached locally; synthetic otherwise (same shapes).

    ``num_folds > 0`` switches to k-fold mode (by_feature/cross_validation):
    the training set is split into ``num_folds`` slices, slice ``fold``
    becomes the validation set, the rest train.
    """
    try:
        from datasets import load_dataset
        from transformers import AutoTokenizer

        raw = load_dataset("glue", "mrpc")
        tok = AutoTokenizer.from_pretrained("bert-base-cased")

        def encode(ex):
            out = tok(
                ex["sentence1"], ex["sentence2"],
                truncation=True, max_length=MAX_LEN, padding="max_length",
            )
            out["labels"] = ex["label"]
            return out

        cols = ["input_ids", "token_type_ids", "attention_mask", "labels"]
        train = raw["train"].map(encode, batched=True).with_format("numpy", columns=cols)
        val = raw["validation"].map(encode, batched=True).with_format("numpy", columns=cols)
        train_data = [{k: np.asarray(r[k]) for k in cols} for r in train]
        val_data = [{k: np.asarray(r[k]) for k in cols} for r in val]
        vocab = tok.vocab_size
    except Exception:
        accelerator.print("datasets/transformers unavailable — synthetic MRPC-shaped data")
        rng = np.random.default_rng(seed)
        vocab = 8192

        def make(n):
            data = []
            for _ in range(n):
                label = int(rng.integers(0, 2))
                # separable signal: class-conditioned token bias
                ids = rng.integers(4, vocab // 2, size=MAX_LEN) + label * (vocab // 2 - 4)
                length = int(rng.integers(16, MAX_LEN))
                mask = np.zeros(MAX_LEN, dtype=np.int32)
                mask[:length] = 1
                ids = ids * mask
                data.append(
                    {
                        "input_ids": ids.astype(np.int32),
                        "token_type_ids": np.zeros(MAX_LEN, dtype=np.int32),
                        "attention_mask": mask,
                        "labels": np.int32(label),
                    }
                )
            return data

        import os as _os

        n_train = int(_os.environ.get("EXAMPLES_N_TRAIN", 1024))
        n_val = int(_os.environ.get("EXAMPLES_N_VAL", 256))
        train_data, val_data = make(n_train), make(n_val)

    if num_folds > 0:
        # k-fold mode: deterministic round-robin split of the training set
        all_data = train_data
        train_data = [r for i, r in enumerate(all_data) if i % num_folds != fold]
        val_data = [r for i, r in enumerate(all_data) if i % num_folds == fold]

    train_dl = prepare_data_loader(
        dataset=train_data, batch_size=batch_size, shuffle=True, data_seed=seed
    )
    val_dl = prepare_data_loader(dataset=val_data, batch_size=batch_size)
    return train_dl, val_dl, vocab


def training_function(args):
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        gradient_accumulation_steps=args.gradient_accumulation_steps,
        log_with="jsonl" if args.project_dir else None,
        project_dir=args.project_dir,
    )
    nn.manual_seed(args.seed)
    train_dl, val_dl, vocab = get_dataloaders(accelerator, args.batch_size, args.seed)

    cfg = BertConfig.small() if args.small else BertConfig.base()
    cfg.vocab_size = max(cfg.vocab_size, vocab)
    model = BertForSequenceClassification(cfg)
    optimizer = optim.AdamW(model.parameters(), lr=args.lr)
    scheduler = optim.get_linear_schedule_with_warmup(
        optimizer, 100, len(train_dl) * args.num_epochs * accelerator.num_devices
    )
    model, optimizer, train_dl, val_dl, scheduler = accelerator.prepare(
        model, optimizer, train_dl, val_dl, scheduler
    )
    if args.project_dir:
        accelerator.init_trackers("nlp_example", config=vars(args))

    def train_step(batch):
        out = model(
            batch["input_ids"],
            attention_mask=batch["attention_mask"],
            token_type_ids=batch["token_type_ids"],
            labels=batch["labels"],
        )
        accelerator.backward(out["loss"])
        optimizer.step()
        scheduler.step()
        # after step, inside accumulate(): no-ops mid-window, so accumulated
        # grads survive until the sync step (reference by_feature pattern)
        optimizer.zero_grad()
        return out["loss"]

    def eval_step(batch):
        out = model(
            batch["input_ids"],
            attention_mask=batch["attention_mask"],
            token_type_ids=batch["token_type_ids"],
        )
        return out["logits"].data.argmax(-1)

    compiled_train = accelerator.compile_step(train_step) if args.capture else train_step
    compiled_eval = accelerator.compile_step(eval_step) if args.capture else eval_step

    for epoch in range(args.num_epochs):
        model.train()
        t0 = time.perf_counter()
        samples = 0
        for step, batch in enumerate(train_dl):
            with accelerator.accumulate(model):
                loss = compiled_train(batch)
            samples += train_dl.total_batch_size
        dt = time.perf_counter() - t0

        model.eval()
        correct = total = 0
        for batch in val_dl:
            preds = compiled_eval(batch)
            preds = accelerator.gather_for_metrics(preds)
            labels = accelerator.gather_for_metrics(batch["labels"])
            correct += int((np.asarray(preds) == np.asarray(labels)).sum())
            total += len(np.asarray(labels))
        acc = correct / max(total, 1)
        loss_val = float(loss.item() if hasattr(loss, "item") else loss)
        accelerator.print(
            f"epoch {epoch}: loss={loss_val:.4f} accuracy={acc:.4f} "
            f"({samples / dt:.1f} samples/s, {samples / dt / accelerator.num_devices:.1f}/chip)"
        )
        if args.project_dir:
            accelerator.log(
                {"loss": loss_val, "accuracy": acc, "samples_per_sec": samples / dt},
                step=epoch,
            )
    accelerator.end_training()
    return acc


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--mixed_precision", type=str, default="bf16", choices=["no", "fp16", "bf16"])
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--num_epochs", type=int, default=3)
    parser.add_argument("--lr", type=float, default=2e-5)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--gradient_accumulation_steps", type=int, default=1)
    parser.add_argument("--project_dir", type=str, default=None)
    parser.add_argument("--small", action="store_true", help="BERT-small config (CI/smoke)")
    parser.add_argument("--no-capture", dest="capture", action="store_false")
    args = parser.parse_args()
    training_function(args)


if __name__ == "__main__":
    main()
