"""Pallas hot-path kernels behind one :class:`KernelPolicy` surface
(docs/kernels.md).

The three hottest paths in the stack leave device time on the table because
XLA will not fuse across a collective or a block-table gather on its own:

* **collective-matmul** (``collective_matmul.py``) — the ZeRO-1 all-gather
  expressed as a chunked ring (``shard_map`` + per-hop transport: RDMA
  semaphores on TPU, ``ppermute`` off-TPU) so partial matmuls consume
  shards as they arrive instead of waiting on one monolithic all-gather;
* **fused quantize+reduce-scatter** (``quantize_rs.py``) —
  ``parallel/compress.py``'s per-block scale compute, rounding and widening
  collapsed into ONE kernel region so scale+round ride the shard boundary
  instead of round-tripping HBM between separate XLA ops; also carries the
  stochastic-rounding wire that reopens the ZeRO-2 first scatter;
* **paged-attention decode** (``paged_attention.py``) — serving's
  materialize-full-page-span gather-then-attend replaced by a kernel that
  walks the block table in VMEM (the vLLM move), one grid program per slot.

Policy discipline (same as telemetry/resilience/aot-cache/fleet): the
policy is resolved from ``KernelKwargs`` / ``$ACCELERATE_KERNELS`` and is
**default-off with the off path byte-identical** — no kernel module is even
imported on the hot path until a kernel is armed.  Off-TPU the kernels run
under the Pallas CPU interpreter (``interpret=True``), which lowers to
plain partitionable StableHLO, so numerics verify **bitwise** against the
reference paths in tier-1 (tests/test_kernels.py) and every fusion claim is
checkable from ``lower().compiler_ir()`` (``inspect.py``).

The AOT executable cache keys its topology fingerprint on
``KernelPolicy.describe()`` — flipping a kernel on is a LOUD cache miss
naming the ``kernels`` field, never a silently-stale executable.
"""

from __future__ import annotations

import os
from typing import Optional

__all__ = [
    "KernelPolicy",
    "KERNEL_NAMES",
    "resolve_kernel_policy",
    "current_kernel_policy",
    "_set_active_kernels",
    "_reset_active_kernels",
]

# the three hot-path fusions, in the order ROADMAP names them
KERNEL_NAMES = ("collective_matmul", "quantized_rs", "paged_attention")


class KernelPolicy:
    """Which Pallas kernels are armed, and how they lower.

    ``interpret=None`` resolves lazily to "not on TPU": tier-1 (and any CPU
    mesh) runs every kernel under the Pallas interpreter — bitwise-testable,
    partitionable StableHLO — while a TPU backend compiles the real Mosaic
    kernel.  The resolution is cached on first use so a policy's lowering
    mode cannot drift between captured variants of one run (which would be
    a recompile hazard: ``interpret`` is a static argument everywhere).
    """

    def __init__(
        self,
        collective_matmul: bool = False,
        quantized_rs: bool = False,
        paged_attention: bool = False,
        interpret: Optional[bool] = None,
    ):
        self.collective_matmul = bool(collective_matmul)
        self.quantized_rs = bool(quantized_rs)
        self.paged_attention = bool(paged_attention)
        self._interpret = interpret

    @property
    def enabled(self) -> bool:
        return self.collective_matmul or self.quantized_rs or self.paged_attention

    @property
    def interpret(self) -> bool:
        if self._interpret is None:
            try:
                import jax

                self._interpret = jax.default_backend() != "tpu"
            except Exception:
                self._interpret = True
        return self._interpret

    def armed(self) -> tuple:
        """The armed kernel names, in canonical order (telemetry/bench)."""
        return tuple(n for n in KERNEL_NAMES if getattr(self, n))

    def describe(self) -> str:
        """Canonical armed-set string for telemetry and human output
        (order-independent spellings collapse)."""
        return "+".join(self.armed()) or "none"

    def cache_tag(self) -> str:
        """What executable caches key on: the armed set PLUS the lowering
        mode.  `interpret` usually follows the backend (which fingerprints
        already hash), but ``KernelKwargs(interpret=...)`` can force it —
        an interpreter-mode executable replayed by a Mosaic-mode run (or
        vice versa) would be exactly the silently-stale entry the
        fingerprint exists to prevent.  ``none`` when nothing is armed
        (mode is meaningless, and resolving it would touch the backend)."""
        if not self.enabled:
            return "none"
        return self.describe() + (":interpret" if self.interpret else ":mosaic")

    def __repr__(self):
        return f"KernelPolicy({self.describe()!r})"


def resolve_kernel_policy(handler=None) -> KernelPolicy:
    """Resolve the active policy from a ``KernelKwargs`` handler (or the
    ``$ACCELERATE_KERNELS`` env var it reads).

    Grammar: a comma/plus-separated subset of ``collective_matmul``,
    ``quantized_rs``, ``paged_attention``; ``all`` (or ``1``) arms all
    three; empty / ``none`` / ``0`` (the default) arms nothing.
    """
    if handler is None:
        from ...utils.dataclasses import KernelKwargs

        handler = KernelKwargs()
    spec = str(handler.kernels or "").strip().lower()
    flags = dict.fromkeys(KERNEL_NAMES, False)
    if spec in ("all", "1", "true", "yes", "on"):
        flags = dict.fromkeys(KERNEL_NAMES, True)
    elif spec not in ("", "0", "none", "false", "no", "off"):
        for name in spec.replace("+", ",").split(","):
            name = name.strip().replace("-", "_")
            if not name:
                continue
            if name not in flags:
                raise ValueError(
                    f"unknown kernel {name!r} in ACCELERATE_KERNELS/"
                    f"KernelKwargs; use a subset of {KERNEL_NAMES} or 'all'"
                )
            flags[name] = True
    return KernelPolicy(interpret=handler.interpret, **flags)


# process-active policy (the Accelerator publishes its resolution here,
# mirroring native/aot_cache's _set_active) — what a standalone
# DecodeService or a bare Optimizer relayout picks up without a handle.
# The _UNSET sentinel distinguishes "no Accelerator resolved anything yet"
# (fall back to the env) from "an Accelerator explicitly disarmed kernels"
# (None — the env must NOT re-arm a policy the user opted out of).
_UNSET = object()
_ACTIVE = _UNSET


def _set_active_kernels(policy: Optional[KernelPolicy]) -> None:
    global _ACTIVE
    _ACTIVE = policy


def _reset_active_kernels() -> None:
    """Back to the never-resolved state (test hygiene)."""
    global _ACTIVE
    _ACTIVE = _UNSET


def current_kernel_policy() -> Optional[KernelPolicy]:
    """The process-active policy (which may be an explicit None — a
    constructed Accelerator's disarm wins over the env), else an
    env-resolved one if the env arms anything, else None — the single
    lookup every default-off call site performs once at construction,
    never per step."""
    if _ACTIVE is not _UNSET:
        return _ACTIVE
    if os.environ.get("ACCELERATE_KERNELS"):
        policy = resolve_kernel_policy()
        return policy if policy.enabled else None
    return None
