"""1F1B fused pipeline schedule: gradient parity with GPipe + memory window.

Round-2 verdict Missing #4: GPipe fill-drain holds num_microbatches stage
inputs alive through the backward; the reference gets 1F1B from
megatron.core's get_forward_backward_func (reference utils/megatron_lm.py:40,
train_step :1035).  Here 1F1B is a fused fwd+bwd shard_map loop
(parallel/pipeline.py): loss computed inside the last stage, cotangents hop
down-ring while later microbatches still flow up, and each stage stores only
``2·S−1`` inputs regardless of M.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.data_loader import batch_to_global_array
from accelerate_tpu.models import GPTConfig, PipelinedGPTLMHeadModel
from accelerate_tpu.parallel.pipeline import (
    bubble_fraction,
    bubble_ticks,
    residual_window,
    schedule_ticks,
)
from accelerate_tpu.utils.dataclasses import PipelineParallelPlugin


def test_memory_window_beats_gpipe_at_m8_s2():
    """At M=8, S=2 the 1F1B window is 3 stage inputs vs GPipe's 8."""
    assert residual_window(2) == 3
    assert residual_window(4) == 7
    # bubble profile: M + 2S - 2 fused cycles (each = 1 fwd + 1 bwd slot)
    assert schedule_ticks(8, 2) == 10


def test_interleaved_profile_m8_s2_v2():
    """The virtual factor's analytic profile (ISSUE 15 acceptance): at
    M=8, S=2, V=2 the interleaved schedule shows STRICTLY fewer bubble
    ticks than the fused one (compared in a common chunk granularity),
    the bubble fraction drops from (S−1)/M to (S−1)/(V·M), the lockstep
    trip count is M·V + S·V + S − 2 chunk ticks, and the residual window
    keeps the 2·S−1 order per hosted span (V·(2S−1) chunk inputs, each
    1/V the fused activation)."""
    fused = bubble_ticks(8, 2, virtual=1, granularity=2)
    interleaved = bubble_ticks(8, 2, virtual=2, granularity=2)
    assert interleaved < fused, (interleaved, fused)
    assert (fused, interleaved) == (4, 2)
    assert bubble_fraction(8, 2, 2) < bubble_fraction(8, 2, 1)
    assert bubble_fraction(8, 2, 2) == (2 - 1) / (2 * 8)
    assert schedule_ticks(8, 2, virtual=2) == 20
    assert residual_window(2, virtual=2) == 6
    # degenerate V=1 reproduces the fused profile exactly
    assert schedule_ticks(8, 2, virtual=1) == schedule_ticks(8, 2)
    assert residual_window(2, virtual=1) == residual_window(2)


def _plain_params(acc, model):
    """Param dict in PLAIN layer order: prepare() commits the interleave
    permutation physically at V>1 (docs/parallel_plan.md §layout contract),
    so cross-schedule comparisons view committed stacks through the plan's
    inverse order.  No-op for uncommitted (plain) runs."""
    stage = acc.plan.stage
    out = {}
    for n, p in model.named_parameters():
        a = np.asarray(p.data)
        if getattr(p, "_layer_layout_committed", False) and stage is not None:
            a = a[np.asarray(stage.inverse_layer_order(a.shape[0]))]
        out[n] = a
    return out


def _train(schedule: str, steps: int = 3, microbatches: int = 8,
           n_layer: int = 2, virtual: int = 0, layout: str = None):
    Accelerator._reset_state()
    nn.manual_seed(0)
    acc = Accelerator(
        parallelism_config=ParallelismConfig(pp_size=2),
        pp_plugin=PipelineParallelPlugin(
            pp_size=2, num_microbatches=microbatches, schedule=schedule,
            virtual_stages=virtual, layout=layout,
        ),
        mixed_precision="no",
    )
    cfg = GPTConfig.tiny()
    if n_layer != cfg.n_layer:
        import dataclasses as _dc

        cfg = _dc.replace(cfg, n_layer=n_layer)
    model = PipelinedGPTLMHeadModel(cfg, num_microbatches=microbatches)
    opt = optim.SGD(model.parameters(), lr=0.1)
    model, opt = acc.prepare(model, opt)

    def step_fn(ids):
        opt.zero_grad()
        out = model(ids, labels=ids)
        acc.backward(out["loss"])
        opt.step()
        return out["loss"]

    step = acc.compile_step(step_fn)
    ids = batch_to_global_array(
        jnp.asarray(
            np.random.default_rng(0).integers(0, 1024, (32, 32)), jnp.int32
        ),
        mesh=acc.mesh,
    )
    losses = [float(step(ids)) for _ in range(steps)]
    return losses, _plain_params(acc, model)


def test_loss_and_grad_parity_with_gpipe():
    """Same init, same data: 1F1B must train identically to GPipe — loss
    trajectory AND updated parameters (grads) agree."""
    l_g, p_g = _train("gpipe")
    l_f, p_f = _train("1f1b")
    np.testing.assert_allclose(l_f, l_g, rtol=2e-5, atol=2e-5)
    for name in p_g:
        np.testing.assert_allclose(
            p_f[name], p_g[name], rtol=3e-4, atol=3e-5, err_msg=name
        )


def test_ignore_index_parity():
    """-100 padded labels must drop out of the fused loss exactly like the
    gpipe path's F.cross_entropy ignore_index."""
    import jax

    from accelerate_tpu.models.gpt import (
        _pure_lm_head_loss,
        lm_shift_loss,
    )
    from accelerate_tpu.nn import Tensor

    rng = np.random.default_rng(0)
    b, s, c, v = 2, 8, 16, 32
    h = jnp.asarray(rng.normal(size=(b, s, c)), jnp.float32)
    labels = rng.integers(0, v, (b, s)).astype(np.int32)
    labels[:, -3:] = -100  # padded tail
    ln_w, ln_b = jnp.ones((c,)), jnp.zeros((c,))
    head_w = jnp.asarray(rng.normal(size=(v, c)), jnp.float32)
    lsum, w = _pure_lm_head_loss(
        h, jnp.asarray(labels), (ln_w, ln_b, head_w), eps=1e-5
    )
    got = float(lsum) / float(w)
    # reference: the tape-path math on the same arrays
    from accelerate_tpu.models.gpt import _pure_layernorm

    logits = Tensor(_pure_layernorm(h, ln_w, ln_b, 1e-5) @ head_w.T)
    want = float(lm_shift_loss(logits, jnp.asarray(labels), v).data)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_padded_label_parity_between_schedules():
    """UNEVEN -100 padding across microbatches: the fused loss must still be
    the global token mean, not a mean of per-microbatch means (which would
    over-weight heavily-padded microbatches)."""
    rng = np.random.default_rng(3)
    ids = rng.integers(0, 1024, (32, 32)).astype(np.int32)
    labels = ids.copy()
    # ragged padding: rows get anywhere from 0 to 24 trailing -100s
    for i in range(32):
        pad = int(rng.integers(0, 25))
        if pad:
            labels[i, -pad:] = -100

    def run(schedule):
        Accelerator._reset_state()
        nn.manual_seed(0)
        acc = Accelerator(
            parallelism_config=ParallelismConfig(pp_size=2),
            pp_plugin=PipelineParallelPlugin(
                pp_size=2, num_microbatches=8, schedule=schedule
            ),
            mixed_precision="no",
        )
        model = PipelinedGPTLMHeadModel(GPTConfig.tiny(), num_microbatches=8)
        opt = optim.SGD(model.parameters(), lr=0.1)
        model, opt = acc.prepare(model, opt)

        def step_fn(x, y):
            opt.zero_grad()
            out = model(x, labels=y)
            acc.backward(out["loss"])
            opt.step()
            return out["loss"]

        step = acc.compile_step(step_fn)
        x = batch_to_global_array(jnp.asarray(ids), mesh=acc.mesh)
        y = batch_to_global_array(jnp.asarray(labels), mesh=acc.mesh)
        losses = [float(step(x, y)) for _ in range(2)]
        return losses, {n: np.asarray(p.data) for n, p in model.named_parameters()}

    l_g, p_g = run("gpipe")
    l_f, p_f = run("1f1b")
    np.testing.assert_allclose(l_f, l_g, rtol=2e-5, atol=2e-5)
    for name in p_g:
        np.testing.assert_allclose(
            p_f[name], p_g[name], rtol=3e-4, atol=3e-5, err_msg=name
        )


def test_1f1b_loss_decreases():
    losses, _ = _train("1f1b", steps=4)
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses


def test_interleaved_grad_parity_with_gpipe_at_v2():
    """ISSUE 15 acceptance: the interleaved schedule (V=2, each device
    hosting two non-contiguous layer spans) trains identically to GPipe —
    loss trajectory AND updated parameters agree on a 4-layer trunk."""
    l_g, p_g = _train("gpipe", n_layer=4)
    l_i, p_i = _train("interleaved", n_layer=4, virtual=2)
    np.testing.assert_allclose(l_i, l_g, rtol=2e-5, atol=2e-5)
    for name in p_g:
        np.testing.assert_allclose(
            p_i[name], p_g[name], rtol=3e-4, atol=3e-5, err_msg=name
        )


def test_interleaved_matches_fused_1f1b():
    """Same seed/data: interleaving is a schedule/layout change, not a
    numerics change — V=2 must track the fused 1F1B trajectory."""
    l_f, p_f = _train("1f1b", n_layer=4)
    l_i, p_i = _train("interleaved", n_layer=4, virtual=2)
    np.testing.assert_allclose(l_i, l_f, rtol=2e-5, atol=2e-5)
    for name in p_f:
        np.testing.assert_allclose(
            p_i[name], p_f[name], rtol=3e-4, atol=3e-5, err_msg=name
        )


def test_committed_layout_matches_gather_reference():
    """ISSUE 17 acceptance: the prepare-time committed layout (zero
    permutation bytes per step) trains bitwise-identically to the legacy
    in-program gather layout — the permutation moved, the math didn't."""
    l_c, p_c = _train("interleaved", n_layer=4, virtual=2)
    l_g, p_g = _train("interleaved", n_layer=4, virtual=2, layout="gather")
    np.testing.assert_array_equal(np.asarray(l_c), np.asarray(l_g))
    for name in p_g:
        np.testing.assert_array_equal(p_c[name], p_g[name], err_msg=name)


# ---------------------------------------------------------------------------
# cross-layout checkpoints + fleet resize (ISSUE 17 layout contract)
# ---------------------------------------------------------------------------
def _ckpt_run(layout, mp="no", schedule="interleaved", virtual=2):
    """An interleaved pp=2, V=2 AdamW run for checkpoint-matrix tests
    (``schedule="1f1b", virtual=0`` gives the plain-layout V=1 twin)."""
    Accelerator._reset_state()
    nn.manual_seed(0)
    acc = Accelerator(
        parallelism_config=ParallelismConfig(pp_size=2),
        pp_plugin=PipelineParallelPlugin(
            pp_size=2, num_microbatches=8, schedule=schedule,
            virtual_stages=virtual, layout=layout,
        ),
        mixed_precision=mp,
    )
    import dataclasses as _dc

    cfg = _dc.replace(GPTConfig.tiny(), n_layer=4)
    model = PipelinedGPTLMHeadModel(cfg, num_microbatches=8)
    opt = optim.AdamW(model.parameters(), lr=1e-3)
    model, opt = acc.prepare(model, opt)

    def step_fn(ids):
        opt.zero_grad()
        out = model(ids, labels=ids)
        acc.backward(out["loss"])
        opt.step()
        return out["loss"]

    step = acc.compile_step(step_fn)
    ids = batch_to_global_array(
        jnp.asarray(
            np.random.default_rng(0).integers(0, 1024, (32, 32)), jnp.int32
        ),
        mesh=acc.mesh,
    )
    return acc, model, opt, step, ids


def _plain_opt_state(acc, model, opt):
    """Moments (+ masters when present) for STACKED params, viewed in plain
    layer order — the cross-layout bitwise-comparison unit.  Leaf→param
    ownership follows ``Optimizer._map_per_param_state``'s SequenceKey +
    exact-shape rule."""
    import jax

    stage = acc.plan.stage
    inner = getattr(opt, "optimizer", opt)
    stacked_ids = {id(p) for _, p in acc._stacked_layer_params(model)}
    committed = {
        id(p)
        for _, p in acc._stacked_layer_params(model)
        if getattr(p, "_layer_layout_committed", False)
    }
    shapes = [tuple(p.shape) for p in inner.param_list]

    def view(leaf, p):
        a = np.asarray(leaf)
        if id(p) in committed and a.ndim:
            a = a[np.asarray(stage.inverse_layer_order(a.shape[0]))]
        return a

    out = {}
    for i, p in enumerate(inner.param_list):
        if id(p) in stacked_ids and inner.master_params[i] is not None:
            out[f"master.{i}"] = view(inner.master_params[i], p)
    for path, leaf in jax.tree_util.tree_flatten_with_path(inner.opt_state)[0]:
        idx = next(
            (k.idx for k in reversed(path)
             if isinstance(k, jax.tree_util.SequenceKey)),
            None,
        )
        if (
            idx is not None
            and idx < len(shapes)
            and hasattr(leaf, "shape")
            and tuple(leaf.shape) == shapes[idx]
            and id(inner.param_list[idx]) in stacked_ids
        ):
            out[f"state.{idx}.{jax.tree_util.keystr(path)}"] = view(
                leaf, inner.param_list[idx]
            )
    return out


@pytest.mark.parametrize(
    "save_kw,load_kw",
    [
        ({"layout": None}, {"layout": "gather"}),
        ({"layout": "gather"}, {"layout": None}),
        pytest.param(
            {"layout": None},
            {"layout": None, "schedule": "1f1b", "virtual": 0},
            marks=pytest.mark.slow,
        ),
        pytest.param(
            {"layout": None, "schedule": "1f1b", "virtual": 0},
            {"layout": None},
            marks=pytest.mark.slow,
        ),
    ],
    ids=[
        "committed_to_gather",
        "gather_to_committed",
        "committed_to_plain_v1",
        "plain_v1_to_committed",
    ],
)
def test_checkpoint_cross_layout_matrix(tmp_path, save_kw, load_kw):
    """Checkpoints written under one stacked-layer layout restore into a
    run living under ANOTHER — the restore transposition covers params,
    fp32 masters, and moments bitwise, and the resumed trajectory tracks
    the uninterrupted one.  Gather- and V=1-layout checkpoints carry no
    ``layer_layout`` meta (byte-identical to pre-layout-era ones), so the
    *→committed legs double as the backward-compat proof."""
    acc, model, opt, step, ids = _ckpt_run(**save_kw)
    for _ in range(2):
        float(step(ids))
    out = str(tmp_path / "ckpt")
    acc.save_state(out)
    saved_params = _plain_params(acc, model)
    saved_opt = _plain_opt_state(acc, model, opt)
    cont = [float(step(ids)) for _ in range(2)]

    acc2, model2, opt2, step2, ids2 = _ckpt_run(**load_kw)
    acc2.load_state(out)
    # params + optimizer state bitwise in the plain view after transposition
    got_params = _plain_params(acc2, model2)
    for name in saved_params:
        np.testing.assert_array_equal(
            got_params[name], saved_params[name], err_msg=name
        )
    got_opt = _plain_opt_state(acc2, model2, opt2)
    assert set(got_opt) == set(saved_opt)
    for name in saved_opt:
        np.testing.assert_array_equal(got_opt[name], saved_opt[name], err_msg=name)
    resumed = [float(step2(ids2)) for _ in range(2)]
    np.testing.assert_allclose(resumed, cont, rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_checkpoint_masters_transpose_bitwise(tmp_path):
    """bf16 params give the optimizer real fp32 masters; a committed-layout
    save restored into a gather-layout run must hand back the SAME master
    bytes in the plain view."""
    acc, model, opt, step, ids = _ckpt_run(None, mp="bf16")
    float(step(ids))
    out = str(tmp_path / "ckpt")
    acc.save_state(out)
    saved = _plain_opt_state(acc, model, opt)
    masters = [k for k in saved if k.startswith("master.")]
    assert masters, "bf16 run grew no fp32 masters for stacked params"

    acc2, model2, opt2, step2, ids2 = _ckpt_run("gather", mp="bf16")
    acc2.load_state(out)
    got = _plain_opt_state(acc2, model2, opt2)
    for name in masters:
        np.testing.assert_array_equal(got[name], saved[name], err_msg=name)


@pytest.mark.slow
def test_fleet_resize_preserves_committed_layout(tmp_path):
    """A dp resize (drain → re-mesh → reshard restore) must keep the
    prepare-time layout of record: the survivors' stacked params stay
    COMMITTED (markers intact, plan still says so), their plain view is
    bitwise the pre-resize one, and training continues."""
    from accelerate_tpu import FleetKwargs

    Accelerator._reset_state()
    nn.manual_seed(0)
    acc = Accelerator(
        parallelism_config=ParallelismConfig(pp_size=2),
        pp_plugin=PipelineParallelPlugin(
            pp_size=2, num_microbatches=8, schedule="interleaved",
            virtual_stages=2,
        ),
        mixed_precision="no",
        kwargs_handlers=[FleetKwargs(enabled=True)],
    )
    import dataclasses as _dc

    cfg = _dc.replace(GPTConfig.tiny(), n_layer=4)
    model = PipelinedGPTLMHeadModel(cfg, num_microbatches=8)
    opt = optim.SGD(model.parameters(), lr=0.1)
    model, opt = acc.prepare(model, opt)
    dp = acc.plan.dp
    if dp < 2:
        pytest.skip("needs dp >= 2 beside pp=2")

    def step_fn(ids):
        opt.zero_grad()
        out = model(ids, labels=ids)
        acc.backward(out["loss"])
        opt.step()
        return out["loss"]

    step = acc.compile_step(step_fn)
    ids = batch_to_global_array(
        jnp.asarray(
            np.random.default_rng(0).integers(0, 1024, (32, 32)), jnp.int32
        ),
        mesh=acc.mesh,
    )
    float(step(ids))
    before = _plain_params(acc, model)

    acc.fleet.resize(acc, target_dp=dp // 2, output_dir=str(tmp_path / "drain"))
    assert acc.plan.pp == 2 and acc.plan.dp == dp // 2
    assert acc.plan.layer_layout == "committed"
    stacked = acc._stacked_layer_params(model)
    assert stacked and all(
        getattr(p, "_layer_layout_committed", False) for _, p in stacked
    )
    after = _plain_params(acc, model)
    for name in before:
        np.testing.assert_array_equal(after[name], before[name], err_msg=name)
    ids2 = batch_to_global_array(
        jnp.asarray(
            np.random.default_rng(0).integers(0, 1024, (32, 32)), jnp.int32
        ),
        mesh=acc.mesh,
    )
    assert np.isfinite(float(step(ids2)))


def test_interleaved_rejects_indivisible_shapes():
    """Bad geometry fails loudly at construction (plan resolution), not
    mid-first-step: M not divisible by S, layers not divisible by S·V."""
    with pytest.raises(ValueError, match="divisible"):
        _train("interleaved", microbatches=3, n_layer=4, virtual=2)
    # layers 2 vs S·V = 4: the layer-order derivation refuses
    from accelerate_tpu.parallel.plan import StagePlan

    with pytest.raises(ValueError, match="not divisible"):
        StagePlan(
            num_stages=2, virtual=2, num_microbatches=8,
            schedule="interleaved",
        ).layer_order(2)


def test_1f1b_rejects_sequence_parallel():
    Accelerator._reset_state()
    nn.manual_seed(0)
    acc = Accelerator(
        parallelism_config=ParallelismConfig(pp_size=2, sp_size=2),
        pp_plugin=PipelineParallelPlugin(pp_size=2, schedule="1f1b"),
    )
    model = PipelinedGPTLMHeadModel(GPTConfig.tiny(), num_microbatches=2)
    model, = (acc.prepare(model),)
    ids = batch_to_global_array(
        jnp.zeros((8, 32), jnp.int32), mesh=acc.mesh
    )
    with pytest.raises(NotImplementedError, match="sequence parallelism"):
        model(ids, labels=ids)


def test_bad_schedule_name_rejected():
    with pytest.raises(ValueError, match="gpipe"):
        PipelineParallelPlugin(pp_size=2, schedule="zigzag")
    # interleaving is a 1F1B property: gpipe can't take a virtual factor,
    # and 'interleaved' with V=1 is a contradiction
    with pytest.raises(ValueError, match="gpipe"):
        PipelineParallelPlugin(pp_size=2, schedule="gpipe", virtual_stages=2)
    with pytest.raises(ValueError, match="virtual_stages"):
        PipelineParallelPlugin(pp_size=2, schedule="interleaved", virtual_stages=1)
