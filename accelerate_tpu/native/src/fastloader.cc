// Native host-side runtime core for accelerate_tpu.
//
// The reference framework gets its host input-pipeline and checkpoint-IO
// performance from vendored native code: torch's C++ DataLoader worker pool
// and pinned-memory collate (reference: src/accelerate/data_loader.py drives
// torch.utils.data.DataLoader, whose hot loops are ATen C++), and torch
// native serialization behind save/load.  This file is the tpu-native
// equivalent: the host-side hot loops that feed HBM — batch assembly
// (gather / stack / pad-stack over sample rows) and checkpoint shard IO
// (chunked parallel pread/pwrite) — as a small C++17 library driven from
// Python via ctypes (no pybind11 in this image).
//
// Design notes:
//  * All entry points take an explicit `threads` count and split the work
//    contiguously over a thread team spawned per call (no persistent pool —
//    the Python wrappers cap `threads` so each thread moves >=1 MiB, keeping
//    spawn+join cost negligible next to the copy).  On a 1-core host they
//    degrade to the fused single-thread loop, which still beats Python-level
//    per-sample slicing + np.stack by removing interpreter overhead from the
//    per-row path.
//  * Row copies are memcpy over caller-provided contiguous buffers: the
//    Python wrapper keeps ownership (numpy arrays), so there is no
//    allocation, GIL interaction, or lifetime management here.
//  * IO uses pread/pwrite with per-thread offsets — one open fd, no seek
//    races, works on any POSIX filesystem.

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cerrno>

namespace {

// Run fn(i) for i in [0, n) over `threads` workers; contiguous block split
// so each worker touches a contiguous dst region (streams well).
template <typename Fn>
void parallel_rows(int64_t n, int threads, Fn fn) {
  if (threads <= 1 || n <= 1) {
    for (int64_t i = 0; i < n; ++i) fn(i);
    return;
  }
  if (threads > n) threads = static_cast<int>(n);
  std::vector<std::thread> pool;
  pool.reserve(threads);
  const int64_t chunk = (n + threads - 1) / threads;
  for (int t = 0; t < threads; ++t) {
    const int64_t lo = t * chunk;
    const int64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    pool.emplace_back([lo, hi, &fn] {
      for (int64_t i = lo; i < hi; ++i) fn(i);
    });
  }
  for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

// Gather rows by index from a contiguous 2-D source into a contiguous batch:
//   dst[i, :] = src[idx[i], :]    (row_bytes per row)
// Bounds are the caller's contract (indices validated Python-side against the
// dataset length); src is typically a memory-mapped token array, so this is
// the "dataset[i] for i in batch_indices" inner loop of a DataLoader worker
// fused into one call.
void at_gather_rows(const void* src, const int64_t* idx, void* dst,
                    int64_t n_rows, int64_t row_bytes, int threads) {
  const char* s = static_cast<const char*>(src);
  char* d = static_cast<char*>(dst);
  parallel_rows(n_rows, threads, [&](int64_t i) {
    std::memcpy(d + i * row_bytes, s + idx[i] * row_bytes, row_bytes);
  });
}

// Collate-stack: dst[i, :] = *srcs[i] for n equally-sized sample buffers.
// This is default_collate's np.stack with the per-sample Python iteration
// removed.
void at_stack_rows(const void* const* srcs, void* dst, int64_t n,
                   int64_t row_bytes, int threads) {
  char* d = static_cast<char*>(dst);
  parallel_rows(n, threads, [&](int64_t i) {
    std::memcpy(d + i * row_bytes, srcs[i], row_bytes);
  });
}

// Pad-stack for ragged rows of `elem` bytes per element:
//   dst[i, :lens[i]] = srcs[i];  dst[i, lens[i]:max_len] = pad pattern.
// The pad pattern is one element (elem bytes) replicated — covers int32 pad
// ids, float masks, etc.  dst rows are max_len elements.
void at_pad_stack(const void* const* srcs, const int64_t* lens, void* dst,
                  int64_t n, int64_t max_len, int64_t elem, const void* pad,
                  int threads) {
  char* d = static_cast<char*>(dst);
  const char* p = static_cast<const char*>(pad);
  const int64_t row_bytes = max_len * elem;
  // All-same-byte patterns (0, -1, 0xFF…) take memset; otherwise seed one
  // element and double the filled region with self-memcpy (log passes).
  bool uniform = true;
  for (int64_t i = 1; i < elem; ++i)
    if (p[i] != p[0]) { uniform = false; break; }
  parallel_rows(n, threads, [&](int64_t i) {
    char* row = d + i * row_bytes;
    const int64_t nb = lens[i] * elem;
    std::memcpy(row, srcs[i], nb);
    const int64_t tail = row_bytes - nb;
    if (tail <= 0) return;
    if (uniform) {
      std::memset(row + nb, p[0], tail);
    } else {
      std::memcpy(row + nb, p, elem);
      int64_t filled = elem;
      while (filled < tail) {
        const int64_t take = filled < tail - filled ? filled : tail - filled;
        std::memcpy(row + nb + filled, row + nb, take);
        filled += take;
      }
    }
  });
}

// Chunked parallel write: creates/truncates `path`, then pwrites `nbytes`
// from buf in `threads` contiguous chunks.  Returns 0 on success, else
// -errno.  Used for checkpoint shard payloads (safetensors body / raw
// weight blobs) where a single write() serializes the page-cache fill.
int at_write_file(const char* path, const void* buf, int64_t nbytes,
                  int threads) {
  int fd = ::open(path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return -errno;
  // Pre-extend so parallel pwrite never races file growth.
  if (nbytes > 0 && ::ftruncate(fd, nbytes) != 0) {
    int e = errno;
    ::close(fd);
    return -e;
  }
  const char* b = static_cast<const char*>(buf);
  std::vector<int> errs(threads > 0 ? threads : 1, 0);
  if (threads <= 1) {
    int64_t off = 0;
    while (off < nbytes) {
      ssize_t w = ::pwrite(fd, b + off, nbytes - off, off);
      if (w < 0) { errs[0] = errno; break; }
      off += w;
    }
  } else {
    const int64_t chunk = (nbytes + threads - 1) / threads;
    std::vector<std::thread> pool;
    for (int t = 0; t < threads; ++t) {
      const int64_t lo = t * chunk;
      int64_t hi = lo + chunk;
      if (hi > nbytes) hi = nbytes;
      if (lo >= hi) break;
      pool.emplace_back([fd, b, lo, hi, t, &errs] {
        int64_t off = lo;
        while (off < hi) {
          ssize_t w = ::pwrite(fd, b + off, hi - off, off);
          if (w < 0) { errs[t] = errno; return; }
          off += w;
        }
      });
    }
    for (auto& th : pool) th.join();
  }
  if (::close(fd) != 0 && errs[0] == 0) errs[0] = errno;
  for (int e : errs)
    if (e != 0) return -e;
  return 0;
}

// Write `nbytes` from buf at `offset` into an EXISTING file (no truncate) —
// the building block for container formats (safetensors): the Python side
// writes the header and pre-sizes the file, then streams each tensor body to
// its offset with chunked parallel pwrite.  Returns 0 or -errno.
int at_write_region(const char* path, const void* buf, int64_t nbytes,
                    int64_t offset, int threads) {
  int fd = ::open(path, O_WRONLY);
  if (fd < 0) return -errno;
  const char* b = static_cast<const char*>(buf);
  std::vector<int> errs(threads > 0 ? threads : 1, 0);
  if (threads <= 1) {
    int64_t off = 0;
    while (off < nbytes) {
      ssize_t w = ::pwrite(fd, b + off, nbytes - off, offset + off);
      if (w < 0) { errs[0] = errno; break; }
      off += w;
    }
  } else {
    const int64_t chunk = (nbytes + threads - 1) / threads;
    std::vector<std::thread> pool;
    for (int t = 0; t < threads; ++t) {
      const int64_t lo = t * chunk;
      int64_t hi = lo + chunk;
      if (hi > nbytes) hi = nbytes;
      if (lo >= hi) break;
      pool.emplace_back([fd, b, lo, hi, offset, t, &errs] {
        int64_t off = lo;
        while (off < hi) {
          ssize_t w = ::pwrite(fd, b + off, hi - off, offset + off);
          if (w < 0) { errs[t] = errno; return; }
          off += w;
        }
      });
    }
    for (auto& th : pool) th.join();
  }
  if (::close(fd) != 0 && errs[0] == 0) errs[0] = errno;
  for (int e : errs)
    if (e != 0) return -e;
  return 0;
}

// Chunked parallel read of exactly `nbytes` from `path` at `offset` into
// buf.  Returns 0 on success, -errno on open/IO failure, -EIO on short read.
int at_read_file(const char* path, void* buf, int64_t nbytes, int64_t offset,
                 int threads) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -errno;
  char* b = static_cast<char*>(buf);
  std::vector<int> errs(threads > 0 ? threads : 1, 0);
  if (threads <= 1) {
    int64_t off = 0;
    while (off < nbytes) {
      ssize_t r = ::pread(fd, b + off, nbytes - off, offset + off);
      if (r < 0) { errs[0] = errno; break; }
      if (r == 0) { errs[0] = EIO; break; }  // short file
      off += r;
    }
  } else {
    const int64_t chunk = (nbytes + threads - 1) / threads;
    std::vector<std::thread> pool;
    for (int t = 0; t < threads; ++t) {
      const int64_t lo = t * chunk;
      int64_t hi = lo + chunk;
      if (hi > nbytes) hi = nbytes;
      if (lo >= hi) break;
      pool.emplace_back([fd, b, lo, hi, offset, t, &errs] {
        int64_t off = lo;
        while (off < hi) {
          ssize_t r = ::pread(fd, b + off, hi - off, offset + off);
          if (r < 0) { errs[t] = errno; return; }
          if (r == 0) { errs[t] = EIO; return; }
          off += r;
        }
      });
    }
    for (auto& th : pool) th.join();
  }
  ::close(fd);
  for (int e : errs)
    if (e != 0) return -e;
  return 0;
}

// ABI/version probe so the Python wrapper can reject a stale cached .so.
int at_abi_version(void) { return 1; }

}  // extern "C"
