"""Multiple models under one ds-config-ingested Accelerator.

Counterpart of the reference's
``test_utils/scripts/external_deps/test_ds_multiple_model.py:190-300``
(multiple_model_training: two models trained in one loop, both improving,
engine/state kept separate per model).  The reference juggles two DeepSpeed
engines with switchable active plugins; the mesh design needs no engine
objects — both models simply prepare onto the same ZeRO layout — so the
contract checked here is the user-visible one: independent updates,
knowledge-distillation-style joint loss, both losses improving, and a
checkpoint that round-trips BOTH models' and optimizers' state
(model_1/optimizer_1 artifact naming).
"""

from __future__ import annotations

import shutil

import numpy as np

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator, set_seed
from accelerate_tpu.utils.deepspeed_compat import from_deepspeed_config


def _mlp():
    return nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))


def multiple_model_training():
    import jax.numpy as jnp

    set_seed(42)
    Accelerator._reset_state()
    compat = from_deepspeed_config(
        {
            "zero_optimization": {"stage": 2},
            "train_micro_batch_size_per_gpu": 8,
            "gradient_accumulation_steps": 1,
        }
    )
    acc = Accelerator(**compat.accelerator_kwargs())

    nn.manual_seed(0)
    teacher, student = _mlp(), _mlp()
    opt_t = optim.AdamW(teacher.parameters(), lr=5e-3)
    opt_s = optim.AdamW(student.parameters(), lr=5e-3)
    teacher, opt_t, student, opt_s = acc.prepare(teacher, opt_t, student, opt_s)

    rng = np.random.default_rng(3)
    x = nn.Tensor(jnp.asarray(rng.normal(size=(32, 8)), jnp.float32))
    y = nn.Tensor(jnp.asarray(rng.normal(size=(32, 4)), jnp.float32))

    def step(xb, yb):
        # teacher fits the labels; student distills from the teacher
        opt_t.zero_grad()
        t_out = teacher(xb)
        t_loss = ((t_out - yb) ** 2).mean()
        acc.backward(t_loss)
        opt_t.step()

        opt_s.zero_grad()
        s_out = student(xb)
        with nn.no_grad():
            target = teacher(xb)
        s_loss = ((s_out - target) ** 2).mean()
        acc.backward(s_loss)
        opt_s.step()
        return t_loss, s_loss

    cstep = acc.compile_step(step)
    t_losses, s_losses = [], []
    for _ in range(12):
        t_l, s_l = cstep(x, y)
        t_losses.append(float(t_l))
        s_losses.append(float(s_l))
    assert t_losses[-1] < t_losses[0], f"teacher did not improve: {t_losses[::4]}"
    assert s_losses[-1] < s_losses[0], f"student did not improve: {s_losses[::4]}"

    # independent updates: the two models must have diverged from each other
    w_t = np.asarray(dict(teacher.named_parameters())["0.weight"].data)
    w_s = np.asarray(dict(student.named_parameters())["0.weight"].data)
    assert not np.allclose(w_t, w_s), "models shared parameters"

    # checkpoint round-trips BOTH models/optimizers (model_1/optimizer_1)
    from accelerate_tpu.test_utils.testing import launch_scoped_tmpdir

    ckpt = launch_scoped_tmpdir("acc_tpu_ds_multi")
    try:
        acc.save_state(ckpt)
        import glob
        import os

        if acc.is_main_process:
            from accelerate_tpu.utils.constants import MODEL_NAME, OPTIMIZER_NAME

            names = {os.path.basename(p) for p in glob.glob(os.path.join(ckpt, "*"))}
            assert any(n.startswith(f"{MODEL_NAME}_1.") for n in names), names
            assert any(n.startswith(f"{OPTIMIZER_NAME}_1.") for n in names), names
        sp = dict(student.named_parameters())["0.weight"]
        sp.data = sp.data * 0.0
        acc.load_state(ckpt)
        restored = np.asarray(dict(student.named_parameters())["0.weight"].data)
        np.testing.assert_allclose(restored, w_s, rtol=1e-5, atol=1e-6)
        acc.wait_for_everyone()
    finally:
        if acc.is_main_process:
            shutil.rmtree(ckpt, ignore_errors=True)
    print(
        f"rank{acc.process_index}: multiple-model ds training ok "
        f"(teacher {t_losses[0]:.3f}->{t_losses[-1]:.3f}, "
        f"student {s_losses[0]:.3f}->{s_losses[-1]:.3f})"
    )


def main():
    multiple_model_training()


if __name__ == "__main__":
    main()
