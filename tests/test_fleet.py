"""Elastic fleet runtime (docs/elastic.md): restore-point vote agreement,
coordinated multi-process rollback replacing the resilience refusal,
host-lost-driven dp resize with bitwise state after reshard and
zero-recompile resume off the AOT-cache prewarm, periodic mid-run fleet
aggregation, and the default-off path touching nothing."""

import json
import os

import jax
import numpy as np
import pytest

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import (
    Accelerator,
    CompilationCacheKwargs,
    FleetKwargs,
    ResilienceKwargs,
    TelemetryKwargs,
)
from accelerate_tpu.checkpointing import is_complete_checkpoint
from accelerate_tpu.data_loader import batch_to_global_array
from accelerate_tpu.fleet import (
    agree_restore_point,
    local_restore_candidates,
    surviving_mesh,
)
from accelerate_tpu.fleet import coordinate as fleet_coordinate
from accelerate_tpu.nn import Tensor
from accelerate_tpu.resilience import FaultPlan
from accelerate_tpu.resilience import retry as res_retry


def _num_devices():
    return len(jax.devices())


def _make_step(handlers=None, seed=0):
    nn.manual_seed(seed)
    acc = Accelerator(kwargs_handlers=handlers or None)
    model = nn.Linear(8, 4)
    opt = optim.AdamW(model.parameters(), lr=1e-2)
    model, opt = acc.prepare(model, opt)

    def step_fn(x):
        opt.zero_grad()
        loss = model(Tensor(x)).sum()
        acc.backward(loss)
        opt.step()
        return loss

    return acc, model, opt, acc.compile_step(step_fn)


def _batches(acc, n, batch=8):
    rng = np.random.default_rng(0)
    return [
        batch_to_global_array(
            np.asarray(rng.normal(size=(batch, 8)), np.float32), mesh=acc.mesh
        )
        for _ in range(n)
    ]


def _write_complete_checkpoint(path, step):
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "accelerator_meta.json"), "w") as f:
        json.dump({"step": step}, f)
    return str(path)


# ---------------------------------------------------------------------------
# fault-plan verb
# ---------------------------------------------------------------------------

def test_host_lost_verb_parses_and_fires_once():
    plan = FaultPlan.parse("host_lost:step=2")
    assert [(d.kind, d.step, d.times) for d in plan.directives] == [
        ("host_lost", 2, 1)
    ]
    from accelerate_tpu.resilience import FaultInjector

    inj = FaultInjector(plan)
    assert not inj.maybe_host_lost(1)  # wrong step
    assert inj.maybe_host_lost(2)
    assert not inj.maybe_host_lost(2)  # times exhausted


def test_host_lost_verb_needs_step():
    with pytest.raises(ValueError):
        FaultPlan.parse("host_lost")


# ---------------------------------------------------------------------------
# pillar 1: restore-point vote
# ---------------------------------------------------------------------------

def test_agree_restore_point_newest_common(tmp_path):
    """The agreement is the HIGHEST-step offer visible to every rank — a
    newer checkpoint only some ranks drained must lose, or the losers'
    collective load_state would hang on its missing shards."""
    a = {"path": "/ckpt/a", "step": 1}
    b = {"path": "/ckpt/b", "step": 2}
    c = {"path": "/ckpt/c", "step": 3}  # rank 0 only: never eligible
    assert agree_restore_point([[c, b, a], [b, a]]) == b
    assert agree_restore_point([[a], [a]]) == a
    assert agree_restore_point([[a, b], [c]]) is None  # disjoint: no vote
    assert agree_restore_point([]) is None
    # world=1 degenerates to the rank's own newest
    assert agree_restore_point([[a, b]]) == b


def test_agree_restore_point_tie_breaks_deterministically():
    """Equal steps must break ties identically on every rank (path order),
    or ranks would load different folders and deadlock."""
    x = {"path": "/ckpt/x", "step": 2}
    y = {"path": "/ckpt/y", "step": 2}
    assert agree_restore_point([[x, y], [y, x]]) == y
    assert agree_restore_point([[y, x], [x, y]]) == y


def test_local_restore_candidates_orders_and_filters(tmp_path):
    acc, _, _, step = _make_step()
    complete_new = _write_complete_checkpoint(tmp_path / "new", step=5)
    incomplete = str(tmp_path / "torn")
    os.makedirs(incomplete)  # no sentinel: killed mid-write
    acc.resilience.enabled = True
    acc.resilience.last_checkpoint = complete_new
    offers = local_restore_candidates(acc)
    assert [o["path"] for o in offers] == [os.path.abspath(complete_new)]
    assert offers[0]["step"] == 5


def test_vote_restore_point_simulated_two_ranks(tmp_path, monkeypatch):
    """The all-ranks agreement pin: simulate the gather of two ranks'
    offers — the newest all-ranks-visible checkpoint wins and the ballot
    lands as a restore_vote fleet event."""
    acc, _, _, _ = _make_step(
        [FleetKwargs(enabled=True), ResilienceKwargs(enabled=True, preemption=False)]
    )
    shared_old = _write_complete_checkpoint(tmp_path / "shared", step=1)
    local_new = _write_complete_checkpoint(tmp_path / "local", step=7)
    acc.resilience.last_checkpoint = local_new
    peer_offers = [{"path": os.path.abspath(shared_old), "step": 1}]
    real_gather = fleet_coordinate.gather_object

    def fake_gather(payload):
        # rank 0 = this process's real offers; rank 1 = a peer that only
        # ever saw the shared checkpoint (its host missed the local drain)
        local = real_gather(payload)
        local.append(peer_offers)
        return local

    monkeypatch.setattr(fleet_coordinate, "gather_object", fake_gather)
    # make this rank ALSO offer the shared checkpoint (both visible here)
    acc.project_configuration.automatic_checkpoint_naming = False
    offers = local_restore_candidates(acc)
    assert len(offers) == 1  # only local_new — shared isn't in this rank's view
    acc.resilience.last_checkpoint = None

    def fake_candidates(accelerator):
        return [
            {"path": os.path.abspath(local_new), "step": 7},
            {"path": os.path.abspath(shared_old), "step": 1},
        ]

    monkeypatch.setattr(fleet_coordinate, "local_restore_candidates", fake_candidates)
    agreed = fleet_coordinate.vote_restore_point(acc, fleet=acc.fleet)
    # local_new (step 7) is NOT in the peer's offers → the shared step-1
    # checkpoint is the only safe restore point
    assert agreed == {"path": os.path.abspath(shared_old), "step": 1}
    votes = [e for e in acc.fleet.events if e["event"] == "restore_vote"]
    assert len(votes) == 1 and votes[0]["ranks"] == 2
    assert votes[0]["agreed"] == os.path.abspath(shared_old)


def test_multiprocess_rollback_refused_without_fleet(monkeypatch):
    """The historical refusal stands when the fleet is off: a lone rank's
    collective load_state would deadlock the mesh."""
    acc, _, _, step = _make_step(
        [ResilienceKwargs(enabled=True, preemption=False)]
    )
    monkeypatch.setattr(res_retry, "_multi_process", lambda: True)
    retrier = acc.resilience.retrier
    assert retrier._rollback_allowed() is False
    assert retrier._coordinator() is None


def test_multiprocess_rollback_coordinated_with_fleet(monkeypatch):
    """ISSUE acceptance: coordinated multi-process rollback replaces the
    single-process refusal — with the fleet armed, a multi-process retrier
    routes exhaustion through the vote protocol instead of refusing."""
    acc, _, _, step = _make_step(
        [
            FleetKwargs(enabled=True),
            ResilienceKwargs(enabled=True, preemption=False),
        ]
    )
    monkeypatch.setattr(res_retry, "_multi_process", lambda: True)
    retrier = acc.resilience.retrier
    assert retrier._coordinator() is acc.fleet
    assert retrier._rollback_allowed() is True
    # opting out of coordination restores the refusal
    acc.fleet.handler.coordinate_rollback = False
    assert retrier._coordinator() is None
    assert retrier._rollback_allowed() is False


def test_coordinated_rollback_end_to_end(tmp_path, monkeypatch):
    """Exhausted retries on a 'multi-process' run vote, agree, restore and
    replay — bitwise — where the pre-fleet retrier raised."""
    acc, _, _, step = _make_step(
        [
            FleetKwargs(enabled=True),
            ResilienceKwargs(
                enabled=True, preemption=False, max_retries=1,
                fault_plan="dispatch:step=3,times=3", retry_backoff_s=0.0,
            ),
        ]
    )
    x = _batches(acc, 1)[0]
    for _ in range(2):
        float(step(x))
    acc.save_state(str(tmp_path / "good"))
    monkeypatch.setattr(res_retry, "_multi_process", lambda: True)
    l2 = float(step(x))
    l3 = float(step(x))  # exhausts → vote → coordinated restore → replay
    assert l3 == l2
    rollbacks = [e for e in acc.resilience.events if e["event"] == "rollback"]
    assert len(rollbacks) == 1 and rollbacks[0]["coordinated"] is True
    assert any(e["event"] == "restore_vote" for e in acc.fleet.events)


# ---------------------------------------------------------------------------
# pillar 2: elastic dp resize
# ---------------------------------------------------------------------------

def test_surviving_mesh_shrinks_dp_only():
    acc, _, _, _ = _make_step()
    mesh = acc.mesh
    dp = dict(mesh.shape)["dp"]
    if dp < 2:
        pytest.skip("needs dp >= 2")
    new = surviving_mesh(mesh, dp // 2)
    assert dict(new.shape)["dp"] == dp // 2
    assert [dict(new.shape)[a] for a in new.axis_names if a != "dp"] == [
        dict(mesh.shape)[a] for a in mesh.axis_names if a != "dp"
    ]
    # survivors are the leading dp blocks: inner-axis neighborhoods intact
    assert new.devices.tolist() == np.take(
        mesh.devices, range(dp // 2), axis=mesh.axis_names.index("dp")
    ).tolist()
    with pytest.raises(ValueError):
        surviving_mesh(mesh, dp * 2)  # growing is a relaunch, not a resize
    with pytest.raises(ValueError):
        surviving_mesh(mesh, 0)


def test_surviving_mesh_honors_lost_blocks():
    """Review-pinned: when the reclamation notice names WHICH dp block
    died, the survivors — not the dead host's devices — make the mesh."""
    acc, _, _, _ = _make_step()
    mesh = acc.mesh
    dp = dict(mesh.shape)["dp"]
    if dp < 2:
        pytest.skip("needs dp >= 2")
    dp_index = mesh.axis_names.index("dp")
    new = surviving_mesh(mesh, dp // 2, lost_blocks=[0])
    # block 0 is gone: the kept blocks start at 1
    expect = np.take(
        mesh.devices, range(1, dp // 2 + 1), axis=dp_index
    ).tolist()
    assert new.devices.tolist() == expect
    with pytest.raises(ValueError):
        surviving_mesh(mesh, dp // 2, lost_blocks=[dp + 3])  # outside axis
    with pytest.raises(ValueError):
        # too many dead blocks for the requested extent
        surviving_mesh(mesh, dp, lost_blocks=[0])


def test_checkpoint_step_fail_soft_on_foreign_meta(tmp_path):
    """Review-pinned: a corrupt/foreign sentinel (non-object JSON) must be
    a skipped candidate, never a crash inside the restore vote."""
    from accelerate_tpu.checkpointing import checkpoint_step

    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "accelerator_meta.json").write_text("[]")
    assert checkpoint_step(str(bad)) is None
    good = tmp_path / "good"
    good.mkdir()
    (good / "accelerator_meta.json").write_text('{"step": 4}')
    assert checkpoint_step(str(good)) == 4


def test_host_lost_injection_trips_should_resize(tmp_path):
    acc, _, _, step = _make_step(
        [FleetKwargs(enabled=True, fault_plan="host_lost:step=1")]
    )
    x = _batches(acc, 1)[0]
    float(step(x))
    assert not acc.fleet.should_resize
    float(step(x))
    assert acc.fleet.should_resize
    assert acc.fleet.should_resize  # sticky
    assert any(e["event"] == "host_lost" for e in acc.fleet.events)


def test_resize_consumes_should_resize_flag(tmp_path):
    """Review-pinned: the documented `if should_resize: resize()` loop must
    not re-drain/re-mesh every later step — resize() consumes the flag it
    handled (a LATER host loss re-trips it)."""
    if _num_devices() < 2:
        pytest.skip("needs >= 2 devices")
    acc, _, _, step = _make_step(
        [FleetKwargs(enabled=True, fault_plan="host_lost:step=0")]
    )
    dp = dict(acc.mesh.shape)["dp"]
    float(step(_batches(acc, 1)[0]))
    assert acc.fleet.should_resize
    acc.fleet.resize(acc, target_dp=dp // 2, output_dir=str(tmp_path / "d"))
    assert not acc.fleet.should_resize
    assert acc.fleet.resizes_total == 1


def test_resize_reshards_bitwise_and_resumes(tmp_path):
    """The acceptance row: a dp=N run with an injected host loss drains a
    complete checkpoint, re-meshes at dp=N/2, reshards ZeRO-1 masters and
    moments BITWISE from the spec-carrying checkpoint, and resumes within
    loss parity of the uninterrupted run."""
    if _num_devices() < 2:
        pytest.skip("needs >= 2 devices")
    steps_total = 5
    lost_at = 2

    # uninterrupted reference at full dp
    Accelerator._reset_state()
    acc_ref, _, _, step_ref = _make_step()
    ref = [float(step_ref(b)) for b in _batches(acc_ref, steps_total)]

    Accelerator._reset_state()
    acc, model, opt, step = _make_step(
        [FleetKwargs(enabled=True, fault_plan=f"host_lost:step={lost_at}")]
    )
    dp = dict(acc.mesh.shape)["dp"]
    assert acc.state.zero1_enabled  # dp > 1, no fsdp owner
    batches = _batches(acc, steps_total)
    losses = []
    resized = None
    i = 0
    while i < len(batches):
        losses.append(float(step(batches[i])))
        i += 1
        if resized is None and acc.fleet.should_resize:
            masters = [
                np.asarray(m) for m in opt.optimizer.master_params if m is not None
            ]
            moments = [
                np.asarray(leaf)
                for leaf in jax.tree_util.tree_leaves(opt.optimizer.capture_state())
            ]
            resized = acc.fleet.resize(
                acc, target_dp=dp // 2, output_dir=str(tmp_path / "drain")
            )
            # drain → COMPLETE checkpoint
            assert is_complete_checkpoint(resized["checkpoint"])
            # re-mesh at the surviving topology
            assert dict(acc.mesh.shape)["dp"] == dp // 2
            assert resized["old_dp"] == dp and resized["dp"] == dp // 2
            # ZeRO-1 masters + moments resharded BITWISE, and actually
            # laid out on the new mesh
            masters_after = [
                np.asarray(m) for m in opt.optimizer.master_params if m is not None
            ]
            for before, after in zip(masters, masters_after):
                assert (before == after).all()
            moments_after = [
                np.asarray(leaf)
                for leaf in jax.tree_util.tree_leaves(opt.optimizer.capture_state())
            ]
            for before, after in zip(moments, moments_after):
                if before.dtype == np.float32 and before.shape:
                    assert (before == after).all()
            for m in opt.optimizer.master_params:
                if m is not None and hasattr(m, "sharding"):
                    assert m.sharding.mesh.shape == acc.mesh.shape
            # surviving batches re-laid on the new mesh
            batches = batches[:i] + [
                batch_to_global_array(np.asarray(b), mesh=acc.mesh)
                for b in batches[i:]
            ]
    assert resized is not None, "host loss never tripped"
    assert len(losses) == steps_total
    # exact through the loss step, loss-parity after the dp change (the
    # reduce order moves with dp; docs/elastic.md documents the tolerance)
    assert losses[: lost_at + 1] == ref[: lost_at + 1]
    np.testing.assert_allclose(losses, ref, rtol=1e-3)
    events = [e["event"] for e in acc.fleet.events]
    assert events.count("host_lost") == 1
    assert events.count("drain") == 1
    assert events.count("resize") == 1


def test_resize_prewarm_zero_recompiles(tmp_path):
    """Acceptance: zero recompiles for programs served by the AOT-cache
    prewarm — a run whose resized topology was already compiled (a prior
    fleet at that dp, same store) resumes with the post-resize first step
    deserialized, not traced."""
    if _num_devices() < 2:
        pytest.skip("needs >= 2 devices")
    cache_dir = str(tmp_path / "aot")
    steps = 3

    def handlers(plan=None):
        out = [
            CompilationCacheKwargs(cache_dir=cache_dir),
            TelemetryKwargs(enabled=True),
            FleetKwargs(enabled=True, fault_plan=plan),
        ]
        return out

    # phase 1 (the "prior fleet"): resize immediately, train at the small
    # topology so its program lands in the store
    Accelerator._reset_state()
    acc, _, _, step = _make_step(handlers())
    dp = dict(acc.mesh.shape)["dp"]
    target = dp // 2
    acc.fleet.resize(acc, target_dp=target, output_dir=str(tmp_path / "seed"))
    for b in _batches(acc, 2):
        float(step(b))
    assert acc.aot_cache.stores >= 1

    # phase 2: fresh run at full dp, host lost at step 1, resize → the
    # post-resize build must be a cache hit (zero trace, zero compile)
    Accelerator._reset_state()
    acc, _, _, step = _make_step(handlers("host_lost:step=1"))
    batches = _batches(acc, steps)
    i = 0
    resized = None
    while i < len(batches):
        float(step(batches[i]))
        i += 1
        if resized is None and acc.fleet.should_resize:
            resized = acc.fleet.resize(
                acc, target_dp=target, output_dir=str(tmp_path / "drain")
            )
            assert resized["aot_prewarmed"] >= 1
            batches = batches[:i] + [
                batch_to_global_array(np.asarray(b), mesh=acc.mesh)
                for b in batches[i:]
            ]
    assert resized is not None
    # the post-resize first call rebuilt (new topology) but deserialized
    # the stored executable: its build phases read zero
    records = acc.telemetry.timeline.records()
    post = [r for r in records if r.built][-1]
    assert post.trace_ms == 0.0 and post.compile_ms == 0.0, (
        post.trace_ms, post.compile_ms,
    )
    hits = [e for e in acc.telemetry.aot_cache_events if e["event"] == "hit"]
    assert len(hits) >= 1


# ---------------------------------------------------------------------------
# pillar 3: periodic fleet aggregation (the resize signal)
# ---------------------------------------------------------------------------

def test_periodic_aggregation_records_fleet_signal():
    acc, _, _, step = _make_step(
        [FleetKwargs(enabled=True, aggregate_every_n=2), TelemetryKwargs(enabled=True)]
    )
    assert acc.fleet.fleet_signal() is None
    for b in _batches(acc, 4):
        float(step(b))
    signals = [
        r for r in acc.telemetry.fleet_events if r.get("kind") == "fleet"
    ]
    assert len(signals) == 2  # cadence 2 over 4 dispatches
    latest = acc.fleet.fleet_signal()
    assert latest is signals[-1]
    assert latest["periodic"] is True and latest["ranks"] == 1
    assert latest["per_rank"][0]["replay_steps"] >= 1
    # the signal rides the retained history → JSONL dump schema
    kinds = {r.get("kind") for r in acc.telemetry.all_records()}
    assert "fleet" in kinds


def test_fleet_events_reach_telemetry_export():
    acc, _, _, step = _make_step(
        [
            FleetKwargs(enabled=True, fault_plan="host_lost:step=0"),
            TelemetryKwargs(enabled=True),
        ]
    )
    float(step(_batches(acc, 1)[0]))
    assert acc.fleet.should_resize
    records = [
        r for r in acc.telemetry.all_records() if r.get("kind") == "fleet_event"
    ]
    assert any(r["event"] == "host_lost" for r in records)


# ---------------------------------------------------------------------------
# default-off
# ---------------------------------------------------------------------------

def test_fleet_default_off_touches_nothing(tmp_path):
    acc, _, _, step = _make_step()
    assert not acc.fleet.enabled
    assert acc.resilience.fleet is None
    assert step._fleet is None  # capture path: one None-check, no hooks
    float(step(_batches(acc, 1)[0]))
    assert acc.fleet.dispatch_calls == 0
    assert acc.fleet.events == []
    with pytest.raises(RuntimeError):
        acc.fleet.resize(acc)


def test_resize_respects_min_dp_floor():
    acc, _, _, _ = _make_step([FleetKwargs(enabled=True, min_dp=4)])
    with pytest.raises(ValueError):
        acc.fleet.resize(acc, target_dp=1)


# ---------------------------------------------------------------------------
# grow-side resize (fleet/grow.py)
# ---------------------------------------------------------------------------

def test_host_gained_and_signal_storm_verbs_parse():
    plan = FaultPlan.parse("host_gained:step=4;signal_storm:step=1,times=6")
    assert [(d.kind, d.step, d.times) for d in plan.directives] == [
        ("host_gained", 4, 1), ("signal_storm", 1, 6),
    ]
    from accelerate_tpu.resilience import FaultInjector

    inj = FaultInjector(plan)
    assert not inj.maybe_host_gained(1)
    assert inj.maybe_host_gained(4)
    assert not inj.maybe_host_gained(4)  # exhausted
    # a storm runs from its start dispatch, alternating spike/drop
    assert inj.maybe_signal_storm(0) is None  # before start
    flaps = [inj.maybe_signal_storm(i) for i in range(1, 8)]
    assert flaps == [True, False, True, False, True, False, None]
    with pytest.raises(ValueError):
        FaultPlan.parse("host_gained")  # needs step=N
    with pytest.raises(ValueError):
        FaultPlan.parse("signal_storm")


def test_grown_mesh_appends_rejoined_blocks():
    from accelerate_tpu.fleet import grown_mesh, max_growable_dp
    from accelerate_tpu.fleet.grow import grown_axis_sizes

    acc, _, _, _ = _make_step()
    mesh = acc.mesh
    dp = dict(mesh.shape)["dp"]
    if dp < 2:
        pytest.skip("needs dp >= 2")
    small = surviving_mesh(mesh, dp // 2)
    assert max_growable_dp(small) == dp
    wide = grown_mesh(small, dp)
    assert dict(wide.shape)["dp"] == dp
    # the survivors' blocks stay in place, the rejoined blocks append —
    # live state never moves under a grow
    assert wide.devices.tolist() == mesh.devices.tolist()
    with pytest.raises(ValueError):
        grown_axis_sizes(small, dp // 2)  # not a widening
    with pytest.raises(ValueError):
        grown_mesh(small, dp * 16)  # more devices than exist


def test_agree_grow_requires_identical_proposals():
    from accelerate_tpu.fleet import agree_grow

    a = {"target_dp": 4, "device_ids": [0, 1, 2, 3]}
    assert agree_grow([a, dict(a)]) == a
    assert agree_grow([a]) == a  # world=1 degenerates
    assert agree_grow([]) is None
    assert agree_grow([a, {"target_dp": 4, "device_ids": [0, 1, 2, 9]}]) is None
    assert agree_grow([a, {"target_dp": 2, "device_ids": [0, 1]}]) is None
    # an error ballot (rank cannot see the rejoined host) aborts — even a
    # unanimous one carries no executable plan
    err = {"target_dp": 4, "error": "only 0 visible"}
    assert agree_grow([a, err]) is None
    assert agree_grow([err, err]) is None


def test_grow_reshards_bitwise_back_to_full_dp(tmp_path):
    """The grow acceptance row: after a shrink, ``fleet.grow()`` re-meshes
    dp back up through the rendezvous, reshards ZeRO-1 masters/moments
    BITWISE onto the wider mesh (vs the values before the grow — a
    from-checkpoint reshard, not a reinit), and the host_gained flag is
    consumed."""
    if _num_devices() < 2:
        pytest.skip("needs >= 2 devices")
    acc, model, opt, step = _make_step(
        [FleetKwargs(enabled=True, fault_plan="host_gained:step=1")]
    )
    dp = dict(acc.mesh.shape)["dp"]
    batches = _batches(acc, 4)
    float(step(batches[0]))
    # shrink first (the host came back AFTER a loss)
    acc.fleet.resize(acc, target_dp=dp // 2, output_dir=str(tmp_path / "d1"))
    assert dict(acc.mesh.shape)["dp"] == dp // 2
    float(step(batch_to_global_array(np.asarray(batches[1]), mesh=acc.mesh)))
    assert acc.fleet.should_grow  # injected at dispatch 1
    masters = [
        np.asarray(m) for m in opt.optimizer.master_params if m is not None
    ]
    moments = [
        np.asarray(leaf)
        for leaf in jax.tree_util.tree_leaves(opt.optimizer.capture_state())
    ]
    info = acc.fleet.grow(acc, target_dp=dp, output_dir=str(tmp_path / "d2"))
    assert info["direction"] == "grow" and info["dp"] == dp
    assert dict(acc.mesh.shape)["dp"] == dp
    assert not acc.fleet.should_grow  # consumed
    assert acc.fleet.grows_total == 1
    masters_after = [
        np.asarray(m) for m in opt.optimizer.master_params if m is not None
    ]
    for before, after in zip(masters, masters_after):
        assert (before == after).all()
    moments_after = [
        np.asarray(leaf)
        for leaf in jax.tree_util.tree_leaves(opt.optimizer.capture_state())
    ]
    for before, after in zip(moments, moments_after):
        if before.dtype == np.float32 and before.shape:
            assert (before == after).all()
    for m in opt.optimizer.master_params:
        if m is not None and hasattr(m, "sharding"):
            assert m.sharding.mesh.shape == acc.mesh.shape
    events = [e["event"] for e in acc.fleet.events]
    assert "grow_rendezvous" in events
    # one resize verb either direction: a wider target routes resize->grow
    acc.fleet.resize(acc, target_dp=dp // 2, output_dir=str(tmp_path / "d3"))
    info2 = acc.fleet.resize(acc, target_dp=dp, output_dir=str(tmp_path / "d4"))
    assert info2["direction"] == "grow"


# ---------------------------------------------------------------------------
# autopilot: FleetKwargs grammar growth
# ---------------------------------------------------------------------------

def test_autopilot_policy_parse_and_resolve():
    from accelerate_tpu.fleet import AutopilotPolicy

    p = AutopilotPolicy.parse("skew_pct=150,window=4,hysteresis=0.2,cooldown=2")
    assert (p.skew_pct, p.window, p.hysteresis, p.cooldown) == (150.0, 4, 0.2, 2)
    assert AutopilotPolicy.resolve(None) is None
    assert AutopilotPolicy.resolve(False) is None
    assert AutopilotPolicy.resolve("off") is None
    assert AutopilotPolicy.resolve("0") is None
    assert AutopilotPolicy.resolve(True) == AutopilotPolicy()
    assert AutopilotPolicy.resolve("on") == AutopilotPolicy()
    assert AutopilotPolicy.resolve({"queue_high": 3.0}).queue_high == 3.0
    assert AutopilotPolicy.resolve(p) is p
    with pytest.raises(ValueError):
        AutopilotPolicy.parse("skew_pct=abc")
    with pytest.raises(ValueError):
        AutopilotPolicy.parse("not_a_knob=1")
    with pytest.raises(ValueError):
        AutopilotPolicy.resolve({"bogus": 1})


def test_autopilot_env_kwargs_precedence(monkeypatch):
    from accelerate_tpu.fleet import AutopilotPolicy

    monkeypatch.setenv("ACCELERATE_FLEET_AUTOPILOT", "skew_pct=50")
    handler = FleetKwargs(enabled=True)
    assert handler.autopilot_policy == AutopilotPolicy(skew_pct=50.0)
    # explicit kwargs beat the env — including an explicit OFF
    handler = FleetKwargs(enabled=True, autopilot="skew_pct=70")
    assert handler.autopilot_policy.skew_pct == 70.0
    handler = FleetKwargs(enabled=True, autopilot="off")
    assert handler.autopilot_policy is None
    monkeypatch.delenv("ACCELERATE_FLEET_AUTOPILOT")
    assert FleetKwargs(enabled=True).autopilot_policy is None  # default off


def test_autopilot_bad_thresholds_raise_at_construction():
    """ISSUE satellite: bad values must raise when the kwargs handler is
    BUILT — never at the autopilot's first fire, mid-training."""
    for bad in (
        "skew_pct=-1", "skew_pct=0", "queue_high=0", "occupancy_low=1.5",
        "window=0", "hysteresis=1.0", "hysteresis=-0.1", "cooldown=-1",
    ):
        with pytest.raises(ValueError):
            FleetKwargs(enabled=True, autopilot=bad)


def test_autopilot_default_off_capture_pytree_byte_identical():
    """ISSUE satellite: with the autopilot left off (and even with the env
    spelling an armed policy while the FLEET itself is off), the captured
    state pytree and the losses are byte-identical to the no-handler
    baseline."""
    x = np.asarray(np.random.default_rng(0).normal(size=(8, 8)), np.float32)

    def leaf_bytes(leaf):
        try:
            return np.asarray(leaf).tobytes()
        except TypeError:  # typed PRNG keys refuse __array__
            return np.asarray(jax.random.key_data(leaf)).tobytes()

    def run(handlers):
        Accelerator._reset_state()
        acc, _, _, step = _make_step(handlers, seed=0)
        loss = float(step(batch_to_global_array(x, mesh=acc.mesh)))
        state = step._collect_state()
        leaves, treedef = jax.tree_util.tree_flatten(state)
        return loss, treedef, [leaf_bytes(l) for l in leaves], acc, step

    base_loss, base_tree, base_leaves, _, base_step = run(None)
    assert base_step._fleet is None
    # fleet OFF + an armed autopilot env: everything still byte-identical
    os.environ["ACCELERATE_FLEET_AUTOPILOT"] = "skew_pct=10,window=1"
    try:
        loss, tree, leaves, acc, step = run([FleetKwargs(enabled=False)])
    finally:
        del os.environ["ACCELERATE_FLEET_AUTOPILOT"]
    assert step._fleet is None and acc.fleet.autopilot is None
    assert loss == base_loss
    assert tree == base_tree
    assert leaves == base_leaves
    # fleet ON without autopilot: no autopilot constructed, no decisions
    loss, tree, leaves, acc, step = run([FleetKwargs(enabled=True)])
    assert acc.fleet.autopilot is None
    assert loss == base_loss and tree == base_tree and leaves == base_leaves


# ---------------------------------------------------------------------------
# autopilot: pure policy evaluation over synthetic signal windows
# ---------------------------------------------------------------------------

def test_evaluate_window_debounce_fires_after_window():
    from accelerate_tpu.fleet import AutopilotPolicy, evaluate_window

    policy = AutopilotPolicy(skew_pct=100.0, window=3, hysteresis=0.25)
    s = lambda v: {"skew_pct": v}  # noqa: E731
    # too young: armed now but held < window -> suppressed
    d = evaluate_window(policy, [s(150.0)])
    assert d["suppressed"] and not d["fired"] and d["signal"] == "skew_pct"
    assert "debounce" in d["reason"]
    # sustained above threshold for the full window -> fires
    d = evaluate_window(policy, [s(150.0), s(150.0), s(150.0)])
    assert d["fired"] and d["action"] == "shrink"
    assert d["window_values"] == [150.0, 150.0, 150.0]
    assert d["held"] == 3 and d["threshold"] == 100.0


def test_evaluate_window_hysteresis_dead_band_and_flap():
    from accelerate_tpu.fleet import AutopilotPolicy, evaluate_window

    policy = AutopilotPolicy(skew_pct=100.0, window=3, hysteresis=0.25)
    s = lambda v: {"skew_pct": v}  # noqa: E731
    # dip into the dead band (>= 75, < 100) does NOT reset the streak
    d = evaluate_window(policy, [s(150.0), s(80.0), s(120.0)])
    assert d["fired"], d
    # flap BELOW the sustain floor resets: armed again but held 1/3
    d = evaluate_window(policy, [s(150.0), s(0.0), s(150.0)])
    assert d["suppressed"] and not d["fired"]
    assert d["held"] == 1 and "flap" in d["reason"]
    # fully in the dead band with no arming crossing: quiet, not fired
    d = evaluate_window(policy, [s(80.0), s(80.0), s(80.0)])
    assert not d["fired"] and not d["suppressed"]


def test_evaluate_window_serving_signals():
    from accelerate_tpu.fleet import AutopilotPolicy, evaluate_window

    policy = AutopilotPolicy(queue_high=4.0, occupancy_low=0.25, window=2)
    deep = {"queue_depth": 6.0, "occupancy": 1.0}
    d = evaluate_window(policy, [deep, deep])
    assert d["fired"] and d["action"] == "grow" and d["signal"] == "queue_depth"
    # idle occupancy shrinks ONLY with an empty queue
    idle = {"queue_depth": 0.0, "occupancy": 0.1}
    d = evaluate_window(policy, [idle, idle])
    assert d["fired"] and d["action"] == "shrink" and d["signal"] == "occupancy"
    idle_but_queued = {"queue_depth": 2.0, "occupancy": 0.1}
    d = evaluate_window(policy, [idle_but_queued, idle_but_queued])
    assert not d["fired"]
    # queue pressure outranks the shrink signals when both hold
    both = {"queue_depth": 6.0, "occupancy": 0.1, "skew_pct": 500.0}
    d = evaluate_window(
        AutopilotPolicy(queue_high=4.0, window=2), [both, both]
    )
    assert d["fired"] and d["action"] == "grow"


# ---------------------------------------------------------------------------
# autopilot: the driver (closed loop, storm, skew)
# ---------------------------------------------------------------------------

def test_autopilot_closed_loop_no_caller_polling(tmp_path):
    """ISSUE acceptance: under an injected host_lost then host_gained plan
    the autopilot ALONE drives dp down and back up — the loop below never
    reads should_resize or calls resize — with final losses within 1e-3 of
    the uninterrupted run."""
    if _num_devices() < 2:
        pytest.skip("needs >= 2 devices")
    steps = 6

    Accelerator._reset_state()
    acc_ref, _, _, step_ref = _make_step()
    raw = [np.asarray(b) for b in _batches(acc_ref, steps)]
    ref = [float(step_ref(b)) for b in _batches(acc_ref, steps)]

    Accelerator._reset_state()
    acc, _, _, step = _make_step(
        [
            FleetKwargs(
                enabled=True, autopilot=True,
                fault_plan="host_lost:step=1;host_gained:step=3",
                checkpoint_dir=str(tmp_path / "drain"),
            )
        ]
    )
    dp = dict(acc.mesh.shape)["dp"]
    losses = [
        float(step(batch_to_global_array(b, mesh=acc.mesh))) for b in raw
    ]
    assert acc.fleet.resizes_total == 1 and acc.fleet.grows_total == 1
    assert dict(acc.mesh.shape)["dp"] == dp
    np.testing.assert_allclose(losses, ref, rtol=1e-3)
    decisions = [e for e in acc.fleet.events if e.get("kind") == "autopilot"]
    fired = [(d["signal"], d["action"]) for d in decisions if d["fired"]]
    assert fired == [("host_lost", "shrink"), ("host_gained", "grow")]
    # every decision reproducible from its record: policy + ts + resize info
    for d in decisions:
        assert "policy" in d and "ts" in d
    for d in decisions:
        if d["fired"]:
            assert d["resize"]["direction"] in ("shrink", "grow")


def test_autopilot_signal_storm_suppressed_zero_resizes():
    """ISSUE acceptance: a signal_storm flapping skew above/below the
    threshold within the debounce window produces suppressed-decision
    records and EXACTLY ZERO resizes."""
    acc, _, _, step = _make_step(
        [
            FleetKwargs(
                enabled=True, autopilot="window=3,cooldown=2",
                fault_plan="signal_storm:step=1,times=8",
            )
        ]
    )
    for b in _batches(acc, 10):
        float(step(b))
    assert acc.fleet.resizes_total == 0 and acc.fleet.grows_total == 0
    decisions = [e for e in acc.fleet.events if e.get("kind") == "autopilot"]
    suppressed = [d for d in decisions if d["suppressed"]]
    assert len(suppressed) >= 2
    assert not any(d["fired"] for d in decisions)
    assert any(d.get("reason", "").startswith("debounce") for d in suppressed)
    # the storm is visible in the recorded window values: the flap itself
    # is part of the forensic record
    assert any(0.0 in d.get("window_values", []) for d in suppressed)


def test_autopilot_sustained_skew_fires_shrink(tmp_path):
    """The soft-signal path end-to-end: a sustained straggler skew above
    the threshold (no host event) makes the autopilot shrink after the
    debounce window, respecting the cooldown afterwards."""
    if _num_devices() < 2:
        pytest.skip("needs >= 2 devices")
    acc, _, _, step = _make_step(
        [
            FleetKwargs(
                enabled=True, autopilot="skew_pct=100,window=2,cooldown=50",
                checkpoint_dir=str(tmp_path / "drain"),
            )
        ]
    )
    dp = dict(acc.mesh.shape)["dp"]
    acc.fleet.fleet_signal = lambda: {"kind": "fleet", "skew_pct": 400.0}
    batches = _batches(acc, 4)
    i = 0
    for b in batches:
        losses = float(step(batch_to_global_array(np.asarray(b), mesh=acc.mesh)))
        i += 1
    assert acc.fleet.resizes_total == 1  # fired once, then cooldown held
    assert dict(acc.mesh.shape)["dp"] == dp // 2
    decisions = [e for e in acc.fleet.events if e.get("kind") == "autopilot"]
    fired = [d for d in decisions if d["fired"]]
    assert len(fired) == 1 and fired[0]["signal"] == "skew_pct"
    assert fired[0]["value"] == 400.0 and fired[0]["threshold"] == 100.0
    # post-fire decisions (if any) were suppressed — the window refilling
    # after the fire cleared it, or the cooldown — never a second resize
    assert all(
        ("cooldown" in d.get("reason", "") or "debounce" in d.get("reason", ""))
        for d in decisions
        if d["suppressed"]
    )


def test_autopilot_shrink_at_floor_suppressed(tmp_path):
    """A hard host loss at the dp floor cannot shrink: the decision is
    recorded as suppressed (naming the floor) and the flag consumed —
    never a raise, never a record-spam loop."""
    acc, _, _, step = _make_step(
        [
            FleetKwargs(
                enabled=True, autopilot=True, min_dp=64,
                fault_plan="host_lost:step=0",
            )
        ]
    )
    for b in _batches(acc, 2):
        float(step(b))
    assert acc.fleet.resizes_total == 0
    decisions = [e for e in acc.fleet.events if e.get("kind") == "autopilot"]
    floor = [d for d in decisions if "floor" in d.get("reason", "")]
    assert len(floor) == 1  # consumed: no identical record on the next step
    assert not acc.fleet.should_resize


def test_autopilot_stale_record_counts_once(tmp_path):
    """Review-pinned: the latest retained skew record is re-READABLE every
    dispatch, but one measurement must count ONCE toward the debounce
    window — a single noisy record re-sampled until it 'held' would fire
    on exactly the transient the debounce exists to suppress."""
    if _num_devices() < 2:
        pytest.skip("needs >= 2 devices")
    acc, _, _, step = _make_step(
        [
            FleetKwargs(
                enabled=True, autopilot="skew_pct=100,window=2,cooldown=50",
                checkpoint_dir=str(tmp_path / "drain"),
            )
        ]
    )
    dp = dict(acc.mesh.shape)["dp"]
    # ONE stale measurement: at_step never advances
    acc.fleet.fleet_signal = lambda: {
        "kind": "fleet", "skew_pct": 400.0, "at_step": 7,
    }
    for b in _batches(acc, 4):
        float(step(batch_to_global_array(np.asarray(b), mesh=acc.mesh)))
    assert acc.fleet.resizes_total == 0, "a single stale measurement resized"
    assert dict(acc.mesh.shape)["dp"] == dp
    # fresh measurements (advancing marks) DO satisfy the window
    marks = iter(range(100, 200))
    acc.fleet.fleet_signal = lambda: {
        "kind": "fleet", "skew_pct": 400.0, "at_step": next(marks),
    }
    for b in _batches(acc, 3):
        float(step(batch_to_global_array(np.asarray(b), mesh=acc.mesh)))
    assert acc.fleet.resizes_total == 1


def test_autopilot_grow_rendezvous_abort_suppressed(monkeypatch):
    """Review-pinned: an aborted grow rendezvous (some rank cannot see the
    rejoined host yet) must NOT raise out of the dispatch hook — the loop
    keeps training, the decision lands suppressed, the sticky flag stays
    set, and the retry backs off instead of re-draining every dispatch."""
    import accelerate_tpu.fleet as fleet_mod
    from accelerate_tpu.fleet import grow as grow_mod

    acc, _, _, step = _make_step(
        [FleetKwargs(enabled=True, autopilot=True, fault_plan="host_gained:step=0")]
    )
    monkeypatch.setattr(
        fleet_mod, "grow_rendezvous", lambda *a, **k: None
    )
    # pretend a rejoined host doubled the pool, so the ceiling check lets
    # the grow reach the (failing) rendezvous
    dp_now = dict(acc.mesh.shape)["dp"]
    monkeypatch.setattr(grow_mod, "max_growable_dp", lambda *a, **k: dp_now * 2)
    drains = []
    monkeypatch.setattr(
        acc.fleet, "drain", lambda accelerator, output_dir=None: (
            drains.append(1), "/tmp/fake-ckpt")[-1],
    )
    for b in _batches(acc, 4):
        float(step(b))  # must not raise
    assert acc.fleet.grows_total == 0
    aborted = [
        e for e in acc.fleet.events
        if e.get("kind") == "autopilot" and "grow aborted" in e.get("reason", "")
    ]
    assert len(aborted) == 1  # backed off, not one abort per dispatch
    assert acc.fleet.should_grow  # flag survives for the retry
    assert len(drains) == 1


def test_autopilot_serving_signal_gated_on_multi_process(monkeypatch):
    """Review-pinned: serving records live on ONE rank's hub — sampling
    them on a multi-process run would fire a collective resize only that
    rank enters (deadlock).  The sampler must drop the serving half when
    the world is > 1."""
    from accelerate_tpu.fleet import autopilot as ap

    acc, _, _, _ = _make_step([FleetKwargs(enabled=True, autopilot=True)])
    acc.fleet.serving_signal = lambda: {
        "event": "step", "step": 3, "queue_depth": 50.0, "occupancy": 1.0,
    }
    sample = acc.fleet.autopilot._sample()
    assert sample["queue_depth"] == 50.0  # single-process: consumed
    monkeypatch.setattr(ap, "_multi_process", lambda: True)
    acc.fleet.autopilot._serving_mark = None
    sample = acc.fleet.autopilot._sample()
    assert "queue_depth" not in sample and "occupancy" not in sample


def test_evaluate_window_armed_grow_defers_shrink_fire():
    """Review-pinned: a fully-held lower-priority shrink must NOT fire
    while the higher-priority queue signal is armed but still debouncing —
    shrinking capacity exactly as serving demand arrives (and cooldown
    then blocking the grow) would invert the documented priority."""
    from accelerate_tpu.fleet import AutopilotPolicy, evaluate_window

    policy = AutopilotPolicy(queue_high=4.0, skew_pct=100.0, window=3)
    held_shrink = {"skew_pct": 150.0}
    both = {"skew_pct": 150.0, "queue_depth": 6.0}
    d = evaluate_window(policy, [held_shrink, held_shrink, both])
    assert not d["fired"] and d["suppressed"]
    assert d["signal"] == "queue_depth" and d["action"] == "grow"
    assert "deferring a held skew_pct shrink" in d["reason"]
    # once the queue clears (drops below its sustain floor, no longer
    # armed), the held shrink fires normally
    cleared = {"skew_pct": 150.0, "queue_depth": 0.0}
    d = evaluate_window(policy, [held_shrink, held_shrink, cleared])
    assert d["fired"] and d["action"] == "shrink" and d["signal"] == "skew_pct"


def test_autopilot_resolve_accepts_plain_ints():
    """Review-pinned: 0/1 must mean off/on like everywhere else in the
    knob surface — not a construction-time TypeError."""
    from accelerate_tpu.fleet import AutopilotPolicy

    assert AutopilotPolicy.resolve(1) == AutopilotPolicy()
    assert AutopilotPolicy.resolve(0) is None
    assert FleetKwargs(enabled=True, autopilot=1).autopilot_policy is not None
    assert FleetKwargs(enabled=True, autopilot=0).autopilot_policy is None


def test_merged_fleet_dump_dedups_periodic_skew_records():
    """Review-pinned: the periodic cadence retains the IDENTICAL skew
    record on every rank (the autopilot needs symmetric inputs) — the
    end-of-training merged dump must keep it once, not world-size times."""
    from accelerate_tpu.telemetry.aggregate import merge_rank_records

    periodic = {"kind": "fleet", "periodic": True, "at_step": 4, "skew_ms": 2.0}
    step = {"kind": "step", "step": 0, "total_ms": 5.0, "built": False}
    per_rank = [[dict(periodic), dict(step)], [dict(periodic), dict(step)]]
    merged = merge_rank_records(per_rank)
    periodics = [r for r in merged if r.get("kind") == "fleet" and r.get("periodic")]
    assert len(periodics) == 1 and periodics[0]["rank"] == 0
    # per-rank step records still merge from every rank, and the final
    # (non-periodic) skew record is appended as before
    assert sum(1 for r in merged if r.get("kind") == "step") == 2
    finals = [r for r in merged if r.get("kind") == "fleet" and not r.get("periodic")]
    assert len(finals) == 1
