"""T5 encoder-decoder tests: HF parity, cached decode, training smoke.

The family completes coverage of the reference's benchmark table (T0pp-11B,
reference benchmarks/big_model_inference/README.md:35).  Parity is asserted
numerically against transformers' CPU T5.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from accelerate_tpu.models import T5Config, T5ForConditionalGeneration


def _hf_pair(**overrides):
    from transformers import T5Config as HFConfig, T5ForConditionalGeneration as HFT5

    from accelerate_tpu.utils.torch_bridge import convert_torch_module

    torch.manual_seed(0)
    kw = dict(
        vocab_size=256, d_model=32, d_kv=8, d_ff=64, num_layers=2,
        num_heads=4, relative_attention_num_buckets=8, dropout_rate=0.0,
    )
    kw.update(overrides)
    hf = HFT5(HFConfig(**kw)).eval()
    return hf, convert_torch_module(hf)


@pytest.fixture(scope="module")
def hf_pair():
    return _hf_pair()


def test_forward_parity_vs_transformers(hf_pair):
    hf, ours = hf_pair
    ids = np.random.default_rng(0).integers(0, 256, (2, 12), dtype=np.int64)
    dec = np.random.default_rng(1).integers(0, 256, (2, 7), dtype=np.int64)
    with torch.no_grad():
        want = hf(
            input_ids=torch.tensor(ids), decoder_input_ids=torch.tensor(dec)
        ).logits.numpy()
    got = np.asarray(
        ours(jnp.asarray(ids, jnp.int32), decoder_input_ids=jnp.asarray(dec, jnp.int32))[
            "logits"
        ].data
    )
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_gated_gelu_untied_parity():
    """T5 v1.1 / T0pp geometry: gated-gelu FFN + untied head."""
    hf, ours = _hf_pair(feed_forward_proj="gated-gelu", tie_word_embeddings=False)
    ids = np.random.default_rng(0).integers(0, 256, (2, 10), dtype=np.int64)
    dec = np.random.default_rng(1).integers(0, 256, (2, 5), dtype=np.int64)
    with torch.no_grad():
        want = hf(
            input_ids=torch.tensor(ids), decoder_input_ids=torch.tensor(dec)
        ).logits.numpy()
    got = np.asarray(
        ours(jnp.asarray(ids, jnp.int32), decoder_input_ids=jnp.asarray(dec, jnp.int32))[
            "logits"
        ].data
    )
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_sampled_decode_matches_full_forward(hf_pair):
    """Cached decode vs per-step full forward, on a DIVERSE token sequence
    (temperature sampling with a fixed key — greedy on a random-init tiny
    model collapses to one token, which would leave the cache untested)."""
    _, ours = hf_pair
    ids = np.random.default_rng(0).integers(0, 256, (2, 12), dtype=np.int32)
    rng = jax.random.PRNGKey(7)
    got = np.asarray(ours.generate(ids, max_new_tokens=6, temperature=1.0, rng=rng))

    # replicate the engine's sampling loop with full forwards (no cache)
    cur = np.zeros((2, 1), dtype=np.int32)  # decoder_start_token_id
    r = jax.random.PRNGKey(7)
    for _ in range(6):
        logits = ours(
            jnp.asarray(ids, jnp.int32), decoder_input_ids=jnp.asarray(cur)
        )["logits"].data
        r, key = jax.random.split(r)
        nxt = np.asarray(
            jax.random.categorical(key, logits[:, -1] / 1.0, axis=-1)
        ).astype(np.int32)
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, cur[:, 1:])


def test_train_step_with_labels(hf_pair):
    import accelerate_tpu.nn as nn
    import accelerate_tpu.optim as optim
    from accelerate_tpu import Accelerator
    from accelerate_tpu.data_loader import batch_to_global_array

    Accelerator._reset_state()
    nn.manual_seed(0)
    acc = Accelerator(mixed_precision="no")
    model = T5ForConditionalGeneration(T5Config.tiny())
    opt = optim.AdamW(model.parameters(), lr=1e-3)
    model, opt = acc.prepare(model, opt)

    def step_fn(src, tgt):
        opt.zero_grad()
        out = model(src, labels=tgt)
        acc.backward(out["loss"])
        opt.step()
        return out["loss"]

    step = acc.compile_step(step_fn)
    rng = np.random.default_rng(0)
    src = batch_to_global_array(
        jnp.asarray(rng.integers(0, 256, (8, 16)), jnp.int32), mesh=acc.mesh
    )
    tgt = batch_to_global_array(
        jnp.asarray(rng.integers(0, 256, (8, 8)), jnp.int32), mesh=acc.mesh
    )
    losses = [float(step(src, tgt)) for _ in range(3)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], losses


def test_from_pretrained_roundtrip(tmp_path, hf_pair):
    hf, ours = hf_pair
    hf.save_pretrained(tmp_path / "t5")
    from accelerate_tpu.utils.hf import from_pretrained

    loaded = from_pretrained(str(tmp_path / "t5"))
    ids = np.random.default_rng(2).integers(0, 256, (1, 10), dtype=np.int32)
    dec = np.random.default_rng(3).integers(0, 256, (1, 4), dtype=np.int32)
    a = np.asarray(ours(jnp.asarray(ids), decoder_input_ids=jnp.asarray(dec))["logits"].data)
    b = np.asarray(loaded(jnp.asarray(ids), decoder_input_ids=jnp.asarray(dec))["logits"].data)
    np.testing.assert_allclose(a, b, atol=1e-6)


def test_unsupported_ffn_rejected():
    from accelerate_tpu.utils.hf import t5_config_from_hf

    with pytest.raises(NotImplementedError, match="feed_forward_proj"):
        t5_config_from_hf({"feed_forward_proj": "gated-silu"})


def test_int8_decode_exact_on_grid():
    """T5 quantized decode must match full-precision decode token for token
    when weights sit on the int8 quantization grid (same engine contract as
    tests/test_quantized_decode.py for the causal families; T0pp-geometry
    decoding is the reference's big-model-inference benchmark)."""
    from accelerate_tpu.models import T5Config, T5ForConditionalGeneration
    import accelerate_tpu.nn as nn

    nn.manual_seed(0)
    model = T5ForConditionalGeneration(T5Config.tiny())
    for name, p in model.named_parameters():
        w = np.asarray(p.data)
        if w.ndim != 2:
            continue
        amax = np.maximum(np.abs(w).max(axis=-1, keepdims=True), 1e-12)
        scale = (amax / 127.0).astype(np.float32)
        p.data = jnp.asarray(np.round(w / scale) * scale)
    ids = np.random.default_rng(0).integers(
        0, model.config.vocab_size, (2, 9)
    ).astype(np.int32)
    rng = jax.random.PRNGKey(3)
    full = np.asarray(model.generate(ids, max_new_tokens=5, temperature=1.0, rng=rng))
    quant = np.asarray(
        model.generate(ids, max_new_tokens=5, temperature=1.0, rng=rng,
                       quantize_weights=8)
    )
    np.testing.assert_array_equal(quant, full)
    # both modes cached side by side; int8 stacks really are int8
    _, by_mode = model._generation_param_cache
    assert set(by_mode) == {0, 8}
    _, (plain, qd, sd) = by_mode[8]
    assert qd and all(v.dtype == jnp.int8 for v in qd.values())


def test_int4_decode_runs():
    from accelerate_tpu.models import T5Config, T5ForConditionalGeneration
    import accelerate_tpu.nn as nn

    nn.manual_seed(0)
    model = T5ForConditionalGeneration(T5Config.tiny())
    ids = np.zeros((1, 6), np.int32)
    out = np.asarray(model.generate(ids, max_new_tokens=3, quantize_weights=4))
    assert out.shape == (1, 3)
    with pytest.raises(ValueError, match="quantize_weights"):
        model.generate(ids, max_new_tokens=2, quantize_weights=2)
