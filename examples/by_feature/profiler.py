"""Feature: profiling a training window with ``accelerator.profile``.

Counterpart of /root/reference/examples/by_feature/profiler.py — the torch
profiler context becomes ``jax.profiler`` tracing: the emitted trace directory
opens in TensorBoard/XProf and shows per-op device timelines.  Lines marked
`# New Code #` are what this feature adds to nlp_example.py.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from nlp_example import get_dataloaders  # noqa: E402

import accelerate_tpu.nn as nn  # noqa: E402
import accelerate_tpu.optim as optim  # noqa: E402
from accelerate_tpu import Accelerator  # noqa: E402
from accelerate_tpu.models import BertConfig, BertForSequenceClassification  # noqa: E402
from accelerate_tpu.utils.dataclasses import ProfileKwargs  # noqa: E402


def training_function(args):
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    nn.manual_seed(args.seed)
    train_dl, val_dl, vocab = get_dataloaders(accelerator, args.batch_size, args.seed)

    cfg = BertConfig.small() if args.small else BertConfig.base()
    cfg.vocab_size = max(cfg.vocab_size, vocab)
    model = BertForSequenceClassification(cfg)
    optimizer = optim.AdamW(model.parameters(), lr=args.lr)
    scheduler = optim.get_linear_schedule_with_warmup(
        optimizer, 100, len(train_dl) * args.num_epochs * accelerator.num_devices
    )
    model, optimizer, train_dl, val_dl, scheduler = accelerator.prepare(
        model, optimizer, train_dl, val_dl, scheduler
    )

    model.train()
    # New Code #
    # trace the first few steps; the trace lands in args.profile_dir and is
    # viewable in TensorBoard's profile tab (XProf)
    with accelerator.profile(ProfileKwargs(output_trace_dir=args.profile_dir)):
        for step, batch in enumerate(train_dl):
            if step >= args.profile_steps:
                break
            optimizer.zero_grad()
            out = model(
                batch["input_ids"],
                attention_mask=batch["attention_mask"],
                token_type_ids=batch["token_type_ids"],
                labels=batch["labels"],
            )
            accelerator.backward(out["loss"])
            optimizer.step()
            scheduler.step()
    accelerator.print(f"profile written to {args.profile_dir}")
    return model


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--mixed_precision", type=str, default="bf16", choices=["no", "fp16", "bf16"])
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--lr", type=float, default=2e-5)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--small", action="store_true")
    # New Code #
    parser.add_argument("--profile_dir", type=str, default="profile_trace")
    parser.add_argument("--profile_steps", type=int, default=5)
    args = parser.parse_args()
    training_function(args)


if __name__ == "__main__":
    main()
