"""Accelerator — the user-facing orchestration core (L4).

Counterpart of ``/root/reference/src/accelerate/accelerator.py`` (3769 LoC).
The API shape survives — ``prepare`` / ``backward`` / ``accumulate`` /
``clip_grad_norm_`` / ``gather_for_metrics`` / ``save_state`` — but the
execution model inverts (SURVEY.md §7): instead of multiplexing over ten
process backends and wrapping mutable torch objects, there is one SPMD
program on a mesh.  ``prepare`` lays parameters and batches onto the mesh;
the imperative loop runs either

* **eagerly** (tape autodiff, op-by-op dispatch) — debugging, parity with the
  reference's "unmodified loop" promise; or
* **captured** (``accelerator.compile_step``): the loop body traces once into
  a single jitted, donated, fully-fused XLA program — forward, backward,
  optimizer update and (sharded) collectives in one launch.  This is the
  performance path that makes TPU throughput competitive.
"""

from __future__ import annotations

import contextlib
import math
import os
from functools import partial
from typing import Any, Callable, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from .data_loader import DataLoaderShard, prepare_data_loader, skip_first_batches
from .logging import get_logger
from .nn import random as nn_random
from .nn.module import Module
from .nn.tape import Tensor
from .optim import LRScheduler, Optimizer
from .optimizer import AcceleratedOptimizer, DynamicLossScaler
from .scheduler import AcceleratedScheduler
from .state import AcceleratorState, GradientState, PartialState
from .utils import operations as ops
from .utils.dataclasses import (
    DataLoaderConfiguration,
    DataParallelPlugin,
    FullyShardedDataParallelPlugin,
    GradientAccumulationPlugin,
    GradScalerKwargs,
    InitProcessGroupKwargs,
    LoggerType,
    ParallelismConfig,
    PrecisionType,
    ProfileKwargs,
    ProjectConfiguration,
    SequenceParallelPlugin,
    TensorParallelPlugin,
)

logger = get_logger(__name__)


class Accelerator:
    def __init__(
        self,
        device_placement: bool = True,
        split_batches: bool = False,
        mixed_precision: Optional[str] = None,
        gradient_accumulation_steps: int = 1,
        cpu: bool = False,
        dataloader_config: Optional[DataLoaderConfiguration] = None,
        fsdp_plugin: Optional[FullyShardedDataParallelPlugin] = None,
        tp_plugin: Optional[TensorParallelPlugin] = None,
        sp_plugin: Optional[SequenceParallelPlugin] = None,
        dp_plugin: Optional[DataParallelPlugin] = None,
        pp_plugin=None,
        parallelism_config: Optional[ParallelismConfig] = None,
        rng_types: Optional[list] = None,
        log_with: Optional[Union[str, list]] = None,
        project_dir: Optional[str] = None,
        project_config: Optional[ProjectConfiguration] = None,
        gradient_accumulation_plugin: Optional[GradientAccumulationPlugin] = None,
        step_scheduler_with_optimizer: bool = True,
        kwargs_handlers: Optional[list] = None,
        dynamo_backend: Optional[str] = None,  # parity; XLA is the only compiler here
    ):
        self.project_configuration = project_config or ProjectConfiguration(
            project_dir=project_dir
        )
        if project_dir is not None and self.project_configuration.project_dir is None:
            self.project_configuration.set_directories(project_dir)

        # kwargs handlers
        self.scaler_handler = None
        self.init_handler = None
        self.profile_handler = None
        self.autocast_handler = None
        self.fp8_recipe_handler = None
        self.ddp_handler = None
        # normalized "fp16"/"bf16"/"powersgd"/"batched_powersgd"/None, set below
        self._comm_hook = None
        self._comm_wrapper = None  # "fp16"/"bf16" factor rounding for powersgd
        self._powersgd_state = None  # per-model {q, err} arrays, capture-threaded
        self.telemetry_handler = None
        self.resilience_handler = None
        self.compression_handler = None
        self.aot_cache_handler = None
        self.fleet_handler = None
        self.kernels_handler = None
        from .utils.dataclasses import FP8RecipeKwargs

        from .utils.dataclasses import (
            AutocastKwargs,
            CompilationCacheKwargs,
            CompressionKwargs,
            DistributedDataParallelKwargs,
            FleetKwargs,
            KernelKwargs,
            ResilienceKwargs,
            TelemetryKwargs,
        )

        for handler in kwargs_handlers or []:
            if isinstance(handler, TelemetryKwargs):
                self.telemetry_handler = handler
            elif isinstance(handler, CompressionKwargs):
                self.compression_handler = handler
            elif isinstance(handler, CompilationCacheKwargs):
                self.aot_cache_handler = handler
            elif isinstance(handler, FleetKwargs):
                self.fleet_handler = handler
            elif isinstance(handler, KernelKwargs):
                self.kernels_handler = handler
            elif isinstance(handler, ResilienceKwargs):
                self.resilience_handler = handler
            elif isinstance(handler, AutocastKwargs):
                self.autocast_handler = handler
            elif isinstance(handler, GradScalerKwargs):
                self.scaler_handler = handler
            elif isinstance(handler, InitProcessGroupKwargs):
                self.init_handler = handler
            elif isinstance(handler, ProfileKwargs):
                self.profile_handler = handler
            elif isinstance(handler, FP8RecipeKwargs):
                self.fp8_recipe_handler = handler
            elif isinstance(handler, DistributedDataParallelKwargs):
                self.ddp_handler = handler
                if handler.comm_hook is not None:
                    hook = str(handler.comm_hook).lower()
                    # accept both the bare value and its enum stringification
                    # (DDPCommunicationHookType.NO prints as "ddpcommunicationhooktype.no")
                    hook = hook.rsplit(".", 1)[-1]
                    if hook in ("no", "none"):
                        # the reference's NO hook is a valid no-op default —
                        # run uncompressed rather than failing construction
                        hook = None
                    elif hook in ("power_sgd", "batched_power_sgd"):
                        hook = hook.replace("_sgd", "sgd")  # normalize spelling
                    elif hook not in ("fp16", "bf16", "powersgd", "batched_powersgd"):
                        # fail at configuration time, not mid-first-train-step
                        raise ValueError(
                            f"unsupported comm_hook {handler.comm_hook!r}; use "
                            "'fp16', 'bf16', 'powersgd' or 'batched_powersgd'"
                        )
                    # normalized copy — the caller-owned handler stays untouched
                    self._comm_hook = hook
                if getattr(handler, "comm_wrapper", None) is not None:
                    wrapper = str(handler.comm_wrapper).lower().rsplit(".", 1)[-1]
                    if wrapper in ("no", "none"):
                        wrapper = None
                    elif wrapper not in ("fp16", "bf16"):
                        raise ValueError(
                            f"unsupported comm_wrapper {handler.comm_wrapper!r}; "
                            "use 'fp16' or 'bf16'"
                        )
                    self._comm_wrapper = wrapper

        # dp-axis collective compression (docs/compression.md): ONE policy
        # surface for the quantized ZeRO-1 collectives (int8/fp8) and the
        # PowerSGD comm hook — CompressionKwargs/$ACCELERATE_COMPRESSION
        # selects it, and the legacy ddp comm_hook="powersgd" spelling
        # resolves to the same PowerSGDCompression object
        from .parallel.compress import powersgd_from_ddp, resolve_policy

        self._compression = resolve_policy(
            self.compression_handler, ddp_handler=self.ddp_handler
        )
        # Pallas hot-path kernels (docs/kernels.md): one default-off policy
        # for the collective-matmul ZeRO-1 gather, the fused quantize+RS
        # wire, and serving's paged-attention decode — resolved here so the
        # optimizer relayout, the serving engine, and the AOT-cache
        # fingerprint all read ONE armed set
        from .native.kernels import _set_active_kernels, resolve_kernel_policy

        self.kernels = resolve_kernel_policy(self.kernels_handler)
        _set_active_kernels(self.kernels if self.kernels.enabled else None)
        # the sync-boundary hook policy: the compression policy itself when
        # it IS a hook (powersgd), else the legacy ddp spelling (which also
        # lets powersgd compose with an int8/fp8 collective policy)
        self._hook_policy = (
            self._compression
            if self._compression.hook_name is not None
            else powersgd_from_ddp(self.ddp_handler)
        )
        if self._hook_policy is not None:
            if self._comm_hook in ("fp16", "bf16"):
                raise ValueError(
                    f"comm_hook={self._comm_hook!r} and compression policy "
                    f"{self._hook_policy.name!r} both claim the gradient sync "
                    "boundary; pick one (the fp16/bf16 cast is the PowerSGD "
                    "comm_wrapper option, not a separate hook)"
                )
            self._comm_hook = self._hook_policy.hook_name
            if self._hook_policy.wrapper_dtype is None and self._comm_wrapper:
                # powersgd selected via CompressionKwargs alongside a legacy
                # ddp comm_wrapper: honor the wrapper rather than silently
                # dropping the requested factor rounding
                from .parallel.compress import _wrapper_dtype

                self._hook_policy.wrapper_dtype = _wrapper_dtype(self._comm_wrapper)

        if fsdp_plugin is None and os.environ.get("ACCELERATE_USE_FSDP", "false").lower() in ("1", "true"):
            fsdp_plugin = FullyShardedDataParallelPlugin()

        self.state = AcceleratorState(
            mixed_precision=mixed_precision,
            cpu=cpu,
            parallelism_config=parallelism_config,
            fsdp_plugin=fsdp_plugin,
            tp_plugin=tp_plugin,
            sp_plugin=sp_plugin,
            dp_plugin=dp_plugin,
            pp_plugin=pp_plugin,
            _from_accelerator=True,
            **(
                {"init_process_group_kwargs": self.init_handler}
                if self.init_handler
                else {}
            ),
        )

        if gradient_accumulation_plugin is None:
            ga_steps = int(
                os.environ.get(
                    "ACCELERATE_GRADIENT_ACCUMULATION_STEPS", gradient_accumulation_steps
                )
            )
            gradient_accumulation_plugin = GradientAccumulationPlugin(num_steps=ga_steps)
        self.gradient_state = GradientState(gradient_accumulation_plugin)

        self.device_placement = device_placement
        self.dataloader_config = dataloader_config or DataLoaderConfiguration(
            split_batches=split_batches
        )
        self.step_scheduler_with_optimizer = step_scheduler_with_optimizer
        self.rng_types = rng_types or ["jax"]

        # fp16 needs dynamic loss scaling; bf16 (the TPU default) does not
        self.scaler = None
        if self.state.mixed_precision == "fp16":
            self.scaler = DynamicLossScaler(self.scaler_handler)

        self._models: list[Module] = []
        self._converted_models: list[Module] = []  # torch→native conversions
        self._converted_optimizers: list[tuple] = []  # (torch_opt, native_opt)
        self._optimizers: list[AcceleratedOptimizer] = []
        self._schedulers: list[AcceleratedScheduler] = []
        self._dataloaders: list[DataLoaderShard] = []
        self._custom_objects: list[Any] = []
        from collections import OrderedDict

        self._save_state_pre_hooks: "OrderedDict[int, Callable]" = OrderedDict()
        self._load_state_pre_hooks: "OrderedDict[int, Callable]" = OrderedDict()

        self.step = 0
        self.flag_tensor = None
        self._capture_cache: dict = {}
        self._capture_ctx: Optional[dict] = None
        # (param, sharding, dp-axis) triples for the ZeRO-2 accumulated-grad
        # layout; empty (one falsy check in backward) unless prepare() armed
        # it.  _zero2_stochastic arms the kernel policy's narrow wire on top
        self._zero2_grads: list = []
        self._zero2_stochastic = False

        # trackers
        from .tracking import filter_trackers

        self.log_with = filter_trackers(log_with, self.logging_dir)
        self.trackers: list = []

        # runtime telemetry (docs/telemetry.md): always constructed (a few
        # empty deques), OFF unless TelemetryKwargs/$ACCELERATE_TELEMETRY
        # turns it on — compile_step pins the enabled instance so the
        # captured path pays one None-check when off
        from .telemetry import Telemetry

        self.telemetry = Telemetry(self.telemetry_handler)

        # resilience (docs/resilience.md): always constructed, OFF unless
        # ResilienceKwargs/$ACCELERATE_RESILIENCE turns it on — compile_step
        # pins the enabled instance so the captured path pays one None-check
        # when off; enabled, it installs the preemption guard and arms the
        # dispatch retrier
        from .resilience import Resilience

        self.resilience = Resilience(self.resilience_handler, telemetry=self.telemetry)

        # ONE resolved ParallelPlan (docs/parallel_plan.md): mesh axes, ZeRO
        # modes, compression, pipeline stage layout — resolved here, once,
        # from ParallelismConfig/plugins/env, published on the Borg state
        # (parallel.plan.current_plan) and re-resolved only by a fleet
        # resize.  Every consumer below (optimizer relayout, compression,
        # capture, AOT fingerprint, fleet, the pipelined models) reads THIS
        # object instead of rediscovering its own axis.
        self._resolve_plan()

        # persistent AOT executable cache (docs/aot_cache.md): always
        # constructed, OFF unless CompilationCacheKwargs/$ACCELERATE_AOT_CACHE
        # names a cache dir — compile_step pins the enabled instance so the
        # captured build path pays one None-check when off; enabled, builds
        # deserialize stored executables instead of tracing+compiling, the
        # hit/miss stream lands as kind="aot_cache" telemetry, and the live
        # counters serve as atpu_aot_cache_* on the metrics endpoint
        from .native.aot_cache import AOTCompilationCache, _set_active

        self.aot_cache = AOTCompilationCache(self.aot_cache_handler)
        # pin the run's topology into the ONE canonical fingerprint now —
        # a restore-path prefetch() can run before the first captured build,
        # and both must hash the same mesh/compression or the prefetch pins
        # a fingerprint no stored entry was keyed under
        self.aot_cache.set_context(
            mesh=self.state.mesh,
            compression=self._compression.name,
            # armed set + lowering mode: a forced interpret flip must be a
            # loud miss too, not a replay of the other mode's executable
            kernels=self.kernels.cache_tag(),
            # the resolved plan digest: a schedule/virtual-stage/ZeRO flip
            # compiles a different program, so it must be a loud miss
            # NAMING the plan field (docs/parallel_plan.md §AOT coupling)
            plan=self.plan.describe(),
        )
        self.aot_cache.attach_telemetry(self.telemetry)
        _set_active(self.aot_cache if self.aot_cache.enabled else None)

        # elastic fleet runtime (docs/elastic.md): always constructed, OFF
        # unless FleetKwargs/$ACCELERATE_FLEET turns it on — compile_step
        # pins the enabled instance so the captured path pays one None-check
        # when off; enabled, it composes the subsystems above into
        # coordinated multi-host rollback (the resilience retrier consults
        # it), host-loss-driven dp resize, and the periodic mid-run fleet
        # aggregation signal
        from .fleet import Fleet

        self.fleet = Fleet(
            self.fleet_handler, telemetry=self.telemetry, resilience=self.resilience
        )
        self.resilience.fleet = self.fleet if self.fleet.enabled else None
        # bumped by fleet.resize() when the mesh changes; fleet-armed
        # CapturedSteps drop their compiled variants when it moves
        self._mesh_generation = 0

        # seed the nn RNG only when explicitly requested or still unseeded —
        # never clobber a user's earlier manual_seed
        if "ACCELERATE_SEED" in os.environ:
            nn_random.manual_seed(int(os.environ["ACCELERATE_SEED"]))
        elif nn_random.default_rng._base_key is None:
            nn_random.manual_seed(nn_random.default_rng._seed)

    # ----------------------------------------------------------------- plan
    def _resolve_plan(self, bump: bool = False):
        """Resolve (or, after a fleet resize, RE-resolve) the run's ONE
        :class:`~accelerate_tpu.parallel.plan.ParallelPlan` from the live
        state and publish it on the Borg state for :func:`current_plan`.

        ``bump=True`` (the resize path) advances the plan generation and
        the mesh generation together, so fleet-armed CapturedSteps drop
        every compiled variant bound to the old layout before their next
        lookup, and syncs the parallelism config's dp entry from the live
        mesh — the single place it may move — so later mesh rebuilds and
        ``zero1_enabled`` reads agree with what the plan says.  At
        construction the config is left untouched: it just BUILT the mesh,
        and pinning the auto-resolved dp onto it would make a second
        Accelerator with an equivalent auto config a conflicting re-init
        on the Borg state.
        """
        from .parallel.plan import DP_AXIS, ParallelPlan

        if bump:
            self.state.parallelism_config.dp_size = dict(
                self.state.mesh.shape
            ).get(DP_AXIS, 1)
        generation = (self.plan.generation + 1) if (bump and hasattr(self, "plan")) else 0
        self.plan = ParallelPlan.resolve(
            self.state, compression=self._compression.name, generation=generation
        )
        self.state.plan = self.plan
        if bump:
            self._mesh_generation = getattr(self, "_mesh_generation", 0) + 1
        return self.plan

    # ------------------------------------------------------------------ props
    @property
    def distributed_type(self):
        return self.state.distributed_type

    @property
    def mesh(self):
        return self.state.mesh

    @property
    def num_processes(self) -> int:
        return self.state.num_processes

    @property
    def process_index(self) -> int:
        return self.state.process_index

    @property
    def local_process_index(self) -> int:
        return self.state.local_process_index

    @property
    def device(self):
        return self.state.device

    @property
    def is_main_process(self) -> bool:
        return self.state.is_main_process

    @property
    def is_local_main_process(self) -> bool:
        return self.state.is_local_main_process

    @property
    def is_last_process(self) -> bool:
        return self.state.is_last_process

    @property
    def mixed_precision(self) -> str:
        return self.state.mixed_precision

    @property
    def num_devices(self) -> int:
        return self.state.num_devices

    @property
    def sync_gradients(self) -> bool:
        return self.gradient_state.sync_gradients

    @property
    def gradient_accumulation_steps(self) -> int:
        return self.gradient_state.num_steps

    @gradient_accumulation_steps.setter
    def gradient_accumulation_steps(self, value: int):
        self.gradient_state.plugin_kwargs.update({"num_steps": value})

    @property
    def project_dir(self):
        return self.project_configuration.project_dir

    @property
    def logging_dir(self):
        return self.project_configuration.logging_dir

    @property
    def use_distributed(self) -> bool:
        return self.state.use_distributed

    @property
    def compute_dtype(self):
        # fp8 keeps bf16 activations/params; only the matmuls drop to fp8
        return jnp.bfloat16 if self.state.mixed_precision in ("bf16", "fp8") else (
            jnp.float16 if self.state.mixed_precision == "fp16" else jnp.float32
        )

    @property
    def save_iteration(self) -> int:
        """Next automatic checkpoint index (reference accelerator.py:680)."""
        return self.project_configuration.iteration

    @property
    def optimizer_step_was_skipped(self) -> bool:
        """True when the last update was dropped (fp16 overflow) — the LR
        should then not advance (reference accelerator.py:3674)."""
        return any(opt.step_was_skipped for opt in self._optimizers)

    @property
    def deepspeed_plugin(self):
        """Always ``None``: there is no DeepSpeed engine on TPU.  DeepSpeed
        configs are INGESTED instead — ``utils/deepspeed_compat.py`` maps
        ZeRO stages/offload onto fsdp mesh layouts (reference
        accelerator.py:603 returns the active plugin)."""
        return None

    # deprecated-in-reference dataloader passthroughs, kept for drop-in
    # parity (reference reads them off dataloader_config the same way)
    @property
    def split_batches(self) -> bool:
        return self.dataloader_config.split_batches

    @property
    def dispatch_batches(self):
        return self.dataloader_config.dispatch_batches

    @property
    def even_batches(self) -> bool:
        return self.dataloader_config.even_batches

    @property
    def use_seedable_sampler(self) -> bool:
        return self.dataloader_config.use_seedable_sampler

    @property
    def non_blocking(self) -> bool:
        return self.dataloader_config.non_blocking

    @property
    def use_stateful_dataloader(self) -> bool:
        return self.dataloader_config.use_stateful_dataloader

    # ------------------------------------------------------------- process ctl
    def wait_for_everyone(self) -> None:
        PartialState().wait_for_everyone()

    def print(self, *args, **kwargs) -> None:
        PartialState().print(*args, **kwargs)

    def on_main_process(self, function):
        return PartialState().on_main_process(function)

    def on_local_main_process(self, function):
        return PartialState().on_local_main_process(function)

    def on_process(self, function=None, process_index=None):
        return PartialState().on_process(function, process_index=process_index)

    def on_last_process(self, function):
        return PartialState().on_last_process(function)

    def on_local_process(self, function=None, local_process_index=None):
        """Run only on the given LOCAL process index (reference
        accelerator.py:908)."""
        return PartialState().on_local_process(
            function, local_process_index=local_process_index
        )

    def trigger_sync_in_backward(self, model=None) -> None:
        """Force the NEXT backward/step to be a sync step after forwards ran
        under ``no_sync`` (reference accelerator.py:1043).  Under SPMD this
        flips the accumulation gate: ``optimizer.step`` will apply."""
        self.gradient_state._set_sync_gradients(True)

    @contextlib.contextmanager
    def main_process_first(self):
        with PartialState().main_process_first():
            yield

    @contextlib.contextmanager
    def local_main_process_first(self):
        with PartialState().local_main_process_first():
            yield

    def split_between_processes(self, inputs, apply_padding: bool = False):
        return PartialState().split_between_processes(inputs, apply_padding)

    # --------------------------------------------------------------- prepare
    def prepare(self, *args, device_placement=None):
        """Re-bind user objects onto the mesh (reference accelerator.py:1283).

        Models: params sharded per plugin rules (replicated for pure DP, fsdp
        axis for ZeRO, tp axis per plan) + precision policy. Optimizers:
        wrapped with accumulation/scaler semantics. DataLoaders: rebuilt as
        SPMD global-batch loaders. Schedulers: wrapped to step per real
        optimizer step.
        """
        result = []
        for obj in args:
            result.append(self._prepare_one(obj))
        # commit the plan's interleaved layer layout BEFORE the optimizer
        # relayout below: masters/moments snapshot the params, so ZeRO-1
        # state is born permuted and updates stay permuted-in-place — the
        # captured step never sees a permutation (docs/parallel_plan.md
        # §layout contract)
        self._commit_layer_layout()
        # re-lay-out optimizer state (Adam moments, fp32 masters) onto the
        # params' mesh shardings: tx.init ran before prepare() sharded the
        # params, so without this the opt state stays on the old layout and
        # ZeRO saves no memory (reference FSDP shards optimizer state too,
        # accelerator.py:1555-1679)
        offload_opt = bool(
            self.state.fsdp_plugin is not None
            and getattr(self.state.fsdp_plugin, "offload_optimizer", False)
        )
        # training-time parameter offload (reference FSDP CPUOffload /
        # DeepSpeed offload_param): params pinned to host between steps,
        # staged back by a forward hook (traced h2d under compile_step)
        offload_params = bool(
            self.state.fsdp_plugin is not None
            and getattr(self.state.fsdp_plugin, "cpu_offload", False)
        )
        # ZeRO-1 (arXiv:2004.13336): with a dp axis and no fsdp owner the
        # state relayout below additionally shards masters + moments over dp,
        # turning the captured update into reduce-scatter → 1/dp-shard-local
        # AdamW → all-gather with no eager-mode change for users.  The armed
        # modes come off the resolved ParallelPlan (docs/parallel_plan.md) —
        # the optimizer never re-derives its own dp axis.
        zero1_mesh = self.state.mesh if self.plan.zero1 else None
        for opt in self._optimizers:
            opt.optimizer.relayout_for_sharded_params(
                offload_to_host=offload_opt,
                offload_params=offload_params,
                zero1_mesh=zero1_mesh,
                # quantized dp collectives + ZeRO-2 grad-accumulation layout
                # (docs/compression.md); both no-ops unless armed
                compression=self._compression,
                zero2=self.plan.zero2,
                # Pallas hot-path kernels (docs/kernels.md): routes the
                # ZeRO-1 writeback gather through the chunked ring and the
                # quantized RS through the fused kernel; None-check off-path
                kernels=self.kernels,
                # per-param state shardings are the plan's to decide
                # (ParallelPlan.state_spec delegates the ZeRO-1 layout rule)
                plan=self.plan,
            )
        if offload_params:
            from .hooks import ParamOffloadHook, add_hook_to_module

            for model in self._models:
                if not getattr(model, "_atpu_param_offload", False):
                    add_hook_to_module(model, ParamOffloadHook(), append=True)
                    model._atpu_param_offload = True
        self._ensure_powersgd_state()
        self._refresh_zero2_grads()
        self._record_collectives()
        self._record_kernels()
        return result[0] if len(result) == 1 else tuple(result)

    # ------------------------------------------------- layer layout contract
    def _stacked_layer_params(self, model):
        """``(name, param)`` pairs whose leading axis is the plan's stacked
        layer axis — identified by the pp-sharded leading dim (the tp_plan
        rule that makes a stack a stack) or by an existing commit marker."""
        from .parallel.plan import PP_AXIS

        out = []
        seen = set()
        for name, p in model.named_parameters():
            if id(p) in seen:
                continue  # tied params appear once
            seen.add(id(p))
            if getattr(p, "_layer_layout_committed", False):
                out.append((name, p))
                continue
            data = getattr(p, "data", None)
            s = getattr(data, "sharding", None)
            spec = getattr(s, "spec", None)
            if not spec:
                continue
            first = spec[0] if len(spec) else None
            names = first if isinstance(first, tuple) else (first,)
            if PP_AXIS in names:
                out.append((name, p))
        return out

    def _commit_layer_layout(self) -> None:
        """Physically reorder every stacked layer param into the plan's
        ``StagePlan.layer_order`` ONCE — the layout of record under
        ``layer_layout == "committed"`` (docs/parallel_plan.md §layout
        contract).  After this the captured 1F1B step consumes the stack in
        place and moves zero permutation bytes; each param carries a
        ``_layer_layout_committed`` marker (the runtime source of truth the
        model's forward keys on, and the idempotency guard a re-prepare or
        fleet resize relies on)."""
        stage = getattr(self.plan, "stage", None)
        if (
            stage is None
            or stage.virtual <= 1
            or self.plan.layer_layout != "committed"
        ):
            return
        from .parallel.pipeline import apply_layer_order

        for model in self._models:
            for _, p in self._stacked_layer_params(model):
                if getattr(p, "_layer_layout_committed", False):
                    continue
                data = p.data
                order = stage.layer_order(int(data.shape[0]))
                p.data = jax.device_put(
                    apply_layer_order(data, order), data.sharding
                )
                p._layer_layout_committed = True

    def _layer_layout_record(self) -> Optional[dict]:
        """Checkpoint meta descriptor of the live stacked-layer layout —
        ``None`` when plain (saved checkpoints then match every pre-layout
        reader bitwise)."""
        stage = getattr(self.plan, "stage", None)
        if (
            stage is None
            or stage.virtual <= 1
            or self.plan.layer_layout != "committed"
        ):
            return None
        if not any(
            getattr(p, "_layer_layout_committed", False)
            for m in self._models
            for _, p in self._stacked_layer_params(m)
        ):
            return None
        return {
            "layer_layout": {
                "layout": "committed",
                "num_stages": stage.num_stages,
                "virtual": stage.virtual,
            }
        }

    def _retarget_layer_layout(self, ckpt_rec: Optional[dict]) -> None:
        """Transpose just-restored stacked arrays from the CHECKPOINT's
        layer layout into the LIVE one (either direction; no-op when they
        match — including the pre-layout-checkpoint → plain-run case, which
        stays bitwise).  Covers model params and, through
        ``Optimizer.relayout_layer_axis``, the fp32 masters and moments —
        bitwise after transposition."""
        stage = getattr(self.plan, "stage", None)
        live_committed = any(
            getattr(p, "_layer_layout_committed", False)
            for m in self._models
            for _, p in self._stacked_layer_params(m)
        )
        ckpt_committed = bool(ckpt_rec) and ckpt_rec.get("layout") == "committed"
        if not live_committed and not ckpt_committed:
            return
        from .parallel.pipeline import apply_layer_order
        from .parallel.plan import _layer_orders

        def composed(num_layers: int):
            # committed array C satisfies C[i] = plain[order[i]]; the ckpt→
            # live transposition is one take by inv_ckpt ∘ order_live
            ident = tuple(range(num_layers))
            inv0 = (
                _layer_orders(
                    int(ckpt_rec["num_stages"]), int(ckpt_rec["virtual"]),
                    num_layers,
                )[1]
                if ckpt_committed
                else ident
            )
            order1 = (
                stage.layer_order(num_layers)
                if live_committed and stage is not None
                else ident
            )
            perm = tuple(inv0[j] for j in order1)
            return None if perm == ident else perm

        transposed: set[int] = set()
        for model in self._models:
            for _, p in self._stacked_layer_params(model):
                data = p.data
                perm = composed(int(data.shape[0]))
                transposed.add(id(p))
                if perm is None:
                    continue
                p.data = jax.device_put(
                    apply_layer_order(data, perm), data.sharding
                )
        for opt in self._optimizers:
            inner = getattr(opt, "optimizer", opt)
            indices = [
                i
                for i, p in enumerate(getattr(inner, "param_list", []))
                if id(p) in transposed
            ]
            if indices:
                inner.relayout_layer_axis(indices, composed)

    def _refresh_zero2_grads(self) -> None:
        """Collect the (param, accumulation-sharding) pairs ZeRO-2 armed at
        relayout time, so ``backward`` pays one cheap loop (empty when off)."""
        # (param, sharding, dp-axis, stochastic-wire-eligible): axis and
        # eligibility come from the optimizer's own relayout bookkeeping —
        # _dp_state_axis is the dp entry the state spec actually gained,
        # and _comp_axis is non-None exactly for the tensors the
        # compression policy's min_size/min_block/dtype gates admit, so the
        # narrow wire below can never quantize a tensor the reference
        # reduce-scatter path would deliberately pass through uncompressed
        self._zero2_grads = [
            (p, p._grad_sharding, opt.optimizer._dp_state_axis[i],
             opt.optimizer._comp_axis[i] is not None)
            for opt in self._optimizers
            for i, p in enumerate(opt.optimizer.param_list)
            if getattr(p, "_grad_sharding", None) is not None
        ]
        # stochastic-rounding ZeRO-2 wire (docs/kernels.md §stochastic
        # wire): the mid-accumulation scatter crosses dp narrow only when
        # the kernel policy AND an int8 collective policy AND ZeRO-2 are
        # all armed — the unbiased floor(y+u) round is what PR 6's
        # deterministic rounding could not offer
        import jax.numpy as jnp

        self._zero2_stochastic = bool(
            self._zero2_grads
            and self.kernels.quantized_rs
            and getattr(self._compression, "wire_dtype", None) is not None
            and jnp.dtype(self._compression.wire_dtype) == jnp.int8
        )

    def _record_collectives(self) -> None:
        """dp-axis collective-bytes attribution (telemetry
        ``kind="collectives"``): the analytic per-step wire bytes of the
        ZeRO-1 reduce-scatter/all-gather pair under the active compression
        policy — the denominator bench.py's A/B compares across policies."""
        if not self.telemetry.enabled:
            return
        for opt in self._optimizers:
            summary = opt.optimizer.compression_summary()
            if summary is not None:
                self.telemetry.record_collectives(summary)

    def _record_kernels(self) -> None:
        """One ``kind="kernel"`` record per armed Pallas kernel
        (docs/kernels.md): which hot path it replaces and how it lowers —
        the attribution bench.py's kernel A/B and the per-phase device
        split join against."""
        if not self.telemetry.enabled or not self.kernels.enabled:
            return
        targets = {
            "collective_matmul": "zero1 all-gather → chunked ring + partial matmuls",
            "quantized_rs": "compress reduce-scatter → fused scale+round region",
            "paged_attention": "serving decode gather → VMEM block-table walk",
        }
        for name in self.kernels.armed():
            self.telemetry.record_kernel(
                {
                    "kernel": name,
                    "target": targets[name],
                    "interpret": self.kernels.interpret,
                    "policy": self.kernels.describe(),
                }
            )

    def _prepare_one(self, obj):
        from .utils.torch_bridge import (
            convert_torch_module,
            convert_torch_optimizer,
            convert_torch_scheduler,
            is_torch_lr_scheduler,
            is_torch_module,
            is_torch_optimizer,
        )

        if is_torch_module(obj):
            # reference prepare_model takes any torch.nn.Module
            # (accelerator.py:1421); convert supported architectures to the
            # native nn with weights copied, then prepare as usual
            obj = convert_torch_module(obj)
            self._converted_models.append(obj)
        elif is_torch_optimizer(obj):
            # param identity can't cross the torch→JAX boundary: rebuild over
            # the converted models' params (reference's XLA param remap,
            # accelerator.py:1376-1410, same problem one framework harder)
            torch_opt = obj
            obj = convert_torch_optimizer(
                torch_opt, self._converted_models or self._models
            )
            self._converted_optimizers.append((torch_opt, obj))
        elif is_torch_lr_scheduler(obj):
            # the scheduler must drive the CONVERTED optimizer, not the
            # discarded torch one (silent frozen-LR bug otherwise)
            obj = convert_torch_scheduler(obj, self._converted_optimizers)
        if isinstance(obj, Module):
            return self.prepare_model(obj)
        if isinstance(obj, AcceleratedOptimizer):
            return obj
        if isinstance(obj, Optimizer):
            return self.prepare_optimizer(obj)
        if isinstance(obj, AcceleratedScheduler):
            return obj
        if isinstance(obj, (LRScheduler,)) or (
            hasattr(obj, "step") and hasattr(obj, "get_last_lr")
        ):
            return self.prepare_scheduler(obj)
        if isinstance(obj, DataLoaderShard) or hasattr(obj, "dataset") or hasattr(obj, "__iter__"):
            if isinstance(obj, (list, tuple, dict)):
                return obj
            return self.prepare_data_loader(obj)
        return obj

    def prepare_model(self, model: Module, device_placement: Optional[bool] = None, evaluation_mode: bool = False) -> Module:
        from .parallel.sharding import shard_module_params

        if self.num_devices > 1 and self.verify_device_map(model):
            # reference accelerator.py:1338-1349: an offload-dispatched model
            # carries align/offload hooks that fight mesh sharding at forward
            # time — refuse loudly instead of silently producing both
            raise ValueError(
                "you can't prepare a model dispatched with a multi-device "
                "device_map for distributed training; load it without "
                "device_map (shard_for_inference / ParallelismConfig handles "
                "multi-chip placement) or train on one device"
            )
        if device_placement is None:
            device_placement = self.device_placement
        # precision policy: params in compute dtype, master fp32 kept by optim
        fsdp = self.state.fsdp_plugin
        param_dtype = fsdp.resolved_dtype("param_dtype") if fsdp is not None else None
        if self.state.mixed_precision == "fp8":
            # swap Linears for fp8-matmul layers FIRST — an fsdp param_dtype
            # must tune the residual dtype, not silently disable fp8
            # (reference fp8 backends convert + autocast, SURVEY.md §2.4)
            from .utils.fp8 import convert_to_float8_training

            convert_to_float8_training(model, self.fp8_recipe_handler)
            model.to(param_dtype or jnp.bfloat16)
        elif param_dtype is not None:
            # FSDP MixedPrecisionPolicy.param_dtype (reference
            # dataclasses.py:1449): explicit per-plugin compute dtype wins
            # over the global mixed_precision default
            model.to(param_dtype)
        elif self.state.mixed_precision in ("bf16", "fp16"):
            model.to(self.compute_dtype)
        if device_placement:
            shard_module_params(
                model,
                self.state.mesh,
                fsdp_plugin=self.state.fsdp_plugin,
                tp_plugin=self.state.tp_plugin,
            )
        if model not in self._models:
            self._models.append(model)
        return model

    def prepare_optimizer(self, optimizer: Optimizer, device_placement: Optional[bool] = None) -> AcceleratedOptimizer:
        if isinstance(optimizer, AcceleratedOptimizer):
            return optimizer
        wrapped = AcceleratedOptimizer(
            optimizer,
            device_placement=device_placement if device_placement is not None else self.device_placement,
            scaler=self.scaler,
        )
        self._optimizers.append(wrapped)
        return wrapped

    def prepare_scheduler(self, scheduler) -> AcceleratedScheduler:
        if isinstance(scheduler, AcceleratedScheduler):
            return scheduler
        optimizers = self._optimizers or [
            getattr(scheduler, "optimizer", None)
        ]
        wrapped = AcceleratedScheduler(
            scheduler,
            optimizers,
            step_with_optimizer=self.step_scheduler_with_optimizer,
            split_batches=self.dataloader_config.split_batches,
        )
        self._schedulers.append(wrapped)
        return wrapped

    def prepare_data_loader(self, data_loader, device_placement: Optional[bool] = None, slice_fn_for_dispatch=None):
        if isinstance(data_loader, DataLoaderShard):
            if data_loader not in self._dataloaders:
                self._dataloaders.append(data_loader)
            data_loader._telemetry = self.telemetry if self.telemetry.enabled else None
            return data_loader
        prepared = prepare_data_loader(
            data_loader,
            split_batches=self.dataloader_config.split_batches,
            put_on_device=device_placement if device_placement is not None else self.device_placement,
            dispatch_batches=self.dataloader_config.dispatch_batches,
            even_batches=self.dataloader_config.even_batches,
            use_seedable_sampler=self.dataloader_config.use_seedable_sampler,
            data_seed=self.dataloader_config.data_seed,
            mesh=self.state.mesh,
            prefetch_size=self.dataloader_config.prefetch_size,
        )
        # pin this accelerator's telemetry hub: the loader's wait accounting
        # must survive (and never be rerouted by) later Accelerator
        # constructions flipping the module-global active slot
        prepared._telemetry = self.telemetry if self.telemetry.enabled else None
        self._dataloaders.append(prepared)
        return prepared

    # -------------------------------------------------------------- training
    def backward(self, loss: Tensor, **kwargs) -> None:
        """Reference accelerator.py:2357: scale for accumulation (+fp16) and
        run the tape backward; grads accumulate in ``param.grad``."""
        if self.gradient_state.num_steps > 1:
            loss = loss / self.gradient_state.num_steps
        if self.scaler is not None:
            loss = loss * self.scaler.scale
        import jax

        with jax.named_scope("atpu_backward"):
            # the scope is HLO metadata only (numerics untouched): the
            # sampled device timeline splits per phase on it
            # (docs/telemetry.md §per-phase attribution)
            loss.backward(**kwargs)
        if self._zero2_grads:
            # ZeRO-2 (docs/compression.md): keep the accumulated grads
            # reduce-scattered between micro-steps so the accumulation
            # buffer is ~1/dp per replica.  Layout-only — the value is the
            # same global array, and deterministically compressing a running
            # fp32 sum every micro-step would round it num_steps times (same
            # reason the comm hook below runs only at the sync boundary).
            # With the kernel policy's stochastic wire armed the scatter
            # crosses dp narrow anyway: floor(y+u) is unbiased per re-round
            # (docs/kernels.md §stochastic wire).
            from .parallel.compress import shard_accumulation

            if self._zero2_stochastic and not self.gradient_state.sync_gradients:
                from .native.kernels.quantize_rs import zero2_stochastic_wire

                for p, s, axis, sr_ok in self._zero2_grads:
                    if p.grad is None:
                        continue
                    if sr_ok and axis is not None:
                        p.grad = zero2_stochastic_wire(
                            p.grad, s, axis, nn_random.next_key(),
                            interpret=self.kernels.interpret,
                        )
                    else:
                        # the policy's eligibility gates exempt this tensor
                        # (too small to amortize the scale granularity):
                        # layout-only, exactly like the reference RS path
                        p.grad = shard_accumulation(p.grad, s)
            else:
                # the sync-boundary micro-step feeds the update directly —
                # its trip is the (exactly-quantized, error-fed) ZeRO-1
                # reduce-scatter, so it stays layout-only here
                for p, s, _axis, _sr_ok in self._zero2_grads:
                    if p.grad is not None:
                        p.grad = shard_accumulation(p.grad, s)
        if self.gradient_state.sync_gradients:
            # only at the sync boundary: re-quantizing the running fp32
            # accumulation every micro-step would pass the sum through
            # half-precision rounding num_steps times (reference DDP hooks
            # likewise compress only the sync-step all-reduce)
            self._apply_comm_hook()

    def _apply_comm_hook(self) -> None:
        """Gradient compression knob (reference DistributedDataParallelKwargs
        comm_hook / register_comm_hook, dataclasses.py:149-225): cast synced
        grads to fp16/bf16 at the backward boundary.

        What this buys under GSPMD: half-width grad buffers in HBM and
        half-width downstream consumers (clipping, any cross-host DCN grad
        traffic issued after this point).  What it does NOT change: the dtype
        of the dp gradient all-reduce XLA inserts *inside* the backward —
        that follows the compute dtype (bf16 mixed precision already reduces
        in bf16), and a cast placed after the reduce cannot legally be hoisted
        above it.  The optimizer upcasts to fp32 masters at apply time.

        The powersgd hooks run the full rank-k + error-feedback recurrence
        (utils/powersgd.py) on the synced gradients instead of a cast; the
        (Q, error) state rides the captured-step pytree like optimizer
        state, so the hook works identically under compile_step."""
        if self._comm_hook in ("powersgd", "batched_powersgd"):
            self._apply_powersgd_hook()
            return
        dtype = None
        if self._comm_hook is not None:
            dtype = jnp.float16 if self._comm_hook == "fp16" else jnp.bfloat16
        elif self.state.fsdp_plugin is not None:
            # FSDP MixedPrecisionPolicy.reduce_dtype rides the same boundary
            dtype = self.state.fsdp_plugin.resolved_dtype("reduce_dtype")
        if dtype is None:
            return
        for model in self._models:
            for p in model.parameters():
                if p.grad is not None and p.grad.dtype != dtype:
                    p.grad = p.grad.astype(dtype)

    # -- PowerSGD machinery (delegates to the CompressionPolicy) -------------
    def _ensure_powersgd_state(self) -> None:
        """Build (Q, error) hook buffers for every prepared model that lacks
        them, through the active :class:`PowerSGDCompression` policy — hook
        selection, eligibility and error-feedback state are one code path
        with the quantized-collective policies (parallel/compress.py).

        Runs eagerly at ``prepare()`` so the captured-step state pytree is
        structurally complete before the first trace (a mid-trace
        structure change would force a second compile)."""
        policy = self._hook_policy
        if policy is None:
            return
        from .nn import random as nn_random

        if self._powersgd_state is None:
            self._powersgd_state = []
        if self.scaler is not None and not getattr(self, "_powersgd_fp16_warned", False):
            self._powersgd_fp16_warned = True
            logger.warning(
                "comm_hook=powersgd with fp16 dynamic loss scaling: the error-"
                "feedback residual is carried at the loss scale it was produced "
                "under, so a scale change mis-scales one step's residual "
                "injection. Prefer mixed_precision='bf16' (no scaler) with "
                "PowerSGD, or accept the transient after each scale update."
            )
        while len(self._powersgd_state) < len(self._models):
            model = self._models[len(self._powersgd_state)]
            named = dict(model.named_parameters())
            shapes = {n: tuple(p.shape) for n, p in named.items()}
            state = policy.init_hook_state(shapes, nn_random.next_key())
            # shard each error buffer like its parameter: it is grad-shaped
            # and grad-sized, and an unsharded fp32 copy would undo ZeRO's
            # memory savings (per-tensor mode; the batched buffer has no
            # per-param layout to inherit)
            if not policy.batched:
                for n, err in state["err"].items():
                    s = getattr(named[n].data, "sharding", None)
                    if isinstance(s, jax.sharding.NamedSharding):
                        state["err"][n] = jax.device_put(
                            err, jax.sharding.NamedSharding(s.mesh, s.spec)
                        )
            self._powersgd_state.append(state)

    def _apply_powersgd_hook(self) -> None:
        from .nn import random as nn_random

        policy = self._hook_policy
        self._ensure_powersgd_state()
        for i, model in enumerate(self._models):
            named = dict(model.named_parameters())
            if policy.batched:
                # the batched error buffer is a FLAT layout over the whole
                # param set — the name set must be identical every call, so
                # zero-fill params without grads and only write back to the
                # ones that had one (parallel/compress.py contract)
                had_grad = {n for n, p in named.items() if p.grad is not None}
                grads = {
                    n: (p.grad if p.grad is not None else jnp.zeros_like(p.data))
                    for n, p in named.items()
                }
            else:
                had_grad = None
                grads = {n: p.grad for n, p in named.items() if p.grad is not None}
            new_grads, new_state = policy.apply_hook(
                grads,
                self._powersgd_state[i],
                rng_key=None if policy.warm_start else nn_random.next_key(),
            )
            for n, g in new_grads.items():
                if had_grad is None or n in had_grad:
                    named[n].grad = g
            self._powersgd_state[i] = new_state

    def _comm_hook_capture_state(self):
        """Arrays the captured step must thread (None when no powersgd)."""
        return self._powersgd_state

    def _bind_comm_hook_state(self, state) -> None:
        if state is not None:
            self._powersgd_state = state

    @contextlib.contextmanager
    def accumulate(self, *models):
        """Reference accelerator.py:1116: flip sync_gradients on schedule.

        Works both eagerly and *inside* a ``compile_step`` body: under
        capture, the owning CapturedStep advances the schedule host-side
        before every replay (one compiled variant per sync_gradients value —
        the micro-step program skips optimizer/scheduler work at trace time
        exactly as the eager path skips it at run time), so the reference's
        canonical ``with accelerator.accumulate(model):`` loop captures
        without restructuring."""
        if self._capture_ctx is not None:
            self._capture_ctx.on_accumulate(self)
            yield
            return
        self._do_sync()
        yield

    def _do_sync(self) -> None:
        if self.gradient_state.sync_with_dataloader and self.gradient_state.end_of_dataloader:
            self.step = 0
            self.gradient_state._set_sync_gradients(True)
        else:
            self.step += 1
            self.gradient_state._set_sync_gradients(
                (self.step % self.gradient_state.num_steps) == 0
                or self.gradient_state.sync_each_batch
            )

    @contextlib.contextmanager
    def no_sync(self, model=None):
        """Reference accelerator.py:1001: suppress the update this micro-step."""
        prev = self.gradient_state.sync_gradients
        self.gradient_state._set_sync_gradients(False)
        try:
            yield
        finally:
            self.gradient_state._set_sync_gradients(prev)

    def verify_device_map(self, model: Module) -> bool:
        """True when ``model`` was dispatched with a multi-device device_map
        (reference accelerator.py:3720 checks ``hf_device_map``; our
        dispatch path records ``atpu_device_map``, big_modeling.py).  Used
        to refuse distributed prepare() of an offload-dispatched model."""
        for m in model.modules():
            dmap = getattr(m, "atpu_device_map", None) or getattr(m, "hf_device_map", None)
            if dmap and len(set(map(str, dict(dmap).values()))) > 1:
                return True
        return False

    def lomo_backward(self, loss, learning_rate: float) -> None:
        """Reference API for LOMO's fused backward (accelerator.py:3731).

        Unsupported here: LOMO fuses the parameter update into torch's
        backward hooks, which has no counterpart in the traced-step model —
        under capture the optimizer update is already fused into the same
        XLA program as the backward, so LOMO's memory win is the default.
        """
        raise NotImplementedError(
            "lomo_backward is torch-hook-specific; under accelerate_tpu the "
            "captured step already fuses backward+update into one XLA program "
            "(use compile_step with any optim.* optimizer)."
        )

    @contextlib.contextmanager
    def join_uneven_inputs(self, joinables, even_batches=None):
        """SPMD requires shape-uniform programs; the loader already evens
        batches (reference join is a torch.distributed.algorithms concept),
        so this is a compatibility no-op context."""
        if even_batches is not None:
            logger.warning(
                "join_uneven_inputs(even_batches=...) has no effect: the SPMD "
                "data loader always produces even batches and tracks the "
                "remainder for gather_for_metrics."
            )
        yield

    def unscale_gradients(self, optimizer=None) -> None:
        """Divide the fp16 loss scale out of the gradients now (reference
        accelerator.py:2450); a no-op in every other precision mode.  The
        following ``optimizer.step`` will not divide again.  Normally called
        for you by ``clip_grad_norm_`` / ``clip_grad_value_``."""
        if optimizer is None:
            optimizers = self._optimizers
        elif isinstance(optimizer, (list, tuple)):
            optimizers = optimizer
        else:
            optimizers = [optimizer]
        for opt in optimizers:
            if hasattr(opt, "unscale_grads"):
                opt.unscale_grads()

    def clip_grad_norm_(self, parameters, max_norm: float, norm_type: float = 2.0):
        """Global-norm clip over ``param.grad`` (reference accelerator.py:2485).

        Works eagerly and under capture (pure jnp ops on the grads).
        Under fp16 the loss scale is divided out first — clipping must see
        true gradient magnitudes (reference clips after unscale_gradients).
        """
        self.unscale_gradients()
        params = list(parameters)
        grads = [p.grad for p in params if p.grad is not None]
        if not grads:
            return jnp.asarray(0.0)
        if norm_type == 2.0:
            total = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in grads))
        else:
            total = sum(jnp.sum(jnp.abs(g.astype(jnp.float32)) ** norm_type) for g in grads) ** (
                1.0 / norm_type
            )
        clip_coef = jnp.minimum(max_norm / (total + 1e-6), 1.0)
        for p in params:
            if p.grad is not None:
                p.grad = (p.grad.astype(jnp.float32) * clip_coef).astype(p.grad.dtype)
        return total

    def clip_grad_value_(self, parameters, clip_value: float) -> None:
        self.unscale_gradients()
        for p in parameters:
            if p.grad is not None:
                p.grad = jnp.clip(p.grad, -clip_value, clip_value)

    # ------------------------------------------------------------ collectives
    def gather(self, tensor):
        data = tensor.data if isinstance(tensor, Tensor) else tensor
        return ops.gather(data)

    def gather_for_metrics(self, input_data, use_gather_object: bool = False):
        """Gather + drop the duplicated tail samples the loader added
        (reference accelerator.py:2601; remainder from GradientState)."""
        try:
            ops.recursively_apply(lambda x: x, input_data, error_on_other_type=True)
            all_tensors = True
        except TypeError:
            all_tensors = False
        used_object_path = use_gather_object or not all_tensors
        if used_object_path:
            data = ops.gather_object(input_data)
        else:
            data = self.gather(
                input_data.data if isinstance(input_data, Tensor) else input_data
            )
        if self.gradient_state.end_of_dataloader and self.gradient_state.remainder > 0:
            remainder = self.gradient_state.remainder
            if used_object_path:
                # the flattened object list carries the sample count in its
                # own length (reference accelerator.py:2659 slices the list
                # itself when use_gather_object)
                return data[: len(data) - remainder]

            def _truncate(t):
                if getattr(t, "ndim", 0) == 0:
                    return t  # scalars carry no batch dim to truncate
                return t[: t.shape[0] - remainder]

            return ops.recursively_apply(_truncate, data)
        return data

    def reduce(self, tensor, reduction: str = "sum", scale: float = 1.0):
        data = tensor.data if isinstance(tensor, Tensor) else tensor
        return ops.reduce(data, reduction, scale)

    def pad_across_processes(self, tensor, dim: int = 0, pad_index: int = 0, pad_first: bool = False):
        data = tensor.data if isinstance(tensor, Tensor) else tensor
        return ops.pad_across_processes(data, dim, pad_index, pad_first)

    # -------------------------------------------------------------- triggers
    def set_trigger(self) -> None:
        """Any process can raise the flag; all see it at check (reference
        accelerator.py:2391 breakpoint trigger for early stopping)."""
        self.flag_tensor = 1

    def check_trigger(self) -> bool:
        flags = ops.gather_object([self.flag_tensor or 0])
        if any(bool(f) for f in flags):
            self.flag_tensor = None
            return True
        return False

    # ------------------------------------------------------------- unwrap/save
    def unwrap_model(self, model: Module, keep_fp32_wrapper: bool = True) -> Module:
        return model  # no wrapper modules exist under SPMD

    def get_state_dict(self, model: Module, unwrap: bool = True):
        sd = model.state_dict()
        # fully gather sharded params for a portable state dict
        return {
            k: np.asarray(jax.device_get(v)) for k, v in sd.items()
        }

    def save_model(
        self,
        model: Module,
        save_directory: str,
        max_shard_size: str = "10GB",
        safe_serialization: bool = True,
    ) -> None:
        from .checkpointing import save_model_weights

        os.makedirs(save_directory, exist_ok=True)
        save_model_weights(
            self.get_state_dict(model), save_directory, safe_serialization=safe_serialization
        )

    def save(self, obj, f, safe_serialization: bool = False) -> None:
        from .checkpointing import save_object

        if self.is_main_process:
            save_object(obj, f, safe_serialization=safe_serialization)

    def register_for_checkpointing(self, *objects) -> None:
        invalid = [o for o in objects if not (hasattr(o, "state_dict") and hasattr(o, "load_state_dict"))]
        if invalid:
            raise ValueError(
                "register_for_checkpointing requires state_dict/load_state_dict "
                f"on every object; invalid: {invalid}"
            )
        self._custom_objects.extend(objects)

    def save_state(
        self,
        output_dir: Optional[str] = None,
        safe_serialization: bool = True,
        sharded_state: Optional[bool] = None,
        async_save: bool = False,
        **kwargs,
    ) -> str:
        """Checkpoint everything registered with the Accelerator.

        ``async_save=True`` overlaps checkpoint serialization and file
        writes with continued training, on any process count.  The save's
        prepare phase runs at call time on the main thread of every
        process: all collectives (unsharded multi-host gathers) plus every
        device→host transfer, materializing the state into host numpy the
        training loop can never invalidate (donation in a captured step
        deletes live buffers regardless of held references; sharded saves
        pull only this host's unique GSPMD shards — O(shard) host memory,
        no extra HBM copy).  The writer thread then only serializes and
        writes files, so it cannot race the training loop's collectives.
        Steps taken after the call never leak into the checkpoint.  One
        save may be in flight at a time; ``wait_for_checkpoint()`` joins
        the writer and runs the collective finalize (barrier +
        stale-artifact cleanup) — ``load_state``/``end_training``/the next
        ``save_state`` call it automatically on every rank, and the writer
        is non-daemon so interpreter exit joins it.
        """
        self.wait_for_checkpoint()
        if self.project_configuration.automatic_checkpoint_naming:
            output_dir = os.path.join(self.project_dir or ".", "checkpoints")
            folders = []
            if os.path.isdir(output_dir):
                folders = [f for f in os.listdir(output_dir) if f.startswith("checkpoint_")]
            iteration = self.project_configuration.iteration
            # rotation (reference accelerator.py:3148-3163)
            limit = self.project_configuration.total_limit
            if limit is not None and len(folders) + 1 > limit and self.is_main_process:
                import shutil

                folders.sort(key=lambda f: int(f.split("_")[-1]))
                for f in folders[: len(folders) + 1 - limit]:
                    shutil.rmtree(os.path.join(output_dir, f), ignore_errors=True)
            output_dir = os.path.join(output_dir, f"checkpoint_{iteration}")
            self.project_configuration.iteration += 1
        if output_dir is None:
            raise ValueError("save_state needs output_dir (or automatic_checkpoint_naming)")
        os.makedirs(output_dir, exist_ok=True)
        if sharded_state is None:
            # default: shard the checkpoint exactly when the state is sharded
            # (fsdp axis populated) and the plugin doesn't demand FULL —
            # reference FSDP state_dict_type semantics (fsdp_utils.py:66)
            plugin = getattr(self.state, "fsdp_plugin", None)
            fsdp_axis = dict(self.mesh.shape).get("fsdp", 1) if self.mesh else 1
            sharded_state = fsdp_axis > 1 and (
                plugin is None or plugin.state_dict_type == "SHARDED_STATE_DICT"
            )
        # pre-hooks see (models, weights, output_dir) and may mutate the
        # weights list — removing/replacing entries takes over saving for
        # those models (reference accelerator.py:3221); whatever is left is
        # exactly what gets written below (both sync and async paths)
        from .checkpointing import FrozenState

        weights = [dict(m.state_dict()) for m in self._models]
        for hook in self._save_state_pre_hooks.values():
            hook(self._models, weights, output_dir)
        model_states = [FrozenState(w) for w in weights]

        # Three-phase save (checkpointing.py): prepare runs EVERY collective
        # (unsharded multi-host gathers) and every device→host transfer here
        # on the main thread of every process, so the write phase is pure
        # file IO.  That is what makes async safe multi-process: the writer
        # thread never issues a collective that could race the training
        # loop's own (the dispatch-loader producer hazard).  snapshot=True
        # additionally deep-copies Python-side state; device arrays are
        # materialized to host numpy either way (donation in a later
        # captured step invalidates live buffers regardless of references).
        from .checkpointing import (
            finalize_accelerator_save,
            prepare_accelerator_save,
            write_accelerator_save,
        )

        plan = prepare_accelerator_save(
            output_dir,
            models=model_states,
            optimizers=self._optimizers,
            schedulers=self._schedulers,
            dataloaders=self._dataloaders,
            custom_objects=self._custom_objects,
            step=self.step,
            scaler=self.scaler,
            safe_serialization=safe_serialization,
            sharded_state=sharded_state,
            snapshot=async_save,
            # spec-carrying layout descriptor: stacked layer arrays are
            # written AS-IS (committed order); the record lets a restore
            # into a different layout transpose them (docs/parallel_plan.md)
            extra_meta=self._layer_layout_record(),
        )
        if not async_save:
            write_accelerator_save(plan)
            finalize_accelerator_save(plan)
            if self.resilience.enabled:
                self.resilience.note_checkpoint(output_dir)
            return output_dir

        import threading as _threading

        def _runner():
            try:
                write_accelerator_save(plan)
            except BaseException as exc:  # noqa: BLE001 — surfaced on wait
                self._async_save_error = exc

        self._async_save_error = None
        self._async_save_plan = plan
        self._async_save_dir = output_dir
        # non-daemon: a normal interpreter exit joins this thread, so a
        # script that ends right after save_state still gets a complete
        # checkpoint instead of a silently truncated one.  The collective
        # finalize (barrier + stale-artifact cleanup) runs on the main
        # thread in wait_for_checkpoint.
        self._async_save_thread = _threading.Thread(
            target=_runner, name="accelerate-tpu-async-save", daemon=False
        )
        self._async_save_thread.start()
        # Exit-without-wait safety net: CPython joins non-daemon threads
        # BEFORE atexit callbacks run, so a handler registered here sees the
        # write finished and can run the (deferred) finalize cleanup.
        # Single-process only — finalize's barriers are no-ops there; with
        # multiple processes an atexit-time collective against ranks that
        # may already be gone could hang, so those must call
        # wait_for_checkpoint (load_state/end_training do) or stale-file
        # cleanup is skipped.
        if self.num_processes == 1 and not getattr(self, "_async_atexit_armed", False):
            import atexit

            def _finalize_at_exit():
                try:
                    self.wait_for_checkpoint()
                except Exception as exc:  # noqa: BLE001 — exit path, log only
                    logger.warning(f"async checkpoint failed at interpreter exit: {exc}")

            atexit.register(_finalize_at_exit)
            self._async_atexit_armed = True
        return output_dir

    def register_save_state_pre_hook(self, hook):
        """Run ``hook(models, weights, output_dir)`` before every
        ``save_state`` write (reference accelerator.py:3074).  ``weights``
        is the list of state dicts about to be saved; mutating it (removing
        or replacing entries) customizes what gets written.  Returns a
        handle whose ``remove()`` detaches the hook."""
        from .hooks import RemovableHandle

        handle = RemovableHandle(self._save_state_pre_hooks)
        self._save_state_pre_hooks[handle.id] = hook
        return handle

    def register_load_state_pre_hook(self, hook):
        """Run ``hook(models, input_dir)`` before every ``load_state``
        restore (reference accelerator.py:3241).  Removing models from the
        list takes over loading for them.  Returns a removable handle."""
        from .hooks import RemovableHandle

        handle = RemovableHandle(self._load_state_pre_hooks)
        self._load_state_pre_hooks[handle.id] = hook
        return handle

    def wait_for_checkpoint(self) -> None:
        """Block until an in-flight ``save_state(async_save=True)`` is
        durable on disk; re-raise any error it hit.

        Collective on multi-process: after joining the local writer thread
        this runs the save's finalize phase (cross-process barrier +
        stale-artifact cleanup), so every process must call it — which the
        automatic call sites (``load_state``/``end_training``/the next
        ``save_state``) already do on every rank.  If the writer failed,
        cleanup is skipped (older checkpoint files stay loadable) and the
        error re-raises after the barrier."""
        thread = getattr(self, "_async_save_thread", None)
        if thread is None:
            return
        thread.join()
        self._async_save_thread = None
        error = getattr(self, "_async_save_error", None)
        self._async_save_error = None
        plan = getattr(self, "_async_save_plan", None)
        self._async_save_plan = None
        saved_dir = getattr(self, "_async_save_dir", None)
        self._async_save_dir = None
        failed = error is not None
        if plan is not None:
            from .checkpointing import finalize_accelerator_save

            if self.num_processes > 1:
                # cleanup must be all-or-nothing: a writer failure on ANY
                # rank means some new artifact is missing/truncated there,
                # and deleting the previous checkpoint's files elsewhere
                # would leave no loadable checkpoint at all
                from .utils.operations import gather_object

                failed = any(gather_object([failed]))
            finalize_accelerator_save(plan, cleanup=not failed)
        if error is not None:
            raise error
        if self.resilience.enabled and saved_dir is not None and not failed:
            # only a save that landed error-free ON EVERY RANK is a valid
            # rollback target
            self.resilience.note_checkpoint(saved_dir)

    def load_state(self, input_dir: Optional[str] = None, **kwargs) -> None:
        from .checkpointing import load_accelerator_state

        self.wait_for_checkpoint()
        if input_dir is None and self.project_configuration.automatic_checkpoint_naming:
            base = os.path.join(self.project_dir or ".", "checkpoints")
            # prefer the newest COMPLETE checkpoint (meta sentinel present):
            # a preempted run killed mid-write leaves a truncated newest
            # folder, and resuming must not load half a state
            from .checkpointing import latest_checkpoint

            input_dir = latest_checkpoint(base)
            if input_dir is None:
                folders = sorted(
                    (f for f in os.listdir(base) if f.startswith("checkpoint_")),
                    key=lambda f: int(f.split("_")[-1]),
                )
                if not folders:
                    raise FileNotFoundError(f"no checkpoints in {base}")
                input_dir = os.path.join(base, folders[-1])
        # pre-hooks see (models, input_dir) and may remove entries from the
        # list to take over loading for those models (reference
        # accelerator.py:3365); the loader restores whatever remains
        models = list(self._models)
        for hook in self._load_state_pre_hooks.values():
            hook(models, input_dir)
        # zero-cold-start coupling (docs/aot_cache.md): a restore — the
        # resilience rollback path and the latest_checkpoint preemption
        # resume both land here — warms the executable cache FIRST, so the
        # replayed step deserializes the same compiled program from memory
        # instead of recompiling (or even touching disk on the step path)
        if self.aot_cache.enabled and self.aot_cache.warm_on_restore:
            self.aot_cache.prefetch()
        override = load_accelerator_state(
            input_dir,
            models=models,
            optimizers=self._optimizers,
            schedulers=self._schedulers,
            dataloaders=self._dataloaders,
            custom_objects=self._custom_objects,
            scaler=self.scaler,
        )
        # cross-layout restore: transpose stacked layer arrays (params +
        # masters/moments) from the checkpoint's layer layout into the live
        # one; bitwise no-op when they match (incl. pre-layout checkpoints
        # into plain runs)
        self._retarget_layer_layout(override.pop("layer_layout", None))
        if "step" in override:
            self.step = override["step"]

    def free_memory(self, *objects):
        """Release references + device buffers (reference accelerator.py:3412)."""
        self._models.clear()
        self._optimizers.clear()
        self._schedulers.clear()
        self._dataloaders.clear()
        self._custom_objects.clear()
        self._capture_cache.clear()
        # the ZeRO-2 pairs hold (param, sharding) references — leaving them
        # would keep every released param's device buffers reachable AND
        # re-layout stale grads on the next backward
        self._zero2_grads.clear()
        self.step = 0
        import gc

        gc.collect()
        return objects

    def clear(self, *objects):
        return self.free_memory(*objects)

    # -------------------------------------------------------------- tracking
    def init_trackers(self, project_name: str, config: Optional[dict] = None, init_kwargs: dict = {}) -> None:
        from .tracking import resolve_trackers

        self.trackers = resolve_trackers(
            self.log_with, project_name, self.logging_dir, init_kwargs
        )
        if config is not None:
            for tracker in self.trackers:
                tracker.store_init_configuration(config)
        if self.telemetry.enabled and self.trackers:
            # bridge: every accelerator.log() drains pending telemetry
            # events (step phases, recompile causes, HBM samples) into the
            # same backends as the user's metrics (telemetry/export.py).
            # First in the list: end_training finishes trackers in order,
            # and the bridge's finish() must flush into delegates that are
            # still open (a finished WandB run rejects further log calls).
            from .telemetry.export import TelemetryTracker

            self.trackers.insert(
                0, TelemetryTracker(self.telemetry, delegates=list(self.trackers))
            )

    def get_tracker(self, name: str, unwrap: bool = False):
        for tracker in self.trackers:
            if tracker.name == name:
                return tracker.tracker if unwrap else tracker
        raise ValueError(f"tracker {name} not initialized")

    def log(self, values: dict, step: Optional[int] = None, log_kwargs: dict = {}) -> None:
        if not self.is_main_process:
            return
        def _clean(v):
            if hasattr(v, "item") and getattr(v, "ndim", 1) == 0:
                return float(v.item())
            if hasattr(v, "tolist"):
                return v.tolist()
            return v

        clean = {k: _clean(v) for k, v in values.items()}
        for tracker in self.trackers:
            tracker.log(clean, step=step, **log_kwargs.get(tracker.name, {}))

    def end_training(self) -> None:
        self.wait_for_checkpoint()  # an in-flight async save must land
        self.resilience.close()  # restore default signal handling
        if self.telemetry.enabled and self.num_processes > 1:
            # fleet merge BEFORE any tracker finishes: the gather is
            # collective (every rank participates), and the main rank's
            # JSONL dump below — whether written here or by the bridge's
            # finish() — must already hold the rank-tagged records plus the
            # kind="fleet" skew record (docs/telemetry.md §aggregation)
            self.telemetry.aggregate_fleet()
        for tracker in self.trackers:
            tracker.finish()
        if self.telemetry.enabled and not any(
            t.name == "telemetry" for t in self.trackers
        ):
            # no-op unless a JSONL dump path was configured; the tracker
            # bridge, when present, already wrote it in finish()
            self.telemetry.write_jsonl()
        # black-box forensics teardown: the joined Chrome/Perfetto timeline
        # (no-op without a configured path), then the watchdog thread — the
        # flight ring itself stays live for any later manual dump
        self.telemetry.export_trace()
        self.telemetry.close_watchdog()
        self.telemetry.close_metrics()  # stop serving /metrics for this run
        self.wait_for_everyone()

    # --------------------------------------------------------------- contexts
    @contextlib.contextmanager
    def autocast(self, autocast_handler=None):
        """Local precision override (reference accelerator.py:3587).

        The *ambient* policy lands at prepare() time (params cast to bf16 and
        compute follows), so with ``enabled=True`` this yields unchanged.
        ``AutocastKwargs(enabled=False)`` opens a locally-fp32 region: the
        numerically-sensitive ``F.*`` ops traced inside (matmuls, norms,
        softmaxes, losses, attention) compute in fp32 regardless of param
        dtype — the reference's "disable autocast around the loss" idiom.
        Pure element-wise activations keep their operand dtype.  The region
        is a trace-time property: under ``compile_step`` the policy active at
        capture time is baked into the replayed program.
        """
        from .nn.amp import autocast_region

        handler = autocast_handler or self.autocast_handler
        if handler is not None and not handler.enabled:
            with autocast_region(jnp.float32):
                yield
            return
        yield

    @contextlib.contextmanager
    def profile(self, profile_handler: Optional[ProfileKwargs] = None):
        """jax.profiler trace (reference accelerator.py:3614 torch.profiler).

        Handler fields map onto ``jax.profiler.ProfileOptions``:
        ``host_tracer_level``/``python_tracer_level`` pass through directly;
        ``with_flops`` turns on HLO-proto capture (FLOPs are derivable from
        the HLO in TensorBoard's op profile); ``profile_memory`` additionally
        writes a device-memory profile next to the trace.
        ``device_tracer_level`` and ``record_shapes`` have no jax.profiler
        equivalent (device tracing is always on for TPU; shapes live in the
        HLO) and are accepted for reference API parity.
        """
        handler = profile_handler or self.profile_handler or ProfileKwargs()
        trace_dir = handler.output_trace_dir
        if trace_dir is None:
            yield None
            return
        os.makedirs(trace_dir, exist_ok=True)
        options = jax.profiler.ProfileOptions()
        options.host_tracer_level = handler.host_tracer_level
        options.python_tracer_level = handler.python_tracer_level
        if handler.with_flops:
            options.enable_hlo_proto = True
        jax.profiler.start_trace(trace_dir, profiler_options=options)
        try:
            yield None
        finally:
            jax.profiler.stop_trace()
            if handler.profile_memory:
                jax.profiler.save_device_memory_profile(
                    os.path.join(trace_dir, "memory.prof")
                )
            if handler.on_trace_ready is not None:
                handler.on_trace_ready(trace_dir)

    @contextlib.contextmanager
    def local_sgd(self, *args, **kwargs):
        from .local_sgd import LocalSGD

        with LocalSGD(self, *args, **kwargs) as ctx:
            yield ctx

    # ---------------------------------------------------------- step capture
    def compile_step(self, fn: Callable) -> Callable:
        """Trace the imperative loop body once; replay as one XLA program.

        ``fn(*array_pytrees)`` may use prepared models/optimizers/schedulers
        imperatively (forward, ``accelerator.backward``, ``optimizer.step()``,
        ``scheduler.step()``...).  State (params, grads, optimizer state, RNG)
        is threaded as donated jit arguments; scheduler steps are deferred to
        python after each replay (their LR lands in the optimizer's
        hyperparams, which are part of the traced state).

        Returns a wrapper with the same signature; the return value of ``fn``
        must be a pytree of arrays/Tensors (e.g. the loss).
        """
        from .capture import CapturedStep

        return CapturedStep(self, fn)

    def __repr__(self):
        return (
            f"Accelerator(mesh={dict(self.state.mesh.shape)}, "
            f"mixed_precision={self.mixed_precision!r}, "
            f"grad_accum={self.gradient_accumulation_steps})"
        )

    # convenience parity helpers
    def skip_first_batches(self, dataloader, num_batches: int = 0):
        return skip_first_batches(dataloader, num_batches)

    @staticmethod
    def _reset_state(reset_partial_state: bool = True):
        AcceleratorState._reset_state(reset_partial_state)
        GradientState._reset_state()
