"""Device-mesh construction from topology.

The single most important architectural inversion versus the reference: where
``/root/reference/src/accelerate/state.py:734-799`` selects one of ten
process-group backends, a TPU program has exactly one runtime (PJRT) and one
distribution mechanism — a :class:`jax.sharding.Mesh` whose axes carry every
parallelism strategy simultaneously (dp / fsdp / tp / sp / ep / pp).
Collectives ride ICI within a slice and DCN across slices; XLA chooses them
from sharding specs, we only lay out the mesh so that the heavily-communicating
axes (tp, sp) map to physically adjacent devices.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from ..utils.constants import ALL_MESH_AXES


# API detection ONCE at import (not per-call exception probing, which would
# mask genuine caller errors by silently retrying on the legacy path)
_HAS_NEW_SHARD_MAP = hasattr(jax, "shard_map")


def shard_map_compat(f, mesh, in_specs, out_specs, check: bool = False):
    """``jax.shard_map`` across the 0.8 API move.

    jax>=0.8 exposes keyword-only ``jax.shard_map`` with ``check_vma``;
    the old ``jax.experimental.shard_map`` used ``check_rep``.  One shim so
    every caller (pipeline schedules, ring attention, tests) follows the
    same path and the deprecation never prints.
    """
    if _HAS_NEW_SHARD_MAP:
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check
        )
    from jax.experimental.shard_map import shard_map as _legacy

    return _legacy(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check
    )


def make_mesh(
    axis_sizes: dict[str, int],
    devices: Optional[Sequence[jax.Device]] = None,
    axis_order: Sequence[str] = ALL_MESH_AXES,
) -> Mesh:
    """Build a Mesh with the given axis sizes.

    Axis order is chosen so that the *fastest-varying* (innermost) axes are the
    most communication-hungry: ``tp`` and ``sp`` land on adjacent chips
    (ICI-neighbouring), ``dp`` is outermost (cheapest collectives: one psum per
    step, latency-tolerant).  ``mesh_utils.create_device_mesh`` then maps the
    logical mesh onto the physical torus so nearest-neighbour ICI links are
    used for the inner axes.
    """
    if devices is None:
        devices = jax.devices()
    sizes = [axis_sizes.get(name, 1) for name in axis_order]
    total = int(np.prod(sizes))
    if total != len(devices):
        raise ValueError(
            f"mesh axis sizes {dict(zip(axis_order, sizes))} require {total} "
            f"devices, have {len(devices)}"
        )
    try:
        from jax.experimental import mesh_utils

        device_array = mesh_utils.create_device_mesh(
            tuple(sizes), devices=list(devices)
        )
    except Exception:
        # CPU simulation or exotic topologies: plain reshape is fine.
        device_array = np.asarray(list(devices)).reshape(tuple(sizes))
    return Mesh(device_array, axis_names=tuple(axis_order))


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis] if axis in mesh.axis_names else 1


def data_axes(mesh: Mesh) -> tuple[str, ...]:
    """Mesh axes over which the global batch is sharded.

    dp and fsdp both consume batch (ZeRO shards params but still feeds each
    device distinct data); sp shards the sequence dimension, not batch.
    """
    return tuple(a for a in ("dp", "fsdp") if mesh_axis_size(mesh, a) > 1) or ("dp",)


def batch_sharding_size(mesh: Mesh) -> int:
    """Number of distinct per-device batch shards."""
    return int(np.prod([mesh.shape[a] for a in data_axes(mesh)]))
