"""Benchmark: GPT-2-small causal-LM training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The flagship workload (BASELINE.md): transformer training throughput,
bf16, full captured step (fwd+bwd+AdamW fused into one XLA program).
``vs_baseline`` compares per-chip tokens/sec against an 8×A100 NCCL DDP
baseline estimate for GPT-2-small of 150k tokens/s/GPU (A100 312 TFLOP/s
bf16 at ~40% MFU over ~6N FLOPs/token; BASELINE.json publishes no number,
so the denominator is this documented estimate).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np

A100_BASELINE_TOKENS_PER_SEC = 150_000.0

BATCH = int(os.environ.get("BENCH_BATCH", 8))
SEQ = int(os.environ.get("BENCH_SEQ", 1024))
STEPS = int(os.environ.get("BENCH_STEPS", 20))
WARMUP = int(os.environ.get("BENCH_WARMUP", 5))


def main() -> None:
    import accelerate_tpu.nn as nn
    import accelerate_tpu.optim as optim
    from accelerate_tpu import Accelerator
    from accelerate_tpu.models import GPTConfig, GPTLMHeadModel

    nn.manual_seed(0)
    acc = Accelerator(mixed_precision="bf16")
    cfg = GPTConfig.small()
    model = GPTLMHeadModel(cfg)
    opt = optim.AdamW(model.parameters(), lr=3e-4, weight_decay=0.1)
    model, opt = acc.prepare(model, opt)

    def step_fn(ids):
        opt.zero_grad()
        out = model(ids, labels=ids)
        acc.backward(out["loss"])
        opt.step()
        return out["loss"]

    step = acc.compile_step(step_fn)
    rng = np.random.default_rng(0)
    from accelerate_tpu.data_loader import batch_to_global_array

    def make_batch(i):
        ids = rng.integers(0, cfg.vocab_size, size=(BATCH, SEQ), dtype=np.int32)
        return batch_to_global_array(jnp.asarray(ids), mesh=acc.mesh)

    batches = [make_batch(i) for i in range(4)]
    loss = step(batches[0])  # always at least one compile+run before timing
    for i in range(max(0, WARMUP - 1)):
        loss = step(batches[(i + 1) % len(batches)])
    float(loss)  # force full sync before timing

    t0 = time.perf_counter()
    for i in range(STEPS):
        loss = step(batches[i % len(batches)])
    final_loss = float(loss)  # device sync: everything above has completed
    dt = time.perf_counter() - t0

    tokens_per_sec = BATCH * SEQ * STEPS / dt
    n_params = model.num_parameters
    flops_per_token = 6 * n_params
    mfu_denom = 197e12 if acc.state.backend in ("tpu", "axon") else None
    result = {
        "metric": "gpt2_small_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(tokens_per_sec / A100_BASELINE_TOKENS_PER_SEC, 4),
    }
    print(json.dumps(result))
    print(
        f"# params={n_params/1e6:.1f}M batch={BATCH}x{SEQ} steps={STEPS} "
        f"time={dt:.2f}s loss={final_loss:.3f} "
        f"model_flops={tokens_per_sec * flops_per_token / 1e12:.1f} TFLOP/s"
        + (f" (~{tokens_per_sec * flops_per_token / mfu_denom * 100:.0f}% MFU)" if mfu_denom else ""),
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
