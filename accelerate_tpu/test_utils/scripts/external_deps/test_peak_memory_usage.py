"""Memory-bound assertions (analog of reference
test_utils/scripts/external_deps/test_peak_memory_usage.py).

The reference trains under each backend and asserts peak CUDA memory stays
inside a per-backend envelope.  TPU-native analog, checkable on the virtual
CPU mesh: ZeRO/FSDP memory comes from *sharding*, so the bound is on
per-device addressable bytes —

* params: each device's addressable shards of every parameter must total
  ≈ params_total / fsdp_size (+ replicated exemptions);
* optimizer state + fp32 masters: same bound (ZeRO-1/2 semantics, the
  round-1 verdict's "optimizer-state sharding unverified" gap);
* ``find_executable_batch_size`` recovers from an induced OOM by halving.
"""

from __future__ import annotations

import numpy as np

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator, set_seed
from accelerate_tpu.models import GPTConfig, GPTLMHeadModel
from accelerate_tpu.state import PartialState
from accelerate_tpu.utils.dataclasses import ParallelismConfig
from accelerate_tpu.utils.memory import find_executable_batch_size


def _addressable_param_bytes(model) -> int:
    """Per-device parameter bytes: the first device's shard of every param."""
    total = 0
    for _, p in model.named_parameters():
        arr = p.data
        shard = arr.addressable_shards[0]
        total += int(np.prod(shard.data.shape)) * arr.dtype.itemsize
    return total


def _addressable_opt_bytes(opt) -> int:
    import jax

    total = 0
    seen = set()

    def _leaf_bytes(leaf):
        nonlocal total
        if isinstance(leaf, jax.Array) and leaf.ndim > 0 and id(leaf) not in seen:
            seen.add(id(leaf))
            shard = leaf.addressable_shards[0]
            total += int(np.prod(shard.data.shape)) * leaf.dtype.itemsize

    jax.tree_util.tree_map(_leaf_bytes, opt.optimizer.capture_state())
    return total


def _build(fsdp_size: int):
    set_seed(0)
    acc = Accelerator(
        mixed_precision="bf16",
        parallelism_config=ParallelismConfig(fsdp_size=fsdp_size),
    )
    cfg = GPTConfig(
        vocab_size=256, n_positions=64, n_embd=64, n_layer=2, n_head=2, dropout=0.0
    )
    model = GPTLMHeadModel(cfg)
    opt = optim.AdamW(model.parameters(), lr=1e-3)
    model, opt = acc.prepare(model, opt)
    # one step so lazily-created fp32 masters + moments exist
    ids = np.zeros((8, 64), dtype=np.int32)
    out = model(ids, labels=ids)
    acc.backward(out["loss"])
    opt.step()
    return acc, model, opt


def main():
    import jax

    n_dev = len(jax.devices())
    if n_dev < 2:
        print("peak-memory script needs a multi-device mesh; skipping bounds")
    else:
        fsdp = min(4, n_dev)
        _, model_r, opt_r = _build(fsdp_size=1)
        bytes_params_repl = _addressable_param_bytes(model_r)
        bytes_opt_repl = _addressable_opt_bytes(opt_r)
        PartialState._reset_state()

        _, model_s, opt_s = _build(fsdp_size=fsdp)
        bytes_params_shard = _addressable_param_bytes(model_s)
        bytes_opt_shard = _addressable_opt_bytes(opt_s)
        PartialState._reset_state()

        # embeddings are fsdp-exempt (gather tables), so the bound is loose:
        # sharded must be well under replicated, approaching 1/fsdp for the
        # trunk-dominated model
        assert bytes_params_shard < 0.75 * bytes_params_repl, (
            bytes_params_shard, bytes_params_repl
        )
        assert bytes_opt_shard < 0.75 * bytes_opt_repl, (
            bytes_opt_shard, bytes_opt_repl
        )
        print(
            f"param bytes/device: {bytes_params_repl} → {bytes_params_shard} "
            f"(fsdp={fsdp}); opt bytes/device: {bytes_opt_repl} → {bytes_opt_shard}"
        )

    # OOM-retry decorator: halve batch until it fits (reference memory.py:120)
    attempts = []

    @find_executable_batch_size(starting_batch_size=64)
    def train(batch_size):
        attempts.append(batch_size)
        if batch_size > 16:
            raise RuntimeError("RESOURCE_EXHAUSTED: Out of memory (synthetic)")
        return batch_size

    final = train()
    assert final == 16 and attempts == [64, 32, 16], attempts
    print("All peak-memory checks passed")


if __name__ == "__main__":
    main()
