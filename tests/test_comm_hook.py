"""DDP comm-hook analog: gradient compression at the backward boundary
(reference DistributedDataParallelKwargs.comm_hook / register_comm_hook)."""

import jax.numpy as jnp
import numpy as np
import pytest

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator
from accelerate_tpu.utils.dataclasses import DistributedDataParallelKwargs


def _setup(comm_hook):
    Accelerator._reset_state()
    nn.manual_seed(0)
    handlers = []
    if comm_hook is not None:
        handlers.append(DistributedDataParallelKwargs(comm_hook=comm_hook))
    acc = Accelerator(kwargs_handlers=handlers)
    model = nn.Linear(8, 4)
    opt = optim.SGD(model.parameters(), lr=0.1)
    model, opt = acc.prepare(model, opt)
    return acc, model, opt


def test_comm_hook_compresses_grads():
    acc, model, opt = _setup("bf16")
    x = nn.Tensor(jnp.ones((2, 8), jnp.float32))
    loss = model(x).sum()
    acc.backward(loss)
    for p in model.parameters():
        assert p.grad is not None and p.grad.dtype == jnp.bfloat16


def test_no_hook_keeps_dtype():
    acc, model, opt = _setup(None)
    x = nn.Tensor(jnp.ones((2, 8), jnp.float32))
    acc.backward(model(x).sum())
    for p in model.parameters():
        assert p.grad is not None and p.grad.dtype == jnp.float32


def test_comm_hook_training_still_converges():
    acc, model, opt = _setup("bf16")
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(16, 8)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(16, 4)), jnp.float32)

    def fn(xb, yb):
        opt.zero_grad()
        pred = model(xb)
        loss = ((pred - yb) ** 2).mean()
        acc.backward(loss)
        opt.step()
        return loss

    step = acc.compile_step(fn)
    losses = [float(step(nn.Tensor(x), nn.Tensor(y))) for _ in range(8)]
    assert losses[-1] < losses[0]


def test_invalid_comm_hook_raises_at_construction():
    Accelerator._reset_state()
    with pytest.raises(ValueError, match="comm_hook"):
        Accelerator(
            kwargs_handlers=[DistributedDataParallelKwargs(comm_hook="int3")]
        )


def test_no_comm_hook_value_is_noop():
    """The reference's DDPCommunicationHookType.NO is a valid no-op default —
    code passing the explicit NO value (or its enum stringification) must run
    uncompressed rather than fail at construction (ADVICE r3)."""
    for value in ("no", "NO", "DDPCommunicationHookType.NO"):
        acc, model, opt = _setup(value)
        # caller-owned handler is never mutated
        assert acc.ddp_handler.comm_hook == value
        x = nn.Tensor(jnp.ones((2, 8), jnp.float32))
        acc.backward(model(x).sum())
        for p in model.parameters():
            assert p.grad is not None and p.grad.dtype == jnp.float32


def test_enum_stringified_fp16_hook_compresses_fp16():
    """An enum-stringified FP16 value must compress to fp16, not silently
    fall through to bf16 (round-4 review finding)."""
    acc, model, opt = _setup("DDPCommunicationHookType.FP16")
    x = nn.Tensor(jnp.ones((2, 8), jnp.float32))
    acc.backward(model(x).sum())
    for p in model.parameters():
        assert p.grad is not None and p.grad.dtype == jnp.float16


def test_accumulation_compresses_only_at_sync():
    """Non-sync micro-steps must keep the running sum in fp32 — re-quantizing
    per micro-step would round away small grads (review finding)."""
    from accelerate_tpu.utils.dataclasses import GradientAccumulationPlugin

    Accelerator._reset_state()
    nn.manual_seed(0)
    acc = Accelerator(
        kwargs_handlers=[DistributedDataParallelKwargs(comm_hook="bf16")],
        gradient_accumulation_plugin=GradientAccumulationPlugin(num_steps=2),
    )
    model = nn.Linear(8, 4)
    opt = optim.SGD(model.parameters(), lr=0.1)
    model, opt = acc.prepare(model, opt)
    x = nn.Tensor(jnp.ones((2, 8), jnp.float32))
    with acc.accumulate(model):  # micro-step 1 of 2: no sync
        acc.backward(model(x).sum())
    assert all(p.grad.dtype == jnp.float32 for p in model.parameters())
    with acc.accumulate(model):  # micro-step 2 of 2: sync boundary
        acc.backward(model(x).sum())
    assert all(p.grad.dtype == jnp.bfloat16 for p in model.parameters())
