"""Fine-tune a Llama-family causal LM with ZeRO/FSDP sharding and sharded
checkpoints — the BASELINE.json config-4 workload shape ("FSDP-wrapped
Llama-2-7B", reference tests/fsdp + accelerator.py:1421 any-module prepare).

What this shows, end to end:

1. **Checkpoint ingestion** — ``--model_path`` loads a real HF Llama
   checkpoint directory (safetensors or torch .bin) through
   ``utils.hf.from_pretrained``; without it a from-scratch proxy config
   trains so the example runs anywhere.
2. **ZeRO sharding as a mesh layout** — ``ParallelismConfig(fsdp_size=N)``:
   params, grads, Adam moments and fp32 masters all live sharded; no wrapper
   class, no engine.
3. **Sharded checkpointing** — ``accelerator.save_state`` writes per-host
   shard files for params AND optimizer state (no full-model gather), and
   ``load_state`` restores onto any mesh shape (save on fsdp=8, resume on
   fsdp=4).

Run (CPU smoke):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/llama_finetune_example.py --tiny --steps 8
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import accelerate_tpu.nn as nn  # noqa: E402
import accelerate_tpu.optim as optim  # noqa: E402
from accelerate_tpu import Accelerator  # noqa: E402
from accelerate_tpu.data_loader import batch_to_global_array  # noqa: E402
from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM  # noqa: E402
from accelerate_tpu.utils.dataclasses import ParallelismConfig  # noqa: E402


def build_model(args) -> LlamaForCausalLM:
    if args.model_path:
        from accelerate_tpu.utils.hf import from_pretrained

        model = from_pretrained(args.model_path, architecture="llama")
        print(f"loaded {model.num_parameters/1e6:.1f}M params from {args.model_path}")
        return model
    cfg = LlamaConfig.tiny() if args.tiny else LlamaConfig.llama2_7b_proxy()
    return LlamaForCausalLM(cfg)


def synthetic_batches(vocab: int, batch: int, seq: int, steps: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    for _ in range(steps):
        yield rng.integers(0, vocab, size=(batch, seq), dtype=np.int32)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--model_path", default=None, help="local HF Llama checkpoint dir")
    parser.add_argument("--tiny", action="store_true", help="tiny from-scratch config")
    parser.add_argument("--fsdp_size", type=int, default=0, help="0 = all devices")
    parser.add_argument("--batch_size", type=int, default=8, help="global batch")
    parser.add_argument("--seq_len", type=int, default=128)
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--lr", type=float, default=1e-4)
    parser.add_argument("--output_dir", default=None, help="save sharded checkpoint here")
    parser.add_argument("--resume_from_checkpoint", default=None)
    args = parser.parse_args()

    import jax

    fsdp = args.fsdp_size or len(jax.devices())
    nn.manual_seed(42)
    accelerator = Accelerator(
        parallelism_config=ParallelismConfig(fsdp_size=fsdp),
        mixed_precision="bf16",
    )
    model = build_model(args)
    optimizer = optim.AdamW(model.parameters(), lr=args.lr, weight_decay=0.1)
    model, optimizer = accelerator.prepare(model, optimizer)

    if args.resume_from_checkpoint:
        accelerator.load_state(args.resume_from_checkpoint)
        accelerator.print(f"resumed from {args.resume_from_checkpoint}")

    def train_step(ids):
        optimizer.zero_grad()
        out = model(ids, labels=ids)
        accelerator.backward(out["loss"])
        accelerator.clip_grad_norm_(model.parameters(), 1.0)
        optimizer.step()
        return out["loss"]

    step = accelerator.compile_step(train_step)
    vocab = model.config.vocab_size
    seq = min(args.seq_len, model.config.max_position_embeddings)
    for i, ids in enumerate(
        synthetic_batches(vocab, args.batch_size, seq, args.steps)
    ):
        loss = step(batch_to_global_array(ids, mesh=accelerator.mesh))
        if i % 5 == 0 or i == args.steps - 1:
            accelerator.print(f"step {i}: loss {float(loss):.4f}")

    if args.output_dir:
        # sharded by default on an fsdp mesh: per-host shard files for params
        # AND optimizer state; resume on any mesh shape via load_state
        path = accelerator.save_state(args.output_dir)
        accelerator.print(f"sharded checkpoint saved to {path}")

    accelerator.end_training()


if __name__ == "__main__":
    main()
