"""Shared filename / naming constants.

Mirrors the on-disk checkpoint naming contract of the reference
(``/root/reference/src/accelerate/utils/constants.py:18-31``) so checkpoints
written by either framework are recognisable, while the payload format here is
TPU-native (msgpack/safetensors pytrees rather than torch pickles).
"""

MODEL_NAME = "pytree_model"
OPTIMIZER_NAME = "optimizer"
SCHEDULER_NAME = "scheduler"
SAMPLER_NAME = "sampler"
RNG_STATE_NAME = "random_states"
CUSTOM_STATES_NAME = "custom_checkpoint"
PROFILE_PATTERN_NAME = "profile_{suffix}"

WEIGHTS_NAME = f"{MODEL_NAME}.safetensors"
WEIGHTS_INDEX_NAME = f"{MODEL_NAME}.safetensors.index.json"
OPTIMIZER_STATE_NAME = f"{OPTIMIZER_NAME}.msgpack"
SCHEDULER_STATE_NAME = f"{SCHEDULER_NAME}.json"
SAMPLER_STATE_NAME = f"{SAMPLER_NAME}.json"

# Default sequence pad multiple: MXU lane width is 128; padding sequence
# lengths to a multiple of 128 avoids XLA recompiles and keeps matmuls tiled.
TPU_PAD_MULTIPLE = 128

# Mesh axis names used across the framework.  One mesh, many layouts: data
# parallelism ("dp"), parameter/optimizer sharding a la ZeRO/FSDP ("fsdp"),
# tensor parallelism ("tp"), sequence/context parallelism ("sp"), expert
# parallelism ("ep"), pipeline stages ("pp").
MESH_AXIS_DP = "dp"
MESH_AXIS_FSDP = "fsdp"
MESH_AXIS_TP = "tp"
MESH_AXIS_SP = "sp"
MESH_AXIS_EP = "ep"
MESH_AXIS_PP = "pp"
ALL_MESH_AXES = (
    MESH_AXIS_DP,
    MESH_AXIS_FSDP,
    MESH_AXIS_TP,
    MESH_AXIS_SP,
    MESH_AXIS_EP,
    MESH_AXIS_PP,
)

# Environment-variable protocol between `accelerate-tpu launch` and child
# processes (reference: /root/reference/src/accelerate/utils/launch.py:98-325).
ACCELERATE_ENV_PREFIX = "ACCELERATE_"

SAFE_GLOBALS = ()
