"""Distributed text generation on a sharded causal LM.

TPU-native counterpart of the reference's distributed-inference examples
(/root/reference/examples/inference/distributed/phi2.py:1): there, each GPU
holds a full model copy and `PartialState.split_between_processes` splits the
prompt list; here the model itself is GSPMD-sharded over the chip mesh with
``shard_for_inference`` (every chip computes every prompt — the TPU-right way
to use aggregate HBM and ICI), while `split_between_processes` +
``gather_object`` still split prompt batches across *hosts* on a multi-host
pod, exactly like the reference splits across ranks.

The decode engine (models/generation.py) runs prefill + every decode step as
compiled XLA programs with a KV cache; ``--quantize 8|4`` decodes through
int8/int4 weight-only quantization on device.

Run (CPU smoke, 8 virtual chips):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/inference/distributed_generation.py --tiny

Run (TPU slice):
    python examples/inference/distributed_generation.py --model_path /path/to/llama
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import jax
import numpy as np

sys.path.append(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)

from accelerate_tpu import PartialState, shard_for_inference  # noqa: E402
from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM  # noqa: E402
from accelerate_tpu.utils.operations import gather_object  # noqa: E402
from accelerate_tpu.utils.random import set_seed  # noqa: E402

PROMPTS = [
    "I would like to",
    "hello how are you",
    "what is going on",
    "roses are red and",
    "welcome to the hotel",
]


def encode(text: str, pad_to: int) -> np.ndarray:
    """Byte-level prompt encoding (runs air-gapped; swap in your tokenizer).

    Left-pads with byte 0 so the batch is one static shape — each new
    (prompt_len, max_new_tokens) pair is one extra XLA compile, so padding
    to a single bucket keeps decode latency flat across prompts.
    """
    ids = np.frombuffer(text.encode("utf-8"), dtype=np.uint8).astype(np.int32)
    return np.pad(ids, (pad_to - len(ids), 0))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--model_path", default=None, help="HF Llama checkpoint dir")
    parser.add_argument("--tiny", action="store_true", help="tiny from-scratch config")
    parser.add_argument("--max_new_tokens", type=int, default=16)
    parser.add_argument("--quantize", type=int, default=None, choices=[8, 4])
    parser.add_argument("--temperature", type=float, default=0.0)
    args = parser.parse_args()

    set_seed(42)
    state = PartialState()

    if args.model_path:
        from accelerate_tpu.utils.hf import from_pretrained

        model = from_pretrained(args.model_path, architecture="llama")
    else:
        cfg = LlamaConfig.tiny() if args.tiny else LlamaConfig.llama2_7b_proxy()
        model = LlamaForCausalLM(cfg)
    model.eval()

    # GSPMD: weights live column/row-sharded over every chip (model.tp_plan);
    # XLA overlaps the all-gathers with compute. This replaces the
    # reference's per-rank device_map copy.
    model = shard_for_inference(model)
    state.print(f"mesh: {dict(model.atpu_mesh.shape)}")

    pad_to = 32
    # Across hosts, split the prompt list like the reference splits across
    # ranks (state.py split_between_processes; single host -> everything).
    with state.split_between_processes(PROMPTS) as local_prompts:
        batch = np.stack([encode(p, pad_to) for p in local_prompts])
        t0 = time.perf_counter()
        out = model.generate(
            batch,
            max_new_tokens=args.max_new_tokens,
            temperature=args.temperature,
            quantize_weights=args.quantize,
        )
        out = jax.device_get(out)
        dt = time.perf_counter() - t0
        completions = [
            bytes(b for b in row[pad_to:].tolist() if 0 < b < 256).decode(
                "utf-8", errors="replace"
            )
            for row in out
        ]

    # Bring every host's completions back to rank 0 (reference gather_object).
    completions = gather_object(completions)
    state.print(
        f"{len(completions)} completions, {args.max_new_tokens} new tokens each, "
        f"{dt:.2f}s (first call includes compile)"
    )
    for prompt, completion in zip(PROMPTS, completions):
        state.print(f"  {prompt!r} -> {completion!r}")


if __name__ == "__main__":
    main()
