"""Micro-benchmark: native host-runtime vs pure-Python/numpy equivalents.

Prints one JSON line per workload. These are HOST-side paths (batch assembly
feeding HBM, checkpoint shard IO) — the TPU is not involved; run anywhere.
Usage: python tools/native_bench.py
"""

import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from accelerate_tpu import native  # noqa: E402


def timeit(fn, reps=5):
    fn()  # warm
    t0 = time.perf_counter()
    for _ in range(reps):
        fn()
    return (time.perf_counter() - t0) / reps


def emit(name, python_s, native_s, note=""):
    print(json.dumps({
        "workload": name,
        "python_ms": round(python_s * 1e3, 2),
        "native_ms": round(native_s * 1e3, 2),
        "speedup": round(python_s / native_s, 2),
        "threads": native._threads_default(),
        "note": note,
    }))


def main():
    assert native.available(), native.load_error()
    rng = np.random.default_rng(0)

    # 1. LM batch assembly: gather 512 rows of 1024 int32 tokens from a
    # memmapped 200M-token buffer (the TokenDataset pretraining path).
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "tokens.bin")
        rows, seq = 200_000, 1024
        np.asarray(rng.integers(0, 50000, (rows, seq)), np.int32).tofile(path)
        mm = np.memmap(path, dtype=np.int32, mode="r", shape=(rows, seq))
        idx = rng.integers(0, rows, 512)
        # per-sample python loop + stack — what a generic Dataset/DataLoader does
        emit("token_batch_gather 512x1024 i32",
             timeit(lambda: np.stack([np.asarray(mm[i]) for i in idx])),
             timeit(lambda: native.gather_rows(mm, idx)),
             "memmap source")

    # 2. Collate: stack 256 float32 image-ish samples.  np.stack's copy loop
    # is already C, so the native win here comes only from threads>1 splitting
    # the batch; default_collate gates on that (data_loader.py).
    samples = [rng.random((3, 224, 224)).astype(np.float32) for _ in range(256)]
    emit("collate_stack 256x3x224x224 f32",
         timeit(lambda: np.stack(samples)),
         timeit(lambda: native.stack_rows(samples)),
         "wins only with threads>1")

    # 3. Ragged pad-stack: 512 variable-length token rows.
    ragged = [np.asarray(rng.integers(0, 50000, rng.integers(200, 1024)), np.int32)
              for _ in range(512)]

    def py_pad():
        ml = max(len(r) for r in ragged)
        out = np.full((len(ragged), ml), -100, np.int32)
        for i, r in enumerate(ragged):
            out[i, : len(r)] = r
        return out

    emit("pad_stack 512 ragged i32",
         timeit(py_pad),
         timeit(lambda: native.pad_stack(ragged, pad_value=-100)))

    # 4. Checkpoint shard write+read: 512 MB safetensors body.
    with tempfile.TemporaryDirectory() as d:
        from accelerate_tpu.native import st
        from safetensors.numpy import load_file as st_load
        from safetensors.numpy import save_file as st_save

        tensors = {f"w{i}": rng.random((1024, 1024)).astype(np.float32)
                   for i in range(128)}
        p_native = os.path.join(d, "n.safetensors")
        p_pkg = os.path.join(d, "p.safetensors")
        emit("safetensors_save 512MB",
             timeit(lambda: st_save(tensors, p_pkg), reps=3),
             timeit(lambda: st.save_file(tensors, p_native), reps=3))
        emit("safetensors_load 512MB",
             timeit(lambda: st_load(p_pkg), reps=3),
             timeit(lambda: st.load_file(p_native), reps=3))


if __name__ == "__main__":
    main()
