"""Feature: DDP comm-hook gradient compression.

Counterpart of /root/reference/examples/by_feature/ddp_comm_hook.py: the
reference registers an fp16/bf16 compression hook on the DDP gradient
all-reduce; here the SPMD analog is
``DistributedDataParallelKwargs(comm_hook=...)`` — synced gradients are cast
to the compression dtype at the backward boundary (half-width grad buffers
and downstream consumers; see Accelerator._apply_comm_hook for exactly what
this does and does not change about XLA's collective dtypes).  The
``powersgd``/``batched_powersgd`` values run rank-k compression with error
feedback instead of a cast (the reference's POWER_SGD hook, now living in
the unified compression layer ``parallel/compress.py`` behind the same
``CompressionPolicy`` surface as the quantized ZeRO-1 collectives — the
modern spelling is ``CompressionKwargs(policy="powersgd")`` /
``ACCELERATE_COMPRESSION=powersgd``, and this legacy kwarg resolves to the
identical policy object; docs/compression.md).  Lines marked `# New Code #`
are what this feature adds to nlp_example.py.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from nlp_example import get_dataloaders  # noqa: E402

import accelerate_tpu.nn as nn  # noqa: E402
import accelerate_tpu.optim as optim  # noqa: E402
from accelerate_tpu import Accelerator  # noqa: E402
from accelerate_tpu.models import BertConfig, BertForSequenceClassification  # noqa: E402

# New Code #
from accelerate_tpu.utils.dataclasses import DistributedDataParallelKwargs  # noqa: E402


def training_function(args):
    # New Code #
    # comm_hook="bf16"|"fp16" compresses synced grads; "no" disables
    handlers = []
    if args.comm_hook != "no":
        handlers.append(DistributedDataParallelKwargs(comm_hook=args.comm_hook))
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision, kwargs_handlers=handlers
    )
    nn.manual_seed(args.seed)
    train_dl, val_dl, vocab = get_dataloaders(accelerator, args.batch_size, args.seed)

    cfg = BertConfig.small() if args.small else BertConfig.base()
    cfg.vocab_size = max(cfg.vocab_size, vocab)
    model = BertForSequenceClassification(cfg)
    optimizer = optim.AdamW(model.parameters(), lr=args.lr)
    scheduler = optim.get_linear_schedule_with_warmup(
        optimizer, 100, len(train_dl) * args.num_epochs * accelerator.num_devices
    )
    model, optimizer, train_dl, val_dl, scheduler = accelerator.prepare(
        model, optimizer, train_dl, val_dl, scheduler
    )

    def train_step(batch):
        out = model(
            batch["input_ids"],
            attention_mask=batch["attention_mask"],
            token_type_ids=batch["token_type_ids"],
            labels=batch["labels"],
        )
        accelerator.backward(out["loss"])
        optimizer.step()
        scheduler.step()
        optimizer.zero_grad()
        return out["loss"]

    step = accelerator.compile_step(train_step)

    loss = None
    for epoch in range(args.num_epochs):
        model.train()
        for batch in train_dl:
            loss = step(batch)
        accelerator.print(f"epoch {epoch}: loss={float(loss.item()):.4f}")
    return model


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--mixed_precision", type=str, default="bf16", choices=["no", "fp16", "bf16"])
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--num_epochs", type=int, default=2)
    parser.add_argument("--lr", type=float, default=2e-5)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--small", action="store_true")
    # New Code #
    parser.add_argument(
        "--comm_hook",
        type=str,
        default="bf16",
        # powersgd/batched_powersgd: rank-k compression with error feedback
        # (utils/powersgd.py) — the reference's POWER_SGD hook analogs
        choices=["no", "fp16", "bf16", "powersgd", "batched_powersgd"],
    )
    args = parser.parse_args()
    training_function(args)


if __name__ == "__main__":
    main()
