from . import functional
from . import functional as F
from . import random
from .layers import (
    AvgPool2d,
    Conv2d,
    CrossEntropyLoss,
    Dropout,
    Embedding,
    GELU,
    Identity,
    LayerNorm,
    Linear,
    MaxPool2d,
    MSELoss,
    ReLU,
    RMSNorm,
    Sigmoid,
    SiLU,
    Softmax,
    Tanh,
)
from .module import Buffer, Module, ModuleDict, ModuleList, Parameter, Sequential
from .moe import MixtureOfExperts
from .random import manual_seed
from .tape import Tensor, backward, enable_grad, is_grad_enabled, no_grad, tape_op
