"""dtype-widen: accidental float64 on TPU paths, and bare widening of
quantized wire payloads.

TPUs have no f64 ALU: with x64 enabled, every float64 op is emulated at a
fraction of peak FLOPs and doubles HBM traffic; with x64 off (the JAX
default), a float64 dtype request silently truncates to f32 — either way the
author didn't get what they wrote.  Flagged: float64/double dtypes handed to
jnp constructors, ``.astype(jnp.float64)``, ``jnp.float64(...)`` casts, and
library code flipping ``jax_enable_x64`` globally.

The compression layer (``parallel/compress.py``) adds a second widening
hazard: a value returned by ``compress.quantize`` is a *wire payload* whose
magnitudes only mean anything together with its per-block scales — a stray
``payload.astype(float32)`` silently drops the scales and hands downstream
consumers garbage-scaled gradients.  Casts INSIDE the compression layer are
the sanctioned quantize/dequantize boundary, so the check is suppressed for
that module by policy (``_POLICY_MODULES`` — a rule-level scope, not inline
comments); everywhere else, widening a tracked payload local with
``.astype`` fires, and ``compress.dequantize(payload, scales)`` is the fix.
"""

from __future__ import annotations

import ast

from ..engine import Finding, Rule

_WIDE_ATTRS = {"jax.numpy.float64", "jax.numpy.double", "numpy.float64", "numpy.double"}
_WIDE_STRS = {"float64", "double", "f8", "<f8", ">f8"}
# jnp constructors whose dtype can also arrive positionally
_DTYPE_POS = {"zeros": 1, "ones": 1, "empty": 1, "asarray": 1, "array": 1, "full": 2}

# modules where quantize/dequantize casts are the sanctioned policy boundary:
# the payload-widening check below never fires inside them (policy-scoped
# suppression — the layer itself IS the dequantize implementation)
_POLICY_MODULES = ("parallel/compress.py",)


class DtypeWiden(Rule):
    id = "dtype-widen"
    kind = "reachability"
    description = "float64 promotion on a TPU path (jnp dtype, astype, or jax_enable_x64)"
    fix_hint = (
        "use float32 (or bfloat16) — TPUs have no f64 ALU, so x64 silently "
        "emulates at a large cost"
    )

    def _is_wide(self, module, node: ast.AST, allow_builtin_float: bool) -> bool:
        resolved = module.resolve(node)
        if resolved in _WIDE_ATTRS:
            return True
        if isinstance(node, ast.Constant) and node.value in _WIDE_STRS:
            return True
        if allow_builtin_float and isinstance(node, ast.Name) and node.id == "float":
            return True  # dtype=float means float64 under x64
        return False

    def _is_policy_module(self, module) -> bool:
        rel = module.rel_path.replace("\\", "/")
        return any(rel.endswith(p) for p in _POLICY_MODULES)

    @staticmethod
    def _scope_walk(root: ast.AST, skip_functions: bool):
        """Descendants of ``root``; with ``skip_functions`` the bodies of
        nested function defs are excluded (module scope must not see
        function locals — a same-named local elsewhere is NOT the payload)."""
        stack = list(ast.iter_child_nodes(root))
        while stack:
            node = stack.pop()
            if skip_functions and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def _check_payloads(self, module) -> list[Finding]:
        """Flag ``compress.quantize`` payload locals widened with a bare
        ``.astype`` — per SCOPE, so an unrelated same-named local in another
        function never fires.  A function scope includes its closures (an
        outer payload cast inside a nested def is still the payload); the
        resulting double visit of nested nodes is de-duplicated."""
        if self._is_policy_module(module):
            return []
        findings: list[Finding] = []
        seen: set[int] = set()
        scopes: list[tuple[ast.AST, bool]] = [(module.tree, True)] + [
            (node, False)
            for node in ast.walk(module.tree)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope, skip_functions in scopes:
            nodes = list(self._scope_walk(scope, skip_functions))
            payloads: set[str] = set()
            for node in nodes:
                if not (
                    isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)
                ):
                    continue
                resolved = module.resolve(node.value.func) or ""
                if not resolved.endswith("compress.quantize"):
                    continue
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        payloads.add(target.id)
                    elif (
                        isinstance(target, (ast.Tuple, ast.List))
                        and target.elts
                        and isinstance(target.elts[0], ast.Name)
                    ):
                        payloads.add(target.elts[0].id)
            if not payloads:
                continue
            for node in nodes:
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype"
                    and node.args
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in payloads
                    and id(node) not in seen
                ):
                    seen.add(id(node))
                    findings.append(
                        Finding(
                            self.id,
                            module.rel_path,
                            node.lineno,
                            node.col_offset,
                            "quantized wire payload cast with .astype() outside "
                            "the compression layer — the per-block scales are "
                            "discarded; use compress.dequantize(payload, scales)",
                        )
                    )
        return findings

    def check(self, module, ctx):
        findings = []

        def hit(node, msg):
            findings.append(
                Finding(self.id, module.rel_path, node.lineno, node.col_offset, msg)
            )

        findings.extend(self._check_payloads(module))
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            resolved = module.resolve(fn) or ""
            leaf = resolved.rsplit(".", 1)[-1]
            if resolved in ("jax.numpy.float64", "jax.numpy.double"):
                hit(node, f"jnp.{leaf}() cast — TPUs emulate f64; use jnp.float32")
            elif resolved.startswith("jax."):
                # dtype= kwarg on any jax/jnp call, plus positional dtype slots
                dtype_expr = None
                for kw in node.keywords:
                    if kw.arg == "dtype":
                        dtype_expr = kw.value
                if dtype_expr is None and leaf in _DTYPE_POS:
                    pos = _DTYPE_POS[leaf]
                    if len(node.args) > pos:
                        dtype_expr = node.args[pos]
                if dtype_expr is not None and self._is_wide(module, dtype_expr, True):
                    hit(
                        node,
                        f"float64 dtype passed to {leaf}() — TPUs emulate f64 "
                        "(or silently truncate with x64 off); use float32/bfloat16",
                    )
                if resolved == "jax.config.update" and node.args:
                    arg0 = node.args[0]
                    truthy = len(node.args) > 1 and not (
                        isinstance(node.args[1], ast.Constant) and not node.args[1].value
                    )
                    if (
                        isinstance(arg0, ast.Constant)
                        and arg0.value == "jax_enable_x64"
                        and truthy
                    ):
                        hit(
                            node,
                            "jax_enable_x64 flipped globally in library code — "
                            "every downstream op widens to f64 on TPU",
                        )
            elif isinstance(fn, ast.Attribute) and fn.attr == "astype" and node.args:
                # .astype(jnp.float64) is unambiguous; .astype(np.float64) only
                # matters inside traced code (host numpy f64 is fine)
                arg = node.args[0]
                if module.resolve(arg) in ("jax.numpy.float64", "jax.numpy.double"):
                    hit(node, ".astype(jnp.float64) — TPUs emulate f64; use float32")
                elif self._is_wide(module, arg, False):
                    reached = module.callgraph.reached
                    for info, _ in module.callgraph.traced_functions():
                        lo = info.node.lineno
                        hi = getattr(info.node, "end_lineno", lo)
                        if lo <= node.lineno <= hi and info.qualname in reached:
                            hit(
                                node,
                                ".astype(float64) inside traced code — TPUs "
                                "emulate f64; use float32",
                            )
                            break
        return findings
