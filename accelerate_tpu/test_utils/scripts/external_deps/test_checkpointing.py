"""Checkpoint/resume equivalence (analog of reference
test_utils/scripts/external_deps/test_checkpointing.py).

Trains a tiny GPT, snapshots mid-run with ``save_state``, keeps training to
the end (run A); then rebuilds everything fresh, ``load_state``s the
snapshot, and trains the same remaining steps (run B).  Every parameter,
optimizer moment, and the LR-schedule position must match run A exactly —
resume is bitwise, not approximate.  Also covers ``skip_first_batches``
mid-epoch resume (reference data_loader.py:1349).
"""

from __future__ import annotations

import os
import tempfile

import numpy as np

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator, set_seed
from accelerate_tpu.models import GPTConfig, GPTLMHeadModel
from accelerate_tpu.state import PartialState

STEPS_TOTAL = 8
STEPS_BEFORE = 3
BATCH, SEQ = 8, 32


def _build():
    set_seed(7)
    acc = Accelerator()
    cfg = GPTConfig(
        vocab_size=128, n_positions=SEQ, n_embd=32, n_layer=2, n_head=2, dropout=0.0
    )
    model = GPTLMHeadModel(cfg)
    opt = optim.AdamW(model.parameters(), lr=1e-3)
    sched = optim.lr_scheduler.StepLR(opt, step_size=2, gamma=0.5)
    model, opt, sched = acc.prepare(model, opt, sched)
    return acc, model, opt, sched


def _batches():
    rng = np.random.default_rng(3)
    return [
        rng.integers(0, 128, size=(BATCH, SEQ), dtype=np.int32)
        for _ in range(STEPS_TOTAL)
    ]


def _step(acc, model, opt, sched, ids):
    out = model(ids, labels=ids)
    acc.backward(out["loss"])
    opt.step()
    sched.step()
    opt.zero_grad()
    return float(out["loss"])


def _params_flat(model) -> dict[str, np.ndarray]:
    return {k: np.asarray(p.data) for k, p in model.named_parameters()}


def main():
    batches = _batches()

    with tempfile.TemporaryDirectory() as tmp:
        ckpt = os.path.join(tmp, "mid")

        # run A: straight through, snapshotting at STEPS_BEFORE
        acc, model, opt, sched = _build()
        for i in range(STEPS_TOTAL):
            if i == STEPS_BEFORE:
                acc.save_state(ckpt)
            _step(acc, model, opt, sched, batches[i])
        final_a = _params_flat(model)
        lr_a = opt.lr
        PartialState._reset_state()

        # run B: fresh everything, resume from the snapshot
        acc, model, opt, sched = _build()
        acc.load_state(ckpt)
        for i in range(STEPS_BEFORE, STEPS_TOTAL):
            _step(acc, model, opt, sched, batches[i])
        final_b = _params_flat(model)
        lr_b = opt.lr
        PartialState._reset_state()

    assert final_a.keys() == final_b.keys()
    for name in final_a:
        np.testing.assert_array_equal(
            final_a[name], final_b[name], err_msg=f"param {name} diverged after resume"
        )
    assert float(lr_a) == float(lr_b), (lr_a, lr_b)

    # mid-epoch resume: skip_first_batches yields exactly the tail
    acc = Accelerator()
    data = list(range(20))
    import torch.utils.data as tud

    dl = acc.prepare(tud.DataLoader(data, batch_size=2))
    skipped = acc.skip_first_batches(dl, 3)
    seen = [int(np.asarray(b).ravel()[0]) for b in skipped]
    full = [int(np.asarray(b).ravel()[0]) for b in dl]
    assert seen == full[3:], (seen, full[3:])
    PartialState._reset_state()

    print("All checkpointing checks passed")


if __name__ == "__main__":
    main()
