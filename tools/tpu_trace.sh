#!/bin/bash
# Standalone jax.profiler trace of the flagship step (10 steady-state steps)
# — extracted from tpu_perf_sweep.sh so the when-up queue can run it without
# repeating the batch/block sweeps already measured in round 3.
# Usage: bash tools/tpu_trace.sh [outdir]
set -u
OUT=$(realpath -m "${1:-/tmp/tpu_trace}")
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

timeout 1200 python - "$OUT" <<'EOF' 2>"$OUT/err_profile.log"
import sys, os
sys.path.insert(0, os.getcwd())
out = sys.argv[1]
import jax, jax.numpy as jnp, numpy as np
import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator
from accelerate_tpu.data_loader import batch_to_global_array
from accelerate_tpu.models import GPTConfig, GPTLMHeadModel

nn.manual_seed(0)
acc = Accelerator(mixed_precision="bf16")
model = GPTLMHeadModel(GPTConfig.small())
opt = optim.AdamW(model.parameters(), lr=3e-4)
model, opt = acc.prepare(model, opt)

def fn(ids):
    opt.zero_grad(); o = model(ids, labels=ids); acc.backward(o["loss"]); opt.step(); return o["loss"]

step = acc.compile_step(fn)
ids = batch_to_global_array(
    jnp.asarray(np.random.default_rng(0).integers(0, 50304, (12, 1024)), jnp.int32),
    mesh=acc.mesh)
for _ in range(5):
    step(ids)
float(step(ids))
jax.profiler.start_trace(os.path.join(out, "trace"))
for _ in range(10):
    loss = step(ids)
float(loss)
jax.profiler.stop_trace()
print({"profile": os.path.join(out, "trace"), "final_loss": round(float(loss), 3)})
EOF
echo "trace written under $OUT/trace"
