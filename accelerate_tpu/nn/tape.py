"""Eager autodiff tape over JAX — the torch→JAX bridge core.

The reference wraps an *imperative* torch loop (``loss.backward()``,
``optimizer.step()``); JAX wants one pure ``train_step``.  This module closes
the gap (SURVEY.md §7 hard-part #1/#2) without porting torch: a lightweight
:class:`Tensor` wrapper records every op's ``jax.vjp`` closure on a tape, so

* eagerly, ``Tensor.backward()`` walks the tape and fills ``param.grad`` —
  imperative semantics for debugging and unmodified reference-style loops;
* under ``Accelerator``'s step capture, the same Python code runs inside one
  ``jax.jit`` trace: the tape ops become traced ops, the vjp closures compose
  into the backward graph, and XLA fuses forward+backward+update into a single
  TPU program — the performance path.

Because each op's transpose comes from ``jax.vjp``, gradients are exactly
JAX's, not a hand-written ruleset.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Iterable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class _TapeState(threading.local):
    def __init__(self):
        self.grad_enabled = True


_state = _TapeState()


class no_grad:
    """Context manager / decorator disabling tape recording (torch parity)."""

    def __enter__(self):
        self.prev = _state.grad_enabled
        _state.grad_enabled = False
        return self

    def __exit__(self, *exc):
        _state.grad_enabled = self.prev
        return False

    def __call__(self, fn):
        def wrapper(*args, **kwargs):
            with no_grad():
                return fn(*args, **kwargs)

        return wrapper


class enable_grad(no_grad):
    def __enter__(self):
        self.prev = _state.grad_enabled
        _state.grad_enabled = True
        return self


def is_grad_enabled() -> bool:
    return _state.grad_enabled


class Node:
    """One tape entry: output ← fn(inputs) with its vjp closure."""

    __slots__ = ("inputs", "vjp_fn")

    def __init__(self, inputs: Sequence["Tensor"], vjp_fn: Callable):
        self.inputs = inputs
        self.vjp_fn = vjp_fn


def _unwrap(x):
    return x.data if isinstance(x, Tensor) else x


class Tensor:
    """An array with an optional autograd tape behind it.

    Not a jax pytree node on purpose: jitted code sees only raw ``.data``
    arrays; the wrapper lives in Python land.
    """

    __slots__ = ("data", "requires_grad", "grad", "_node")

    def __init__(self, data, requires_grad: bool = False, _node: Optional[Node] = None):
        from .meta import MetaArray

        if isinstance(data, Tensor):
            data = data.data
        self.data = (
            data if isinstance(data, (jax.Array, MetaArray)) else jnp.asarray(data)
        )
        self.requires_grad = requires_grad
        self.grad: Optional[jax.Array] = None
        self._node = _node

    # -- array-ish surface --------------------------------------------------
    @property
    def shape(self):
        return self.data.shape

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def ndim(self):
        return self.data.ndim

    @property
    def size(self):
        return self.data.size

    @property
    def T(self):
        return tape_op(lambda x: x.T, self)

    def __len__(self):
        return self.data.shape[0]

    def __array__(self, dtype=None, copy=None):
        """np/jnp.asarray(tensor) → the data, NOT gradient-tracked.

        Without this hook the array constructors walk the Tensor as a
        nested Python sequence — one ``__getitem__`` tape op per element,
        minutes for a modest batch (found via a hung BERT forward whose
        input_ids were wrapped in a Tensor).  Deliberately NOT
        ``__jax_array__``: that hook additionally changes jax.Array binary-
        op dispatch so ``raw_jnp <op> tensor`` unwraps instead of deferring
        to the Tensor's reflected op — silently detaching the tape
        (verified; reflected-op dispatch is covered by tests).
        """
        arr = np.asarray(jax.device_get(self.data))
        return arr.astype(dtype) if dtype is not None else arr

    def __repr__(self):
        grad_str = ", requires_grad=True" if self.requires_grad else ""
        return f"Tensor({self.data!r}{grad_str})"

    def numel(self) -> int:
        return int(self.data.size)

    def numpy(self):
        return np.asarray(jax.device_get(self.data))

    def item(self):
        return self.data.item()

    # scalar conversions (torch parity: float(loss), int(count), if tensor:)
    def __float__(self) -> float:
        return float(self.data)

    def __int__(self) -> int:
        return int(self.data)

    def __bool__(self) -> bool:
        return bool(self.data)

    def __format__(self, spec: str) -> str:
        if self.data.ndim == 0:
            return format(self.data.item(), spec)
        return format(self.data, spec)

    def tolist(self):
        return self.numpy().tolist()

    def detach(self) -> "Tensor":
        return Tensor(self.data)

    def clone(self) -> "Tensor":
        return tape_op(lambda x: x + 0, self)

    def astype(self, dtype) -> "Tensor":
        return tape_op(lambda x: x.astype(dtype), self)

    # torch-spelling conveniences
    def float(self):
        return self.astype(jnp.float32)

    def to(self, dtype):
        return self.astype(dtype)

    def cpu(self):
        return self

    # -- arithmetic ---------------------------------------------------------
    def __add__(self, other):
        return tape_op(jnp.add, self, other)

    __radd__ = __add__

    def __sub__(self, other):
        return tape_op(jnp.subtract, self, other)

    def __rsub__(self, other):
        return tape_op(jnp.subtract, other, self)

    def __mul__(self, other):
        return tape_op(jnp.multiply, self, other)

    __rmul__ = __mul__

    def __truediv__(self, other):
        return tape_op(jnp.divide, self, other)

    def __rtruediv__(self, other):
        return tape_op(jnp.divide, other, self)

    def __matmul__(self, other):
        return tape_op(jnp.matmul, self, other)

    def __rmatmul__(self, other):
        return tape_op(jnp.matmul, other, self)

    def __pow__(self, other):
        return tape_op(jnp.power, self, other)

    def __neg__(self):
        return tape_op(jnp.negative, self)

    def __getitem__(self, idx):
        idx = _unwrap(idx) if isinstance(idx, Tensor) else idx
        return tape_op(lambda x: x[idx], self)

    # comparisons produce plain (non-diff) tensors
    def __eq__(self, other):  # noqa: E721
        return Tensor(self.data == _unwrap(other))

    def __ne__(self, other):
        return Tensor(self.data != _unwrap(other))

    def __lt__(self, other):
        return Tensor(self.data < _unwrap(other))

    def __le__(self, other):
        return Tensor(self.data <= _unwrap(other))

    def __gt__(self, other):
        return Tensor(self.data > _unwrap(other))

    def __ge__(self, other):
        return Tensor(self.data >= _unwrap(other))

    def __hash__(self):
        return id(self)

    # -- shape ops ----------------------------------------------------------
    def reshape(self, *shape):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return tape_op(lambda x: x.reshape(shape), self)

    view = reshape

    def transpose(self, *axes):
        if not axes:
            axes = None
        elif len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return tape_op(lambda x: jnp.transpose(x, axes), self)

    def swapaxes(self, a, b):
        return tape_op(lambda x: jnp.swapaxes(x, a, b), self)

    def squeeze(self, axis=None):
        return tape_op(lambda x: jnp.squeeze(x, axis), self)

    def unsqueeze(self, axis):
        return tape_op(lambda x: jnp.expand_dims(x, axis), self)

    def flatten(self, start_dim=0, end_dim=-1):
        def _flatten(x):
            shape = x.shape
            end = end_dim % x.ndim
            new_shape = shape[:start_dim] + (-1,) + shape[end + 1 :]
            return x.reshape(new_shape)

        return tape_op(_flatten, self)

    # -- reductions ---------------------------------------------------------
    def sum(self, axis=None, keepdims=False):
        return tape_op(lambda x: jnp.sum(x, axis=axis, keepdims=keepdims), self)

    def mean(self, axis=None, keepdims=False):
        return tape_op(lambda x: jnp.mean(x, axis=axis, keepdims=keepdims), self)

    def max(self, axis=None, keepdims=False):
        return tape_op(lambda x: jnp.max(x, axis=axis, keepdims=keepdims), self)

    def min(self, axis=None, keepdims=False):
        return tape_op(lambda x: jnp.min(x, axis=axis, keepdims=keepdims), self)

    def var(self, axis=None, keepdims=False):
        return tape_op(lambda x: jnp.var(x, axis=axis, keepdims=keepdims), self)

    def argmax(self, axis=None):
        return Tensor(jnp.argmax(self.data, axis=axis))

    def argmin(self, axis=None):
        return Tensor(jnp.argmin(self.data, axis=axis))

    # -- elementwise --------------------------------------------------------
    def exp(self):
        return tape_op(jnp.exp, self)

    def log(self):
        return tape_op(jnp.log, self)

    def sqrt(self):
        return tape_op(jnp.sqrt, self)

    def tanh(self):
        return tape_op(jnp.tanh, self)

    def abs(self):
        return tape_op(jnp.abs, self)

    def clip(self, min=None, max=None):
        return tape_op(lambda x: jnp.clip(x, min, max), self)

    # -- autograd -----------------------------------------------------------
    def backward(self, gradient=None) -> None:
        """Reverse-walk the tape, accumulating into ``.grad`` of leaves."""
        if gradient is None:
            if self.data.ndim != 0:
                raise RuntimeError(
                    "backward() on a non-scalar requires an explicit `gradient`"
                )
            gradient = jnp.ones_like(self.data)
        else:
            gradient = _unwrap(gradient)
        backward(self, gradient)


def tape_op(fn: Callable, *inputs) -> "Tensor":
    """Run ``fn`` (single array out) on raw arrays; record its vjp if any
    input needs grad."""
    raws = tuple(_unwrap(x) for x in inputs)
    tensor_inputs = [x for x in inputs if isinstance(x, Tensor)]
    needs_grad = _state.grad_enabled and any(
        t.requires_grad or t._node is not None for t in tensor_inputs
    )
    if not needs_grad:
        return Tensor(fn(*raws))
    out, vjp_fn = jax.vjp(fn, *raws)
    return Tensor(out, _node=Node(tuple(inputs), vjp_fn))


def backward(root: Tensor, root_grad) -> None:
    """Reverse-mode accumulation over the recorded tape.

    Topological order via iterative DFS (no recursion limits on deep nets).
    Multi-output nodes are rare (we currently emit per-output nodes that share
    a vjp; cotangents for sibling outputs are zero).
    """
    # 1. topo-sort nodes reachable from root
    topo: list[Tensor] = []
    visited: set[int] = set()
    stack: list[tuple[Tensor, bool]] = [(root, False)]
    while stack:
        tensor, processed = stack.pop()
        if processed:
            topo.append(tensor)
            continue
        if id(tensor) in visited:
            continue
        visited.add(id(tensor))
        stack.append((tensor, True))
        if tensor._node is not None:
            for inp in tensor._node.inputs:
                if isinstance(inp, Tensor) and id(inp) not in visited:
                    if inp._node is not None or inp.requires_grad:
                        stack.append((inp, False))

    # 2. reverse accumulate
    grads: dict[int, jax.Array] = {id(root): root_grad}
    for tensor in reversed(topo):
        g = grads.pop(id(tensor), None)
        if g is None:
            continue
        if tensor.requires_grad:
            tensor.grad = g if tensor.grad is None else tensor.grad + g
        node = tensor._node
        if node is None:
            continue
        input_grads = node.vjp_fn(g)
        for inp, ig in zip(node.inputs, input_grads):
            if not isinstance(inp, Tensor) or ig is None:
                continue
            if getattr(ig, "dtype", None) == jax.dtypes.float0:
                continue  # integer-typed input (e.g. token ids): no gradient
            if not (inp.requires_grad or inp._node is not None):
                continue
            key = id(inp)
            if key in grads:
                grads[key] = grads[key] + ig
            else:
                grads[key] = ig


def grad_of(params: Iterable[Tensor]) -> list[Optional[jax.Array]]:
    return [p.grad for p in params]
