"""Rank-aware logging (reference: /root/reference/src/accelerate/logging.py).

``get_logger(__name__)`` returns an adapter whose records can be restricted to
the main process (``main_process_only=True``, the default behaviour of the
reference's MultiProcessAdapter :22) or emitted once per process in process
order (``in_order=True``).
"""

from __future__ import annotations

import functools
import logging
import os


class MultiProcessAdapter(logging.LoggerAdapter):
    @staticmethod
    def _should_log(main_process_only: bool) -> bool:
        from .state import PartialState

        state = PartialState()
        return not main_process_only or state.is_main_process

    def log(self, level, msg, *args, **kwargs):
        if not self.isEnabledFor(level):
            return
        from .state import PartialState

        main_process_only = kwargs.pop("main_process_only", True)
        in_order = kwargs.pop("in_order", False)
        kwargs.setdefault("stacklevel", 2)
        state = PartialState()
        # in_order comes from the caller's kwargs, identical on every rank;
        # the flow-insensitive taint fixpoint overtaints it through the later
        # `msg, kwargs = self.process(...)` reassignment under _should_log
        # (taint born below a read still poisons it — docs/graftlint.md)
        # graftlint: disable=collective-divergence -- overtaint, guard is rank-symmetric
        if in_order and state.num_processes > 1:
            for i in range(state.num_processes):
                if i == state.process_index:
                    msg, kw = self.process(msg, kwargs)
                    self.logger.log(level, msg, *args, **kw)
                state.wait_for_everyone()
            return
        if self._should_log(main_process_only):
            msg, kwargs = self.process(msg, kwargs)
            self.logger.log(level, msg, *args, **kwargs)

    @functools.lru_cache(None)
    def warning_once(self, *args, **kwargs):
        self.warning(*args, **kwargs)


def get_logger(name: str, log_level: str | None = None) -> MultiProcessAdapter:
    if log_level is None:
        log_level = os.environ.get("ACCELERATE_LOG_LEVEL", None)
    logger = logging.getLogger(name)
    if log_level is not None:
        logger.setLevel(log_level.upper())
        logger.root.setLevel(log_level.upper())
    return MultiProcessAdapter(logger, {})
