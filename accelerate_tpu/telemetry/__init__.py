"""Runtime telemetry for the capture path (``accelerator.telemetry``).

Four pillars, all default-OFF and zero-overhead when off:

1. **Step-phase timing** (`timeline.py`) — every ``CapturedStep.__call__``
   records dataloader-wait / assembly / trace / compile / dispatch ms into a
   ring-buffered :class:`~.timeline.StepTimeline`, with
   ``jax.profiler.TraceAnnotation`` spans around each phase so xprof traces
   collected through ``accelerator.profile()`` show named capture phases.
2. **Recompile forensics** (`recompile.py`) — every new compiled variant is
   diffed against the previous cache key and emits a
   :class:`~.recompile.RecompileEvent` naming exactly what moved (arg
   shape/dtype, treedef, ``sync_gradients``, training mode, state structure /
   donation split, input-layout drift).
3. **Resource accounting** (`resources.py`) — per-device live HBM bytes from
   ``jax.live_arrays()`` plus per-program ``memory_analysis()`` /
   ``cost_analysis()`` (FLOPs, bytes accessed, collective bytes) sampled at
   capture and on demand.
4. **Export** (`export.py`) — events flow to the existing ``GeneralTracker``
   fleet through :class:`TelemetryTracker`, or to a schema'd JSONL file that
   ``tools/telemetry_report.py`` renders.
5. **Device-time attribution** (`profiler.py`) — every Nth step
   (``TelemetryKwargs(profile_every_n=...)``, default off) the dispatch runs
   inside a ``jax.profiler`` trace session parsed into a
   :class:`~.profiler.DeviceStepRecord` (per-device busy/idle,
   compute/collective/transfer split, top ops, MFU), joined 1:1 to the
   host-side ``StepRecord`` by step index.
6. **Fleet aggregation** (`aggregate.py`) — rank-0 ``gather_object`` merge
   of every hub's records with per-rank skew statistics
   (``Telemetry.aggregate_fleet``, collective; ``end_training`` calls it on
   multi-process runs so the JSONL dump is fleet-wide).
7. **Live metrics endpoint** (`metrics.py`) — a stdlib HTTP thread serving
   Prometheus text (``TelemetryKwargs(metrics_port=...)`` /
   ``Telemetry.serve_metrics()``): step-phase timings, recompile/fault
   counters, collective bytes, device-time gauges, and any registered
   provider (the decode service self-registers its ``metrics()`` snapshot).
8. **Black-box forensics** (`flightrec.py` / `watchdog.py` /
   `trace_export.py`) — the flight recorder is the ONE exception to the
   default-off convention: an always-on, bounded, per-process event ring
   (step dispatches, collective-sequence ticks, fleet/serving/checkpoint
   phases) that the default-off hang watchdog dumps — with faulthandler
   stacks — to a per-rank JSON on stall/signal/exit, and
   ``tools/blackbox_report.py`` merges across ranks by collective sequence
   number.  ``trace_export.py`` joins the ring with the host/device step
   records into one Chrome/Perfetto timeline.

Enable with ``ACCELERATE_TELEMETRY=1`` or
``Accelerator(kwargs_handlers=[TelemetryKwargs(enabled=True)])``.  With the
knob off (the default), ``CapturedStep.__call__`` executes the identical code
path as before this subsystem existed — the only cost anywhere is a
``None``-check.  Docs: docs/telemetry.md.
"""

from __future__ import annotations

import time
from collections import deque
from contextlib import contextmanager
from typing import Optional

from .profiler import DeviceStepRecord
from .recompile import RecompileEvent, diff_keys, key_id
from .resources import (
    CollectiveRecord,
    KernelRecord,
    ProgramRecord,
    ResourceSample,
    program_stats,
    sample_live,
)
from .timeline import PHASES, StepRecord, StepTimeline

SCHEMA_VERSION = 1

# the active enabled Telemetry instance — fallback wait-time sink for data
# loaders never prepared through an Accelerator (prepared loaders carry a
# pinned hub instead); None when telemetry is off — every producer-side hook
# is gated on that None
_ACTIVE: Optional["Telemetry"] = None


def current_telemetry() -> Optional["Telemetry"]:
    return _ACTIVE


def _set_active(telemetry: Optional["Telemetry"]) -> None:
    global _ACTIVE
    _ACTIVE = telemetry


class Telemetry:
    """Per-Accelerator telemetry hub; the enabled instance is also published
    module-wide for producers (data loader) that have no accelerator handle."""

    def __init__(self, handler=None):
        if handler is None:
            from ..utils.dataclasses import TelemetryKwargs

            handler = TelemetryKwargs()
        self.enabled = bool(handler.enabled)
        self.annotate_spans = bool(handler.annotate_spans)
        self.resource_sampling = bool(handler.sample_resources)
        self.jsonl_path = handler.jsonl_path
        self.timeline = StepTimeline(capacity=handler.timeline_size)
        self.recompile_events: deque[RecompileEvent] = deque(maxlen=handler.max_events)
        self.program_records: deque[ProgramRecord] = deque(maxlen=handler.max_events)
        self.resource_samples: deque[ResourceSample] = deque(maxlen=handler.max_events)
        # per-policy dp-collective-bytes attribution (parallel/compress.py),
        # recorded at prepare() time — the bench A/B denominator
        self.collective_records: deque[CollectiveRecord] = deque(
            maxlen=handler.max_events
        )
        # resilience subsystem events (init/retry/rollback/preemption),
        # already kind-tagged dicts — see resilience/__init__.py
        self.resilience_events: deque[dict] = deque(maxlen=handler.max_events)
        # serving subsystem events (per-step occupancy/queue depth, per-
        # request TTFT/TPOT completions) — see serving/scheduler.py
        self.serving_events: deque[dict] = deque(maxlen=handler.max_events)
        # serving fault-tolerance events (decode retry, requeue, drain,
        # resume, recovered admissions) — see serving/recovery.py and
        # docs/serving.md §fault tolerance
        self.serving_recovery_events: deque[dict] = deque(maxlen=handler.max_events)
        # AOT executable cache events (hit/miss/store/warm with cause,
        # bytes, load vs avoided compile ms) — see native/aot_cache.py
        self.aot_cache_events: deque[dict] = deque(maxlen=handler.max_events)
        # elastic fleet runtime events (host_lost/restore_vote/resize,
        # kind="fleet_event") plus the periodic mid-run skew records
        # (kind="fleet") the aggregate cadence appends — see fleet/
        self.fleet_events: deque[dict] = deque(maxlen=handler.max_events)
        # armed Pallas hot-path kernels (docs/kernels.md), recorded at
        # prepare() like the collective-bytes attribution
        self.kernel_records: deque[KernelRecord] = deque(maxlen=handler.max_events)
        # compiled-variant key id -> {hlo op name -> atpu phase}: parsed
        # from the program's HLO metadata at build when sampling is armed,
        # joined by record_device_step into the per-phase device split
        self._scope_maps: dict = {}
        # native Prometheus histogram of replay step latency (metrics.py):
        # cumulative _bucket series for the endpoint instead of
        # point-in-time percentiles; observation is two int bumps per step
        from .metrics import LatencyHistogram

        self.step_hist = LatencyHistogram()
        # sampled device-time attribution (profiler.py): a DeviceStepRecord
        # per sampled step, joined to the host StepRecord by step index;
        # profiler is None unless the cadence knob armed it — the unsampled
        # hot path pays one None-check in CapturedStep.__call__
        self.profile_every_n = int(getattr(handler, "profile_every_n", 0) or 0)
        self.device_records: deque[DeviceStepRecord] = deque(
            maxlen=handler.max_events
        )
        self.profiler = None
        if self.enabled and self.profile_every_n > 0:
            from .profiler import StepProfiler

            profile_dir = getattr(handler, "profile_dir", None)
            self.profiler = StepProfiler(
                self.profile_every_n,
                base_dir=profile_dir,
                # a user-pinned dir means they want the raw traces on disk;
                # the default tempdir traces are deleted after parsing
                keep_traces=profile_dir is not None,
            )
        self.recompiles_total = 0
        self.steps_total = 0
        # fleet aggregation (aggregate.py): set by aggregate_fleet() on the
        # main rank — the JSONL dump then describes every rank, not one
        self._fleet_records: Optional[list] = None
        # first step index NOT yet covered by a periodic fleet tick: each
        # tick gathers only newer replay records, so the collective payload
        # is the delta and the skew record describes the CURRENT window
        self._fleet_agg_mark = 0
        # live metrics endpoint (metrics.py): providers registered here are
        # rendered by whatever MetricsServer is attached to this hub
        self._metrics_providers: list = []
        # /healthz readiness sources (metrics.py §healthz): fn() -> dict
        # with a "ready" bool; the endpoint ANDs them into one 200/503
        self._health_providers: list = []
        self.metrics_server = None
        self._dataloader_wait_ms = 0.0
        # wait that batches consumed OUTSIDE any captured step incurred
        # (eager eval epochs, early-broken loops) — discarded from step
        # attribution at loader-epoch end instead of dumped onto the next
        # captured step's record (docs/telemetry.md)
        self.eager_dataloader_wait_ms = 0.0
        self._wait_by_owner: dict = {}
        # export queue: every record lands here once, drained by the
        # TelemetryTracker bridge / flush(); bounded so an undrained run
        # cannot grow without limit.  Only the bridge consumes it, so
        # enqueueing (and the per-record to_dict()) is skipped entirely
        # until one attaches — sink-less runs like bench's primary loop pay
        # zero per-step export work (ROADMAP item)
        self._export_queue: deque[dict] = deque(maxlen=4096)
        self._export_sink = False
        self._drains_total = 0
        # latest-constructed wins the module slot: a later telemetry-off
        # Accelerator must clear it, or its data loaders keep crediting
        # wait time to the previous run's (possibly defunct) instance
        displaced = _ACTIVE
        _set_active(self if self.enabled else None)
        # black-box forensics (flightrec.py/watchdog.py): the recorder is
        # process-global and always-on; the watchdog arms from its knob
        # INDEPENDENTLY of `enabled` — hang forensics must not require the
        # full telemetry pipeline (docs/telemetry.md §watchdog)
        from . import flightrec as _flightrec

        self.flightrec = _flightrec.recorder()
        self.watchdog = None
        self.trace_export_path = getattr(handler, "trace_export_path", None)
        watchdog_s = getattr(handler, "watchdog_s", None)
        if watchdog_s:
            from .watchdog import HangWatchdog

            self.watchdog = HangWatchdog(
                timeout_s=watchdog_s,
                dump_dir=getattr(handler, "blackbox_dir", None) or "blackbox",
                recorder=self.flightrec,
            ).start()
        metrics_port = getattr(handler, "metrics_port", None)
        if self.enabled and metrics_port is not None:
            if displaced is not None and displaced.metrics_server is not None:
                # latest-constructed wins the endpoint too: the displaced
                # hub's server (typically on the same env-pinned port) would
                # otherwise squat the bind and serve frozen counters for the
                # rest of the process
                displaced.close_metrics()
            self.serve_metrics(port=metrics_port)

    # -- spans ---------------------------------------------------------------
    @contextmanager
    def span(self, name: str):
        """xprof-visible phase span (``jax.profiler.TraceAnnotation``); a
        no-op region when span annotation is off."""
        if not self.annotate_spans:
            yield
            return
        import jax

        with jax.profiler.TraceAnnotation(name):
            yield

    # -- producers -----------------------------------------------------------
    def record_dataloader_wait(self, ms: float, owner=None) -> None:
        """Host time a loader spent producing one batch.  ``owner`` (the
        loader) keys the batch-scoped attribution: wait still pending when
        that loader's epoch ends was incurred by batches no captured step
        consumed, and is discarded rather than billed to the next step."""
        self._dataloader_wait_ms += ms
        if owner is not None:
            self._wait_by_owner[owner] = self._wait_by_owner.get(owner, 0.0) + ms

    def pop_dataloader_wait_ms(self) -> float:
        ms, self._dataloader_wait_ms = self._dataloader_wait_ms, 0.0
        if self._wait_by_owner:
            self._wait_by_owner.clear()
        return ms

    def discard_dataloader_wait(self, owner) -> float:
        """Epoch-end settlement for one loader: whatever wait it recorded
        that no captured step popped belongs to batches consumed *outside*
        the capture path (an eager eval epoch, an early-broken loop) — move
        it to ``eager_dataloader_wait_ms`` so the next captured step's
        record shows only its own batch's wait (docs/telemetry.md)."""
        ms = self._wait_by_owner.pop(owner, 0.0)
        if ms:
            self._dataloader_wait_ms = max(0.0, self._dataloader_wait_ms - ms)
            self.eager_dataloader_wait_ms += ms
        return ms

    def next_step_index(self) -> int:
        """Global captured-call counter (across every CapturedStep)."""
        index = self.steps_total
        self.steps_total += 1
        return index

    def record_step(self, record: StepRecord) -> None:
        self.timeline.append(record)
        if not record.built:
            # replay latencies only: a build's trace+compile would park the
            # whole histogram mass in the top bucket and say nothing about
            # the steady state the SLO cares about
            self.step_hist.observe(record.total_ms)
        if self._export_sink:
            self._export_queue.append(record.to_dict())

    def record_recompile(self, event: RecompileEvent) -> None:
        self.recompiles_total += 1
        self.recompile_events.append(event)
        if self._export_sink:
            self._export_queue.append(event.to_dict())

    def record_program(self, key, label: str, compiled) -> ProgramRecord:
        record = ProgramRecord(key=key_id(key), label=label, stats=program_stats(compiled))
        self.program_records.append(record)
        if self.profiler is not None:
            # per-phase device attribution (docs/telemetry.md): the HLO
            # text is the only place the atpu named scopes survive to —
            # CPU/TPU trace events carry bare op names — so snapshot the
            # op->scope map per variant while the compiled handle is here
            from .profiler import scope_map_from_compiled

            self._scope_maps[record.key] = scope_map_from_compiled(compiled)
            if len(self._scope_maps) > len(self.program_records) + 8:
                # the deque rolls old program records off at max_events;
                # maps for rolled-off variants must roll too (each holds
                # thousands of op names — a churning long-lived process
                # would otherwise leak them for its lifetime)
                live = {p.key for p in self.program_records}
                for stale in [k for k in self._scope_maps if k not in live]:
                    del self._scope_maps[stale]
        if self._export_sink:
            self._export_queue.append(record.to_dict())
        return record

    def record_kernel(self, payload: dict) -> None:
        """Armed Pallas-kernel attribution (docs/kernels.md), kind-tagged
        ``"kernel"`` into the retained history and export stream — one
        record per armed kernel, written at ``prepare()``."""
        if not self.enabled:
            return
        stats = dict(payload)
        record = KernelRecord(kernel=stats.pop("kernel", "?"), stats=stats)
        self.kernel_records.append(record)
        if self._export_sink:
            self._export_queue.append(record.to_dict())

    def record_collectives(self, summary: dict) -> CollectiveRecord:
        """dp-axis collective-bytes attribution for one optimizer's update
        (``parallel.compress.collective_bytes`` output), kind-tagged
        ``"collectives"`` into the retained history and export stream."""
        stats = dict(summary)
        record = CollectiveRecord(policy=stats.pop("policy", "none"), stats=stats)
        self.collective_records.append(record)
        if self._export_sink:
            self._export_queue.append(record.to_dict())
        return record

    def record_resilience(self, payload: dict) -> None:
        """Resilience event (init report, dispatch retry, rollback,
        preemption, drain) — kind-tagged into the same retained history and
        export stream as the capture-path records."""
        if not self.enabled:
            return
        record = dict(payload)
        record["kind"] = "resilience"
        self.resilience_events.append(record)
        if self._export_sink:
            self._export_queue.append(dict(record))

    def record_serving(self, payload: dict) -> None:
        """Serving event (step occupancy, request completion, admission
        stall) from the decode service — kind-tagged ``"serving"`` into the
        same retained history and export stream as the capture records."""
        if not self.enabled:
            return
        record = dict(payload)
        record["kind"] = "serving"
        self.serving_events.append(record)
        if self._export_sink:
            self._export_queue.append(dict(record))

    def record_serving_recovery(self, payload: dict) -> None:
        """Serving fault-tolerance event (decode retry, exhaustion
        requeue, preemption drain, journal resume, recovered admission)
        from the decode service — kind-tagged ``"serving_recovery"`` into
        the same retained history and export stream as the capture records
        (docs/serving.md §fault tolerance)."""
        if not self.enabled:
            return
        record = dict(payload)
        record["kind"] = "serving_recovery"
        self.serving_recovery_events.append(record)
        if self._export_sink:
            self._export_queue.append(dict(record))

    def record_aot_cache(self, payload: dict) -> None:
        """AOT executable cache event (hit/miss/store/warm with cause,
        bytes, load_ms vs avoided compile_ms) — kind-tagged ``"aot_cache"``
        into the same retained history and export stream as the capture
        records (docs/aot_cache.md)."""
        if not self.enabled:
            return
        record = dict(payload)
        record["kind"] = "aot_cache"
        self.aot_cache_events.append(record)
        if self._export_sink:
            self._export_queue.append(dict(record))

    def record_fleet(self, payload: dict) -> None:
        """Elastic-fleet record: hub events (host_lost, restore_vote,
        resize, ...) default to ``kind="fleet_event"``; the periodic
        aggregation cadence passes ready-made ``kind="fleet"`` skew records
        through unchanged (docs/elastic.md)."""
        if not self.enabled:
            return
        record = dict(payload)
        record.setdefault("kind", "fleet_event")
        self.fleet_events.append(record)
        if self._export_sink:
            self._export_queue.append(dict(record))

    def record_device_step(self, record: DeviceStepRecord) -> DeviceStepRecord:
        """Sampled device-time record from the profiler: join the program's
        analytic FLOPs (``cost_analysis`` recorded at build) by variant key
        and derive MFU where a per-chip peak is known, then retain/export
        like every other kind."""
        if record.flops is None:
            for program in reversed(self.program_records):
                if program.key == record.key:
                    flops = program.stats.get("flops")
                    if isinstance(flops, (int, float)) and flops > 0:
                        record.flops = float(flops)
                    break
        if record.mfu is None and record.flops:
            from .profiler import derive_mfu

            record.mfu = derive_mfu(
                record.flops, record.window_ms, n_devices=len(record.devices)
            )
        if not record.phases:
            # per-phase split (docs/telemetry.md): join the sampled op
            # durations to the variant's op->scope map so the
            # compute/collective split reads per atpu phase, not one
            # whole-step window.  Fail-soft: no map (pre-build sample,
            # metadata-less backend) leaves phases empty.
            scope_map = self._scope_maps.get(record.key)
            if scope_map and record.op_detail:
                from .profiler import split_phases

                record.phases = split_phases(record.op_detail, scope_map)
        self.device_records.append(record)
        if self._export_sink:
            self._export_queue.append(record.to_dict())
        return record

    def restore_scope_map(self, key: str, scope_map: dict) -> None:
        """Adopt a PERSISTED HLO op→scope map for a compiled variant
        (docs/aot_cache.md): executables deserialized from the AOT store
        carry no HLO metadata, so ``record_program``'s live parse yields an
        empty map and every sample of that variant would read empty
        ``phases`` — the store's side payload carries the map the compiling
        process parsed, and the capture path restores it here on a warm
        load.  No-op unless the sampler is armed (the maps only feed the
        per-phase device split) or the map is empty."""
        if self.profiler is None or not scope_map:
            return
        self._scope_maps[key] = dict(scope_map)

    def rekey_last_device_step(self, new_key: str) -> None:
        """Re-key the most recent device-step record (and its pending export
        dict) — the first-call accumulate re-file moves the program record to
        the traced sync flag's key, and a sampled first call must follow or
        its device_step↔program join dangles."""
        if not self.device_records:
            return
        record = self.device_records[-1]
        old_key = record.key
        record.key = new_key
        for pending in reversed(self._export_queue):
            if pending.get("kind") == "device_step" and pending.get("key") == old_key:
                pending["key"] = new_key
                break

    def rekey_last_program(self, new_key: str) -> None:
        """Re-key the most recent program record (and its not-yet-drained
        export dict) — the capture path calls this when a first-call
        accumulate re-files the variant under the traced sync flag, so the
        per-program HBM/FLOP stats join to the right variant."""
        if not self.program_records:
            return
        record = self.program_records[-1]
        old_key = record.key
        record.key = new_key
        if old_key in self._scope_maps:
            # the per-phase join keys on the same variant id — follow the
            # re-file or the next sample of this variant loses its split
            self._scope_maps[new_key] = self._scope_maps.pop(old_key)
        for pending in reversed(self._export_queue):
            if pending.get("kind") == "program" and pending.get("key") == old_key:
                pending["key"] = new_key
                break

    def sample_resources(self, tag: str) -> ResourceSample:
        """Per-device live-bytes snapshot, on demand or at capture time."""
        sample = sample_live(tag)
        self.resource_samples.append(sample)
        if self._export_sink:
            self._export_queue.append(sample.to_dict())
        return sample

    # -- consumers -----------------------------------------------------------
    def attach_export_sink(self) -> None:
        """Called by the TelemetryTracker bridge: start feeding the export
        queue, and backfill it with the retained history recorded before the
        bridge existed (records were not enqueued then — sink-less gating)."""
        if self._export_sink:
            return
        self._export_sink = True
        if self._drains_total == 0 and not self._export_queue:
            for record in self.all_records():
                if record.get("kind") in (
                    "step", "recompile", "program", "collectives",
                    "resources", "resilience", "serving", "serving_recovery",
                    "device_step", "aot_cache", "fleet", "fleet_event",
                    "kernel", "autopilot",
                ):
                    self._export_queue.append(record)

    def drain(self) -> list[dict]:
        """Pop every not-yet-exported record (tracker-bridge feed)."""
        self._drains_total += 1
        out = list(self._export_queue)
        self._export_queue.clear()
        return out

    def summary(self) -> dict:
        out = self.timeline.summary()
        out["recompiles_total"] = self.recompiles_total
        out["schema_version"] = SCHEMA_VERSION
        out["eager_dataloader_wait_ms"] = round(self.eager_dataloader_wait_ms, 3)
        if self.aot_cache_events:
            events = list(self.aot_cache_events)
            out["aot_cache_hits"] = sum(1 for e in events if e.get("event") == "hit")
            out["aot_cache_misses"] = sum(
                1 for e in events if e.get("event") == "miss"
            )
        if self.device_records:
            records = list(self.device_records)
            out["device_samples"] = len(records)
            out["device_busy_ms_mean"] = round(
                sum(r.busy_ms for r in records) / len(records), 3
            )
            out["device_collective_share_mean"] = round(
                sum(r.collective_share for r in records) / len(records), 4
            )
        # flight-recorder health rides the summary record so a JSONL dump
        # documents whether the black box was recording (and how full)
        out["flightrec"] = self.flightrec.health()
        return out

    def all_records(self) -> list[dict]:
        """Full retained history in schema order (JSONL dump feed)."""
        records: list[dict] = [
            {
                "kind": "meta",
                "schema_version": SCHEMA_VERSION,
                "time": time.time(),
                "steps_total": self.steps_total,
                "recompiles_total": self.recompiles_total,
            }
        ]
        records += [r.to_dict() for r in self.timeline.records()]
        records += [d.to_dict() for d in self.device_records]
        records += [e.to_dict() for e in self.recompile_events]
        records += [p.to_dict() for p in self.program_records]
        records += [c.to_dict() for c in self.collective_records]
        records += [k.to_dict() for k in self.kernel_records]
        records += [s.to_dict() for s in self.resource_samples]
        records += [dict(e) for e in self.resilience_events]
        records += [dict(e) for e in self.serving_events]
        records += [dict(e) for e in self.serving_recovery_events]
        records += [dict(e) for e in self.aot_cache_events]
        records += [dict(e) for e in self.fleet_events]
        records.append(self.summary())
        return records

    def export_records(self) -> list[dict]:
        """What the JSONL dump writes: the fleet-merged view when
        ``aggregate_fleet`` ran (every record rank-tagged + the skew
        record), the rank-local history otherwise."""
        if self._fleet_records is not None:
            return self._fleet_records
        return self.all_records()

    def aggregate_fleet(self, periodic: bool = False) -> Optional[list[dict]]:
        """COLLECTIVE — every process must call (``end_training`` does on
        multi-process runs; the fleet hub's cadence does mid-run; safe and
        communication-free on one).  Gathers all ranks' retained records to
        the main process, rank-tags them, and appends the ``kind="fleet"``
        skew record; the main process also caches the merge so
        ``write_jsonl`` dumps the fleet view.  Returns the merged records
        on main, ``None`` elsewhere.

        ``periodic=True`` is the mid-run mode (docs/elastic.md): instead of
        freezing the final fleet dump, the skew/straggler record is
        computed and RETAINED (``record_fleet``) on EVERY rank — the
        allgather hands each rank the identical ballot, so each computes
        the identical record deterministically.  That symmetry is what
        makes the record usable as an *autoscaler input*: every rank's
        autopilot evaluates the same signal window and reaches the same
        resize decision at the same dispatch (rank-divergent signals would
        deadlock the collective resize).  Returns ``[skew_record]``."""
        from .aggregate import fleet_skew, gather_fleet, merge_rank_records

        if periodic:
            # mid-run payload discipline: only the replay step records the
            # skew summary consumes ride the collective, and only the DELTA
            # since the previous tick — re-gathering the whole retained
            # history every tick would pickle O(window × ranks) per tick
            # and dilute the "current straggler" signal with steps an
            # earlier tick already described
            from ..utils.operations import gather_object

            mark = self._fleet_agg_mark
            local = [
                r.to_dict()
                for r in self.timeline.records()
                if not r.built and r.step >= mark
            ]
            self._fleet_agg_mark = self.steps_total
            # NOT gather_fleet (which nulls non-main ranks): every rank
            # keeps the full gather and derives the same pure skew record
            per_rank = gather_object([local])
            skew = fleet_skew(per_rank)
            skew["periodic"] = True
            skew["at_step"] = self.steps_total
            skew["window_from_step"] = mark
            self.record_fleet(skew)
            return [skew]
        per_rank = gather_fleet(self.all_records())
        if per_rank is None:
            return None
        self._fleet_records = merge_rank_records(per_rank)
        return self._fleet_records

    # -- metrics endpoint ----------------------------------------------------
    def register_metrics_provider(self, name: str, fn) -> str:
        """Attach a live snapshot source (``fn() -> dict``) to whatever
        MetricsServer serves this hub; same-name re-registration replaces
        (latest service wins)."""
        from .metrics import register_provider

        return register_provider(self._metrics_providers, name, fn)

    def register_health_provider(self, name: str, fn) -> str:
        """Attach a readiness source (``fn() -> dict`` with a ``"ready"``
        bool) to whatever MetricsServer serves this hub's ``/healthz``;
        same-name re-registration replaces (latest service wins)."""
        from .metrics import register_provider

        return register_provider(self._health_providers, name, fn)

    def serve_metrics(self, port: int = 0, host: str = "127.0.0.1"):
        """Start (or return) the hub's Prometheus endpoint — idempotent;
        ``port=0`` binds ephemerally (read ``.port`` back).  A bind failure
        warns and returns ``None``: observability must not kill the job."""
        if self.metrics_server is not None:
            return self.metrics_server
        from .metrics import MetricsServer

        try:
            self.metrics_server = MetricsServer(
                telemetry=self, port=port, host=host
            ).start()
        except (OSError, OverflowError, ValueError) as exc:
            # OSError: port in use / denied; OverflowError/ValueError: an
            # out-of-range or malformed port — same contract for all three
            from ..logging import get_logger

            get_logger(__name__).warning(
                "metrics endpoint failed to bind %s:%s: %s", host, port, exc
            )
            return None
        return self.metrics_server

    def close_metrics(self) -> None:
        server, self.metrics_server = self.metrics_server, None
        if server is not None:
            server.close()

    def close_watchdog(self) -> None:
        watchdog, self.watchdog = self.watchdog, None
        if watchdog is not None:
            watchdog.stop()

    def export_trace(self, path: Optional[str] = None) -> Optional[str]:
        """Write the joined Chrome/Perfetto timeline (trace_export.py) when
        a path is configured or given; fail-soft ``None`` otherwise."""
        path = path or self.trace_export_path
        if path is None:
            return None
        from .trace_export import export_chrome_trace

        return export_chrome_trace(path, telemetry=self, recorder=self.flightrec)

    def write_jsonl(self, path: Optional[str] = None) -> Optional[str]:
        from .export import write_jsonl

        path = path or self.jsonl_path
        if path is None:
            return None
        from ..state import PartialState

        if PartialState._shared_state and not PartialState().is_main_process:
            # one writer per run: every process resolves the same path, and
            # concurrent mode-'w' writers would interleave a corrupt dump
            return None
        try:
            return write_jsonl(self, path)
        except OSError as exc:
            # telemetry is best-effort: a bad dump path (missing dir,
            # permissions) must not crash end_training or leave the
            # remaining trackers unfinished
            from ..logging import get_logger

            get_logger(__name__).warning(
                "telemetry JSONL dump to %r failed: %s", path, exc
            )
            return None


def __getattr__(name):
    # lazy: export.py imports tracking.py (the tracker fleet), which must not
    # load just because the data loader imported this package for the
    # current_telemetry() gate
    if name == "TelemetryTracker":
        from .export import TelemetryTracker

        return TelemetryTracker
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "PHASES",
    "CollectiveRecord",
    "DeviceStepRecord",
    "ProgramRecord",
    "RecompileEvent",
    "ResourceSample",
    "SCHEMA_VERSION",
    "StepRecord",
    "StepTimeline",
    "Telemetry",
    "TelemetryTracker",
    "current_telemetry",
    "diff_keys",
    "key_id",
    "program_stats",
]
