"""CLI subcommand package (reference: src/accelerate/commands/)."""
