"""gather_for_metrics correctness (analog of reference
test_utils/scripts/external_deps/test_metrics.py).

The reference computes sklearn metrics on MRPC predictions gathered across
ranks and asserts they equal the bare-metal single-process values — the
trap being the duplicated tail: with uneven splits the even-batches loader
loops back to the start, so a naive gather double-counts samples.

Zero-egress analog on the virtual multi-device mesh: for every
(dataset_len, batch_size) geometry — including ones whose tails wrap — run
an eval loop through ``prepare()`` + ``gather_for_metrics`` and assert

* the gathered sample count equals the dataset length exactly,
* the gathered (prediction, label) multiset equals the dataset's, in order,
* accuracy computed from the gathered arrays equals the single-process
  value bit-for-bit.
"""

from __future__ import annotations

import numpy as np

from accelerate_tpu import Accelerator, set_seed
from accelerate_tpu.data_loader import prepare_data_loader
from accelerate_tpu.state import PartialState

GEOMETRIES = [
    (64, 16),  # even: no remainder
    (66, 16),  # ragged tail of 2
    (67, 16),  # ragged tail of 3
    (16, 16),  # single batch
    (17, 16),  # single batch + 1
    (63, 8),   # tail of 7
]


def _dataset(n: int):
    rng = np.random.default_rng(n)
    xs = rng.standard_normal((n, 4)).astype(np.float32)
    labels = (xs.sum(axis=1) > 0).astype(np.int32)
    return [{"x": xs[i], "label": labels[i], "idx": np.int32(i)} for i in range(n)]


def _model_predict(batch):
    # deterministic "model": sign of the feature sum (no params needed —
    # the subject under test is the gather/dedup plumbing, not learning)
    return (np.asarray(batch["x"]).sum(axis=1) > 0).astype(np.int32)


def main() -> None:
    accelerator = Accelerator()
    set_seed(0)
    for n, bs in GEOMETRIES:
        data = _dataset(n)
        want_preds = np.array([_model_predict({"x": d["x"][None]})[0] for d in data])
        want_labels = np.array([d["label"] for d in data])
        want_acc = float((want_preds == want_labels).mean())

        dl = prepare_data_loader(dataset=data, batch_size=bs, shuffle=False)
        dl = accelerator.prepare(dl)

        got_preds, got_labels, got_idx = [], [], []
        for batch in dl:
            preds = _model_predict(batch)
            p, l, i = accelerator.gather_for_metrics(
                (preds, batch["label"], batch["idx"])
            )
            got_preds.append(np.asarray(p))
            got_labels.append(np.asarray(l))
            got_idx.append(np.asarray(i))
        got_preds = np.concatenate(got_preds)
        got_labels = np.concatenate(got_labels)
        got_idx = np.concatenate(got_idx)

        assert len(got_preds) == n, (
            f"({n},{bs}): gathered {len(got_preds)} samples, want {n} — "
            "duplicated tail not truncated"
        )
        assert (got_idx == np.arange(n)).all(), (
            f"({n},{bs}): sample order/coverage wrong: {got_idx.tolist()}"
        )
        np.testing.assert_array_equal(got_labels, want_labels)
        np.testing.assert_array_equal(got_preds, want_preds)
        got_acc = float((got_preds == got_labels).mean())
        assert got_acc == want_acc, f"({n},{bs}): {got_acc} != {want_acc}"
        if accelerator.is_main_process:
            print(f"  geometry ({n:3d}, bs {bs:2d}): n={len(got_preds)} acc={got_acc:.3f} OK")

    if accelerator.is_main_process:
        print(f"All metrics checks passed on {PartialState().num_processes} processes")


if __name__ == "__main__":
    main()
