"""Host-offloaded optimizer state (VERDICT r3 Missing #5 / item 10).

The reference reaches ZeRO optimizer-state offload through DeepSpeed
(/root/reference/src/accelerate/utils/dataclasses.py:1019 offload_optimizer);
the TPU-native mechanism is XLA host memory kinds: Adam moments and fp32
masters live in `pinned_host` memory with the SAME mesh layout as their
params, streamed to the chip only for the update. HBM then holds only
params+grads+activations — the memory the offload frees is exactly the
`pinned_host` bytes these tests assert.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.nn import Tensor
from accelerate_tpu.utils.dataclasses import FullyShardedDataParallelPlugin


def _per_param_state_leaves(opt):
    shapes = {tuple(p.shape) for p in opt.param_list}
    return [
        leaf
        for leaf in jax.tree_util.tree_leaves(opt.opt_state)
        if hasattr(leaf, "shape") and tuple(leaf.shape) in shapes
    ]


def _setup(offload, steps=3, capture=True):
    Accelerator._reset_state()
    nn.manual_seed(0)
    acc = Accelerator(
        parallelism_config=ParallelismConfig(fsdp_size=8),
        fsdp_plugin=FullyShardedDataParallelPlugin(offload_optimizer=offload),
        mixed_precision="bf16",
    )
    model = nn.Linear(16, 8)
    opt = optim.AdamW(model.parameters(), lr=1e-2)
    model, opt = acc.prepare(model, opt)

    def step_fn(xb):
        opt.zero_grad()
        loss = model(Tensor(xb)).sum()
        acc.backward(loss)
        opt.step()
        return loss

    step = acc.compile_step(step_fn) if capture else step_fn
    x = jnp.ones((8, 16), jnp.bfloat16)
    for _ in range(steps):
        loss = step(x)
    return acc, model, opt, float(loss)


def test_offloaded_state_lives_in_pinned_host():
    acc, model, opt, _ = _setup(offload=True, steps=3)
    inner = opt.optimizer
    moments = _per_param_state_leaves(inner)
    assert moments, "no per-param optimizer state found"
    for leaf in moments:
        assert leaf.sharding.memory_kind == "pinned_host", leaf.sharding
        # layout (mesh spec) is preserved — offload does not unshard
        assert isinstance(leaf.sharding, jax.sharding.NamedSharding)
    for m in inner.master_params:
        if m is not None:
            assert m.sharding.memory_kind == "pinned_host"
    # params themselves stay in device HBM
    for p in model.parameters():
        assert p.data.sharding.memory_kind == "device"


def test_offload_numerics_match_device_state():
    """Offloading is a placement decision, not a math change."""
    _, model_a, _, loss_a = _setup(offload=False, steps=4)
    w_a = np.asarray(jax.device_get(model_a.weight.data), dtype=np.float32)
    _, model_b, _, loss_b = _setup(offload=True, steps=4)
    w_b = np.asarray(jax.device_get(model_b.weight.data), dtype=np.float32)
    assert loss_a == pytest.approx(loss_b, rel=1e-5)
    np.testing.assert_allclose(w_a, w_b, rtol=1e-5, atol=1e-6)


def test_offload_eager_path_repins_after_step():
    acc, model, opt, _ = _setup(offload=True, steps=2, capture=False)
    for leaf in _per_param_state_leaves(opt.optimizer):
        assert leaf.sharding.memory_kind == "pinned_host"


def test_offload_frees_hbm_bytes():
    """The HBM-savings assertion: with offload, zero bytes of per-param
    optimizer state (2 moments + fp32 master per param) remain in device
    memory; without it, all of them do."""

    def device_state_bytes(opt):
        inner = opt.optimizer
        total = 0
        for leaf in _per_param_state_leaves(inner) + [
            m for m in inner.master_params if m is not None
        ]:
            if leaf.sharding.memory_kind == "device":
                total += leaf.nbytes
        return total

    _, _, opt_dev, _ = _setup(offload=False, steps=2)
    on_device = device_state_bytes(opt_dev)
    _, _, opt_host, _ = _setup(offload=True, steps=2)
    assert on_device > 0
    assert device_state_bytes(opt_host) == 0, (
        "offloaded optimizer state still resident in device memory"
    )


def test_ds_config_offload_optimizer_maps_to_plugin(tmp_path):
    """DeepSpeed offload_optimizer now maps to the real mechanism instead of
    a warn-and-ignore (closes VERDICT r3 partial row)."""
    from accelerate_tpu.utils.deepspeed_compat import from_deepspeed_config

    cfg = {
        "zero_optimization": {
            "stage": 2,
            "offload_optimizer": {"device": "cpu"},
        },
        "train_micro_batch_size_per_gpu": 2,
    }
    resolved = from_deepspeed_config(cfg)
    plugin = resolved.fsdp_plugin
    assert plugin is not None and plugin.offload_optimizer is True


def test_ds_config_offload_with_stage0_warns_not_shards():
    """Stage 0 = pure DDP: offload_optimizer must NOT fabricate a FULL_SHARD
    plugin the config never asked for (round-4 review finding)."""
    from accelerate_tpu.utils.deepspeed_compat import from_deepspeed_config

    cfg = {
        "zero_optimization": {
            "stage": 0,
            "offload_optimizer": {"device": "cpu"},
        },
    }
    with pytest.warns(UserWarning, match="stage 0"):
        resolved = from_deepspeed_config(cfg)
    assert resolved.fsdp_plugin is None
