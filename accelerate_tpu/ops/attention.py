"""Attention ops: XLA reference implementation + TPU routing.

``sdpa_tpu`` picks the Pallas flash-attention kernel
(ops/flash_attention.py) when running on TPU with MXU-friendly shapes, else
the jnp reference (which XLA still fuses into a few kernels on any backend).

Layout convention everywhere: (batch, num_heads, seq, head_dim) — torch SDPA
parity so reference-style model code ports untouched.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def sdpa_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    is_causal: bool = False,
    scale: Optional[float] = None,
    window: int = 0,
) -> jax.Array:
    if window > 0 and not is_causal:
        raise ValueError("sliding window requires is_causal=True")
    if scale is None:
        scale = q.shape[-1] ** -0.5
    # accumulate logits/softmax in fp32 regardless of input dtype
    logits = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if is_causal:
        q_len, k_len = logits.shape[-2], logits.shape[-1]
        causal = jnp.tril(jnp.ones((q_len, k_len), dtype=bool), k_len - q_len)
        if window > 0:
            # sliding band: query i sees keys (i-window, i]
            causal &= ~jnp.tril(
                jnp.ones((q_len, k_len), dtype=bool), k_len - q_len - window
            )
        logits = jnp.where(causal, logits, _NEG_INF)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, _NEG_INF)
        else:
            logits = logits + mask.astype(logits.dtype)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)


# single source of truth for "the Pallas kernels are safe here" — shared
# with ops/ring_attention.py so the two dispatchers cannot drift
_MXU_HEAD_DIMS = (64, 128, 256)
_TPU_BACKENDS = ("tpu", "axon")


def _on_tpu(x: Optional[jax.Array] = None) -> bool:
    try:
        return jax.default_backend() in _TPU_BACKENDS
    except Exception:
        return False


def sdpa_tpu(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    is_causal: bool = False,
    scale: Optional[float] = None,
    window: int = 0,
) -> jax.Array:
    """Dispatch: Pallas flash kernel on TPU for MXU-tileable shapes.

    ``ACCELERATE_TPU_FLASH=0`` forces the XLA reference path, ``=1`` forces the
    Pallas kernel (when importable); unset picks per shape.  XLA's fused
    attention is often faster at short sequences where the S×S scores fit
    comfortably in VMEM; the Pallas kernel wins when S is large enough that
    materializing scores thrashes HBM.
    """
    import os

    seq_q, seq_k, head_dim = q.shape[-2], k.shape[-2], q.shape[-1]
    force = os.environ.get("ACCELERATE_TPU_FLASH", "").strip()
    if force == "0":
        return sdpa_reference(
            q, k, v, mask=mask, is_causal=is_causal, scale=scale, window=window
        )
    tileable = (
        mask is None
        and seq_q % 128 == 0
        and seq_k % 128 == 0
        and head_dim in _MXU_HEAD_DIMS
    )
    if force == "1":
        from . import flash_attention as _fa_mod

        use_flash = tileable and _fa_mod._HAS_PLTPU
    else:
        use_flash = tileable and _on_tpu(q)
    if use_flash:
        try:
            from .flash_attention import flash_attention
        except ImportError:
            _warn_no_flash_once()
        else:
            return flash_attention(
                q, k, v, is_causal=is_causal, scale=scale, window=window
            )
    return sdpa_reference(
        q, k, v, mask=mask, is_causal=is_causal, scale=scale, window=window
    )


_warned_no_flash = False


def _warn_no_flash_once() -> None:
    global _warned_no_flash
    if not _warned_no_flash:
        _warned_no_flash = True
        import logging

        logging.getLogger(__name__).warning(
            "Pallas flash-attention kernel unavailable; using the XLA "
            "reference attention path."
        )
