"""Hang watchdog (docs/telemetry.md §watchdog) — default OFF.

A background daemon thread that arms a wall-clock deadline around the
process's *blocking* sections — host collectives (``utils/operations.py``
guards every gather/broadcast/reduce) and blocking device syncs (the
profiler's ``block_until_ready`` in ``capture.py``) — and, when a section
overruns its deadline, dumps the postmortem: ``faulthandler`` stacks for
every thread plus the flight-recorder ring (``telemetry/flightrec.py``) to
a **per-rank** JSON file.  The same dump path fires on a fatal signal
(SIGTERM/SIGABRT, chained to any previously-installed handler such as the
resilience :class:`~..resilience.preemption.PreemptionGuard`) and at
``atexit``, so a rank that dies *without* hanging still leaves its half of
the cross-rank story for ``tools/blackbox_report.py`` (the atexit dump
yields to an earlier stall/signal dump rather than overwriting it — the
stalled rank's exit usually *follows* the stall).

Two invariants, both load-bearing:

* **The watchdog never issues a collective.**  It names the stalled
  section; coordinating about the stall over the very mesh that is stalled
  would deadlock the postmortem too.  This module is declared
  rank-local-by-design to the graftlint taint pass (``analysis/taint.py``),
  which asserts the no-collective contract statically.
* **Zero overhead when off** (the telemetry package convention): nothing
  here runs — no thread, no signal handlers — unless
  ``TelemetryKwargs(watchdog_s=...)`` / ``$ACCELERATE_WATCHDOG_S`` armed
  it, and the producer-side guard sites pay one module-attribute read plus
  a ``None``-check.

The dump itself is fail-soft (an unwritable dir yields a warning, never an
exception) and firing does not kill the process: the stalled collective may
yet complete (a transient network partition), and killing ranks is the
fleet layer's decision, not the recorder's.
"""

from __future__ import annotations

import atexit
import faulthandler
import os
import signal
import sys
import threading
import time
import traceback
from contextlib import contextmanager
from typing import Optional

from ..logging import get_logger
from . import flightrec

logger = get_logger(__name__)

# the armed watchdog (latest-wins, like telemetry's _ACTIVE slot); None when
# the feature is off — every guard site gates on that None
_ACTIVE: Optional["HangWatchdog"] = None


def current_watchdog() -> Optional["HangWatchdog"]:
    return _ACTIVE


def _set_active(watchdog: Optional["HangWatchdog"]) -> None:
    global _ACTIVE
    _ACTIVE = watchdog


def _thread_stacks() -> dict:
    """Python stacks for every live thread, embeddable in the JSON dump
    (the ``faulthandler`` text goes to a sidecar — its C-level dump cannot
    be captured into a string without a pipe)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        label = f"{names.get(ident, '?')}:{ident}"
        out[label] = traceback.format_stack(frame)
    return out


class HangWatchdog:
    """Deadline-armed stall detector over the flight-recorder ring."""

    def __init__(
        self,
        timeout_s: float,
        dump_dir: str = "blackbox",
        recorder: Optional[flightrec.FlightRecorder] = None,
        poll_s: Optional[float] = None,
        install_signal_handlers: bool = True,
        dump_at_exit: bool = True,
    ):
        self.timeout_s = max(0.1, float(timeout_s))
        self.dump_dir = dump_dir
        self.recorder = recorder if recorder is not None else flightrec.recorder()
        self.poll_s = poll_s if poll_s is not None else min(1.0, self.timeout_s / 4.0)
        self._install_signals = bool(install_signal_handlers)
        self._dump_at_exit = bool(dump_at_exit)
        # the armed section: (label, deadline_monotonic) — written by the
        # guarded thread, read by the watchdog thread; a tuple swap is
        # atomic enough (torn reads are impossible, stale reads self-heal
        # one poll later)
        self._armed: Optional[tuple] = None
        self._guard_depth = 0
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._prev_handlers: dict = {}
        self._exit_hook = None
        self.fired = 0
        self.last_dump_path: Optional[str] = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "HangWatchdog":
        """Arm the watchdog: spawn the poll thread, install the fatal-signal
        and atexit dump hooks, publish to the module slot."""
        if self._thread is not None:
            return self
        displaced = _ACTIVE
        if displaced is not None and displaced is not self:
            displaced.stop()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="atpu-watchdog", daemon=True
        )
        self._thread.start()
        if self._install_signals:
            self._install_signal_dumps()
        if self._dump_at_exit:
            self._exit_hook = self._dump_at_exit_hook
            atexit.register(self._exit_hook)
        _set_active(self)
        self.recorder.record("watchdog_armed", timeout_s=self.timeout_s)
        return self

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=2 * self.poll_s + 1.0)
        for signum, prev in self._prev_handlers.items():
            try:
                signal.signal(signum, prev)
            except (ValueError, TypeError):
                pass
        self._prev_handlers.clear()
        if self._exit_hook is not None:
            try:
                atexit.unregister(self._exit_hook)
            except Exception:
                pass
            self._exit_hook = None
        if _ACTIVE is self:
            _set_active(None)

    # -- guard sites ---------------------------------------------------------
    @contextmanager
    def guard(self, label: str, timeout_s: Optional[float] = None):
        """Arm the deadline around one blocking section.  Reentrant: nested
        guards keep the OUTERMOST deadline (the outer section's budget
        already covers its inner calls)."""
        self.arm(label, timeout_s=timeout_s)
        try:
            yield
        finally:
            self.disarm()

    def arm(self, label: str, timeout_s: Optional[float] = None) -> None:
        with self._lock:
            self._guard_depth += 1
            if self._guard_depth == 1:
                budget = self.timeout_s if timeout_s is None else float(timeout_s)
                self._armed = (label, time.monotonic() + budget, time.monotonic())

    def disarm(self) -> None:
        with self._lock:
            self._guard_depth = max(0, self._guard_depth - 1)
            if self._guard_depth == 0:
                self._armed = None

    # -- the poll thread -----------------------------------------------------
    def _run(self) -> None:
        fired_for = None  # the armed tuple a dump already described
        while not self._stop.wait(self.poll_s):
            armed = self._armed
            if armed is None:
                fired_for = None
                continue
            label, deadline, since = armed
            if time.monotonic() < deadline or armed is fired_for:
                continue
            fired_for = armed
            self.fired += 1
            stalled_s = time.monotonic() - since
            self.recorder.record(
                "watchdog_stall", label=label, stalled_s=round(stalled_s, 3)
            )
            logger.error(
                "watchdog: %r blocked for %.1fs (budget %.1fs) — dumping "
                "flight ring + stacks to %s",
                label, stalled_s, self.timeout_s, self.dump_dir,
            )
            self._dump("watchdog_stall", label=label, stalled_s=stalled_s)

    # -- dumps ---------------------------------------------------------------
    def _dump(self, reason: str, label: Optional[str] = None,
              stalled_s: Optional[float] = None) -> Optional[str]:
        """Write the per-rank postmortem (flight ring + thread stacks) and a
        ``faulthandler`` sidecar.  Fail-soft, collective-free, callable from
        the watchdog thread, a signal handler, or atexit."""
        extra = {
            "watchdog_timeout_s": self.timeout_s,
            "watchdog_fired": self.fired,
            "stalled_label": label,
            "stalled_s": round(stalled_s, 3) if stalled_s is not None else None,
            "threads": _thread_stacks(),
        }
        path = self.recorder.dump(self.dump_dir, reason=reason, extra=extra)
        if path is None:
            logger.warning("watchdog: blackbox dump to %r failed", self.dump_dir)
            return None
        self.last_dump_path = path
        try:
            with open(f"{path}.stacks.txt", "w", encoding="utf-8") as f:
                faulthandler.dump_traceback(file=f, all_threads=True)
        except Exception:
            pass  # the JSON dump already carries the python-level stacks
        return path

    def dump_now(self, reason: str = "manual") -> Optional[str]:
        return self._dump(reason)

    def _dump_at_exit_hook(self) -> None:
        # the atexit dump covers a rank that dies WITHOUT a stall or fatal
        # signal; if a more specific dump already landed (the stalled rank's
        # collective raising once a peer dies makes exit follow the stall),
        # overwriting it with "atexit" would erase the postmortem
        if self.last_dump_path is None:
            self._dump("atexit")

    # -- fatal-signal chaining -----------------------------------------------
    def _install_signal_dumps(self) -> None:
        """Dump-then-chain on fatal signals.  Chaining (rather than
        replacing) composes with the resilience PreemptionGuard in either
        install order: the dump is recorded, then the previous handler —
        sticky-flag guard, user handler, or OS default — runs unchanged."""
        for signum in (signal.SIGTERM, signal.SIGABRT):
            try:
                self._prev_handlers[signum] = signal.signal(
                    signum, self._handle_signal
                )
            except ValueError:
                # not the main thread: the atexit + watchdog dumps still
                # cover the postmortem, so stay inert rather than fail
                self._prev_handlers.clear()
                return

    def _handle_signal(self, signum, frame) -> None:
        self.recorder.record("fatal_signal", signum=int(signum))
        self._dump("signal")
        prev = self._prev_handlers.get(signum)
        if callable(prev):
            prev(signum, frame)
        elif prev == signal.SIG_DFL:
            # re-deliver with the default disposition restored so the
            # process still dies with the right status
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)
