#!/usr/bin/env python
"""elastic_smoke — `make elastic-smoke`: prove the survive-and-resize path
end-to-end on 4 virtual CPU devices in seconds (docs/elastic.md).

Tiny GPT at dp=4 with the fleet armed and a ``host_lost`` fault injected
right before step 2's dispatch.  The loop finishes that step, reads the
sticky ``should_resize`` flag, and ``fleet.resize()`` drains a COMPLETE
checkpoint → re-meshes at dp=2 over the survivors → re-lays ZeRO-1
masters/moments onto the new topology → restores the spec-carrying
checkpoint (reshard, not reinit) → prewarms the AOT executable store for
the new mesh — then training resumes at dp=2 within loss parity of an
uninterrupted dp=4 run.  The scenario runs TWICE against one cache dir:
the first pass compiles-and-stores the dp=2 programs, the second pass's
post-resize first step must deserialize them (zero trace/compile phase
time, >= 1 cache hit).  Exit 0 = complete drain checkpoint, resized mesh,
loss parity both passes, and zero recompiles for the prewarmed programs.
"""

import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=4"
    ).strip()
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

STEPS = 5
HOST_LOST_AT = 2
TARGET_DP = 2
LOSS_RTOL = 1e-3  # documented resize tolerance: the dp reduce order moves


def main() -> int:
    import jax
    import numpy as np

    import accelerate_tpu.nn as nn
    import accelerate_tpu.optim as optim
    from accelerate_tpu import (
        Accelerator,
        CompilationCacheKwargs,
        FleetKwargs,
        TelemetryKwargs,
    )
    from accelerate_tpu.checkpointing import is_complete_checkpoint
    from accelerate_tpu.data_loader import batch_to_global_array
    from accelerate_tpu.models import GPTConfig, GPTLMHeadModel

    errors: list[str] = []
    tmp = tempfile.mkdtemp(prefix="atpu_elastic_")
    cache_dir = os.path.join(tmp, "aot")

    def build(fleet=False, plan=None):
        Accelerator._reset_state()
        jax.clear_caches()
        nn.manual_seed(0)
        handlers = [TelemetryKwargs(enabled=True)]
        if fleet:
            handlers += [
                FleetKwargs(enabled=True, fault_plan=plan),
                CompilationCacheKwargs(cache_dir=cache_dir),
            ]
        acc = Accelerator(kwargs_handlers=handlers)
        model = GPTLMHeadModel(
            GPTConfig(vocab_size=256, n_positions=64, n_embd=32, n_layer=1, n_head=2)
        )
        opt = optim.AdamW(model.parameters(), lr=1e-3)
        model, opt = acc.prepare(model, opt)

        def step_fn(ids):
            opt.zero_grad()
            out = model(ids, labels=ids)
            acc.backward(out["loss"])
            opt.step()
            return out["loss"]

        rng = np.random.default_rng(0)
        raw = [
            rng.integers(0, 256, (8, 32), dtype=np.int32) for _ in range(STEPS)
        ]
        return acc, acc.compile_step(step_fn), raw

    def run_elastic(tag, drain_dir):
        acc, step, raw = build(fleet=True, plan=f"host_lost:step={HOST_LOST_AT}")
        if dict(acc.mesh.shape)["dp"] != 4:
            errors.append(f"{tag}: expected dp=4 start, got {dict(acc.mesh.shape)}")
        losses, info, i = [], None, 0
        while i < len(raw):
            batch = batch_to_global_array(raw[i], mesh=acc.mesh)
            losses.append(float(step(batch)))
            i += 1
            if info is None and acc.fleet.should_resize:
                info = acc.fleet.resize(acc, target_dp=TARGET_DP, output_dir=drain_dir)
        if info is None:
            errors.append(f"{tag}: host_lost never tripped should_resize")
            return losses, acc, {}
        if len(losses) != STEPS:
            errors.append(f"{tag}: ran {len(losses)} steps, expected {STEPS}")
        if not is_complete_checkpoint(info["checkpoint"]):
            errors.append(f"{tag}: drain checkpoint incomplete")
        if dict(acc.mesh.shape)["dp"] != TARGET_DP:
            errors.append(f"{tag}: mesh not resized: {dict(acc.mesh.shape)}")
        events = [e["event"] for e in acc.fleet.events]
        for expected in ("host_lost", "drain", "resize"):
            if expected not in events:
                errors.append(f"{tag}: missing fleet event {expected}: {events}")
        return losses, acc, info

    # uninterrupted dp=4 reference
    acc_ref, step, raw = build()
    reference = [
        float(step(batch_to_global_array(batch, mesh=acc_ref.mesh)))
        for batch in raw
    ]

    # pass 1 (cold store): resize compiles the dp=2 program and stores it
    losses1, acc1, _ = run_elastic("cold", os.path.join(tmp, "drain1"))
    if acc1.aot_cache.stores < 1:
        errors.append(f"cold: no AOT stores recorded ({acc1.aot_cache.stores})")

    # pass 2 (warm store): the post-resize first step must be a prewarm hit
    losses2, acc2, info2 = run_elastic("warm", os.path.join(tmp, "drain2"))
    if info2.get("aot_prewarmed", 0) < 1:
        errors.append(f"warm: prewarm staged no entries ({info2})")
    built = [r for r in acc2.telemetry.timeline.records() if r.built]
    if built:
        post = built[-1]  # the post-resize rebuild
        if post.trace_ms != 0.0 or post.compile_ms != 0.0:
            errors.append(
                "warm: post-resize step recompiled "
                f"(trace={post.trace_ms}ms compile={post.compile_ms}ms) — "
                "the prewarmed program was not served"
            )
    hits = sum(
        1 for e in acc2.telemetry.aot_cache_events if e["event"] == "hit"
    )
    if hits < 1:
        errors.append("warm: no aot_cache hits recorded")

    for tag, losses in (("cold", losses1), ("warm", losses2)):
        if len(losses) == len(reference) and not np.allclose(
            losses, reference, rtol=LOSS_RTOL
        ):
            errors.append(
                f"{tag}: losses diverged beyond rtol={LOSS_RTOL}: "
                f"{losses} vs {reference}"
            )

    for error in errors:
        print(f"elastic-smoke: FAIL: {error}", file=sys.stderr)
    if errors:
        return 1
    print(
        f"elastic-smoke: ok — host_lost at step {HOST_LOST_AT}, drain → "
        f"re-mesh dp=4→{TARGET_DP} → reshard → resume at loss parity "
        f"(rtol={LOSS_RTOL}); warm pass prewarmed {info2['aot_prewarmed']} "
        f"entries, post-resize step zero trace/compile, {hits} cache hits"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
