"""sharding-spec-drift: a sharding plan that disagrees with checkpoint metadata.

Checkpoint index files (``<name>.index.json``, written by
``utils/fsdp_utils.collect_sharded_model_state``) record the save-time
``PartitionSpec`` of every tensor.  Loading reshards by global slice bounds,
so a drifted plan does not corrupt data — it silently *re-lays-out* the
whole model on step one (all-gather + re-shard of every parameter, a
multi-second stall and a new compile on real pods) and invalidates any
capture cache keyed on the old layout.  This rule catches the drift at lint
time: run with ``--ckpt-index <dir-or-index.json>`` and every literal
``tp_plan`` / ``sharding_plan`` dict in the analyzed source is cross-checked
against the recorded specs.

Without ``--ckpt-index`` the rule is inert (there is nothing to compare
against), so it never fires during plain ``make lint``.

Beyond literal ``tp_plan`` edits, the rule also checks the *fsdp strategy*
against the checkpoint: ``plan_param_spec`` only lays an ``fsdp`` axis onto
parameters under ``FULL_SHARD`` / ``HYBRID_SHARD``.  A checkpoint whose
index records fsdp-sharded tensors loaded by source that now says
``sharding_strategy="NO_SHARD"`` (or ``SHARD_GRAD_OP``) will all-gather and
re-lay-out every parameter at step one — the same silent cost as a plan
edit, caught the same way.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from ..engine import Finding, Rule

_PLAN_NAME_RE = re.compile(r"(tp_plan|sharding_plan)", re.IGNORECASE)


def _template_entries(node: ast.AST) -> Optional[list]:
    """Normalize a literal partition-spec template into per-dim axis lists.

    ``("tp", None)`` → ``[["tp"], []]``; nested tuples collect multi-axis
    dims.  Returns None when any entry is not a literal (runtime-computed
    templates cannot be checked statically).
    """
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    dims: list = []
    for e in node.elts:
        if isinstance(e, ast.Constant) and e.value is None:
            dims.append([])
        elif isinstance(e, ast.Constant) and isinstance(e.value, str):
            dims.append([e.value])
        elif isinstance(e, (ast.Tuple, ast.List)) and all(
            isinstance(x, ast.Constant) and isinstance(x.value, str) for x in e.elts
        ):
            dims.append([x.value for x in e.elts])
        else:
            return None
    return dims


def _normalize_spec(spec: list) -> list:
    """Recorded JSON spec (str | [str, ...] | null per dim) → per-dim axis
    lists with trailing replicated dims stripped."""
    dims = []
    for e in spec or []:
        if e is None:
            dims.append([])
        elif isinstance(e, str):
            dims.append([e])
        else:
            dims.append(list(e))
    while dims and not dims[-1]:
        dims.pop()
    return dims


def _plan_dicts(module):
    """Yield (plan_name, ast.Dict) for every literal sharding-plan binding."""
    for node in ast.walk(module.tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        if not isinstance(value, ast.Dict):
            continue
        for t in targets:
            name = t.id if isinstance(t, ast.Name) else (
                t.attr if isinstance(t, ast.Attribute) else None
            )
            if name and _PLAN_NAME_RE.search(name):
                yield name, value
                break


# strategies under which plan_param_spec does NOT shard parameters
_NON_SHARDING = {"NO_SHARD", "SHARD_GRAD_OP"}


def _strategy_literals(module):
    """Yield (value, node) for every literal ``sharding_strategy`` binding:
    a keyword argument (``FullyShardedDataParallelPlugin(sharding_strategy=
    "NO_SHARD")``) or an assignment whose target name says so."""
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if (
                    kw.arg == "sharding_strategy"
                    and isinstance(kw.value, ast.Constant)
                    and isinstance(kw.value.value, str)
                ):
                    yield kw.value.value, kw.value
        elif isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            value = node.value
            if not (isinstance(value, ast.Constant) and isinstance(value.value, str)):
                continue
            for t in targets:
                name = t.id if isinstance(t, ast.Name) else (
                    t.attr if isinstance(t, ast.Attribute) else None
                )
                if name and name.endswith("sharding_strategy"):
                    yield value.value, value
                    break


class ShardingSpecDrift(Rule):
    id = "sharding-spec-drift"
    kind = "syntactic"
    description = (
        "sharding plan or fsdp strategy disagrees with the checkpoint "
        "metadata records (needs --ckpt-index)"
    )
    fix_hint = (
        "match the plan to the checkpoint's recorded PartitionSpec, or "
        "re-save the checkpoint under the new plan"
    )

    def check(self, module, ctx):
        specs = getattr(ctx, "ckpt_specs", None)
        if not specs:
            return []
        findings: list[Finding] = []
        findings.extend(self._check_strategy(module, specs))
        for plan_name, dict_node in _plan_dicts(module):
            claimed: set = set()  # first matching pattern wins, like plan_param_spec
            for key_node, value_node in zip(dict_node.keys, dict_node.values):
                if not (
                    isinstance(key_node, ast.Constant)
                    and isinstance(key_node.value, str)
                ):
                    continue
                pattern = key_node.value
                template = _template_entries(value_node)
                if template is None:
                    continue
                try:
                    compiled = re.compile(pattern)
                except re.error:
                    continue
                planned = list(template)
                while planned and not planned[-1]:
                    planned.pop()
                mismatched = []
                for tensor, recorded in specs.items():
                    if tensor in claimed:
                        continue
                    if not (compiled.fullmatch(tensor) or compiled.search(tensor)):
                        continue
                    claimed.add(tensor)
                    rec = _normalize_spec(recorded)
                    if not rec:
                        # fully replicated at save time: a size-1 mesh axis
                        # canonicalizes any template away, so this proves
                        # nothing about drift
                        continue
                    # the runtime pads templates with None to the param rank,
                    # and plan_param_spec ADDS "fsdp" onto a template-free dim
                    # on fsdp>1 meshes — a recorded "fsdp" the template never
                    # mentioned is auto-sharding, not drift
                    n = max(len(planned), len(rec))
                    a = planned + [[]] * (n - len(planned))
                    b = [
                        [
                            axis
                            for axis in dim
                            if not (axis == "fsdp" and "fsdp" not in a[i])
                        ]
                        for i, dim in enumerate(rec + [[]] * (n - len(rec)))
                    ]
                    if a != b:
                        mismatched.append((tensor, rec))
                if mismatched:
                    tensor, rec = mismatched[0]
                    more = (
                        f" (+{len(mismatched) - 1} more tensor(s))"
                        if len(mismatched) > 1
                        else ""
                    )
                    findings.append(
                        Finding(
                            self.id,
                            module.rel_path,
                            key_node.lineno,
                            key_node.col_offset,
                            f"plan entry {pattern!r} assigns axes {planned} "
                            f"but the checkpoint recorded {rec} for "
                            f"'{tensor}'{more}; loading reshards the whole "
                            "tensor at step one — resave the checkpoint or "
                            "revert the plan edit",
                            symbol=plan_name,
                        )
                    )
        return findings

    def _check_strategy(self, module, specs):
        """The plan_param_spec side of drift: fsdp-sharded records vs a
        source strategy that no longer shards parameters."""
        fsdp_tensors = [
            tensor
            for tensor, recorded in specs.items()
            if any("fsdp" in dim for dim in _normalize_spec(recorded))
        ]
        if not fsdp_tensors:
            # no fsdp axis recorded proves nothing: the checkpoint may have
            # been saved on an fsdp:1 mesh, which canonicalizes the axis away
            return []
        findings = []
        for value, node in _strategy_literals(module):
            if value in _NON_SHARDING:
                findings.append(
                    Finding(
                        self.id,
                        module.rel_path,
                        node.lineno,
                        node.col_offset,
                        f"sharding_strategy={value!r} but the checkpoint "
                        f"records fsdp-sharded tensors (e.g. "
                        f"'{fsdp_tensors[0]}'"
                        + (
                            f", +{len(fsdp_tensors) - 1} more"
                            if len(fsdp_tensors) > 1
                            else ""
                        )
                        + ") — plan_param_spec will not shard under this "
                        "strategy, so loading all-gathers and re-lays-out "
                        "every parameter at step one; restore FULL_SHARD or "
                        "resave the checkpoint",
                    )
                )
        return findings
