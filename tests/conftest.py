"""Test config: force an 8-device virtual CPU mesh before jax imports.

This is the TPU-native analog of the reference's Pattern-3 CPU multi-"device"
simulation (SURVEY.md §4): instead of spawning gloo processes, XLA itself
exposes N host devices via --xla_force_host_platform_device_count, so every
sharding/collective path runs exactly the SPMD code it would on a pod.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Persistent XLA compilation cache: the suite is compile-bound (hundreds of
# jit programs over identical tiny shapes), and the cache works on the CPU
# backend too — measured 2× on a warm rerun.  Env vars (not config.update)
# so subprocess-launched scripts (launcher/example tests) inherit it.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.environ.get("ACCELERATE_TPU_TEST_CACHE", "/tmp/accelerate_tpu_jax_cache"),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

# The container's sitecustomize registers the axon TPU backend and pins the
# platform; override back to the virtual 8-device CPU mesh for tests.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavyweight test (subprocess launches, big compiles); "
        "skipped unless RUN_SLOW=1, selectable via -m slow / -m 'not slow'",
    )
    config.addinivalue_line(
        "markers",
        "graftlint: static-analyzer tests (pure AST, no tracing); "
        "selectable via -m graftlint",
    )


@pytest.fixture(autouse=True)
def _reset_singletons():
    """Each test gets fresh Borg state (mirrors reference test hygiene)."""
    yield
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
