#!/bin/bash
# Periodically probe the TPU backend; record status to /tmp/tpu_status.txt.
# Spaced retries: the observed outage pattern is hang-then-UNAVAILABLE, so
# occasional probes over a long window can catch the backend coming back.
while true; do
  ts=$(date +%s)
  out=$(timeout 120 python -c "
import jax
ds = jax.devices()
print('OK', ds[0].platform, len(ds))
" 2>&1 | tail -1)
  echo "$ts $out" >> /tmp/tpu_status.txt
  if echo "$out" | grep -q '^OK'; then
    echo "$ts TPU_UP" >> /tmp/tpu_status.txt
  fi
  sleep 240
done
