"""Pillar 4 — deterministic fault injection (test-only).

The resilience subsystem exists because of failures that cannot be scheduled:
hung PJRT clients, spot reclamation SIGTERMs, transient XLA runtime errors.
This module makes them schedulable so the whole subsystem is testable on CPU
with no flaky hardware: a :class:`FaultPlan` names exactly which fault fires
when, and a :class:`FaultInjector` replays it deterministically.

Plan grammar (``ACCELERATE_FAULT_PLAN`` or ``ResilienceKwargs.fault_plan``) —
semicolon-separated directives, ``key=int`` options after a colon:

* ``init_hang`` / ``init_hang:times=2`` — the next N backend-init probes fail
  as if the PJRT client hung (no real subprocess, no real timeout wait).
* ``dispatch:step=2`` / ``dispatch:step=2,times=3`` — the captured-step
  dispatch with global index ``step`` raises an
  :class:`InjectedTransientError` N times (retries of the same call keep
  faulting until ``times`` is exhausted, which is how rollback exhaustion is
  driven in tests).
* ``sigterm:step=2`` — deliver a real ``SIGTERM`` to this process right
  before the dispatch of global step ``step`` (mid-step preemption).
* ``host_lost:step=2`` — mark a whole host as preempted right before the
  dispatch of global step ``step``.  Consumed by the elastic fleet runtime
  (``fleet.should_resize``, docs/elastic.md): unlike ``sigterm`` — "this
  process must drain and exit" — ``host_lost`` means "a peer is gone, the
  survivors must drain and re-mesh at the smaller topology".
* ``host_gained:step=4`` — mark a host as RETURNED right before dispatch
  ``step`` (the rejoin beacon a scheduler sends when a reclaimed host comes
  back).  Consumed by the elastic fleet runtime (``fleet.should_grow``):
  the survivors drain and re-mesh dp *up* over the rejoined blocks.
* ``hang:step=2`` / ``hang:step=2,seconds=30`` — the process sleeps for
  ``seconds`` (default 3600 — effectively forever on a test clock) right
  before the dispatch of global step ``step``.  The rank never reaches its
  next collective, so every OTHER rank blocks inside theirs — the canonical
  stalled-rank scenario the hang watchdog (``telemetry/watchdog.py``) and
  blackbox postmortem (``tools/blackbox_report.py``) exist for.
* ``signal_storm:step=2,times=6`` — for the next ``times`` autopilot
  evaluation ticks starting at dispatch ``step``, flap the observed
  straggler-skew signal alternately above and below the autopilot's
  threshold.  Consumed by the fleet autopilot (docs/elastic.md): the
  hysteresis/debounce proof — a storm must produce suppressed-decision
  telemetry and exactly zero resizes.
* ``decode_fault:step=2`` / ``decode_fault:step=2,times=3`` — the serving
  engine iteration with index ``step`` raises an
  :class:`InjectedTransientError` inside the decode dispatch N times
  (retries keep faulting until ``times`` is spent — how the serving
  retry-exhaustion requeue is driven).  Consumed by
  :class:`~..serving.DecodeService` (docs/serving.md §fault tolerance).
* ``serving_sigterm:step=2`` — deliver a real ``SIGTERM`` right before
  serving engine step ``step``, with slots in flight — the mid-decode
  preemption the request journal + drain path exists for.

Injection points are reached only when resilience is enabled AND a plan is
configured — production runs never pay for (or trip over) this module.
"""

from __future__ import annotations

import os
import signal
from dataclasses import dataclass, field
from typing import Optional

ENV_FAULT_PLAN = "ACCELERATE_FAULT_PLAN"


class InjectedTransientError(RuntimeError):
    """Simulated transient runtime failure (classified retryable by
    :func:`~.retry.classify_failure`, exactly like an UNAVAILABLE status)."""


@dataclass
class _Directive:
    kind: str  # init_hang | dispatch | sigterm | host_lost | host_gained | signal_storm | hang | decode_fault | serving_sigterm
    step: Optional[int] = None  # dispatch index (dispatch/sigterm/hang)
    times: int = 1  # how many firings remain
    fired: int = 0
    seconds: int = 3600  # hang duration (hang only)


@dataclass
class FaultPlan:
    directives: list[_Directive] = field(default_factory=list)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        directives: list[_Directive] = []
        for raw in spec.split(";"):
            raw = raw.strip()
            if not raw:
                continue
            kind, _, opts_raw = raw.partition(":")
            kind = kind.strip()
            if kind not in (
                "init_hang", "dispatch", "sigterm", "host_lost",
                "host_gained", "signal_storm", "hang",
                "decode_fault", "serving_sigterm",
            ):
                raise ValueError(
                    f"unknown fault directive {kind!r} in {spec!r}; use "
                    "init_hang / dispatch / sigterm / host_lost / "
                    "host_gained / signal_storm / hang / decode_fault / "
                    "serving_sigterm"
                )
            opts: dict[str, int] = {}
            for pair in opts_raw.split(","):
                pair = pair.strip()
                if not pair:
                    continue
                key, _, value = pair.partition("=")
                try:
                    opts[key.strip()] = int(value)
                except ValueError:
                    raise ValueError(
                        f"fault option {pair!r} in {spec!r} is not key=int"
                    ) from None
            allowed = {"step", "times"} | ({"seconds"} if kind == "hang" else set())
            unknown = set(opts) - allowed
            if unknown:
                raise ValueError(f"unknown fault options {sorted(unknown)} in {raw!r}")
            if (
                kind in ("dispatch", "sigterm", "host_lost", "host_gained",
                         "signal_storm", "hang", "decode_fault",
                         "serving_sigterm")
                and "step" not in opts
            ):
                raise ValueError(f"{kind!r} directive needs step=N ({raw!r})")
            directives.append(
                _Directive(
                    kind=kind, step=opts.get("step"), times=opts.get("times", 1),
                    seconds=opts.get("seconds", 3600),
                )
            )
        return cls(directives)


class FaultInjector:
    """Replays a :class:`FaultPlan`; every hook is deterministic and cheap."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan

    @classmethod
    def from_spec(cls, spec: Optional[str]) -> Optional["FaultInjector"]:
        spec = spec if spec is not None else os.environ.get(ENV_FAULT_PLAN)
        if not spec:
            return None
        return cls(FaultPlan.parse(spec))

    def _pending(self, kind: str, step: Optional[int] = None):
        for d in self.plan.directives:
            if d.kind != kind or d.fired >= d.times:
                continue
            if step is not None and d.step != step:
                continue
            return d
        return None

    # -- hooks ---------------------------------------------------------------
    def maybe_init_fault(self, timeout_s: float) -> Optional[str]:
        """Simulate one hung init probe; returns the failure detail, or None
        to let the real probe run."""
        directive = self._pending("init_hang")
        if directive is None:
            return None
        directive.fired += 1
        return (
            f"backend init exceeded {timeout_s:.0f}s (hung PJRT client) "
            "[injected]"
        )

    def maybe_sigterm(self, dispatch_index: int) -> None:
        """Deliver a real SIGTERM before the given dispatch (the handler the
        preemption guard installed sets its sticky flag synchronously)."""
        directive = self._pending("sigterm", step=dispatch_index)
        if directive is None:
            return
        directive.fired += 1
        os.kill(os.getpid(), signal.SIGTERM)

    def maybe_host_lost(self, dispatch_index: int) -> bool:
        """True when a scheduled host loss fires at this dispatch — the
        elastic fleet runtime's preemption signal (a real fleet would read
        the scheduler's reclamation notice here)."""
        directive = self._pending("host_lost", step=dispatch_index)
        if directive is None:
            return False
        directive.fired += 1
        return True

    def maybe_host_gained(self, dispatch_index: int) -> bool:
        """True when a scheduled host RETURN fires at this dispatch — the
        grow-side signal (a real fleet would read the scheduler's rejoin
        beacon here; docs/elastic.md)."""
        directive = self._pending("host_gained", step=dispatch_index)
        if directive is None:
            return False
        directive.fired += 1
        return True

    def maybe_signal_storm(self, dispatch_index: int) -> Optional[bool]:
        """Storm override for the autopilot's skew sample: ``True`` = spike
        above the threshold, ``False`` = drop below it, ``None`` = no storm
        active.  Unlike the step-pinned verbs, a storm runs from its start
        dispatch for ``times`` consecutive ticks, alternating spike/drop —
        the flap the hysteresis window must suppress."""
        for d in self.plan.directives:
            if (
                d.kind == "signal_storm"
                and d.fired < d.times
                and d.step is not None
                and dispatch_index >= d.step
            ):
                d.fired += 1
                return d.fired % 2 == 1  # spike first, then drop, then spike...
        return None

    def maybe_hang(self, dispatch_index: int) -> bool:
        """Sleep for the directive's ``seconds`` right before the given
        dispatch — this rank goes silent while its peers block in their next
        collective.  Records a ``hang_injected`` flight event *before*
        sleeping (so the postmortem dump shows the injection, not a
        mystery); returns True when a hang fired."""
        directive = self._pending("hang", step=dispatch_index)
        if directive is None:
            return False
        directive.fired += 1
        from ..telemetry import flightrec

        flightrec.record(
            "hang_injected", step=dispatch_index, seconds=directive.seconds
        )
        import time

        time.sleep(directive.seconds)
        return True

    def maybe_decode_fault(self, step_index: int) -> None:
        """Raise a transient fault inside the serving decode dispatch for
        the given ENGINE STEP index (``DecodeService.stats["steps"]``);
        retries of the same step keep hitting this until ``times`` is
        exhausted — which is how the eviction-and-requeue exhaustion path
        is driven (docs/serving.md §fault tolerance)."""
        directive = self._pending("decode_fault", step=step_index)
        if directive is None:
            return
        directive.fired += 1
        raise InjectedTransientError(
            f"UNAVAILABLE: injected transient decode fault at engine step "
            f"{step_index} (firing {directive.fired}/{directive.times})"
        )

    def maybe_serving_sigterm(self, step_index: int) -> None:
        """Deliver a real SIGTERM right before the given serving engine
        step — the mid-decode preemption (slots in flight) the request
        journal + drain path recovers from."""
        directive = self._pending("serving_sigterm", step=step_index)
        if directive is None:
            return
        directive.fired += 1
        os.kill(os.getpid(), signal.SIGTERM)

    def maybe_dispatch_fault(self, dispatch_index: int) -> None:
        """Raise a transient fault for the given dispatch; retries of the same
        call keep hitting this until ``times`` is exhausted."""
        directive = self._pending("dispatch", step=dispatch_index)
        if directive is None:
            return
        directive.fired += 1
        raise InjectedTransientError(
            f"UNAVAILABLE: injected transient dispatch fault at step "
            f"{dispatch_index} (firing {directive.fired}/{directive.times})"
        )
