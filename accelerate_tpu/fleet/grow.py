"""Grow-side resize — re-mesh dp *up* when a host comes back.

PR 11's resize only shrank: growing was refused with "new hosts need a
rendezvous, which is a relaunch".  This module implements that rendezvous
half (the torchelastic new-member flow we deferred): a returned host — the
``host_gained`` fault-plan verb on CPU, a scheduler's rejoin beacon in
production — trips ``fleet.should_grow``; ``fleet.grow()`` then drains a
COMPLETE checkpoint, runs the **grow rendezvous barrier** (every rank
gathers its proposed target and visible device set; a pure agreement
function accepts the plan only when every rank proposes the identical
topology), widens the ``dp`` axis over the rejoined device blocks, re-lays
ZeRO-1 masters/moments and compression residuals onto the wider mesh
(``remesh_accelerator`` — the exact relayout the shrink path uses), AOT-
prewarms the wider topology so recovery is deserialize-not-compile, and
reshards the spec-carrying checkpoint onto it — masters/moments bitwise
versus a from-checkpoint cold start, same 1e-3 loss-parity bound as the
shrink (dp reduce order moves; docs/elastic.md).

Device accounting: the dp axis is outermost, so a host's devices are whole
dp-axis blocks.  ``grown_mesh`` appends the rejoined blocks AFTER the
survivors' blocks, drawn from the process-visible device pool in stable id
order — every rank computes the identical mesh, which the rendezvous
ballot then double-checks before anything re-lays out.

On a real multi-host fleet the NEW process must additionally join the
``jax.distributed`` world before its devices appear in the pool; the
rendezvous barrier here is exercised under a real 2-process gloo/CPU
world in ``tests/test_fleet_distributed.py``.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
from jax.sharding import Mesh

from ..logging import get_logger
from ..utils.operations import gather_object

logger = get_logger(__name__)


def _device_pool(devices=None) -> list:
    if devices is not None:
        return list(devices)
    import jax

    return list(jax.devices())


def max_growable_dp(mesh: Mesh, devices=None, non_dp_extent=None) -> int:
    """The dp ceiling the visible device pool supports at this mesh's inner
    extents — what a grow decision bounds its target by.  Callers with a
    resolved plan pass ``plan.non_dp_extent`` (the plan owns the re-mesh
    constraint, docs/parallel_plan.md); the mesh walk remains as the
    plan-less fallback for direct API use."""
    if non_dp_extent is not None:
        inner = int(non_dp_extent)
    else:
        inner = 1
        for axis, size in dict(mesh.shape).items():
            if axis != "dp":
                inner *= size
    pool = _device_pool(devices)
    return len(pool) // max(1, inner)


def grown_axis_sizes(mesh: Mesh, target_dp: int) -> dict[str, int]:
    """The widened axis-size dict: ``dp`` grown to ``target_dp``, every
    other axis preserved.  Validates the grow is a real widening."""
    sizes = dict(mesh.shape)
    dp = sizes.get("dp", 1)
    if target_dp <= dp:
        raise ValueError(
            f"grow needs target_dp > current dp ({target_dp} <= {dp}); "
            "shrinking is fleet.resize()'s job"
        )
    sizes["dp"] = target_dp
    return sizes


def grown_mesh(mesh: Mesh, target_dp: int, devices=None) -> Mesh:
    """The mesh widened to ``target_dp`` dp blocks: the current blocks stay
    in place (live state never moves under a grow — only the NEW blocks
    receive resharded state) and the rejoined blocks are appended from the
    device pool in stable id order, so every rank builds the identical
    mesh.  ``devices`` overrides the pool (tests, explicit rejoin notices);
    default is every process-visible device."""
    sizes = grown_axis_sizes(mesh, target_dp)
    if "dp" not in mesh.axis_names:
        raise ValueError(f"mesh {dict(mesh.shape)} has no dp axis to grow")
    dp_index = mesh.axis_names.index("dp")
    dp = mesh.shape["dp"]
    current = {d.id for d in mesh.devices.flat}
    pool = _device_pool(devices)
    candidates = sorted(
        (d for d in pool if d.id not in current), key=lambda d: d.id
    )
    inner_shape = list(mesh.devices.shape)
    inner = int(np.prod([s for i, s in enumerate(inner_shape) if i != dp_index]))
    needed = (target_dp - dp) * inner
    if len(candidates) < needed:
        raise ValueError(
            f"grow to dp={target_dp} needs {needed} rejoined devices; only "
            f"{len(candidates)} are visible outside the current mesh"
        )
    block_shape = list(inner_shape)
    block_shape[dp_index] = target_dp - dp
    new_blocks = np.asarray(candidates[:needed], dtype=object).reshape(block_shape)
    device_array = np.concatenate([mesh.devices, new_blocks], axis=dp_index)
    new = Mesh(device_array, axis_names=mesh.axis_names)
    assert dict(new.shape) == sizes
    return new


# ---------------------------------------------------------------------------
# rendezvous barrier — pure agreement over gathered proposals
# ---------------------------------------------------------------------------

def grow_proposal(mesh: Mesh, target_dp: int, devices=None) -> dict:
    """This rank's rendezvous ballot: the target extent and the exact
    device ids the widened mesh would bind, in mesh order.  A rank that
    CANNOT build the target (the rejoined host is not visible to it yet)
    ballots its error instead of crashing the barrier — the rendezvous must
    abort cleanly, with the straggler named in the recorded ballot."""
    try:
        ids = [
            int(d.id)
            for d in grown_mesh(mesh, target_dp, devices=devices).devices.flat
        ]
    except ValueError as exc:
        return {"target_dp": int(target_dp), "error": str(exc)[:200]}
    return {"target_dp": int(target_dp), "device_ids": ids}


def agree_grow(per_rank: list[dict]) -> Optional[dict]:
    """The grow plan every rank can execute: all ranks must propose the
    IDENTICAL target and device list — any disagreement (a rank that
    cannot see the rejoined host yet, a straggling notice) aborts the grow
    rather than letting ranks re-mesh onto different topologies and
    deadlock the first collective.  ``None`` = no agreement."""
    if not per_rank:
        return None
    first = per_rank[0]
    if "device_ids" not in first:
        return None  # an error ballot — even unanimously, there is no plan
    for proposal in per_rank[1:]:
        if proposal != first:
            return None
    return dict(first)


def grow_rendezvous(accelerator, target_dp: int, fleet=None,
                    devices=None) -> Optional[dict]:
    """COLLECTIVE — every rank must call (``fleet.grow`` does).  Gathers
    each rank's proposal and returns the agreement; every rank computes it
    from the same gathered ballot, so no second broadcast is needed.
    Records a ``grow_rendezvous`` fleet event with the full ballot."""
    local = grow_proposal(accelerator.state.mesh, target_dp, devices=devices)
    per_rank = gather_object([local])
    agreed = agree_grow(per_rank)
    if fleet is not None:
        fleet.record_event(
            "grow_rendezvous",
            ranks=len(per_rank),
            ballot=[dict(p) for p in per_rank],
            agreed=agreed is not None,
            target_dp=agreed["target_dp"] if agreed is not None else None,
        )
    if agreed is None:
        logger.warning(
            "grow rendezvous found no agreement across %d ranks", len(per_rank)
        )
    return agreed


__all__ = [
    "agree_grow",
    "grow_proposal",
    "grow_rendezvous",
    "grown_axis_sizes",
    "grown_mesh",
    "max_growable_dp",
]
