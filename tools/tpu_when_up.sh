#!/bin/bash
# Watch for the TPU tunnel to return; when it does, run the round-5 queued
# perf work ONCE, in VERDICT priority order, leaving artifacts in the repo
# root (picked up by the round-end auto-commit if no one is around).
#   1. plain bench.py            -> BENCH_r05_live.json  (the headline artifact)
#   2. BENCH_FULL staged extras  -> BENCH_FULL_r05.json  (BERT-MRPC row first —
#                                    the BASELINE primary metric)
#   2b. flash bwd block sweep    -> in-log JSON lines (the dq write-amp fix
#                                    changed the tiling economics; fwd blocks
#                                    are covered by the flag experiments)
#   3. flag experiments          -> TPU_EXPERIMENTS_r05.log
#   4. best-config bench rerun   -> BENCH_r05_best.json (only if a flag
#                                    experiment beat the plain run AND the
#                                    100-step replay confirms it)
#   5. profiler trace            -> /tmp/tpu_sweep5/trace (+ note in log)
# Usage: setsid nohup bash tools/tpu_when_up.sh &
set -u
cd "$(dirname "$0")/.."
MARK=/tmp/tpu_when_up_r05.ran
[ -e "$MARK" ] && exit 0
while true; do
  ok=$(timeout -k 10 110 python - <<'EOF' 2>/dev/null
import jax
d = jax.devices()
print("UP" if d and d[0].platform in ("tpu", "axon") else "")
EOF
  )
  if echo "$ok" | grep -q UP; then break; fi
  sleep 300
done
touch "$MARK"
{
  echo "== TPU returned $(date -u +%FT%TZ) =="
  echo "== 1. plain bench (driver-format artifact) =="
  BENCH_INIT_ATTEMPTS=2 timeout 1800 python bench.py 2>/tmp/bench_r05_err.log \
    | tee BENCH_r05_live.json
  echo "== 2. BENCH_FULL staged extras (BERT-MRPC primary row first) =="
  BENCH_FULL=1 BENCH_INIT_ATTEMPTS=2 BENCH_PARTIAL_PATH=BENCH_FULL_r05.json \
    timeout 4900 python bench.py 2>/tmp/bench_full_r05_err.log
  echo "== 2b. flash bwd block sweep (write-amp fix changes the tiling economics) =="
  for BK in 256 512; do
    echo "-- bwd block $BK --"
    ACCELERATE_TPU_FLASH_BWD_BLOCK_Q=$BK ACCELERATE_TPU_FLASH_BWD_BLOCK_K=$BK \
      BENCH_INIT_ATTEMPTS=2 timeout 1200 python bench.py \
      2>/tmp/bench_sweep_r05_bwd${BK}_err.log
  done
  echo "== 3. flag experiments =="
  bash tools/tpu_flag_experiments.sh /tmp/tpu_exp5 && cat /tmp/tpu_exp5/exp.log
  echo "== 4. best-config bench rerun (if an experiment beat the plain run) =="
  bash tools/tpu_best_rerun.sh /tmp/tpu_exp5/exp.log BENCH_r05_live.json \
    BENCH_r05_best.json || true
  echo "== 5. profiler trace =="
  bash tools/tpu_trace.sh /tmp/tpu_sweep5 || true
} > TPU_EXPERIMENTS_r05.log 2>&1
