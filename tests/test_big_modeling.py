"""Big-model machinery end-to-end tests (mirrors reference tests/test_big_modeling.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import accelerate_tpu.nn as nn
from accelerate_tpu.big_modeling import (
    cpu_offload,
    cpu_offload_with_hook,
    disk_offload,
    dispatch_model,
    init_empty_weights,
    init_on_device,
    load_checkpoint_and_dispatch,
    materialize_meta_module,
    shard_for_inference,
)
from accelerate_tpu.nn.meta import is_meta
from accelerate_tpu.utils.modeling import find_tied_parameters


class ModelForTest(nn.Module):
    def __init__(self):
        super().__init__()
        self.linear1 = nn.Linear(3, 4)
        self.batchnorm = nn.LayerNorm(4)
        self.linear2 = nn.Linear(4, 5)

    def forward(self, x):
        return self.linear2(self.batchnorm(self.linear1(x)))


class BiggerModelForTest(nn.Module):
    def __init__(self):
        super().__init__()
        self.linear1 = nn.Linear(3, 4)
        self.linear2 = nn.Linear(4, 5)
        self.batchnorm = nn.LayerNorm(5)
        self.linear3 = nn.Linear(5, 6)
        self.linear4 = nn.Linear(6, 5)

    def forward(self, x):
        return self.linear4(self.linear3(self.batchnorm(self.linear2(self.linear1(x)))))


def test_init_empty_weights():
    with init_empty_weights():
        model = ModelForTest()
    assert all(is_meta(p.data) for p in model.parameters())
    # sizing works, forward obviously can't run
    assert model.num_parameters == 3 * 4 + 4 + 4 + 4 + 4 * 5 + 5


def test_init_empty_weights_without_buffers():
    class WithBuffer(nn.Module):
        def __init__(self):
            super().__init__()
            self.linear = nn.Linear(2, 2)
            from accelerate_tpu.nn import init as nn_init

            self.register_buffer("pos", nn_init.arange(8))

    with init_empty_weights(include_buffers=False):
        model = WithBuffer()
    assert is_meta(model.linear.weight.data)
    # buffers keep their TRUE values (not zeros) in this mode
    np.testing.assert_array_equal(np.asarray(model.pos.data), np.arange(8))


def test_init_on_device():
    cpu = jax.local_devices(backend="cpu")[0]
    with init_on_device(cpu):
        model = ModelForTest()
    assert list(model.linear1.weight.data.devices())[0].platform == "cpu"


def test_materialize_meta_module():
    with init_empty_weights():
        model = ModelForTest()
    materialize_meta_module(model, device=0)
    assert not any(is_meta(p.data) for p in model.parameters())
    out = model(nn.Tensor(jnp.ones((2, 3))))
    assert out.shape == (2, 5)


def test_cpu_offload():
    model = ModelForTest()
    x = nn.Tensor(jnp.ones((2, 3)))
    base = model(x).numpy()
    cpu_offload(model, execution_device=0)
    np.testing.assert_allclose(model(x).numpy(), base, rtol=1e-5, atol=1e-6)
    # params parked again after forward
    assert is_meta(model.linear1.weight.data)


def test_cpu_offload_with_hook():
    model1 = ModelForTest()
    model2 = ModelForTest()
    x = nn.Tensor(jnp.ones((2, 3)))
    e1 = model1(x).numpy()
    model1, hook1 = cpu_offload_with_hook(model1, execution_device=0)
    model2, hook2 = cpu_offload_with_hook(model2, execution_device=0, prev_module_hook=hook1)
    np.testing.assert_allclose(model1(x).numpy(), e1, rtol=1e-5)
    model2(x)  # offloads model1 first
    dev = list(model1.linear1.weight.data.devices())[0]
    assert dev.platform == "cpu"
    hook2.remove()


def test_disk_offload(tmp_path):
    model = ModelForTest()
    x = nn.Tensor(jnp.ones((2, 3)))
    base = model(x).numpy()
    disk_offload(model, str(tmp_path / "offload"), execution_device=0)
    np.testing.assert_allclose(model(x).numpy(), base, rtol=1e-5, atol=1e-6)
    assert (tmp_path / "offload" / "index.json").exists()


def test_dispatch_model_multichip():
    model = BiggerModelForTest()
    x = nn.Tensor(jnp.ones((2, 3)))
    base = model(x).numpy()
    device_map = {"linear1": 0, "linear2": 1, "batchnorm": 1, "linear3": 2, "linear4": 3}
    dispatch_model(model, device_map)
    np.testing.assert_allclose(model(x).numpy(), base, rtol=1e-5, atol=1e-6)
    # weights actually live on their mapped chips
    assert list(model.linear1.weight.data.devices())[0] == jax.devices()[0]
    assert list(model.linear3.weight.data.devices())[0] == jax.devices()[2]


def test_dispatch_model_cpu_offload(tmp_path):
    model = BiggerModelForTest()
    x = nn.Tensor(jnp.ones((2, 3)))
    base = model(x).numpy()
    device_map = {"linear1": 0, "linear2": 0, "batchnorm": 0, "linear3": "cpu", "linear4": "disk"}
    dispatch_model(model, device_map, offload_dir=str(tmp_path / "off"))
    np.testing.assert_allclose(model(x).numpy(), base, rtol=1e-5, atol=1e-6)
    # offloaded blocks are parked outside forward
    assert is_meta(model.linear4.weight.data)


def test_dispatch_model_tied_weights():
    class Tied(nn.Module):
        def __init__(self):
            super().__init__()
            self.a = nn.Linear(4, 4, bias=False)
            self.b = nn.Linear(4, 4, bias=False)
            self.b.weight = self.a.weight

        def forward(self, x):
            return self.b(self.a(x))

    model = Tied()
    x = nn.Tensor(jnp.ones((2, 4)))
    base = model(x).numpy()
    dispatch_model(model, {"a": 0, "b": "cpu"})
    np.testing.assert_allclose(model(x).numpy(), base, rtol=1e-5, atol=1e-6)
    assert find_tied_parameters(model) == [["a.weight", "b.weight"]]


def test_load_checkpoint_and_dispatch_auto(tmp_path):
    from safetensors.numpy import save_file

    src = BiggerModelForTest()
    x = nn.Tensor(jnp.ones((2, 3)))
    base = src(x).numpy()
    sd = {k: np.asarray(v) for k, v in src.state_dict().items()}
    save_file(sd, str(tmp_path / "model.safetensors"))

    with init_empty_weights():
        model = BiggerModelForTest()
    model = load_checkpoint_and_dispatch(
        model, str(tmp_path / "model.safetensors"), device_map="auto",
        max_memory={0: 200, 1: 200, "cpu": 10_000},
    )
    assert hasattr(model, "atpu_device_map")
    np.testing.assert_allclose(model(x).numpy(), base, rtol=1e-5, atol=1e-6)


def test_shard_for_inference_matches():
    model = ModelForTest()
    x = nn.Tensor(jnp.ones((2, 3)))
    base = model(x).numpy()
    from accelerate_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    shard_for_inference(
        model, mesh, tp_plan={r".*linear1\.weight": ("tp", None), r".*linear2\.weight": (None, "tp")}
    )
    np.testing.assert_allclose(model(x).numpy(), base, rtol=1e-5, atol=1e-6)
    # linear1 weight is actually sharded over 2 chips
    shards = model.linear1.weight.data.sharding.device_set
    assert len(shards) == 2


def test_shard_for_inference_rejects_meta():
    with init_empty_weights():
        model = ModelForTest()
    with pytest.raises(ValueError):
        shard_for_inference(model)
