"""Toy models/data for numerically-checkable training.

Counterpart of ``/root/reference/src/accelerate/test_utils/training.py``
(RegressionModel/RegressionDataset :1-162): y = a·x + b with scalar learnable
a, b, so trained weights can be asserted against a closed-form/single-process
baseline exactly.
"""

from __future__ import annotations

import numpy as np

import accelerate_tpu.nn as nn
from accelerate_tpu.nn import Tensor

__all__ = ["RegressionDataset", "RegressionModel", "mocked_dataloaders"]


class RegressionDataset:
    """List-like dataset of {'x': float, 'y': 2x+1+noise} samples."""

    def __init__(self, a=2, b=3, length=64, seed=96):
        rng = np.random.default_rng(seed)
        self.length = length
        self.a, self.b = a, b
        self.x = rng.normal(size=(length,)).astype(np.float32)
        self.y = (a * self.x + b + 0.1 * rng.normal(size=(length,))).astype(np.float32)

    def __len__(self):
        return self.length

    def __getitem__(self, i):
        return {"x": self.x[i], "y": self.y[i]}


class RegressionModel(nn.Module):
    """y_hat = a*x + b (reference training.py RegressionModel)."""

    def __init__(self, a=0.0, b=0.0):
        super().__init__()
        self.a = nn.Parameter(np.array(float(a), dtype=np.float32))
        self.b = nn.Parameter(np.array(float(b), dtype=np.float32))

    def forward(self, x):
        if not isinstance(x, Tensor):
            x = Tensor(x)
        return x * self.a + self.b


def mocked_dataloaders(accelerator, batch_size: int = 8, length: int = 64):
    """Tiny deterministic train/val loaders (reference
    tests/test_examples.py mocked_dataloaders)."""
    from accelerate_tpu import prepare_data_loader

    train = RegressionDataset(length=length, seed=42)
    val = RegressionDataset(length=length // 2, seed=43)
    train_dl = prepare_data_loader(
        dataset=[train[i] for i in range(len(train))],
        batch_size=batch_size,
        shuffle=True,
        data_seed=42,
    )
    val_dl = prepare_data_loader(
        dataset=[val[i] for i in range(len(val))], batch_size=batch_size
    )
    return train_dl, val_dl
