"""Persistent AOT executable cache — the zero-cold-start subsystem
(``accelerator.aot_cache``, docs/aot_cache.md).

Every fresh process — a preempted-and-rescheduled worker, an autoscaled
serving replica, a bench rerun — pays full trace+compile before its first
useful step.  The capture path already builds through ``jit.lower().
compile()`` (the AOT split telemetry measures); this module persists that
compiled object across processes: ``jax.experimental.serialize_executable``
pickles the underlying PJRT executable (donation, shardings and out-tree
included), and a later process with a matching topology fingerprint
deserializes it and dispatches — **zero trace, zero XLA compile**, bit-for-
bit the same program.

Layout (one directory, ``CompilationCacheKwargs.cache_dir`` /
``$ACCELERATE_AOT_CACHE``):

* ``{variant}-{fp}.pkl`` — pickled ``{payload, in_tree, out_tree, side}``
  where ``payload`` is the serialized executable, the trees are the pickled
  pytree defs ``serialize`` hands back, and ``side`` carries the trace-time
  metadata a skipped trace can no longer discover (``uses_accumulate``,
  deferred scheduler replays by registry index).
* ``{variant}-{fp}.json`` — metadata: the full fingerprint dict, byte size,
  the compile_ms the entry cost (reported as ``avoided_compile_ms`` on
  every later hit), created/used stamps for LRU, and a human key
  description.  Listing/eviction/mismatch diagnosis never unpickles.
* ``profile-{step}.json`` — per-captured-step sidecar (``uses_accumulate``)
  consulted *before* the first call computes its cache key, so an
  accumulate-using body advances its schedule host-side exactly like a warm
  step and lands on the key the cold process stored under.

Key anatomy: the **variant digest** hashes the existing capture cache key
(arg treedef/shapes/dtypes, ``sync_gradients``, training modes) extended
with the carried state's structure (treedef, per-leaf shape/dtype/sharding/
memory-kind), the donation split (host mask) and a digest of the step
body's source.  The **fingerprint digest** hashes the topology/compiler
environment: jax+jaxlib versions, platform, device kind+count, process
count, mesh shape, compression policy, the compiler-mode flags
(``FINGERPRINT_FLAGS`` — ``jax_default_matmul_precision`` et al., whose
flip would otherwise deserialize a program compiled under the other
numerics silently) and the cache format version.  A
lookup globs ``{variant}-*``: an exact fingerprint match is a hit; a
variant match under a DIFFERENT fingerprint is the stale-entry case — the
mismatching fields are named in a loud ``kind="aot_cache"`` miss record and
the caller falls through to a normal compile.  Never a crash, never a
wrong-program dispatch.

Multi-host atomicity: entries are written to a per-pid temp file in the
cache dir and ``os.replace``d into place, so concurrent writers (every
host of a fleet warming the same NFS/GCS-fuse dir) can race freely — a
reader sees either the old complete entry or the new complete entry,
never a torn one.  All IO is fail-soft: a corrupt/truncated/unpicklable
entry is a miss with a cause, not an exception on the step path.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import pickle
import tempfile
import time
from typing import Any, Optional

from ..logging import get_logger

logger = get_logger(__name__)

# Process-wide latch for the jax-compilation-cache second layer: once a
# scope-dependent (profiler-armed) run disarms it, NO later-constructed
# cache may silently re-arm it — jax's config is global, the sampler stays
# live for the process, and cache-served executables carry no HLO scope
# metadata (docs/telemetry.md §phases).  A latch, not an instance field:
# the hazard is exactly that a DIFFERENT instance re-arms the layer.
_JAX_CACHE_LAYER_DISARMED = False


def _jax_cache_layer_disarmed() -> bool:
    return _JAX_CACHE_LAYER_DISARMED


def _set_jax_cache_layer_disarmed(value: bool) -> None:
    global _JAX_CACHE_LAYER_DISARMED
    _JAX_CACHE_LAYER_DISARMED = value

# bump when the entry layout / side-metadata schema changes: old entries
# then report a format mismatch and fall through to a normal compile
# (2: compiler flags joined the fingerprint as flat flag:* fields)
# (3: the resolved ParallelPlan digest joined as the `plan` field — a
#  schedule/virtual-stage/ZeRO/compression flip is a loud miss naming it)
AOT_CACHE_FORMAT = 3

# compiler-mode flags that change the COMPILED PROGRAM without moving any
# shape/dtype/topology field the fingerprint already hashes: a flip between
# the storing and loading process would deserialize a program compiled
# under the other mode and silently dispatch the wrong numerics.  Flat
# ``flag:<name>`` fields (not one nested dict) so a stale-flag miss names
# the exact flag that moved.
FINGERPRINT_FLAGS = (
    "jax_default_matmul_precision",
    "jax_enable_x64",
    "jax_numpy_dtype_promotion",
    "jax_numpy_rank_promotion",
    "jax_default_prng_impl",
)

# the active enabled cache — serving constructs (DecodeService) resolve it
# here when no explicit cache is passed, mirroring telemetry's module slot
_ACTIVE: Optional["AOTCompilationCache"] = None


def current_aot_cache() -> Optional["AOTCompilationCache"]:
    return _ACTIVE


def _set_active(cache: Optional["AOTCompilationCache"]) -> None:
    global _ACTIVE
    _ACTIVE = cache


def _digest(obj: Any) -> str:
    """Stable content digest of a JSON-able description."""
    blob = json.dumps(obj, sort_keys=True, default=repr).encode("utf-8")
    return hashlib.sha256(blob).hexdigest()[:16]


def _leaf_aval(x) -> list:
    """(shape, dtype, sharding, memory_kind) description of one state/arg
    leaf — what must match for a stored executable to accept it."""
    import numpy as _np

    shape = list(_np.shape(x))
    dtype = getattr(x, "dtype", None)  # typed PRNG keys stringify as key<fry>
    if dtype is None and x is not None:
        try:
            dtype = _np.result_type(x)
        except TypeError:
            dtype = type(x).__name__
    dtype = str(dtype)
    s = getattr(x, "sharding", None)
    return [shape, dtype, repr(s) if s is not None else None,
            getattr(s, "memory_kind", None)]


def topology_fingerprint(mesh=None, compression: Optional[str] = None,
                         kernels: Optional[str] = None,
                         plan: Optional[dict] = None) -> dict:
    """The invalidation matrix (docs/aot_cache.md): any field moving between
    the storing and the loading process makes the entry stale.  ``kernels``
    is the armed Pallas-kernel set (``KernelPolicy.describe()``,
    docs/kernels.md): a kernel-armed program computes through different IR
    than the reference path, so flipping a kernel must be a loud miss
    NAMING the ``kernels`` field — never a silently-stale executable.
    ``plan`` is the resolved ``ParallelPlan.describe()`` digest
    (docs/parallel_plan.md): the pipeline schedule / virtual-stage factor /
    ZeRO modes shape the compiled program beyond the raw mesh dict, so a
    plan flip must likewise be a loud miss NAMING the ``plan`` field."""
    import jax
    import jaxlib

    devices = jax.devices()
    fingerprint = {
        "format": AOT_CACHE_FORMAT,
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": devices[0].platform,
        "device_kind": devices[0].device_kind,
        "device_count": len(devices),
        "process_count": jax.process_count(),
        "mesh": dict(mesh.shape) if mesh is not None else None,
        "compression": compression,
        "kernels": kernels or "none",
        "plan": plan,
    }
    for flag in FINGERPRINT_FLAGS:
        # repr, not str: distinguishes unset (None) from the string "None",
        # and keeps every value JSON-stable
        fingerprint[f"flag:{flag}"] = repr(getattr(jax.config, flag, None))
    return fingerprint


def fingerprint_mismatch(stored: Optional[dict], live: dict) -> str:
    """Human cause naming exactly which fingerprint fields moved.  When
    nothing moved the entry itself is broken (an orphaned metadata file, a
    torn write) — say that instead of the self-contradictory 'match'."""
    if not isinstance(stored, dict):
        return "entry metadata carries no fingerprint"
    moved = []
    for field in sorted(set(stored) | set(live)):
        if stored.get(field) != live.get(field):
            moved.append(f"{field} {stored.get(field)!r} -> {live.get(field)!r}")
    if not moved:
        return (
            "entry unreadable despite matching fingerprint "
            "(missing or torn payload)"
        )
    return "fingerprint mismatch: " + "; ".join(moved)


def _atomic_write_bytes(path: str, data: bytes) -> None:
    """Write-then-rename so concurrent multi-host writers never tear an
    entry; the temp file lives in the same dir (rename must not cross
    filesystems)."""
    fd, tmp = tempfile.mkstemp(
        prefix=os.path.basename(path) + ".", suffix=f".{os.getpid()}.tmp",
        dir=os.path.dirname(path),
    )
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _atomic_write_json(path: str, payload: dict) -> None:
    _atomic_write_bytes(path, json.dumps(payload, sort_keys=True).encode("utf-8"))


class AOTCompilationCache:
    """The on-disk store plus hit/miss accounting; inert when disabled."""

    def __init__(self, handler=None):
        if handler is None:
            from ..utils.dataclasses import CompilationCacheKwargs

            handler = CompilationCacheKwargs()
        self.handler = handler
        self.enabled = bool(handler.enabled) and handler.cache_dir is not None
        self.cache_dir = handler.cache_dir
        self.max_bytes = int(handler.max_bytes)
        self.warm_on_restore = bool(handler.warm_on_restore)
        self.hits = 0
        self.misses = 0
        self.stores = 0
        self.evictions = 0
        self.last_prefetch_count = 0
        self._metrics_memo = None  # (monotonic, entries, bytes) scrape memo
        self._prefetched: dict[str, bytes] = {}
        self._telemetry = None
        self._fingerprint: Optional[dict] = None
        if not self.enabled:
            return
        try:
            os.makedirs(self.cache_dir, exist_ok=True)
        except OSError as exc:
            logger.warning(
                "AOT cache dir %r is unusable (%s); cache disabled", self.cache_dir, exc
            )
            self.enabled = False
            return
        if handler.jax_cache_dir:
            if _jax_cache_layer_disarmed():
                # a profiler-armed hub already disarmed the layer for this
                # PROCESS (attach_telemetry below): the config is global,
                # and a later-constructed cache silently re-arming it would
                # reintroduce metadata-less cache-served executables while
                # the sampler is still live
                logger.info(
                    "jax compilation cache layer (%s) NOT armed: disarmed "
                    "process-wide for a scope-dependent run",
                    handler.jax_cache_dir,
                )
            else:
                # second layer (SNIPPETS.md [2]): jax's own persistent XLA
                # compilation cache catches programs outside the capture path
                try:
                    import jax

                    jax.config.update(
                        "jax_compilation_cache_dir", handler.jax_cache_dir
                    )
                except Exception as exc:
                    logger.warning("jax compilation cache dir not set: %s", exc)

    # -- telemetry -----------------------------------------------------------
    def attach_telemetry(self, hub) -> None:
        """Pin the enabled telemetry hub so every hit/miss/store lands as a
        ``kind="aot_cache"`` record, and expose the live counters on the
        hub's Prometheus endpoint (``atpu_aot_cache_hits_total`` /
        ``_misses_total``).

        Scope-fidelity guard (ROADMAP carried item, docs/telemetry.md
        §phases): when the hub samples device time (``profile_every_n``),
        the per-phase split joins trace events to the op→scope map parsed
        from the compiled program's HLO metadata — but an executable served
        by jax's own XLA compilation cache (the ``jax_cache_dir`` second
        layer) carries NO metadata, and unlike the first-layer AOT store it
        has no side payload to persist the storing process's map in.  A
        pre-compile parse can't substitute either: the lowered module's
        scope paths hang off UNOPTIMIZED instruction names, which never
        match the post-fusion names trace events carry.  So a
        scope-dependent run disarms that layer — every program it compiles
        is a real compile whose metadata is parseable, and the per-phase
        device split stays populated regardless of warm/cold.  The
        first-layer AOT store keeps serving (its entries carry the
        persisted map)."""
        if hub is None or not getattr(hub, "enabled", False) or not self.enabled:
            return
        self._telemetry = hub
        hub.register_metrics_provider("aot_cache", self.metrics)
        if getattr(hub, "profiler", None) is not None:
            # the hazard is the PROCESS-GLOBAL config, not this instance's
            # own knob: another cache may have armed the layer already (or
            # may try later), so a dir-less cache attaching the sampler
            # must still disarm whatever is set and latch the process
            armed_dir = None
            try:
                import jax

                armed_dir = jax.config.jax_compilation_cache_dir
                if armed_dir:
                    jax.config.update("jax_compilation_cache_dir", None)
            except Exception as exc:
                logger.warning(
                    "could not disarm the jax compilation cache for the "
                    "scope-dependent run: %s", exc,
                )
                return
            # latch it process-wide: any cache constructed AFTER this point
            # must not re-arm the layer (the __init__ arm checks the latch)
            _set_jax_cache_layer_disarmed(True)
            if armed_dir or self.handler.jax_cache_dir:
                logger.info(
                    "jax compilation cache layer (%s) disarmed: device-time "
                    "sampling is on, and cache-served executables carry no "
                    "HLO scope metadata (phases would sample empty)",
                    armed_dir or self.handler.jax_cache_dir,
                )
                self._record(
                    "jax_cache_layer_disarmed", scope="train",
                    key="jax_cache_dir",
                    cause="device-time sampling armed: executables served "
                    "from the XLA compilation cache carry no HLO metadata "
                    "and would sample empty phases",
                )

    _METRICS_TTL_S = 15.0  # dir-stat memo: scrapes must not stat a shared
    # NFS/GCS cache dir per entry every 15 s — counters below are live ints

    def metrics(self) -> dict:
        now = time.monotonic()
        memo = self._metrics_memo
        if memo is None or now - memo[0] > self._METRICS_TTL_S:
            entries, total = self._entries()
            memo = self._metrics_memo = (now, len(entries), total)
        return {
            "hits_total": self.hits,
            "misses_total": self.misses,
            "stores_total": self.stores,
            "evictions_total": self.evictions,
            "entries": memo[1],
            "bytes": memo[2],
        }

    def _record(self, event: str, **fields) -> None:
        if self._telemetry is not None:
            self._telemetry.record_aot_cache({"event": event, **fields})
        # scalar mirror into the flight ring (docs/telemetry.md §flight
        # recorder): AOT-store I/O — hit / miss / store / store_failed —
        # is postmortem-relevant (a hang inside deserialize_and_load shows
        # as a hit with no following step_begin)
        from ..telemetry import flightrec

        flightrec.record(
            "aot_cache",
            event=event,
            **{k: v for k, v in fields.items()
               if v is None or isinstance(v, (bool, int, float, str))},
        )

    # -- fingerprint ---------------------------------------------------------
    def set_context(self, mesh=None, compression: Optional[str] = None,
                    kernels: Optional[str] = None,
                    plan: Optional[dict] = None) -> None:
        """Pin the owning run's mesh/compression/kernel-policy/plan digest
        into the cache's ONE canonical fingerprint (the Accelerator calls
        this at construction; a fleet resize re-pins it).  Every consumer —
        captured-step digests, serving warm, restore prefetch — must hash
        the same fingerprint, or a prefetch that runs before the first step
        (the preemption-resume flow) would pin a mesh-less fingerprint and
        every later lookup would miss."""
        if self.enabled:
            self._fingerprint = topology_fingerprint(
                mesh=mesh, compression=compression, kernels=kernels, plan=plan
            )

    def fingerprint(self) -> dict:
        if self._fingerprint is None:
            # no pinned context (a standalone cache, e.g. direct API use):
            # mesh-less, but consistently so for both store and load
            self._fingerprint = topology_fingerprint()
        return self._fingerprint

    # -- entry IO ------------------------------------------------------------
    def _paths(self, variant_digest: str, fp_digest: str) -> tuple[str, str]:
        stem = os.path.join(self.cache_dir, f"{variant_digest}-{fp_digest}")
        return stem + ".pkl", stem + ".json"

    def _entries(self) -> tuple[list[str], int]:
        """Metadata paths + total payload bytes (LRU bookkeeping input).
        Profile sidecars are not entries — they carry no executable."""
        if not self.enabled:
            return [], 0
        metas = [
            p
            for p in glob.glob(os.path.join(self.cache_dir, "*-*.json"))
            if not os.path.basename(p).startswith("profile-")
        ]
        total = 0
        for meta_path in metas:
            try:
                total += os.path.getsize(meta_path[: -len(".json")] + ".pkl")
            except OSError:
                continue
        return metas, total

    def lookup(self, variant_digest: str, fingerprint: dict,
               scope: str, key_desc: str, defer_hit: bool = False) -> Optional[dict]:
        """Load one entry.  Exact fingerprint match → the unpickled entry
        dict (``payload``/``in_tree``/``out_tree``/``side``/``meta``);
        a variant twin under a different fingerprint → a LOUD miss naming
        the moved fields; anything broken → a miss with its cause.

        ``defer_hit``: return the entry WITHOUT counting/recording the hit —
        the caller still has to validate side metadata and deserialize, and
        a hit record for a lookup that ends up unusable would make the event
        stream disagree with the counters; the caller settles the outcome
        via ``commit_hit`` or ``record_miss``."""
        if not self.enabled:
            return None
        fp_digest = _digest(fingerprint)
        pkl_path, meta_path = self._paths(variant_digest, fp_digest)
        t0 = time.perf_counter()
        raw = self._prefetched.get(pkl_path)
        if raw is None:
            try:
                with open(pkl_path, "rb") as f:
                    raw = f.read()
            except OSError:
                raw = None
        meta: dict = {}
        cause = None
        if raw is None:
            # stale-fingerprint diagnosis: a same-variant entry stored under
            # a different topology exists — name what moved (the acceptance
            # contract: loud miss, normal compile, never a wrong dispatch)
            twins = glob.glob(
                os.path.join(self.cache_dir, f"{variant_digest}-*.json")
            )
            if twins:
                try:
                    with open(twins[0], encoding="utf-8") as f:
                        stale = json.load(f)
                except (OSError, ValueError):
                    stale = {}
                cause = fingerprint_mismatch(stale.get("fingerprint"), fingerprint)
            else:
                cause = "no entry for this program variant"
        else:
            try:
                with open(meta_path, encoding="utf-8") as f:
                    meta = json.load(f)
            except (OSError, ValueError):
                meta = {}
            stored_fp = meta.get("fingerprint")
            if stored_fp != fingerprint:
                # defense in depth: the digest already keyed the fingerprint,
                # but a hand-edited/corrupt metadata file must not smuggle a
                # foreign-topology executable into a dispatch
                cause = fingerprint_mismatch(stored_fp, fingerprint)
            else:
                try:
                    entry = pickle.loads(raw)
                except Exception as exc:
                    cause = f"entry unpicklable ({type(exc).__name__}: {exc})"[:200]
                else:
                    entry["meta"] = meta
                    entry["_pending_hit"] = {
                        "meta_path": meta_path,
                        "bytes": len(raw),
                        "load_ms": round((time.perf_counter() - t0) * 1e3, 3),
                    }
                    if not defer_hit:
                        self.commit_hit(entry, scope, key_desc)
                    return entry
        self.record_miss(scope, key_desc, cause)
        return None

    def commit_hit(self, entry: dict, scope: str, key_desc: str) -> None:
        """Settle a (possibly deferred) lookup as a hit: count it, refresh
        the LRU stamp, and emit the hit record."""
        pending = entry.pop("_pending_hit", None)
        if pending is None:
            return
        meta = entry.get("meta") or {}
        self.hits += 1
        self._touch(pending["meta_path"], meta)
        self._record(
            "hit", scope=scope, key=key_desc,
            bytes=pending["bytes"],
            load_ms=pending["load_ms"],
            avoided_compile_ms=meta.get("compile_ms"),
            avoided_trace_ms=meta.get("trace_ms"),
        )

    def record_miss(self, scope: str, key_desc: str, cause: Optional[str]) -> None:
        self.misses += 1
        self._record("miss", scope=scope, key=key_desc, cause=cause)
        if cause and "mismatch" in cause:
            logger.warning("AOT cache miss for %s: %s", key_desc, cause)

    def store(self, variant_digest: str, fingerprint: dict, compiled,
              side: Optional[dict], scope: str, key_desc: str,
              trace_ms: float = 0.0, compile_ms: float = 0.0) -> bool:
        """Serialize one compiled executable.  Fail-soft: a backend that
        refuses serialization (or an unpicklable side payload) records a
        ``store_failed`` event and the run continues uncached."""
        if not self.enabled:
            return False
        from jax.experimental import serialize_executable

        fp_digest = _digest(fingerprint)
        pkl_path, meta_path = self._paths(variant_digest, fp_digest)
        try:
            payload, in_tree, out_tree = serialize_executable.serialize(compiled)
            blob = pickle.dumps(
                {
                    "payload": payload,
                    "in_tree": in_tree,
                    "out_tree": out_tree,
                    "side": side or {},
                },
                protocol=pickle.HIGHEST_PROTOCOL,
            )
            # verify-on-store: round-trip the entry BEFORE it reaches disk.
            # XLA:CPU's executable serialization can emit an incomplete
            # object when the process already JIT-compiled other programs
            # (function symbols deduplicated against process state — the
            # load then dies with "Symbols not found" in EVERY process);
            # a serialized program that cannot deserialize here would only
            # ever produce downstream loud misses, so refuse it now and
            # keep the run on its in-memory compiled object
            probe = pickle.loads(blob)
            serialize_executable.deserialize_and_load(
                probe["payload"], probe["in_tree"], probe["out_tree"]
            )
            _atomic_write_bytes(pkl_path, blob)
            _atomic_write_json(
                meta_path,
                {
                    "fingerprint": fingerprint,
                    "scope": scope,
                    "key": key_desc,
                    "bytes": len(blob),
                    "trace_ms": round(trace_ms, 3),
                    "compile_ms": round(compile_ms, 3),
                    "created_at": time.time(),
                    "used_at": time.time(),
                    "side": {
                        k: v
                        for k, v in (side or {}).items()
                        # bulky payloads stay in the pickle only: the JSON
                        # metadata is the listing/diagnosis surface and must
                        # stay cheap to read per entry
                        if k not in ("scheduler_replays", "scope_map")
                    },
                    "sig": (side or {}).get("sig"),
                    "service": (side or {}).get("service"),
                },
            )
        except Exception as exc:
            # a payload written before the metadata write failed (ENOSPC et
            # al.) would be invisible to LRU accounting and unloadable
            # forever — drop both halves so the entry is absent, not torn
            for path in (pkl_path, meta_path):
                try:
                    os.unlink(path)
                except OSError:
                    pass
            self._record(
                "store_failed", scope=scope, key=key_desc,
                cause=f"{type(exc).__name__}: {exc}"[:200],
            )
            logger.warning("AOT cache store failed for %s: %s", key_desc, exc)
            return False
        self.stores += 1
        self._record("store", scope=scope, key=key_desc, bytes=len(blob),
                     compile_ms=round(compile_ms, 3))
        self._evict_over_budget(keep=meta_path)
        return True

    def _touch(self, meta_path: str, meta: dict) -> None:
        """Refresh the LRU stamp (best-effort — a read-only shared cache
        still serves hits, it just ages uniformly)."""
        try:
            meta = dict(meta)
            meta["used_at"] = time.time()
            _atomic_write_json(meta_path, meta)
        except OSError:
            pass

    def _evict_over_budget(self, keep: Optional[str] = None) -> None:
        """Drop least-recently-used entries until the payload total fits
        ``max_bytes``.  The entry just written is exempt — evicting it would
        make a store a no-op whenever one program exceeds the budget."""
        metas, total = self._entries()
        if total <= self.max_bytes:
            return
        aged = []
        for meta_path in metas:
            if meta_path == keep:
                continue
            try:
                with open(meta_path, encoding="utf-8") as f:
                    used_at = json.load(f).get("used_at", 0.0)
            except (OSError, ValueError):
                used_at = 0.0
            aged.append((used_at, meta_path))
        for _, meta_path in sorted(aged):
            if total <= self.max_bytes:
                break
            pkl_path = meta_path[: -len(".json")] + ".pkl"
            try:
                size = os.path.getsize(pkl_path)
                os.unlink(pkl_path)
                os.unlink(meta_path)
            except OSError:
                continue
            self._prefetched.pop(pkl_path, None)
            total -= size
            self.evictions += 1

    # -- warm/prefetch -------------------------------------------------------
    def prefetch(self) -> int:
        """Read every entry matching the live fingerprint into memory so the
        next captured-call build is a dict lookup, not a disk read — the
        resilience coupling: ``load_state`` (rollback-restore and the
        ``latest_checkpoint`` resume path) calls this first, so
        restore-after-fault replays the serialized executable off the hot
        path (docs/aot_cache.md §resilience)."""
        if not self.enabled:
            return 0
        live = self.fingerprint()
        fp_digest = _digest(live)
        # entries staged for a PREVIOUS fingerprint are dead weight now: an
        # elastic fleet that resizes repeatedly (shrink → grow → shrink…)
        # re-pins the context each time, and without this sweep every past
        # topology's executables would stay resident for the process's life
        suffix = f"-{fp_digest}.pkl"
        for stale in [p for p in self._prefetched if not p.endswith(suffix)]:
            del self._prefetched[stale]
        count = 0
        for pkl_path in glob.glob(
            os.path.join(self.cache_dir, f"*-{fp_digest}.pkl")
        ):
            try:
                with open(pkl_path, "rb") as f:
                    self._prefetched[pkl_path] = f.read()
                count += 1
            except OSError:
                continue
        self.last_prefetch_count = count
        self._record("warm", scope="restore", entries=count)
        return count

    # -- captured-step integration -------------------------------------------
    def _fn_digest(self, fn) -> str:
        import inspect

        try:
            return _digest(inspect.getsource(fn))
        except (OSError, TypeError):
            return _digest(f"{getattr(fn, '__module__', '?')}."
                           f"{getattr(fn, '__qualname__', repr(fn))}")

    def captured_digests(self, step, key, state_template, host_mask):
        """(variant_digest, fingerprint, fn_digest) for one CapturedStep
        variant — the on-disk identity of one compiled program."""
        import jax

        flat_state, state_treedef = jax.tree_util.tree_flatten(state_template)
        variant = {
            "key": repr(key),
            "state_treedef": repr(state_treedef),
            "state_avals": [_leaf_aval(x) for x in flat_state],
            "host_mask": list(host_mask),
            "fn": self._fn_digest(step.fn),
        }
        # mesh/compression ride the ONE pinned fingerprint (set_context)
        return _digest(variant), self.fingerprint(), variant["fn"]

    def load_captured(self, step, key, state_template, host_mask):
        """(compiled, side) for a stored captured-step variant, or
        (None, None) — a miss (already recorded) or a side payload that no
        longer maps onto this process's scheduler registry."""
        variant_digest, fingerprint, _ = self.captured_digests(
            step, key, state_template, host_mask
        )
        from ..telemetry.recompile import key_id

        # defer the hit: side-metadata validation and the deserialize below
        # can still turn this lookup into a miss, and the event stream must
        # agree with the counters
        entry = self.lookup(
            variant_digest, fingerprint, "train", key_id(key), defer_hit=True
        )
        if entry is None:
            return None, None
        side = entry.get("side") or {}
        if side.get("uses_accumulate") and step._uses_accumulate is None:
            # the profile sidecar is missing (partial dir copy): without it
            # the first call did not advance the accumulation schedule
            # host-side, so dispatching this entry would skip an advance —
            # fall through to a real trace, which advances it
            self.record_miss(
                "train", key_id(key),
                "accumulate-using entry without a step profile sidecar; "
                "tracing to rediscover the schedule",
            )
            return None, None
        schedulers = step.accelerator._schedulers
        for replay in side.get("scheduler_replays", []):
            if not 0 <= replay.get("index", -1) < len(schedulers):
                self.record_miss(
                    "train", key_id(key),
                    "stored scheduler replay index not in this process's "
                    "scheduler registry",
                )
                return None, None
        try:
            from jax.experimental import serialize_executable

            compiled = serialize_executable.deserialize_and_load(
                entry["payload"], entry["in_tree"], entry["out_tree"]
            )
        except Exception as exc:
            self.record_miss(
                "train", key_id(key),
                f"deserialize failed ({type(exc).__name__}: {exc})"[:200],
            )
            return None, None
        self.commit_hit(entry, "train", key_id(key))
        return compiled, side

    def store_captured(self, step, key, compiled, ctx, state_template,
                       host_mask, trace_ms: float, compile_ms: float) -> bool:
        """Persist one freshly compiled captured-step variant plus the
        trace-time side metadata a skipped trace cannot rediscover."""
        variant_digest, fingerprint, fn_digest = self.captured_digests(
            step, key, state_template, host_mask
        )
        schedulers = step.accelerator._schedulers
        replays = []
        for scheduler, args, kwargs in ctx.deferred_scheduler_steps:
            if scheduler not in schedulers:
                self._record(
                    "store_failed", scope="train", key=str(variant_digest),
                    cause="deferred scheduler not registered on the "
                    "accelerator; entry not serializable",
                )
                return False
            try:
                json.dumps([list(args), dict(kwargs)])
            except (TypeError, ValueError):
                self._record(
                    "store_failed", scope="train", key=str(variant_digest),
                    cause="deferred scheduler args not JSON-serializable",
                )
                return False
            replays.append(
                {"index": schedulers.index(scheduler), "args": list(args),
                 "kwargs": dict(kwargs)}
            )
        side = {
            "uses_accumulate": bool(ctx.used_accumulate),
            "scheduler_replays": replays,
        }
        # per-phase device attribution survives the warm start (ROADMAP
        # carried item, docs/telemetry.md §phases): a deserialized
        # executable carries NO HLO metadata, so the op→scope map must be
        # parsed NOW — while the freshly compiled object still has it — and
        # persisted beside the executable; the loading process restores it
        # into its telemetry hub (capture.py) so warm samples keep the
        # split instead of reading empty phases.  Gated on the storing
        # step's telemetry: as_text() stringifies the whole HLO module
        # (can be tens of MB on big programs), and a telemetry-off run has
        # no atpu scopes in its trace to map anyway (the named_scope spans
        # only exist when telemetry instrumented the capture).
        if step._telemetry is not None:
            from ..telemetry.profiler import scope_map_from_compiled

            scope_map = scope_map_from_compiled(compiled)
            if scope_map:
                side["scope_map"] = scope_map
        from ..telemetry.recompile import key_id

        ok = self.store(
            variant_digest, fingerprint, compiled, side, "train",
            key_id(key), trace_ms=trace_ms, compile_ms=compile_ms,
        )
        if ok:
            self._store_profile(fn_digest, {"uses_accumulate": side["uses_accumulate"]})
        return ok

    # -- step profile sidecar ------------------------------------------------
    def _profile_path(self, fn_digest: str) -> str:
        return os.path.join(self.cache_dir, f"profile-{fn_digest}.json")

    def _store_profile(self, fn_digest: str, profile: dict) -> None:
        try:
            _atomic_write_json(self._profile_path(fn_digest), profile)
        except OSError:
            pass

    def step_profile_uses_accumulate(self, step) -> Optional[bool]:
        """The stored ``uses_accumulate`` flag for this step body, or None
        when no profile exists.  Consulted before the FIRST call computes
        its cache key: an accumulate-using body must advance its schedule
        host-side (like every warm call does) so the key it computes is the
        post-advance key the cold process stored under."""
        if not self.enabled:
            return None
        try:
            with open(self._profile_path(self._fn_digest(step.fn)),
                      encoding="utf-8") as f:
                profile = json.load(f)
        except (OSError, ValueError):
            return None
        flag = profile.get("uses_accumulate")
        return bool(flag) if flag is not None else None


class AOTServingPrograms:
    """Per-DecodeService view of the cache: one deserialized executable per
    bucket signature, warmed from disk at service construction so a fresh
    replica's first prefill/decode dispatches without compiling.

    ``call`` replaces the plain-jit dispatch in ``serving/engine.py`` when a
    cache is armed: signature hit → dispatch the pinned executable; miss →
    ``jit_fn.lower(...).compile()`` explicitly (so the object is
    serializable), store, dispatch.  CompileWatcher bookkeeping is kept
    equivalent: cold builds count as compiles, disk/memory hits never do,
    and a build on an already-seen signature still raises the steady-state
    recompile event the smoke/bench assertions read.
    """

    def __init__(self, cache: AOTCompilationCache, service_fingerprint: dict):
        self.cache = cache
        self.service_digest = _digest(service_fingerprint)
        self.programs: dict[str, Any] = {}
        self.warmed = 0

    def _variant_digest(self, sig) -> str:
        return _digest({"service": self.service_digest, "sig": repr(sig)})

    def warm(self) -> int:
        """Deserialize every stored bucket program of this service's
        geometry+topology — replica spin-up collapses to disk reads."""
        if not self.cache.enabled:
            return 0
        live = self.cache.fingerprint()
        fp_digest = _digest(live)
        from jax.experimental import serialize_executable

        for meta_path in glob.glob(
            os.path.join(self.cache.cache_dir, f"*-{fp_digest}.json")
        ):
            try:
                with open(meta_path, encoding="utf-8") as f:
                    meta = json.load(f)
            except (OSError, ValueError):
                continue
            if meta.get("scope") != "serving" or meta.get("service") != self.service_digest:
                continue
            if meta.get("fingerprint") != live:
                # digest collision or hand-edited metadata: the fingerprint
                # check is the contract — never load a foreign-topology entry
                continue
            pkl_path = meta_path[: -len(".json")] + ".pkl"
            try:
                with open(pkl_path, "rb") as f:
                    entry = pickle.loads(f.read())
                compiled = serialize_executable.deserialize_and_load(
                    entry["payload"], entry["in_tree"], entry["out_tree"]
                )
            except Exception as exc:
                self.cache.record_miss(
                    "serving", str(meta.get("sig")),
                    f"warm deserialize failed "
                    f"({type(exc).__name__}: {exc})"[:200],
                )
                continue
            sig_key = (entry.get("side") or {}).get("sig") or meta.get("sig")
            if sig_key:
                self.programs[sig_key] = compiled
                self.warmed += 1
                self.cache.hits += 1
                # refresh the LRU stamp: a warm-only replica fleet never
                # goes through lookup(), and un-touched entries would age
                # as never-used — evicted before genuinely stale ones
                self.cache._touch(meta_path, meta)
                self.cache._record(
                    "hit", scope="serving", key=sig_key,
                    bytes=meta.get("bytes"),
                    avoided_compile_ms=meta.get("compile_ms"),
                    avoided_trace_ms=meta.get("trace_ms"),
                )
        return self.warmed

    def call(self, label: str, sig, jit_fn, args, statics, watcher=None):
        sig_key = repr(sig)
        if watcher is not None:
            watcher._calls += 1
        compiled = self.programs.get(sig_key)
        stale_drop = False
        if compiled is not None:
            try:
                return compiled(*args)
            except (TypeError, ValueError) as exc:
                # argument validation rejected the live avals — a stale
                # executable (validation precedes donation, so the pools are
                # intact).  Drop it, rebuild below, loud miss.
                stale_drop = True
                self.programs.pop(sig_key, None)
                self.cache.record_miss(
                    "serving", sig_key,
                    f"stale executable rejected inputs "
                    f"({type(exc).__name__}: {exc})"[:200],
                )
                compiled = None
        t0 = time.perf_counter()
        lowered = jit_fn.lower(*args, **statics)
        t1 = time.perf_counter()
        compiled = lowered.compile()
        t2 = time.perf_counter()
        self.programs[sig_key] = compiled
        if watcher is not None:
            # one contract for both dispatch routes (CompileWatcher.
            # note_build): a rebuild of a program that was live — whether
            # the watcher saw its cold build or it was warmed from disk
            # (stale_drop) — is a steady-state recompile
            watcher.note_build(
                label, sig, seen=stale_drop or (sig in watcher._seen)
            )
        self.cache.store(
            self._variant_digest(sig), self.cache.fingerprint(), compiled,
            {"sig": sig_key, "service": self.service_digest}, "serving",
            sig_key, trace_ms=(t1 - t0) * 1e3, compile_ms=(t2 - t1) * 1e3,
        )
        return compiled(*args)


__all__ = [
    "AOT_CACHE_FORMAT",
    "FINGERPRINT_FLAGS",
    "AOTCompilationCache",
    "AOTServingPrograms",
    "current_aot_cache",
    "fingerprint_mismatch",
    "topology_fingerprint",
]
