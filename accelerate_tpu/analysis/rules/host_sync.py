"""host-sync-in-trace: device→host transfers reachable from traced code.

Inside a ``jax.jit`` / ``shard_map`` trace the value is a tracer: ``.item()``,
``float()``, ``np.asarray`` and ``jax.device_get`` either raise a
ConcretizationTypeError outright or — worse, under ``io_callback``-style
escape hatches — silently serialize every device step on a host round-trip.
On a pod that is a cross-host stall per step.  ``jnp.asarray`` (a device op)
is the trace-safe spelling and is deliberately NOT flagged.
"""

from __future__ import annotations

import ast

from ..callgraph import iter_own_nodes
from ..engine import Finding, Rule

# methods that force a host transfer wherever they appear
_SINK_METHODS = {"item", "tolist"}
# numpy module functions that concretize their argument on host
_NUMPY_SINKS = {"asarray", "array", "ascontiguousarray", "copy"}
_JAX_SINKS = {"jax.device_get"}
_BUILTIN_CASTS = {"float", "int", "bool", "complex"}


def _is_static_expr(node: ast.AST) -> bool:
    """Expressions whose value is known at trace time (no host sync): python
    literals, ``len()``, and shape/ndim/size attribute reads."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return node.func.id == "len"
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in ("shape", "ndim", "size"):
            return True
    return False


class HostSyncInTrace(Rule):
    id = "host-sync-in-trace"
    kind = "reachability"
    description = (
        "host transfer (.item()/.tolist()/float()/np.asarray/jax.device_get/"
        ".block_until_ready) reachable from jit/shard_map/compile_step-traced code"
    )
    fix_hint = (
        "keep the value on device (jnp ops) or move the read outside the "
        "traced region; use jax.debug.print for trace-time logging"
    )

    def check(self, module, ctx):
        findings = []
        for info, reason in module.callgraph.traced_functions():
            for node in iter_own_nodes(info.node):
                if not isinstance(node, ast.Call):
                    continue
                msg = self._sink_message(module, node)
                if msg:
                    findings.append(
                        Finding(
                            self.id,
                            module.rel_path,
                            node.lineno,
                            node.col_offset,
                            f"{msg} in traced code ({reason})",
                            symbol=info.qualname,
                        )
                    )
        return findings

    def _sink_message(self, module, node: ast.Call):
        fn = node.func
        resolved = module.resolve(fn)
        if resolved in _JAX_SINKS or (resolved or "").endswith(".device_get"):
            return "jax.device_get forces a device→host transfer"
        if resolved and "." in resolved:
            head, leaf = resolved.rsplit(".", 1)
            if (
                head in ("numpy", "np")
                and leaf in _NUMPY_SINKS
                and not self._host_metadata_arg(module, node)
            ):
                return f"np.{leaf}() concretizes a tracer on host (use jnp.{leaf})"
        if (
            isinstance(fn, ast.Name)
            and fn.id in _BUILTIN_CASTS
            and not self._host_metadata_arg(module, node)
            and len(node.args) == 1
            and not node.keywords
            and not _is_static_expr(node.args[0])
        ):
            return f"{fn.id}() concretizes a traced value to a python scalar"
        if isinstance(fn, ast.Attribute):
            if fn.attr in _SINK_METHODS:
                return f".{fn.attr}() forces a device→host transfer"
            if fn.attr == "block_until_ready":
                return ".block_until_ready() blocks the host (tracers don't have it)"
            if fn.attr == "numpy" and not node.args and not node.keywords:
                return ".numpy() forces a device→host transfer"
        return None

    @staticmethod
    def _host_metadata_arg(module, node: ast.Call) -> bool:
        """True when the argument is host metadata, never a tracer: device
        handles (``jax.devices()``), mesh/sharding topology queries."""
        for arg in node.args:
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Call):
                    resolved = module.resolve(sub.func) or ""
                    if resolved.rsplit(".", 1)[-1] in (
                        "devices",
                        "local_devices",
                        "device_count",
                        "local_device_count",
                        "process_index",
                    ):
                        return True
        return False
