"""Sharded (FSDP/GSPMD) checkpoint save/load + offline merge.

Counterpart of ``/root/reference/src/accelerate/utils/fsdp_utils.py``
(save_fsdp_model :66, save_fsdp_optimizer :175, merge_fsdp_weights :275).
The reference delegates to ``torch.distributed.checkpoint`` with per-rank
``__{rank}_0.distcp`` files; here the unit of sharding is the GSPMD layout of
each ``jax.Array``: every host writes the *unique addressable shards* it owns,
with the global slice bounds encoded in each entry's key, and the offline
merge pastes slices back into full arrays — valid for ANY NamedSharding, not
just axis-0 sharding.

Layout of a sharded checkpoint directory::

    <dir>/<name>.shard-00000-of-00004.safetensors   # rank 0's unique slices
    <dir>/<name>.shard-00001-of-00004.safetensors
    ...
    <dir>/<name>.index.json   # tensor → global shape/dtype + shard count

Entry keys inside a shard file are ``<tensor>|<start>:<stop>,...`` (one
``start:stop`` pair per dimension), so any subset of shard files is
self-describing and the merge tool needs no per-rank metadata.
"""

from __future__ import annotations

import json
import math
import os
from typing import Any, Optional

import numpy as np

from .constants import MODEL_NAME

__all__ = [
    "save_sharded_model_state",
    "load_sharded_model_state",
    "load_sharded_resharded",
    "merge_sharded_weights",
    "sharded_index_path",
]


def _shard_file(name: str, rank: int, world: int) -> str:
    return f"{name}.shard-{rank:05d}-of-{world:05d}.safetensors"


def sharded_index_path(directory: str, name: str = MODEL_NAME) -> str:
    return os.path.join(directory, f"{name}.index.json")


def _bf16_np():
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


def _bf16_to_view(arr: np.ndarray) -> np.ndarray:
    # safetensors.numpy rejects ml_dtypes.bfloat16; store as a raw uint16 view
    if arr.dtype == _bf16_np():
        return arr.view(np.uint16)
    return arr


def _maybe_bf16_from_view(arr: np.ndarray, dtype: str) -> np.ndarray:
    if dtype == "bfloat16" and arr.dtype == np.uint16:
        return arr.view(_bf16_np())
    return arr


def _dtype_str(dtype) -> str:
    if dtype == _bf16_np():
        return "bfloat16"
    return str(np.dtype(dtype))


def _slice_key(tensor_name: str, bounds: list[tuple[int, int]]) -> str:
    spec = ",".join(f"{a}:{b}" for a, b in bounds) or "scalar"
    return f"{tensor_name}|{spec}"


def _parse_slice_key(key: str) -> tuple[str, list[tuple[int, int]]]:
    tensor_name, _, spec = key.rpartition("|")
    if not tensor_name:
        return key, []
    if spec == "scalar":
        return tensor_name, []
    bounds = []
    for pair in spec.split(","):
        a, b = pair.split(":")
        bounds.append((int(a), int(b)))
    return tensor_name, bounds


def _unique_shard_bounds(arr) -> list:
    """(bounds, numpy_data) per unique addressable shard.

    Under dp/tp replication several local devices hold the same slice; one
    copy is enough for the checkpoint.
    """
    seen: set = set()
    out = []
    for shard in arr.addressable_shards:
        bounds = tuple(
            (int(s.start or 0), int(s.stop if s.stop is not None else dim))
            for s, dim in zip(shard.index, arr.shape)
        )
        if bounds not in seen:
            seen.add(bounds)
            out.append((list(bounds), np.asarray(shard.data)))
    return out


def collect_sharded_model_state(
    state_dict: dict[str, Any],
    name: str = MODEL_NAME,
    process_index: Optional[int] = None,
    num_processes: Optional[int] = None,
) -> tuple[str, dict[str, np.ndarray], dict[str, Any]]:
    """Materialise this host's unique shards to host numpy WITHOUT writing.

    Returns ``(shard_filename, arrays, index)`` where ``arrays`` maps slice
    keys to write-ready (bf16-viewed) numpy buffers and ``index`` is the
    rank-0 index.json payload.  Purely host-local — no collectives — so the
    async checkpoint path can run it at call time on the main thread and
    hand the result to a writer thread that only touches disk.
    """
    import jax

    rank = jax.process_index() if process_index is None else process_index
    world = jax.process_count() if num_processes is None else num_processes

    # D2H overlap: start every shard's device→host copy before the first
    # blocking np.asarray below, so the stall is max(transfer) not
    # sum(transfer) — matters for async save, whose call-time cost is
    # exactly this collection
    for value in state_dict.values():
        if isinstance(value, jax.Array) and hasattr(value, "addressable_shards"):
            for shard in value.addressable_shards:
                if hasattr(shard.data, "copy_to_host_async"):
                    shard.data.copy_to_host_async()

    local_arrays: dict[str, np.ndarray] = {}
    index: dict[str, Any] = {"metadata": {"num_shards": world}, "tensors": {}}
    for tensor_name, value in state_dict.items():
        spec = None
        if isinstance(value, jax.Array) and hasattr(value, "addressable_shards"):
            shards = _unique_shard_bounds(value)
            shape = [int(d) for d in value.shape]
            dtype = _dtype_str(np.asarray(shards[0][1]).dtype)
            s = getattr(value, "sharding", None)
            if isinstance(s, jax.sharding.NamedSharding):
                from ..parallel.sharding import spec_to_jsonable

                spec = spec_to_jsonable(s.spec)
        else:
            arr = np.asarray(value)
            shards = [([(0, int(d)) for d in arr.shape], arr)]
            shape = list(arr.shape)
            dtype = _dtype_str(arr.dtype)
        for bounds, data in shards:
            local_arrays[_slice_key(tensor_name, bounds)] = _bf16_to_view(data)
        entry: dict[str, Any] = {"shape": shape, "dtype": dtype}
        if spec is not None:
            # save-time PartitionSpec: restore reshards by slice bounds
            # regardless, but the record lets tooling (graftlint
            # sharding-spec-drift) catch a plan edit that silently disagrees
            # with how this checkpoint was laid out
            entry["spec"] = spec
        index["tensors"][tensor_name] = entry
    return _shard_file(name, rank, world), local_arrays, index


SHARD_FILE_METADATA = {"format": "accelerate_tpu-sharded"}


def save_sharded_model_state(
    state_dict: dict[str, Any],
    output_dir: str,
    name: str = MODEL_NAME,
    process_index: Optional[int] = None,
    num_processes: Optional[int] = None,
) -> str:
    """Write this host's unique shards of every array + (rank0) the index.

    Reference: save_fsdp_model with SHARDED_STATE_DICT
    (fsdp_utils.py:121-143).  Unlike the gather-to-rank0 path in
    ``checkpointing.save_model_weights`` this never materialises a full array
    in host memory, so it scales to models larger than one host's RAM.
    """
    import jax

    from ..native.st import pick_save_file

    save_file = pick_save_file()  # parallel native body IO when available
    rank = jax.process_index() if process_index is None else process_index
    os.makedirs(output_dir, exist_ok=True)
    fname, local_arrays, index = collect_sharded_model_state(
        state_dict, name=name, process_index=process_index, num_processes=num_processes
    )
    save_file(local_arrays, os.path.join(output_dir, fname), metadata=SHARD_FILE_METADATA)
    if rank == 0:
        with open(sharded_index_path(output_dir, name), "w") as f:
            json.dump(index, f, indent=1)
    return output_dir


def _load_all_shard_files(directory: str, name: str) -> dict[str, np.ndarray]:
    from ..native import available as _native_ok
    from ..native.st import load_file as _native_load

    if _native_ok():
        # zero-copy read-only views: this merge path only reads the shard
        # arrays (slices are copied into fresh outputs downstream)
        def load_file(p):
            return _native_load(p, writable=False)
    else:
        from safetensors.numpy import load_file

    out: dict[str, np.ndarray] = {}
    found = False
    for fname in sorted(os.listdir(directory)):
        if fname.startswith(f"{name}.shard-") and fname.endswith(".safetensors"):
            out.update(load_file(os.path.join(directory, fname)))
            found = True
    if not found:
        raise FileNotFoundError(
            f"no {name}.shard-*.safetensors files under {directory}"
        )
    return out


def merge_sharded_weights(
    input_dir: str,
    output_path: Optional[str] = None,
    name: str = MODEL_NAME,
    safe_serialization: bool = True,
) -> str:
    """Offline merge of a sharded checkpoint into one full-weights file.

    Reference: merge_fsdp_weights fsdp_utils.py:275 / ``accelerate
    merge-weights`` CLI (commands/merge.py:26).  Pure host-side numpy — runs
    with no accelerator attached.
    """
    index_file = sharded_index_path(input_dir, name)
    if not os.path.exists(index_file):
        raise FileNotFoundError(
            f"{index_file} not found — not a sharded checkpoint directory"
        )
    with open(index_file) as f:
        index = json.load(f)
    flat = _load_all_shard_files(input_dir, name)

    by_tensor: dict[str, list[tuple[list, np.ndarray]]] = {}
    for key, data in flat.items():
        tensor_name, bounds = _parse_slice_key(key)
        by_tensor.setdefault(tensor_name, []).append((bounds, data))

    merged: dict[str, np.ndarray] = {}
    for tensor_name, entry in index["tensors"].items():
        shape = tuple(entry["shape"])
        pieces = by_tensor.get(tensor_name)
        if not pieces:
            raise ValueError(f"no shards found for tensor {tensor_name!r}")
        pieces = [
            (bounds, _maybe_bf16_from_view(data, entry["dtype"]))
            for bounds, data in pieces
        ]
        full = np.zeros(shape, dtype=pieces[0][1].dtype)
        filled = np.zeros(shape, dtype=bool) if shape else None
        for bounds, data in pieces:
            sl = tuple(slice(a, b) for a, b in bounds)
            full[sl] = data.reshape(full[sl].shape)
            if filled is not None:
                filled[sl] = True
        if filled is not None and not filled.all():
            raise ValueError(
                f"tensor {tensor_name!r} has uncovered regions after merge; "
                "checkpoint is incomplete (were all ranks' shard files copied?)"
            )
        merged[tensor_name] = full

    if output_path is None:
        output_path = os.path.join(
            input_dir, f"{name}.safetensors" if safe_serialization else f"{name}.npz"
        )
    if safe_serialization:
        from ..native.st import pick_save_file

        save_file = pick_save_file()
        bf16 = _bf16_np()
        meta = {
            "format": "accelerate_tpu",
            "bf16_keys": json.dumps([k for k, v in merged.items() if v.dtype == bf16]),
        }
        save_file(
            {k: _bf16_to_view(v) for k, v in merged.items()}, output_path, metadata=meta
        )
    else:
        np.savez(output_path, **merged)
    return output_path


# diagnostics written by load_sharded_resharded: {"max_block_bytes": int,
# "tensors": {name: (max_block_bytes, full_bytes, n_unique_blocks)}} — lets
# tests (and operators) verify the loader never materialised a full tensor
load_stats: dict = {}


def _intersect(a: tuple, b: tuple):
    """Intersection of two bounds lists [(start, stop), ...], or None."""
    out = []
    for (a0, a1), (b0, b1) in zip(a, b):
        lo, hi = max(a0, b0), min(a1, b1)
        if lo >= hi:
            return None
        out.append((lo, hi))
    return out


def _scan_shard_entries(directory: str, name: str) -> dict[str, list]:
    """tensor → [(bounds, file, key)] across every shard file, WITHOUT
    loading any tensor data (safetensors header scan only)."""
    from safetensors import safe_open

    entries: dict[str, list] = {}
    found = False
    for fname in sorted(os.listdir(directory)):
        if fname.startswith(f"{name}.shard-") and fname.endswith(".safetensors"):
            found = True
            path = os.path.join(directory, fname)
            with safe_open(path, framework="numpy") as f:
                for key in f.keys():
                    tensor_name, bounds = _parse_slice_key(key)
                    entries.setdefault(tensor_name, []).append((bounds, path, key))
    if not found:
        raise FileNotFoundError(f"no {name}.shard-*.safetensors files under {directory}")
    return entries


def load_sharded_resharded(
    targets: dict[str, Any], input_dir: str, name: str = MODEL_NAME
) -> dict[str, Any]:
    """Restore a sharded checkpoint onto the CURRENT mesh layout, N→M safe.

    ``targets`` maps tensor name → a live ``jax.Array`` template whose
    sharding/dtype describe where the restored tensor must land (typically
    ``model.state_dict()`` of the freshly-prepared model).  For every tensor
    the loader assembles only the blocks THIS process's devices own, range-
    reading the stored slices via safetensors lazy slicing — per-host peak
    memory is O(local shard bytes), never O(full tensor), which is the whole
    point of sharded checkpoints at 7B+ scale (reference saves per-rank
    ``__{rank}_0.distcp`` for the same reason, fsdp_utils.py:66-246).

    The stored slice bounds are GLOBAL coordinates, so the checkpoint's
    process count / mesh shape is irrelevant: saving on fsdp=8 and restoring
    on fsdp=4 (or tp×fsdp, or replicated) reads whichever stored pieces
    intersect each new local block.
    """
    import jax
    from safetensors import safe_open

    index_file = sharded_index_path(input_dir, name)
    if not os.path.exists(index_file):
        raise FileNotFoundError(
            f"{index_file} not found — not a sharded checkpoint directory"
        )
    with open(index_file) as f:
        index = json.load(f)
    entries = _scan_shard_entries(input_dir, name)

    handles: dict[str, Any] = {}

    def handle(path):
        if path not in handles:
            handles[path] = safe_open(path, framework="numpy")
        return handles[path]

    out: dict[str, Any] = {}
    load_stats.setdefault("max_block_bytes", 0)
    load_stats.setdefault("tensors", {})
    try:
        for tensor_name, template in targets.items():
            entry = index["tensors"].get(tensor_name)
            if entry is None:
                raise KeyError(f"tensor {tensor_name!r} not in checkpoint index")
            shape = tuple(entry["shape"])
            if shape != tuple(template.shape):
                raise ValueError(
                    f"shape mismatch for {tensor_name!r}: checkpoint {shape} vs "
                    f"target {tuple(template.shape)} (resharding cannot change shapes)"
                )
            pieces = entries.get(tensor_name)
            if not pieces:
                raise ValueError(f"no shards found for tensor {tensor_name!r}")
            stored_dtype = entry["dtype"]
            sharding = template.sharding
            dev_indices = sharding.addressable_devices_indices_map(shape)
            block_cache: dict[tuple, np.ndarray] = {}
            device_arrays = []
            for device, idx in dev_indices.items():
                bounds = tuple(
                    (int(s.start or 0), int(s.stop if s.stop is not None else dim))
                    for s, dim in zip(idx, shape)
                ) if idx is not None else tuple((0, int(d)) for d in shape)
                if bounds not in block_cache:
                    block_shape = [b - a for a, b in bounds]
                    np_dtype = (
                        np.dtype(np.uint16)
                        if stored_dtype == "bfloat16"
                        else np.dtype(stored_dtype)
                    )
                    block = np.zeros(block_shape, dtype=np_dtype)
                    covered = np.zeros(block_shape, dtype=bool) if block_shape else None
                    for piece_bounds, path, key in pieces:
                        if not piece_bounds:  # scalar entry
                            block[...] = handle(path).get_tensor(key)
                            covered = None
                            continue
                        inter = _intersect(bounds, tuple(piece_bounds))
                        if inter is None:
                            continue
                        src = tuple(
                            slice(lo - p0, hi - p0)
                            for (lo, hi), (p0, _) in zip(inter, piece_bounds)
                        )
                        dst = tuple(
                            slice(lo - b0, hi - b0)
                            for (lo, hi), (b0, _) in zip(inter, bounds)
                        )
                        block[dst] = handle(path).get_slice(key)[src]
                        if covered is not None:
                            covered[dst] = True
                    if covered is not None and not covered.all():
                        raise ValueError(
                            f"tensor {tensor_name!r}: local block {bounds} has "
                            "uncovered regions — incomplete checkpoint (were all "
                            "hosts' shard files copied to shared storage?)"
                        )
                    block_cache[bounds] = _maybe_bf16_from_view(block, stored_dtype)
                device_arrays.append(
                    jax.device_put(block_cache[bounds], device)
                )
            arr = jax.make_array_from_single_device_arrays(
                shape, sharding, device_arrays
            )
            if arr.dtype != template.dtype:
                arr = arr.astype(template.dtype)
            out[tensor_name] = arr
            max_block = max((b.nbytes for b in block_cache.values()), default=0)
            full_bytes = int(np.prod(shape)) * next(iter(block_cache.values())).itemsize if block_cache else 0
            load_stats["tensors"][tensor_name] = (
                max_block, full_bytes, len(block_cache)
            )
            load_stats["max_block_bytes"] = max(
                load_stats["max_block_bytes"], max_block
            )
    finally:
        handles.clear()
    return out


def load_sharded_model_state(
    input_dir: str, name: str = MODEL_NAME
) -> dict[str, np.ndarray]:
    """Load a sharded checkpoint fully into host memory (merge in RAM)."""
    index_file = sharded_index_path(input_dir, name)
    with open(index_file) as f:
        index = json.load(f)
    flat = _load_all_shard_files(input_dir, name)
    by_tensor: dict[str, list[tuple[list, np.ndarray]]] = {}
    for key, data in flat.items():
        tensor_name, bounds = _parse_slice_key(key)
        by_tensor.setdefault(tensor_name, []).append((bounds, data))
    out: dict[str, np.ndarray] = {}
    for tensor_name, entry in index["tensors"].items():
        shape = tuple(entry["shape"])
        pieces = [
            (bounds, _maybe_bf16_from_view(data, entry["dtype"]))
            for bounds, data in by_tensor.get(tensor_name, [])
        ]
        if not pieces:
            raise ValueError(f"no shards found for tensor {tensor_name!r}")
        full = np.zeros(shape, dtype=pieces[0][1].dtype)
        for bounds, data in pieces:
            sl = tuple(slice(a, b) for a, b in bounds)
            full[sl] = data.reshape(full[sl].shape)
        out[tensor_name] = full
    return out
