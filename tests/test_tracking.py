"""Tracker layer tests (reference tracking.py: 8 backends + filter logic).

The heavy backends (wandb/mlflow/aim/clearml/dvclive/swanlab) aren't in the
image, so adapters are exercised through injected fake modules — what matters
is the adapter contract (init/config/log/finish routed main-process-only) and
the filter/resolve pipeline, not the vendor SDKs.
"""

import sys
import types

import pytest

import accelerate_tpu.tracking as tracking
from accelerate_tpu.tracking import (
    LOGGER_TYPE_TO_CLASS,
    filter_trackers,
    resolve_trackers,
)


def test_registry_covers_reference_backends():
    # reference ships TB/WandB/CometML/Aim/MLflow/ClearML/DVCLive (+swanlab
    # probe); jsonl is the native zero-dep default
    for name in (
        "jsonl", "tensorboard", "wandb", "mlflow", "comet_ml",
        "aim", "clearml", "dvclive", "swanlab",
    ):
        assert name in LOGGER_TYPE_TO_CLASS, name
        assert name in tracking._AVAILABILITY, name


def test_filter_skips_unavailable_with_warning(tmp_path):
    names = filter_trackers(["jsonl", "clearml"], logging_dir=str(tmp_path))
    assert names == ["jsonl"]  # clearml not installed → skipped, not raised


def test_filter_unknown_raises():
    with pytest.raises(ValueError, match="unknown tracker"):
        filter_trackers(["not_a_tracker"])


def test_dvclive_adapter_contract(monkeypatch, tmp_path):
    logged = {"metrics": [], "params": None, "ended": False, "steps": []}

    class FakeLive:
        def __init__(self, **kwargs):
            self.step = 0

        def log_params(self, params):
            logged["params"] = params

        def log_metric(self, k, v):
            logged["metrics"].append((self.step, k, v))

        def next_step(self):
            logged["steps"].append(self.step)
            self.step += 1

        def end(self):
            logged["ended"] = True

    fake = types.ModuleType("dvclive")
    fake.Live = FakeLive
    monkeypatch.setitem(sys.modules, "dvclive", fake)

    t = tracking.DVCLiveTracker("run")
    t.store_init_configuration({"lr": 0.1})
    t.log({"loss": 1.5, "text": "skipped"}, step=3)
    t.finish()
    assert logged["params"] == {"lr": 0.1}
    assert logged["metrics"] == [(3, "loss", 1.5)]
    assert logged["ended"]


def test_clearml_adapter_contract(monkeypatch):
    calls = {"scalars": [], "single": [], "config": None, "closed": False}

    class FakeLogger:
        def report_scalar(self, title, series, value, iteration):
            calls["scalars"].append((title, series, value, iteration))

        def report_single_value(self, name, value):
            calls["single"].append((name, value))

    class FakeTask:
        @staticmethod
        def current_task():
            return None

        @staticmethod
        def init(project_name, task_name):
            return FakeTask()

        def connect_configuration(self, cfg):
            calls["config"] = cfg

        def get_logger(self):
            return FakeLogger()

        def close(self):
            calls["closed"] = True

    fake = types.ModuleType("clearml")
    fake.Task = FakeTask
    monkeypatch.setitem(sys.modules, "clearml", fake)

    t = tracking.ClearMLTracker("run")
    t.store_init_configuration({"bs": 8})
    t.log({"train/loss": 0.5}, step=2)
    t.finish()
    assert calls["config"] == {"bs": 8}
    assert calls["scalars"] == [("train", "loss", 0.5, 2)]
    assert calls["closed"]


def test_resolve_passes_prebuilt_tracker_through():
    class Custom(tracking.GeneralTracker):
        name = "custom"
        requires_logging_directory = False

        def __init__(self):
            super().__init__()

        def store_init_configuration(self, values):
            pass

        def log(self, values, step=None):
            pass

    c = Custom()
    assert resolve_trackers([c], "proj", None, {}) == [c]
