"""BERT fine-tuning with every by_feature capability in one script.

Counterpart of /root/reference/examples/complete_nlp_example.py: the base
nlp_example loop plus checkpoint/resume, experiment tracking, gradient
accumulation, and cross-process early stopping — the diff checker
(tests/test_examples.py) asserts this file contains every line those feature
scripts add.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.append(os.path.dirname(os.path.abspath(__file__)))
from nlp_example import get_dataloaders  # noqa: E402

import accelerate_tpu.nn as nn  # noqa: E402
import accelerate_tpu.optim as optim  # noqa: E402
from accelerate_tpu import Accelerator  # noqa: E402
from accelerate_tpu.models import BertConfig, BertForSequenceClassification  # noqa: E402


def training_function(args):
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        gradient_accumulation_steps=args.gradient_accumulation_steps,
        log_with="all" if args.with_tracking else None,
        project_dir=args.project_dir,
    )
    nn.manual_seed(args.seed)
    train_dl, val_dl, vocab = get_dataloaders(accelerator, args.batch_size, args.seed)

    cfg = BertConfig.small() if args.small else BertConfig.base()
    cfg.vocab_size = max(cfg.vocab_size, vocab)
    model = BertForSequenceClassification(cfg)
    optimizer = optim.AdamW(model.parameters(), lr=args.lr)
    scheduler = optim.get_linear_schedule_with_warmup(
        optimizer, 100, len(train_dl) * args.num_epochs * accelerator.num_devices
    )
    model, optimizer, train_dl, val_dl, scheduler = accelerator.prepare(
        model, optimizer, train_dl, val_dl, scheduler
    )

    if args.with_tracking:
        accelerator.init_trackers("nlp_example_tracking", config=vars(args))

    # checkpoint resume: restore full state, then skip consumed batches
    start_epoch = 0
    resume_step = 0
    if args.resume_from_checkpoint:
        accelerator.load_state(args.resume_from_checkpoint)
        tag = os.path.basename(args.resume_from_checkpoint.rstrip("/"))
        if "epoch" in tag:
            start_epoch = int(tag.replace("epoch_", "")) + 1
        elif "step" in tag:
            resume_step = int(tag.replace("step_", ""))
            start_epoch = resume_step // len(train_dl)
            resume_step -= start_epoch * len(train_dl)

    overall_step = 0
    stop_training = False
    for epoch in range(start_epoch, args.num_epochs):
        model.train()
        total_loss = 0.0
        active_dl = train_dl
        if args.resume_from_checkpoint and epoch == start_epoch and resume_step:
            active_dl = accelerator.skip_first_batches(train_dl, resume_step)
        for step, batch in enumerate(active_dl):
            with accelerator.accumulate(model):
                out = model(
                    batch["input_ids"],
                    attention_mask=batch["attention_mask"],
                    token_type_ids=batch["token_type_ids"],
                    labels=batch["labels"],
                )
                accelerator.backward(out["loss"])
                optimizer.step()
                scheduler.step()
                optimizer.zero_grad()
            total_loss += float(out["loss"].item())
            overall_step += 1
            if args.checkpointing_steps == "step":
                accelerator.save_state(os.path.join(args.output_dir, f"step_{overall_step}"))
            # any process may pull the trigger on its local condition...
            if float(out["loss"].item()) < args.early_stop_threshold:
                accelerator.set_trigger()
            # ...and ALL processes see it (all-reduced) and break together
            if accelerator.check_trigger():
                stop_training = True
                break
        if args.checkpointing_steps == "epoch":
            accelerator.save_state(os.path.join(args.output_dir, f"epoch_{epoch}"))

        model.eval()
        correct = total = 0
        for batch in val_dl:
            out = model(
                batch["input_ids"],
                attention_mask=batch["attention_mask"],
                token_type_ids=batch["token_type_ids"],
            )
            preds = out["logits"].data.argmax(-1)
            preds = accelerator.gather_for_metrics(preds)
            labels = accelerator.gather_for_metrics(batch["labels"])
            correct += int((np.asarray(preds) == np.asarray(labels)).sum())
            total += len(np.asarray(labels))
        acc = correct / max(total, 1)
        accelerator.print(f"epoch {epoch}: accuracy={acc:.4f}")
        if args.with_tracking:
            accelerator.log({"train_loss": total_loss / len(train_dl), "accuracy": acc}, step=epoch)
        if stop_training:
            accelerator.print(f"early stop at epoch {epoch}")
            break
    if args.with_tracking:
        accelerator.end_training()
    return acc


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--mixed_precision", type=str, default="bf16", choices=["no", "fp16", "bf16"])
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--num_epochs", type=int, default=2)
    parser.add_argument("--lr", type=float, default=2e-5)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--small", action="store_true")
    parser.add_argument("--gradient_accumulation_steps", type=int, default=2)
    parser.add_argument("--with_tracking", action="store_true")
    parser.add_argument("--project_dir", type=str, default="logs")
    parser.add_argument("--checkpointing_steps", type=str, default="epoch", choices=["epoch", "step", "no"])
    parser.add_argument("--resume_from_checkpoint", type=str, default=None)
    parser.add_argument("--output_dir", type=str, default="ckpt_example")
    parser.add_argument("--early_stop_threshold", type=float, default=0.1)
    args = parser.parse_args()
    training_function(args)


if __name__ == "__main__":
    main()
