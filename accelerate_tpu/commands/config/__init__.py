"""Config subcommand package (reference: src/accelerate/commands/config/)."""

from __future__ import annotations

import argparse
from typing import Optional

from .config import config_command, config_command_parser
from .config_args import Config, default_config_file, load_config_from_file
from .default import default_command_parser, write_basic_config
from .update import update_command_parser

__all__ = [
    "Config",
    "default_config_file",
    "load_config_from_file",
    "write_basic_config",
    "get_config_parser",
]


def get_config_parser(subparsers: Optional[argparse._SubParsersAction] = None):
    """``config`` with nested ``default``/``update`` subcommands
    (reference commands/config/__init__.py:30)."""
    if subparsers is not None:
        parser = subparsers.add_parser("config", help="Launch configuration")
    else:
        parser = argparse.ArgumentParser("accelerate-tpu config")
    parser.add_argument("--config_file", default=None)
    inner = parser.add_subparsers(dest="config_subcommand")
    default_command_parser(inner)
    update_command_parser(inner)
    parser.set_defaults(func=config_command)
    return parser
