"""Sharding-plan regression tests for the round-1 involuntary-full-remat bug.

The multichip dryrun (dp2×fsdp2×tp2) hit XLA "involuntary full
rematerialization" because (a) the embedding table got doubly sharded
(vocab→tp from the tp_plan, embd→fsdp from the ZeRO rule) so every lookup
emitted an embd-sharded activation, and (b) nothing pinned activations to the
loader's batch layout.  The fix: gather tables are fsdp-exempt (Megatron
layout: vocab-over-tp only) and models constrain the residual stream at layer
boundaries.  MULTICHIP_r02's clean tail is the end-to-end proof; these unit
tests pin the plan-level invariants.
"""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import accelerate_tpu.nn as nn
from accelerate_tpu.models import GPTConfig, GPTLMHeadModel
from accelerate_tpu.parallel.sharding import (
    activation_spec,
    constrain_activation,
    plan_param_spec,
    shard_module_params,
)
from accelerate_tpu.utils.dataclasses import FullyShardedDataParallelPlugin


def _mesh():
    devices = np.array(jax.devices()[:8]).reshape(2, 2, 2)
    return Mesh(devices, ("dp", "fsdp", "tp"))


def test_embedding_weight_is_fsdp_exempt():
    nn.manual_seed(0)
    emb = nn.Embedding(64, 32)
    assert getattr(emb.weight, "fsdp_exempt", False)


def test_plan_skips_fsdp_for_exempt_params():
    mesh = _mesh()
    plugin = FullyShardedDataParallelPlugin(sharding_strategy="FULL_SHARD")
    spec = plan_param_spec(
        "wte.weight", (1024, 128), mesh, plugin,
        tp_plan={r"wte\.weight": ("tp", None)}, fsdp_exempt=True,
    )
    assert spec == P("tp"), f"embedding table must not be fsdp-sharded, got {spec}"
    # non-exempt params still get ZeRO sharding
    spec2 = plan_param_spec("h.0.mlp.c_fc.weight", (512, 128), mesh, plugin)
    assert "fsdp" in [a for a in spec2 if a is not None]


def test_gpt_plan_has_no_fsdp_on_embeddings():
    nn.manual_seed(0)
    mesh = _mesh()
    model = GPTLMHeadModel(GPTConfig.tiny())
    plugin = FullyShardedDataParallelPlugin(sharding_strategy="FULL_SHARD")
    plan = shard_module_params(model, mesh, fsdp_plugin=plugin)
    for name in ("wte.weight", "wpe.weight"):
        assert "fsdp" not in [a for a in plan[name] if a is not None], (
            f"{name} sharded {plan[name]}: gather tables must stay off the fsdp axis"
        )


def test_activation_spec_matches_loader_layout():
    mesh = _mesh()
    assert activation_spec(3, mesh) == P(("dp", "fsdp"))
    assert activation_spec(2, mesh) == P(("dp", "fsdp"))


def test_constrain_activation_applies_batch_sharding():
    import jax.numpy as jnp

    mesh = _mesh()
    x = jnp.ones((16, 8, 32))
    out = jax.jit(lambda v: constrain_activation(v, mesh=mesh))(x)
    from jax.sharding import NamedSharding

    want = NamedSharding(mesh, activation_spec(3, mesh))
    assert out.sharding.is_equivalent_to(want, 3), out.sharding


def test_constrain_activation_is_differentiable():
    import jax.numpy as jnp

    mesh = _mesh()
    from accelerate_tpu.nn import Tensor

    t = Tensor(jnp.ones((4, 4)), requires_grad=True)
    y = constrain_activation(t, mesh=mesh)
    (y * y).sum().backward()
    np.testing.assert_allclose(np.asarray(t.grad), 2 * np.ones((4, 4)))
