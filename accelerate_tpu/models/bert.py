"""BERT-family encoder on accelerate_tpu.nn.

The flagship fine-tuning workload (BASELINE.json: BERT-base MRPC via
examples/nlp_example.py).  Written TPU-first: bf16-friendly, SDPA routed to
the Pallas flash kernel when shapes allow, weights carrying a TP plan so the
same model runs replicated, ZeRO-sharded, or tensor-parallel purely by mesh
layout.  Reference model source for parity: HF transformers BERT (the
reference repo itself ships no models — SURVEY.md §2; models are part of this
framework's larger scope).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import jax.numpy as jnp

from .. import nn
from ..nn import F, Tensor


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    layer_norm_eps: float = 1e-12
    num_labels: int = 2

    @classmethod
    def base(cls) -> "BertConfig":
        return cls()

    @classmethod
    def small(cls) -> "BertConfig":
        return cls(hidden_size=256, num_hidden_layers=4, num_attention_heads=4, intermediate_size=1024)


def _bert_init(model: nn.Module, initializer_range: float = 0.02) -> None:
    """HF BERT init: N(0, 0.02) for all weight matrices, zero biases."""
    import jax

    from ..nn import random as nn_random

    from ..nn.meta import is_meta

    for name, p in model.named_parameters():
        if is_meta(p.data):
            continue  # init_empty_weights: nothing to initialise
        if name.endswith("bias"):
            p.data = jnp.zeros_like(p.data)
        elif p.ndim >= 2:
            p.data = initializer_range * jax.random.normal(
                nn_random.next_key(), p.shape, dtype=p.dtype
            )


class BertEmbeddings(nn.Module):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(config.vocab_size, config.hidden_size)
        self.position_embeddings = nn.Embedding(config.max_position_embeddings, config.hidden_size)
        self.token_type_embeddings = nn.Embedding(config.type_vocab_size, config.hidden_size)
        self.LayerNorm = nn.LayerNorm(config.hidden_size, eps=config.layer_norm_eps)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        seq_len = input_ids.shape[-1]
        if position_ids is None:
            position_ids = jnp.arange(seq_len)[None, :]
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(jnp.asarray(input_ids))
        emb = (
            self.word_embeddings(input_ids)
            + self.position_embeddings(position_ids)
            + self.token_type_embeddings(token_type_ids)
        )
        return self.dropout(self.LayerNorm(emb))


class BertSelfAttention(nn.Module):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.num_heads = config.num_attention_heads
        self.head_dim = config.hidden_size // config.num_attention_heads
        self.query = nn.Linear(config.hidden_size, config.hidden_size)
        self.key = nn.Linear(config.hidden_size, config.hidden_size)
        self.value = nn.Linear(config.hidden_size, config.hidden_size)
        self.dropout_p = config.attention_probs_dropout_prob

    def forward(self, hidden, attention_mask=None):
        b, s, _ = hidden.shape

        def split(x):
            return x.reshape(b, s, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)

        q, k, v = split(self.query(hidden)), split(self.key(hidden)), split(self.value(hidden))
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attention_mask,
            dropout_p=self.dropout_p if self.training else 0.0,
        )
        return out.transpose(0, 2, 1, 3).reshape(b, s, self.num_heads * self.head_dim)


class BertLayer(nn.Module):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.attention = BertSelfAttention(config)
        self.attention_output = nn.Linear(config.hidden_size, config.hidden_size)
        self.attention_norm = nn.LayerNorm(config.hidden_size, eps=config.layer_norm_eps)
        self.intermediate = nn.Linear(config.hidden_size, config.intermediate_size)
        self.output = nn.Linear(config.intermediate_size, config.hidden_size)
        self.output_norm = nn.LayerNorm(config.hidden_size, eps=config.layer_norm_eps)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, hidden, attention_mask=None):
        attn = self.attention(hidden, attention_mask)
        hidden = self.attention_norm(hidden + self.dropout(self.attention_output(attn)))
        ff = self.output(F.gelu(self.intermediate(hidden)))
        return self.output_norm(hidden + self.dropout(ff))


class BertModel(nn.Module):
    _no_split_modules = ["BertLayer", "BertEmbeddings"]
    # tensor-parallel plan: attention projections split on output features,
    # FFN split on the intermediate axis
    tp_plan = {
        r".*\.(query|key|value)\.weight": ("tp", None),
        r".*\.(query|key|value)\.bias": ("tp",),
        r".*\.intermediate\.weight": ("tp", None),
        r".*\.intermediate\.bias": ("tp",),
        r".*\.attention_output\.weight": (None, "tp"),
        r".*layer\.\d+\.output\.weight": (None, "tp"),
    }

    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.layer = nn.ModuleList([BertLayer(config) for _ in range(config.num_hidden_layers)])
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size)
        _bert_init(self)

    def forward(self, input_ids, attention_mask=None, token_type_ids=None):
        if attention_mask is not None:
            mask = jnp.asarray(
                attention_mask.data if isinstance(attention_mask, Tensor) else attention_mask
            )
            # (b, s) padding mask → (b, 1, 1, s) additive-compatible bool
            attention_mask = (mask[:, None, None, :] > 0)
        from ..parallel.sharding import constrain_activation

        hidden = constrain_activation(self.embeddings(input_ids, token_type_ids))
        for layer in self.layer:
            # pin batch to (dp, fsdp) at every layer boundary (see models/gpt.py)
            hidden = constrain_activation(layer(hidden, attention_mask))
        pooled = F.tanh(self.pooler(hidden[:, 0]))
        return hidden, pooled


class BertForSequenceClassification(nn.Module):
    _no_split_modules = BertModel._no_split_modules
    tp_plan = BertModel.tp_plan

    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.bert = BertModel(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self.classifier = nn.Linear(config.hidden_size, config.num_labels)

    def forward(self, input_ids, attention_mask=None, token_type_ids=None, labels=None):
        _, pooled = self.bert(input_ids, attention_mask, token_type_ids)
        logits = self.classifier(self.dropout(pooled))
        if labels is not None:
            loss = F.cross_entropy(logits, labels)
            return {"loss": loss, "logits": logits}
        return {"logits": logits}
