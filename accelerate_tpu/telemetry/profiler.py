"""Pillar 5 — sampled device-time attribution (docs/telemetry.md).

``StepRecord.dispatch_ms`` is *launch* latency: under JAX's async dispatch
the host returns the moment the program is enqueued, so the one number the
EQuARX-style comms A/B and the serving hot path actually need — where the
*device* spends its time (compute vs collective vs host transfer vs idle) —
is invisible to host timers.  This module closes that gap without giving up
the async pipeline: every Nth captured call (``TelemetryKwargs(
profile_every_n=...)`` / ``$ACCELERATE_TELEMETRY_PROFILE_N``, default off)
the dispatch runs inside a ``jax.profiler`` trace session, the sampled call
blocks until the device finishes (that is the sampling overhead — bounded
by the cadence), and the resulting trace-event JSON is parsed into a
:class:`DeviceStepRecord` joined 1:1 to the host-side ``StepRecord`` by
step index.

The parser reads the ``*.trace.json.gz`` chrome-trace dump the profiler
writes on every backend — CPU included (XLA:CPU emits per-HLO-op events on
its Eigen worker threads), which is what lets the whole pillar test in
tier-1 without a TPU.  Device ops are the ``X`` events carrying an
``args.hlo_op`` tag (or living under a ``/device:...`` process); per-device
*busy* is the interval **union** of those ops (ops overlap across worker
threads, so summing durations would double-count), *idle* is the profiled
window minus busy, and the compute/collective/transfer split is classified
from op names.  MFU derives from the captured program's existing
``cost_analysis()`` FLOPs against a per-chip peak (``$ACCELERATE_PEAK_FLOPS``
override, known-TPU table otherwise; ``None`` where no peak is known).

Everything here is fail-soft: an unparseable or empty trace, a backend
without trace events, or a profiler session already held by the user's
``accelerator.profile()`` yields *no* record (and, after repeated start
failures, disables sampling for the run) — never an exception on the
capture path.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
import shutil
import tempfile
import time
from dataclasses import dataclass, field
from typing import Optional

from ..logging import get_logger

logger = get_logger(__name__)

# op-name classification for the device-time split.  HLO collective ops keep
# their names through fusion labels on every backend we parse.
_COLLECTIVE_RE = re.compile(
    r"all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute|"
    r"collective-broadcast|partition-id|replica-id|psum|ragged-all-to-all",
    re.IGNORECASE,
)
_TRANSFER_RE = re.compile(
    r"\bcopy|infeed|outfeed|host-transfer|send\b|recv\b|dynamic-update-slice-host",
    re.IGNORECASE,
)

# (device_kind substring, peak dense FLOP/s per chip, bf16) — best-effort;
# $ACCELERATE_PEAK_FLOPS overrides, unknown kinds (CPU) yield None → no MFU
_PEAK_FLOPS_BY_KIND = (
    ("v6e", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def peak_flops_per_device() -> Optional[float]:
    """Per-chip peak FLOP/s: env override first, TPU kind table second,
    ``None`` when unknown (CPU and friends — MFU is then not derivable)."""
    env = os.environ.get("ACCELERATE_PEAK_FLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            logger.warning("ACCELERATE_PEAK_FLOPS=%r is not a number", env)
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return None
    for tag, peak in _PEAK_FLOPS_BY_KIND:
        if tag in kind:
            return peak
    return None


def derive_mfu(flops: float, window_ms: float, n_devices: int = 1) -> Optional[float]:
    """Model-FLOPs utilization of one profiled step: the program's analytic
    FLOPs (``cost_analysis`` — whole-program) over the device-time window
    against the fleet's aggregate peak.  ``None`` without a known peak."""
    peak = peak_flops_per_device()
    if not peak or window_ms <= 0 or not flops:
        return None
    return flops / (window_ms / 1e3) / (peak * max(1, n_devices))


@dataclass
class DeviceStepRecord:
    """Device-side view of one sampled captured call, joined to the host
    :class:`~.timeline.StepRecord` with the same ``step`` index."""

    step: int  # global captured-call index — the join key
    key: str  # compiled-variant key id (same as StepRecord.key)
    window_ms: float  # host wall of the profiled span (dispatch → blocked)
    busy_ms: float  # mean per-device op-interval union
    idle_ms: float  # mean per-device (window - busy), >= 0
    compute_ms: float  # mean per-device op-duration sums by class
    collective_ms: float
    transfer_ms: float
    devices: dict = field(default_factory=dict)  # per-device split
    top_ops: list = field(default_factory=list)  # [[name, ms], ...] desc
    op_events: int = 0  # device-op events parsed
    overhead_ms: float = 0.0  # stop_trace + parse cost (outside window_ms)
    flops: Optional[float] = None  # from the program's cost_analysis
    mfu: Optional[float] = None  # None without a known per-chip peak
    # per-atpu-phase compute/collective/transfer split (docs/telemetry.md):
    # op durations joined to the program's HLO op->scope map — empty when
    # no scope map exists for the variant (fail-soft)
    phases: dict = field(default_factory=dict)
    # raw {op name: [class, ms]} the phase join consumes; not exported
    op_detail: dict = field(default_factory=dict)

    @property
    def collective_share(self) -> float:
        """Collective fraction of device op time (the EQuARX headline)."""
        total = self.compute_ms + self.collective_ms + self.transfer_ms
        return self.collective_ms / total if total > 0 else 0.0

    def to_dict(self) -> dict:
        return {
            "kind": "device_step",
            "step": self.step,
            "key": self.key,
            "window_ms": round(self.window_ms, 3),
            "busy_ms": round(self.busy_ms, 3),
            "idle_ms": round(self.idle_ms, 3),
            "compute_ms": round(self.compute_ms, 3),
            "collective_ms": round(self.collective_ms, 3),
            "transfer_ms": round(self.transfer_ms, 3),
            "collective_share": round(self.collective_share, 4),
            "devices": {k: dict(v) for k, v in self.devices.items()},
            "top_ops": [[n, round(ms, 3)] for n, ms in self.top_ops],
            "op_events": self.op_events,
            "overhead_ms": round(self.overhead_ms, 3),
            "flops": self.flops,
            "mfu": self.mfu,
            "phases": {
                name: {k: (round(v, 3) if isinstance(v, float) else v)
                       for k, v in split.items()}
                for name, split in self.phases.items()
            },
        }


def _union_ms(intervals: list) -> float:
    """Total covered length (ms) of possibly-overlapping (start, end) µs
    intervals — per-device busy must not double-count ops that ran
    concurrently on different worker threads."""
    if not intervals:
        return 0.0
    intervals.sort()
    covered = 0.0
    cur_start, cur_end = intervals[0]
    for start, end in intervals[1:]:
        if start > cur_end:
            covered += cur_end - cur_start
            cur_start, cur_end = start, end
        elif end > cur_end:
            cur_end = end
    covered += cur_end - cur_start
    return covered / 1e3


def classify_op(name: str) -> str:
    if _COLLECTIVE_RE.search(name):
        return "collective"
    if _TRANSFER_RE.search(name):
        return "transfer"
    return "compute"


def parse_trace_events(events: list, top_k: int = 10) -> dict:
    """Trace-event JSON (chrome format, µs timestamps) → per-device busy +
    compute/collective/transfer split + top-k ops by device time.

    A *device op* is a complete (``ph == "X"``) event carrying an
    ``args.hlo_op`` tag, or any complete event under a process whose
    metadata name starts with ``/device:`` (the TPU layout).  Everything
    else — python frames, runtime bookkeeping, thread markers — is host
    noise and ignored."""
    process_names: dict = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            process_names[ev.get("pid")] = ev.get("args", {}).get("name", "")
    per_device: dict[str, dict] = {}
    intervals: dict[str, list] = {}
    op_ms: dict[str, float] = {}
    op_detail: dict[str, list] = {}  # name -> [class, summed ms]
    n_ops = 0
    for ev in events:
        if ev.get("ph") != "X":
            continue
        args = ev.get("args")
        pname = process_names.get(ev.get("pid"), "")
        is_op = (isinstance(args, dict) and "hlo_op" in args) or pname.startswith(
            "/device:"
        )
        if not is_op:
            continue
        try:
            ts, dur = float(ev["ts"]), float(ev["dur"])
        except (KeyError, TypeError, ValueError):
            continue
        name = str(ev.get("name", "?"))
        device = pname or f"pid:{ev.get('pid')}"
        dev = per_device.setdefault(
            device,
            {"busy_ms": 0.0, "compute_ms": 0.0, "collective_ms": 0.0,
             "transfer_ms": 0.0, "idle_ms": 0.0, "ops": 0},
        )
        op_class = classify_op(name)
        dev[f"{op_class}_ms"] += dur / 1e3
        dev["ops"] += 1
        intervals.setdefault(device, []).append((ts, ts + dur))
        op_ms[name] = op_ms.get(name, 0.0) + dur / 1e3
        entry = op_detail.setdefault(name, [op_class, 0.0])
        entry[1] += dur / 1e3
        n_ops += 1
    for device, dev in per_device.items():
        dev["busy_ms"] = _union_ms(intervals[device])
    top_ops = sorted(op_ms.items(), key=lambda kv: kv[1], reverse=True)[:top_k]
    return {
        "devices": per_device,
        "top_ops": top_ops,
        "op_events": n_ops,
        "op_detail": op_detail,
    }


# HLO-text instruction metadata: `%name = ... metadata={... op_name="path"}`
# — the only place the atpu named scopes survive to (trace events carry
# bare instruction names on every backend we parse)
_HLO_OP_NAME_RE = re.compile(r"%?([\w.\-]+) = [^\n]*op_name=\"([^\"]+)\"")


def scope_map_from_compiled(compiled) -> dict:
    """``{hlo instruction name: atpu phase}`` from a compiled program's HLO
    text.  The phase is the DEEPEST ``atpu``-prefixed segment of the op's
    scope path (``jit(f)/atpu_captured_body/atpu_update/add`` →
    ``atpu_update``); unscoped instructions are omitted.  Fail-soft: any
    error returns an empty map and the sample simply carries no phase
    split."""
    try:
        text = compiled.as_text()
    except Exception:
        return {}
    scope_map: dict = {}
    for match in _HLO_OP_NAME_RE.finditer(text):
        name, path = match.group(1), match.group(2)
        phase = None
        for segment in path.split("/"):
            if segment.startswith("atpu"):
                phase = segment  # keep walking: deepest wins
        if phase is not None:
            scope_map[name] = phase
    return scope_map


def split_phases(op_detail: dict, scope_map: dict) -> dict:
    """Join sampled per-op durations (``{name: [class, ms]}``) to the
    program's op->scope map: the whole-step compute/collective/transfer
    split re-read per atpu phase.  Ops outside every atpu scope (input
    copies, infeed, runtime bookkeeping) land in ``"unscoped"``."""
    phases: dict = {}
    for name, (op_class, ms) in op_detail.items():
        phase = scope_map.get(name, "unscoped")
        split = phases.setdefault(
            phase,
            {"total_ms": 0.0, "compute_ms": 0.0, "collective_ms": 0.0,
             "transfer_ms": 0.0, "ops": 0},
        )
        split[f"{op_class}_ms"] += ms
        split["total_ms"] += ms
        split["ops"] += 1
    return phases


def find_trace_json(trace_dir: str) -> Optional[str]:
    """Newest ``*.trace.json.gz`` under a profiler log dir (the profiler
    nests its dump under ``plugins/profile/<timestamp>/``)."""
    paths = glob.glob(
        os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True
    )
    return max(paths, key=os.path.getmtime) if paths else None


def parse_trace_dir(trace_dir: str) -> Optional[dict]:
    path = find_trace_json(trace_dir)
    if path is None:
        return None
    try:
        with gzip.open(path, "rt", encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    events = data.get("traceEvents") if isinstance(data, dict) else data
    if not isinstance(events, list):
        return None
    return parse_trace_events(events)


class StepProfiler:
    """Sampled ``jax.profiler`` trace capture around captured-step dispatch.

    One instance per telemetry hub.  ``should_sample`` is the only call on
    the unsampled hot path (an int modulus); ``start``/``stop`` bracket the
    sampled call's dispatch and are deliberately synchronous — the sampled
    step blocks until the device drains so its ops land inside the session.
    Traces land under per-step subdirs of ``base_dir`` and are deleted
    after parsing unless the caller pinned a directory (``keep_traces``)."""

    _MAX_START_FAILURES = 3  # consecutive; then sampling is off for the run

    def __init__(self, every_n: int, base_dir: Optional[str] = None,
                 keep_traces: bool = False):
        self.every_n = max(0, int(every_n))
        self._base_dir = base_dir
        self.keep_traces = bool(keep_traces)
        self._active_dir: Optional[str] = None
        self._t0 = 0.0
        self._start_failures = 0
        self.samples = 0
        self.last_error: Optional[str] = None

    @property
    def base_dir(self) -> str:
        if self._base_dir is None:
            self._base_dir = tempfile.mkdtemp(prefix="atpu_profile_")
        return self._base_dir

    def should_sample(self, step_index: int) -> bool:
        return (
            self.every_n > 0
            and self._start_failures < self._MAX_START_FAILURES
            and step_index % self.every_n == 0
        )

    def start(self, step_index: int, t0: Optional[float] = None) -> bool:
        """Open a trace session for this step; False (and never raises) when
        the profiler is unavailable or already held (user xprof session).

        ``t0`` (a ``perf_counter`` stamp) backdates the measured window to
        the captured call's entry: the session itself brackets only the
        dispatch (so a raising build can never orphan it), but the step's
        device-visible wall clock — and the idle the device spends while
        the host assembles arguments — starts at call entry."""
        import jax

        if self._active_dir is not None:
            # a previous sampled call raised between start and stop: close
            # the orphaned session so sampling recovers instead of failing
            # every later start
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            if not self.keep_traces:
                shutil.rmtree(self._active_dir, ignore_errors=True)
            self._active_dir = None
        trace_dir = os.path.join(self.base_dir, f"step{step_index:08d}")
        try:
            jax.profiler.start_trace(trace_dir)
        except Exception as exc:
            self._start_failures += 1
            self.last_error = f"{type(exc).__name__}: {exc}"
            if self._start_failures == 1:
                logger.warning(
                    "sampled profiler trace could not start (%s); will retry "
                    "up to %d times before disabling sampling for this run",
                    self.last_error, self._MAX_START_FAILURES,
                )
            return False
        self._start_failures = 0
        self._active_dir = trace_dir
        # without a caller-provided call-entry stamp the window opens AFTER
        # start_trace returns: the first session of a process pays a
        # multi-second profiler init that is not device time
        self._t0 = time.perf_counter() if t0 is None else t0
        return True

    def abort(self) -> None:
        """Close an in-flight session without recording (the sampled call
        raised mid-dispatch): best-effort stop + dump cleanup, so the
        session cannot keep tracing every step until the next sample."""
        trace_dir, self._active_dir = self._active_dir, None
        if trace_dir is None:
            return
        import jax

        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        if not self.keep_traces:
            shutil.rmtree(trace_dir, ignore_errors=True)

    def stop(self, step_index: int, key: str, outputs) -> Optional[DeviceStepRecord]:
        """Block on ``outputs``, close the session, parse the dump.  Returns
        ``None`` (never raises) when the trace is empty or unparseable."""
        import jax

        trace_dir, self._active_dir = self._active_dir, None
        if trace_dir is None:
            return None
        try:
            jax.block_until_ready(outputs)
        except Exception:
            pass  # a dispatch error is the caller's to handle, not ours
        t1 = time.perf_counter()
        window_ms = (t1 - self._t0) * 1e3
        parsed = None
        try:
            jax.profiler.stop_trace()
            parsed = parse_trace_dir(trace_dir)
        except Exception as exc:
            self.last_error = f"{type(exc).__name__}: {exc}"
            logger.warning("sampled profiler trace failed: %s", self.last_error)
        finally:
            if not self.keep_traces:
                shutil.rmtree(trace_dir, ignore_errors=True)
        overhead_ms = (time.perf_counter() - t1) * 1e3
        if not parsed or not parsed["devices"]:
            self.last_error = self.last_error or "trace contained no device ops"
            return None
        devices = parsed["devices"]
        for dev in devices.values():
            dev["idle_ms"] = max(0.0, window_ms - dev["busy_ms"])
        n = len(devices)
        mean = lambda field: sum(d[field] for d in devices.values()) / n  # noqa: E731
        self.samples += 1
        return DeviceStepRecord(
            step=step_index,
            key=key,
            window_ms=window_ms,
            busy_ms=mean("busy_ms"),
            idle_ms=mean("idle_ms"),
            compute_ms=mean("compute_ms"),
            collective_ms=mean("collective_ms"),
            transfer_ms=mean("transfer_ms"),
            devices=devices,
            top_ops=[list(kv) for kv in parsed["top_ops"]],
            op_events=parsed["op_events"],
            overhead_ms=overhead_ms,
            op_detail=parsed.get("op_detail", {}),
        )
