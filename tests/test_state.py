import os

import jax
import pytest

from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
from accelerate_tpu.utils.dataclasses import (
    GradientAccumulationPlugin,
    ParallelismConfig,
)


def test_partial_state_singleton():
    a = PartialState()
    b = PartialState()
    assert a.__dict__ is b.__dict__
    assert a.num_devices == 8
    assert a.num_processes == 1
    assert a.is_main_process


def test_partial_state_repr():
    s = PartialState()
    r = repr(s)
    assert "Num devices: 8" in r


def test_split_between_processes_single():
    s = PartialState()
    with s.split_between_processes([1, 2, 3]) as chunk:
        assert chunk == [1, 2, 3]


def test_on_main_process_decorator():
    s = PartialState()
    calls = []
    fn = s.on_main_process(lambda: calls.append(1))
    fn()
    assert calls == [1]


def test_accelerator_state_default_mesh():
    state = AcceleratorState()
    assert state.mesh.shape["dp"] == 8
    assert state.mesh.shape["tp"] == 1
    assert state.num_batch_shards == 8
    # PartialState attrs pass through
    assert state.num_devices == 8
    assert state.is_main_process


def test_accelerator_state_parallelism_config():
    cfg = ParallelismConfig(fsdp_size=2, tp_size=2)
    state = AcceleratorState(parallelism_config=cfg)
    assert state.mesh.shape["dp"] == 2
    assert state.mesh.shape["fsdp"] == 2
    assert state.mesh.shape["tp"] == 2
    assert state.use_fsdp and state.use_tp


def test_accelerator_state_env_parallelism(monkeypatch):
    monkeypatch.setenv("TP_SIZE", "4")
    state = AcceleratorState()
    assert state.mesh.shape["tp"] == 4
    assert state.mesh.shape["dp"] == 2


def test_accelerator_state_bad_mesh():
    with pytest.raises(ValueError):
        AcceleratorState(parallelism_config=ParallelismConfig(tp_size=3))


def test_mixed_precision_validation():
    with pytest.raises(ValueError):
        AcceleratorState(mixed_precision="fp64")


def test_mixed_precision_conflict():
    AcceleratorState(mixed_precision="bf16")
    with pytest.raises(ValueError):
        AcceleratorState(mixed_precision="fp16")


def test_on_process_decorator_factory_form():
    s = PartialState()
    calls = []

    @s.on_process(process_index=0)
    def fn():
        calls.append("ran")

    fn()
    assert calls == ["ran"]

    @s.on_main_process()
    def fn2():
        calls.append("main")

    fn2()
    assert calls == ["ran", "main"]


def test_split_between_processes_tuple_dict_values(monkeypatch):
    s = PartialState()
    # Simulate being process 1 of 2: the short chunk gets padded from a tuple.
    monkeypatch.setitem(s.__dict__, "num_processes", 2)
    monkeypatch.setitem(s.__dict__, "process_index", 1)
    monkeypatch.setattr(s, "wait_for_everyone", lambda: None)
    with s.split_between_processes({"a": (1, 2, 3)}, apply_padding=True) as chunk:
        assert chunk == {"a": [3, 3]}
    with s.split_between_processes((10, 20, 30)) as chunk:
        assert chunk == [30]


def test_partial_state_rejects_unknown_kwargs():
    with pytest.raises(TypeError):
        PartialState(bogus_kwarg=1)


def test_accelerator_state_conflicting_parallelism_reinit():
    AcceleratorState(parallelism_config=ParallelismConfig())
    with pytest.raises(ValueError):
        AcceleratorState(parallelism_config=ParallelismConfig(tp_size=2))


def test_gradient_state():
    gs = GradientState(GradientAccumulationPlugin(num_steps=4))
    assert gs.num_steps == 4
    assert gs.sync_gradients
    assert not gs.in_dataloader
    assert gs.remainder == -1
    gs._set_sync_gradients(False)
    assert not GradientState().sync_gradients


def test_gradient_state_dataloader_registry():
    gs = GradientState()

    class FakeDL:
        end_of_dataloader = True
        remainder = 3

    dl = FakeDL()
    gs._add_dataloader(dl)
    assert gs.in_dataloader
    assert gs.end_of_dataloader
    assert gs.remainder == 3
    gs._remove_dataloader(dl)
    assert not gs.in_dataloader
