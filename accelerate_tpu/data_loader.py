"""Data sharding & device feeding — the L3 data layer.

Counterpart of ``/root/reference/src/accelerate/data_loader.py`` (1425 LoC).
Same user-visible semantics — per-shard batch distribution, ``even_batches``
tail looping, seedable shuffling, mid-epoch resume — rebuilt for SPMD:

* the reference gives each of N processes its own torch DataLoader slice; here
  one *global* batch per step is assembled host-side and laid onto the mesh's
  data axes as a single ``jax.Array`` (``jax.make_array_from_process_local_data``
  on pods, sharded ``device_put`` on one host);
* the XLA ``MpDeviceLoader`` prefetch (reference :643-693) becomes an explicit
  double-buffered host→device pipeline: the next batch's transfer is in flight
  while the current step computes — keeping HBM fed off the critical path;
* uneven tails: SPMD requires every device to see identical shapes, so the
  ``even_batches`` loop-back semantics of the reference
  (BatchSamplerShard data_loader.py:195-262) are the *only* mode on the hot
  path; the duplicate count is tracked in ``GradientState.remainder`` for
  ``gather_for_metrics`` truncation.

Works with torch ``DataLoader``/``Dataset`` objects (torch CPU tensors are
converted at the boundary) and with plain indexables/iterables.
"""

from __future__ import annotations

import itertools
import math
import os
import time
from typing import Any, Callable, Iterable, Iterator, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .logging import get_logger
from .state import AcceleratorState, GradientState, PartialState
from .utils.dataclasses import DataLoaderConfiguration

logger = get_logger(__name__)

_PYTORCH_DATALOADER_KWARGS = {
    "batch_size": 1,
    "shuffle": False,
    "sampler": None,
    "batch_sampler": None,
    "num_workers": 0,
    "collate_fn": None,
    "pin_memory": False,
    "drop_last": False,
    "timeout": 0,
    "worker_init_fn": None,
    "multiprocessing_context": None,
    "generator": None,
    "prefetch_factor": 2,
    "persistent_workers": False,
}


# ---------------------------------------------------------------------------
# Samplers
# ---------------------------------------------------------------------------
class SeedableRandomSampler:
    """Deterministic shuffling: permutation seeded by ``seed + epoch``.

    Reference: SeedableRandomSampler data_loader.py:72 — identical contract
    (same seed+epoch → same order on every process/host).
    """

    def __init__(self, data_source_len: int, seed: int = 0, epoch: int = 0):
        self.data_source_len = data_source_len
        self.seed = seed
        self.epoch = epoch

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __iter__(self) -> Iterator[int]:
        rng = np.random.default_rng(self.seed + self.epoch)
        yield from rng.permutation(self.data_source_len).tolist()

    def __len__(self) -> int:
        return self.data_source_len

    def state_dict(self) -> dict:
        return {"seed": self.seed, "epoch": self.epoch}

    def load_state_dict(self, state: dict) -> None:
        self.seed = state["seed"]
        self.epoch = state["epoch"]


class SequentialSampler:
    def __init__(self, data_source_len: int):
        self.data_source_len = data_source_len

    def set_epoch(self, epoch: int) -> None:
        pass

    def __iter__(self) -> Iterator[int]:
        yield from range(self.data_source_len)

    def __len__(self) -> int:
        return self.data_source_len


class BatchSampler:
    """Group sampler indices into batches (torch parity)."""

    def __init__(self, sampler, batch_size: int, drop_last: bool = False):
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last

    def set_epoch(self, epoch: int) -> None:
        if hasattr(self.sampler, "set_epoch"):
            self.sampler.set_epoch(epoch)

    def __iter__(self) -> Iterator[list[int]]:
        batch: list[int] = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self) -> int:
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return math.ceil(n / self.batch_size)


class GlobalBatchSampler:
    """Yield, per step, the list of ``num_shards`` per-shard index batches.

    This is the engine behind both BatchSamplerShard (one shard's view) and
    the SPMD global loader (all shards concatenated).  Tail semantics follow
    the reference (data_loader.py:195-262):

    * ``even_batches=True`` (default): when the epoch doesn't fill the final
      group of ``num_shards`` batches — or the final batch is short — indices
      loop back to the beginning of the epoch's stream until every shard has a
      full ``batch_size`` batch.  ``remainder`` records how many samples are
      duplicates.
    * ``even_batches=False``: the final partial group is dropped for shards
      beyond what exists (callers must handle ragged step counts; incompatible
      with single-program SPMD, used only for host-level iteration).
    * ``split_batches=True``: each underlying batch is one *global* batch,
      split ``num_shards``-ways (batch_size must divide evenly).
    """

    def __init__(
        self,
        batch_sampler: BatchSampler,
        num_shards: int,
        split_batches: bool = False,
        even_batches: bool = True,
    ):
        self.batch_sampler = batch_sampler
        self.num_shards = num_shards
        self.split_batches = split_batches
        self.even_batches = even_batches
        self.batch_size = getattr(batch_sampler, "batch_size", None)
        if split_batches and self.batch_size is not None and self.batch_size % num_shards != 0:
            raise ValueError(
                f"split_batches=True requires batch_size ({self.batch_size}) to be a "
                f"round multiple of num_shards ({num_shards})."
            )
        self.remainder = 0  # duplicated samples in the final step (set per epoch)
        self.dropped = 0  # samples lost to a ragged tail under even_batches=False
        self._warned_ragged_drop = False

    def set_epoch(self, epoch: int) -> None:
        if hasattr(self.batch_sampler, "set_epoch"):
            self.batch_sampler.set_epoch(epoch)

    def __iter__(self) -> Iterator[list[list[int]]]:
        self.remainder = 0
        self.dropped = 0
        if self.split_batches:
            seen_split: list[int] = []
            target_global = self.batch_size
            for batch in self.batch_sampler:
                if target_global and len(seen_split) < target_global:
                    # padding only ever reads the first global batch's worth
                    # of the epoch stream — don't hold every index in memory
                    seen_split.extend(batch[: target_global - len(seen_split)])
                elif not target_global:
                    seen_split.extend(batch)
                full = max(
                    target_global or 0,
                    self.num_shards * math.ceil(len(batch) / self.num_shards),
                )
                if len(batch) != full:
                    # a short global batch breaks per-shard shapes even when
                    # it divides evenly over num_shards (e.g. 2 of 4 samples
                    # on 2 shards would yield size-1 shard batches).  An
                    # over-long batch (custom sampler lying about batch_size)
                    # is padded up to the next num_shards multiple instead.
                    if not self.even_batches:
                        self.dropped += len(batch)
                        continue
                    # pad from the start of the epoch's sample stream
                    needed = full - len(batch)
                    src = (
                        seen_split
                        if len(seen_split) >= needed
                        else seen_split * math.ceil(needed / max(len(seen_split), 1))
                    )
                    # `remainder` is "duplicates in the most recent batch":
                    # consumers (gather_for_metrics) read it after the final
                    # batch to truncate the looped-back tail
                    self.remainder = needed
                    batch = batch + src[:needed]
                else:
                    self.remainder = 0
                shard_size = len(batch) // self.num_shards
                yield [
                    batch[i * shard_size : (i + 1) * shard_size]
                    for i in range(self.num_shards)
                ]
            return

        group: list[list[int]] = []
        seen: list[int] = []
        target = self.batch_size
        for batch in self.batch_sampler:
            seen.extend(batch)
            group.append(batch)
            if len(group) == self.num_shards:
                # decide the group's fate the moment it fills: with a torch
                # BatchSampler only the epoch's last batch can be short, but a
                # custom batch_sampler may emit short batches anywhere — each
                # group is padded/dropped independently so iteration never
                # stalls on an over-full group
                if all(target is None or len(b) == target for b in group):
                    self.remainder = 0
                    yield group
                else:
                    ragged = self._finish_ragged_group(group, seen, target)
                    if ragged is not None:
                        yield ragged
                group = []
        if not group:
            return
        ragged = self._finish_ragged_group(group, seen, target)
        if ragged is not None:
            yield ragged

    def _finish_ragged_group(
        self,
        group: list[list[int]],
        seen: list[int],
        target: Optional[int],
    ) -> Optional[list[list[int]]]:
        """Even out (or drop) a group with missing/short batches.

        ``even_batches=True``: loop indices back to the start of the epoch's
        sample stream until every shard holds a full ``batch_size`` batch
        (reference BatchSamplerShard semantics, data_loader.py:195-262);
        duplicates are counted in ``remainder`` for gather_for_metrics.
        ``even_batches=False``: the ragged group is dropped — SPMD needs every
        shard on identical shapes — with a one-time warning.
        """
        if not self.even_batches:
            # SPMD requires every shard to run the same program on the same
            # shapes; a ragged tail group has no uniform global batch, so it
            # is dropped — the TPU-native reading of the reference's
            # "shards without a full batch stop iterating" semantics
            # (reference data_loader.py:195-262).  The reference still feeds
            # the ragged tail to the shards that have data; we diverge, so
            # warn (once) with the number of samples the epoch loses.
            dropped = sum(len(b) for b in group)
            self.dropped += dropped
            if not self._warned_ragged_drop:
                self._warned_ragged_drop = True
                logger.warning(
                    "even_batches=False: dropping the ragged tail group "
                    f"({dropped} samples) — under SPMD every shard must run an "
                    "identical program, so unlike the reference the short tail "
                    "is not delivered to a subset of shards. Metrics computed "
                    "through this loader omit these samples; use "
                    "even_batches=True with gather_for_metrics to dedup instead."
                )
            return None
        # loop back to the start of the epoch's sample stream to even out
        # (reference semantics: indices restart from the first samples)
        flat = list(itertools.chain.from_iterable(group))
        size = target or len(group[0])
        needed_total = self.num_shards * size
        dup_source = seen if len(seen) >= needed_total else (seen * math.ceil(needed_total / max(len(seen), 1)))
        padded = flat + dup_source[: max(0, needed_total - len(flat))]
        # "duplicates in the most recent group" — assignment, not +=: the
        # value consumers see after exhaustion must describe the FINAL group,
        # which is what gather_for_metrics truncates (mid-epoch duplicates
        # from nonstandard samplers cannot be deduped there)
        self.remainder = max(0, needed_total - len(flat))
        return [padded[i * size : (i + 1) * size] for i in range(self.num_shards)]

    def _num_full_batches(self) -> int:
        """Count of full ``batch_size`` batches the inner sampler will emit
        (exact for torch-style samplers where only the last batch is short)."""
        n = len(self.batch_sampler)
        sampler = getattr(self.batch_sampler, "sampler", None)
        if (
            self.batch_size
            and sampler is not None
            and not getattr(self.batch_sampler, "drop_last", False)
        ):
            try:
                return len(sampler) // self.batch_size
            except TypeError:
                pass
        return n

    def __len__(self) -> int:
        if self.split_batches:
            if self.even_batches:
                return len(self.batch_sampler)
            # short global batches are dropped, full ones pass through
            return self._num_full_batches()
        n = len(self.batch_sampler)
        if self.even_batches:
            return math.ceil(n / self.num_shards)
        # ragged tail groups are dropped (see __iter__): only groups made of
        # num_shards FULL batches count, and a trailing short batch poisons
        # the group it lands in
        return self._num_full_batches() // self.num_shards

    @property
    def total_batch_size(self) -> int:
        if self.split_batches:
            return self.batch_size
        return (self.batch_size or 0) * self.num_shards


class BatchSamplerShard:
    """One shard's view of a GlobalBatchSampler (reference data_loader.py:109).

    Provided for reference-API parity and multi-process host sharding; the
    SPMD loader uses the underlying GlobalBatchSampler directly.
    """

    def __init__(
        self,
        batch_sampler,
        num_processes: int = 1,
        process_index: int = 0,
        split_batches: bool = False,
        even_batches: bool = True,
    ):
        self.global_sampler = GlobalBatchSampler(
            batch_sampler, num_processes, split_batches=split_batches, even_batches=even_batches
        )
        self.num_processes = num_processes
        self.process_index = process_index
        self.split_batches = split_batches
        self.even_batches = even_batches
        self.batch_size = getattr(batch_sampler, "batch_size", None)

    def set_epoch(self, epoch: int) -> None:
        self.global_sampler.set_epoch(epoch)

    def __iter__(self) -> Iterator[list[int]]:
        for group in self.global_sampler:
            if self.process_index < len(group):
                yield group[self.process_index]

    def __len__(self) -> int:
        return len(self.global_sampler)

    @property
    def total_batch_size(self) -> int:
        return self.global_sampler.total_batch_size


class IterableDatasetShard:
    """Shard an iterable dataset across processes (reference :265).

    Buffers ``batch_size * num_processes`` items and hands each process its
    slice; the tail loops back to the first buffered items when
    ``even_batches`` requires it.
    """

    def __init__(
        self,
        dataset: Iterable,
        batch_size: int = 1,
        drop_last: bool = False,
        num_processes: int = 1,
        process_index: int = 0,
        split_batches: bool = False,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.num_processes = num_processes
        self.process_index = process_index
        self.split_batches = split_batches

    def set_epoch(self, epoch: int) -> None:
        if hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)

    def __iter__(self):
        real_batch_size = (
            self.batch_size if self.split_batches else self.batch_size * self.num_processes
        )
        process_slice = range(
            self.process_index * (real_batch_size // self.num_processes),
            (self.process_index + 1) * (real_batch_size // self.num_processes),
        )
        first_batch = None
        current_batch: list = []
        for element in self.dataset:
            current_batch.append(element)
            if len(current_batch) == real_batch_size:
                for i in process_slice:
                    yield current_batch[i]
                if first_batch is None:
                    first_batch = current_batch.copy()
                current_batch = []
        if not self.drop_last and len(current_batch) > 0:
            if first_batch is None:
                first_batch = current_batch.copy()
            while len(current_batch) < real_batch_size:
                current_batch += first_batch
            for i in process_slice:
                yield current_batch[i]


class TokenDataset:
    """Fixed-length LM pretraining rows over a flat token buffer.

    The reference's pretraining input path is a torch Dataset whose per-sample
    ``__getitem__`` runs in C++ DataLoader workers; the TPU-native equivalent
    keeps the tokens in one contiguous (usually ``np.memmap``) buffer and
    assembles whole batches with a single fused native gather
    (``native.gather_rows``) — no per-sample Python, nothing but the gathered
    rows ever paged in.  Works as a plain map-style dataset too (len/getitem),
    so it composes with every sampler/loader in this module.

    ``tokens`` may be a path to a raw token file (dtype ``token_dtype``), or a
    1-D/2-D array.  1-D input is viewed as ``[n // seq_len, seq_len]`` rows
    (remainder tokens dropped).
    """

    def __init__(self, tokens, seq_len: Optional[int] = None, token_dtype=np.int32):
        if isinstance(tokens, (str, os.PathLike)):
            tokens = np.memmap(tokens, dtype=token_dtype, mode="r")
        tokens = np.asarray(tokens) if not isinstance(tokens, np.memmap) else tokens
        if tokens.ndim == 1:
            if seq_len is None:
                raise ValueError("seq_len is required for flat token input")
            n_rows = tokens.shape[0] // seq_len
            tokens = tokens[: n_rows * seq_len].reshape(n_rows, seq_len)
        elif tokens.ndim != 2:
            raise ValueError("tokens must be 1-D or 2-D")
        self.rows = tokens
        self.seq_len = tokens.shape[1]

    def __len__(self) -> int:
        return self.rows.shape[0]

    def __getitem__(self, i: int) -> np.ndarray:
        return np.asarray(self.rows[i])

    def batch(self, indices, out: Optional[np.ndarray] = None) -> np.ndarray:
        """Gather a whole [len(indices), seq_len] batch in one native call.

        Validation happens here, before the native/numpy branch, so behavior
        is identical whether or not the native library built on this host.
        """
        from . import native

        indices = np.asarray(indices, dtype=np.int64)
        if indices.ndim != 1:
            raise ValueError(f"indices must be 1-D, got shape {indices.shape}")
        # normalize negatives so native and numpy paths agree with __getitem__
        indices = np.where(indices < 0, indices + self.rows.shape[0], indices)
        if indices.size and (indices.min() < 0 or indices.max() >= self.rows.shape[0]):
            raise IndexError("batch index out of range")
        expect = (indices.shape[0], self.seq_len)
        if out is not None and (
            out.shape != expect or out.dtype != self.rows.dtype
            or not out.flags.c_contiguous
        ):
            raise ValueError(f"out must be C-contiguous {expect} {self.rows.dtype}")
        if native.available() and self.rows.flags.c_contiguous:
            return native.gather_rows(self.rows, indices, out=out)
        gathered = self.rows[indices]
        if out is not None:
            out[...] = gathered
            return out
        return np.asarray(gathered)


# ---------------------------------------------------------------------------
# Collation
# ---------------------------------------------------------------------------
def _to_numpy(x):
    if isinstance(x, np.ndarray):
        return x
    if hasattr(x, "detach") and hasattr(x, "numpy"):  # torch tensor / our Tensor
        return np.asarray(x.detach().numpy() if hasattr(x.detach(), "numpy") else x.numpy())
    if isinstance(x, jax.Array):
        return np.asarray(x)
    return np.asarray(x)


def default_collate(samples: Sequence[Any]):
    """Stack a list of samples into batched numpy arrays (torch parity).

    Homogeneous contiguous numpy samples take the native stack path
    (``native.stack_rows``: the reference gets this loop from torch's C++
    collate); everything else falls back to np.stack.
    """
    first = samples[0]
    if isinstance(first, dict):
        return {k: default_collate([s[k] for s in samples]) for k in first}
    if isinstance(first, (tuple, list)) and not isinstance(first, str):
        return type(first)(default_collate(list(col)) for col in zip(*samples))
    arrs = [_to_numpy(s) for s in samples]
    a0 = arrs[0]
    # np.stack's copy loop is already native; the threaded stack only pays
    # for itself when there are worker threads to split a big batch across
    # (measured: parity at 1 thread on large samples, slower on small ones
    # from per-sample pointer marshalling).
    if (
        len(arrs) > 1
        and a0.ndim > 0
        and a0.nbytes * len(arrs) > (1 << 20)
        and all(
            a.shape == a0.shape and a.dtype == a0.dtype and a.flags.c_contiguous
            for a in arrs
        )
    ):
        from . import native

        if native.available() and native._threads_default() > 1:
            return native.stack_rows(arrs)
    return np.stack(arrs)


class PaddingCollate:
    """Dynamic right-padding collate for ragged token sequences.

    torch-world counterpart: tokenizer ``pad``/DataCollatorWithPadding.
    Pads each batch to its longest row — rounded up to ``pad_to_multiple_of``
    so XLA sees a small set of bucketed shapes instead of one program per
    length (compile-cache friendly; 128 matches the MXU lane tile).  Ragged
    1-D integer rows take the native ``pad_stack``; everything else falls
    back to :func:`default_collate`.

    Works on flat samples (list of 1-D arrays) and dict samples
    (``{"input_ids": ..., "labels": ...}``); ``pad_values`` maps dict keys to
    their pad id (default ``pad_value`` elsewhere, e.g. -100 for labels).
    """

    def __init__(self, pad_value=0, pad_to_multiple_of: int = 128,
                 pad_values: Optional[dict] = None):
        self.pad_value = pad_value
        self.pad_to_multiple_of = max(1, pad_to_multiple_of)
        self.pad_values = pad_values or {}

    def _target_len(self, rows) -> int:
        longest = max(r.shape[0] for r in rows)
        m = self.pad_to_multiple_of
        return ((longest + m - 1) // m) * m

    def _pad_rows(self, rows, pad_value):
        from . import native

        rows = [_to_numpy(r) for r in rows]
        if not all(r.ndim == 1 for r in rows):
            return default_collate(rows)
        if any(r.dtype != rows[0].dtype for r in rows):
            # refuse loudly on BOTH paths: the numpy fallback would silently
            # wrap e.g. int64 token ids into an int32 batch
            raise ValueError(
                f"PaddingCollate: mixed row dtypes "
                f"{sorted({str(r.dtype) for r in rows})} — cast the dataset "
                "to one dtype"
            )
        target = self._target_len(rows)
        if native.available() and all(
            r.flags.c_contiguous and r.dtype == rows[0].dtype for r in rows
        ):
            return native.pad_stack(rows, max_len=target, pad_value=pad_value)
        out = np.full((len(rows), target), pad_value, dtype=rows[0].dtype)
        for i, r in enumerate(rows):
            out[i, : r.shape[0]] = r
        return out

    def __call__(self, samples: Sequence[Any]):
        first = samples[0]
        if isinstance(first, dict):
            return {
                k: self._pad_rows(
                    [s[k] for s in samples], self.pad_values.get(k, self.pad_value)
                )
                for k in first
            }
        return self._pad_rows(list(samples), self.pad_value)


# ---------------------------------------------------------------------------
# Device placement
# ---------------------------------------------------------------------------
def batch_to_global_array(batch, mesh=None, sharding=None):
    """Host GLOBAL batch (numpy pytree) → sharded global jax.Array pytree.

    Single host: ``device_put`` with a batch-dim NamedSharding (XLA splits
    across local devices).  Multi-host: ``x`` is still the full global batch
    (every process collates the same global batch from the synchronized
    sampler), so each process device_puts exactly the slices its OWN devices
    are assigned under the sharding and assembles the global array from
    those — handing the whole batch to
    ``jax.make_array_from_process_local_data`` instead would treat it as
    this process's shard and silently double the batch (caught by the
    2-process run of test_script.py: every sample appeared twice).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .parallel.mesh import data_axes

    if sharding is None:
        if mesh is None:
            mesh = AcceleratorState().mesh
        from .parallel.sharding import canonical_spec

        sharding = NamedSharding(mesh, canonical_spec(P(data_axes(mesh)), mesh))

    multi_host = jax.process_count() > 1

    def _place(x):
        x = np.asarray(x)
        if x.ndim == 0:
            return jnp.asarray(x)
        if multi_host:
            idx_map = sharding.addressable_devices_indices_map(x.shape)
            arrs = [jax.device_put(x[idx], d) for d, idx in idx_map.items()]
            return jax.make_array_from_single_device_arrays(x.shape, sharding, arrs)
        return jax.device_put(x, sharding)

    from .utils.operations import recursively_apply

    return recursively_apply(
        _place, batch, test_type=lambda o: isinstance(o, (np.ndarray, jax.Array))
    )


# ---------------------------------------------------------------------------
# DataLoaders
# ---------------------------------------------------------------------------
class _BackgroundPrefetcher:
    """Run a host-batch generator in a producer thread behind a bounded queue.

    The reference's DataLoader gets host/compute overlap from C++ worker
    processes (torch ``num_workers``); under SPMD one producer THREAD is the
    right shape — collate is numpy/native code that releases the GIL, the
    queue bound applies backpressure, and single-producer order keeps
    synchronized-RNG sampling deterministic.  Exceptions propagate to the
    consumer; ``close()`` (or garbage collection of the consumer) stops the
    producer promptly even when the queue is full.
    """

    _SENTINEL = object()

    def __init__(
        self,
        gen_factory: Callable[[Callable[[], bool]], Iterator],
        depth: int,
        unbounded_close: bool = False,
    ):
        import queue as _queue
        import threading as _threading

        self._queue: Any = _queue.Queue(maxsize=max(1, depth))
        self._stop = _threading.Event()
        self._done = False  # sticky exhaustion (consumer side)
        self._gen_factory = gen_factory
        # dispatch-mode multi-process producers run *collectives*; abandoning
        # one mid-collective would let a stale thread race the next epoch's
        # broadcasts (silent corruption) — a loud hang is strictly better there
        self._unbounded_close = unbounded_close
        self._thread = _threading.Thread(target=self._produce, daemon=True)
        self._thread.start()

    def _put_retrying(self, entry) -> bool:
        """Put with stop-aware retries; never gives up while the consumer
        lives (a bounded timeout here would drop terminal sentinels — and
        with them a dataset exception — whenever the queue stayed full)."""
        while not self._stop.is_set():
            try:
                self._queue.put(entry, timeout=0.1)
                return True
            except Exception:  # queue.Full
                continue
        return False

    def _produce(self):
        try:
            # hand the generator our stop flag so it can bail between
            # *element* pulls, not just at put boundaries — the streaming
            # path fetches a whole global batch between puts, and an
            # abandoned producer must not keep draining a shared iterable
            # dataset into the void (round-4 review finding)
            gen = self._gen_factory(self._stop.is_set)
            for item in gen:
                if not self._put_retrying((item, None)):
                    return
            self._put_retrying((self._SENTINEL, None))
        except BaseException as exc:  # noqa: BLE001 — propagate to consumer
            self._put_retrying((self._SENTINEL, exc))

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            # sticky: match the plain-generator contract instead of blocking
            # on a queue that will never be fed again
            raise StopIteration
        item, exc = self._queue.get()
        if item is self._SENTINEL:
            self._done = True
            if exc is not None:
                raise exc
            raise StopIteration
        return item

    def close(self):
        self._stop.set()
        self._done = True
        # drain-and-join: a blocked put wakes, sees the stop flag, and the
        # thread exits BEFORE we return — a stale producer advancing the
        # shared sampler concurrently with the next epoch would corrupt
        # remainder bookkeeping (and, in dispatch mode, emit an unpaired
        # collective).  Bounded: a __getitem__ stuck on network/disk can
        # never finish its current item, and hanging the whole training
        # process in a finally block is worse than abandoning the daemon
        # thread (it can no longer touch the sampler once _stop is set).
        deadline = time.monotonic() + 5.0
        while self._thread.is_alive():
            try:
                while True:
                    self._queue.get_nowait()
            except Exception:
                pass
            self._thread.join(timeout=0.2)
            if (
                not self._unbounded_close
                and time.monotonic() > deadline
                and self._thread.is_alive()
            ):
                logger.warning(
                    "prefetch worker did not exit within 5s (dataset "
                    "__getitem__ appears blocked); abandoning daemon thread"
                )
                break


class DataLoaderStateMixin:
    """Tracks end-of-iteration + remainder in GradientState (reference :407)."""

    def begin(self):
        self.end_of_dataloader = False
        self.remainder = -1
        self.gradient_state._add_dataloader(self)

    def end(self):
        self.gradient_state._remove_dataloader(self)


_TELEMETRY_UNPINNED = object()  # DataLoaderShard._telemetry default sentinel


class DataLoaderShard(DataLoaderStateMixin):
    """The SPMD data loader: one global sharded batch per step.

    Replaces both reference DataLoaderShard (:499) and the XLA
    MpDeviceLoaderWrapper (:643): iteration yields jax.Arrays already laid out
    on the mesh's data axes, with ``prefetch_size`` transfers in flight.
    """

    def __init__(
        self,
        dataset,
        global_batch_sampler: Optional[GlobalBatchSampler] = None,
        collate_fn: Optional[Callable] = None,
        device_placement: bool = True,
        mesh=None,
        prefetch_size: int = 2,
        rng_types: Optional[list] = None,
        synchronized_generator=None,
        skip_batches: int = 0,
        _drop_last: bool = False,
        num_workers: int = 0,
        **kwargs,
    ):
        self.dataset = dataset
        self.global_batch_sampler = global_batch_sampler
        self.collate_fn = collate_fn or default_collate
        self.device_placement = device_placement
        self.mesh = mesh
        self.prefetch_size = max(1, prefetch_size)
        # torch-parity knob: 0 = assemble host batches inline; >=1 = one
        # background producer thread (+ a fetch pool for sample reads when >1)
        self.num_workers = max(0, int(num_workers))
        self._fetch_pool = None
        self.rng_types = rng_types
        self.synchronized_generator = synchronized_generator
        self.skip_batches = skip_batches
        self.gradient_state = GradientState()
        self.epoch = 0
        self.end_of_dataloader = False
        self.remainder = -1
        self._iteration = 0
        # telemetry hub pinned by Accelerator.prepare_data_loader (None =
        # prepared with telemetry off); _TELEMETRY_UNPINNED = never prepared
        # through an accelerator, fall back to the module-global active hub
        self._telemetry = _TELEMETRY_UNPINNED
        # streaming-mode settings (used when global_batch_sampler is None)
        self._stream_global_batch = kwargs.pop("stream_global_batch", 1)
        self._stream_drop_last = _drop_last

    # -- epoch / length -----------------------------------------------------
    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        if self.global_batch_sampler is not None:
            self.global_batch_sampler.set_epoch(epoch)
        if hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)

    def __len__(self) -> int:
        if self.global_batch_sampler is None:
            raise TypeError("streaming DataLoaderShard has no length")
        return len(self.global_batch_sampler) - self.skip_batches

    @property
    def total_batch_size(self) -> int:
        if self.global_batch_sampler is None:
            return self._stream_global_batch
        return self.global_batch_sampler.total_batch_size

    @property
    def batch_sampler(self):
        return self.global_batch_sampler

    @property
    def total_dataset_length(self) -> int:
        """Reference data_loader.py:624: length of the FULL dataset, not the
        per-process shard."""
        if hasattr(self.dataset, "total_length"):
            return self.dataset.total_length
        return len(self.dataset)

    def get_sampler(self):
        """The index sampler feeding the batch sampler (reference
        data_loader.py:630); None for streaming datasets."""
        inner = getattr(self.global_batch_sampler, "batch_sampler", None)
        return getattr(inner, "sampler", None)

    def set_sampler(self, sampler) -> None:
        """Swap the index sampler between epochs (reference :633) — e.g. to
        replace a SeedableRandomSampler after resuming."""
        inner = getattr(self.global_batch_sampler, "batch_sampler", None)
        if inner is None:
            raise TypeError("streaming DataLoaderShard has no sampler to swap")
        inner.sampler = sampler

    # -- iteration ----------------------------------------------------------
    def _producer_runs_collectives(self) -> bool:
        """Whether _host_batches issues collectives (dispatch mode, >1 proc):
        such a producer must never be abandoned mid-collective."""
        return False

    def _host_batches(self, should_stop=None) -> Iterator[tuple[Any, int]]:
        """Yield (collated numpy global batch, remainder_if_final_else_0).

        ``should_stop`` (a nullary callable) comes from the background
        prefetcher's stop flag; the sampler path only advances the shared
        sampler on generator resume, so the put-boundary check suffices
        there, but the streaming path checks it per element."""
        if self.global_batch_sampler is None:
            yield from self._iterable_host_batches(should_stop)
            return
        sampler_iter = iter(self.global_batch_sampler)
        prev_group = None
        for group in sampler_iter:
            if prev_group is not None:
                yield self._collate_group(prev_group), 0
            prev_group = group
        if prev_group is not None:
            yield self._collate_group(prev_group), self.global_batch_sampler.remainder

    def _iterable_host_batches(self, should_stop=None) -> Iterator[tuple[Any, int]]:
        """Streaming path: batch an iterable dataset into global batches,
        looping the tail back to the first samples (IterableDatasetShard
        semantics, reference data_loader.py:265)."""
        size = self._stream_global_batch
        first_batch: Optional[list] = None
        current: list = []
        pending: Optional[list] = None
        pending_remainder = 0
        for element in self.dataset:
            if should_stop is not None and should_stop():
                return
            current.append(element)
            if len(current) == size:
                if pending is not None:
                    yield self.collate_fn(pending), 0
                pending, pending_remainder = current, 0
                if first_batch is None:
                    first_batch = current.copy()
                current = []
        if current and not self._stream_drop_last:
            if pending is not None:
                yield self.collate_fn(pending), 0
            remainder = size - len(current)
            source = first_batch if first_batch is not None else current
            while len(current) < size:
                current += source
            pending, pending_remainder = current[:size], remainder
        if pending is not None:
            yield self.collate_fn(pending), pending_remainder

    def _collate_group(self, group: list[list[int]]):
        flat_indices = list(itertools.chain.from_iterable(group))
        if self.num_workers > 1:
            # parallel sample fetches (torch worker parity): pays off when
            # dataset[i] does real work (decode, disk read); plain numpy rows
            # are better off on the single producer thread
            from concurrent.futures import ThreadPoolExecutor

            if self._fetch_pool is None:
                self._fetch_pool = ThreadPoolExecutor(max_workers=self.num_workers)
            samples = list(self._fetch_pool.map(self.dataset.__getitem__, flat_indices))
        else:
            samples = [self.dataset[i] for i in flat_indices]
        return self.collate_fn(samples)

    def __iter__(self):
        self.begin()
        self.set_epoch(self.epoch)
        self._iteration = self.skip_batches  # in-epoch position (for resume)
        prefetcher = None
        # telemetry (docs/telemetry.md): when enabled, the host time this
        # loader spends producing + device-placing each yielded batch is
        # reported as that step's dataloader-wait phase.  The hub pinned at
        # prepare() time wins — a later Accelerator construction must not
        # reroute (or sever) this loader's wait accounting; the module-global
        # slot only serves loaders never prepared through an accelerator
        telemetry = self._telemetry
        if telemetry is _TELEMETRY_UNPINNED:
            from .telemetry import current_telemetry

            telemetry = current_telemetry()
        try:
            if self.num_workers > 0:
                prefetcher = _BackgroundPrefetcher(
                    self._host_batches,
                    depth=self.prefetch_size,
                    unbounded_close=self._producer_runs_collectives(),
                )
                batches: Iterator = iter(prefetcher)
            else:
                batches = self._host_batches()
            # skip for mid-epoch resume
            for _ in range(self.skip_batches):
                next(batches, None)

            # double-buffered device feed; each pending entry carries its own
            # produce+place cost so a multi-batch queue refill is never
            # lumped onto the one step that happened to trigger it
            pending: list[tuple[Any, int, float]] = []
            exhausted = False
            host_iter = iter(batches)
            while True:
                while not exhausted and len(pending) < self.prefetch_size:
                    t_batch = time.perf_counter() if telemetry is not None else 0.0
                    try:
                        host_batch, remainder = next(host_iter)
                    except StopIteration:
                        exhausted = True
                        break
                    if self.device_placement:
                        placed = batch_to_global_array(host_batch, mesh=self.mesh)
                    else:
                        placed = host_batch
                    produce_ms = (
                        (time.perf_counter() - t_batch) * 1e3
                        if telemetry is not None
                        else 0.0
                    )
                    pending.append((placed, remainder, produce_ms))
                if not pending:
                    break
                batch, remainder, produce_ms = pending.pop(0)
                if telemetry is not None:
                    # owner-keyed so the hub can settle at epoch end: wait
                    # recorded here is only *attributed* to a step if a
                    # captured call actually pops it before this loader's
                    # iteration finishes (batch-scoped attribution,
                    # docs/telemetry.md)
                    telemetry.record_dataloader_wait(produce_ms, owner=self)
                if exhausted and not pending:
                    self.end_of_dataloader = True
                    self.remainder = remainder
                yield batch
                self._iteration += 1
        finally:
            if prefetcher is not None:
                prefetcher.close()  # joins the producer — pool is idle after
            if self._fetch_pool is not None:
                self._fetch_pool.shutdown(wait=False)
                self._fetch_pool = None
            if telemetry is not None:
                # batch-scoped settlement: wait this epoch recorded that no
                # captured step popped was incurred by batches consumed
                # OUTSIDE the capture path (an eager eval epoch, an
                # early-broken loop) — discard it into the hub's eager
                # counter instead of dumping it onto the next captured
                # step's record
                telemetry.discard_dataloader_wait(self)
            self.skip_batches = 0
            self.end()
        # epoch completed in full: advance and reset the in-epoch position
        self.epoch += 1
        self._iteration = 0

    def state_dict(self) -> dict:
        return {"epoch": self.epoch, "iteration": self._iteration}

    def load_state_dict(self, state: dict) -> None:
        self.epoch = state.get("epoch", 0)
        self.skip_batches = state.get("iteration", 0)


class DataLoaderDispatcher(DataLoaderShard):
    """Main-process-reads, broadcast-to-all loader (reference :696).

    On TPU pods the default DataLoaderShard already forms one global batch
    per step, so dispatch mode differs only in *who reads the data*: process 0
    reads the full global batch and broadcasts host-level shards to peers
    (useful when the dataset lives only on host 0).
    """

    def _producer_runs_collectives(self) -> bool:
        return PartialState().num_processes > 1

    def _host_batches(self, should_stop=None):
        state = PartialState()
        if state.num_processes == 1:
            yield from super()._host_batches(should_stop)
            return
        from .utils import operations as ops

        # producer/consumer protocol: roles are rank-asymmetric by design but
        # every yield pairs one broadcast_object_list + broadcast on BOTH
        # sides, and the terminal "stop" broadcast_object_list pairs with the
        # peers' final loop read — statically mismatched token counts,
        # dynamically matched handshake (pinned by tests/test_data_loader.py)
        # graftlint: disable=collective-divergence -- handshake-symmetric protocol
        if state.is_main_process:
            for host_batch, remainder in super()._host_batches(should_stop):
                skeleton = ops.get_data_structure(host_batch)
                ops.broadcast_object_list([("batch", remainder, skeleton)])
                yield ops.broadcast(host_batch), remainder
            if should_stop is not None and should_stop():
                # aborted mid-stream by close(): peers are tearing down too —
                # emitting the terminal broadcast here would race the next
                # epoch's collectives from a dying thread
                return
            ops.broadcast_object_list([("stop", 0, None)])
        else:
            while True:
                signal = ops.broadcast_object_list([None])[0]
                if signal is None or signal[0] == "stop":
                    break
                _, remainder, skeleton = signal
                batch = ops.broadcast(ops.initialize_tensors(skeleton))
                yield batch, remainder


class SkipBatchSampler:
    """Batch sampler skipping the first ``skip_batches`` (reference :1309)."""

    def __init__(self, batch_sampler, skip_batches: int = 0):
        self.batch_sampler = batch_sampler
        self.skip_batches = skip_batches

    def __iter__(self):
        for index, samples in enumerate(self.batch_sampler):
            if index >= self.skip_batches:
                yield samples

    def __len__(self):
        return len(self.batch_sampler) - self.skip_batches

    @property
    def total_batch_size(self):
        return self.batch_sampler.total_batch_size


def skip_first_batches(dataloader, num_batches: int = 0):
    """New loader resuming ``num_batches`` into the epoch (reference :1349)."""
    if isinstance(dataloader, DataLoaderShard):
        new = type(dataloader)(
            dataloader.dataset,
            global_batch_sampler=dataloader.global_batch_sampler,
            collate_fn=dataloader.collate_fn,
            device_placement=dataloader.device_placement,
            mesh=dataloader.mesh,
            prefetch_size=dataloader.prefetch_size,
            skip_batches=num_batches,
            _drop_last=dataloader._stream_drop_last,
            num_workers=dataloader.num_workers,
            stream_global_batch=dataloader._stream_global_batch,
        )
        new.epoch = dataloader.epoch
        new._telemetry = dataloader._telemetry  # keep the prepare-time pin
        return new
    # generic iterable fallback
    def _gen():
        for i, batch in enumerate(dataloader):
            if i >= num_batches:
                yield batch

    return _gen()


# ---------------------------------------------------------------------------
# prepare_data_loader
# ---------------------------------------------------------------------------
def _extract_torch_dataloader(dataloader):
    """Pull (dataset, batch_size, shuffle, collate_fn, drop_last, num_workers)
    out of a torch DataLoader without importing torch at module scope."""
    dataset = dataloader.dataset
    batch_size = dataloader.batch_size
    drop_last = getattr(dataloader, "drop_last", False)
    collate = getattr(dataloader, "collate_fn", None)
    sampler = getattr(dataloader, "sampler", None)
    shuffle = type(sampler).__name__ == "RandomSampler"
    # torch default_collate produces torch tensors; replace with ours unless custom
    if collate is not None and getattr(collate, "__module__", "").startswith("torch"):
        collate = None
    num_workers = getattr(dataloader, "num_workers", 0) or 0
    return dataset, batch_size, shuffle, collate, drop_last, num_workers


def prepare_data_loader(
    dataloader=None,
    device=None,
    num_processes: Optional[int] = None,
    process_index: Optional[int] = None,
    split_batches: bool = False,
    put_on_device: bool = True,
    rng_types: Optional[list] = None,
    dispatch_batches: Optional[bool] = None,
    even_batches: bool = True,
    slice_fn_for_dispatch=None,
    use_seedable_sampler: bool = True,
    data_seed: Optional[int] = None,
    non_blocking: bool = False,
    use_stateful_dataloader: bool = False,
    *,
    dataset=None,
    batch_size: Optional[int] = None,
    shuffle: bool = False,
    collate_fn: Optional[Callable] = None,
    drop_last: bool = False,
    mesh=None,
    prefetch_size: int = 2,
    num_workers: Optional[int] = None,
) -> DataLoaderShard:
    """Build the SPMD loader from a torch DataLoader, our kwargs, or both.

    Reference: prepare_data_loader data_loader.py:988.  ``num_processes`` here
    is the number of *batch shards* — mesh dp×fsdp size — not host count;
    host-level sharding happens inside via process_index slicing of the global
    batch.
    """
    state = AcceleratorState() if AcceleratorState._shared_state else None
    if mesh is None and state is not None:
        mesh = state.mesh
    if num_processes is None:
        from .parallel.mesh import batch_sharding_size

        num_processes = batch_sharding_size(mesh) if mesh is not None else 1

    if dataloader is not None and dataset is None:
        if isinstance(dataloader, DataLoaderShard):
            return dataloader
        if hasattr(dataloader, "dataset"):  # torch DataLoader or similar
            (dataset, batch_size, shuffle, collate_fn, drop_last,
             extracted_workers) = _extract_torch_dataloader(dataloader)
            if num_workers is None:  # unset -> inherit; explicit 0 stays 0
                num_workers = extracted_workers
        else:
            dataset = dataloader
            batch_size = batch_size or 1

    if dataset is None:
        raise ValueError("prepare_data_loader needs a dataloader or a dataset")
    if num_workers is None:
        num_workers = 0
    if batch_size is None:
        batch_size = 1

    has_len = hasattr(dataset, "__len__") and hasattr(dataset, "__getitem__")
    if not has_len:
        # streaming (iterable) dataset path
        global_batch = batch_size if split_batches else batch_size * num_processes
        return DataLoaderShard(
            dataset,
            global_batch_sampler=None,
            collate_fn=collate_fn,
            device_placement=put_on_device,
            mesh=mesh,
            prefetch_size=prefetch_size,
            rng_types=rng_types,
            _drop_last=drop_last,
            num_workers=num_workers,
            stream_global_batch=global_batch,
        )

    n = len(dataset)
    if use_seedable_sampler or shuffle:
        sampler = (
            SeedableRandomSampler(n, seed=data_seed or 0)
            if shuffle
            else SequentialSampler(n)
        )
    else:
        sampler = SequentialSampler(n)
    # with split_batches the user batch_size is already the global size;
    # otherwise it is per-shard and the global sampler groups num_shards of them
    batch_sampler = BatchSampler(sampler, batch_size, drop_last=drop_last)
    global_sampler = GlobalBatchSampler(
        batch_sampler,
        num_shards=num_processes,
        split_batches=split_batches,
        even_batches=even_batches,
    )
    cls = DataLoaderDispatcher if dispatch_batches else DataLoaderShard
    return cls(
        dataset,
        global_batch_sampler=global_sampler,
        collate_fn=collate_fn,
        device_placement=put_on_device,
        mesh=mesh,
        prefetch_size=prefetch_size,
        rng_types=rng_types,
        num_workers=num_workers,
    )
