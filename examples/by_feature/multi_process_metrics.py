"""Feature: exact distributed eval metrics with ``gather_for_metrics``.

Counterpart of /root/reference/examples/by_feature/multi_process_metrics.py:
the SPMD loader pads the final global batch by looping back to the epoch
start; ``gather_for_metrics`` tracks that remainder and truncates the
duplicates so metrics match a single-process run exactly.  Lines marked
`# New Code #` are what this feature adds to nlp_example.py.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from nlp_example import get_dataloaders  # noqa: E402

import accelerate_tpu.nn as nn  # noqa: E402
import accelerate_tpu.optim as optim  # noqa: E402
from accelerate_tpu import Accelerator  # noqa: E402
from accelerate_tpu.models import BertConfig, BertForSequenceClassification  # noqa: E402


def training_function(args):
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    nn.manual_seed(args.seed)
    train_dl, val_dl, vocab = get_dataloaders(accelerator, args.batch_size, args.seed)

    cfg = BertConfig.small() if args.small else BertConfig.base()
    cfg.vocab_size = max(cfg.vocab_size, vocab)
    model = BertForSequenceClassification(cfg)
    optimizer = optim.AdamW(model.parameters(), lr=args.lr)
    scheduler = optim.get_linear_schedule_with_warmup(
        optimizer, 100, len(train_dl) * args.num_epochs * accelerator.num_devices
    )
    model, optimizer, train_dl, val_dl, scheduler = accelerator.prepare(
        model, optimizer, train_dl, val_dl, scheduler
    )

    for epoch in range(args.num_epochs):
        model.train()
        for step, batch in enumerate(train_dl):
            optimizer.zero_grad()
            out = model(
                batch["input_ids"],
                attention_mask=batch["attention_mask"],
                token_type_ids=batch["token_type_ids"],
                labels=batch["labels"],
            )
            accelerator.backward(out["loss"])
            optimizer.step()
            scheduler.step()

        model.eval()
        # New Code #
        # accumulate predictions/references across the whole eval set; the
        # final-batch duplicates (loop-back padding) are truncated by
        # gather_for_metrics using the loader's tracked remainder
        all_preds, all_labels = [], []
        for batch in val_dl:
            out = model(
                batch["input_ids"],
                attention_mask=batch["attention_mask"],
                token_type_ids=batch["token_type_ids"],
            )
            preds = out["logits"].data.argmax(-1)
            # New Code #
            preds, labels = accelerator.gather_for_metrics(
                (preds, batch["labels"])
            )
            all_preds.append(np.asarray(preds))
            all_labels.append(np.asarray(labels))
        # New Code #
        preds = np.concatenate(all_preds)
        labels = np.concatenate(all_labels)
        acc = float((preds == labels).mean())
        accelerator.print(
            f"epoch {epoch}: accuracy={acc:.4f} over exactly {len(labels)} samples"
        )
    return acc


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--mixed_precision", type=str, default="bf16", choices=["no", "fp16", "bf16"])
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--num_epochs", type=int, default=2)
    parser.add_argument("--lr", type=float, default=2e-5)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--small", action="store_true")
    args = parser.parse_args()
    training_function(args)


if __name__ == "__main__":
    main()
