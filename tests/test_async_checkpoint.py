"""Async (overlapped) checkpointing: save_state(async_save=True).

The TPU-native practice (orbax-style) the reference lacks: jax arrays are
immutable, so holding references at call time freezes the checkpoint
contents while a background thread runs the D2H copies and file writes —
training continues immediately and must NOT leak into the snapshot.
"""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.nn import Tensor


def _setup(**acc_kwargs):
    Accelerator._reset_state()
    nn.manual_seed(0)
    acc = Accelerator(**acc_kwargs)
    model = nn.Linear(8, 4)
    opt = optim.AdamW(model.parameters(), lr=1e-2)
    model, opt = acc.prepare(model, opt)

    def step(x):
        opt.zero_grad()
        loss = model(Tensor(x)).sum()
        acc.backward(loss)
        opt.step()
        return loss

    return acc, model, opt, step


def test_async_save_roundtrip(tmp_path):
    acc, model, opt, step = _setup()
    step(jnp.ones((4, 8)))
    saved_w = np.asarray(jax.device_get(model.weight.data)).copy()
    acc.save_state(str(tmp_path / "ckpt"), async_save=True)
    acc.wait_for_checkpoint()
    model.weight.data = model.weight.data * 0 + 9.0
    acc.load_state(str(tmp_path / "ckpt"))
    np.testing.assert_allclose(np.asarray(model.weight.data), saved_w)


def test_async_save_snapshots_at_call_time(tmp_path):
    """Steps taken AFTER save_state(async_save=True) returns must not leak
    into the checkpoint — it captures the state at call time."""
    acc, model, opt, step = _setup()
    step(jnp.ones((4, 8)))
    at_save = np.asarray(jax.device_get(model.weight.data)).copy()
    acc.save_state(str(tmp_path / "ckpt"), async_save=True)
    # training continues immediately, mutating params while the save runs
    for _ in range(3):
        step(jnp.ones((4, 8)))
    after = np.asarray(jax.device_get(model.weight.data))
    assert not np.allclose(after, at_save)  # training really moved on
    acc.wait_for_checkpoint()
    acc.load_state(str(tmp_path / "ckpt"))
    np.testing.assert_allclose(np.asarray(model.weight.data), at_save)
    # optimizer state came from the snapshot too: one more identical step
    # from the restored state must be deterministic
    step(jnp.ones((4, 8)))


def test_async_save_survives_captured_step_donation(tmp_path):
    """compile_step DONATES the live state buffers each call; the async
    snapshot must hold materialized copies, not references that donation
    deletes (round-4 review finding)."""
    acc, model, opt, _ = _setup()

    def step_fn(x):
        opt.zero_grad()
        loss = model(Tensor(x)).sum()
        acc.backward(loss)
        opt.step()
        return loss

    step = acc.compile_step(step_fn)
    x = jnp.ones((4, 8))
    step(x)
    step(x)  # warmed: donation active from here on
    at_save = np.asarray(jax.device_get(model.weight.data)).copy()
    acc.save_state(str(tmp_path / "ckpt"), async_save=True)
    for _ in range(3):  # each call donates the previous state buffers
        step(x)
    acc.wait_for_checkpoint()  # raises if the writer read deleted arrays
    acc.load_state(str(tmp_path / "ckpt"))
    np.testing.assert_allclose(
        np.asarray(jax.device_get(model.weight.data)), at_save, rtol=1e-6
    )


def test_async_save_sharded_fsdp(tmp_path):
    """Sharded (per-shard files) async save under an fsdp mesh round-trips."""
    acc, model, opt, step = _setup(
        parallelism_config=ParallelismConfig(fsdp_size=8), mixed_precision="bf16"
    )
    step(jnp.ones((8, 8), jnp.bfloat16))
    saved_w = np.asarray(jax.device_get(model.weight.data), dtype=np.float32)
    acc.save_state(str(tmp_path / "ckpt"), async_save=True, sharded_state=True)
    acc.wait_for_checkpoint()
    assert any(
        ".shard-" in f and f.startswith("pytree_model")
        for f in os.listdir(tmp_path / "ckpt")
    ), os.listdir(tmp_path / "ckpt")
    model.weight.data = model.weight.data * 0
    acc.load_state(str(tmp_path / "ckpt"))
    np.testing.assert_allclose(
        np.asarray(jax.device_get(model.weight.data), dtype=np.float32), saved_w
    )


def test_async_save_error_surfaces_on_wait(tmp_path):
    acc, model, opt, step = _setup()
    target = tmp_path / "blocked"
    target.mkdir()
    # a directory squatting on the weights filename makes the background
    # thread's open() fail (chmod tricks don't stop a root test runner)
    (target / "pytree_model.safetensors").mkdir()
    acc.save_state(str(target), async_save=True)
    with pytest.raises(BaseException):
        acc.wait_for_checkpoint()


def test_next_save_waits_for_inflight(tmp_path):
    """A second save_state (sync or async) drains the in-flight one first —
    two concurrent writers to checkpoint dirs would interleave rotation."""
    acc, model, opt, step = _setup()
    acc.save_state(str(tmp_path / "a"), async_save=True)
    acc.save_state(str(tmp_path / "b"))  # must not start until 'a' landed
    assert os.path.exists(tmp_path / "a" / "accelerator_meta.json")
    assert os.path.exists(tmp_path / "b" / "accelerator_meta.json")
    assert getattr(acc, "_async_save_thread", None) is None


def test_end_training_waits(tmp_path):
    acc, model, opt, step = _setup()
    acc.save_state(str(tmp_path / "ckpt"), async_save=True)
    acc.end_training()
    assert os.path.exists(tmp_path / "ckpt" / "accelerator_meta.json")


# --------------------------------------------------------- state pre-hooks
def test_save_load_state_pre_hooks_roundtrip_sidecar(tmp_path):
    """Hooks save/load a sidecar config next to the checkpoint (reference
    register_save_state_pre_hook / register_load_state_pre_hook,
    accelerator.py:3074/3241)."""
    acc, model, opt, step = _setup()
    seen = {}

    def save_hook(models, weights, output_dir):
        assert len(models) == len(weights) == 1
        with open(os.path.join(output_dir, "sidecar.txt"), "w") as f:
            f.write("cfg-v7")

    def load_hook(models, input_dir):
        with open(os.path.join(input_dir, "sidecar.txt")) as f:
            seen["cfg"] = f.read()

    h1 = acc.register_save_state_pre_hook(save_hook)
    h2 = acc.register_load_state_pre_hook(load_hook)
    acc.save_state(str(tmp_path / "ckpt"))
    acc.load_state(str(tmp_path / "ckpt"))
    assert seen["cfg"] == "cfg-v7"
    h1.remove()
    h2.remove()
    acc.save_state(str(tmp_path / "ckpt2"))
    assert not os.path.exists(tmp_path / "ckpt2" / "sidecar.txt")  # detached


def test_save_hook_can_override_weights(tmp_path):
    """Mutating the weights list customizes what is written — the reference's
    documented take-over-saving pattern."""
    acc, model, opt, step = _setup()

    def save_hook(models, weights, output_dir):
        weights[0] = {k: v * 0 + 5.0 for k, v in weights[0].items()}

    acc.register_save_state_pre_hook(save_hook)
    acc.save_state(str(tmp_path / "ckpt"))
    acc.load_state(str(tmp_path / "ckpt"))
    np.testing.assert_allclose(np.asarray(model.weight.data), 5.0)


def test_save_hook_applies_to_async_saves(tmp_path):
    acc, model, opt, step = _setup()
    acc.register_save_state_pre_hook(
        lambda models, weights, output_dir: weights.__setitem__(
            0, {k: v * 0 + 3.0 for k, v in weights[0].items()}
        )
    )
    acc.save_state(str(tmp_path / "ckpt"), async_save=True)
    acc.wait_for_checkpoint()
    acc.load_state(str(tmp_path / "ckpt"))
    np.testing.assert_allclose(np.asarray(model.weight.data), 3.0)


def test_load_hook_can_remove_model_from_restore(tmp_path):
    acc, model, opt, step = _setup()
    acc.save_state(str(tmp_path / "ckpt"))
    model.weight.data = model.weight.data * 0 + 42.0
    acc.register_load_state_pre_hook(lambda models, input_dir: models.clear())
    acc.load_state(str(tmp_path / "ckpt"))
    # the hook took over model loading: nothing restored the clobber
    np.testing.assert_allclose(np.asarray(model.weight.data), 42.0)
