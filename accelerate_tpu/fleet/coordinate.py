"""Pillar 1 — coordinated multi-host drain + rollback.

The resilience layer's rollback deliberately refuses multi-process runs: a
lone rank restoring while its peers proceed to the next step's collectives
would deadlock the mesh (``resilience/retry.py``).  The fix is the
torchelastic-style restore protocol this module implements:

1. **Offer** — every rank enumerates the COMPLETE checkpoints it can see
   (:func:`local_restore_candidates`): the resilience layer's last noted
   checkpoint plus every sentinel-complete folder under the automatic-naming
   directory, each tagged with the training step its meta sentinel records.
2. **Vote** — an allgather barrier (:func:`vote_restore_point`,
   ``gather_object`` hands every rank the full offer list) after which each
   rank runs the SAME pure agreement function over the SAME gathered offers:
   the newest checkpoint present in EVERY rank's offer set wins
   (:func:`agree_restore_point`).  A checkpoint only some ranks can see — a
   host-local directory, a drain that landed after a peer died — can never
   be chosen, because the loser ranks' collective ``load_state`` would hang
   on its missing shards.
3. **Restore** — all ranks issue the collective ``load_state`` against the
   agreed point together (:func:`coordinated_rollback`).

Why every rank reaches the vote: a captured-step dispatch is SPMD — a
transient fault on the program's collective path surfaces on EVERY rank's
dispatch of that step, so each rank's retrier exhausts on the same call
index and enters the protocol together (the same all-ranks-observe-the-
fault assumption torchelastic's rendezvous makes).  A genuinely one-sided
failure (a single rank's host dying) is the *elastic resize* case, not a
rollback (docs/elastic.md).

The agreement math is pure host code over offer dicts, so it tests on a
single process with synthetic per-rank offer lists — exactly like the
telemetry fleet-skew merge.
"""

from __future__ import annotations

import os
from typing import Optional

from ..checkpointing import checkpoint_step, is_complete_checkpoint
from ..logging import get_logger
from ..utils.operations import gather_object

logger = get_logger(__name__)


def local_restore_candidates(accelerator) -> list[dict]:
    """This rank's restore-point offers: ``{"path", "step"}`` per COMPLETE
    checkpoint it can see, newest first.  Sources: the resilience hub's
    last noted checkpoint and the automatic-naming directory."""
    paths: list[str] = []
    resilience = getattr(accelerator, "resilience", None)
    if resilience is not None and resilience.last_checkpoint:
        paths.append(resilience.last_checkpoint)
    project = accelerator.project_configuration
    if project.automatic_checkpoint_naming and accelerator.project_dir:
        base = os.path.join(accelerator.project_dir, "checkpoints")
        if os.path.isdir(base):
            paths.extend(
                os.path.join(base, f)
                for f in os.listdir(base)
                if f.startswith("checkpoint_") and f.split("_")[-1].isdigit()
            )
    offers: list[dict] = []
    seen: set[str] = set()
    for path in paths:
        path = os.path.abspath(path)
        if path in seen or not is_complete_checkpoint(path):
            continue
        seen.add(path)
        step = checkpoint_step(path)
        offers.append({"path": path, "step": step if step is not None else -1})
    offers.sort(key=lambda o: (o["step"], o["path"]), reverse=True)
    return offers


def agree_restore_point(per_rank: list[list[dict]]) -> Optional[dict]:
    """The restore point every rank can load: the highest-step offer whose
    path appears in EVERY rank's offer list (ties broken by path so all
    ranks deterministically pick the same folder).  ``None`` when the
    intersection is empty — no checkpoint is safe to restore collectively."""
    if not per_rank:
        return None
    common: Optional[dict] = None
    path_sets = [{o["path"] for o in offers} for offers in per_rank]
    for offer in per_rank[0]:
        if all(offer["path"] in paths for paths in path_sets):
            if common is None or (offer["step"], offer["path"]) > (
                common["step"], common["path"]
            ):
                common = offer
    return dict(common) if common is not None else None


def vote_restore_point(accelerator, fleet=None) -> Optional[dict]:
    """COLLECTIVE — every rank must call (the coordinated-rollback path
    does).  Allgathers each rank's offers and returns the agreement; every
    rank computes it from the same gathered list, so no second broadcast is
    needed.  Records a ``restore_vote`` fleet event with the full ballot."""
    from ..telemetry import flightrec

    local = local_restore_candidates(accelerator)
    flightrec.record("fleet_vote_begin", offers=len(local))
    # gather_object flattens one list level: each rank contributes
    # [its offer list] and everyone receives [rank0_offers, rank1_offers, ...]
    per_rank = gather_object([local])
    # the agree_* merge ticks the collective-sequence counter: every rank
    # computes it at the same ordinal position, so the seq stays the
    # cross-rank alignment key through the vote (docs/telemetry.md)
    flightrec.note_collective("agree_restore_point", ranks=len(per_rank))
    agreed = agree_restore_point(per_rank)
    flightrec.record(
        "fleet_vote_end",
        agreed=agreed["path"] if agreed is not None else None,
    )
    if fleet is not None:
        fleet.record_event(
            "restore_vote",
            ranks=len(per_rank),
            # the full ballot: what each rank offered — the forensic record
            # an operator needs when the agreed point looks wrong after an
            # incident (offers are few per rank; sentinel-complete only)
            ballot=[[dict(o) for o in offers] for offers in per_rank],
            agreed=agreed["path"] if agreed is not None else None,
            agreed_step=agreed["step"] if agreed is not None else None,
        )
    return agreed


def coordinated_rollback(accelerator, fleet=None) -> Optional[str]:
    """Vote, then have every rank issue the collective ``load_state``
    against the agreed restore point.  Returns the restored path, or
    ``None`` when no all-ranks-visible checkpoint exists (the caller then
    escalates exactly as the no-checkpoint single-process case does)."""
    agreed = vote_restore_point(accelerator, fleet=fleet)
    if agreed is None:
        return None
    accelerator.load_state(agreed["path"])
    if fleet is not None:
        fleet.record_event(
            "coordinated_rollback", checkpoint=agreed["path"], step=agreed["step"]
        )
    logger.info("coordinated rollback restored %s", agreed["path"])
    return agreed["path"]
