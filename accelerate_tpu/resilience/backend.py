"""Pillar 1 — hardened backend init.

Promotes bench.py's round-1 postmortem mitigation ("the whole round's perf
story died on one flaky backend init") into library behavior:

* the PJRT probe runs in a THROWAWAY subprocess — a hung client holds the
  C++ runtime lock and cannot be cancelled in-process, so the only safe
  watchdog is a separate interpreter;
* configurable attempts with exponential backoff + jitter (the observed
  outage mode is hang-then-UNAVAILABLE with occasional recovery, so spaced
  retries materially raise the odds of catching the backend up);
* an ordered platform fallback chain (requested → cpu by default) so a run
  always comes up SOMEWHERE and says so, instead of dying rc!=0;
* a structured :class:`InitReport` (per-attempt cause, elapsed, fallback)
  that bench.py serializes into its JSON diagnostics and the resilience hub
  emits as a telemetry event.

Opt-in at state construction via ``ACCELERATE_RESILIENCE_INIT=1`` (see
``state.PartialState``), or call :func:`init_backend` directly (bench.py
does).  Env knobs: ``ACCELERATE_RESILIENCE_INIT_ATTEMPTS`` (5),
``ACCELERATE_RESILIENCE_INIT_TIMEOUT_S`` (120),
``ACCELERATE_RESILIENCE_INIT_BACKOFF_S`` (5),
``ACCELERATE_RESILIENCE_INIT_FALLBACK`` (comma chain, default ``cpu``).
"""

from __future__ import annotations

import os
import random
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

# the container sitecustomize pins the TPU plugin regardless of the
# JAX_PLATFORMS env var; config.update after import is what actually selects
# the backend — without it a CPU-fallback probe still dials the (possibly
# wedged) TPU tunnel and hangs
_PROBE_CODE = (
    "import os, jax; p = os.environ.get('JAX_PLATFORMS'); "
    "p and jax.config.update('jax_platforms', p); "
    "d = jax.devices(); print(d[0].platform, len(d))"
)

# most recent report from this process — the resilience hub picks it up at
# Accelerator construction so an init that ran before telemetry existed
# still lands in the event stream
LAST_INIT_REPORT: Optional["InitReport"] = None


@dataclass
class InitAttempt:
    platform: str  # "(default)" = whatever the env/sitecustomize selects
    ok: bool
    detail: str
    elapsed_s: float

    def to_dict(self) -> dict:
        return {
            "platform": self.platform,
            "ok": self.ok,
            "detail": self.detail,
            "elapsed_s": round(self.elapsed_s, 3),
        }


@dataclass
class InitReport:
    """Structured outcome of one hardened init: which platform came up, how
    many probes it took, and what each failed attempt saw."""

    requested: str
    platform: Optional[str]  # platform that came up (None = nothing probed ok)
    ok: bool
    fallback: Optional[str]  # set when platform != requested
    attempts: list[InitAttempt] = field(default_factory=list)
    elapsed_s: float = 0.0
    ts: float = 0.0  # epoch seconds at init start (outage-log joinable)

    @property
    def requested_attempts(self) -> list[InitAttempt]:
        return [a for a in self.attempts if a.platform == self.requested]

    def to_bench_diag(self) -> dict:
        """The exact diagnostic keys bench.py has emitted since r02
        (``init_attempts``/``init_detail``/``platform_requested`` + optional
        ``fallback``), plus ``init_ts`` so tools/outage_summary.py can join
        the init against probe-log DOWN windows."""
        requested = self.requested_attempts or self.attempts
        diag = {
            "init_attempts": len(requested),
            "init_detail": requested[-1].detail if requested else "",
            "platform_requested": self.requested,
            "init_ts": int(self.ts),
        }
        if self.fallback is not None:
            diag["fallback"] = self.fallback
        return diag

    def to_event(self) -> dict:
        return {
            "event": "init",
            "requested": self.requested,
            "platform": self.platform,
            "ok": self.ok,
            "fallback": self.fallback,
            "attempts": len(self.attempts),
            "elapsed_s": round(self.elapsed_s, 3),
            "detail": self.attempts[-1].detail if self.attempts else "",
        }


def probe_backend_once(
    platform: Optional[str] = None,
    timeout_s: float = 120.0,
    injector=None,
) -> tuple[bool, str]:
    """Try initializing a JAX backend in a throwaway subprocess.

    ``platform=None`` probes whatever the current env selects (the requested
    backend); a string pins ``JAX_PLATFORMS`` for the probe only.  Returns
    ``(ok, detail)`` — detail is the probe's stdout on success, the failure
    cause on failure.
    """
    if injector is not None:
        detail = injector.maybe_init_fault(timeout_s)
        if detail is not None:
            return False, detail
    env = os.environ.copy()
    if platform is not None:
        env["JAX_PLATFORMS"] = platform
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_CODE],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env=env,
        )
    except subprocess.TimeoutExpired:
        return False, f"backend init exceeded {timeout_s:.0f}s (hung PJRT client)"
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()
        return False, tail[-1][:300] if tail else f"rc={proc.returncode}"
    return True, proc.stdout.strip()


def backoff_delay(
    attempt: int,
    base_s: float,
    cap_s: float = 30.0,
    jitter: float = 0.25,
    rng: Optional[random.Random] = None,
) -> float:
    """One delay of the exponential-backoff schedule: ``base * 2**attempt``,
    capped, with symmetric jitter so a fleet of preempted workers doesn't
    reprobe a recovering backend in lockstep.  The single shared formula —
    init probing and dispatch retry both use it."""
    rng = rng if rng is not None else random.Random()
    delay = min(cap_s, base_s * (2.0 ** attempt))
    return max(0.0, delay * (1.0 + rng.uniform(-jitter, jitter)))


def backoff_delays(
    attempts: int,
    base_s: float,
    cap_s: float = 30.0,
    jitter: float = 0.25,
    rng: Optional[random.Random] = None,
) -> list[float]:
    """Delays BETWEEN ``attempts`` probes (see :func:`backoff_delay`)."""
    rng = rng if rng is not None else random.Random()
    return [
        backoff_delay(attempt, base_s, cap_s, jitter, rng)
        for attempt in range(max(0, attempts - 1))
    ]


def _env_float(name: str, default: float) -> float:
    value = os.environ.get(name)
    return float(value) if value is not None else default


def init_backend(
    platforms: Optional[list[str]] = None,
    attempts: Optional[int] = None,
    timeout_s: Optional[float] = None,
    backoff_s: Optional[float] = None,
    backoff_cap_s: float = 30.0,
    jitter: float = 0.25,
    apply: bool = True,
    telemetry=None,
    injector=None,
    sleep: Callable[[float], None] = time.sleep,
    rng: Optional[random.Random] = None,
) -> InitReport:
    """Probe → retry with backoff → fall down the platform chain.

    ``platforms`` is the ordered chain to try; ``None`` resolves to
    ``[requested] + ACCELERATE_RESILIENCE_INIT_FALLBACK`` (default
    ``[requested, "cpu"]``).  The first (requested) entry gets the full
    ``attempts`` budget; each fallback entry gets one probe — fallbacks exist
    to come up NOW, not to be retried.  If even the last chain entry fails
    its probe it is applied anyway (``ok=False``): a run that limps up on CPU
    and says so beats one that dies before emitting an artifact.

    With ``apply=True`` a fallback platform is pinned into
    ``os.environ["JAX_PLATFORMS"]`` (and ``jax.config`` when jax is already
    imported) so every later ``jax.devices()`` in this process — and every
    subprocess — lands on the platform that actually came up.
    """
    global LAST_INIT_REPORT
    if attempts is None:
        attempts = int(os.environ.get("ACCELERATE_RESILIENCE_INIT_ATTEMPTS", 5))
    if timeout_s is None:
        timeout_s = _env_float("ACCELERATE_RESILIENCE_INIT_TIMEOUT_S", 120.0)
    if backoff_s is None:
        backoff_s = _env_float("ACCELERATE_RESILIENCE_INIT_BACKOFF_S", 5.0)
    if platforms is None:
        requested = os.environ.get("JAX_PLATFORMS") or "(default)"
        chain_env = os.environ.get("ACCELERATE_RESILIENCE_INIT_FALLBACK", "cpu")
        fallbacks = [p.strip() for p in chain_env.split(",") if p.strip()]
        platforms = [requested] + [p for p in fallbacks if p != requested]
    else:
        # an explicit chain defines its own "requested" head
        requested = platforms[0]

    t_start = time.monotonic()
    report = InitReport(
        requested=requested, platform=None, ok=False, fallback=None, ts=time.time()
    )
    for chain_index, platform in enumerate(platforms):
        # full retry budget for the requested platform, one shot per fallback
        budget = max(1, attempts) if chain_index == 0 else 1
        delays = backoff_delays(budget, backoff_s, backoff_cap_s, jitter, rng)
        for attempt in range(budget):
            t0 = time.monotonic()
            ok, detail = probe_backend_once(
                platform=None if platform == "(default)" else platform,
                timeout_s=timeout_s,
                injector=injector,
            )
            report.attempts.append(
                InitAttempt(platform, ok, detail, time.monotonic() - t0)
            )
            if ok:
                report.ok = True
                report.platform = platform
                break
            if attempt < budget - 1:
                sleep(delays[attempt])
        if report.ok:
            break
    if not report.ok:
        # last resort: apply the final chain entry unprobed-ok so the run
        # still reaches an artifact (bench r02-r05 behavior, now library-wide)
        report.platform = platforms[-1]
    if report.platform != requested:
        report.fallback = report.platform
        if apply and report.platform != "(default)":
            os.environ["JAX_PLATFORMS"] = report.platform
            try:
                import jax

                jax.config.update("jax_platforms", report.platform)
            except Exception:  # backend already initialized: env still set
                pass
    report.elapsed_s = time.monotonic() - t_start
    if telemetry is not None and getattr(telemetry, "enabled", False):
        telemetry.record_resilience(report.to_event())
    LAST_INIT_REPORT = report
    return report
