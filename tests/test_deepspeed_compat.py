"""ds_config.json ingestion → native mesh plugins (ZeRO subsumption)."""

import json

import pytest

from accelerate_tpu import Accelerator
from accelerate_tpu.utils.deepspeed_compat import from_deepspeed_config


ZERO3 = {
    "zero_optimization": {
        "stage": 3,
        "offload_optimizer": {"device": "none"},
        "offload_param": {"device": "none"},
    },
    "bf16": {"enabled": True},
    "gradient_accumulation_steps": 4,
    "train_micro_batch_size_per_gpu": "auto",
    "gradient_clipping": 1.0,
}


def test_zero3_maps_to_full_shard(tmp_path):
    path = tmp_path / "ds_config.json"
    path.write_text(json.dumps(ZERO3))
    compat = from_deepspeed_config(str(path), micro_batch_size=8)
    assert compat.zero_stage == 3
    assert compat.fsdp_plugin.sharding_strategy == "FULL_SHARD"
    assert compat.mixed_precision == "bf16"
    assert compat.gradient_accumulation_steps == 4
    assert compat.micro_batch_size == 8  # "auto" resolved from caller
    assert compat.gradient_clipping == 1.0


def test_zero2_and_fp16_and_stage0():
    c2 = from_deepspeed_config({"zero_optimization": {"stage": 2}, "fp16": {"enabled": True}})
    assert c2.fsdp_plugin.sharding_strategy == "SHARD_GRAD_OP"
    assert c2.mixed_precision == "fp16"
    c0 = from_deepspeed_config({})
    assert c0.fsdp_plugin is None and c0.zero_stage == 0 and c0.mixed_precision == "no"


def test_offload_param_maps_and_stage0_warns():
    # stage >= 1: offload_param maps to the real param-offload mechanism
    # (tests/test_param_offload.py exercises it end to end)
    cfg = {"zero_optimization": {"stage": 3, "offload_param": {"device": "cpu"}}}
    compat = from_deepspeed_config(cfg)
    assert compat.fsdp_plugin.cpu_offload is True
    # stage 0 has no fsdp plugin to ride — still warns
    cfg0 = {"zero_optimization": {"stage": 0, "offload_param": {"device": "cpu"}}}
    with pytest.warns(UserWarning, match="offload"):
        from_deepspeed_config(cfg0)


def test_unsupported_stage_raises():
    with pytest.raises(ValueError):
        from_deepspeed_config({"zero_optimization": {"stage": 7}})


def test_kwargs_build_a_working_accelerator():
    compat = from_deepspeed_config(ZERO3)
    acc = Accelerator(**compat.accelerator_kwargs())
    assert acc.mixed_precision == "bf16"
    assert acc.gradient_state.num_steps == 4
