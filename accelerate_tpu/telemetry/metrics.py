"""Pillar 7 — live metrics endpoint: Prometheus text over stdlib HTTP.

A running training job or decode service should be scrapable without
touching its process: :class:`MetricsServer` runs a daemon
``http.server`` thread serving ``GET /metrics`` in Prometheus text
exposition format (version 0.0.4), plus a ``GET /healthz``
readiness+liveness probe (JSON; 200 while every registered health source
reports ready, 503 otherwise — the decode service registers
"programs warmed ∧ pool allocated ∧ not draining").  Every scrape renders *live* — the
server holds no state beyond its provider callables, so the numbers are
whatever the telemetry hub / :class:`~..serving.DecodeService` report at
that instant.

Metric namespace: ``atpu_<provider>_<field>``; nested dicts flatten with
``_``; names ending ``_total`` are typed ``counter``, everything else
``gauge``.  A :class:`LatencyHistogram` value renders as a native
Prometheus histogram — cumulative ``_bucket{le="..."}`` series plus
``_sum``/``_count`` — so step/TTFT/TPOT latencies expose full
distributions a server-side ``histogram_quantile()`` can aggregate across
the fleet, instead of point-in-time p50/p99 gauges that cannot be merged.
Providers are fail-soft: one raising provider becomes a comment line in
the scrape, never a 500.

Wiring: ``TelemetryKwargs(metrics_port=...)`` / ``$ACCELERATE_METRICS_PORT``
starts one automatically (port 0 = ephemeral, read ``server.port``);
``Telemetry.serve_metrics()`` starts one on demand; a ``DecodeService``
constructed with a telemetry hub registers its ``metrics()`` snapshot as
the ``serving`` provider (occupancy, queue depth, block-pool free %, and
sliding-window TTFT/TPOT percentiles).
"""

from __future__ import annotations

import re
import threading
from bisect import bisect_left
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from ..logging import get_logger

logger = get_logger(__name__)

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# the grammar of one exposition sample line this module emits: a bare
# metric name, optionally the one label histograms require
# (`_bucket{le="..."}`), then the value.  Exported so the smoke tool and
# the endpoint tests validate the SAME grammar the renderer produces —
# a format change here updates every validator with it.
SAMPLE_LINE_RE = re.compile(
    r"^[a-zA-Z_][a-zA-Z0-9_]*(\{le=\"[^\"]+\"\})? [-+0-9eE.naif]+$"
)

# default latency bucket bounds (ms): log-ish spacing from sub-ms decode
# steps to multi-minute cold compiles; +Inf is implicit
DEFAULT_LATENCY_BUCKETS_MS = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0, 30000.0, 60000.0,
)


class LatencyHistogram:
    """Cumulative Prometheus histogram recorder.

    ``observe()`` is two integer bumps and a float add — cheap enough for
    the capture hot path and the serving completion path.  Rendering emits
    the standard ``_bucket{le=...}`` / ``_sum`` / ``_count`` series, which
    (unlike the sliding-window p50/p99 gauges they replace) are monotonic
    counters a Prometheus server can rate() and quantile() over any window
    and aggregate across ranks/replicas.  Writer/scraper races read a
    bucket count at most one observation stale — monotonicity is preserved
    because counts only ever grow.
    """

    def __init__(self, buckets=DEFAULT_LATENCY_BUCKETS_MS):
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket bound")
        # per-bound counts (NON-cumulative internally; cumulated at render)
        self._counts = [0] * (len(self.buckets) + 1)  # [+Inf] last
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        self._counts[bisect_left(self.buckets, float(value))] += 1
        self.sum += float(value)
        self.count += 1

    def cumulative_counts(self) -> list[int]:
        """Per-bound cumulative counts, ``+Inf`` last (== ``count``)."""
        out, running = [], 0
        for c in self._counts:
            running += c
            out.append(running)
        return out

    def render_lines(self, name: str) -> list[str]:
        lines = [f"# TYPE {name} histogram"]
        cumulative = self.cumulative_counts()
        for bound, c in zip(self.buckets, cumulative):
            le = f"{bound:g}"
            lines.append(f'{name}_bucket{{le="{le}"}} {c}')
        lines.append(f'{name}_bucket{{le="+Inf"}} {cumulative[-1]}')
        lines.append(f"{name}_sum {self.sum}")
        lines.append(f"{name}_count {cumulative[-1]}")
        return lines


def register_provider(providers: list, name: str, fn: Callable[[], dict]) -> str:
    """Replace-or-append a ``(name, fn)`` snapshot source — the one
    registry semantics shared by the hub and the server (latest wins on a
    name collision: the restart-the-service-in-one-process case)."""
    for i, (existing, _) in enumerate(providers):
        if existing == name:
            providers[i] = (name, fn)
            return name
    providers.append((name, fn))
    return name

_NAME_OK_RE = re.compile(r"[^a-zA-Z0-9_]")


def _metric_name(*parts: str) -> str:
    name = "_".join(p for p in parts if p)
    name = _NAME_OK_RE.sub("_", name)
    if not name or not (name[0].isalpha() or name[0] == "_"):
        name = "_" + name
    return name


def _flatten(values: dict, prefix: str = "") -> list:
    flat = []
    for key, value in values.items():
        name = f"{prefix}_{key}" if prefix else str(key)
        if isinstance(value, LatencyHistogram):
            flat.append((name, value))
        elif isinstance(value, dict):
            flat.extend(_flatten(value, name))
        elif isinstance(value, bool):
            flat.append((name, int(value)))
        elif isinstance(value, (int, float)) and value == value:  # drop NaN
            flat.append((name, value))
        # None / strings / lists have no Prometheus sample type: skipped
    return flat


def render_prometheus(sections: list) -> str:
    """``[(provider, values_dict), ...]`` → text exposition.  Scalar values
    render as counter/gauge samples; :class:`LatencyHistogram` values
    render as native histogram series.  Duplicate metric names (two
    providers under one name) keep the first sample — duplicates are
    invalid exposition."""
    lines: list[str] = []
    seen: set[str] = set()
    for provider, values in sections:
        for key, value in _flatten(values):
            name = _metric_name("atpu", provider, key)
            if name in seen:
                continue
            seen.add(name)
            if isinstance(value, LatencyHistogram):
                lines.extend(value.render_lines(name))
                continue
            kind = "counter" if name.endswith("_total") else "gauge"
            lines.append(f"# TYPE {name} {kind}")
            lines.append(f"{name} {value}")
    return "\n".join(lines) + "\n"


def telemetry_metrics(telemetry) -> dict:
    """The hub's scrape snapshot: step counters, replay phase timings,
    recompile/fault counters, collective bytes, and the latest sampled
    device-time split."""
    out = {
        "steps_total": telemetry.steps_total,
        "recompiles_total": telemetry.recompiles_total,
        "resilience_events_total": len(telemetry.resilience_events),
        "fleet_events_total": len(telemetry.fleet_events),
        "eager_dataloader_wait_ms_total": round(
            telemetry.eager_dataloader_wait_ms, 3
        ),
        # native histogram: replay step latency distribution (_bucket series)
        "step_latency_ms": telemetry.step_hist,
    }
    for key, value in telemetry.timeline.summary().items():
        if isinstance(value, (int, float)) and (
            key.startswith("replay_") or key.startswith("build_")
        ):
            out[key] = value
    if telemetry.collective_records:
        last = telemetry.collective_records[-1]
        for key in (
            "dp_collective_bytes",
            "dp_collective_bytes_uncompressed",
            "compression_ratio",
        ):
            value = last.stats.get(key)
            if isinstance(value, (int, float)):
                out[key] = value
    if telemetry.device_records:
        dev = telemetry.device_records[-1]
        out["device_window_ms"] = dev.window_ms
        out["device_busy_ms"] = dev.busy_ms
        out["device_idle_ms"] = dev.idle_ms
        out["device_compute_ms"] = dev.compute_ms
        out["device_collective_ms"] = dev.collective_ms
        out["device_transfer_ms"] = dev.transfer_ms
        out["device_collective_share"] = dev.collective_share
        out["device_samples_total"] = len(telemetry.device_records)
        if dev.mfu is not None:
            out["device_mfu"] = dev.mfu
    # flight-recorder self-health (docs/telemetry.md §flight recorder):
    # ring depth, drop count and staleness — an alert on
    # atpu_telemetry_flightrec_last_event_age_seconds is the cheapest
    # external hang detector there is.  _flatten drops the None age of a
    # ring that has never recorded.
    rec = getattr(telemetry, "flightrec", None)
    if rec is not None:
        out["flightrec"] = rec.health()
    return out


class _Handler(BaseHTTPRequestHandler):
    server_version = "atpu-metrics/1.0"

    def do_GET(self):  # noqa: N802 (http.server API)
        if self.path.split("?", 1)[0] in ("/metrics", "/metrics/"):
            body = self.server.render_fn().encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path.split("?", 1)[0] in ("/healthz", "/healthz/"):
            # readiness + liveness probe (docs/serving.md §fault
            # tolerance): 200 while every registered health source reports
            # ready (for the decode service: programs warmed ∧ pool
            # allocated ∧ not draining), 503 otherwise — the orchestrator's
            # drain/route-away signal
            import json as _json

            status, payload = self.server.health_fn()
            body = (_json.dumps(payload) + "\n").encode("utf-8")
            self.send_response(status)
            self.send_header("Content-Type", "application/json; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path in ("", "/"):
            body = b"accelerate_tpu metrics endpoint; scrape /metrics\n"
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self.send_error(404)

    def log_message(self, *args):  # scrapes must not spam the job's stderr
        pass


class MetricsServer:
    """One daemon HTTP thread serving live Prometheus text on ``/metrics``.

    ``telemetry`` (optional) contributes the hub snapshot plus every
    provider registered on the hub (``register_metrics_provider`` — the
    decode service self-registers there); ``add_provider``/``add_service``
    attach additional sources directly.  ``port=0`` binds an ephemeral port
    (read it back from ``.port`` — tests and multi-job hosts)."""

    def __init__(self, telemetry=None, port: int = 0, host: str = "127.0.0.1"):
        self.telemetry = telemetry
        self._requested = (host, int(port))
        self._providers: list = []  # (name, callable) -> dict
        self._health_providers: list = []  # (name, callable) -> dict
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- providers -----------------------------------------------------------
    def add_provider(self, name: str, fn: Callable[[], dict]) -> str:
        """Register a snapshot callable (replace-or-append, latest wins)."""
        return register_provider(self._providers, name, fn)

    def add_service(self, service) -> str:
        """Scrape a :class:`~..serving.DecodeService` (its ``metrics()``
        snapshot) under the ``serving`` namespace; its ``health()``
        snapshot joins ``/healthz`` too when the service exposes one."""
        if hasattr(service, "health"):
            self.add_health_provider("serving", service.health)
        return self.add_provider("serving", service.metrics)

    def add_health_provider(self, name: str, fn: Callable[[], dict]) -> str:
        """Register a readiness source for ``/healthz`` (``fn() -> dict``
        with a ``"ready"`` bool; replace-or-append, latest wins)."""
        return register_provider(self._health_providers, name, fn)

    def _sections(self) -> list:
        sections: list = []
        if self.telemetry is not None:
            hub = self.telemetry
            sections.append(("telemetry", lambda: telemetry_metrics(hub)))
            sections.extend(getattr(hub, "_metrics_providers", []))
        sections.extend(self._providers)
        return sections

    def render(self) -> str:
        rendered = []
        failures = []
        for name, fn in self._sections():
            try:
                values = fn()
                if isinstance(values, dict):
                    rendered.append((name, values))
                else:
                    failures.append((name, "provider returned non-dict"))
            except Exception as exc:  # one bad provider must not kill a scrape
                failures.append((name, f"{type(exc).__name__}: {exc}"))
        body = render_prometheus(rendered)
        for name, err in failures:
            body += f"# provider {name} failed: {err}\n"
        return body

    def health(self) -> tuple:
        """``/healthz`` body: ``(status_code, payload)``.  Liveness is the
        response itself (the thread answered); readiness is the AND over
        every registered health source's ``"ready"``.  A raising provider
        reads as not-ready (fail-closed: an orchestrator must not route
        traffic at a replica whose own health check is broken); an empty
        snapshot (a dropped weakref'd service) is skipped."""
        sources: list = []
        if self.telemetry is not None:
            sources.extend(getattr(self.telemetry, "_health_providers", []))
        sources.extend(self._health_providers)
        payload: dict = {"live": True, "ready": True, "services": {}}
        for name, fn in sources:
            try:
                snapshot = fn()
            except Exception as exc:
                snapshot = {"ready": False, "error": f"{type(exc).__name__}: {exc}"}
            if not snapshot:
                continue
            payload["services"][name] = snapshot
            payload["ready"] = payload["ready"] and bool(snapshot.get("ready", True))
        return (200 if payload["ready"] else 503), payload

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "MetricsServer":
        if self._httpd is not None:
            return self
        httpd = ThreadingHTTPServer(self._requested, _Handler)
        httpd.daemon_threads = True
        httpd.render_fn = self.render
        httpd.health_fn = self.health
        self._httpd = httpd
        self._thread = threading.Thread(
            target=httpd.serve_forever, name="atpu-metrics", daemon=True
        )
        self._thread.start()
        logger.info("metrics endpoint serving on %s", self.url)
        return self

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd is not None else None

    @property
    def url(self) -> Optional[str]:
        if self._httpd is None:
            return None
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}/metrics"

    def close(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        httpd.shutdown()
        httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
