"""ZeRO-1 cross-replica sharded weight update (arXiv:2004.13336).

The contract under test: with a dp mesh axis and no fsdp owner, fp32
masters + optax moments live dp-sharded (NamedSharding over the largest
divisible axis), the captured step runs reduce-scatter → shard-local
update → all-gather inside ONE XLA program, and nothing else changes —
losses match the replicated update to float tolerance, per-replica
optimizer-state bytes drop ~1/dp, and no recompiles happen across replays.

Runs on any virtual CPU mesh size: the default tier-1 suite forces 8
devices (tests/conftest.py) and `make multichip` re-runs this file at 4
(XLA_FLAGS=--xla_force_host_platform_device_count=4), so both dp extents
exercise the same assertions.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator, DataParallelPlugin
from accelerate_tpu.data_loader import batch_to_global_array
from accelerate_tpu.nn import F
from accelerate_tpu.utils.memory import opt_state_bytes_per_replica

DIM = 64  # divides both multichip extents (4 and 8) exactly
ODD = 6  # divides neither: the per-param replicated fallback path


@pytest.fixture(autouse=True)
def _fresh():
    Accelerator._reset_state()
    nn.manual_seed(0)
    yield
    Accelerator._reset_state()


def _build(zero1, precision="bf16", dim=DIM):
    Accelerator._reset_state()
    nn.manual_seed(0)
    acc = Accelerator(
        mixed_precision=precision, dp_plugin=DataParallelPlugin(zero1=zero1)
    )
    model = nn.Sequential(nn.Linear(dim, dim), nn.ReLU(), nn.Linear(dim, dim))
    opt = optim.AdamW(model.parameters(), lr=1e-3)
    model, opt = acc.prepare(model, opt)

    def step_fn(x, y):
        opt.zero_grad()
        pred = model(x)
        loss = F.mse_loss(pred, y)
        acc.backward(loss)
        opt.step()
        return loss

    return acc, model, opt, acc.compile_step(step_fn)


def _batches(acc, n=2, dim=DIM):
    rng = np.random.default_rng(0)

    def mk():
        return batch_to_global_array(
            jnp.asarray(rng.normal(size=(8, dim)).astype(np.float32)), mesh=acc.mesh
        )

    return [(mk(), mk()) for _ in range(n)]


def _losses(step, batches, steps):
    return [float(step(*batches[i % len(batches)])) for i in range(steps)]


def test_zero1_defaults_on_for_dp_and_shards_state():
    acc, model, opt, _ = _build(zero1=None)
    dp = acc.mesh.shape["dp"]
    assert dp > 1, "suite requires a multi-device virtual mesh"
    assert acc.state.zero1_enabled
    inner = opt.optimizer
    for p, m in zip(inner.param_list, inner.master_params):
        assert m is not None  # bf16 params ⇒ fp32 masters
        assert "dp" in str(m.sharding.spec), f"master not dp-sharded: {m.sharding.spec}"
        # params themselves stay on their own (replicated) layout
        assert p.data.sharding.spec == jax.sharding.PartitionSpec()


@pytest.mark.parametrize("precision", ["bf16", "no"])
def test_sharded_update_losses_match_replicated(precision):
    """Acceptance: sharded vs replicated update agree to 1e-6 over 10 steps
    (bitwise on this CPU mesh — the update math is elementwise-identical,
    just partitioned)."""
    acc_on, _, _, step_on = _build(zero1=True, precision=precision)
    on = _losses(step_on, _batches(acc_on), 10)

    acc_off, _, _, step_off = _build(zero1=False, precision=precision)
    off = _losses(step_off, _batches(acc_off), 10)

    diffs = [abs(a - b) for a, b in zip(on, off)]
    assert max(diffs) <= 1e-6, f"loss divergence {diffs}"


def test_opt_state_bytes_shrink_about_one_over_dp():
    acc, _, opt_on, step = _build(zero1=True)
    dp = acc.mesh.shape["dp"]
    _losses(step, _batches(acc), 2)  # bytes must hold AFTER captured steps
    sharded = opt_state_bytes_per_replica(opt_on)

    acc_off, _, opt_off, step_off = _build(zero1=False)
    _losses(step_off, _batches(acc_off), 2)
    repl = opt_state_bytes_per_replica(opt_off)

    assert sharded <= repl / dp + 4096, (
        f"opt state not ZeRO-1 sharded: {sharded}B/replica vs {repl}B "
        f"replicated (expected ~{repl // dp}B)"
    )
    if dp >= 4:
        assert sharded <= 0.35 * repl  # the ISSUE acceptance bound


def test_no_recompile_across_replays():
    acc, _, _, step = _build(zero1=True)
    batches = _batches(acc)
    _losses(step, batches, 10)
    assert len(step._cache) == 1, "captured-step cache grew across replays"
    (entry,) = step._cache.values()
    assert entry[0]._cache_size() == 1, (
        "inner jit re-traced: carried-state sharding drifted between replays"
    )


def test_indivisible_params_fall_back_to_replicated():
    acc, _, opt, step = _build(zero1=True, dim=ODD)
    assert ODD % acc.mesh.shape["dp"] != 0
    inner = opt.optimizer
    for m in inner.master_params:
        assert m.sharding.spec == jax.sharding.PartitionSpec()
    # and the step still runs + replays without recompiling
    _losses(step, _batches(acc, dim=ODD), 3)
    (entry,) = step._cache.values()
    assert entry[0]._cache_size() == 1


def test_sharded_checkpoint_records_specs_and_reshards(tmp_path):
    """Save under ZeRO-1 (dp-sharded state) → restore into a replicated-
    update run: the loader reshards by global bounds and training continues
    on the exact numbers; index.json carries the save-time PartitionSpecs."""
    import json

    acc, model, opt, step = _build(zero1=True)
    batches = _batches(acc)
    _losses(step, batches, 3)
    ckpt = str(tmp_path / "ckpt")
    acc.save_state(ckpt, sharded_state=True)

    with open(os.path.join(ckpt, "optimizer.index.json")) as f:
        index = json.load(f)
    specs = [e.get("spec") for e in index["tensors"].values()]
    assert any(s and "dp" in str(s) for s in specs), (
        f"optimizer index.json records no dp-sharded spec: {specs}"
    )
    import pickle

    with open(os.path.join(ckpt, "optimizer.meta.bin"), "rb") as f:
        meta = pickle.load(f)
    assert any("dp" in str(v) for v in meta["partition_specs"].values())

    # continue the reference run, and a restored zero1=off run, in lockstep
    ref = _losses(step, batches, 2)
    acc2, model2, opt2, step2 = _build(zero1=False)
    acc2.load_state(ckpt)
    restored = _losses(step2, _batches(acc2), 2)
    diffs = [abs(a - b) for a, b in zip(ref, restored)]
    assert max(diffs) <= 1e-6, f"restored run diverged: {diffs}"
    # the replicated run's state really is replicated after the reshard
    for leaf in jax.tree_util.tree_leaves(opt2.optimizer.opt_state):
        if isinstance(leaf, jax.Array) and leaf.ndim >= 1:
            assert leaf.sharding.spec == jax.sharding.PartitionSpec()


def test_pickle_checkpoint_restores_onto_zero1_layout(tmp_path):
    """The full-array (pickle) optimizer checkpoint must come back COMMITTED
    to this run's dp-sharded layout — an uncommitted host array would flip
    the next captured call's placement into a silent re-trace."""
    acc, model, opt, step = _build(zero1=True)
    batches = _batches(acc)
    _losses(step, batches, 3)
    ckpt = str(tmp_path / "ckpt")
    acc.save_state(ckpt, sharded_state=False)
    ref = _losses(step, batches, 2)

    acc2, model2, opt2, step2 = _build(zero1=True)
    acc2.load_state(ckpt)
    for m in opt2.optimizer.master_params:
        assert "dp" in str(m.sharding.spec), f"master lost dp layout: {m.sharding.spec}"
    restored = _losses(step2, _batches(acc2), 2)
    diffs = [abs(a - b) for a, b in zip(ref, restored)]
    assert max(diffs) <= 1e-6, f"restored run diverged: {diffs}"
    (entry,) = step2._cache.values()
    assert entry[0]._cache_size() == 1, "restore forced a re-trace"


def test_explicit_opt_out_keeps_replicated_state():
    _, _, opt, _ = _build(zero1=False)
    for m in opt.optimizer.master_params:
        assert m.sharding.spec == jax.sharding.PartitionSpec()
