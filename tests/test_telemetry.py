"""Telemetry subsystem (docs/telemetry.md): phases recorded per step on CPU,
recompile forensics attribute the right cause, the disabled path touches
nothing, the tracker bridge writes valid JSONL, and the telemetry AOT
capture path is loss-bitwise-identical to the plain jit path."""

import json
import os
import sys

import jax.numpy as jnp
import numpy as np
import pytest

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator, TelemetryKwargs
from accelerate_tpu.data_loader import batch_to_global_array
from accelerate_tpu.models import GPTConfig, GPTLMHeadModel
from accelerate_tpu.telemetry import (
    StepRecord,
    StepTimeline,
    Telemetry,
    _set_active,
    current_telemetry,
    diff_keys,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _reset_active_telemetry():
    yield
    _set_active(None)


def _tiny_cfg():
    return GPTConfig(vocab_size=256, n_positions=64, n_embd=32, n_layer=1, n_head=2)


def _make_step(enabled=True, acc_kwargs=None, **tel_kwargs):
    nn.manual_seed(0)
    acc = Accelerator(
        kwargs_handlers=[TelemetryKwargs(enabled=enabled, **tel_kwargs)],
        **(acc_kwargs or {}),
    )
    model = GPTLMHeadModel(_tiny_cfg())
    opt = optim.AdamW(model.parameters(), lr=1e-3)
    model, opt = acc.prepare(model, opt)

    def step_fn(ids):
        opt.zero_grad()
        out = model(ids, labels=ids)
        acc.backward(out["loss"])
        opt.step()
        return out["loss"]

    return acc, model, acc.compile_step(step_fn)


def _batch(acc, seq=32, seed=0):
    ids = np.random.default_rng(seed).integers(0, 256, (8, seq), dtype=np.int32)
    return batch_to_global_array(jnp.asarray(ids), mesh=acc.mesh)


# ---------------------------------------------------------------------------
# pillar 1: step-phase timing
# ---------------------------------------------------------------------------

def test_phases_recorded_per_step_and_cover_wall_clock():
    acc, _, step = _make_step()
    batch = _batch(acc)
    for _ in range(3):
        loss = step(batch)
    assert np.isfinite(float(loss))
    records = acc.telemetry.timeline.records()
    assert len(records) == 3
    build, *replays = records
    assert build.built and not any(r.built for r in replays)
    assert build.trace_ms > 0 and build.compile_ms > 0
    for rec in records:
        assert rec.total_ms > 0
        for phase in ("assembly_ms", "trace_ms", "compile_ms", "dispatch_ms",
                      "dataloader_wait_ms"):
            assert getattr(rec, phase) >= 0.0
        # the phases partition __call__: their sum accounts for the wall
        # clock (acceptance: within 20%)
        assert rec.phase_sum_ms <= rec.total_ms * 1.001
        assert rec.phase_sum_ms >= rec.total_ms * 0.8, (
            rec.phase_sum_ms,
            rec.total_ms,
        )
    # replays share the build's variant key and do not re-trace
    assert {r.key for r in records} == {build.key}
    assert len(step._cache) == 1


def test_dataloader_wait_phase_flows_from_prepared_loader():
    acc, _, step = _make_step()

    data = np.random.default_rng(0).integers(0, 256, (128, 32)).astype(np.int32)

    class Dataset:
        def __len__(self):
            return len(data)

        def __getitem__(self, i):
            return data[i]

    from accelerate_tpu.data_loader import prepare_data_loader

    loader = prepare_data_loader(Dataset(), batch_size=8, mesh=acc.mesh)
    waits = []
    for batch in loader:
        step(batch)
        waits.append(acc.telemetry.timeline.last().dataloader_wait_ms)
    assert len(waits) == 2
    assert all(w > 0 for w in waits), waits


def test_prepared_loader_keeps_pinned_hub_after_later_accelerator():
    acc, _, step = _make_step()

    data = np.random.default_rng(0).integers(0, 256, (128, 32)).astype(np.int32)

    class Dataset:
        def __len__(self):
            return len(data)

        def __getitem__(self, i):
            return data[i]

    from accelerate_tpu.data_loader import prepare_data_loader

    loader = acc.prepare_data_loader(
        prepare_data_loader(Dataset(), batch_size=8, mesh=acc.mesh)
    )
    assert loader._telemetry is acc.telemetry
    # a later telemetry-off Accelerator clears the module-global slot …
    acc2 = Accelerator()
    assert current_telemetry() is None
    # … but the prepared loader's wait accounting survives via its pin
    for batch in loader:
        step(batch)
    assert acc.telemetry.timeline.last().dataloader_wait_ms > 0


def test_eager_eval_epoch_wait_is_not_dumped_on_next_step():
    """Batch-scoped wait attribution (ISSUE 8 satellite): an eager eval
    epoch consumes its batches with no captured step, so its accumulated
    loader wait must be settled at epoch end into the hub's eager counter —
    pre-fix it stayed pending and the NEXT captured step's record absorbed
    the whole eval epoch's wait as its own."""
    acc, _, step = _make_step()

    data = np.random.default_rng(0).integers(0, 256, (128, 32)).astype(np.int32)

    class Dataset:
        def __len__(self):
            return len(data)

        def __getitem__(self, i):
            return data[i]

    from accelerate_tpu.data_loader import prepare_data_loader

    loader = prepare_data_loader(Dataset(), batch_size=8, mesh=acc.mesh)
    for _ in loader:  # eager eval epoch: no captured step pops any wait
        pass
    # the regression pin: nothing pending for the next step, the eval
    # epoch's wait is accounted where it belongs
    assert acc.telemetry._dataloader_wait_ms == 0.0
    assert acc.telemetry.eager_dataloader_wait_ms > 0
    assert acc.telemetry.summary()["eager_dataloader_wait_ms"] > 0
    # a captured step after the eval phase still gets its own batch's wait
    for batch in loader:
        step(batch)
        break
    assert acc.telemetry.timeline.last().dataloader_wait_ms > 0


def test_program_labels_stay_unique_across_rebuilds():
    acc, _, step = _make_step()
    step(_batch(acc, seq=32))
    step(_batch(acc, seq=48))
    # evict a variant and replay it: the rebuild (the layout-drift retry
    # shape — pop + rebuild) must get a fresh label, not reuse an old one
    step._cache.clear()
    step(_batch(acc, seq=32))
    labels = [p.label for p in acc.telemetry.program_records]
    assert labels == ["capture:0", "capture:1", "capture:2"]


def test_telemetry_losses_bitwise_equal_to_disabled_path():
    def run(enabled):
        Accelerator._reset_state()
        _set_active(None)
        acc, _, step = _make_step(enabled=enabled)
        batch = _batch(acc)
        return [float(step(batch)) for _ in range(3)]

    assert run(True) == run(False)


# ---------------------------------------------------------------------------
# pillar 2: recompile forensics
# ---------------------------------------------------------------------------

def test_shape_change_emits_recompile_event_naming_the_argument():
    acc, _, step = _make_step()
    step(_batch(acc, seq=32))
    assert len(acc.telemetry.recompile_events) == 0  # first build: expected
    step(_batch(acc, seq=48))
    events = list(acc.telemetry.recompile_events)
    assert len(events) == 1
    assert "arg[0] shape changed" in events[0].cause
    assert "(8, 32)" in events[0].cause and "(8, 48)" in events[0].cause
    assert events[0].kind == "key"
    assert acc.telemetry.recompiles_total == 1


def test_train_eval_flip_emits_recompile_event():
    acc, model, step = _make_step()
    batch = _batch(acc)
    step(batch)
    model.eval()
    step(batch)
    events = list(acc.telemetry.recompile_events)
    assert len(events) == 1
    assert "training changed" in events[0].cause


def test_accumulate_refile_keeps_forensics_baseline():
    """First-call accumulate re-files the cache entry under the traced
    sync_gradients flag; forensics must diff later misses against the
    re-filed key, or the flagship accumulation-boundary recompile loses
    its cause attribution."""
    from accelerate_tpu.nn import F, Tensor

    nn.manual_seed(0)
    acc = Accelerator(
        gradient_accumulation_steps=2,
        kwargs_handlers=[TelemetryKwargs(enabled=True)],
    )
    model = nn.Linear(4, 1)
    opt = optim.SGD(model.parameters(), lr=0.1)
    model, opt = acc.prepare(model, opt)

    def step_fn(xb, yb):
        with acc.accumulate(model):
            pred = model(Tensor(xb)).squeeze(-1)
            loss = F.mse_loss(pred, Tensor(yb))
            acc.backward(loss)
            opt.step()
            opt.zero_grad()
        return loss

    step = acc.compile_step(step_fn)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 4)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(2,)).astype(np.float32))
    step(x, y)  # builds + re-files under the traced sync flag
    step(x, y)  # sync flips at the accumulation boundary → second variant
    events = list(acc.telemetry.recompile_events)
    assert len(events) == 1
    assert "sync_gradients flipped" in events[0].cause, events[0].cause
    # the build's record key matches its variant's replays, not the
    # popped pre-advance key
    records = acc.telemetry.timeline.records()
    step(x, y)  # replay of variant 1
    assert acc.telemetry.timeline.last().key == records[0].key
    # program records follow the re-file too: each variant's HBM/FLOP
    # stats join to its own key, with no cross-variant collision
    prog_keys = [p.key for p in acc.telemetry.program_records]
    assert prog_keys == [records[0].key, records[1].key]
    assert len(set(prog_keys)) == 2


def test_repeated_layout_drift_falls_back_to_plain_jit():
    """One layout drift rebuilds AOT (loud event, fresh executable); a
    second drift on the same variant means layouts alternate — the AOT
    path must yield to plain jit or it would trace+compile every step."""
    acc, _, step = _make_step()
    batch = _batch(acc)
    loss0 = float(step(batch))
    key = next(iter(step._cache))

    class _Rejecting:
        def __call__(self, *a, **k):
            raise ValueError("simulated sharding/layout mismatch")

    def _inject():
        entry = step._cache[key]
        step._cache[key] = (_Rejecting(), *entry[1:])

    _inject()  # drift 1 → loud event, rebuilt still AOT (no .lower on Compiled)
    step(batch)
    assert acc.telemetry.recompile_events[-1].kind == "layout"
    assert not hasattr(step._cache[key][0], "lower")

    _inject()  # drift 2 on the same key → plain-jit fallback (jitted has .lower)
    loss2 = float(step(batch))
    assert "falling back to plain jit" in acc.telemetry.recompile_events[-1].cause
    assert hasattr(step._cache[key][0], "lower")
    assert np.isfinite(loss2) and loss2 != loss0  # training kept moving

    events_before = len(acc.telemetry.recompile_events)
    step(batch)  # jit dispatch absorbs further calls: no new events, no rebuild
    assert len(acc.telemetry.recompile_events) == events_before
    rec = acc.telemetry.timeline.last()
    assert not rec.built and rec.trace_ms == 0.0 and rec.compile_ms == 0.0


def test_diff_keys_names_every_moved_component():
    prev = ("treeA", (((4, 32), "int32"),), True, (True,))
    new = ("treeA", (((4, 48), "int32"),), False, (False,))
    causes = diff_keys(prev, new)
    text = "\n".join(causes)
    assert "arg[0] shape changed" in text
    assert "sync_gradients flipped" in text
    assert "model[0].training changed" in text


# ---------------------------------------------------------------------------
# pillar 3: resource accounting
# ---------------------------------------------------------------------------

def test_capture_records_program_stats_and_resource_sample():
    acc, _, step = _make_step()
    step(_batch(acc))
    programs = list(acc.telemetry.program_records)
    assert len(programs) == 1
    # CPU backend exposes both analyses; at minimum the FLOP count must land
    assert programs[0].stats.get("flops", 0) > 0
    samples = list(acc.telemetry.resource_samples)
    assert len(samples) == 1
    assert samples[0].total_bytes > 0
    # on-demand sampling works outside capture too
    sample = acc.telemetry.sample_resources("manual")
    assert sample.total_bytes > 0 and sample.tag == "manual"


# ---------------------------------------------------------------------------
# telemetry off: identical path, no allocations
# ---------------------------------------------------------------------------

def test_disabled_leaves_ring_buffer_and_counters_untouched(monkeypatch):
    monkeypatch.delenv("ACCELERATE_TELEMETRY", raising=False)
    nn.manual_seed(0)
    acc = Accelerator()  # no handler, env unset → default off
    model = GPTLMHeadModel(_tiny_cfg())
    opt = optim.AdamW(model.parameters(), lr=1e-3)
    model, opt = acc.prepare(model, opt)

    def step_fn(ids):
        opt.zero_grad()
        out = model(ids, labels=ids)
        acc.backward(out["loss"])
        opt.step()
        return out["loss"]

    step = acc.compile_step(step_fn)
    assert step._telemetry is None
    assert current_telemetry() is None
    slots_before = list(acc.telemetry.timeline._slots)
    batch = _batch(acc)
    for _ in range(3):
        step(batch)
    assert len(acc.telemetry.timeline) == 0
    assert acc.telemetry.timeline._slots == slots_before  # ring untouched
    assert acc.telemetry.steps_total == 0
    assert acc.telemetry.recompiles_total == 0
    assert len(acc.telemetry._export_queue) == 0
    # the pre-telemetry host-assembly counters still tick (replays only)
    assert step.host_assembly_calls == 2


def test_ring_buffer_capacity_bounds_retention():
    timeline = StepTimeline(capacity=4)
    for i in range(10):
        timeline.append(
            StepRecord(
                step=i, key="k", built=False, total_ms=1.0, assembly_ms=0.2,
                trace_ms=0.0, compile_ms=0.0, dispatch_ms=0.8,
                dataloader_wait_ms=0.0,
            )
        )
    assert len(timeline) == 4
    assert timeline.total_appended == 10
    assert [r.step for r in timeline.records()] == [6, 7, 8, 9]
    assert timeline.last().step == 9


# ---------------------------------------------------------------------------
# pillar 4: export
# ---------------------------------------------------------------------------

def test_tracker_bridge_writes_valid_jsonl(tmp_path):
    acc, _, step = _make_step(
        acc_kwargs={"log_with": "jsonl", "project_dir": str(tmp_path)}
    )
    acc.init_trackers("run", config={"lr": 1e-3}, init_kwargs={})
    # the bridge was auto-inserted FIRST so end_training's in-order finish()
    # flushes it into delegates that are still open
    names = [t.name for t in acc.trackers]
    assert names == ["telemetry", "jsonl"]
    assert acc.get_tracker("telemetry").tracker is acc.telemetry

    step(_batch(acc, seq=32))
    step(_batch(acc, seq=48))  # recompile event
    acc.log({"loss": 1.0}, step=0)  # piggyback drain
    acc.end_training()

    path = os.path.join(str(tmp_path), "run", "metrics.jsonl")
    records = [json.loads(line) for line in open(path)]
    assert all(isinstance(r, dict) for r in records)
    keys = {k for r in records for k in r}
    assert "telemetry/step/total_ms" in keys
    assert "telemetry/recompile/cause" in keys
    assert any(k.startswith("telemetry/program/") for k in keys)
    # the drain is one-shot: nothing pending after flush
    assert len(acc.telemetry._export_queue) == 0


def test_write_jsonl_roundtrips_through_report_tool(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        from telemetry_report import load_records, render, validate
    finally:
        sys.path.pop(0)

    acc, _, step = _make_step()
    for _ in range(3):
        step(_batch(acc))
    path = str(tmp_path / "run.jsonl")
    acc.telemetry.write_jsonl(path)
    records = load_records(path)
    assert validate(records, min_steps=3) == []
    kinds = {r["kind"] for r in records}
    assert {"meta", "step", "program", "resources", "summary"} <= kinds
    report = render(records)
    assert "step-time breakdown" in report
    assert "steady state" in report  # no recompiles in this run


def test_export_queue_skipped_without_sink():
    """ROADMAP item: with no tracker bridge attached, per-step records skip
    the export queue (and its to_dict()) entirely — sink-less runs like
    bench's primary loop pay zero per-step export work.  The retained
    history (timeline, JSONL dump) is unaffected."""
    acc, _, step = _make_step()
    for _ in range(3):
        step(_batch(acc))
    assert len(acc.telemetry.timeline) == 3  # retained history intact
    assert len(acc.telemetry.program_records) == 1
    assert len(acc.telemetry._export_queue) == 0  # nothing enqueued
    # the JSONL dump feed reads the retained history, not the queue
    kinds = {r["kind"] for r in acc.telemetry.all_records()}
    assert {"step", "program"} <= kinds


def test_bridge_attach_backfills_pre_attach_records(tmp_path):
    """Records produced BEFORE init_trackers (no sink yet → not enqueued)
    still reach the delegates: the bridge backfills from retained history
    when it attaches."""
    acc, _, step = _make_step(
        acc_kwargs={"log_with": "jsonl", "project_dir": str(tmp_path)}
    )
    step(_batch(acc, seq=32))  # pre-attach: queue stays empty
    assert len(acc.telemetry._export_queue) == 0
    acc.init_trackers("run", config=None, init_kwargs={})
    assert len(acc.telemetry._export_queue) > 0  # backfilled on attach
    step(_batch(acc, seq=48))  # post-attach: normal enqueue (recompile too)
    acc.log({"loss": 1.0}, step=0)
    acc.end_training()
    path = os.path.join(str(tmp_path), "run", "metrics.jsonl")
    keys = {k for line in open(path) for k in json.loads(line)}
    # both the pre-attach step and the post-attach recompile were exported
    assert "telemetry/step/total_ms" in keys
    assert "telemetry/recompile/cause" in keys


# ---------------------------------------------------------------------------
# pillar 5: black-box flight recorder (always-on) + hang watchdog
# ---------------------------------------------------------------------------

import signal
import time

from accelerate_tpu.telemetry import flightrec
from accelerate_tpu.telemetry.flightrec import FlightRecorder
from accelerate_tpu.telemetry.watchdog import HangWatchdog, current_watchdog


def test_flightrec_ring_wraps_and_counts_drops():
    rec = FlightRecorder(capacity=16)
    for i in range(40):
        rec.record("tick", i=i)
    assert rec.events_total == 40
    assert rec.depth == 16
    assert rec.dropped == 24
    events = rec.snapshot()
    # oldest retained first; exactly the last `capacity` survive the wrap
    assert [e["seq"] for e in events] == list(range(24, 40))
    assert [e["i"] for e in events] == list(range(24, 40))
    health = rec.health()
    assert health["events_total"] == 40
    assert health["dropped_total"] == 24
    assert health["depth"] == 16
    assert health["last_event_age_seconds"] >= 0.0


def test_flightrec_collective_seq_and_dump_roundtrip(tmp_path):
    rec = FlightRecorder(capacity=64)
    assert rec.health()["last_event_age_seconds"] is None  # nothing yet
    assert [rec.note_collective("gather_object", world=2) for _ in range(3)] \
        == [1, 2, 3]
    rec.record("step_begin", step=0)
    path = rec.dump(str(tmp_path), reason="manual", extra={"note": "hi"})
    assert path is not None and os.path.basename(path).startswith("blackbox_rank")
    dump = json.load(open(path, encoding="utf-8"))
    assert dump["kind"] == "blackbox"
    assert dump["reason"] == "manual"
    assert dump["collective_seq"] == 3
    assert dump["note"] == "hi"
    collectives = [e for e in dump["events"] if e["kind"] == "collective"]
    assert [e["cseq"] for e in collectives] == [1, 2, 3]
    assert all(e["op"] == "gather_object" for e in collectives)
    # the wall anchor lets tools place monotonic stamps on absolute time
    assert dump["anchor_wall"] > 0 and dump["time_unix"] > 0
    # an explicit .json path is honored verbatim (no rank suffix appended)
    explicit = rec.dump(str(tmp_path / "sub" / "my.json"), reason="manual")
    assert explicit is not None and explicit.endswith("my.json")
    assert json.load(open(explicit))["events_total"] == rec.events_total


def test_flightrec_disabled_is_noop():
    rec = FlightRecorder(capacity=32, enabled=False)
    rec.record("tick")
    assert rec.note_collective("gather") == 0  # seq untouched
    assert rec.events_total == 0 and rec.depth == 0
    assert rec.snapshot() == []


def test_flightrec_shields_slot_schema_keys_from_payload_passthrough():
    # producers mirror whole payload dicts (``**payload``) into the ring;
    # payload keys named like the slot schema (fleet autopilot decisions
    # carry their own "kind") must neither raise nor clobber the schema
    rec = FlightRecorder(capacity=32)
    rec.record("fleet", **{"kind": "skew", "t": 9.9, "seq": 7, "event": "x"})
    got = rec.note_collective("gather", **{"op": "inner", "cseq": 99, "kind": "y"})
    assert got == 1
    ev, coll = rec.snapshot()
    assert ev["kind"] == "fleet" and ev["seq"] == 0
    assert (ev["field_kind"], ev["field_t"], ev["field_seq"]) == ("skew", 9.9, 7)
    assert coll["kind"] == "collective" and coll["op"] == "gather"
    assert coll["cseq"] == 1
    assert (coll["field_op"], coll["field_cseq"]) == ("inner", 99)


def test_captured_step_records_flight_events_without_telemetry(monkeypatch):
    """The recorder is the default-off convention's one exception: with
    telemetry fully off, captured-step begin/end still lands in the ring
    (with a locally-maintained step index)."""
    fresh = FlightRecorder(capacity=64)
    monkeypatch.setattr(flightrec, "_RECORDER", fresh)
    nn.manual_seed(0)
    acc = Accelerator()  # telemetry off
    model = GPTLMHeadModel(_tiny_cfg())
    opt = optim.AdamW(model.parameters(), lr=1e-3)
    model, opt = acc.prepare(model, opt)

    def step_fn(ids):
        opt.zero_grad()
        out = model(ids, labels=ids)
        acc.backward(out["loss"])
        opt.step()
        return out["loss"]

    step = acc.compile_step(step_fn)
    assert step._telemetry is None
    batch = _batch(acc)
    for _ in range(3):
        step(batch)
    kinds = [(e["kind"], e.get("step")) for e in fresh.snapshot()
             if e["kind"] in ("step_begin", "step_end")]
    assert kinds == [
        ("step_begin", 0), ("step_end", 0),
        ("step_begin", 1), ("step_end", 1),
        ("step_begin", 2), ("step_end", 2),
    ]


def test_captured_step_skips_ring_when_recorder_disabled(monkeypatch):
    """The bench A/B "off" arm: a recorder disabled BEFORE compile_step is
    never consulted again on the hot path (pinned None at construction)."""
    fresh = FlightRecorder(capacity=64, enabled=False)
    monkeypatch.setattr(flightrec, "_RECORDER", fresh)
    nn.manual_seed(0)
    acc = Accelerator()
    model = GPTLMHeadModel(_tiny_cfg())
    opt = optim.AdamW(model.parameters(), lr=1e-3)
    model, opt = acc.prepare(model, opt)

    def step_fn(ids):
        opt.zero_grad()
        out = model(ids, labels=ids)
        acc.backward(out["loss"])
        opt.step()
        return out["loss"]

    step = acc.compile_step(step_fn)
    assert step._flightrec is None
    step(_batch(acc))
    fresh.enabled = True  # re-enabling later does not reach the pinned step
    step(_batch(acc))
    assert all(e["kind"] != "step_begin" for e in fresh.snapshot())


def _test_watchdog(tmp_path, **kwargs):
    rec = FlightRecorder(capacity=128)
    wd = HangWatchdog(
        timeout_s=kwargs.pop("timeout_s", 0.3),
        dump_dir=str(tmp_path),
        recorder=rec,
        poll_s=0.05,
        install_signal_handlers=kwargs.pop("install_signal_handlers", False),
        dump_at_exit=kwargs.pop("dump_at_exit", False),
        **kwargs,
    )
    return rec, wd


def test_watchdog_fires_on_stall_and_dump_is_valid(tmp_path):
    rec, wd = _test_watchdog(tmp_path)
    wd.start()
    try:
        assert current_watchdog() is wd
        rec.note_collective("gather_object")
        with wd.guard("collective:gather_object #1"):
            # the "hung" section: wait on the dump path (set AFTER the poll
            # thread finishes writing), not the fired counter (set before)
            deadline = time.monotonic() + 10.0
            while wd.last_dump_path is None and time.monotonic() < deadline:
                time.sleep(0.05)
        assert wd.fired >= 1
        assert wd.last_dump_path is not None
        dump = json.load(open(wd.last_dump_path, encoding="utf-8"))
        assert dump["reason"] == "watchdog_stall"
        assert dump["stalled_label"] == "collective:gather_object #1"
        assert dump["stalled_s"] >= 0.3
        assert dump["collective_seq"] == 1
        assert dump["threads"]  # python stacks for every live thread
        assert any(e["kind"] == "watchdog_stall" for e in dump["events"])
        assert os.path.exists(f"{wd.last_dump_path}.stacks.txt")  # sidecar
    finally:
        wd.stop()
    assert current_watchdog() is None


def test_watchdog_fires_once_per_armed_section(tmp_path):
    rec, wd = _test_watchdog(tmp_path)
    wd.start()
    try:
        with wd.guard("slow"):
            deadline = time.monotonic() + 10.0
            while wd.fired == 0 and time.monotonic() < deadline:
                time.sleep(0.05)
            time.sleep(0.5)  # well past a second deadline: must NOT re-fire
        assert wd.fired == 1
        # a fresh armed section can fire again
        with wd.guard("slow again"):
            deadline = time.monotonic() + 10.0
            while wd.fired == 1 and time.monotonic() < deadline:
                time.sleep(0.05)
        assert wd.fired == 2
    finally:
        wd.stop()


def test_watchdog_nested_guard_keeps_outermost_deadline(tmp_path):
    _, wd = _test_watchdog(tmp_path, timeout_s=30.0)
    with wd.guard("outer"):
        with wd.guard("inner", timeout_s=0.01):
            label, deadline, _ = wd._armed
            assert label == "outer"  # inner arm did not displace the outer
            assert deadline > time.monotonic() + 10
        assert wd._armed is not None  # still armed until the outer exits
    assert wd._armed is None


def test_watchdog_stop_restores_signal_handlers_and_slot(tmp_path):
    prev_term = signal.getsignal(signal.SIGTERM)
    prev_abrt = signal.getsignal(signal.SIGABRT)
    rec, wd = _test_watchdog(tmp_path, install_signal_handlers=True)
    wd.start()
    assert signal.getsignal(signal.SIGTERM) == wd._handle_signal
    assert signal.getsignal(signal.SIGABRT) == wd._handle_signal
    wd.stop()
    assert signal.getsignal(signal.SIGTERM) is prev_term
    assert signal.getsignal(signal.SIGABRT) is prev_abrt
    assert current_watchdog() is None
    # manual dumps work without the thread (the preemption-guard hook path)
    path = wd.dump_now(reason="preemption_signal")
    assert json.load(open(path))["reason"] == "preemption_signal"


def test_watchdog_atexit_dump_yields_to_earlier_stall_dump(tmp_path):
    # the stalled rank usually EXITS after the stall (its collective raises
    # once a peer dies): the atexit dump must not overwrite the stall dump
    rec, wd = _test_watchdog(tmp_path, dump_at_exit=True)
    wd.start()
    try:
        assert wd._exit_hook is not None
        rec.note_collective("gather_object")
        with wd.guard("collective:gather_object #1"):
            deadline = time.monotonic() + 10.0
            while wd.last_dump_path is None and time.monotonic() < deadline:
                time.sleep(0.05)
        assert wd.last_dump_path is not None
        wd._exit_hook()  # what atexit would run at interpreter shutdown
        dump = json.load(open(wd.last_dump_path, encoding="utf-8"))
        assert dump["reason"] == "watchdog_stall"
    finally:
        wd.stop()

    # a rank that dies without ever stalling still leaves its half
    rec2, wd2 = _test_watchdog(tmp_path / "clean", dump_at_exit=True)
    wd2.start()
    try:
        rec2.note_collective("broadcast")
        wd2._exit_hook()
        assert wd2.last_dump_path is not None
        dump = json.load(open(wd2.last_dump_path, encoding="utf-8"))
        assert dump["reason"] == "atexit"
    finally:
        wd2.stop()


def test_watchdog_start_displaces_prior_instance(tmp_path):
    _, first = _test_watchdog(tmp_path)
    _, second = _test_watchdog(tmp_path)
    first.start()
    try:
        second.start()
        assert current_watchdog() is second
        assert first._thread is None  # stopped, not leaked
    finally:
        second.stop()
        first.stop()


def test_trace_export_writes_joinable_tracks(tmp_path, monkeypatch):
    from accelerate_tpu.telemetry.trace_export import validate_trace

    # fresh ring: the process-global recorder carries earlier tests' steps
    monkeypatch.setattr(flightrec, "_RECORDER", FlightRecorder(capacity=256))
    trace_path = str(tmp_path / "trace.json")
    acc, _, step = _make_step(profile_every_n=1, trace_export_path=trace_path)
    for _ in range(2):
        step(_batch(acc))
    acc.end_training()
    doc = json.load(open(trace_path, encoding="utf-8"))
    assert validate_trace(doc) == []
    by_tid = {}
    for ev in doc["traceEvents"]:
        step_arg = (ev.get("args") or {}).get("step")
        if step_arg is not None:
            by_tid.setdefault(ev["tid"], set()).add(step_arg)
    # host phases (1), device ops (2) and flight events (3) share the steps
    assert by_tid.get(1) == by_tid.get(2) == by_tid.get(3) == {0, 1}


# ---------------------------------------------------------------------------
# pillar 6 edge cases: fleet aggregation on degenerate per-rank shapes
# ---------------------------------------------------------------------------

from accelerate_tpu.telemetry.aggregate import fleet_skew, merge_rank_records


def _replay(total_ms, dispatch_ms=0.0, **extra):
    return {"kind": "step", "built": False, "total_ms": total_ms,
            "dispatch_ms": dispatch_ms, **extra}


def test_fleet_skew_single_rank_reports_without_comparing():
    out = fleet_skew([[_replay(10.0), _replay(12.0)]])
    assert out["kind"] == "fleet" and out["ranks"] == 1
    assert out["per_rank"][0]["replay_steps"] == 2
    assert out["per_rank"][0]["replay_total_ms_mean"] == 11.0
    # a one-rank fleet has no skew pair to compare
    assert "slowest_rank" not in out and "skew_ms" not in out


def test_fleet_skew_empty_and_ragged_inputs():
    assert fleet_skew([]) == {"kind": "fleet", "ranks": 0, "per_rank": []}
    # ragged: one rank with replays, one empty, one with only builds /
    # malformed records — none of it may crash or fabricate a comparison
    ragged = [
        [_replay(10.0)],
        [],
        [{"kind": "step", "built": True, "total_ms": 9.0},
         {"kind": "step", "built": False, "total_ms": None},
         {"kind": "recompile"}],
    ]
    out = fleet_skew(ragged)
    assert [s["replay_steps"] for s in out["per_rank"]] == [1, 0, 0]
    assert "slowest_rank" not in out  # only one usable rank


def test_fleet_skew_names_straggler_and_phase():
    per_rank = [
        [_replay(10.0, dispatch_ms=8.0)],
        [_replay(30.0, dispatch_ms=27.0)],
    ]
    out = fleet_skew(per_rank)
    assert out["slowest_rank"] == 1 and out["fastest_rank"] == 0
    assert out["skew_ms"] == 20.0 and out["skew_pct"] == 200.0
    assert out["straggler_phase"] == "dispatch_ms"
    assert out["straggler_phase_delta_ms"] == 19.0


def test_merge_rank_records_tags_without_mutating_and_dedups_periodic():
    rank0 = [_replay(10.0), {"kind": "fleet", "periodic": True, "ranks": 2}]
    rank1 = [_replay(11.0), {"kind": "fleet", "periodic": True, "ranks": 2}]
    originals = [dict(r) for r in rank0]
    merged = merge_rank_records([rank0, rank1])
    assert rank0 == originals  # inputs untouched
    # rank-tagged copies; rank 1's periodic fleet duplicate dropped
    fleet_periodic = [r for r in merged if r.get("periodic")]
    assert len(fleet_periodic) == 1 and fleet_periodic[0]["rank"] == 0
    steps = [(r["rank"], r["total_ms"]) for r in merged if r["kind"] == "step"]
    assert steps == [(0, 10.0), (1, 11.0)]
    # the appended summary record is the fleet_skew of the same inputs
    assert merged[-1]["kind"] == "fleet" and merged[-1]["ranks"] == 2


def test_merge_rank_records_empty_world():
    merged = merge_rank_records([])
    assert merged == [{"kind": "fleet", "ranks": 0, "per_rank": []}]
