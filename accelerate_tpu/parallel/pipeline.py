"""GPipe-style pipeline parallelism over the ``pp`` mesh axis.

Counterpart of the reference's PiPPy integration (inference.py:124
``prepare_pippy`` — trace, split at layer boundaries, ScheduleGPipe) rebuilt
as SPMD: stage parameters carry a leading layer axis sharded over ``pp``;
under ``shard_map`` each device runs its own contiguous span of layers and
activations hop to the next stage with ``lax.ppermute`` each tick.
``T = num_microbatches + num_stages - 1`` ticks fill and drain the pipeline;
everything is pure jnp with static trip counts, so JAX transposes it for
training as well as inference.

Composition: the shard_map covers the whole mesh, so the stage body may use
other named axes manually — ``seq_axis`` shards the activations' sequence
dimension over ``sp`` and the body can run ring attention with ``ppermute``
over that axis (models/gpt.py PipelinedGPTLMHeadModel does exactly this).

On TPU slices GSPMD tensor/data sharding usually beats PP (ICI is fast and
XLA overlaps collectives); PP earns its keep across slices (DCN) — which is
why it is a mesh axis here and composes with dp/fsdp/sp rather than being a
separate engine.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _apply_local_layers(stage_fn, local_params, h):
    """Apply this stage's span of layers (leading local axis) sequentially."""

    def body(carry, layer_params):
        return stage_fn(layer_params, carry), None

    out, _ = jax.lax.scan(body, h, local_params)
    return out


def _gpipe_local(
    stage_params,
    x,
    *,
    stage_fn,
    axis_name: str,
    num_microbatches: int,
    num_stages: int,
):
    """Per-device GPipe schedule under shard_map.

    stage_params: this stage's layer span (leading local-layer axis).
    x: (local_batch, ...) input — microbatched HERE, per device, so the split
    never reshards the dp/fsdp batch layout (a global (b,...)→(M, b/M, ...)
    reshape would interleave the sharded batch dim and force a full reshard).
    Returns (local_batch, ...) outputs (only the last stage's are real; psum
    over the pp ring replicates them).  ``num_stages`` is static so the tick
    loop has a static trip count (reverse-mode AD requires it).
    """
    stage_idx = jax.lax.axis_index(axis_name)
    M = num_microbatches
    if x.shape[0] % M != 0:
        raise ValueError(
            f"per-device batch {x.shape[0]} not divisible by num_microbatches {M}"
        )
    x_mb = x.reshape(M, x.shape[0] // M, *x.shape[1:])
    T = M + num_stages - 1

    # activation probe to get output shape/dtype of one stage
    sample_out = jax.eval_shape(
        lambda p, x: _apply_local_layers(stage_fn, p, x), stage_params, x_mb[0]
    )
    act0 = jnp.zeros(sample_out.shape, sample_out.dtype)
    outputs0 = jnp.zeros((M,) + sample_out.shape, sample_out.dtype)
    perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

    def tick(t, carry):
        incoming, outputs = carry
        mb_idx = t - stage_idx
        active = jnp.logical_and(mb_idx >= 0, mb_idx < M)
        # stage 0 reads its microbatch; later stages use the ring input
        x_idx = jnp.clip(mb_idx, 0, M - 1)
        my_input = jnp.where(
            stage_idx == 0,
            jax.lax.dynamic_index_in_dim(x_mb, x_idx, keepdims=False).astype(incoming.dtype)
            if x_mb.shape[1:] == incoming.shape
            else incoming,
            incoming,
        )
        out = _apply_local_layers(stage_fn, stage_params, my_input)
        out = jnp.where(active, out, jnp.zeros_like(out))
        # last stage records its finished microbatch
        outputs = jax.lax.cond(
            jnp.logical_and(active, stage_idx == num_stages - 1),
            lambda o: jax.lax.dynamic_update_index_in_dim(o, out, x_idx, 0),
            lambda o: o,
            outputs,
        )
        # all stages forward their activation to the next stage
        nxt = jax.lax.ppermute(out, axis_name, perm)
        return nxt, outputs

    _, outputs = jax.lax.fori_loop(0, T, tick, (act0, outputs0))
    # only the last stage holds real outputs; broadcast them around the ring
    # so the result is replicated over pp (callers slice/psum as needed)
    outputs = jax.lax.psum(
        jnp.where(stage_idx == num_stages - 1, outputs, jnp.zeros_like(outputs)),
        axis_name,
    )
    return outputs.reshape(x.shape[0], *outputs.shape[2:])


def gpipe(
    stage_fn: Callable,
    stacked_params,
    x: jax.Array,
    num_microbatches: int,
    mesh: Optional[Mesh] = None,
    axis_name: str = "pp",
    batch_axes: tuple = ("dp", "fsdp"),
    seq_axis: Optional[str] = None,
):
    """Run ``stage_fn(layer_params_i, x)`` as a pipeline over the ``pp`` axis.

    ``stacked_params``: pytree whose leaves have a leading ``num_layers`` axis
    (``num_layers`` divisible by the pp size; each stage scans its contiguous
    span).  ``x``: (batch, ...) global input — reshaped to
    (num_microbatches, batch/M, ...).  ``seq_axis``: optionally shard x's
    second data dimension (seq) over that mesh axis; the stage body may then
    use it manually (ring attention).

    Constraint (GPipe classic): every layer must map activations to the same
    shape/dtype.  Embedding/head layers live outside the pipelined trunk.
    """
    if mesh is None:
        from ..state import AcceleratorState

        if AcceleratorState._shared_state:
            mesh = AcceleratorState().mesh
    if mesh is None:
        # no Accelerator context: trivial one-device full-axes mesh so stage
        # bodies that use named axes (ring attention) still have axis context
        import numpy as np

        from ..utils.constants import ALL_MESH_AXES

        mesh = Mesh(
            np.asarray(jax.devices()[:1]).reshape((1,) * len(ALL_MESH_AXES)),
            ALL_MESH_AXES,
        )
    n_stages = mesh.shape.get(axis_name, 1)
    num_layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if num_layers % max(n_stages, 1) != 0:
        raise ValueError(
            f"num_layers {num_layers} not divisible by pp size {n_stages}"
        )
    if n_stages == 1 and seq_axis is None:
        # degenerate: sequential scan over layers on one device group (only
        # when the body needs no named-axis context)
        def body(h, p):
            return stage_fn(p, h), None

        out, _ = jax.lax.scan(body, x, stacked_params)
        return out

    from jax.experimental.shard_map import shard_map

    batch_spec = tuple(a for a in batch_axes if mesh.shape.get(a, 1) > 1) or None
    param_specs = jax.tree_util.tree_map(
        lambda _: P(axis_name), stacked_params
    )
    # microbatching happens per-device inside the body: the in_spec matches
    # the loader/constraint layout exactly, so entering the pipeline moves
    # zero bytes
    data_axes_spec = [batch_spec] + [None] * (x.ndim - 1)
    if seq_axis is not None and x.ndim >= 2:
        data_axes_spec[1] = seq_axis  # (batch, seq, ...)
    x_spec = P(*data_axes_spec)
    out_spec = x_spec

    fn = shard_map(
        functools.partial(
            _gpipe_local,
            stage_fn=stage_fn,
            axis_name=axis_name,
            num_microbatches=num_microbatches,
            num_stages=n_stages,
        ),
        mesh=mesh,
        in_specs=(param_specs, x_spec),
        out_specs=out_spec,
        check_rep=False,
    )
    return fn(stacked_params, x)
