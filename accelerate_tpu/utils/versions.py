"""Version comparison helpers (reference: /root/reference/src/accelerate/utils/versions.py)."""

from __future__ import annotations

import importlib.metadata
import operator

from packaging.version import Version, parse

STR_OPERATION_TO_FUNC = {
    ">": operator.gt,
    ">=": operator.ge,
    "==": operator.eq,
    "!=": operator.ne,
    "<=": operator.le,
    "<": operator.lt,
}


def compare_versions(library_or_version, operation: str, requirement_version: str) -> bool:
    """Compare an installed library version (or a Version) against a requirement."""
    if operation not in STR_OPERATION_TO_FUNC:
        raise ValueError(
            f"`operation` must be one of {list(STR_OPERATION_TO_FUNC)}, got {operation}"
        )
    if isinstance(library_or_version, str):
        library_or_version = parse(importlib.metadata.version(library_or_version))
    elif not isinstance(library_or_version, Version):
        library_or_version = parse(str(library_or_version))
    return STR_OPERATION_TO_FUNC[operation](
        library_or_version, parse(requirement_version)
    )


def is_jax_version(operation: str, version: str) -> bool:
    import jax

    return compare_versions(parse(jax.__version__), operation, version)
