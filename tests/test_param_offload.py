"""Training-time parameter offload (ZeRO-Infinity analog): params pinned to
host between steps, staged back by a traced forward hook.

Reference capability: torch FSDP ``CPUOffload(offload_params=True)`` and
DeepSpeed ``offload_param`` (reference utils/dataclasses.py:1082-1090).
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.data_loader import batch_to_global_array
from accelerate_tpu.models import GPTConfig, GPTLMHeadModel
from accelerate_tpu.utils.dataclasses import FullyShardedDataParallelPlugin


def _param_memory_kinds(model):
    return {
        n: getattr(p.data.sharding, "memory_kind", None)
        for n, p in model.named_parameters()
    }


def _train(cpu_offload, steps=4, capture=True, offload_optimizer=False, seed=0):
    Accelerator._reset_state()
    nn.manual_seed(seed)
    acc = Accelerator(
        parallelism_config=ParallelismConfig(fsdp_size=2),
        fsdp_plugin=FullyShardedDataParallelPlugin(
            cpu_offload=cpu_offload, offload_optimizer=offload_optimizer
        ),
        mixed_precision="no",
    )
    model = GPTLMHeadModel(GPTConfig.tiny())
    opt = optim.AdamW(model.parameters(), lr=1e-3)
    model, opt = acc.prepare(model, opt)

    def fn(ids):
        opt.zero_grad()
        out = model(ids, labels=ids)
        acc.backward(out["loss"])
        opt.step()
        return out["loss"]

    step = acc.compile_step(fn) if capture else fn
    ids = batch_to_global_array(
        jnp.asarray(np.random.default_rng(0).integers(0, 1024, (8, 16)), jnp.int32),
        mesh=acc.mesh,
    )
    losses = [float(step(ids)) for _ in range(steps)]
    return losses, model, opt, acc


def test_params_live_on_host_between_steps():
    losses, model, opt, acc = _train(cpu_offload=True)
    kinds = set(_param_memory_kinds(model).values())
    assert kinds == {"pinned_host"}, kinds
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_param_offload_numerics_match_unoffloaded():
    """Pinning + staging is pure data movement: identical math, identical
    losses to the plain fsdp run."""
    base, _, _, _ = _train(cpu_offload=False)
    off, _, _, _ = _train(cpu_offload=True)
    np.testing.assert_allclose(off, base, rtol=1e-5)


def test_param_offload_eager_path():
    losses, model, opt, acc = _train(cpu_offload=True, capture=False, steps=2)
    assert losses[-1] < losses[0] or np.isclose(losses[-1], losses[0], rtol=0.2)
    kinds = set(_param_memory_kinds(model).values())
    assert kinds == {"pinned_host"}, kinds


def test_full_zero_infinity_composition():
    """params + optimizer state + masters all host-resident between steps."""
    losses, model, opt, acc = _train(cpu_offload=True, offload_optimizer=True)
    assert losses[-1] < losses[0]
    assert set(_param_memory_kinds(model).values()) == {"pinned_host"}
    state_leaves = [
        leaf
        for leaf in jax.tree_util.tree_leaves(opt.optimizer.opt_state)
        if hasattr(leaf, "sharding") and getattr(leaf, "ndim", 0) >= 2
    ]
    assert state_leaves and all(
        leaf.sharding.memory_kind == "pinned_host" for leaf in state_leaves
    )


def test_ds_config_offload_param_maps_to_cpu_offload():
    from accelerate_tpu.utils.deepspeed_compat import from_deepspeed_config

    compat = from_deepspeed_config(
        {
            "zero_optimization": {
                "stage": 3,
                "offload_param": {"device": "cpu"},
                "offload_optimizer": {"device": "cpu"},
            },
            "train_micro_batch_size_per_gpu": 1,
        }
    )
    assert compat.fsdp_plugin.cpu_offload is True
    assert compat.fsdp_plugin.offload_optimizer is True


def test_estimate_memory_full_offload_row():
    from accelerate_tpu.commands.estimate import (
        estimate_training_usage_offloaded,
        estimate_training_usage_param_offloaded,
    )

    assert estimate_training_usage_param_offloaded(100.0) < (
        estimate_training_usage_offloaded(100.0)
    )
