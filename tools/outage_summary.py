#!/usr/bin/env python
"""outage_summary — aggregate tools/tpu_when_up.sh probe logs.

    python tools/outage_summary.py TPU_OUTAGE_r*.log
    python tools/outage_summary.py --json TPU_OUTAGE_r05.log

The watcher writes one line per probe: ``<epoch-seconds> <STATE> <detail>``
where STATE is ``TPU_UP`` (probe saw a healthy accelerator) or ``DOWN``
(probe failed; detail is the last stderr line).  The raw logs were
write-only; this renders what the round verdicts actually need: total
up/down time, availability, and the longest DOWN window per log.

Interval attribution: the span between consecutive probes belongs to the
*earlier* probe's state (the probe cadence is ~4-6 min, so this is the
finest resolution the data supports).  The span after the final probe is
unknown and excluded.  Exit 0 on success, 2 when no parseable probe lines
were found in any input.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def parse_log(path: str) -> list[tuple[int, bool]]:
    """[(epoch_seconds, is_up), ...] in file order; unparseable lines skipped."""
    probes: list[tuple[int, bool]] = []
    with open(path, encoding="utf-8", errors="replace") as f:
        for line in f:
            parts = line.split(None, 2)
            if len(parts) < 2 or not parts[0].isdigit():
                continue
            state = parts[1].upper()
            if state not in ("TPU_UP", "UP", "DOWN"):
                continue
            probes.append((int(parts[0]), state != "DOWN"))
    return probes


def summarize(probes: list[tuple[int, bool]]) -> dict:
    up_s = down_s = 0
    transitions = 0
    longest_down = {"seconds": 0, "start": None, "end": None}
    run_start: int | None = None  # start epoch of the current DOWN run
    for (t0, state0), (t1, state1) in zip(probes, probes[1:]):
        span = max(0, t1 - t0)
        if state0:
            up_s += span
        else:
            down_s += span
            if run_start is None:
                run_start = t0
        if state0 != state1:
            transitions += 1
        # a DOWN run ends when the *next* probe is up (or at the last probe)
        if run_start is not None and (state1 or (t1, state1) == probes[-1]):
            if t1 - run_start > longest_down["seconds"]:
                longest_down = {"seconds": t1 - run_start, "start": run_start, "end": t1}
            if state1:
                run_start = None
    observed = up_s + down_s
    return {
        "probes": len(probes),
        "probes_up": sum(1 for _, up in probes if up),
        "probes_down": sum(1 for _, up in probes if not up),
        "first_probe": probes[0][0] if probes else None,
        "last_probe": probes[-1][0] if probes else None,
        "observed_s": observed,
        "up_s": up_s,
        "down_s": down_s,
        "availability_pct": round(100.0 * up_s / observed, 1) if observed else None,
        "transitions": transitions,
        "longest_down_s": longest_down["seconds"],
        "longest_down_start": longest_down["start"],
        "longest_down_end": longest_down["end"],
    }


def _hms(seconds) -> str:
    if not seconds:
        return "0m"
    h, rem = divmod(int(seconds), 3600)
    m = rem // 60
    return f"{h}h{m:02d}m" if h else f"{m}m"


def _utc(epoch) -> str:
    if epoch is None:
        return "-"
    return time.strftime("%Y-%m-%d %H:%MZ", time.gmtime(epoch))


def render(path: str, s: dict) -> str:
    avail = f"{s['availability_pct']}%" if s["availability_pct"] is not None else "n/a"
    lines = [
        f"{path}: {s['probes']} probes "
        f"({_utc(s['first_probe'])} → {_utc(s['last_probe'])})",
        f"  up   {_hms(s['up_s']):>7}   down {_hms(s['down_s']):>7}   "
        f"availability {avail}   transitions {s['transitions']}",
        f"  longest DOWN window: {_hms(s['longest_down_s'])}"
        + (
            f" ({_utc(s['longest_down_start'])} → {_utc(s['longest_down_end'])})"
            if s["longest_down_start"] is not None
            else ""
        ),
    ]
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="outage_summary", description=__doc__)
    parser.add_argument("logs", nargs="+", help="TPU_OUTAGE_r*.log files")
    parser.add_argument("--json", action="store_true", help="machine output")
    args = parser.parse_args(argv)

    summaries = {}
    for path in args.logs:
        try:
            probes = parse_log(path)
        except OSError as e:
            print(f"outage_summary: cannot read {path}: {e}", file=sys.stderr)
            continue
        if not probes:
            print(f"outage_summary: no probe lines in {path}", file=sys.stderr)
            continue
        summaries[path] = summarize(probes)

    if not summaries:
        return 2
    if args.json:
        print(json.dumps(summaries, indent=2))
    else:
        for path, s in summaries.items():
            print(render(path, s))
    return 0


if __name__ == "__main__":
    sys.exit(main())
