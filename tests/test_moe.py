"""MixtureOfExperts: dense Switch dispatch vs a naive per-token reference,
gradient flow, capacity semantics, and ep-axis sharding (new capability —
the reference has no MoE layer, SURVEY.md §2.2 row EP)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import accelerate_tpu.nn as nn
from accelerate_tpu.nn.moe import MixtureOfExperts, _switch_moe_forward
from accelerate_tpu.state import AcceleratorState
from accelerate_tpu.utils.dataclasses import ParallelismConfig


def _naive_moe(x, rw, rb, wi, bi, wo, bo, capacity, top_k):
    """Per-token python loop with explicit capacity counters."""
    g, d = x.shape
    E = rw.shape[0]
    probs = np.asarray(jax.nn.softmax((x @ rw.T + rb).astype(jnp.float32), axis=-1))
    fill = [0] * E
    y = np.zeros((g, d), dtype=np.float32)
    remaining = probs.copy()
    # GShard convention: ALL first choices claim capacity before any second
    # choice does (round-major, then token order within the round)
    for _ in range(top_k):
        for t in range(g):
            e = int(remaining[t].argmax())
            gate = remaining[t][e]
            remaining[t][e] = 0.0
            if fill[e] >= capacity:
                continue
            fill[e] += 1
            hidden = np.asarray(
                jax.nn.gelu(x[t] @ np.asarray(wi[e]).T + np.asarray(bi[e]), approximate=True)
            )
            y[t] += gate * (hidden @ np.asarray(wo[e]).T + np.asarray(bo[e]))
    return y


@pytest.mark.parametrize("top_k", [1, 2])
def test_dense_dispatch_matches_naive(top_k):
    rng = np.random.default_rng(0)
    g, d, ff, E, cap = 16, 8, 16, 4, 6
    x = jnp.asarray(rng.normal(size=(g, d)), jnp.float32)
    rw = jnp.asarray(rng.normal(size=(E, d)) * 0.5, jnp.float32)
    rb = jnp.zeros((E,), jnp.float32)
    wi = jnp.asarray(rng.normal(size=(E, ff, d)) * 0.1, jnp.float32)
    bi = jnp.zeros((E, ff), jnp.float32)
    wo = jnp.asarray(rng.normal(size=(E, d, ff)) * 0.1, jnp.float32)
    bo = jnp.zeros((E, d), jnp.float32)

    y = _switch_moe_forward(x, rw, rb, wi, bi, wo, bo, capacity=cap, top_k=top_k)
    y_ref = _naive_moe(x, rw, rb, wi, bi, wo, bo, capacity=cap, top_k=top_k)
    np.testing.assert_allclose(np.asarray(y), y_ref, rtol=2e-4, atol=2e-5)


def test_capacity_drops_excess_tokens():
    """With capacity 1 and a router hard-wired to one expert, only the first
    token gets processed; the rest pass through with zero MoE output."""
    g, d, ff, E = 4, 4, 8, 2
    x = jnp.ones((g, d), jnp.float32)
    rw = jnp.zeros((E, d), jnp.float32)
    rb = jnp.asarray([10.0, -10.0])  # everyone wants expert 0
    wi = jnp.ones((E, ff, d), jnp.float32) * 0.1
    bi = jnp.zeros((E, ff), jnp.float32)
    wo = jnp.ones((E, d, ff), jnp.float32) * 0.1
    bo = jnp.zeros((E, d), jnp.float32)
    y = _switch_moe_forward(x, rw, rb, wi, bi, wo, bo, capacity=1, top_k=1)
    assert float(jnp.abs(y[0]).sum()) > 0.0
    np.testing.assert_allclose(np.asarray(y[1:]), 0.0, atol=1e-6)


def test_module_forward_backward_and_aux_loss():
    nn.manual_seed(0)
    moe = MixtureOfExperts(d_model=8, d_ff=16, num_experts=4, top_k=2)
    x = nn.Tensor(
        jnp.asarray(np.random.default_rng(1).normal(size=(2, 8, 8)), jnp.float32),
        requires_grad=True,
    )
    y = moe(x)
    assert y.shape == (2, 8, 8)
    aux = moe.last_aux_loss
    assert aux is not None and aux.ndim == 0
    # balanced-ish routing at init: aux close to 1 (perfectly balanced == 1)
    assert 0.5 < float(aux) < 4.0

    loss = (y * y).sum() + aux * 0.01
    nn.backward(loss, jnp.ones(()))
    for name, p in moe.named_parameters():
        assert p.grad is not None, name
    assert float(jnp.abs(moe.router.grad).sum()) > 0.0


def test_ep_sharded_forward_matches_replicated():
    """Experts sharded over ep: same numbers as the unsharded layer, expert
    weights actually laid out on the ep axis."""
    state = AcceleratorState(parallelism_config=ParallelismConfig(ep_size=4, dp_size=2))
    mesh = state.mesh
    nn.manual_seed(0)
    moe = MixtureOfExperts(d_model=8, d_ff=16, num_experts=4, top_k=1)
    x = jnp.asarray(np.random.default_rng(2).normal(size=(16, 8)), jnp.float32)
    y_repl = np.asarray(moe(nn.Tensor(x)).data)

    # lay the stacked expert weights on ep
    for p in (moe.w_in, moe.b_in, moe.w_out, moe.b_out):
        spec = P("ep", *([None] * (p.data.ndim - 1)))
        p.data = jax.device_put(p.data, NamedSharding(mesh, spec))
    assert moe.w_in.data.sharding.spec == P("ep", None, None)

    y_shard = np.asarray(moe(nn.Tensor(x)).data)
    np.testing.assert_allclose(y_shard, y_repl, rtol=2e-5, atol=2e-5)


def test_gpt_tiny_moe_trains():
    """GPTConfig.tiny_moe: MoE blocks integrate with the LM loss (aux term
    included) and a few SGD steps reduce the loss."""
    import accelerate_tpu.optim as optim
    from accelerate_tpu.models import GPTConfig, GPTLMHeadModel

    nn.manual_seed(0)
    cfg = GPTConfig.tiny_moe()
    model = GPTLMHeadModel(cfg)
    assert any(
        isinstance(b.mlp, MixtureOfExperts) for b in model.h
    ) and not all(isinstance(b.mlp, MixtureOfExperts) for b in model.h)
    opt = optim.AdamW(model.parameters(), lr=1e-3)
    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, size=(2, 64)).astype(np.int32)
    losses = []
    for _ in range(4):
        out = model(ids, labels=ids)
        loss = out["loss"]
        nn.backward(loss, jnp.ones(()))
        opt.step()
        opt.zero_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
