"""Process/device state singletons — the L1 layer.

Counterpart of ``/root/reference/src/accelerate/state.py`` (PartialState :123,
AcceleratorState :850, GradientState :1181), rebuilt on PJRT:

* process discovery = ``jax.distributed.initialize`` (multi-host DCN rendezvous
  via coordinator address, the MASTER_ADDR analog) instead of
  ``torch.distributed.init_process_group`` with ten backend strings;
* topology (hosts, slices, chips) read off PJRT device attributes instead of
  LOCAL_RANK/WORLD_SIZE env protocol;
* the distributed "type" collapses to mesh-axis layout (see
  ``utils/dataclasses.ParallelismConfig``) because SPMD replaces
  DDP/FSDP/TP-as-separate-code-paths.

Like the reference, states are Borg singletons: any object anywhere can call
``PartialState()`` and observe the same initialised state.
"""

from __future__ import annotations

import logging
import os
from contextlib import contextmanager
from functools import partial, wraps
from typing import Any, Callable, Optional

import jax
import numpy as np

from .parallel.mesh import batch_sharding_size, make_mesh
from .utils.dataclasses import (
    DistributedType,
    GradientAccumulationPlugin,
    InitProcessGroupKwargs,
    ParallelismConfig,
    PrecisionType,
)
from .utils.environment import (
    get_coordinator_address,
    get_num_processes_env,
    get_process_index_env,
    parse_choice_from_env,
    parse_flag_from_env,
)

logger = logging.getLogger(__name__)

_jax_distributed_initialized = False


def _maybe_init_jax_distributed(kwargs: Optional[InitProcessGroupKwargs]) -> None:
    """Join the multi-host rendezvous if the launch env asks for one.

    Reference boundary: state.py:226,267 (init_process_group).  Here the
    boundary is ``jax.distributed.initialize``, which blocks on all peers —
    exactly like the reference's process-group rendezvous.
    """
    global _jax_distributed_initialized
    if _jax_distributed_initialized:
        return
    num_processes = (kwargs.num_processes if kwargs else None) or get_num_processes_env()
    if num_processes is None or num_processes <= 1:
        return
    coordinator = (
        (kwargs.coordinator_address if kwargs else None) or get_coordinator_address()
    )
    process_id = (
        kwargs.process_id if kwargs and kwargs.process_id is not None else None
    )
    if process_id is None:
        process_id = get_process_index_env()
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    _jax_distributed_initialized = True


class PartialState:
    """Borg singleton for process topology and process control.

    Reference: PartialState state.py:123.  ``num_processes`` counts *host
    processes* (the unit of data loading and checkpoint IO); ``num_devices``
    counts global chips (the unit of SPMD compute).  The reference's
    one-process-per-GPU model makes these equal; on TPU they differ and both
    are exposed.
    """

    _shared_state: dict[str, Any] = {}
    _known_attrs = [
        "_cpu",
        "backend",
        "device",
        "devices",
        "local_devices",
        "distributed_type",
        "num_processes",
        "process_index",
        "local_process_index",
        "debug",
    ]

    def __init__(self, cpu: bool = False, **kwargs):
        self.__dict__ = self._shared_state
        if self.initialized:
            return
        init_kwargs = kwargs.pop("init_process_group_kwargs", None)
        if kwargs and init_kwargs is None:
            import dataclasses as _dc

            recognized = {f.name for f in _dc.fields(InitProcessGroupKwargs)}
            unknown = set(kwargs) - recognized
            if unknown:
                raise TypeError(
                    f"PartialState got unexpected keyword arguments {sorted(unknown)}; "
                    f"recognized distributed-init kwargs: {sorted(recognized)}"
                )
            init_kwargs = InitProcessGroupKwargs(**kwargs)
        self._cpu = cpu
        self.debug = parse_flag_from_env("ACCELERATE_DEBUG_MODE")
        if cpu:
            # The env var alone is ignored once another platform is pinned
            # (e.g. by a sitecustomize); the config update is authoritative.
            os.environ.setdefault("JAX_PLATFORMS", "cpu")
            try:
                jax.config.update("jax_platforms", "cpu")
            except RuntimeError as e:
                raise RuntimeError(
                    "PartialState(cpu=True) requested after the JAX backend was "
                    "already initialized on another platform; construct the "
                    "state before any jax.devices()/jit call."
                ) from e
        _maybe_init_jax_distributed(init_kwargs)
        if not cpu and parse_flag_from_env("ACCELERATE_RESILIENCE_INIT"):
            # hardened backend init (docs/resilience.md): a subprocess probe
            # with retry/backoff and a platform fallback chain runs BEFORE
            # the in-process jax.devices() below, so a hung PJRT client
            # can't wedge this trainer — it either comes up, or the chain
            # pins a platform that does.  Default-off: the flag-check is the
            # entire cost.
            from .resilience.backend import init_backend

            self.init_report = init_backend()
        self.devices = jax.devices()
        self.local_devices = jax.local_devices()
        self.backend = self.devices[0].platform
        self.num_processes = jax.process_count()
        self.process_index = jax.process_index()
        # One process per host on TPU → every process is its own host's local
        # main process (index 0). A launcher running several processes per
        # host (CPU simulation) overrides via env.
        self.local_process_index = int(
            os.environ.get("ACCELERATE_LOCAL_PROCESS_INDEX", 0)
        )
        self.device = self.local_devices[0]
        if self.num_processes > 1:
            self.distributed_type = DistributedType.MULTI_HOST
        elif self.backend in ("tpu", "axon") or len(self.devices) > 1:
            self.distributed_type = DistributedType.TPU
        else:
            self.distributed_type = DistributedType.NO

    @property
    def initialized(self) -> bool:
        return "distributed_type" in self.__dict__

    @staticmethod
    def _reset_state() -> None:
        """Reset the Borg state (testing only; reference state.py:1175)."""
        PartialState._shared_state.clear()
        AcceleratorState._shared_state.clear()
        GradientState._shared_state.clear()

    def __repr__(self) -> str:
        return (
            f"Distributed environment: {self.distributed_type}\n"
            f"Num host processes: {self.num_processes}\n"
            f"Process index: {self.process_index}\n"
            f"Local process index: {self.local_process_index}\n"
            f"Num devices: {self.num_devices}\n"
            f"Device: {self.device}\n"
        )

    # -- topology -----------------------------------------------------------
    @property
    def num_devices(self) -> int:
        return len(self.devices)

    @property
    def local_device_count(self) -> int:
        return len(self.local_devices)

    @property
    def use_distributed(self) -> bool:
        return self.num_processes > 1 or self.num_devices > 1

    @property
    def is_main_process(self) -> bool:
        return self.process_index == 0

    @property
    def is_local_main_process(self) -> bool:
        return self.local_process_index == 0

    @property
    def is_last_process(self) -> bool:
        return self.process_index == self.num_processes - 1

    # -- process control ----------------------------------------------------
    def wait_for_everyone(self) -> None:
        """Cross-host barrier (reference state.py:359).

        Implemented as a named sync over global devices — a tiny psum that
        every host must join, the SPMD analog of ``dist.barrier()``.
        """
        if self.num_processes > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("accelerate_tpu.wait_for_everyone")

    def _goes_first(self, is_main: bool):
        if not is_main:
            self.wait_for_everyone()
        yield
        if is_main:
            self.wait_for_everyone()

    @contextmanager
    def main_process_first(self):
        yield from self._goes_first(self.is_main_process)

    @contextmanager
    def local_main_process_first(self):
        yield from self._goes_first(self.is_local_main_process)

    @contextmanager
    def split_between_processes(
        self, inputs, apply_padding: bool = False
    ):
        """Split a list/tuple/dict-of-lists evenly across host processes.

        Pure-Python logic matching reference semantics (state.py:407): each
        process receives a contiguous chunk; with ``apply_padding`` the last
        element is repeated so every process gets the same count (needed when
        the downstream op is collective).
        """
        if self.num_processes == 1:
            yield inputs
            return
        if isinstance(inputs, dict):
            lengths = {k: len(v) for k, v in inputs.items()}
            if len(set(lengths.values())) > 1:
                raise ValueError(
                    "split_between_processes requires all dict values to have "
                    f"the same length, got {lengths}"
                )
            length = next(iter(lengths.values())) if lengths else 0
        else:
            length = len(inputs)
        split_sizes = [length // self.num_processes] * self.num_processes
        for i in range(length % self.num_processes):
            split_sizes[i] += 1
        start = sum(split_sizes[: self.process_index])
        end = start + split_sizes[self.process_index]

        def _slice(obj):
            chunk = list(obj[start:end])
            if apply_padding and len(chunk) < max(split_sizes) and len(obj) > 0:
                chunk = chunk + list(obj[-1:]) * (max(split_sizes) - len(chunk))
            return chunk

        if isinstance(inputs, dict):
            yield {k: _slice(v) for k, v in inputs.items()}
        else:
            yield _slice(list(inputs) if isinstance(inputs, tuple) else inputs)

    def on_main_process(self, function: Callable = None):
        """Decorator: run only on the global main process (state.py:537).

        Supports both ``@state.on_main_process`` and the parenthesized factory
        form ``@state.on_main_process()``.
        """
        if function is None:
            return partial(self.on_main_process)

        @wraps(function)
        def wrapper(*args, **kwargs):
            if self.is_main_process:
                return function(*args, **kwargs)

        return wrapper

    def on_local_main_process(self, function: Callable = None):
        if function is None:
            return partial(self.on_local_main_process)

        @wraps(function)
        def wrapper(*args, **kwargs):
            if self.is_local_main_process:
                return function(*args, **kwargs)

        return wrapper

    @property
    def default_device(self):
        """The first visible device (reference state.py default_device: the
        device work lands on without explicit placement)."""
        import jax

        return jax.devices()[0]

    def on_local_process(self, function: Callable = None, local_process_index: int = None):
        """Decorator: run only on the given LOCAL process index (reference
        state.py on_local_process)."""
        if function is None:
            return partial(self.on_local_process, local_process_index=local_process_index)
        index = local_process_index or 0

        @wraps(function)
        def wrapper(*args, **kwargs):
            if self.local_process_index == index:
                return function(*args, **kwargs)

        return wrapper

    def on_process(self, function: Callable = None, process_index: int = None):
        if function is None:
            return partial(self.on_process, process_index=process_index)
        if process_index is None:
            process_index = 0

        @wraps(function)
        def wrapper(*args, **kwargs):
            if self.process_index == process_index:
                return function(*args, **kwargs)

        return wrapper

    def on_last_process(self, function: Callable):
        return self.on_process(function, process_index=self.num_processes - 1)

    def print(self, *args, **kwargs) -> None:
        if self.is_local_main_process:
            print(*args, **kwargs)

    def destroy_process_group(self) -> None:
        """Tear down the multi-host rendezvous (reference state.py:333)."""
        global _jax_distributed_initialized
        if _jax_distributed_initialized:
            jax.distributed.shutdown()
            _jax_distributed_initialized = False


class AcceleratorState:
    """Adds precision policy, parallelism layout, and the Mesh to PartialState.

    Reference: AcceleratorState state.py:850.  Where the reference resolves a
    DistributedType override chain (env flags promoting MULTI_GPU→FSDP etc.,
    state.py:958-970), here the same env flags resolve to mesh axis sizes.
    """

    _shared_state: dict[str, Any] = {}

    def __init__(
        self,
        mixed_precision: Optional[str] = None,
        cpu: bool = False,
        parallelism_config: Optional[ParallelismConfig] = None,
        fsdp_plugin=None,
        tp_plugin=None,
        sp_plugin=None,
        pp_plugin=None,
        ep_plugin=None,
        dp_plugin=None,
        _from_accelerator: bool = False,
        **kwargs,
    ):
        self.__dict__ = self._shared_state
        if self.initialized:
            conflicts = []
            if mixed_precision is not None and mixed_precision != self.mixed_precision:
                conflicts.append(
                    f"mixed_precision {self.mixed_precision!r} → {mixed_precision!r}"
                )
            if (
                parallelism_config is not None
                and parallelism_config != self.parallelism_config
            ):
                conflicts.append(
                    f"parallelism_config {self.parallelism_config!r} → {parallelism_config!r}"
                )
            for name, new in (
                ("fsdp_plugin", fsdp_plugin),
                ("tp_plugin", tp_plugin),
                ("sp_plugin", sp_plugin),
                ("pp_plugin", pp_plugin),
                ("ep_plugin", ep_plugin),
                ("dp_plugin", dp_plugin),
            ):
                if new is not None and new != getattr(self, name):
                    conflicts.append(name)
            if conflicts:
                raise ValueError(
                    "AcceleratorState is already initialized; conflicting "
                    f"re-init of: {', '.join(conflicts)}. Call "
                    "AcceleratorState._reset_state() first."
                )
            return
        self._partial = PartialState(cpu=cpu, **kwargs)
        mixed_precision = (
            mixed_precision
            if mixed_precision is not None
            else parse_choice_from_env("ACCELERATE_MIXED_PRECISION", "no")
        )
        mixed_precision = str(mixed_precision).lower()
        if mixed_precision not in PrecisionType.list():
            raise ValueError(
                f"mixed_precision must be one of {PrecisionType.list()}, got "
                f"{mixed_precision!r}"
            )
        self.mixed_precision = mixed_precision
        self.fsdp_plugin = fsdp_plugin
        self.tp_plugin = tp_plugin
        self.sp_plugin = sp_plugin
        self.pp_plugin = pp_plugin
        self.ep_plugin = ep_plugin
        if dp_plugin is None and "ACCELERATE_ZERO1" in os.environ:
            # launcher↔child env protocol: a bare ACCELERATE_ZERO1 resolves
            # to a plugin even when the script never constructs one
            from .utils.dataclasses import DataParallelPlugin

            dp_plugin = DataParallelPlugin()
        self.dp_plugin = dp_plugin

        if parallelism_config is None:
            parallelism_config = ParallelismConfig.from_env()
            if fsdp_plugin is not None:
                parallelism_config.fsdp_size = (
                    fsdp_plugin.fsdp_size or self._partial.num_devices
                )
            if tp_plugin is not None:
                parallelism_config.tp_size = tp_plugin.tp_size
            if sp_plugin is not None:
                parallelism_config.sp_size = sp_plugin.sp_size
            if pp_plugin is not None:
                parallelism_config.pp_size = pp_plugin.pp_size
            if ep_plugin is not None:
                parallelism_config.ep_size = ep_plugin.ep_size
        if parallelism_config.fsdp_size > 1 and self.fsdp_plugin is None:
            # an fsdp mesh axis without a plugin would silently replicate
            # params over it (no memory saving); default to ZeRO-3 semantics
            from .utils.dataclasses import FullyShardedDataParallelPlugin

            self.fsdp_plugin = FullyShardedDataParallelPlugin(
                fsdp_size=parallelism_config.fsdp_size
            )
        self.parallelism_config = parallelism_config
        axis_sizes = parallelism_config.axis_sizes(self._partial.num_devices)
        self.mesh = make_mesh(axis_sizes)

    def __repr__(self) -> str:
        """Reference AcceleratorState.__repr__ (state.py:995): the PartialState
        report plus precision — and, TPU-side, the resolved device mesh."""
        out = self._partial.__repr__() + f"Mixed precision type: {self.mixed_precision}\n"
        if self.initialized:
            out += f"Mesh: {dict(self.mesh.shape)}\n"
        return out

    # Everything PartialState exposes is reachable here too.
    def __getattr__(self, name: str):
        partial = self.__dict__.get("_partial")
        if partial is not None and (
            name in partial.__dict__ or hasattr(PartialState, name)
        ):
            return getattr(partial, name)
        raise AttributeError(
            f"`AcceleratorState` object has no attribute `{name}`"
        )

    @property
    def initialized(self) -> bool:
        return "mesh" in self.__dict__

    @staticmethod
    def _reset_state(reset_partial_state: bool = False) -> None:
        AcceleratorState._shared_state.clear()
        if reset_partial_state:
            PartialState._reset_state()

    @property
    def num_batch_shards(self) -> int:
        """Distinct batch shards across the mesh (dp×fsdp axes)."""
        return batch_sharding_size(self.mesh)

    @property
    def use_fsdp(self) -> bool:
        return self.parallelism_config.fsdp_size > 1 or self.fsdp_plugin is not None

    @property
    def zero1_enabled(self) -> bool:
        """Cross-replica sharded weight update (ZeRO-1) over the dp axis.

        Resolution order: an explicit ``DataParallelPlugin.zero1`` wins;
        otherwise automatic — on for dp > 1 unless an fsdp axis already owns
        the params (FULL_SHARD/HYBRID_SHARD relayouts state onto the param
        shards, so dp-sharding it again buys nothing by default).
        """
        if not self.initialized or self.mesh.shape.get("dp", 1) <= 1:
            return False
        plugin = self.__dict__.get("dp_plugin")
        if plugin is not None and plugin.zero1 is not None:
            return bool(plugin.zero1)
        if self.mesh.shape.get("fsdp", 1) > 1 and (
            self.fsdp_plugin is None
            or self.fsdp_plugin.sharding_strategy in ("FULL_SHARD", "HYBRID_SHARD")
        ):
            return False
        return True

    @property
    def zero2_enabled(self) -> bool:
        """ZeRO-2-style sharded gradient accumulation over the dp axis.

        Strictly opt-in (``DataParallelPlugin(zero2=True)`` /
        ``ACCELERATE_ZERO2=1``) because it changes the ``.grad`` layout
        contract between micro-steps, and only meaningful when ZeRO-1 owns
        a dp-sharded update for the sharded grads to feed
        (docs/compression.md).
        """
        plugin = self.__dict__.get("dp_plugin")
        if plugin is None or not plugin.zero2:
            return False
        return self.zero1_enabled

    @property
    def use_tp(self) -> bool:
        return self.parallelism_config.tp_size > 1

    @property
    def use_sp(self) -> bool:
        return self.parallelism_config.sp_size > 1


class GradientState:
    """Gradient-accumulation bookkeeping shared across all wrappers.

    Reference: GradientState state.py:1181.  ``sync_gradients`` tells the
    optimizer wrapper whether this micro-step should apply an update;
    ``end_of_dataloader``/``remainder`` drive uneven-tail handling in
    ``gather_for_metrics``.  The reference's XLA-specific
    ``is_xla_gradients_synced`` flag has no analog: under SPMD the gradient
    all-reduce is part of the compiled step, never manually deferred.
    """

    _shared_state: dict[str, Any] = {}

    def __init__(self, gradient_accumulation_plugin: Optional[GradientAccumulationPlugin] = None):
        self.__dict__ = self._shared_state
        if not self.initialized:
            self.sync_gradients = True
            self.active_dataloader = None
            self.dataloader_references = [None]
            self.plugin_kwargs = (
                gradient_accumulation_plugin.to_dict()
                if gradient_accumulation_plugin is not None
                else {}
            )
            self._is_accumulating = False
        if gradient_accumulation_plugin is not None and (
            self.plugin_kwargs != gradient_accumulation_plugin.to_dict()
        ):
            self.plugin_kwargs = gradient_accumulation_plugin.to_dict()

    @property
    def initialized(self) -> bool:
        return "sync_gradients" in self.__dict__

    @property
    def num_steps(self) -> int:
        return self.plugin_kwargs.get("num_steps") or 1

    @property
    def adjust_scheduler(self) -> bool:
        return self.plugin_kwargs.get("adjust_scheduler", False)

    @property
    def sync_with_dataloader(self) -> bool:
        return self.plugin_kwargs.get("sync_with_dataloader", True)

    @property
    def sync_each_batch(self) -> bool:
        return self.plugin_kwargs.get("sync_each_batch", False)

    @property
    def end_of_dataloader(self) -> bool:
        if not self.in_dataloader:
            return False
        return self.active_dataloader.end_of_dataloader

    @property
    def remainder(self) -> int:
        if not self.in_dataloader:
            return -1
        return self.active_dataloader.remainder

    @property
    def in_dataloader(self) -> bool:
        return self.active_dataloader is not None

    def _set_sync_gradients(self, sync_gradients: bool) -> None:
        self.sync_gradients = sync_gradients

    def _add_dataloader(self, dataloader) -> None:
        self.active_dataloader = dataloader
        self.dataloader_references.append(dataloader)

    def _remove_dataloader(self, dataloader) -> None:
        if dataloader in self.dataloader_references:
            self.dataloader_references.remove(dataloader)
        self.active_dataloader = self.dataloader_references[-1]

    @staticmethod
    def _reset_state() -> None:
        GradientState._shared_state.clear()

    def __repr__(self) -> str:
        return (
            f"Sync Gradients: {self.sync_gradients}\n"
            f"At end of current dataloader: {self.end_of_dataloader}\n"
            f"Extra samples added: {self.remainder}\n"
            f"Gradient accumulation plugin: {self.plugin_kwargs}\n"
        )
