"""Importable test harness (reference: src/accelerate/test_utils/)."""

from .testing import (
    TempDirTestCase,
    default_launch_command,
    device_count,
    execute_subprocess,
    launch_test_script,
    require_cpu,
    require_multi_device,
    require_non_cpu,
    require_fp8,
    require_multi_host,
    require_pallas,
    require_single_device,
    require_torch,
    require_tpu,
    require_transformers,
    run_command,
    skip,
    slow,
)
from .training import RegressionDataset, RegressionModel, mocked_dataloaders
