"""DeepSpeed-config ingestion: map a ``ds_config.json`` onto mesh plugins.

The reference hands the whole model to the DeepSpeed engine
(/root/reference/src/accelerate/accelerator.py:1745, utils/deepspeed.py:121
``HfDeepSpeedConfig`` querying ``zero_optimization.*``).  On TPU there is no
engine to delegate to — ZeRO stages ARE sharding layouts on the ``fsdp``
mesh axis — but users migrating from the reference carry ds_config.json
files, so this module reads the common fields and returns the equivalent
native configuration:

  zero_optimization.stage 0      → NO_SHARD (pure DP)
  zero_optimization.stage 1/2    → SHARD_GRAD_OP (grads+opt-state sharded)
  zero_optimization.stage 3      → FULL_SHARD (params too)
  fp16.enabled / bf16.enabled    → mixed_precision
  train_micro_batch_size_per_gpu → per-device batch size
  gradient_accumulation_steps    → gradient_accumulation_steps
  gradient_clipping              → clip value for clip_grad_norm_
  offload_{param,optimizer}      → warning (host offload is the big-model
                                   path here, not a ZeRO knob)

``"auto"`` values resolve to the caller-supplied defaults, mirroring the
reference's auto-fill contract (utils/deepspeed.py:253).
"""

from __future__ import annotations

import json
import warnings
from dataclasses import dataclass, field
from typing import Any, Optional

from .dataclasses import FullyShardedDataParallelPlugin

_STAGE_TO_STRATEGY = {
    0: "NO_SHARD",
    1: "SHARD_GRAD_OP",
    2: "SHARD_GRAD_OP",
    3: "FULL_SHARD",
}


@dataclass
class DeepSpeedCompatConfig:
    """The native equivalents extracted from one ds_config dict."""

    fsdp_plugin: Optional[FullyShardedDataParallelPlugin]
    mixed_precision: str
    gradient_accumulation_steps: int
    micro_batch_size: Optional[int]
    gradient_clipping: Optional[float]
    zero_stage: int
    raw: dict = field(repr=False, default_factory=dict)

    def accelerator_kwargs(self) -> dict[str, Any]:
        """kwargs ready to splat into ``Accelerator(...)``."""
        kwargs: dict[str, Any] = {
            "mixed_precision": self.mixed_precision,
            "gradient_accumulation_steps": self.gradient_accumulation_steps,
        }
        if self.fsdp_plugin is not None:
            kwargs["fsdp_plugin"] = self.fsdp_plugin
        return kwargs


def _get(cfg: dict, dotted: str, default=None):
    node: Any = cfg
    for part in dotted.split("."):
        if not isinstance(node, dict) or part not in node:
            return default
        node = node[part]
    return node


def _resolve_auto(value, fallback):
    return fallback if value in ("auto", None) else value


def from_deepspeed_config(
    config: "dict | str",
    *,
    micro_batch_size: Optional[int] = None,
    gradient_accumulation_steps: int = 1,
) -> DeepSpeedCompatConfig:
    """Parse a DeepSpeed config (dict or path to JSON) into native settings.

    Keyword fallbacks fill ``"auto"`` entries the way the reference's
    ``deepspeed_config_process`` does.
    """
    if isinstance(config, str):
        with open(config) as f:
            cfg = json.load(f)
    else:
        cfg = dict(config)

    stage = _resolve_auto(_get(cfg, "zero_optimization.stage", 0), 0)
    if stage not in _STAGE_TO_STRATEGY:
        raise ValueError(f"unsupported zero_optimization.stage: {stage!r}")

    fsdp_plugin = None
    if stage > 0:
        fsdp_plugin = FullyShardedDataParallelPlugin()
        # assign AFTER construction: __post_init__ re-reads
        # FSDP_SHARDING_STRATEGY from the environment (launcher protocol)
        # and would silently override the ds_config-derived stage
        fsdp_plugin.sharding_strategy = _STAGE_TO_STRATEGY[stage]

    opt_dev = _get(cfg, "zero_optimization.offload_optimizer.device")
    if opt_dev in ("cpu", "nvme"):
        # ZeRO-offload of *optimizer state* has a real TPU mechanism: the
        # moments/masters live in pinned host memory and stream to the chip
        # for the update (FullyShardedDataParallelPlugin.offload_optimizer).
        # nvme maps to host too — TPU VMs have no per-chip NVMe tier.
        if fsdp_plugin is not None:  # stage > 0: the plugin carries the stage
            fsdp_plugin.offload_optimizer = True
            if opt_dev == "nvme":
                warnings.warn(
                    "ds_config offload_optimizer.device='nvme' maps to pinned "
                    "host memory on TPU (no per-chip NVMe tier)",
                    stacklevel=2,
                )
        else:
            # stage 0 = pure DDP: fabricating an FSDP plugin here would
            # silently FULL_SHARD params the config never asked to shard
            warnings.warn(
                "ds_config requests offload_optimizer with zero stage 0; "
                "optimizer-state host offload rides the fsdp plugin — set "
                "zero stage >= 1 (or pass FullyShardedDataParallelPlugin("
                "offload_optimizer=True) with your intended strategy)",
                stacklevel=2,
            )
    param_dev = _get(cfg, "zero_optimization.offload_param.device")
    if param_dev in ("cpu", "nvme"):
        # ZeRO-Infinity training-time param offload has a real TPU
        # mechanism too: fsdp-sharded params pinned to host between steps,
        # staged back by a traced forward hook
        # (FullyShardedDataParallelPlugin.cpu_offload → hooks.ParamOffloadHook
        # + optim.reoffload_params_to_host)
        if fsdp_plugin is not None:
            fsdp_plugin.cpu_offload = True
            if param_dev == "nvme":
                warnings.warn(
                    "ds_config offload_param.device='nvme' maps to pinned "
                    "host memory on TPU (no per-chip NVMe tier)",
                    stacklevel=2,
                )
        else:
            warnings.warn(
                "ds_config requests offload_param with zero stage 0; param "
                "host offload rides the fsdp plugin — set zero stage >= 1 "
                "(or pass FullyShardedDataParallelPlugin(cpu_offload=True) "
                "with your intended strategy)",
                stacklevel=2,
            )

    if _resolve_auto(_get(cfg, "bf16.enabled"), False):
        mixed_precision = "bf16"
    elif _resolve_auto(_get(cfg, "fp16.enabled"), False):
        mixed_precision = "fp16"
    else:
        mixed_precision = "no"

    accum = _resolve_auto(
        _get(cfg, "gradient_accumulation_steps"), gradient_accumulation_steps
    )
    mbs = _resolve_auto(_get(cfg, "train_micro_batch_size_per_gpu"), micro_batch_size)
    clip = _resolve_auto(_get(cfg, "gradient_clipping"), None)

    return DeepSpeedCompatConfig(
        fsdp_plugin=fsdp_plugin,
        mixed_precision=mixed_precision,
        gradient_accumulation_steps=int(accum),
        micro_batch_size=None if mbs is None else int(mbs),
        gradient_clipping=None if clip is None else float(clip),
        zero_stage=int(stage),
        raw=cfg,
    )
