#!/usr/bin/env python
"""resilience_smoke — `make resilience-smoke`: prove the preemption path
end-to-end on CPU in seconds (docs/resilience.md).

Tiny model, resilience on with an injected SIGTERM scheduled right before
step 2's dispatch.  The training loop finishes that step, reads the sticky
``should_exit`` flag, drains a checkpoint through the async
save_state/wait_for_checkpoint machinery and stops — then a fresh
accelerator resumes from that checkpoint and must reproduce the
uninterrupted run's remaining losses BITWISE.  Exit 0 = complete checkpoint
(meta sentinel present), bitwise-equal resume, and preemption/drain events
in the resilience stream.
"""

import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

STEPS = 5
SIGTERM_AT = 2


def main() -> int:
    import jax.numpy as jnp
    import numpy as np

    import accelerate_tpu.nn as nn
    import accelerate_tpu.optim as optim
    from accelerate_tpu import Accelerator, ResilienceKwargs
    from accelerate_tpu.checkpointing import is_complete_checkpoint
    from accelerate_tpu.models import GPTConfig, GPTLMHeadModel
    from accelerate_tpu.data_loader import batch_to_global_array

    errors: list[str] = []
    ckpt = os.path.join(tempfile.mkdtemp(prefix="atpu_resilience_"), "preempted")

    def build(res_kwargs=None):
        Accelerator._reset_state()
        nn.manual_seed(0)
        acc = Accelerator(kwargs_handlers=[res_kwargs] if res_kwargs else None)
        model = GPTLMHeadModel(
            GPTConfig(vocab_size=256, n_positions=64, n_embd=32, n_layer=1, n_head=2)
        )
        opt = optim.AdamW(model.parameters(), lr=1e-3)
        model, opt = acc.prepare(model, opt)

        def step_fn(ids):
            opt.zero_grad()
            out = model(ids, labels=ids)
            acc.backward(out["loss"])
            opt.step()
            return out["loss"]

        rng = np.random.default_rng(0)
        batches = [
            batch_to_global_array(
                jnp.asarray(rng.integers(0, 256, (8, 32), dtype=np.int32)),
                mesh=acc.mesh,
            )
            for _ in range(STEPS)
        ]
        return acc, acc.compile_step(step_fn), batches

    # uninterrupted reference
    _, step, batches = build()
    reference = [float(step(b)) for b in batches]

    # preempted run: injected SIGTERM right before step 2's dispatch
    acc, step, batches = build(
        ResilienceKwargs(
            enabled=True, fault_plan=f"sigterm:step={SIGTERM_AT}", retry=False
        )
    )
    seen = []
    for batch in batches:
        seen.append(float(step(batch)))
        if acc.resilience.should_exit:
            acc.resilience.drain(acc, ckpt)
            break
    acc.resilience.close()
    events = [e["event"] for e in acc.resilience.events]
    if len(seen) != SIGTERM_AT + 1:
        errors.append(f"expected to stop after step {SIGTERM_AT}, ran {len(seen)}")
    if "preemption" not in events or "drain" not in events:
        errors.append(f"missing preemption/drain events: {events}")
    if not is_complete_checkpoint(ckpt):
        errors.append(f"checkpoint at {ckpt} is not complete")

    # resume and finish the run
    acc2, step2, batches = build()
    acc2.load_state(ckpt)
    resumed = [float(step2(b)) for b in batches[len(seen):]]
    if seen + resumed != reference:
        errors.append(
            f"resume not bitwise-equal: interrupted {seen} + resumed {resumed} "
            f"!= reference {reference}"
        )

    for error in errors:
        print(f"resilience-smoke: FAIL: {error}", file=sys.stderr)
    if errors:
        return 1
    print(
        f"resilience-smoke: ok — SIGTERM at step {SIGTERM_AT}, complete "
        f"checkpoint, resume bitwise-equal over {len(resumed)} remaining steps"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
