"""Pytree collective operations — the L2 layer.

Counterpart of ``/root/reference/src/accelerate/utils/operations.py`` (867 LoC).
The reference branches per DistributedType into NCCL/gloo/xm calls; here there
is exactly one distribution model:

* **device-level** collectives (the hot path) never appear in this file — they
  are emitted by XLA from sharding specs inside the compiled step and ride ICI;
* **host-level** utilities below move data between host processes over the
  PJRT/DCN fabric (``jax.experimental.multihost_utils``) or between host and
  device (``jax.device_put``).  These are the cold-path analogues of
  ``gather``/``broadcast``/``reduce``/``pad_across_processes``.

All ops are pytree-recursive over nested list/tuple/dict/namedtuple structures
(reference ``recursively_apply`` operations.py:84) and accept jax.Array, numpy,
and Python scalars.
"""

from __future__ import annotations

import pickle
from contextlib import contextmanager
from functools import wraps
from typing import Any, Callable, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np


class DistributedOperationException(Exception):
    """Raised when an operation cannot run consistently across processes
    (reference operations.py:355)."""


def is_tensor_like(obj: Any) -> bool:
    return isinstance(obj, (jax.Array, np.ndarray))


def honor_type(obj, generator):
    """Rebuild ``obj``'s container type from ``generator`` (namedtuple-aware;
    reference operations.py:60)."""
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):  # namedtuple
        return type(obj)(*list(generator))
    return type(obj)(generator)


def recursively_apply(
    func: Callable,
    data: Any,
    *args,
    test_type: Callable[[Any], bool] = is_tensor_like,
    error_on_other_type: bool = False,
    **kwargs,
):
    """Apply ``func`` to every leaf of ``data`` passing ``test_type``.

    Reference pytree engine: operations.py:84.  Implemented directly (not via
    jax.tree_util) so Mapping subclasses and namedtuples round-trip with their
    own types, and non-tensor leaves pass through untouched.
    """
    if test_type(data):
        return func(data, *args, **kwargs)
    if isinstance(data, (tuple, list)):
        return honor_type(
            data,
            (
                recursively_apply(
                    func,
                    o,
                    *args,
                    test_type=test_type,
                    error_on_other_type=error_on_other_type,
                    **kwargs,
                )
                for o in data
            ),
        )
    if isinstance(data, Mapping):
        return type(data)(
            {
                k: recursively_apply(
                    func,
                    v,
                    *args,
                    test_type=test_type,
                    error_on_other_type=error_on_other_type,
                    **kwargs,
                )
                for k, v in data.items()
            }
        )
    if error_on_other_type:
        raise TypeError(
            f"Unsupported type {type(data)} passed to a collective op; only "
            "nested list/tuple/dicts of arrays are supported."
        )
    return data


# ---------------------------------------------------------------------------
# Host ↔ device movement
# ---------------------------------------------------------------------------
def send_to_device(tensor, device=None, non_blocking: bool = False, skip_keys=None):
    """Recursively move arrays to a device or sharding (reference :135).

    ``device`` may be a jax.Device, a Sharding, or None (default device).
    Transfers are always async under PJRT; ``non_blocking`` kept for parity.
    """
    if skip_keys is not None and isinstance(tensor, Mapping):
        skip = (skip_keys,) if isinstance(skip_keys, str) else tuple(skip_keys)
        return type(tensor)(
            {
                k: (v if k in skip else send_to_device(v, device))
                for k, v in tensor.items()
            }
        )

    def _send(t):
        return jax.device_put(t, device)

    return recursively_apply(_send, tensor)


def get_data_structure(data):
    """Shape/dtype skeleton of a pytree (reference :169) for broadcast of
    structure before payload."""

    def _describe(t):
        return {"shape": tuple(np.shape(t)), "dtype": str(np.asarray(t).dtype)}

    return recursively_apply(_describe, data)


def initialize_tensors(data_structure):
    """Materialize zeros matching a skeleton from ``get_data_structure``."""

    def _init(desc):
        return jnp.zeros(desc["shape"], dtype=desc["dtype"])

    return recursively_apply(
        _init, data_structure, test_type=lambda o: isinstance(o, dict) and "shape" in o
    )


def find_device(data):
    """First device found in a pytree (reference :1010)."""
    if isinstance(data, (tuple, list)):
        for obj in data:
            device = find_device(obj)
            if device is not None:
                return device
    elif isinstance(data, Mapping):
        for obj in data.values():
            device = find_device(obj)
            if device is not None:
                return device
    elif isinstance(data, jax.Array):
        devs = getattr(data.sharding, "device_set", None)
        if devs:
            return next(iter(devs))
    return None


def find_batch_size(data) -> Optional[int]:
    """Batch size (dim 0) of the first array leaf (reference :254)."""
    if isinstance(data, (tuple, list)):
        for obj in data:
            result = find_batch_size(obj)
            if result is not None:
                return result
    elif isinstance(data, Mapping):
        for obj in data.values():
            result = find_batch_size(obj)
            if result is not None:
                return result
    elif is_tensor_like(data) and np.ndim(data) > 0:
        return int(np.shape(data)[0])
    return None


def listify(data):
    """Convert array leaves to nested Python lists (reference :273)."""

    def _to_list(t):
        return np.asarray(jax.device_get(t)).tolist()

    return recursively_apply(_to_list, data)


def slice_tensors(data, tensor_slice, process_index=None, num_processes=None):
    """Slice every array leaf (reference :570)."""

    def _slice(t, s):
        return t[s]

    return recursively_apply(_slice, data, tensor_slice)


def concatenate(data, dim: int = 0):
    """Concatenate a list of pytrees leaf-wise (reference :600)."""
    if isinstance(data[0], (tuple, list)):
        return honor_type(
            data[0], (concatenate([d[i] for d in data], dim=dim) for i in range(len(data[0])))
        )
    if isinstance(data[0], Mapping):
        return type(data[0])(
            {k: concatenate([d[k] for d in data], dim=dim) for k in data[0].keys()}
        )
    if not is_tensor_like(data[0]):
        raise TypeError(f"Can only concatenate arrays/containers, got {type(data[0])}.")
    if isinstance(data[0], np.ndarray):
        return np.concatenate(data, axis=dim)
    return jnp.concatenate(data, axis=dim)


# ---------------------------------------------------------------------------
# Cross-process (host-level) collectives
# ---------------------------------------------------------------------------
def _num_processes() -> int:
    return jax.process_count()


@contextmanager
def _blackbox(op: str):
    """Black-box instrumentation around one *multi-process* host collective
    (docs/telemetry.md §flight recorder): tick the flight recorder's
    collective-sequence counter — the cross-rank alignment key every rank
    must advance identically, which is how ``tools/blackbox_report.py``
    names the lagging rank after a hang — and, when the hang watchdog is
    armed, put the blocking section on its deadline.  Single-process calls
    short-circuit before reaching this, so the unsynchronized path pays
    nothing and the sequence counts exactly the real collectives."""
    from ..telemetry import flightrec
    from ..telemetry import watchdog as _watchdog

    seq = flightrec.note_collective(op, world=_num_processes())
    wd = _watchdog.current_watchdog()
    if wd is None:
        yield
        return
    with wd.guard(f"collective:{op} #{seq}"):
        yield


def verify_operation(function: Callable):
    """Debug-mode shape verification before a collective (reference :364).

    With ``ACCELERATE_DEBUG_MODE=1`` every rank's pytree shape skeleton is
    all-gathered and compared before the real op, turning silent hangs from
    mismatched collectives into a loud DistributedOperationException.
    """

    @wraps(function)
    def wrapper(*args, **kwargs):
        from ..state import PartialState

        state = PartialState()
        if not state.debug or state.num_processes == 1:
            return function(*args, **kwargs)
        operation = f"{function.__module__}.{function.__name__}"
        tensor = kwargs.get("tensor", args[0] if args else None)
        shapes = get_data_structure(tensor)
        output = gather_object([shapes])
        if output[0] is not None and not all(o == output[0] for o in output[1:]):
            raise DistributedOperationException(
                f"Cannot apply the desired operation ({operation}) due to "
                f"distributed shape mismatch across processes: {output}"
            )
        return function(*args, **kwargs)

    return wrapper


@verify_operation
def gather(tensor):
    """Gather across host processes, concatenating along dim 0 (reference :419).

    For a globally-sharded jax.Array the data is already the concatenation —
    the op reshards to fully-replicated so every host can address all of it.
    For host-local (numpy / single-device) arrays it all-gathers across
    processes.
    """

    def _gather(t):
        if isinstance(t, jax.Array) and not t.is_fully_addressable:
            from jax.experimental import multihost_utils

            return multihost_utils.process_allgather(t, tiled=True)
        if _num_processes() == 1:
            return t
        from jax.experimental import multihost_utils

        return multihost_utils.process_allgather(np.asarray(t), tiled=True)

    if _num_processes() == 1:
        return recursively_apply(_gather, tensor, error_on_other_type=True)
    with _blackbox("gather"):
        return recursively_apply(_gather, tensor, error_on_other_type=True)


def gather_object(object: Any):
    """Gather picklable objects from all processes (reference :445).

    Reference semantics exactly: each process passes a *list* and receives
    the flattened concatenation over processes (`[x for y in out for x in y]`,
    reference operations.py:436-441); a single process gets its object back
    unchanged (reference :460)."""
    if _num_processes() == 1:
        return object
    from jax.experimental import multihost_utils

    with _blackbox("gather_object"):
        payload = np.frombuffer(pickle.dumps(object), dtype=np.uint8)
        size = np.array([payload.size], dtype=np.int64)
        all_sizes = multihost_utils.process_allgather(size)
        max_size = int(all_sizes.max())
        padded = np.zeros(max_size, dtype=np.uint8)
        padded[: payload.size] = payload
        gathered = multihost_utils.process_allgather(padded)
        per_process = [
            pickle.loads(gathered[i, : int(all_sizes[i, 0])].tobytes())
            for i in range(gathered.shape[0])
        ]
        return [x for y in per_process for x in y]


@verify_operation
def broadcast(tensor, from_process: int = 0):
    """Broadcast array leaves from ``from_process`` to all (reference :539)."""

    def _broadcast(t):
        if _num_processes() == 1:
            return t
        from jax.experimental import multihost_utils

        return multihost_utils.broadcast_one_to_all(
            np.asarray(jax.device_get(t)), is_source=jax.process_index() == from_process
        )

    if _num_processes() == 1:
        return recursively_apply(_broadcast, tensor, error_on_other_type=True)
    with _blackbox("broadcast"):
        return recursively_apply(_broadcast, tensor, error_on_other_type=True)


def broadcast_object_list(object_list: list, from_process: int = 0):
    """Broadcast picklable objects from one process, in place (reference :560).

    True one-to-all: only ``from_process`` pickles; everyone else contributes
    a zero buffer.  Two ``broadcast_one_to_all`` rounds (size, then payload)
    keep per-step dispatch traffic O(payload), not O(world × payload) — the
    reference's dispatcher leans on this every batch (data_loader.py:778).
    """
    if _num_processes() == 1:
        return object_list
    import pickle

    from jax.experimental import multihost_utils

    is_source = jax.process_index() == from_process
    with _blackbox("broadcast_object_list"):
        if is_source:
            payload = np.frombuffer(pickle.dumps(list(object_list)), dtype=np.uint8)
        else:
            payload = np.zeros(0, dtype=np.uint8)
        size = multihost_utils.broadcast_one_to_all(
            np.array([payload.size], dtype=np.int64), is_source=is_source
        )
        buf = payload if is_source else np.zeros(int(size[0]), dtype=np.uint8)
        data = multihost_utils.broadcast_one_to_all(buf, is_source=is_source)
        src = pickle.loads(np.asarray(data).tobytes())
    for i in range(len(object_list)):
        object_list[i] = src[i]
    return object_list


@verify_operation
def reduce(tensor, reduction: str = "mean", scale: float = 1.0):
    """Sum/mean each leaf across host processes (reference :724)."""

    def _reduce(t):
        if _num_processes() == 1:
            arr = jnp.asarray(t)
            return arr * scale if scale != 1.0 else arr
        from jax.experimental import multihost_utils

        stacked = multihost_utils.process_allgather(np.asarray(jax.device_get(t)))
        out = stacked.sum(axis=0) * scale
        if reduction == "mean":
            out = out / _num_processes()
        return jnp.asarray(out)

    if _num_processes() == 1:
        return recursively_apply(_reduce, tensor, error_on_other_type=True)
    with _blackbox("reduce"):
        return recursively_apply(_reduce, tensor, error_on_other_type=True)


@verify_operation
def pad_across_processes(tensor, dim: int = 0, pad_index: int = 0, pad_first: bool = False):
    """Pad each leaf to the max size across processes on ``dim`` (reference :628)."""

    def _pad(t):
        if np.ndim(t) == 0:
            return t
        ndim = np.ndim(t)
        d = dim % ndim if ndim else 0
        size = np.array(np.shape(t), dtype=np.int64)
        if _num_processes() == 1:
            return t
        from jax.experimental import multihost_utils

        sizes = multihost_utils.process_allgather(size)
        max_size = int(sizes[:, d].max())
        if max_size == np.shape(t)[d]:
            return t
        old_size = np.shape(t)
        new_size = list(old_size)
        new_size[d] = max_size
        new_tensor = jnp.full(new_size, pad_index, dtype=jnp.asarray(t).dtype)
        if pad_first:
            indices = tuple(
                slice(max_size - old_size[d], max_size) if i == d else slice(None)
                for i in range(ndim)
            )
        else:
            indices = tuple(
                slice(0, old_size[d]) if i == d else slice(None) for i in range(ndim)
            )
        return new_tensor.at[indices].set(jnp.asarray(t))

    return recursively_apply(_pad, tensor, error_on_other_type=True)


def pad_input_tensors(tensor, batch_size: int, num_processes: int, dim: int = 0):
    """Pad dim 0 so batch splits evenly across processes (reference :680)."""
    remainder = batch_size % num_processes
    if remainder == 0:
        return tensor
    missing = num_processes - remainder

    def _pad(t):
        if np.ndim(t) == 0 or np.shape(t)[0] != batch_size:
            return t
        arr = jnp.asarray(t)
        pad = jnp.repeat(arr[-1:], missing, axis=0)
        return jnp.concatenate([arr, pad], axis=0)

    return recursively_apply(_pad, tensor, error_on_other_type=True)


# ---------------------------------------------------------------------------
# Precision conversion
# ---------------------------------------------------------------------------
def convert_to_fp32(tensor):
    """Upcast half-precision leaves to float32 (reference :786)."""

    def _convert(t):
        return jnp.asarray(t, dtype=jnp.float32)

    def _is_half(t):
        return is_tensor_like(t) and t.dtype in (
            np.dtype("float16"),
            np.dtype(jnp.bfloat16),
        )

    return recursively_apply(_convert, tensor, test_type=_is_half)


class ConvertOutputsToFp32:
    """Wrap a forward so its float outputs come back fp32 (reference :800).

    Kept as a class (not a closure) so wrapped models stay picklable.
    """

    def __init__(self, model_forward):
        self.model_forward = model_forward
        wraps(model_forward)(self)

    def __call__(self, *args, **kwargs):
        return convert_to_fp32(self.model_forward(*args, **kwargs))


convert_outputs_to_fp32 = ConvertOutputsToFp32
