"""GPT-J family decoder — the reference's headline big-model-inference
benchmark family (GPT-J-6B, reference
benchmarks/big_model_inference/README.md:31-32).

Parallel-residual decoder: attention AND MLP both read the same
pre-norm ``ln_1(x)`` and add into the residual together
(``x + attn(h) + mlp(h)``), rotary position embeddings in the
*interleaved* (rotate-every-two) GPT-J convention on the first
``rotary_dim`` head dims, untied LM head WITH bias.  Same one-math
structure as models/llama.py: each layer's forward is a single
``tape_op`` over the pure per-layer pair the KV-cache decode engine
(models/generation.py) scans over.  Parameter naming mirrors HF
(``h.N.attn.q_proj`` …) for key-mapped checkpoint ingestion.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from .. import nn
from ..nn import Tensor
from .gpt import _pure_layernorm, lm_head_loss, maybe_remat


@dataclasses.dataclass
class GPTJConfig:
    vocab_size: int = 50400
    n_positions: int = 2048
    n_embd: int = 4096
    n_layer: int = 28
    n_head: int = 16
    rotary_dim: int = 64
    n_inner: int = 16384
    layer_norm_eps: float = 1e-5
    initializer_range: float = 0.02

    @classmethod
    def tiny(cls) -> "GPTJConfig":
        return cls(
            vocab_size=1024, n_positions=256, n_embd=128, n_layer=2, n_head=4,
            rotary_dim=16, n_inner=256,
        )

    @classmethod
    def gptj_6b(cls) -> "GPTJConfig":
        return cls()  # the defaults are GPT-J-6B


# ---------------------------------------------------------------------------
# Pure per-layer math.  Keys: ln1_w, ln1_b, q_w, k_w, v_w, o_w,
# fcin_w, fcin_b, fcout_w, fcout_b (projections are bias-free except MLP).
# ---------------------------------------------------------------------------
_LAYER_KEYS = (
    "ln1_w", "ln1_b", "q_w", "k_w", "v_w", "o_w",
    "fcin_w", "fcin_b", "fcout_w", "fcout_b",
)


def _rope_interleaved(x, positions, rotary_dim: int):
    """GPT-J rotary: rotate-every-two on the first ``rotary_dim`` dims.

    HF convention (transformers GPTJAttention): fp32 sincos duplicated
    per-pair, ``x1 = x[..., ::2]; x2 = x[..., 1::2]`` rotated and
    re-interleaved; dims past ``rotary_dim`` pass through unchanged.
    """
    rot, pas = x[..., :rotary_dim], x[..., rotary_dim:]
    inv = 1.0 / (
        10000.0 ** (jnp.arange(0, rotary_dim, 2, dtype=jnp.float32) / rotary_dim)
    )
    freqs = positions.astype(jnp.float32)[:, None] * inv[None, :]  # (s, r/2)
    sin = jnp.repeat(jnp.sin(freqs), 2, axis=-1).astype(x.dtype)[None, None]
    cos = jnp.repeat(jnp.cos(freqs), 2, axis=-1).astype(x.dtype)[None, None]
    x1 = rot[..., ::2]
    x2 = rot[..., 1::2]
    rotated = jnp.stack([-x2, x1], axis=-1).reshape(rot.shape)
    return jnp.concatenate([rot * cos + rotated * sin, pas], axis=-1)


def gptj_attn_in(l, x, positions, *, n_head: int, rotary_dim: int, eps: float):
    b, s, c = x.shape
    d = c // n_head
    h = _pure_layernorm(x, l["ln1_w"], l["ln1_b"], eps)

    def heads(t):
        return t.reshape(b, s, n_head, d).transpose(0, 2, 1, 3)

    q = _rope_interleaved(heads(h @ l["q_w"].T), positions, rotary_dim)
    k = _rope_interleaved(heads(h @ l["k_w"].T), positions, rotary_dim)
    v = heads(h @ l["v_w"].T)
    return q, k, v


def gptj_attn_out(l, x, att, *, eps: float):
    """Parallel residual: out_proj(att) + mlp(ln_1(x)) + x — the MLP reads
    the SAME normed input as attention (GPT-J block shape)."""
    b, s, c = x.shape
    att = att.transpose(0, 2, 1, 3).reshape(b, s, c)
    h = _pure_layernorm(x, l["ln1_w"], l["ln1_b"], eps)
    ff = jax.nn.gelu(h @ l["fcin_w"].T + l["fcin_b"], approximate=True)
    return x + att @ l["o_w"].T + ff @ l["fcout_w"].T + l["fcout_b"]


class GPTJBlock(nn.Module):
    def __init__(self, config: GPTJConfig):
        super().__init__()
        self.config = config
        c = config.n_embd
        self.ln_1 = nn.LayerNorm(c, eps=config.layer_norm_eps)

        class _Attn(nn.Module):
            def __init__(self):
                super().__init__()
                self.q_proj = nn.Linear(c, c, bias=False)
                self.k_proj = nn.Linear(c, c, bias=False)
                self.v_proj = nn.Linear(c, c, bias=False)
                self.out_proj = nn.Linear(c, c, bias=False)

        class _MLP(nn.Module):
            def __init__(self):
                super().__init__()
                self.fc_in = nn.Linear(c, config.n_inner)
                self.fc_out = nn.Linear(config.n_inner, c)

        self.attn = _Attn()
        self.mlp = _MLP()

    def param_tensors(self):
        a, m = self.attn, self.mlp
        return [  # order == _LAYER_KEYS
            self.ln_1.weight, self.ln_1.bias,
            a.q_proj.weight, a.k_proj.weight, a.v_proj.weight, a.out_proj.weight,
            m.fc_in.weight, m.fc_in.bias, m.fc_out.weight, m.fc_out.bias,
        ]

    def forward(self, x):
        cfg = self.config
        positions = jnp.arange(x.shape[1])

        def fn(xv, *flat):
            from ..ops.attention import sdpa_tpu

            l = dict(zip(_LAYER_KEYS, flat))
            q, k, v = gptj_attn_in(
                l, xv, positions,
                n_head=cfg.n_head, rotary_dim=cfg.rotary_dim,
                eps=cfg.layer_norm_eps,
            )
            att = sdpa_tpu(q, k, v, is_causal=True)
            return gptj_attn_out(l, xv, att, eps=cfg.layer_norm_eps)

        return nn.tape_op(maybe_remat(fn), x, *self.param_tensors())


class GPTJForCausalLM(nn.Module):
    _no_split_modules = ["GPTJBlock"]
    tp_plan = {
        r".*\.(q_proj|k_proj|v_proj|fc_in)\.weight": ("tp", None),
        r".*\.fc_in\.bias": ("tp",),
        r".*\.(out_proj|fc_out)\.weight": (None, "tp"),
        r"wte\.weight": ("tp", None),
        r"lm_head\.weight": ("tp", None),
        r"lm_head\.bias": ("tp",),
    }

    def __init__(self, config: GPTJConfig):
        super().__init__()
        self.config = config
        self.wte = nn.Embedding(config.vocab_size, config.n_embd)
        self.h = nn.ModuleList([GPTJBlock(config) for _ in range(config.n_layer)])
        self.ln_f = nn.LayerNorm(config.n_embd, eps=config.layer_norm_eps)
        self.lm_head = nn.Linear(config.n_embd, config.vocab_size)  # untied, biased
        from ..nn import random as nn_random
        from ..nn.meta import is_meta

        std = config.initializer_range
        for name, p in self.named_parameters():
            if is_meta(p.data):
                continue
            if p.ndim >= 2:
                p.data = std * jax.random.normal(nn_random.next_key(), p.shape, p.dtype)
            elif name.endswith("bias"):
                p.data = jnp.zeros_like(p.data)

    def forward(self, input_ids, labels=None):
        from ..parallel.sharding import constrain_activation

        ids = jnp.asarray(input_ids.data if isinstance(input_ids, Tensor) else input_ids)
        x = self.wte(ids)
        x = constrain_activation(x)
        for block in self.h:
            x = constrain_activation(block(x))
        x = self.ln_f(x)
        if labels is not None:
            loss, logits = lm_head_loss(
                x, self.lm_head, labels, self.config.vocab_size
            )
            return {"loss": loss, "logits": logits}
        return {"logits": self.lm_head(x)}

    def generate(self, input_ids, max_new_tokens: int, temperature: float = 0.0,
                 rng=None, quantize_weights=None, **kwargs):
        from .generation import generate

        return generate(self, input_ids, max_new_tokens, temperature, rng,
                        quantize_weights=quantize_weights, **kwargs)

    @property
    def num_flops_per_token(self) -> float:
        n = self.num_parameters
        c = self.config
        return 6 * n + 12 * c.n_layer * c.n_embd * c.n_positions

    def _decoder_spec(self):
        from .generation import DecoderSpec

        cfg = self.config
        return DecoderSpec(
            family=GPTJ_DECODER,
            cfg=_GPTJDecodeCfg(
                n_head=cfg.n_head,
                n_kv_head=cfg.n_head,
                head_dim=cfg.n_embd // cfg.n_head,
                rotary_dim=cfg.rotary_dim,
                eps=cfg.layer_norm_eps,
            ),
            max_len=cfg.n_positions,
            stack=self._stack_decoder_params,
        )

    def _stack_decoder_params(self) -> tuple[dict, dict]:
        stacks = [b.param_tensors() for b in self.h]
        layers = {
            key: jnp.stack([ts[i].data for ts in stacks])
            for i, key in enumerate(_LAYER_KEYS)
        }
        g = {
            "wte": self.wte.weight.data,
            "ln_f_w": self.ln_f.weight.data,
            "ln_f_b": self.ln_f.bias.data,
            "head_w": self.lm_head.weight.data,
            "head_b": self.lm_head.bias.data,
        }
        return g, layers


@dataclasses.dataclass(frozen=True)
class _GPTJDecodeCfg:
    n_head: int
    n_kv_head: int
    head_dim: int
    rotary_dim: int
    eps: float


def _dec_embed(g, ids, positions, cfg):
    return g["wte"][ids]


def _dec_attn_in(l, x, positions, cfg):
    return gptj_attn_in(
        l, x, positions,
        n_head=cfg.n_head, rotary_dim=cfg.rotary_dim, eps=cfg.eps,
    )


def _dec_attn_out(l, x, att, cfg):
    return gptj_attn_out(l, x, att, eps=cfg.eps)


def _dec_finalize(g, x, cfg):
    x = _pure_layernorm(x[:, -1], g["ln_f_w"], g["ln_f_b"], cfg.eps)
    return x @ g["head_w"].T + g["head_b"]


def _make_decoder():
    from .generation import DecoderFamily

    return DecoderFamily(
        embed=_dec_embed,
        attn_in=_dec_attn_in,
        attn_out=_dec_attn_out,
        finalize=_dec_finalize,
    )


GPTJ_DECODER = _make_decoder()
