"""AcceleratedScheduler — LR stepping synced to real optimizer steps.

Counterpart of ``/root/reference/src/accelerate/scheduler.py`` (98 LoC),
same contract: without ``split_batches`` the scheduler steps
``step_with_optimizer × num_shards`` times per call so the LR curve written
for a single-process loop lands on the same schedule when the global batch is
N× larger; steps are skipped while gradients accumulate or when the fp16
scaler dropped the optimizer step (scheduler.py:54-82).
"""

from __future__ import annotations

from .state import AcceleratorState, GradientState


class AcceleratedScheduler:
    def __init__(
        self,
        scheduler,
        optimizers,
        step_with_optimizer: bool = True,
        split_batches: bool = False,
    ):
        self.scheduler = scheduler
        self.optimizers = optimizers if isinstance(optimizers, (list, tuple)) else [optimizers]
        self.split_batches = split_batches
        self.step_with_optimizer = step_with_optimizer
        self.gradient_state = GradientState()

    def step(self, *args, _from_capture_replay: bool = False, **kwargs) -> None:
        if not _from_capture_replay:
            from .capture import current_capture

            ctx = current_capture()
            if ctx is not None:
                # under step capture: LR math is python-side; defer to after
                # the compiled call (LR flows into the program as data via
                # opt_state.hyperparams)
                ctx.defer_scheduler(self, args, kwargs)
                return
        if not self.step_with_optimizer:
            self.scheduler.step(*args, **kwargs)
            return
        if not self.gradient_state.sync_gradients:
            # mid-accumulation micro-step: never advance the LR (reference
            # scheduler.py:61-64 returns here regardless of adjust_scheduler)
            return
        # only advance when at least one wrapped optimizer really stepped
        for opt in self.optimizers:
            if getattr(opt, "step_was_skipped", False):
                return
        if self.split_batches:
            self.scheduler.step(*args, **kwargs)
        else:
            num_shards = 1
            if AcceleratorState._shared_state:
                num_shards = AcceleratorState().num_batch_shards
            for _ in range(num_shards):
                self.scheduler.step(*args, **kwargs)

    def get_last_lr(self):
        return self.scheduler.get_last_lr()

    def state_dict(self):
        return self.scheduler.state_dict()

    def load_state_dict(self, state_dict):
        self.scheduler.load_state_dict(state_dict)

    def get_lr(self):
        return self.scheduler.get_lr()

    def print_lr(self, *args, **kwargs):
        if hasattr(self.scheduler, "print_lr"):
            return self.scheduler.print_lr(*args, **kwargs)

    def __repr__(self):
        return f"AcceleratedScheduler({self.scheduler})"
