#!/usr/bin/env python
"""telemetry_smoke — `make telemetry-smoke`: prove the telemetry pipeline
end-to-end on CPU in seconds.

Two legs:

1. **Single-process pipeline** — tiny model, 4 captured steps with
   telemetry + per-step profiling + Chrome trace export on, full JSONL
   export, then schema validation through tools/telemetry_report.py and
   structural validation of the exported trace
   (``telemetry.trace_export.validate_trace``): the host-phase, device-op
   and flight-event tracks must all carry events for the same steps, and
   the always-on flight recorder must have recorded every step.

2. **Two-process injected hang** — a REAL 2-rank ``jax.distributed``
   gloo/CPU world where rank 1's fault injector sleeps
   (``hang:step=2``) before its third ``gather_object``: rank 0 blocks
   inside the collective, its hang watchdog fires on the stall deadline
   and writes ``blackbox_rank0.json``; a SIGTERM to the sleeping rank 1
   exercises the watchdog's fatal-signal dump path; then
   tools/blackbox_report.py must merge the dumps and name the stalled
   rank (1) and the first divergent collective (#3, gather_object).

Exit 0 = both legs pass.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import textwrap
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _pipeline_leg() -> list[str]:
    """Leg 1: the single-process telemetry pipeline + trace export."""
    import numpy as np
    import jax.numpy as jnp

    import accelerate_tpu.nn as nn
    import accelerate_tpu.optim as optim
    from accelerate_tpu import Accelerator, TelemetryKwargs
    from accelerate_tpu.data_loader import batch_to_global_array
    from accelerate_tpu.models import GPTConfig, GPTLMHeadModel
    from accelerate_tpu.telemetry.trace_export import validate_trace

    from telemetry_report import load_records, validate

    tmp = tempfile.mkdtemp(prefix="atpu_telemetry_")
    path = os.path.join(tmp, "run.jsonl")
    trace_path = os.path.join(tmp, "trace.json")
    nn.manual_seed(0)
    acc = Accelerator(
        kwargs_handlers=[
            TelemetryKwargs(
                enabled=True, jsonl_path=path,
                profile_every_n=1,  # every step sampled → device-op track
                trace_export_path=trace_path,
            )
        ]
    )
    model = GPTLMHeadModel(
        GPTConfig(vocab_size=256, n_positions=64, n_embd=32, n_layer=1, n_head=2)
    )
    opt = optim.AdamW(model.parameters(), lr=1e-3)
    model, opt = acc.prepare(model, opt)

    def step_fn(ids):
        opt.zero_grad()
        out = model(ids, labels=ids)
        acc.backward(out["loss"])
        opt.step()
        return out["loss"]

    step = acc.compile_step(step_fn)
    rng = np.random.default_rng(0)

    def batch(seq):
        ids = rng.integers(0, 256, (4, seq), dtype=np.int32)
        return batch_to_global_array(jnp.asarray(ids), mesh=acc.mesh)

    for _ in range(3):
        loss = step(batch(32))
    float(loss)
    step(batch(48))  # forced shape change → recompile event with a cause
    health = acc.telemetry.flightrec.health()
    acc.end_training()  # writes the JSONL dump + the Chrome trace

    records = load_records(path)
    errors = validate(records, min_steps=4)
    builds = [r for r in records if r.get("kind") == "step" and r.get("built")]
    if not any(r["trace_ms"] > 0 and r["compile_ms"] > 0 for r in builds):
        errors.append("no build step with nonzero trace/compile time")
    recompiles = [r for r in records if r.get("kind") == "recompile"]
    if not any("arg[0] shape changed" in (r.get("cause") or "") for r in recompiles):
        errors.append(f"shape-change recompile cause missing: {recompiles}")

    # the always-on flight recorder saw every captured step and is healthy
    if health["events_total"] < 8:  # >= 4 step_begin/step_end pairs
        errors.append(f"flight recorder too quiet: {health}")
    if health["dropped_total"] != 0:
        errors.append(f"flight recorder dropped events: {health}")

    # the exported Chrome trace is well-formed and carries host-phase,
    # device-op and flight-event tracks for the SAME steps
    try:
        with open(trace_path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        doc = None
        errors.append(f"trace export unreadable: {e}")
    if doc is not None:
        errors.extend(validate_trace(doc))
        host_steps, device_steps, flight_steps = set(), set(), set()
        for ev in doc.get("traceEvents", []):
            step_arg = (ev.get("args") or {}).get("step")
            if step_arg is None:
                continue
            if ev.get("tid") == 1 and ev.get("ph") == "X":
                host_steps.add(step_arg)
            elif ev.get("tid") == 2 and ev.get("ph") == "X":
                device_steps.add(step_arg)
            elif ev.get("tid") == 3:
                flight_steps.add(step_arg)
        common = host_steps & device_steps & flight_steps
        if len(common) < 4:
            errors.append(
                "trace tracks do not share steps: host="
                f"{sorted(host_steps)} device={sorted(device_steps)} "
                f"flight={sorted(flight_steps)}"
            )
    if not errors:
        steps = [r for r in records if r.get("kind") == "step"]
        print(
            f"telemetry-smoke: pipeline ok — {len(steps)} steps, "
            f"{len(builds)} builds, {len(recompiles)} recompile event(s), "
            f"{health['events_total']} flight events, trace at {trace_path}"
        )
    return errors


_HANG_WORKER = textwrap.dedent(
    """
    import json
    import os
    import sys

    pid = int(sys.argv[1])
    port = sys.argv[2]
    blackbox_dir = sys.argv[3]
    out_path = sys.argv[4]

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("XLA_FLAGS", None)  # 1 local device per process
    import jax

    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid
    )
    sys.path.insert(0, "@REPO@")

    from accelerate_tpu.resilience.inject import FaultInjector
    from accelerate_tpu.telemetry import flightrec
    from accelerate_tpu.telemetry.watchdog import HangWatchdog
    from accelerate_tpu.utils.operations import gather_object

    # rank 1 goes silent right before the step-2 collective; rank 0 will
    # block inside gather_object #3 until its watchdog deadline fires
    injector = (
        FaultInjector.from_spec("hang:step=2,seconds=600") if pid == 1 else None
    )
    wd = HangWatchdog(timeout_s=3.0, dump_dir=blackbox_dir).start()

    for step in range(4):
        flightrec.record("step_begin", step=step)
        if injector is not None:
            injector.maybe_hang(step)
        gathered = gather_object([step])
        flightrec.record("step_end", step=step)

    # only reached if nothing hung (a failure of this leg)
    wd.stop()
    with open(out_path, "w") as f:
        json.dump({"pid": pid, "completed": True}, f)
    """
).replace("@REPO@", REPO)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_for(path: str, timeout_s: float) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if os.path.exists(path):
            return True
        time.sleep(0.25)
    return False


def _hang_leg() -> list[str]:
    """Leg 2: injected hang in a real 2-process world → watchdog dumps →
    merged blackbox report names the stalled rank and collective."""
    from blackbox_report import load_dump, merge

    errors: list[str] = []
    tmp = tempfile.mkdtemp(prefix="atpu_blackbox_")
    blackbox_dir = os.path.join(tmp, "blackbox")
    worker = os.path.join(tmp, "worker.py")
    with open(worker, "w", encoding="utf-8") as f:
        f.write(_HANG_WORKER)
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), str(port), blackbox_dir,
             os.path.join(tmp, f"rank{i}.json")],
            env=env, cwd=REPO,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        for i in range(2)
    ]
    dump0 = os.path.join(blackbox_dir, "blackbox_rank0.json")
    dump1 = os.path.join(blackbox_dir, "blackbox_rank1.json")
    try:
        # rank 0 blocks in gather #3; its 3s watchdog deadline must produce
        # the stall dump (generous ceiling covers the distributed handshake)
        if not _wait_for(dump0, timeout_s=120):
            errors.append("rank 0 watchdog never dumped on the stall")
        # the hung rank's dump comes from the fatal-signal path: SIGTERM the
        # sleeping rank 1, its watchdog handler dumps then chains to death
        if procs[1].poll() is None:
            procs[1].send_signal(signal.SIGTERM)
        if not _wait_for(dump1, timeout_s=60):
            errors.append("rank 1 watchdog never dumped on SIGTERM")
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                errors.append("worker did not die on SIGKILL")
    if errors:
        return errors

    dumps = [d for d in (load_dump(dump0), load_dump(dump1)) if d is not None]
    if len(dumps) != 2:
        return [f"expected 2 parseable dumps, got {len(dumps)}"]
    report = merge(dumps)
    if report["stalled_ranks"] != [1]:
        errors.append(f"stalled rank not identified: {report}")
    if report["first_divergent_seq"] != 3:
        errors.append(f"first divergent collective seq != 3: {report}")
    if report["first_divergent_op"] != "gather_object":
        errors.append(f"divergent op not named: {report}")
    ranks = {r["rank"]: r for r in report["ranks"]}
    if ranks.get(0, {}).get("reason") != "watchdog_stall":
        errors.append(f"rank 0 dump reason != watchdog_stall: {ranks.get(0)}")
    if ranks.get(1, {}).get("reason") != "signal":
        errors.append(f"rank 1 dump reason != signal: {ranks.get(1)}")
    if not ranks.get(1, {}).get("hang_injected"):
        errors.append("rank 1 dump does not show the injected hang")
    if not errors:
        print(
            "telemetry-smoke: hang leg ok — watchdog dumped both ranks, "
            f"report names rank {report['stalled_ranks']} stalled at "
            f"collective #{report['first_divergent_seq']} "
            f"({report['first_divergent_op']})"
        )
    return errors


def main() -> int:
    errors = _pipeline_leg()
    errors += _hang_leg()
    for error in errors:
        print(f"telemetry-smoke: FAIL: {error}", file=sys.stderr)
    if errors:
        return 1
    print("telemetry-smoke: ok — pipeline + injected-hang legs passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
