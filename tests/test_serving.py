"""Decode service: continuous batching + paged KV cache (docs/serving.md).

The acceptance contract (ISSUE 7): mixed-length concurrent requests through
the service produce greedy tokens identical to single-request ``generate()``,
with zero recompile events after warmup, FIFO admission, immediate eviction,
and leak-free block accounting — all on the CPU mesh.
"""

import numpy as np
import pytest

import accelerate_tpu.nn as nn
from accelerate_tpu.models import GPTConfig, GPTLMHeadModel
from accelerate_tpu.serving import (
    BlockPool,
    DecodeService,
    ServingConfig,
    blocks_for_request,
    bucket_length,
)


@pytest.fixture(scope="module")
def tiny_model():
    nn.manual_seed(0)
    model = GPTLMHeadModel(GPTConfig.tiny())
    model.eval()
    return model


def _prompts(lengths, vocab=1024, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, (n,), dtype=np.int32) for n in lengths]


# ---------------------------------------------------------------------------
# kv_blocks: allocator + bucketing
# ---------------------------------------------------------------------------

def test_bucket_length_rounds_up_and_clamps():
    assert bucket_length(1, 16) == 16
    assert bucket_length(16, 16) == 16
    assert bucket_length(17, 16) == 32
    assert bucket_length(60, 16, cap=64) == 64
    # never below n, even past the cap
    assert bucket_length(70, 16, cap=64) == 70
    with pytest.raises(ValueError):
        bucket_length(0, 16)


def test_block_pool_alloc_free_no_leaks():
    pool = BlockPool(num_blocks=9, block_size=4, max_slots=2, blocks_per_slot=4)
    assert pool.usable_blocks == 8
    a = pool.alloc(0, 3)
    b = pool.alloc(1, 4)
    assert len(set(a) | set(b)) == 7 and 0 not in a + b
    assert pool.free_blocks == 1
    assert not pool.can_alloc(2)
    pool.check_no_leaks()
    assert pool.free_slot(0) == 3
    assert pool.free_blocks == 4
    # freed blocks are reusable; double-free is a no-op
    assert pool.free_slot(0) == 0
    c = pool.alloc(0, 4)
    assert 0 not in c
    pool.check_no_leaks()
    pool.free_slot(0)
    pool.free_slot(1)
    assert pool.free_blocks == pool.usable_blocks
    pool.check_no_leaks()


def test_block_pool_rejects_oversized_and_double_alloc():
    pool = BlockPool(num_blocks=9, block_size=4, max_slots=2, blocks_per_slot=4)
    with pytest.raises(ValueError, match="blocks_per_slot"):
        pool.alloc(0, 5)
    pool.alloc(0, 2)
    with pytest.raises(ValueError, match="already holds"):
        pool.alloc(0, 1)


# ---------------------------------------------------------------------------
# the acceptance contract: continuous batching == single-request generate()
# ---------------------------------------------------------------------------

def test_continuous_batch_matches_single_request_generate(tiny_model):
    """8 concurrent mixed-length requests with staggered arrivals: every
    request's greedy tokens are identical to a lone generate() of the same
    prompt, and the steady state is zero recompiles (ISSUE 7 acceptance)."""
    service = DecodeService(
        tiny_model, ServingConfig(max_slots=4, block_size=16, prompt_bucket=16)
    )
    lengths = [3, 9, 17, 30, 5, 24, 12, 40]
    budgets = [6, 4, 8, 3, 7, 5, 6, 4]
    prompts = _prompts(lengths)
    # stagger arrivals: two submissions per step while earlier requests are
    # mid-decode — sequences genuinely join an in-flight batch
    rids, pending = [], list(zip(prompts, budgets))
    while pending or service.has_work:
        for _ in range(2):
            if pending:
                p, b = pending.pop(0)
                rids.append(service.submit(p, max_new_tokens=b))
        service.step()
    for rid, p, b in zip(rids, prompts, budgets):
        want = np.asarray(tiny_model.generate(p[None], max_new_tokens=b))[0]
        got = service.results[rid].output_ids
        np.testing.assert_array_equal(got, want, err_msg=f"request {rid}")
    # eviction returned every block
    service.pool.check_no_leaks()
    assert service.pool.free_blocks == service.pool.usable_blocks


def test_zero_recompiles_in_steady_state(tiny_model):
    """After one decode build + one prefill build per prompt bucket, every
    further call replays — the CompileWatcher forensics count stays 0."""
    from accelerate_tpu.serving import engine

    engine._prefill_jit.clear_cache()
    engine._decode_jit.clear_cache()
    service = DecodeService(
        tiny_model, ServingConfig(max_slots=4, block_size=16, prompt_bucket=16)
    )
    # warmup: both buckets + the decode program
    for n in (4, 20):
        service.submit(np.ones(n, np.int32), max_new_tokens=3)
    service.run()
    warm = service.watcher.compiles_total
    assert warm >= 3  # 2 prefill buckets + 1 decode program
    # a second wave over the same buckets, different lengths/budgets
    for p, b in zip(_prompts([5, 9, 17, 31, 2, 26], seed=1), [4, 2, 5, 3, 6, 2]):
        service.submit(p, max_new_tokens=b)
    service.run()
    assert service.watcher.compiles_total == warm
    assert service.recompile_events == 0


def test_zero_recompiles_with_prepared_model():
    """Regression: a PREPARED model's params carry a NamedSharding, and the
    first captured call used to return the (uncommitted, single-device)
    pools re-committed onto that mesh — flipping the input sharding and
    silently recompiling every program on its second call.  The service now
    commits pools/rng streams replicated on the params' mesh up front."""
    from accelerate_tpu import Accelerator

    Accelerator._reset_state()
    nn.manual_seed(0)
    acc = Accelerator()
    model = acc.prepare(GPTLMHeadModel(GPTConfig.tiny()))
    model.eval()
    service = DecodeService(
        model, ServingConfig(max_slots=4, block_size=16, prompt_bucket=16)
    )
    for n in (4, 20):
        service.submit(np.ones(n, np.int32), max_new_tokens=3)
    service.run()
    warm = service.watcher.compiles_total
    for p, b in zip(_prompts([5, 17, 9, 30], seed=9), [4, 6, 3, 5]):
        service.submit(p, max_new_tokens=b)
    service.run()
    assert service.watcher.compiles_total == warm
    assert service.recompile_events == 0


def test_admission_fifo_and_immediate_eviction(tiny_model):
    """Admission is FIFO; a finished sequence frees its slot immediately and
    the next queued request takes it while others are still mid-decode."""
    service = DecodeService(
        tiny_model, ServingConfig(max_slots=2, block_size=16, prompt_bucket=16)
    )
    prompts = _prompts([4, 5, 6, 7], seed=2)
    # r0 finishes after 3 tokens, r1 is long; r2/r3 wait in the queue
    r0 = service.submit(prompts[0], max_new_tokens=3)
    r1 = service.submit(prompts[1], max_new_tokens=12)
    r2 = service.submit(prompts[2], max_new_tokens=3)
    r3 = service.submit(prompts[3], max_new_tokens=3)
    service.step()  # admits r0 + r1 (FIFO), decodes one token
    assert [r.rid for r in service._slot_req if r is not None] == [r0, r1]
    assert [r.rid for r in service._queue] == [r2, r3]
    done = service.step()  # r0 hits its budget -> evicted this step
    assert [r.rid for r in done] == [r0]
    service.step()  # r2 takes r0's slot NEXT step, r1 still running
    assert r2 in [r.rid for r in service._slot_req if r is not None]
    assert service.results.keys() >= {r0}
    service.run()
    # completion order respects arrival for equal budgets: r2 before r3
    assert list(service.results) == sorted(
        service.results, key=lambda rid: service.results[rid].done_t
    )
    assert service.results[r2].done_t < service.results[r3].done_t
    assert (r1 in service.results) and (r3 in service.results)
    service.pool.check_no_leaks()


def test_queue_backpressure_on_block_exhaustion(tiny_model):
    """An undersized pool gates admission (requests wait) instead of
    failing: with blocks for ~one max request, the service degrades to
    near-serial but still completes everything."""
    service = DecodeService(
        tiny_model,
        ServingConfig(
            max_slots=4, block_size=16, prompt_bucket=16, num_blocks=5
        ),
    )
    prompts = _prompts([17, 20, 25], seed=3)
    rids = [service.submit(p, max_new_tokens=4) for p in prompts]
    service.step()
    # only the head fit (needs 2 blocks of the 4 usable... the second also
    # fits; the third waits)
    assert service.active_slots <= 2 and len(service._queue) >= 1
    service.run()
    for rid, p in zip(rids, prompts):
        want = np.asarray(tiny_model.generate(p[None], max_new_tokens=4))[0]
        np.testing.assert_array_equal(service.results[rid].output_ids, want)
    service.pool.check_no_leaks()


def test_submit_validation(tiny_model):
    service = DecodeService(
        tiny_model, ServingConfig(max_slots=2, block_size=16, prompt_bucket=16)
    )
    with pytest.raises(ValueError, match="capacity"):
        service.submit(np.ones(250, np.int32), max_new_tokens=20)
    with pytest.raises(ValueError, match="max_new_tokens"):
        service.submit(np.ones(4, np.int32), max_new_tokens=0)
    with pytest.raises(ValueError, match="empty"):
        service.submit(np.zeros(0, np.int32), max_new_tokens=2)
    with pytest.raises(ValueError, match="multiple"):
        DecodeService(
            tiny_model, ServingConfig(block_size=16, prompt_bucket=24)
        )


def test_per_request_stop_token(tiny_model):
    """A request with eos stops the step its sampled token hits it (the eos
    itself is emitted, matching generate()); others run to budget."""
    prompts = _prompts([6, 8], seed=4)
    # the greedy continuation's 3rd token plays the "eos"; it may repeat
    # earlier in the stream, so the expected stop is its FIRST occurrence
    p_len = len(prompts[0])
    ref = np.asarray(tiny_model.generate(prompts[0][None], max_new_tokens=8))[0]
    eos = int(ref[p_len + 2])
    first_hit = int(np.argmax(ref[p_len:] == eos))
    service = DecodeService(
        tiny_model, ServingConfig(max_slots=2, block_size=16, prompt_bucket=16)
    )
    r0 = service.submit(prompts[0], max_new_tokens=8, eos_token_id=eos)
    r1 = service.submit(prompts[1], max_new_tokens=8)
    service.run()
    got = service.results[r0].output_ids
    # stopped at the stop token, which is itself emitted
    assert got.shape[0] == p_len + first_hit + 1 and got[-1] == eos
    np.testing.assert_array_equal(got, ref[: len(got)])
    want1 = np.asarray(tiny_model.generate(prompts[1][None], max_new_tokens=8))[0]
    np.testing.assert_array_equal(service.results[r1].output_ids, want1)
    service.pool.check_no_leaks()


def test_quantized_mode_composes(tiny_model):
    """int8 weight mode rides the SAME stacked-param cache as generate():
    serving outputs match quantized single-request decode token for token."""
    service = DecodeService(
        tiny_model,
        ServingConfig(
            max_slots=4, block_size=16, prompt_bucket=16, quantize_weights=8
        ),
    )
    prompts = _prompts([5, 11, 19], seed=5)
    rids = [service.submit(p, max_new_tokens=5) for p in prompts]
    service.run()
    for rid, p in zip(rids, prompts):
        want = np.asarray(
            tiny_model.generate(p[None], max_new_tokens=5, quantize_weights=8)
        )[0]
        np.testing.assert_array_equal(service.results[rid].output_ids, want)
    # both modes live side by side in the per-model stack cache
    assert set(tiny_model._generation_param_cache[1]) >= {8}


def test_serving_telemetry_records(tiny_model):
    """With a hub attached, every step emits a kind='serving' occupancy
    record and every completion a TTFT/TPOT record; the JSONL dump carries
    them (docs/telemetry.md schema)."""
    from accelerate_tpu.telemetry import Telemetry
    from accelerate_tpu.utils.dataclasses import TelemetryKwargs

    hub = Telemetry(TelemetryKwargs(enabled=True))
    service = DecodeService(
        tiny_model,
        ServingConfig(max_slots=2, block_size=16, prompt_bucket=16),
        telemetry=hub,
    )
    rids = [service.submit(p, max_new_tokens=3) for p in _prompts([4, 7, 9], seed=6)]
    service.run()
    records = [r for r in hub.all_records() if r.get("kind") == "serving"]
    steps = [r for r in records if r["event"] == "step"]
    completes = [r for r in records if r["event"] == "complete"]
    assert steps and all(
        0.0 <= r["occupancy"] <= 1.0 and "queue_depth" in r for r in steps
    )
    assert {r["rid"] for r in completes} == set(rids)
    assert all(r["ttft_ms"] is not None and r["ttft_ms"] >= 0 for r in completes)
    # multi-token requests report a per-token latency
    assert all(r["tpot_ms"] is not None for r in completes if r["new_tokens"] > 1)
    # occupancy statistic matches the recorded stream
    assert service.mean_batch_occupancy == pytest.approx(
        sum(r["occupancy"] for r in steps) / len(steps)
    )


def test_one_token_request_completes_at_admission(tiny_model):
    """max_new_tokens=1 finishes inside _admit (prefill samples the only
    token) and never occupies a decode slot."""
    service = DecodeService(
        tiny_model, ServingConfig(max_slots=2, block_size=16, prompt_bucket=16)
    )
    p = _prompts([6], seed=7)[0]
    rid = service.submit(p, max_new_tokens=1)
    done = service.step()
    assert [r.rid for r in done] == [rid]
    assert service.active_slots == 0
    want = np.asarray(tiny_model.generate(p[None], max_new_tokens=1))[0]
    np.testing.assert_array_equal(service.results[rid].output_ids, want)
    service.pool.check_no_leaks()


def test_result_retention_is_bounded(tiny_model):
    """A long-running service must not grow host memory with its request
    history: results retains the newest max_retained_results, and
    pop_result is the streaming-consumer take-and-drop API."""
    service = DecodeService(
        tiny_model,
        ServingConfig(
            max_slots=2, block_size=16, prompt_bucket=16,
            max_retained_results=2,
        ),
    )
    rids = [service.submit(p, max_new_tokens=2) for p in _prompts([4, 5, 6, 7], seed=10)]
    service.run()
    assert list(service.results) == rids[-2:]  # oldest two evicted
    taken = service.pop_result(rids[-1])
    assert taken is not None and taken.rid == rids[-1]
    assert service.pop_result(rids[-1]) is None
    assert service.pop_result(rids[0]) is None


def test_sampled_serving_is_slot_independent(tiny_model):
    """Per-slot RNG streams: a request's sampled tokens don't depend on
    which neighbours share the batch (solo run == batched run, same rid)."""
    def run(lengths, budgets, seed_rid_of_interest):
        service = DecodeService(
            tiny_model,
            ServingConfig(
                max_slots=4, block_size=16, prompt_bucket=16, temperature=1.0
            ),
        )
        prompts = _prompts(lengths, seed=8)
        rids = [service.submit(p, max_new_tokens=b) for p, b in zip(prompts, budgets)]
        service.run()
        return service.results[rids[seed_rid_of_interest]].output_ids

    solo = run([9], [5], 0)
    crowded = run([9, 4, 17, 30], [5, 6, 4, 3], 0)
    np.testing.assert_array_equal(solo, crowded)


# ---------------------------------------------------------------------------
# device-resident multi-token decode (ISSUE 14): n-token captured blocks,
# on-device token feedback, one host sync per block
# ---------------------------------------------------------------------------

def _serve_all(service, prompts, budgets, per_step=2):
    """Staggered submission driver shared by the multi-token cases."""
    rids, pending = [], list(zip(prompts, budgets))
    while pending or service.has_work:
        for _ in range(per_step):
            if pending:
                p, b = pending.pop(0)
                rids.append(service.submit(p, max_new_tokens=b))
        service.step()
    return rids


def test_blocks_for_request_covers_overrun_horizon():
    """Reservation math: decode_steps=1 is the classic formula exactly;
    n>1 rounds the decode span up to whole n-blocks (the ≤ n-1 overrun
    writes stay inside the slot's own reservation) and clamps to the
    slot's table length for near-capacity requests."""
    # classic: ceil(max(bucket, p+new)/bs)
    assert blocks_for_request(3, 6, 16, 16) == 1
    assert blocks_for_request(3, 20, 16, 16) == 2
    assert blocks_for_request(30, 3, 32, 16) == 3
    # n=8: a 6-token budget runs 1 + ceil(5/8)*8 = 9 positions past p_len
    assert blocks_for_request(3, 6, 16, 16, decode_steps=8) == 1
    assert blocks_for_request(14, 6, 16, 16, decode_steps=8) == 2  # 14+9=23
    # max_new=1 never holds a decode slot: horizon is 1 at every n
    assert blocks_for_request(3, 1, 16, 16, decode_steps=8) == 1
    # clamp: the overrun horizon may round past the table — tail writes are
    # trash-block/clamped-in-slot safe, so never reserve past the table
    assert blocks_for_request(50, 14, 64, 16, decode_steps=8,
                              blocks_per_slot=4) == 4


def test_multi_token_matches_generate_and_n1(tiny_model):
    """The tentpole acceptance: n=8 greedy tokens are per-sequence
    BITWISE identical to single-request generate() AND to the n=1 path,
    under staggered admission landing at block boundaries mid-flight."""
    lengths = [3, 9, 17, 30, 5, 24, 12, 40]
    budgets = [6, 4, 8, 3, 7, 5, 6, 4]
    prompts = _prompts(lengths)
    outs = {}
    for n in (1, 8):
        service = DecodeService(
            tiny_model,
            ServingConfig(max_slots=4, block_size=16, prompt_bucket=16,
                          decode_steps=n),
        )
        rids = _serve_all(service, prompts, budgets)
        outs[n] = [service.results[rid].output_ids for rid in rids]
        service.pool.check_no_leaks()
        assert service.pool.free_blocks == service.pool.usable_blocks
        assert service.recompile_events == 0
    for p, b, got1, got8 in zip(prompts, budgets, outs[1], outs[8]):
        want = np.asarray(tiny_model.generate(p[None], max_new_tokens=b))[0]
        np.testing.assert_array_equal(got1, want)
        np.testing.assert_array_equal(got8, want)


def test_multi_token_mid_block_eos_masking(tiny_model):
    """A stop token landing MID-block finishes the request at that token:
    the block's overrun tail is discarded (never reaches the output), the
    eos itself is emitted, and the output equals the generate() prefix —
    while a slot-mate without eos runs to budget unperturbed."""
    prompts = _prompts([6, 8], seed=4)
    p_len = len(prompts[0])
    ref = np.asarray(tiny_model.generate(prompts[0][None], max_new_tokens=8))[0]
    eos = int(ref[p_len + 2])  # 3rd generated token plays the eos
    first_hit = int(np.argmax(ref[p_len:] == eos))
    service = DecodeService(
        tiny_model,
        ServingConfig(max_slots=2, block_size=16, prompt_bucket=16,
                      decode_steps=8),
    )
    r0 = service.submit(prompts[0], max_new_tokens=8, eos_token_id=eos)
    r1 = service.submit(prompts[1], max_new_tokens=8)
    service.run()
    got = service.results[r0].output_ids
    assert got.shape[0] == p_len + first_hit + 1 and got[-1] == eos
    np.testing.assert_array_equal(got, ref[: len(got)])
    want1 = np.asarray(tiny_model.generate(prompts[1][None], max_new_tokens=8))[0]
    np.testing.assert_array_equal(service.results[r1].output_ids, want1)
    service.pool.check_no_leaks()


def test_multi_token_overrun_keeps_pool_leak_free(tiny_model):
    """Budgets that are NOT multiples of n overrun the captured block by up
    to n-1 micro-steps on an UNDERSIZED pool: every overrun write lands in
    the finishing slot's own reservation (or the trash block), the pool
    drains leak-free, and outputs stay exact."""
    service = DecodeService(
        tiny_model,
        ServingConfig(max_slots=4, block_size=16, prompt_bucket=16,
                      num_blocks=7, decode_steps=8),
    )
    prompts = _prompts([17, 20, 25], seed=3)
    budgets = [4, 11, 6]  # none a multiple of 8
    rids = [
        service.submit(p, max_new_tokens=b) for p, b in zip(prompts, budgets)
    ]
    service.run()
    for rid, p, b in zip(rids, prompts, budgets):
        want = np.asarray(tiny_model.generate(p[None], max_new_tokens=b))[0]
        np.testing.assert_array_equal(service.results[rid].output_ids, want)
    service.pool.check_no_leaks()
    assert service.pool.free_blocks == service.pool.usable_blocks


def test_zero_recompiles_steady_state_multi_token(tiny_model):
    """The zero-recompile contract holds at n>1: one decode-block program +
    one prefill program per bucket at warmup, then pure replays — and the
    decode_steps flip itself is a NEW signature, never a steady-state
    recompile event."""
    from accelerate_tpu.serving import engine

    engine._prefill_jit.clear_cache()
    engine._decode_n_jit.clear_cache()
    service = DecodeService(
        tiny_model,
        ServingConfig(max_slots=4, block_size=16, prompt_bucket=16,
                      decode_steps=8),
    )
    for n in (4, 20):
        service.submit(np.ones(n, np.int32), max_new_tokens=3)
    service.run()
    warm = service.watcher.compiles_total
    assert warm >= 3  # 2 prefill buckets + 1 decode-block program
    for p, b in zip(_prompts([5, 9, 17, 31, 2, 26], seed=1), [4, 2, 5, 3, 6, 2]):
        service.submit(p, max_new_tokens=b)
    service.run()
    assert service.watcher.compiles_total == warm
    assert service.recompile_events == 0
    assert service.host_syncs_per_token < 0.5  # blocks, not per-token syncs


def test_decode_steps_default_off_and_env_wiring(tiny_model, monkeypatch):
    """decode_steps defaults to 1 (today's per-token path, byte-identical)
    and resolves from $ACCELERATE_SERVING_DECODE_STEPS; a malformed value
    warns and keeps the default; <1 is rejected at construction."""
    assert ServingConfig().decode_steps == 1
    monkeypatch.setenv("ACCELERATE_SERVING_DECODE_STEPS", "8")
    assert ServingConfig().decode_steps == 8
    monkeypatch.setenv("ACCELERATE_SERVING_DECODE_STEPS", "fast")
    assert ServingConfig().decode_steps == 1
    monkeypatch.delenv("ACCELERATE_SERVING_DECODE_STEPS")
    with pytest.raises(ValueError, match="decode_steps"):
        DecodeService(tiny_model, ServingConfig(decode_steps=0))
    # explicit config wins over env
    monkeypatch.setenv("ACCELERATE_SERVING_DECODE_STEPS", "4")
    service = DecodeService(
        tiny_model,
        ServingConfig(max_slots=2, block_size=16, prompt_bucket=16,
                      decode_steps=1),
    )
    p = _prompts([7], seed=11)[0]
    rid = service.submit(p, max_new_tokens=5)
    service.run()
    want = np.asarray(tiny_model.generate(p[None], max_new_tokens=5))[0]
    np.testing.assert_array_equal(service.results[rid].output_ids, want)
    # the per-token path syncs once per token
    assert service.host_syncs_per_token == 1.0


@pytest.mark.parametrize("decode_steps", [4, 8])
def test_steady_state_step_uploads_nothing(tiny_model, decode_steps):
    """Regression (ISSUE 14 satellite): DecodeService.step() used to
    re-upload tables/positions/tokens every step even with no admission.
    On the multi-token path the decode state is device-resident — a
    steady-state step performs ZERO host→device transfers, enforced with a
    hard jax transfer guard (any upload raises), and the service's own h2d
    counter agrees.  (decode_steps=1 deliberately keeps the legacy
    per-step uploads: identical input avals → identical compiled binary →
    the bitwise generate() parity contract stays anchored to the exact
    program the seed service always ran.)"""
    import jax

    service = DecodeService(
        tiny_model,
        ServingConfig(max_slots=2, block_size=16, prompt_bucket=16,
                      decode_steps=decode_steps),
    )
    prompts = _prompts([5, 9], seed=12)
    rids = [service.submit(p, max_new_tokens=30) for p in prompts]
    service.step()  # admission step: uploads happen here, by design
    uploads_admit = service.stats["h2d_uploads"]
    assert uploads_admit >= 1
    with jax.transfer_guard_host_to_device("disallow"):
        for _ in range(3):
            service.step()
    assert service.stats["h2d_uploads"] == uploads_admit
    service.run()
    for rid, p in zip(rids, prompts):
        want = np.asarray(tiny_model.generate(p[None], max_new_tokens=30))[0]
        np.testing.assert_array_equal(service.results[rid].output_ids, want)


def test_multi_token_telemetry_and_metrics_counters(tiny_model):
    """The new serving counters (docs/telemetry.md): step records carry
    decode_steps/emitted, metrics() exposes host_syncs_per_token and the
    h2d upload counter, and at n=8 the sync ratio lands near 1/8."""
    from accelerate_tpu.telemetry import Telemetry
    from accelerate_tpu.utils.dataclasses import TelemetryKwargs

    hub = Telemetry(TelemetryKwargs(enabled=True))
    service = DecodeService(
        tiny_model,
        ServingConfig(max_slots=4, block_size=16, prompt_bucket=16,
                      decode_steps=8),
        telemetry=hub,
    )
    prompts = _prompts([4, 7, 9], seed=6)
    _serve_all(service, prompts, [9, 8, 9])
    steps = [
        r for r in hub.all_records()
        if r.get("kind") == "serving" and r.get("event") == "step"
    ]
    decoded = [r for r in steps if r["active"]]
    assert decoded and all(r["decode_steps"] == 8 for r in steps)
    assert all(r["emitted"] >= r["active"] for r in decoded)
    metrics = service.metrics()
    assert metrics["decode_steps"] == 8
    assert metrics["decode_tokens_total"] == sum(r["emitted"] for r in steps)
    assert metrics["h2d_uploads_total"] == service.stats["h2d_uploads"]
    # one sync per 8-token block; stops discard some overrun tokens, so the
    # ratio sits between 1/8 and the all-discarded worst case
    assert 1 / 8 <= metrics["host_syncs_per_token"] <= 1 / 8 + 0.05
