"""Native host-runtime library: batch assembly + checkpoint IO.

Covers the C++ fastloader core (gather/stack/pad-stack/file IO), the
safetensors-compatible container (round-trip both ways against the
safetensors package), the data_loader integrations (default_collate fast
path, TokenDataset.batch), and the numpy-fallback kill switch.
Reference behavior being mirrored: torch's C++ DataLoader collate and
native checkpoint serialization (see accelerate_tpu/native/__init__.py).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from accelerate_tpu import native
from accelerate_tpu.data_loader import TokenDataset, default_collate

pytestmark = pytest.mark.skipif(
    not native.available(), reason=f"native lib unavailable: {native.load_error()}"
)


def test_gather_rows_matches_fancy_indexing():
    rng = np.random.default_rng(0)
    src = rng.integers(0, 1000, (64, 17), dtype=np.int32)
    idx = rng.integers(0, 64, 33)
    np.testing.assert_array_equal(native.gather_rows(src, idx), src[idx])


def test_gather_rows_3d_and_out_buffer():
    src = np.random.default_rng(1).random((10, 3, 5)).astype(np.float32)
    idx = np.array([9, 0, 4])
    out = np.empty((3, 3, 5), np.float32)
    got = native.gather_rows(src, idx, out=out)
    assert got is out
    np.testing.assert_array_equal(out, src[idx])


def test_gather_rows_bounds_check():
    src = np.zeros((4, 2), np.int32)
    with pytest.raises(IndexError):
        native.gather_rows(src, np.array([0, 4]))
    with pytest.raises(IndexError):
        native.gather_rows(src, np.array([-1]))


def test_stack_rows_matches_np_stack():
    rows = [np.random.default_rng(i).random((6, 4)).astype(np.float32) for i in range(9)]
    np.testing.assert_array_equal(native.stack_rows(rows), np.stack(rows))


def test_stack_rows_rejects_ragged():
    with pytest.raises(ValueError):
        native.stack_rows([np.zeros(3, np.float32), np.zeros(4, np.float32)])


def test_stack_rows_validates_out():
    rows = [np.zeros((4,), np.float32)] * 3
    with pytest.raises(ValueError):
        native.stack_rows(rows, out=np.empty((2, 4), np.float32))  # too small
    with pytest.raises(ValueError):
        native.stack_rows(rows, out=np.empty((3, 4), np.float64))  # wrong dtype
    out = np.empty((3, 4), np.float32)
    assert native.stack_rows(rows, out=out) is out


def test_pad_stack():
    rows = [np.array([1, 2, 3], np.int32), np.array([7], np.int32),
            np.array([4, 5], np.int32)]
    got = native.pad_stack(rows, pad_value=-100)
    np.testing.assert_array_equal(
        got, np.array([[1, 2, 3], [7, -100, -100], [4, 5, -100]], np.int32)
    )


def test_pad_stack_float_and_max_len():
    rows = [np.array([1.5], np.float32)]
    got = native.pad_stack(rows, max_len=4, pad_value=0.25)
    np.testing.assert_array_equal(got, np.array([[1.5, 0.25, 0.25, 0.25]], np.float32))
    with pytest.raises(ValueError):
        native.pad_stack([np.zeros(5, np.float32)], max_len=3)


def test_file_roundtrip_and_offset(tmp_path):
    path = str(tmp_path / "blob.bin")
    x = np.random.default_rng(3).random((257, 33)).astype(np.float64)
    native.write_file(path, x)
    np.testing.assert_array_equal(native.read_into(path, np.empty_like(x)), x)
    # offset read of row 5
    row = native.read_into(path, np.empty(33, np.float64), offset=5 * 33 * 8)
    np.testing.assert_array_equal(row, x[5])


def test_write_region(tmp_path):
    path = str(tmp_path / "region.bin")
    native.write_file(path, np.zeros(16, np.uint8))
    native.write_region(path, np.arange(4, dtype=np.uint8), offset=6)
    got = native.read_into(path, np.empty(16, np.uint8))
    expect = np.zeros(16, np.uint8)
    expect[6:10] = [0, 1, 2, 3]
    np.testing.assert_array_equal(got, expect)


def test_read_short_file_errors(tmp_path):
    path = str(tmp_path / "short.bin")
    native.write_file(path, np.zeros(8, np.uint8))
    with pytest.raises(OSError):
        native.read_into(path, np.empty(64, np.uint8))


def test_missing_file_errors(tmp_path):
    with pytest.raises(OSError):
        native.read_into(str(tmp_path / "nope.bin"), np.empty(4, np.uint8))


# --- safetensors-compatible container -------------------------------------
def _sample_tensors():
    rng = np.random.default_rng(7)
    return {
        "w": rng.random((33, 9)).astype(np.float32),
        "b": rng.integers(-5, 5, (9,), dtype=np.int64),
        "flag": np.array(True),
        "u16view": rng.integers(0, 2**16, (4, 4)).astype(np.uint16),
        "empty": np.zeros((0, 3), np.float32),
        # >4MB: exercises the parallel region-writer path, not the buffered one
        "big": rng.random((1100, 1024)).astype(np.float32),
    }


def test_st_roundtrip_native(tmp_path):
    from accelerate_tpu.native import st

    path = str(tmp_path / "m.safetensors")
    tensors = _sample_tensors()
    st.save_file(tensors, path, metadata={"format": "accelerate_tpu-sharded"})
    back = st.load_file(path)
    assert set(back) == set(tensors)
    for k in tensors:
        # strict shape check: assert_array_equal broadcasts, which would let
        # a 0-d -> (1,) regression slip through (it did once)
        assert back[k].shape == tensors[k].shape, k
        np.testing.assert_array_equal(back[k], tensors[k])
    np.testing.assert_array_equal(st.load_tensor(path, "b"), tensors["b"])


def test_st_native_write_safetensors_read(tmp_path):
    """Files we write load with the safetensors package (format parity)."""
    from safetensors.numpy import load_file as st_load

    from accelerate_tpu.native import st

    path = str(tmp_path / "m.safetensors")
    tensors = _sample_tensors()
    st.save_file(tensors, path)
    back = st_load(path)
    for k in tensors:
        assert back[k].shape == tensors[k].shape, k
        np.testing.assert_array_equal(back[k], tensors[k])


def test_st_safetensors_write_native_read(tmp_path):
    """Files safetensors writes load through the native reader."""
    from safetensors.numpy import save_file as st_save

    from accelerate_tpu.native import st

    path = str(tmp_path / "m.safetensors")
    tensors = _sample_tensors()
    st_save(tensors, path)
    back = st.load_file(path)
    for k in tensors:
        assert back[k].shape == tensors[k].shape, k
        np.testing.assert_array_equal(back[k], tensors[k])


def test_st_pathlike_and_writable_contract(tmp_path):
    """PathLike paths work, and default loads are writable (package parity);
    writable=False gives read-only zero-copy views."""
    from accelerate_tpu.native import st

    path = tmp_path / "m.safetensors"  # a PosixPath, not str
    tensors = _sample_tensors()
    st.save_file(tensors, path)
    back = st.load_file(path)
    back["w"] += 1  # must NOT raise: independent writable array
    np.testing.assert_array_equal(back["w"], tensors["w"] + 1)
    ro = st.load_file(path, writable=False)
    with pytest.raises(ValueError):
        ro["w"] += 1
    np.testing.assert_array_equal(st.load_tensor(path, "b"), tensors["b"])


def test_st_rejects_corrupt_header(tmp_path):
    """A hostile/corrupt header must fail loudly, not drive a huge read
    (reference: safetensors' Rust core validates both; see ADVICE r3)."""
    import struct

    from accelerate_tpu.native import st

    path = str(tmp_path / "m.safetensors")
    tensors = _sample_tensors()
    st.save_file(tensors, path)

    # header length pointing past the file
    bogus = str(tmp_path / "hlen.safetensors")
    with open(path, "rb") as f:
        raw = f.read()
    with open(bogus, "wb") as f:
        f.write(struct.pack("<Q", 1 << 40) + raw[8:])
    with pytest.raises(ValueError, match="header"):
        st.load_file(bogus)

    # offsets that disagree with shape x dtype
    (hlen,) = struct.unpack("<Q", raw[:8])
    import json

    header = json.loads(raw[8 : 8 + hlen])
    name = next(k for k in header if k != "__metadata__")
    header[name]["data_offsets"][1] += 16
    bad_hdr = json.dumps(header, separators=(",", ":")).encode()
    bad_hdr += b" " * ((8 - len(bad_hdr) % 8) % 8)
    bad = str(tmp_path / "offsets.safetensors")
    with open(bad, "wb") as f:
        f.write(struct.pack("<Q", len(bad_hdr)) + bad_hdr + raw[8 + hlen :])
    with pytest.raises(ValueError, match="data_offsets"):
        st.load_file(bad)
    with pytest.raises(ValueError, match="data_offsets"):
        st.load_tensor(bad, name)


def test_st_bf16(tmp_path):
    import ml_dtypes

    from accelerate_tpu.native import st

    path = str(tmp_path / "bf16.safetensors")
    x = np.random.default_rng(9).random((8, 8)).astype(ml_dtypes.bfloat16)
    st.save_file({"x": x}, path)
    np.testing.assert_array_equal(st.load_file(path)["x"], x)


# --- integrations ----------------------------------------------------------
def test_default_collate_uses_native_and_matches():
    samples = [np.full((3, 2), i, np.float32) for i in range(8)]
    np.testing.assert_array_equal(default_collate(samples), np.stack(samples))


def test_token_dataset_memmap_batch(tmp_path):
    tokens = np.arange(100, dtype=np.int32)
    path = str(tmp_path / "tokens.bin")
    tokens.tofile(path)
    ds = TokenDataset(path, seq_len=8)
    assert len(ds) == 12 and ds.seq_len == 8
    np.testing.assert_array_equal(ds[3], np.arange(24, 32, dtype=np.int32))
    batch = ds.batch([11, 0, 5])
    np.testing.assert_array_equal(batch, ds.rows[np.array([11, 0, 5])])
    # negative indices normalize identically on native and numpy paths
    np.testing.assert_array_equal(ds.batch([-1, -12]), ds.rows[np.array([11, 0])])


def test_token_dataset_2d_and_errors():
    ds = TokenDataset(np.zeros((4, 16), np.int32))
    assert len(ds) == 4
    with pytest.raises(ValueError):
        TokenDataset(np.zeros(64, np.int32))  # flat needs seq_len
    with pytest.raises(ValueError):
        TokenDataset(np.zeros((2, 2, 2), np.int32))


def test_token_dataset_batch_validation_uniform():
    """batch() validates identically on native and numpy paths."""
    ds = TokenDataset(np.arange(64, dtype=np.int32).reshape(4, 16))
    with pytest.raises(ValueError):
        ds.batch(np.array([[0, 1]]))  # non-1-D
    with pytest.raises(IndexError):
        ds.batch([4])
    with pytest.raises(ValueError):
        ds.batch([0, 1], out=np.empty((2, 16), np.float32))  # wrong dtype
    out = np.empty((2, 16), np.int32)
    assert ds.batch([1, 3], out=out) is out


def test_sharded_checkpoint_files_still_compatible(tmp_path):
    """save_sharded_model_state (now native-IO) stays safe_open-readable."""
    from safetensors import safe_open

    from accelerate_tpu.utils.fsdp_utils import save_sharded_model_state

    state = {"layer.w": np.random.default_rng(5).random((6, 4)).astype(np.float32)}
    save_sharded_model_state(state, str(tmp_path), process_index=0, num_processes=1)
    files = [f for f in os.listdir(tmp_path) if f.endswith(".safetensors")]
    assert len(files) == 1
    with safe_open(str(tmp_path / files[0]), framework="numpy") as f:
        keys = list(f.keys())
        assert len(keys) == 1
        np.testing.assert_array_equal(f.get_tensor(keys[0]), state["layer.w"])


def test_kill_switch_subprocess():
    """ACCELERATE_TPU_NO_NATIVE=1 disables the library; collate still works."""
    code = (
        "import numpy as np;"
        "from accelerate_tpu import native;"
        "from accelerate_tpu.data_loader import default_collate;"
        "assert not native.available();"
        "assert 'disabled' in native.load_error();"
        "out = default_collate([np.ones(3, np.float32)] * 4);"
        "assert out.shape == (4, 3)"
    )
    env = dict(os.environ, ACCELERATE_TPU_NO_NATIVE="1", JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
